"""Device mesh + sharding rules.

The TPU replacement for the reference's parameter-server data parallelism
(reference tf_euler/python/run_loop.py:371-397 ClusterSpec{ps,worker} +
replica_device_setter): parameters are replicated across the mesh, each
batch is sharded over the 'data' axis, and XLA inserts the gradient
all-reduce over ICI inside the jitted train step. No parameter servers,
no explicit gradient exchange code.

A second optional 'model' axis row-shards the big per-node tables — the
device-resident feature/label consts and the Scalable* historical-embedding
stores. This is the TPU-native version of the reference's PS-sharded
embedding tables (reference tf_euler/python/utils/embedding.py:22-67 'mod'
partitioned scatter): total table HBM scales with the model axis, and XLA
inserts the gather/scatter collectives inside the jitted step.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Top-level train-state keys holding [num_nodes, dim]-shaped tables that
# row-shard over the 'model' axis.
_TABLE_KEYS = ("consts", "stores", "grad_stores")


def honor_jax_platforms_env() -> None:
    """Make JAX_PLATFORMS effective even when a site hook pre-registered
    another backend at interpreter start: the env var alone is ignored once
    plugins are registered; only the config knob switches before backend
    init. Call from CLI entry points before any jax.devices()."""
    import os

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "(jnp.ones((128, 128), jnp.bfloat16) @ jnp.ones((128, 128),"
    " jnp.bfloat16)).block_until_ready();"
    "print(d[0].platform)"
)
_probed_ok = False


def probe_backend_once(timeout_s: float):
    """One killable-subprocess attempt to init the ambient backend and
    run a tiny matmul. Returns (platform, None) on success or
    (None, error string). Shared by probe_backend_or_die and bench.py's
    retry loop so relay-wedge handling cannot drift between the
    training and measurement paths."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"init timed out after {timeout_s:.0f}s"
    if r.returncode == 0 and r.stdout.strip():
        return r.stdout.strip().splitlines()[-1], None
    tail = (r.stderr or r.stdout).strip().splitlines()
    return None, f"rc={r.returncode} {tail[-1] if tail else ''}"


def probe_backend_or_die(timeout_s: float | None = None) -> None:
    """Fail FAST with a recovery recipe instead of hanging forever when
    the ambient TPU backend (the axon relay here) is wedged.

    Backend init on a dead relay blocks at the C level — no traceback,
    0% CPU, uninterruptible — so this runs init + a tiny matmul in a
    KILLABLE subprocess first (the child exits before the parent
    initializes, so it never holds the chip). Only probes when the
    FIRST ambient platform could be a TPU (JAX_PLATFORMS unset, or
    axon/tpu leading a comma list — "tpu,cpu" still inits TPU first);
    explicit CPU runs and EULER_TPU_SKIP_BACKEND_PROBE=1 skip it, and a
    SUCCESSFUL probe is cached per process (a failed one re-probes, so
    callers that catch the error can re-check after the relay
    recovers). Call from CLI entry points before any jax use
    (run_loop.main and the examples do)."""
    global _probed_ok
    import os

    if _probed_ok or os.environ.get("EULER_TPU_SKIP_BACKEND_PROBE") == "1":
        return
    first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip()
    if first not in ("", "axon", "tpu"):
        return
    if timeout_s is None:
        timeout_s = float(
            os.environ.get("EULER_TPU_PROBE_TIMEOUT", 150)
        )  # first TPU compile can take ~20-40 s; default is generous
    platform, err = probe_backend_once(timeout_s)
    if platform is not None:
        _probed_ok = True
        return
    if "timed out" in (err or ""):
        raise RuntimeError(
            f"TPU backend unreachable: {err} (wedged relay/driver — "
            "proceeding would hang this process forever at 0% CPU). "
            "Options: retry later; JAX_PLATFORMS=cpu to run on CPU; "
            "EULER_TPU_SKIP_BACKEND_PROBE=1 to skip this check; "
            "EULER_TPU_PROBE_TIMEOUT=<s> to wait longer."
        )
    raise RuntimeError(
        f"TPU backend probe failed: {err} — JAX_PLATFORMS=cpu runs on "
        "CPU; EULER_TPU_SKIP_BACKEND_PROBE=1 skips this check."
    )


def enable_compile_cache(default_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at
    JAX_COMPILATION_CACHE_DIR (or ``default_dir``): chip windows are
    scarce and each TPU program compile costs 20-40 s — a relaunched
    config, the next checks step, or the next session reuses compiles.
    Call before the first jit; no-op when neither location is given."""
    import os

    d = os.environ.get("JAX_COMPILATION_CACHE_DIR") or default_dir
    if d:
        jax.config.update("jax_compilation_cache_dir", d)


def force_cpu_devices(n_devices: int) -> None:
    """Force an n_devices-wide virtual CPU platform, overriding any ambient
    JAX_PLATFORMS / XLA_FLAGS (the environment here exports
    JAX_PLATFORMS=axon, and the axon site hook pre-registers the TPU
    backend, so env vars alone are a no-op — only jax.config switches the
    platform before backend init). Must run BEFORE the backend initializes;
    raises if the backend is already up with too few devices."""
    import os
    import re

    opt = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", opt, flags
        )
    else:
        flags = (flags + " " + opt).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if len(devs) < n_devices or devs[0].platform != "cpu":
        raise RuntimeError(
            f"{len(devs)} {devs[0].platform} devices visible after forcing "
            f"{n_devices} virtual CPU devices — the JAX backend was "
            "already initialized; call force_cpu_devices() before any "
            "jax.devices()/jit in this process"
        )


def make_mesh(
    num_devices: int | None = None,
    devices=None,
    model_parallel: int = 1,
) -> Mesh:
    """(data, model) mesh over the first num_devices devices.

    model_parallel=1 (default) is pure data parallelism; k>1 dedicates a
    k-wide 'model' axis for row-sharded tables.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    devices = np.asarray(devices)
    if len(devices) % model_parallel != 0:
        raise ValueError(
            f"{len(devices)} devices not divisible by "
            f"model_parallel={model_parallel}"
        )
    return Mesh(
        devices.reshape(-1, model_parallel), ("data", "model")
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over 'data' (replicated over 'model')."""
    return NamedSharding(mesh, P("data"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Row-shard a [rows, dim] table over the 'model' axis."""
    return NamedSharding(mesh, P("model"))


def _model_axis_size(mesh: Mesh) -> int:
    return mesh.shape.get("model", 1)


def _is_table(path, x) -> bool:
    """True for leaves under a _TABLE_KEYS top-level state key — the
    per-node tables that row-shard (and row-pad) over the model axis.
    Under consts, only the per-node lookup tables (features / labels)
    shard; device-sampling structures (adj / roots / negs and anything
    else) replicate — their cumulative-weight arrays must stay contiguous
    and unpadded (zero-padding would unsort the searchsorted input)."""
    key = path[0]
    name = getattr(key, "key", getattr(key, "idx", None))
    if name not in _TABLE_KEYS or np.ndim(x) < 1:
        return False
    if name == "consts" and len(path) > 1:
        sub = getattr(path[1], "key", getattr(path[1], "idx", None))
        if sub not in ("features", "labels", "sparse"):
            return False
    return True


def state_sharding(mesh: Mesh, state):
    """Sharding pytree for a train state: params/optimizer replicated,
    per-node tables (consts, Scalable stores) row-sharded when the mesh has
    a model axis. Matches state's tree structure, for jit in_/out_shardings
    and device_put."""
    rep = replicated_sharding(mesh)
    if _model_axis_size(mesh) <= 1:
        return jax.tree.map(lambda _: rep, state)
    tab = table_sharding(mesh)
    return jax.tree_util.tree_map_with_path(
        lambda path, x: tab if _is_table(path, x) else rep, state
    )


def pad_tables_for_mesh(state, mesh: Mesh):
    """Pad table rows (dim 0) up to a multiple of the model axis so they
    shard evenly. Extra rows are zero and never indexed (valid ids are
    <= max_id+1 < original row count). Resuming a checkpoint requires the
    same model_parallel setting, since store shapes include the padding."""
    k = _model_axis_size(mesh)
    if k <= 1:
        return state

    def pad(path, x):
        if _is_table(path, x):
            extra = (-x.shape[0]) % k
            if extra:
                return jax.numpy.pad(
                    x, [(0, extra)] + [(0, 0)] * (np.ndim(x) - 1)
                )
        return x

    return jax.tree_util.tree_map_with_path(pad, state)


def put_global(tree, shardings):
    """device_put a host pytree onto its shardings, multi-process aware.

    Single-controller: plain jax.device_put. Under jax.distributed
    (process_count > 1) the shardings span devices this process cannot
    address, so each leaf becomes a global jax.Array assembled from the
    process-local shards instead — every process must hold the SAME full
    host value (true for replicated params initialised from one PRNG
    seed and for consts derived from the same graph). This is the
    multi-host analog of the reference's parameter-server variable
    placement (reference tf_euler/python/run_loop.py:391-394)."""
    if jax.process_count() == 1:
        return jax.device_put(tree, shardings)

    def put(x, s):
        if isinstance(x, jax.Array) and x.sharding == s:
            # already placed (e.g. a checkpoint-restored global array) —
            # np.asarray on it would crash for model-axis-sharded leaves
            # (spans non-addressable devices) and needlessly round-trip
            # everything else
            return x
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, s, lambda idx: x[idx])

    return jax.tree.map(put, tree, shardings)


def shard_batch(batch, mesh: Mesh):
    """Place a host batch pytree onto the mesh, leading dim sharded
    (scalars — e.g. a device-sampling seed — are replicated).

    Multi-process (jax.distributed): ``batch`` is this process's LOCAL
    shard — leading dims concatenate across processes in process order,
    so the global batch is num_processes x the local size. Scalars must
    be identical on every process (they replicate)."""
    sharding = batch_sharding(mesh)
    rep = replicated_sharding(mesh)
    if jax.process_count() > 1:
        def put(x):
            x = np.asarray(x)
            if np.ndim(x) == 0:
                return jax.make_array_from_callback(
                    x.shape, rep, lambda idx: x[idx]
                )
            return jax.make_array_from_process_local_data(sharding, x)

        return jax.tree.map(put, batch)
    return jax.tree.map(
        lambda x: jax.device_put(
            x, rep if np.ndim(x) == 0 else sharding
        ),
        batch,
    )
