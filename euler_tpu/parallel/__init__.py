from euler_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated_sharding,
    shard_batch,
)
from euler_tpu.parallel.prefetch import prefetch

__all__ = [
    "batch_sharding",
    "make_mesh",
    "replicated_sharding",
    "shard_batch",
    "prefetch",
]
