from euler_tpu.parallel.mesh import (
    batch_sharding,
    enable_compile_cache,
    force_cpu_devices,
    honor_jax_platforms_env,
    probe_backend_once,
    probe_backend_or_die,
    make_mesh,
    pad_tables_for_mesh,
    put_global,
    replicated_sharding,
    shard_batch,
    state_sharding,
    table_sharding,
)
from euler_tpu.parallel.prefetch import pipeline, prefetch

__all__ = [
    "batch_sharding",
    "enable_compile_cache",
    "force_cpu_devices",
    "honor_jax_platforms_env",
    "probe_backend_once",
    "probe_backend_or_die",
    "make_mesh",
    "pad_tables_for_mesh",
    "put_global",
    "replicated_sharding",
    "shard_batch",
    "state_sharding",
    "table_sharding",
    "prefetch",
    "pipeline",
]
