"""Host->device prefetch pipeline.

The TPU replacement for the reference's AsyncOpKernel machinery
(reference tf_euler/kernels/*.cc ComputeAsync + callback chains): instead of
async graph ops inside the step graph, the sampler runs in background
threads (the native engine releases the GIL) producing batch k+1..k+depth
while the device computes step k.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator


def prefetch(
    make_batch: Callable[[int], dict],
    num_steps: int,
    depth: int = 2,
    num_threads: int = 2,
    start: int = 0,
    worker_init: Callable[[int], None] | None = None,
) -> Iterator[dict]:
    """Yield num_steps batches for steps start..start+num_steps, produced
    ahead of time by worker threads.

    make_batch(step) must be thread-safe (the graph engine is: the store is
    immutable and RNG is thread-local). worker_init(worker_idx) runs once
    at the start of each worker thread — e.g. to seed its thread-local
    sampler RNG for reproducible runs.
    """
    if start:
        base_make = make_batch
        make_batch = lambda step: base_make(step + start)  # noqa: E731
    if num_threads <= 1 or depth <= 0:
        if worker_init is not None:
            worker_init(0)
        for step in range(num_steps):
            yield make_batch(step)
        return

    out: "queue.Queue" = queue.Queue()
    cv = threading.Condition()
    next_step = [0]  # next step a worker may claim
    consumed = [0]  # steps the consumer has yielded
    stop = threading.Event()

    def worker(widx: int):
        try:
            if worker_init is not None:
                worker_init(widx)
        except Exception as e:  # surface init errors instead of hanging
            with cv:
                # claim the next unclaimed step so the consumer is
                # guaranteed to reach this error entry
                step = next_step[0]
                next_step[0] = step + 1
            out.put((step, e))
            return
        while not stop.is_set():
            with cv:
                # Backpressure: never run more than `depth` steps ahead of
                # the consumer, even across the reorder buffer — otherwise a
                # slow step would let the other workers produce (and retain)
                # arbitrarily many batches.
                while (
                    not stop.is_set()
                    and next_step[0] < num_steps
                    and next_step[0] - consumed[0] >= depth + 1
                ):
                    cv.wait(timeout=0.1)
                step = next_step[0]
                if stop.is_set() or step >= num_steps:
                    return
                next_step[0] = step + 1
            try:
                batch = make_batch(step)
            except Exception as e:  # surface errors to the consumer
                out.put((step, e))
                return
            out.put((step, batch))

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(num_threads)
    ]
    for t in threads:
        t.start()
    try:
        # Reorder: batches may complete out of order with >1 worker. The
        # pending dict is bounded by depth+1 thanks to the backpressure.
        pending: dict[int, object] = {}
        for want in range(num_steps):
            while want not in pending:
                step, item = out.get()
                pending[step] = item
            item = pending.pop(want)
            if isinstance(item, Exception):
                raise item
            yield item
            with cv:
                consumed[0] = want + 1
                cv.notify_all()
    finally:
        stop.set()
        with cv:
            cv.notify_all()
        for t in threads:
            t.join(timeout=1.0)
