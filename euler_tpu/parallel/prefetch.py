"""Host->device prefetch pipeline.

The TPU replacement for the reference's AsyncOpKernel machinery
(reference tf_euler/kernels/*.cc ComputeAsync + callback chains): instead of
async graph ops inside the step graph, the sampler runs in background
threads (the native engine releases the GIL) producing batch k+1..k+depth
while the device computes step k.

Instrumented for the step-phase profiler (OBSERVABILITY.md "Step
phases"): with ``profile`` on (default: whenever telemetry is enabled)
the pipeline records

  * ``input_stall`` — consumer wall time blocked on the queue per step
    (ROADMAP item 1's acceptance metric is this histogram's mean,
    ``input_stall_ms``);
  * ``sample`` — per-worker ``make_batch`` produce time (suppress with
    ``record_sample=False`` when the caller times finer-grained phases
    inside make_batch itself, as train.py does);
  * queue-depth and workers-busy value histograms at every dequeue —
    what tells a starved queue (depth 0, workers busy) apart from
    slow/dead workers (depth 0, workers idle);
  * the ``prefetch_produced`` / ``prefetch_dropped`` /
    ``prefetch_worker_errors`` counters. A worker that dies after init
    still surfaces as the consumer's exception at its step, but the
    counter and a journaled error span make it visible in any metrics
    scrape even when the consumer is mid-step.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator


def _profiler():
    """(record_phase, record_gauges, counter_add) when the telemetry
    stack is importable and enabled, else None — prefetch() stays
    usable in processes that never touch the native library."""
    try:
        from euler_tpu.graph.native import counter_add
        from euler_tpu.telemetry import (
            record_phase,
            record_prefetch_gauges,
            telemetry_enabled,
        )

        if not telemetry_enabled():
            return None
        return record_phase, record_prefetch_gauges, counter_add
    except Exception:
        return None


def prefetch(
    make_batch: Callable[[int], dict],
    num_steps: int,
    depth: int = 2,
    num_threads: int = 2,
    start: int = 0,
    worker_init: Callable[[int], None] | None = None,
    profile: bool | None = None,
    record_sample: bool = True,
) -> Iterator[dict]:
    """Yield num_steps batches for steps start..start+num_steps, produced
    ahead of time by worker threads.

    make_batch(step) must be thread-safe (the graph engine is: the store is
    immutable and RNG is thread-local). worker_init(worker_idx) runs once
    at the start of each worker thread — e.g. to seed its thread-local
    sampler RNG for reproducible runs.

    profile=None enables step-phase recording iff telemetry is enabled
    (the `telemetry=0` kill-switch reaches here too); False forces the
    zero-instrumentation path.
    """
    prof = _profiler() if profile in (None, True) else None
    if start:
        base_make = make_batch
        make_batch = lambda step: base_make(step + start)  # noqa: E731
    if num_threads <= 1 or depth <= 0:
        # Synchronous path: the consumer IS the producer, so every
        # sample is, by definition, a full consumer stall — exactly the
        # input_stall the async pipeline above exists to hide.
        if worker_init is not None:
            worker_init(0)
        for step in range(num_steps):
            t0 = time.perf_counter()
            batch = make_batch(step)
            if prof is not None:
                dur_us = (time.perf_counter() - t0) * 1e6
                record, gauges, count = prof
                if record_sample:
                    record("sample", dur_us, step=step + start)
                record("input_stall", dur_us, step=step + start)
                gauges(0, 0)
                count("prefetch_produced")
            yield batch
        return

    out: "queue.Queue" = queue.Queue()
    cv = threading.Condition()
    next_step = [0]  # next step a worker may claim
    consumed = [0]  # steps the consumer has yielded
    busy = [0]  # workers currently inside make_batch
    stop = threading.Event()

    def worker(widx: int):
        try:
            if worker_init is not None:
                worker_init(widx)
        except Exception as e:  # surface init errors instead of hanging
            if prof is not None:
                prof[2]("prefetch_worker_errors")
            with cv:
                # claim the next unclaimed step so the consumer is
                # guaranteed to reach this error entry
                step = next_step[0]
                next_step[0] = step + 1
            out.put((step, e))
            return
        while not stop.is_set():
            with cv:
                # Backpressure: never run more than `depth` steps ahead of
                # the consumer, even across the reorder buffer — otherwise a
                # slow step would let the other workers produce (and retain)
                # arbitrarily many batches.
                while (
                    not stop.is_set()
                    and next_step[0] < num_steps
                    and next_step[0] - consumed[0] >= depth + 1
                ):
                    cv.wait(timeout=0.1)
                step = next_step[0]
                if stop.is_set() or step >= num_steps:
                    return
                next_step[0] = step + 1
                busy[0] += 1
            t0 = time.perf_counter()
            try:
                batch = make_batch(step)
            except Exception as e:  # surface errors to the consumer
                if prof is not None:
                    # the counter + an error span make the death visible
                    # in a scrape even while the consumer is mid-step
                    prof[2]("prefetch_worker_errors")
                    try:
                        from euler_tpu.telemetry import record_span

                        record_span(
                            int((time.perf_counter() - t0) * 1e6),
                            outcome=1,
                        )
                    except Exception:
                        pass
                with cv:
                    busy[0] -= 1
                out.put((step, e))
                return
            if prof is not None:
                if record_sample:
                    prof[0](
                        "sample", (time.perf_counter() - t0) * 1e6,
                        step=step + start,
                    )
                prof[2]("prefetch_produced")
            with cv:
                busy[0] -= 1
            out.put((step, batch))

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(num_threads)
    ]
    for t in threads:
        t.start()
    # Reorder: batches may complete out of order with >1 worker. The
    # pending dict is bounded by depth+1 thanks to the backpressure.
    pending: dict[int, object] = {}
    try:
        for want in range(num_steps):
            t_wait = time.perf_counter()
            while want not in pending:
                step, item = out.get()
                pending[step] = item
            if prof is not None:
                record, gauges, _ = prof
                record(
                    "input_stall",
                    (time.perf_counter() - t_wait) * 1e6,
                    step=want + start,
                )
                # ready batches beyond the one about to be consumed
                gauges(out.qsize() + len(pending) - 1, busy[0])
            item = pending.pop(want)
            if isinstance(item, Exception):
                raise item
            yield item
            with cv:
                consumed[0] = want + 1
                cv.notify_all()
    finally:
        stop.set()
        with cv:
            cv.notify_all()
        for t in threads:
            t.join(timeout=1.0)
        if prof is not None:
            # batches produced but never consumed (early close / error
            # teardown): the pipeline-efficiency side of the ledger
            dropped = sum(
                1 for v in pending.values()
                if not isinstance(v, Exception)
            )
            while True:
                try:
                    _, item = out.get_nowait()
                except queue.Empty:
                    break
                if not isinstance(item, Exception):
                    dropped += 1
            if dropped:
                prof[2]("prefetch_dropped", dropped)


def pipeline(
    start_fn: Callable[[int], object],
    finish_fn: Callable[[int, object], dict],
    num_steps: int,
    depth: int = 2,
    start: int = 0,
    worker_init: Callable[[int], None] | None = None,
    profile: bool | None = None,
    record_sample: bool = True,
) -> Iterator[dict]:
    """Depth-N in-flight step ring over a SPLIT sampler (train.py
    ``sampler_depth=``): yield num_steps batches for steps
    start..start+num_steps, kept ``depth`` submits ahead of consumption.

    Where :func:`prefetch` overlaps steps by running whole ``make_batch``
    calls on Python worker threads, this overlaps them at the native
    layer: ``start_fn(step)`` SUBMITS the step's sampling without
    blocking (remote graphs: one eg_remote_sample_async op whose hop
    chain runs on the client's dispatcher pool — no Python thread holds
    the step open) and returns a pending token; ``finish_fn(step,
    pending)`` blocks on that token and assembles the batch. One driver
    thread keeps up to ``depth`` steps submitted, finishes them strictly
    in order, and lands results in the same bounded queue / phase
    instrumentation contract as prefetch — the consumer loop, the
    ``input_stall`` histogram, the ``eg_prefetch_*`` gauges (queue depth
    + in-flight submits), and the produced/dropped/worker-error counters
    all read identically, so train()'s consumer side is unchanged.

    Exceptions from either fn surface at the consumer's matching step,
    like prefetch; pending tokens submitted after a failure are dropped
    (their native slots recycle via the handle finalizer).
    """
    from collections import deque

    prof = _profiler() if profile in (None, True) else None
    depth = max(1, int(depth))
    if start:
        base_start, base_finish = start_fn, finish_fn
        start_fn = lambda step: base_start(step + start)  # noqa: E731
        finish_fn = (  # noqa: E731
            lambda step, pending: base_finish(step + start, pending)
        )
    # bounded: in-flight native submits are capped by the ring, finished
    # batches by the queue — the driver blocks on put when the consumer
    # falls behind, so at most depth submitted + depth+1 finished exist
    out: "queue.Queue" = queue.Queue(maxsize=depth + 1)
    stop = threading.Event()
    busy = [0]  # steps currently submitted but not yet finished

    def put(step, item) -> bool:
        while not stop.is_set():
            try:
                out.put((step, item), timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def driver():
        try:
            if worker_init is not None:
                worker_init(0)
        except Exception as e:
            if prof is not None:
                prof[2]("prefetch_worker_errors")
            put(0, e)
            return
        inflight: "deque[tuple[int, object]]" = deque()
        step = 0
        cur = 0
        try:
            while not stop.is_set() and (inflight or step < num_steps):
                while step < num_steps and len(inflight) < depth:
                    inflight.append((step, start_fn(step)))
                    step += 1
                    busy[0] = len(inflight)
                cur, pending = inflight.popleft()
                t0 = time.perf_counter()
                batch = finish_fn(cur, pending)
                busy[0] = len(inflight)
                if prof is not None:
                    if record_sample:
                        prof[0](
                            "sample", (time.perf_counter() - t0) * 1e6,
                            step=cur + start,
                        )
                    prof[2]("prefetch_produced")
                if not put(cur, batch):
                    return
        except Exception as e:
            if prof is not None:
                prof[2]("prefetch_worker_errors")
                try:
                    from euler_tpu.telemetry import record_span

                    record_span(0, outcome=1)
                except Exception:
                    pass
            # tokens still in the ring are abandoned; their handles'
            # finalizers recycle the native slots
            put(cur if cur >= 0 else 0, e)

    t = threading.Thread(target=driver, daemon=True)
    t.start()
    try:
        for want in range(num_steps):
            t_wait = time.perf_counter()
            _, item = out.get()  # driver produces strictly in order
            if prof is not None:
                record, gauges, _ = prof
                record(
                    "input_stall",
                    (time.perf_counter() - t_wait) * 1e6,
                    step=want + start,
                )
                gauges(out.qsize(), busy[0])
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
        t.join(timeout=1.0)
        if prof is not None:
            dropped = 0
            while True:
                try:
                    _, item = out.get_nowait()
                except queue.Empty:
                    break
                if not isinstance(item, Exception):
                    dropped += 1
            if dropped:
                prof[2]("prefetch_dropped", dropped)
