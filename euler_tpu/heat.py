"""Data-plane heat surface over the native access profiler.

The native layer (graph/_native/eg_heat.{h,cc}) tracks WHICH vertex ids
the cluster touches: a space-saving top-K hot-key table plus a count-min
sketch per side (client feeds post-coalesce per op, shard services feed
pre-execute per op + requesting conn), client fan-out attribution for
SampleNeighbor/GetDenseFeature (ids_requested / ids_after_dedup /
cache_hits / ids_on_wire / shards touched / bytes per shard), and
cache-efficacy classes (hits/misses/evictions bucketed by the key's
sketch-estimated frequency). This module is the Python half:

    euler_tpu.heat_json()            this process's full heat dump
    euler_tpu.heat_json(g, shard)    a live shard's dump (kHeat opcode)
    euler_tpu.heat_topk()            hot ids, hottest first
    euler_tpu.heat_topk(g, shard=1)  a live shard's hot ids
    euler_tpu.set_heat(False)        process-global kill-switch

plus `set_heat_topk()` (tracker capacity), `heat_reset()`,
`record_heat()` (feed an app-level id stream through the same
primitive), and `estimate()` (count-min point estimate). Config keys
`heat=` / `heat_topk=` reach the same switches through graph config
(remote mode) and service options. Everything also rides the existing
telemetry surfaces: the `heat` section of `telemetry_json()` / the
STATS scrape, `heat_spread:<op>` histograms in the shared `hist` map,
and the `eg_heat_*` Prometheus families of `metrics_text()`
(OBSERVABILITY.md "Data-plane heat").
"""

from __future__ import annotations

import ctypes
import math

import numpy as np

from euler_tpu.graph.native import lib
from euler_tpu.telemetry import _json_abi

# Side selectors of the native layer (eg_heat.h HeatSide).
SIDES = ("client", "server")

# Wire-op names in native slot order (eg_telemetry.h kWireOpNames);
# record_heat() maps op names through this, pinned by tests.
OP_NAMES = (
    "other", "ping", "info", "sample_node", "sample_edge", "node_type",
    "sample_neighbor", "full_neighbor", "topk_neighbor", "dense_feature",
    "edge_dense_feature", "sparse_feature", "edge_sparse_feature",
    "binary_feature", "edge_binary_feature", "node_weight",
    "sample_neighbor_uniq", "stats", "history", "heat", "placement",
)


def heat_json(graph=None, shard: int | None = None) -> dict:
    """Full heat dump: top-K tables (ids as Python ints), sketch
    geometry + totals, per-(side, op) ids ledger, client fan-out
    attribution, per-shard wire bytes, per-conn server ledger, and
    cache-efficacy classes.

    No arguments: this process. With (graph, shard): one live shard's
    dump over the kHeat wire opcode (the graph's ordinary transport
    config applies)."""
    if graph is None:
        data = _json_abi(lambda buf, cap: lib().eg_heat_json(buf, cap))
    else:
        if getattr(graph, "mode", None) != "remote":
            raise ValueError("heat_json(graph=...) needs a mode='remote' "
                             "graph (a local graph IS this process)")
        if shard is None:
            raise ValueError("heat_json(graph=...) needs shard=")
        h = graph._h
        data = _json_abi(
            lambda buf, cap: lib().eg_remote_heat(h, shard, buf, cap)
        )
    for side in SIDES:
        for e in data["topk"][side]:
            e["id"] = int(e["id"])  # decimal string on the wire (u64-safe)
    return data


def heat_topk(graph=None, shard: int | None = None, side: str = "client",
              k: int | None = None) -> list:
    """Hot ids, hottest first: [{"id", "count", "err"}]. `count` upper-
    bounds the true feed count and `count - err` lower-bounds it
    (space-saving guarantee; err == 0 means exact). Local by default;
    (graph, shard) scrapes a live shard — use side="server" there (a
    shard process's client table is empty)."""
    data = heat_json(graph, shard)
    if side not in SIDES:
        raise ValueError(f"side must be one of {SIDES}")
    top = data["topk"][side]
    return top[:k] if k is not None else top


def heat_enabled() -> bool:
    return lib().eg_heat_enabled() == 1


def set_heat(on: bool) -> None:
    """Process-global heat kill-switch (`heat=` config key). The master
    telemetry switch gates it too: `telemetry=0` silences heat even
    when this flag is on."""
    lib().eg_heat_set_enabled(1 if on else 0)


def set_heat_topk(k: int) -> None:
    """Resize the hot-key tracker (`heat_topk=` config key; clamped to
    the fixed native pool). Resets the tables — space-saving guarantees
    only hold for a capacity kept over the whole stream."""
    lib().eg_heat_set_topk(int(k))


def heat_reset() -> None:
    """Zero sketches, top-K tables, ledgers and cache classes (the
    enabled flag and tracker capacity survive)."""
    lib().eg_heat_reset()


def record_heat(ids, op: str | int = "other", side: str = "client") -> None:
    """Feed a batch of ids through the same primitive the native hook
    points use — app-level access streams, and the exactness tests that
    pin the sketch against ground-truth counts."""
    arr = np.ascontiguousarray(np.asarray(ids).reshape(-1))
    if arr.dtype != np.uint64:
        arr = arr.astype(np.int64, copy=False).view(np.uint64)
    op_i = OP_NAMES.index(op) if isinstance(op, str) else int(op)
    lib().eg_heat_record(
        SIDES.index(side), op_i,
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(arr),
    )


def estimate(id: int, side: str = "client") -> int:
    """Count-min point estimate for one id: >= its true feed count
    always; overestimates bounded by (e/width) * stream-length per
    query with probability 1 - e^-depth (geometry in the dump's
    ``sketch`` section, pinned by tests)."""
    u64 = int(np.int64(id).view(np.uint64)) if id < 0 else int(id)
    return int(lib().eg_heat_estimate(SIDES.index(side), u64))


def zipf_fit(topk: list) -> dict:
    """Least-squares fit of log(count) ~ -alpha * log(rank) over a
    top-K table (hottest first): the tail exponent of the access skew.
    Returns {"alpha", "r2", "n"}; {} when under 3 points."""
    counts = [e["count"] for e in topk if e["count"] > 0]
    if len(counts) < 3:
        return {}
    x = np.log(np.arange(1, len(counts) + 1, dtype=np.float64))
    y = np.log(np.asarray(counts, dtype=np.float64))
    alpha, intercept = np.polyfit(x, y, 1)
    pred = alpha * x + intercept
    ss_res = float(np.sum((y - pred) ** 2))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    return {"alpha": round(float(-alpha), 4), "r2": round(r2, 4),
            "n": len(counts)}


def cache_hit_ceiling(topk: list, total: int, capacity_rows: int) -> dict:
    """Projected hit rate of a frequency-aware cache that pinned the
    `capacity_rows` hottest ids: sum of (count - 1) over the top-C ids
    divided by the total access stream (every access after an id's
    first is a hit). Counts beyond the tracked top-K are extrapolated
    from the Zipf fit; with capacity <= K the projection is exact up to
    the space-saving err bounds."""
    if total <= 0 or not topk:
        return {}
    counts = [e["count"] for e in topk]
    k = len(counts)
    cap = max(int(capacity_rows), 0)
    hits = sum(c - 1 for c in counts[:min(cap, k)])
    # guaranteed floor: space-saving only promises true >= count - err,
    # so a churned table (large errs) must not inflate the projection
    hits_lb = sum(
        max(e["count"] - e["err"] - 1, 0) for e in topk[:min(cap, k)]
    )
    extrapolated = 0
    if cap > k:
        fit = zipf_fit(topk)
        if fit:
            # extend the fitted power law over ranks k+1..cap
            c_k = counts[-1]
            alpha = fit["alpha"]
            for r in range(k + 1, cap + 1):
                c_r = c_k * (r / k) ** (-alpha)
                if c_r < 1.0:
                    break
                extrapolated += c_r - 1.0
    ceiling = min(1.0, (hits + extrapolated) / total)
    return {
        "capacity_rows": cap,
        "projected_hit_rate": round(ceiling, 4),
        "projected_hit_rate_lb": round(min(1.0, hits_lb / total), 4),
        "from_tracked_topk": round(min(1.0, hits / total), 4),
        "extrapolated": extrapolated > 0,
    }


def topk_share(data: dict, side: str = "client") -> float:
    """Share of the side's whole access stream absorbed by its tracked
    top-K ids — the one-number skew headline (1.0 = every access was a
    tracked hot id)."""
    total = data["sketch"]["total"][side]
    if not total:
        return 0.0
    return min(1.0, sum(e["count"] for e in data["topk"][side]) / total)


# epsilon of the count-min bound, derived from the dump's geometry
def cms_epsilon(data: dict) -> float:
    """e/width: with probability 1 - e^-depth an estimate exceeds the
    true count by at most epsilon * total-ids-fed."""
    return math.e / data["sketch"]["width"]
