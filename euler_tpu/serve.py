"""eg_serve: online embedding inference over a trained checkpoint.

The serving gap named by ROADMAP item 3: everything below this module
already exists — the graph client (local or sharded-remote, with PR-9
placement routing and neighbor/feature caches), the trained checkpoint
(checkpoint.py), the jitted embed step (Model.make_embed_step) — and
nothing answered "embed these user ids". This module wires them into a
server:

    request -> MicroBatcher (coalesce + shed + deadline)
            -> per-unique-id neighborhood sampling (graph client)
            -> one padded-bucket jitted forward -> rows per request

Determinism is a serving feature here, not an accident: each id's
neighborhood is sampled ONCE with an id-derived native RNG seed and
cached (``--serve_sample_cache``), so an id's embedding is bit-stable
across requests, across co-batched traffic, and identical to
:meth:`EmbedServer.embed_direct` — the parity anchor the serve tests
and the load drill pin.

Every dispatch pads to ONE fixed bucket (``max_batch`` rows, padding
repeats a real sampled block), so a single XLA program serves all
traffic. That is what makes the parity claim honest: within one
program, row-wise model math is position- and padding-independent
(pinned by tests), while XLA re-tiles per SHAPE — empirically, the
same row differs ~1e-6 between a size-1 and a size-8 program, so
variable buckets could never promise bit-identity. Phase telemetry
rides the native
``serve:*`` histograms; admission/shedding rides the ``serve_*``
counters (FAULTS.md).

Usage (inference-mode sampling, all_edge_type metapaths — the
evaluate/save_embedding convention):

    python -m euler_tpu.serve --data_dir ... --model graphsage_supervised \
        --model_dir ckpt --serve_port 9200 [--serve_slo_ms 50] ...

or train-then-serve in one process: ``python -m euler_tpu ...
--serve_after=1`` (run_loop; serves with the training sampling config).
"""

from __future__ import annotations

import logging
import signal
import sys
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from euler_tpu import devprof
from euler_tpu import telemetry as T
from euler_tpu.graph import native
from euler_tpu.serving import MicroBatcher, SLOTracker, EmbedFrontend

log = logging.getLogger("euler_tpu.serve")

_MIX = 0x9E3779B97F4A7C15  # splitmix64 increment
_MASK = (1 << 64) - 1


def _id_seed(seed: int, nid: int) -> int:
    """Deterministic nonzero 64-bit RNG seed for one (server seed, id)."""
    h = (nid * _MIX + seed * 0xBF58476D1CE4E5B9 + 0x94D049BB133111EB) & _MASK
    h ^= h >> 31
    return (h * _MIX) & _MASK or 1


class EmbedServer:
    """Micro-batched embedding inference over one model + graph + state.

    ``state`` is a restored (or freshly initialized) train-state pytree
    — the same structure Checkpointer.restore returns. The graph client
    carries its own transport config (retries/deadline_ms/caches), so a
    sharded-remote deployment needs nothing extra here: configure the
    Graph with ``deadline_ms`` at or under the serve deadline and every
    sampling RPC inherits the budget.
    """

    def __init__(self, model, graph, state, *, max_batch: int = 64,
                 max_wait_us: int = 2000, queue_cap: int = 128,
                 slo_ms: float = 100.0, seed: int = 42,
                 sample_cache: int = 65536,
                 strict_bucket: bool = False):
        import jax

        if getattr(model, "device_sampling", False):
            raise ValueError(
                "EmbedServer samples neighborhoods on the host per "
                "unique id (the determinism anchor); build the serving "
                "model with device_sampling=False"
            )
        self.model = model
        self.graph = graph
        self.max_batch = int(max_batch)
        self.seed = int(seed)
        self.sample_cache = max(int(sample_cache), 1)
        self._state = state
        self._jax = jax
        # Compile-storm guard (OBSERVABILITY.md "Device plane"): the
        # fixed-bucket design means ONE compile, ever — any post-warmup
        # recompile is a broken bucket contract (and a silent 100x), so
        # it bumps serve_recompiles + journals the shape diff; with
        # strict_bucket= it raises devprof.RecompileError.
        self._embed_fn = devprof.watch(
            jax.jit(model.make_embed_step()),
            name="embed_step",
            strict=strict_bucket,
            on_recompile=lambda e: native.counter_add("serve_recompiles"),
        )
        self._cache: OrderedDict = OrderedDict()
        self._cache_lock = threading.Lock()
        self.slo = SLOTracker(slo_ms)
        self.batcher = MicroBatcher(
            self._embed_unique,
            max_batch=max_batch,
            max_wait_us=max_wait_us,
            queue_cap=queue_cap,
            on_done=self._on_done,
        )

    # ---- lifecycle ----

    def start(self) -> "EmbedServer":
        self.batcher.start()
        return self

    def close(self) -> None:
        self.batcher.close()

    def __enter__(self) -> "EmbedServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- request path ----

    def embed(self, ids, deadline_ms: Optional[float] = None) -> np.ndarray:
        """Embeddings for ids through the micro-batcher: [n, dim]
        float32, one row per requested id (duplicates allowed). Raises
        serving.BusyError / serving.DeadlineError on shed/expiry."""
        return np.asarray(
            self.batcher.submit(ids, deadline_ms=deadline_ms),
            dtype=np.float32,
        )

    def embed_direct(self, nid: int) -> np.ndarray:
        """Reference path: one id, no micro-batching — the bit-parity
        anchor the batched path is pinned against."""
        return np.asarray(
            self._forward([self._block(int(nid))])[0], dtype=np.float32
        )

    def stats(self) -> dict:
        """Live serving stats (the frontend's ``stats`` op): SLO
        verdict, serve-phase percentiles, serve counters, coalescing
        ledger."""
        hists = T.serve_hists()
        phases = {}
        for name, h in hists.items():
            if not h["count"]:
                continue
            pct = T.percentiles(h, (50, 99))
            phases[name] = {
                "count": h["count"],
                "p50_us": round(pct.get(50, 0.0), 1),
                "p99_us": round(pct.get(99, 0.0), 1),
            }
        ctr = {
            k: v for k, v in native.counters().items()
            if k.startswith("serve_")
        }
        batch_h = T.telemetry_json()["hist"].get("serve_batch", {})
        batch = {}
        if batch_h.get("count"):
            batch = {
                "dispatches": batch_h["count"],
                "mean_unique_ids": round(
                    batch_h["sum_us"] / batch_h["count"], 2
                ),
            }
        return {
            "slo": self.slo.report(),
            "serve_phases": phases,
            "counters": ctr,
            "batch": batch,
            "devprof": devprof.compile_summary(),
        }

    # ---- internals ----

    def _on_done(self, total_us: float, error) -> None:
        if error is None:
            self.slo.record(total_us)

    def _block(self, nid: int) -> dict:
        """One id's sampled model inputs — drawn once with an
        id-derived seed, then cached (hot ids sample zero times).

        Entries are keyed by the graph client's cache generation
        (Graph.cache_gen, bumped on every observed epoch flip): a hit
        sampled before a rolling graph refresh evicts and resamples
        against the new snapshot (counted epoch_stale_hits_evicted, the
        same ledger the native feature/neighbor caches use), so the
        bit-stability promise holds *within* an epoch — exactly the
        window in which it is meaningful."""
        gen = getattr(self.graph, "cache_gen", 0)
        with self._cache_lock:
            ent = self._cache.get(nid)
            if ent is not None:
                if ent[0] == gen:
                    self._cache.move_to_end(nid)
                    return ent[1]
                del self._cache[nid]
                native.counter_add("epoch_stale_hits_evicted", 1)
        native.lib().eg_seed(_id_seed(self.seed, nid))
        blk = self.model.sample_embed(
            self.graph, np.array([nid], dtype=np.int64)
        )
        with self._cache_lock:
            self._cache[nid] = (gen, blk)
            while len(self._cache) > self.sample_cache:
                self._cache.popitem(last=False)
        return blk

    def _forward(self, blocks: list) -> np.ndarray:
        """One fixed-bucket device dispatch over per-id blocks: always
        padded to max_batch rows, so ONE jitted program serves every
        dispatch — the bit-parity guarantee (see module docstring)."""
        n = len(blocks)
        padded = blocks + [blocks[0]] * (self.max_batch - n)
        batch = self._jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
            *padded,
        )
        devprof.count_h2d(batch)
        emb = self._jax.block_until_ready(
            self._embed_fn(self._state, batch)
        )
        devprof.count_d2h(emb)
        return np.asarray(emb)[:n]

    def _embed_unique(self, uids: np.ndarray) -> np.ndarray:
        """The batcher's callback: sample per unique id (cached), then
        dispatch in max_batch-sized chunks."""
        t0 = time.monotonic()
        blocks = [self._block(int(i)) for i in uids]
        T.record_serve_phase("sample", (time.monotonic() - t0) * 1e6)
        t1 = time.monotonic()
        outs = [
            self._forward(blocks[off:off + self.max_batch])
            for off in range(0, len(blocks), self.max_batch)
        ]
        T.record_serve_phase("dispatch", (time.monotonic() - t1) * 1e6)
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)


def restore_serving_state(model, graph, args, mesh):
    """Initialize the state structure and restore the checkpoint from
    --model_dir — REQUIRED here: serving fresh random params is a bug,
    so unlike training's resume path this raises (Checkpointer.restore's
    loud ValueError) when the directory has no checkpoint."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.parallel import pad_tables_for_mesh

    opt = train_lib.get_optimizer(args.optimizer, args.learning_rate)
    example = np.asarray(
        graph.sample_node(args.batch_size, args.train_node_type)
    )
    state = model.init_state(
        jax.random.PRNGKey(args.seed), graph, example, opt
    )
    state = pad_tables_for_mesh(state, mesh)
    ckpt = Checkpointer(args.model_dir)
    try:
        state = ckpt.restore(state)
    finally:
        ckpt.close()
    return state


def build_server(model, graph, args, mesh) -> EmbedServer:
    """EmbedServer from the run_loop flag surface + a restored
    checkpoint."""
    state = restore_serving_state(model, graph, args, mesh)
    return EmbedServer(
        model, graph, state,
        max_batch=args.serve_max_batch,
        max_wait_us=args.serve_max_wait_us,
        queue_cap=args.serve_queue_cap,
        slo_ms=args.serve_slo_ms,
        seed=args.seed,
        sample_cache=args.serve_sample_cache,
        strict_bucket=bool(args.serve_strict_bucket),
    )


def run_serve(model, graph, args, mesh, block: bool = True):
    """Start the embedding server + frontend (run_loop --serve_after
    and the serve CLI both land here).

    ``block=True`` serves until SIGTERM/SIGINT, draining on the way out
    (the rolling-restart contract: stop accepting, finish in-flight,
    drain the batch queue). ``block=False`` returns the live
    ``(server, frontend)`` for in-process callers/tests — the caller
    owns ``frontend.stop()`` + ``server.close()``."""
    server = build_server(model, graph, args, mesh).start()
    frontend = EmbedFrontend(
        server,
        host=args.serve_host,
        port=args.serve_port,
        max_conns=args.serve_max_conns,
        default_deadline_ms=args.serve_deadline_ms,
    )
    log.info(
        "serving embeddings on %s (max_batch=%d max_wait_us=%d "
        "queue_cap=%d slo_ms=%g)", frontend.address,
        args.serve_max_batch, args.serve_max_wait_us,
        args.serve_queue_cap, args.serve_slo_ms,
    )
    if not block:
        return server, frontend
    stop = threading.Event()

    def _stop(signum, _frame):
        log.info("signal %d: draining embedding server", signum)
        stop.set()

    prev = {
        s: signal.signal(s, _stop)
        for s in (signal.SIGTERM, signal.SIGINT)
    }
    try:
        while not stop.wait(0.5):
            pass
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        frontend.drain()
        server.close()  # drains the queued batches
        frontend.stop()
        report = server.slo.report()
        log.info("serve SLO at exit: %s", report)
    return server, frontend


def main(argv=None) -> int:
    """`python -m euler_tpu.serve`: serve a trained checkpoint.

    Reuses the run_loop flag surface (graph/model/checkpoint flags mean
    the same thing) + the serve flags; the model is built with the
    INFERENCE sampling config (all_edge_type metapaths — the
    evaluate/save_embedding convention), so --mode is ignored."""
    from euler_tpu import run_loop

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    logging.getLogger("absl").setLevel(logging.WARNING)
    from euler_tpu.parallel import (
        honor_jax_platforms_env,
        make_mesh,
        probe_backend_or_die,
    )

    honor_jax_platforms_env()
    args = run_loop.define_flags().parse_args(argv)
    args.mode = "evaluate"  # inference sampling config (all_edge_type)
    probe_backend_or_die()
    if not args.telemetry:
        T.set_telemetry(False)
    # device plane + compile cache before the embed jit: the serve
    # forward is the program the cache saves a relaunch from
    # recompiling, and the compile-storm guard needs the listener live
    devprof.setup(enabled=args.devprof, compile_cache=args.compile_cache,
                  model_dir=args.model_dir, sample_ms=1000)
    graph, services = run_loop.build_graph(args)
    try:
        mesh = make_mesh(args.num_devices,
                         model_parallel=args.model_parallel)
        model = run_loop.build_model(args, graph)
        run_serve(model, graph, args, mesh, block=True)
    finally:
        ledger = {k: v for k, v in native.counters().items() if v}
        if ledger:
            log.info("serve counters at exit: %s", ledger)
        for s in services:
            if hasattr(s, "drain"):
                s.drain()
            s.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
