"""Observability surface over the native telemetry subsystem.

The native layer (graph/_native/eg_telemetry.{h,cc}) records log2-
bucketed latency histograms per RPC op (client whole-call, server
handler, server queue wait, dial, retry backoff), keeps a slowest-N
span journal on each side correlated by wire-propagated trace ids, and
answers the STATS wire opcode with one JSON dump of everything plus the
admission gauges. This module is the Python half:

    euler_tpu.metrics_text()            Prometheus text, local process
    euler_tpu.metrics_text(graph=g)     every shard of a live cluster
    euler_tpu.slow_spans()              local slow-span journal
    euler_tpu.scrape(g, shard)          one shard's raw telemetry dict
    euler_tpu.set_telemetry(False)      process-global kill-switch

plus the step-phase profiler surface (native eg_phase.{h,cc}): the
training loop and prefetch pipeline record per-step phase timers
(input_stall / sample / h2d / device / host / step) and prefetch
pipeline gauges through :func:`record_phase` /
:func:`record_prefetch_gauges`; they land in the same native "hist" map
as the RPC latency histograms, so metrics_text(), snapshot(), the STATS
scrape, and scripts/metrics_dump.py all report them with one renderer
(OBSERVABILITY.md "Step phases"), and the percentile/bucket arithmetic
here is shared with scripts/metrics_dump.py and the --metrics_every
JSONL emitter used by run_loop.
"""

from __future__ import annotations

import ctypes
import json
import time

from euler_tpu.graph.native import lib

# Bucket layout — MUST match eg_telemetry.h HistBucketOf: bucket 0 =
# [0, 1µs); bucket b (1..26) = [2^(b-1), 2^b) µs; bucket 27 = [2^26, inf).
NUM_BUCKETS = 28

# Step-phase order — MUST match eg_phase.h StepPhase (the profiler
# records by index through the eg_phase_record ABI, pinned by tests).
# "compile" is the device-plane add-on (euler_tpu/devprof.py): XLA
# backend compile wall time, NOT part of the step-sum identity.
PHASES = ("input_stall", "sample", "h2d", "device", "host", "step",
          "compile")

# Serve-request phase order — MUST match eg_phase.h ServePhase (the
# serving layer records by index through the eg_serve_record ABI,
# pinned by tests). OBSERVABILITY.md "Serve phases".
SERVE_PHASES = ("queue_wait", "sample", "dispatch", "total")


def bucket_of(us: int) -> int:
    """Bucket index of a microsecond value (the Python twin of the
    native HistBucketOf, pinned against it by tests)."""
    if us <= 0:
        return 0
    b = int(us).bit_length()
    return min(b, NUM_BUCKETS - 1)


def bucket_edges_us() -> list:
    """Upper bucket edges in µs (27 finite edges, last bucket +Inf)."""
    return [1 << b for b in range(NUM_BUCKETS - 1)]


def percentiles(hist: dict, qs=(50, 90, 99)) -> dict:
    """Estimate percentiles from one histogram dict ({"b": [...],
    "count": n, "sum_us": s}) by linear interpolation inside the
    containing log2 bucket. Returns {q: µs float}; empty hist -> {}."""
    buckets = hist["b"]
    total = sum(buckets)
    if total == 0:
        return {}
    out = {}
    for q in qs:
        rank = q / 100.0 * total
        acc = 0.0
        for b, n in enumerate(buckets):
            if n == 0:
                continue
            if acc + n >= rank:
                lo = 0.0 if b == 0 else float(1 << (b - 1))
                # the open-ended last bucket gets a 2x-wide estimate span
                hi = float(1 << b) if b < NUM_BUCKETS - 1 else lo * 2.0
                frac = (rank - acc) / n
                out[q] = lo + (hi - lo) * frac
                break
            acc += n
    return out


# ---------------------------------------------------------------------------
# native calls
# ---------------------------------------------------------------------------


def _json_abi(call) -> dict:
    """Run a (buf, cap) -> needed-length ABI call, growing the buffer
    until the dump fits, and parse the JSON."""
    cap = 1 << 16
    while True:
        buf = ctypes.create_string_buffer(cap)
        n = call(buf, cap)
        if n < 0:
            raise RuntimeError(lib().eg_last_error().decode())
        if n < cap:
            return json.loads(buf.value.decode())
        cap = n + 1


def telemetry_json() -> dict:
    """This process's full telemetry dump: counters, span-timer stats,
    every histogram, the slow-span journal (no admission gauges — those
    belong to a serving process and arrive via :func:`scrape`)."""
    return _json_abi(lambda buf, cap: lib().eg_telemetry_json(buf, cap))


def scrape(graph, shard: int) -> dict:
    """Scrape one live shard's telemetry over the STATS wire opcode.

    Returns the shard process's dump — same shape as
    :func:`telemetry_json` plus a ``gauges`` section (handler pool size,
    workers busy, queue depth, open conns, draining) — fetched with the
    graph's ordinary transport config (retries, deadline, failover)."""
    if getattr(graph, "mode", None) != "remote":
        raise ValueError("scrape() needs a mode='remote' graph "
                         "(a local graph IS this process: use "
                         "telemetry_json())")
    h = graph._h
    return _json_abi(
        lambda buf, cap: lib().eg_remote_scrape(h, shard, buf, cap)
    )


def ping(graph, shard: int) -> bool:
    """One kPing round trip to ``shard`` through the full transport
    stack (retries, deadline, wire negotiation) — the health probe a
    readiness check should use, because it exercises exactly the path
    real calls take. True when the shard answered."""
    if getattr(graph, "mode", None) != "remote":
        raise ValueError("ping() needs a mode='remote' graph")
    return lib().eg_remote_ping(graph._h, shard) == 1


def telemetry_enabled() -> bool:
    return lib().eg_telemetry_enabled() == 1


def set_telemetry(on: bool) -> None:
    """Process-global telemetry kill-switch (`telemetry=` config key):
    False stops histogram + slow-span recording everywhere (counters
    and span-timer stats keep working — they predate this subsystem)."""
    lib().eg_telemetry_set_enabled(1 if on else 0)


def telemetry_reset() -> None:
    """Zero every histogram and both-side span journals (the enabled
    flag and journal capacity survive)."""
    lib().eg_telemetry_reset()


def set_slow_capacity(n: int) -> None:
    """Resize the slowest-N span journal (`slow_spans=` config key)."""
    lib().eg_telemetry_set_slow_capacity(int(n))


# ---------------------------------------------------------------------------
# step-phase profiler (native eg_phase.h; OBSERVABILITY.md "Step phases")
# ---------------------------------------------------------------------------

# Optional per-event sink the trace recorder (euler_tpu/trace.py)
# registers: fn(phase, us, step) called on every record_phase while a
# trace capture is active. None (the default) costs one global read.
_trace_sink = None


def set_trace_sink(fn) -> None:
    """Install (or clear, with None) the per-event phase sink — the
    trace recorder's tap into :func:`record_phase`."""
    global _trace_sink
    _trace_sink = fn


def record_phase(phase: str, us: float, step: int | None = None) -> None:
    """One step-phase µs sample (train loop / prefetch pipeline call
    sites). Lands in the ``phase:<name>`` histogram of
    :func:`telemetry_json` (kill-switch honored natively) and, while a
    trace capture is active, in the trace recorder's event buffer."""
    lib().eg_phase_record(PHASES.index(phase), max(int(us), 0))
    sink = _trace_sink
    if sink is not None:
        sink(phase, us, step)


def record_prefetch_gauges(queue_depth: int, workers_busy: int) -> None:
    """One prefetch-pipeline sample at consumer dequeue: ready batches
    waiting and workers inside make_batch — the two value histograms
    that tell queue starvation (depth pinned at 0, workers busy) apart
    from slow/dead workers (depth 0, workers idle)."""
    L = lib()
    L.eg_phase_gauge(0, max(int(queue_depth), 0))
    L.eg_phase_gauge(1, max(int(workers_busy), 0))


def record_serve_phase(phase: str, us: float) -> None:
    """One serve-request phase µs sample (euler_tpu/serving call
    sites). Lands in the ``serve:<name>`` histogram of
    :func:`telemetry_json`; the kill-switch is honored natively, so
    ``telemetry=0`` leaves the serve hot path histogram-free."""
    lib().eg_serve_record(SERVE_PHASES.index(phase), max(int(us), 0))


def record_serve_batch(unique_ids: int) -> None:
    """One micro-batch device dispatch: unique ids in the batch. Count
    over the ``serve_batch`` value histogram is dispatches, sum is ids —
    their ratio the request-coalescing factor."""
    lib().eg_serve_batch(max(int(unique_ids), 0))


def serve_hists(data: dict | None = None) -> dict:
    """{phase: histogram dict} for the serve-request phases, extracted
    from a telemetry dump (default: this process's)."""
    data = data or telemetry_json()
    return {
        key.partition(":")[2]: h
        for key, h in data["hist"].items()
        if key.startswith("serve:")
    }


def phase_hists(data: dict | None = None) -> dict:
    """{phase: histogram dict} extracted from a telemetry dump
    (default: this process's)."""
    data = data or telemetry_json()
    return {
        key.partition(":")[2]: h
        for key, h in data["hist"].items()
        if key.startswith("phase:")
    }


def record_span(total_us: int, op: int = 0, side: str = "client",
                outcome: int = 0, shard: int = -1, trace: int = 0,
                queue_us: int = 0, handler_us: int = 0,
                wire_us: int = 0) -> None:
    """Offer an app-level span to the local journal (the same primitive
    the native transport sites use)."""
    lib().eg_telemetry_record_span(
        1 if side == "server" else 0, int(op), int(outcome), int(shard),
        int(trace), int(queue_us), int(handler_us), int(wire_us),
        int(total_us),
    )


def slow_spans(graph=None, shard: int | None = None) -> list:
    """Slowest-N spans, slowest first: local journal by default, a live
    shard's when (graph, shard) name one. Trace ids come back as
    Python ints (0 = not propagated: v1/v2 peer or telemetry off)."""
    data = telemetry_json() if graph is None else scrape(graph, shard)
    spans = data["slow_spans"]
    for s in spans:
        s["trace"] = int(s["trace"])
    return spans


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

# kind -> (family, help, series-label for the per-kind key suffix;
# scalar kinds have no suffix and ignore the label)
_HIST_FAMILIES = {
    "client_call": ("eg_client_call_latency_us",
                    "Client whole-call latency per RPC op (retries "
                    "included), microseconds", "op"),
    "server_handler": ("eg_server_handler_latency_us",
                       "Server handler time per RPC op (decode + "
                       "execute + encode), microseconds", "op"),
    "server_queue": ("eg_server_queue_wait_us",
                     "Poller-ready to handler pickup wait, microseconds",
                     "op"),
    "dial": ("eg_dial_latency_us", "DialTcp latency, microseconds", "op"),
    "backoff": ("eg_retry_backoff_us",
                "Retry backoff sleeps, microseconds", "op"),
    "phase": ("eg_step_phase_us",
              "Training step-phase wall time (input_stall/sample/h2d/"
              "device/host/step, plus XLA compile), microseconds",
              "phase"),
    "prefetch_depth": ("eg_prefetch_queue_depth",
                       "Ready batches in the prefetch queue at consumer "
                       "dequeue (value histogram)", "op"),
    "prefetch_busy": ("eg_prefetch_workers_busy",
                      "Prefetch workers inside make_batch at consumer "
                      "dequeue (value histogram)", "op"),
    "heat_spread": ("eg_heat_shard_spread",
                    "Shards touched per client call (value histogram "
                    "per op — data-plane heat fan-out attribution)",
                    "op"),
    "serve": ("eg_serve_phase_us",
              "Serve-request phase wall time (queue_wait/sample/"
              "dispatch/total), microseconds", "phase"),
    "serve_batch": ("eg_serve_batch_ids",
                    "Unique ids per micro-batch device dispatch (value "
                    "histogram; count = dispatches, sum = ids)", "op"),
}

_GAUGE_FAMILIES = {
    "workers": ("eg_workers", "Fixed handler pool size"),
    "workers_active": ("eg_workers_active", "Workers currently serving"),
    "queue_depth": ("eg_queue_depth",
                    "Ready connections waiting for a worker"),
    "conns": ("eg_conns", "Admitted open connections"),
    "draining": ("eg_draining", "1 while the server drains"),
    "epoch": ("eg_epoch",
              "Current serving snapshot epoch (0 = base load; each "
              "applied delta flips it up by one)"),
}

# Process resource gauges (eg_blackbox.h: sampled live for every dump,
# background-sampled into the HISTORY ring, frozen into postmortems).
_RESOURCE_FAMILIES = {
    "rss_bytes": ("eg_rss_bytes",
                  "Resident set size of the process, bytes"),
    "open_fds": ("eg_open_fds", "Open file descriptors"),
    "threads": ("eg_threads", "Live OS threads"),
    "cache_bytes": ("eg_cache_bytes",
                    "Client feature-row cache resident bytes"),
    "nbr_cache_bytes": ("eg_nbr_cache_bytes",
                        "Client neighbor-list cache resident bytes"),
    "device_mem_bytes": ("eg_device_mem_bytes",
                         "Device (HBM) bytes in use — memory_stats() "
                         "where present, live-array census on CPU"),
    "device_mem_peak_bytes": ("eg_device_mem_peak_bytes",
                              "High-water mark of eg_device_mem_bytes "
                              "since start/reset"),
    "device_buffers": ("eg_device_buffers",
                       "Live device buffers at the last devprof sample"),
}


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
    return "{" + inner + "}"


def _render(sources: list) -> str:
    """Render [(telemetry dict, base labels), ...] as one Prometheus
    text exposition — families emitted once, series per source."""
    lines = []
    edges = bucket_edges_us()

    for kind, (fam, help_text, label) in _HIST_FAMILIES.items():
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} histogram")
        for data, base in sources:
            for key, h in sorted(data["hist"].items()):
                k, _, op = key.partition(":")
                if k != kind:
                    continue
                labels = dict(base)
                if op:
                    labels[label] = op
                cum = 0
                for b, n in enumerate(h["b"]):
                    cum += n
                    le = str(edges[b]) if b < len(edges) else "+Inf"
                    bl = dict(labels)
                    bl["le"] = le
                    lines.append(f"{fam}_bucket{_fmt_labels(bl)} {cum}")
                lines.append(
                    f"{fam}_sum{_fmt_labels(labels)} {h['sum_us']}"
                )
                lines.append(
                    f"{fam}_count{_fmt_labels(labels)} {h['count']}"
                )

    lines.append("# HELP eg_counter_total Transport/server event "
                 "counters (see FAULTS.md)")
    lines.append("# TYPE eg_counter_total counter")
    for data, base in sources:
        for name, v in sorted(data["counters"].items()):
            labels = dict(base)
            labels["name"] = name
            lines.append(f"eg_counter_total{_fmt_labels(labels)} {v}")

    lines.append("# HELP eg_stat_calls_total Span-timer call counts "
                 "per engine op")
    lines.append("# TYPE eg_stat_calls_total counter")
    for data, base in sources:
        for name, (count, total_ns, max_ns) in sorted(
            data["stats"].items()
        ):
            labels = dict(base)
            labels["op"] = name
            lines.append(
                f"eg_stat_calls_total{_fmt_labels(labels)} {count}"
            )

    for gkey, (fam, help_text) in _GAUGE_FAMILIES.items():
        emitted_header = False
        for data, base in sources:
            gauges = data.get("gauges")
            if gauges is None or gkey not in gauges:
                continue
            if not emitted_header:
                lines.append(f"# HELP {fam} {help_text}")
                lines.append(f"# TYPE {fam} gauge")
                emitted_header = True
            lines.append(f"{fam}{_fmt_labels(dict(base))} {gauges[gkey]}")

    for rkey, (fam, help_text) in _RESOURCE_FAMILIES.items():
        emitted_header = False
        for data, base in sources:
            resource = data.get("resource")
            if resource is None or rkey not in resource:
                continue
            if not emitted_header:
                lines.append(f"# HELP {fam} {help_text}")
                lines.append(f"# TYPE {fam} gauge")
                emitted_header = True
            lines.append(
                f"{fam}{_fmt_labels(dict(base))} {resource[rkey]}"
            )

    # live serve-SLO gauges (eg_devprof.h "serve_slo" section): the
    # windowed p50/p99 the SLOTracker pushes through the ABI, plus the
    # lifetime violation count — a scrape reads serving latency without
    # draining the server. Headers always (the section is always
    # emitted, zeros included).
    lines.append("# HELP eg_serve_slo_ms Serve request latency over the "
                 "SLO tracker window, milliseconds")
    lines.append("# TYPE eg_serve_slo_ms gauge")
    for data, base in sources:
        slo = data.get("serve_slo")
        if slo is None:
            continue
        for q in ("p50", "p99"):
            labels = dict(base)
            labels["quantile"] = q
            lines.append(
                f"eg_serve_slo_ms{_fmt_labels(labels)} "
                f"{slo[q + '_us'] / 1000.0:.3f}"
            )
    lines.append("# HELP eg_serve_slo_violations_total Lifetime serve "
                 "replies over the SLO target")
    lines.append("# TYPE eg_serve_slo_violations_total counter")
    for data, base in sources:
        slo = data.get("serve_slo")
        if slo is None:
            continue
        lines.append(
            f"eg_serve_slo_violations_total{_fmt_labels(dict(base))} "
            f"{slo['violations']}"
        )

    # data-plane heat (eg_heat.h "heat" section): per-(side, op) id
    # feeds, cache-efficacy classes, and the top-K concentration
    # headline — nonzero series only, headers always (dashboards before
    # traffic)
    lines.append("# HELP eg_heat_ids_total Vertex ids fed to the heat "
                 "profiler per side and op (client: post-coalesce; "
                 "server: pre-execute)")
    lines.append("# TYPE eg_heat_ids_total counter")
    for data, base in sources:
        heat = data.get("heat")
        if not heat:
            continue
        for key, v in sorted(heat["ids"].items()):
            side, _, op = key.partition(":")
            labels = dict(base)
            labels["side"] = side
            labels["op"] = op
            lines.append(f"eg_heat_ids_total{_fmt_labels(labels)} {v}")
    lines.append("# HELP eg_heat_cache_class_total Feature-cache events "
                 "bucketed by the key's sketch-estimated frequency class "
                 "(class c covers estimates in [2^(c-1), 2^c))")
    lines.append("# TYPE eg_heat_cache_class_total counter")
    for data, base in sources:
        heat = data.get("heat")
        if not heat:
            continue
        for event, classes in sorted(heat["cache_class"].items()):
            for cls, v in enumerate(classes):
                if not v:
                    continue
                labels = dict(base)
                labels["event"] = event
                labels["class"] = str(cls)
                lines.append(
                    f"eg_heat_cache_class_total{_fmt_labels(labels)} {v}"
                )
    lines.append("# HELP eg_heat_topk_share Share of the side's access "
                 "stream absorbed by its tracked top-K hot ids")
    lines.append("# TYPE eg_heat_topk_share gauge")
    for data, base in sources:
        heat = data.get("heat")
        if not heat:
            continue
        for side in ("client", "server"):
            total = heat["sketch"]["total"].get(side, 0)
            if not total:
                continue
            share = min(
                1.0,
                sum(e["count"] for e in heat["topk"][side]) / total,
            )
            labels = dict(base)
            labels["side"] = side
            lines.append(
                f"eg_heat_topk_share{_fmt_labels(labels)} {share:.6f}"
            )

    return "\n".join(lines) + "\n"


def metrics_text(graph=None, shard: int | None = None) -> str:
    """Prometheus text exposition of the telemetry state.

    * no arguments — this process (training client, or a shard served
      in-process);
    * ``graph`` (remote mode) — scrape every shard of the live cluster
      over the STATS opcode, one series set per shard (label
      ``shard="N"``); pass ``shard=`` to scrape just one.

    Every RPC op appears in both the client_call and server_handler
    histogram families even at zero count, so dashboards can be built
    before traffic exists."""
    if graph is None:
        return _render([(telemetry_json(), {})])
    shards = [shard] if shard is not None else list(
        range(graph.num_shards)
    )
    return _render(
        [(scrape(graph, s), {"shard": str(s)}) for s in shards]
    )


# ---------------------------------------------------------------------------
# JSONL emission (run_loop --metrics_every)
# ---------------------------------------------------------------------------


def snapshot(step: int | None = None) -> dict:
    """One compact metrics record for periodic JSONL emission: non-zero
    counters, per-op client-call count + p50/p99 µs, step-phase
    count/p50/p99 per phase plus the headline ``input_stall_ms`` (mean
    consumer stall per step — ROADMAP item 1's acceptance metric), and
    prefetch pipeline means. Gauges-free (local process)."""
    data = telemetry_json()
    ops = {}
    for key, h in data["hist"].items():
        kind, _, op = key.partition(":")
        if kind != "client_call" or h["count"] == 0:
            continue
        pct = percentiles(h, (50, 99))
        ops[op] = {
            "count": h["count"],
            "p50_us": round(pct.get(50, 0.0), 1),
            "p99_us": round(pct.get(99, 0.0), 1),
        }
    phases = {}
    for name, h in phase_hists(data).items():
        if h["count"] == 0:
            continue
        pct = percentiles(h, (50, 99))
        phases[name] = {
            "count": h["count"],
            "p50_us": round(pct.get(50, 0.0), 1),
            "p99_us": round(pct.get(99, 0.0), 1),
        }
    out = {
        "step": step,
        "unix_ms": int(time.time() * 1000),
        "counters": {k: v for k, v in data["counters"].items() if v},
        "ops": ops,
        "phases": phases,
    }
    stall = phase_hists(data).get("input_stall")
    if stall and stall["count"]:
        out["input_stall_ms"] = round(
            stall["sum_us"] / stall["count"] / 1000.0, 3
        )
    for key, name in (("prefetch_depth", "mean_queue_depth"),
                      ("prefetch_busy", "mean_workers_busy")):
        h = data["hist"].get(key)
        if h and h["count"]:
            out.setdefault("prefetch", {})[name] = round(
                h["sum_us"] / h["count"], 2
            )
    return out


def append_metrics_line(path: str, step: int | None = None) -> None:
    """Append one :func:`snapshot` line to a JSONL file (the
    ``run_loop --metrics_every=N`` emitter)."""
    with open(path, "a") as f:
        f.write(json.dumps(snapshot(step)) + "\n")
