"""PPI preset CLI (reference tf_euler/python/ppi_main.py:24-33: max_id
56944, 50-dim features, 121 sigmoid classes).

    python -m euler_tpu.ppi_main --data_dir <ppi .dat dir> [overrides]
"""

import sys

from euler_tpu.run_loop import main

PPI_DEFAULTS = [
    "--max_id", "56944",
    "--feature_idx", "1",
    "--feature_dim", "50",
    "--label_idx", "0",
    "--label_dim", "121",
    "--all_edge_type", "0,1",
]


def run(argv=None) -> int:
    argv = PPI_DEFAULTS + list(argv if argv is not None else sys.argv[1:])
    return main(argv)


if __name__ == "__main__":
    sys.exit(run())
