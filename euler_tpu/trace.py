"""Unified Chrome-trace / Perfetto export for the step-phase profiler.

The histograms (telemetry.py) say how MUCH time each phase takes; this
module says WHEN — one merged trace where a slow training step can be
followed from the consumer's ``input_stall`` slice to the prefetch
worker's ``sample`` slice to the exact shard handler that caused it,
linked by the PR-5 wire-v3 trace ids.

Three inputs merge into one ``traceEvents`` JSON (the Chrome trace
format Perfetto and chrome://tracing both open):

  * per-step phase events — :class:`TraceRecorder` taps
    ``telemetry.record_phase`` while active, so the train loop and
    prefetch workers need no extra plumbing;
  * this process's slow-span journal (client side of every RPC);
  * each live shard's journal via the STATS scrape (server side).

Timeline: CLOCK_MONOTONIC microseconds — ``time.monotonic_ns()//1000``
in Python, ``std::chrono::steady_clock`` in the native spans
(``end_us``). The epoch is machine-wide, so phase events and shard
spans from different PROCESSES on one host line up exactly. Shards on
other hosts sit at their own clock offset; the trace-id FLOW events
("s"/"f" pairs) still draw the client-call → server-handler arrows
regardless of skew.

Surfaces: ``run_loop --trace_file=`` writes the merged trace at the end
of training; ``scripts/trace_dump.py`` exports from a live cluster (or
merges into an existing trace file) standalone.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import threading
import time
from collections import deque

from euler_tpu import telemetry as _telemetry

# Synthetic pids: one "process" lane per source in the merged view.
PID_TRAIN = 1
PID_SHARD_BASE = 100  # shard s renders as pid 100+s
PID_DEVICE_BASE = 200  # jax.profiler device lanes render from pid 200

# Name of the alignment marker devprof stamps into a jax.profiler
# capture. The profiler's timestamps sit on their own epoch (NOT
# CLOCK_MONOTONIC — observed ~850 s apart on Linux); embedding the
# monotonic µs in an annotation name lets ingest_profiler_dir solve
# for the offset exactly instead of guessing from wall clocks.
ALIGN_PREFIX = "eg_align:"


def now_us() -> int:
    """CLOCK_MONOTONIC µs — the exporter's one clock (matches the
    native spans' steady_clock end_us stamps)."""
    return time.monotonic_ns() // 1000


class TraceRecorder:
    """Bounded in-memory buffer of step-phase events.

    ``start()`` registers the recorder as the telemetry phase sink;
    every ``record_phase(phase, us, step)`` anywhere in the process
    (train loop, prefetch consumer, prefetch workers) then lands here
    with its thread identity, until ``stop()``. The buffer is a ring:
    beyond ``capacity`` events the oldest fall off (``dropped`` counts
    them) — a week-long run cannot OOM the trainer."""

    def __init__(self, capacity: int = 200_000):
        self._events: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self.dropped = 0
        self.started_us: int | None = None

    def start(self) -> "TraceRecorder":
        self.started_us = now_us()
        _telemetry.set_trace_sink(self._on_phase)
        return self

    def stop(self) -> None:
        if _telemetry._trace_sink is self._on_phase:
            _telemetry.set_trace_sink(None)

    def _on_phase(self, phase: str, us: float, step: int | None) -> None:
        end = now_us()
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(
                (phase, end - max(int(us), 0), int(us), step,
                 threading.current_thread().name)
            )

    def events(self) -> list:
        with self._lock:
            return list(self._events)


def _phase_trace_events(phase_events: list) -> list:
    """Recorder tuples -> complete ("X") slice events on the train pid,
    one tid lane per recording thread."""
    out = []
    tids: dict = {}
    for phase, ts, dur, step, thread_name in phase_events:
        tid = tids.setdefault(thread_name, len(tids) + 1)
        ev = {
            "name": phase, "cat": "phase", "ph": "X",
            "ts": ts, "dur": dur, "pid": PID_TRAIN, "tid": tid,
        }
        if step is not None:
            ev["args"] = {"step": step}
        out.append(ev)
    for thread_name, tid in tids.items():
        out.append({
            "name": "thread_name", "ph": "M", "pid": PID_TRAIN,
            "tid": tid, "args": {"name": thread_name},
        })
    return out


def _span_trace_events(data: dict, pid: int, label: str) -> list:
    """One telemetry dump's slow-span journal -> slice events (client
    spans on tid 90, server spans on tid 91) carrying the wire-v3 trace
    id, outcome, and the queue/handler/wire decomposition."""
    out = []
    for s in data.get("slow_spans", []):
        end = int(s.get("end_us", 0))
        dur = int(s["total_us"])
        server = s["side"] == "server"
        out.append({
            "name": s["op"], "cat": "rpc", "ph": "X",
            "ts": end - dur, "dur": dur,
            "pid": pid, "tid": 91 if server else 90,
            "args": {
                "trace": f"{int(s['trace']):#x}",
                "side": s["side"], "outcome": s["outcome"],
                "shard": s["shard"], "queue_us": s["queue_us"],
                "handler_us": s["handler_us"], "wire_us": s["wire_us"],
                "source": label,
            },
        })
    out.append({
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": label},
    })
    for tid, name in ((90, "rpc client calls"), (91, "rpc handlers")):
        out.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name},
        })
    return out


def _flow_events(span_events: list) -> list:
    """Client-call -> server-handler flow arrows: for every wire-v3
    trace id seen on BOTH a client and a server span, emit an
    "s"/"f" pair so Perfetto links them across process lanes (and
    across clock skew, when shards live on other hosts)."""
    by_trace: dict = {}
    for ev in span_events:
        args = ev.get("args")
        if not args or "trace" not in args:
            continue
        if int(args["trace"], 16) == 0:
            continue  # id not propagated (v1/v2 peer / telemetry off)
        side = args["side"]
        by_trace.setdefault(args["trace"], {})[side] = ev
    out = []
    for trace, sides in by_trace.items():
        if "client" not in sides or "server" not in sides:
            continue
        cli, srv = sides["client"], sides["server"]
        common = {"name": "rpc", "cat": "rpc-flow", "id": trace}
        out.append({**common, "ph": "s", "ts": cli["ts"],
                    "pid": cli["pid"], "tid": cli["tid"]})
        out.append({**common, "ph": "f", "bp": "e",
                    "ts": srv["ts"] + srv["dur"],
                    "pid": srv["pid"], "tid": srv["tid"]})
    return out


def align_annotation(monotonic_us: int | None = None):
    """Context manager stamping the clock-alignment marker into an
    active ``jax.profiler`` capture: a named TraceAnnotation whose name
    carries CLOCK_MONOTONIC µs, so ingestion can map the profiler's
    private epoch onto the exporter's timeline exactly. Enter it (with
    an empty body) right after ``start_trace``."""
    import jax

    return jax.profiler.TraceAnnotation(
        f"{ALIGN_PREFIX}{monotonic_us if monotonic_us is not None else now_us()}"
    )


def _latest_profiler_trace(profile_dir: str) -> str | None:
    """Newest ``*.trace.json(.gz)`` under the TensorBoard-style layout
    ``<dir>/plugins/profile/<run>/`` that jax.profiler writes."""
    root = os.path.join(profile_dir, "plugins", "profile")
    paths = glob.glob(os.path.join(root, "*", "*.trace.json.gz"))
    paths += glob.glob(os.path.join(root, "*", "*.trace.json"))
    return max(paths, key=os.path.getmtime) if paths else None


# Which profiler lanes are device-plane: TPU/GPU device processes, or
# the XLA runtime executor threads (on CPU the kernel slices land on
# threads named ``tf_XLATfrtCpuClient/...`` inside the python process).
_DEVICE_PID_RE = re.compile(r"XLA|TPU|GPU|[Dd]evice")
_DEVICE_TID_RE = re.compile(r"XLA")


def ingest_profiler_dir(profile_dir: str, max_events: int = 50_000) -> list:
    """A ``jax.profiler`` trace directory -> device-lane trace events
    aligned to the exporter's CLOCK_MONOTONIC timeline.

    Reads the newest capture, keeps the complete ("X") slices on
    device/XLA-runtime lanes, shifts their timestamps by the offset
    solved from the ``eg_align:<monotonic_us>`` annotation (raw
    profiler time if no marker was stamped), and remaps pids to the
    PID_DEVICE_BASE block so the kernels render as their own process
    lanes next to the host phases. Returns [] when the directory holds
    no capture — trace export must never fail a training teardown."""
    path = _latest_profiler_trace(profile_dir)
    if path is None:
        return []
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as f:
            raw = json.load(f)
    except Exception:
        return []
    events = raw.get("traceEvents") or []

    pid_names: dict = {}
    tid_names: dict = {}
    offset = None
    for ev in events:
        if ev.get("ph") == "M":
            if ev.get("name") == "process_name":
                pid_names[ev.get("pid")] = (ev.get("args") or {}).get(
                    "name", ""
                )
            elif ev.get("name") == "thread_name":
                tid_names[(ev.get("pid"), ev.get("tid"))] = (
                    ev.get("args") or {}
                ).get("name", "")
        elif offset is None and "ts" in ev:
            m = re.search(ALIGN_PREFIX + r"(\d+)", str(ev.get("name", "")))
            if m:
                offset = int(m.group(1)) - int(ev["ts"])
    if offset is None:
        offset = 0  # unstamped capture: lanes keep the profiler epoch

    lanes: dict = {}  # source pid -> synthetic device pid
    used_tids: set = set()
    out = []
    for ev in events:
        if ev.get("ph") != "X" or "ts" not in ev:
            continue
        pid, tid = ev.get("pid"), ev.get("tid")
        if not (
            _DEVICE_PID_RE.search(pid_names.get(pid, ""))
            or _DEVICE_TID_RE.search(tid_names.get((pid, tid), ""))
        ):
            continue
        new_pid = lanes.setdefault(pid, PID_DEVICE_BASE + len(lanes))
        used_tids.add((pid, tid))
        out.append({
            "name": ev.get("name", "?"), "cat": "device", "ph": "X",
            "ts": int(ev["ts"]) + offset, "dur": int(ev.get("dur", 0)),
            "pid": new_pid, "tid": tid,
        })
    if len(out) > max_events:
        # Keep the biggest slices: a multi-step device capture can hold
        # millions of sub-µs events that would swamp the merged export.
        out.sort(key=lambda e: e["dur"], reverse=True)
        del out[max_events:]
        out.sort(key=lambda e: e["ts"])
    for pid, new_pid in lanes.items():
        out.append({
            "name": "process_name", "ph": "M", "pid": new_pid,
            "args": {"name": f"device: {pid_names.get(pid) or pid}"},
        })
    for pid, tid in used_tids:
        name = tid_names.get((pid, tid))
        if name:
            out.append({
                "name": "thread_name", "ph": "M", "pid": lanes[pid],
                "tid": tid, "args": {"name": name},
            })
    return out


def chrome_trace(phase_events: list | None = None,
                 span_sources: list | None = None,
                 base_events: list | None = None) -> dict:
    """Build the merged trace dict.

    phase_events: TraceRecorder tuples (or None);
    span_sources: [(telemetry dump dict, pid, label), ...];
    base_events: pre-built traceEvents to merge under (an existing
    trace file's, in trace_dump.py's merge mode)."""
    events = list(base_events or [])
    if phase_events:
        events.extend(_phase_trace_events(phase_events))
        events.append({
            "name": "process_name", "ph": "M", "pid": PID_TRAIN,
            "args": {"name": "train (step phases)"},
        })
    span_events: list = []
    for data, pid, label in span_sources or []:
        span_events.extend(_span_trace_events(data, pid, label))
    events.extend(span_events)
    events.extend(_flow_events(
        [e for e in events if e.get("cat") == "rpc"]
    ))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def gather_span_sources(graph=None) -> list:
    """This process's journal plus — for a live remote graph — every
    reachable shard's, as ``chrome_trace`` span_sources. A shard that
    fails to scrape is skipped (trace export must never fail a training
    teardown), noted under its label."""
    sources = [(_telemetry.telemetry_json(), PID_TRAIN,
                "train (client journal)")]
    if graph is not None and getattr(graph, "mode", None) == "remote":
        for s in range(graph.num_shards):
            try:
                sources.append((_telemetry.scrape(graph, s),
                                PID_SHARD_BASE + s, f"shard {s}"))
            except Exception:
                pass  # unreachable shard: trace ships without its side
    return sources


def write_trace(path: str, recorder: TraceRecorder | None = None,
                graph=None, base_events: list | None = None,
                profile_dir: str | None = None) -> dict:
    """Export the merged trace to ``path`` and return it. When a
    ``jax.profiler`` capture directory is given its device lanes merge
    in, time-aligned with the host phase events."""
    base = list(base_events or [])
    if profile_dir:
        base.extend(ingest_profiler_dir(profile_dir))
    trace = chrome_trace(
        recorder.events() if recorder is not None else None,
        gather_span_sources(graph),
        base,
    )
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


def validate_chrome_trace(trace: dict) -> list:
    """Structural validity check (tests + trace_dump --smoke): returns
    the trace's events after asserting the Chrome-trace invariants the
    viewers rely on. Raises ValueError on the first violation."""
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError("not a Chrome trace: no traceEvents key")
    events = trace["traceEvents"]
    if not isinstance(events, list):
        raise ValueError("traceEvents is not a list")
    for ev in events:
        for k in ("name", "ph", "pid"):
            if k not in ev:
                raise ValueError(f"event missing {k!r}: {ev}")
        if ev["ph"] == "X":
            if "ts" not in ev or "dur" not in ev:
                raise ValueError(f"X event missing ts/dur: {ev}")
            if ev["dur"] < 0 or not isinstance(ev["ts"], int):
                raise ValueError(f"bad X timing: {ev}")
        if ev["ph"] in ("s", "f") and "id" not in ev:
            raise ValueError(f"flow event missing id: {ev}")
    return events


def correlated_trace_ids(trace: dict) -> set:
    """Trace ids carried by BOTH a client and a server rpc slice — the
    cross-process correlation the acceptance test pins."""
    sides: dict = {}
    for ev in trace["traceEvents"]:
        args = ev.get("args") or {}
        if ev.get("cat") == "rpc" and "trace" in args:
            sides.setdefault(args["trace"], set()).add(args["side"])
    return {t for t, ss in sides.items()
            if {"client", "server"} <= ss and int(t, 16) != 0}
