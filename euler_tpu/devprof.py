"""Device-plane observability: compile attribution, HBM gauges, transfer
counters (OBSERVABILITY.md "Device plane").

Four observability planes (PR 5-8) instrumented the host and the wire;
this module watches the DEVICE half of the step: every XLA backend
compile (count + log2-µs latency histogram), every recompile after a
function's warmup (the classic silent 100x — a shape/dtype drift makes
jit quietly rebuild the program), device memory in use, and the
host<->device transfer volume. Everything lands in the existing native
surfaces through the eg_counter_add / eg_phase_record / eg_devprof ABI,
so metrics_text(), the STATS scrape, blackbox postmortems and
scripts/metrics_dump.py report the device plane with zero new plumbing:

    devprof.install()                once per process, before first jit
    fn = devprof.watch(jitted, "loss_step")   recompile attribution
    devprof.recompile_ledger()       journaled recompiles, newest last
    devprof.sample_device_mem()      one-shot HBM/buffer gauge refresh
    devprof.count_h2d(batch)         transfer-byte bracketing
    devprof.set_devprof(False)       process-global kill-switch

Compile COUNTS ride ``device_compiles`` / ``device_recompiles`` /
``serve_recompiles`` (eg_stats.h), compile LATENCY rides the
``phase:compile`` histogram (eg_phase.h), memory gauges ride the
blackbox resource section (eg_blackbox.h + eg_devprof.h). The primary
compile detector is a ``jax.monitoring`` event listener (exact backend
compile durations); where events are unavailable the wrapped-jit
fallback in :class:`Watched` feeds the same counters from cache-size
deltas. Attribution (WHICH function recompiled, WHAT drifted) always
comes from :class:`Watched`'s per-function shape-signature registry.
"""

from __future__ import annotations

import logging
import threading
import time

from euler_tpu import telemetry
from euler_tpu.graph import native
from euler_tpu.graph.native import lib

log = logging.getLogger("euler_tpu.devprof")

# The jax.monitoring event key of one XLA backend compile (fires once
# per compile, duration in seconds). Pinned by tests against the live
# jax in the image; a jax without it simply leaves the listener idle
# and the wrapped-jit fallback owns the counters.
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_LEDGER_CAP = 256

_enabled = True
_installed = False
_listener_ok = False
_lock = threading.Lock()
_ledger: list = []
_sampler_stop = None
_sampler_thread = None


class RecompileError(RuntimeError):
    """A watched function recompiled after warmup under strict=True
    (the eg_serve ``strict_bucket=`` contract: the padded fixed-bucket
    forward must compile exactly once)."""


def devprof_enabled() -> bool:
    return _enabled


def set_devprof(on: bool) -> None:
    """Process-global device-plane kill-switch (`devprof=` config key):
    False stops compile counting/journaling, memory sampling and
    transfer-byte counting — the listener and wrappers stay in place
    but write nothing."""
    global _enabled
    _enabled = bool(on)


def monitoring_active() -> bool:
    """True when the jax.monitoring compile listener is registered (it
    then owns device_compiles + the compile histogram; the wrapped-jit
    fallback only attributes)."""
    return _listener_ok


def _on_event_duration(event: str, duration: float, **kw) -> None:
    # Called from inside jax's compile path — must never raise.
    try:
        if not _enabled or event != COMPILE_EVENT:
            return
        native.counter_add("device_compiles")
        telemetry.record_phase("compile", duration * 1e6)
    except Exception:  # pragma: no cover - defensive
        pass


def install(sample_ms: int = 0) -> bool:
    """Arm the device plane (idempotent): register the jax.monitoring
    compile listener; with ``sample_ms > 0`` also start the background
    device-memory sampler. Returns True when the listener registered
    (False = fallback mode: Watched owns the counters too)."""
    global _installed, _listener_ok
    with _lock:
        if not _installed:
            try:
                import jax.monitoring as _mon

                _mon.register_event_duration_secs_listener(
                    _on_event_duration
                )
                _listener_ok = True
            except Exception as e:  # noqa: BLE001 - fallback mode
                log.info("devprof: jax.monitoring unavailable (%s); "
                         "wrapped-jit fallback owns compile counters", e)
                _listener_ok = False
            _installed = True
    if sample_ms > 0:
        start_sampler(sample_ms)
    return _listener_ok


def setup(enabled: bool = True, compile_cache: bool | None = None,
          model_dir: str | None = None, sample_ms: int = 0) -> bool:
    """CLI-startup arming shared by `python -m euler_tpu.run_loop` and
    `python -m euler_tpu.serve` (their --devprof / --compile_cache
    flags land here). Disarms the plane when ``enabled`` is False;
    otherwise installs the compile listener, optionally starts the
    memory sampler, and points JAX's persistent compilation cache at
    $JAX_COMPILATION_CACHE_DIR / <model_dir>/jax_cache —
    ``compile_cache=None`` means auto: on for TPU/GPU backends (where a
    program compile costs 20-40 s), off on CPU. Returns devprof_enabled().
    """
    if not enabled:
        set_devprof(False)
        return False
    install(sample_ms=sample_ms)
    on = compile_cache
    if on is None:
        import jax

        on = jax.default_backend() != "cpu"
    if on:
        import os

        from euler_tpu.parallel import enable_compile_cache

        d = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            model_dir or ".", "jax_cache"
        )
        enable_compile_cache(default_dir=d)
        log.info("devprof: persistent compile cache at %s", d)
    return True


# ---------------------------------------------------------------------------
# compile attribution: per-function shape-signature registry
# ---------------------------------------------------------------------------


def _leaf_sig(x) -> tuple:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype))
    return (type(x).__name__,)


def _signature(args: tuple, kwargs: dict) -> tuple:
    import jax

    return tuple(
        _leaf_sig(leaf)
        for leaf in jax.tree_util.tree_leaves((args, kwargs))
    )


def sig_diff(old, new) -> list:
    """Human-readable per-leaf diff between two signatures — the
    'exactly WHAT drifted' half of a recompile journal entry."""
    if old is None:
        return ["first compile"]
    out = []
    n = max(len(old), len(new))
    for i in range(n):
        a = old[i] if i < len(old) else None
        b = new[i] if i < len(new) else None
        if a != b:
            out.append(f"leaf{i}: {_fmt_sig(a)} -> {_fmt_sig(b)}")
    return out or [f"leaf count {len(old)} -> {len(new)}"]


def _fmt_sig(s) -> str:
    if s is None:
        return "absent"
    if len(s) == 2:
        return f"{s[0]} {s[1]}"
    return str(s[0])


def _journal(entry: dict) -> None:
    with _lock:
        _ledger.append(entry)
        del _ledger[:-_LEDGER_CAP]
    # the same event lands in the slow-span journal (op 0 = "other",
    # client side) so a scrape's slowest-N view shows the recompile
    # wall time next to the RPC spans it starved
    telemetry.record_span(int(entry["wall_us"]), op=0, side="client")
    log.warning("devprof: recompile of %s after warmup: %s",
                entry["fn"], "; ".join(entry["diff"]))


def recompile_ledger() -> list:
    """Journaled post-warmup recompiles, oldest first (bounded to the
    last 256): [{"t_us", "fn", "diff", "sig", "prev", "wall_us"}]."""
    with _lock:
        return list(_ledger)


def devprof_reset() -> None:
    """Clear the recompile ledger (native gauges/counters reset with
    telemetry_reset()/counters_reset())."""
    with _lock:
        del _ledger[:]


class Watched:
    """A jitted callable with a shape-signature registry: detects every
    compile the call triggered (cache-size delta; signature-registry
    fallback), and journals any compile AFTER warmup as a recompile
    with the exact arg-shape/dtype diff that caused it.

    ``on_recompile(entry)`` is the serve compile-storm hook;
    ``strict=True`` raises :class:`RecompileError` (the result is
    computed first — the caller may catch and keep it)."""

    def __init__(self, fn, name: str | None = None, strict: bool = False,
                 counter: str = "device_recompiles",
                 on_recompile=None):
        self._fn = fn
        self.name = name or getattr(fn, "__name__", "jit_fn")
        self.strict = strict
        self._counter = counter
        self._on_recompile = on_recompile
        self._sigs: dict = {}
        self._last_sig = None
        self.warm = False
        self.compiles = 0
        self.recompiles = 0

    def _cache_size(self):
        cs = getattr(self._fn, "_cache_size", None)
        if cs is None:
            return None
        try:
            return cs()
        except Exception:  # noqa: BLE001 - jit internals moved
            return None

    def mark_warm(self) -> None:
        """Declare warmup done: the NEXT compile is a recompile even if
        no tracked call compiled yet (serve warms up out-of-band)."""
        self.warm = True

    def __call__(self, *args, **kwargs):
        if not _enabled:
            return self._fn(*args, **kwargs)
        before = self._cache_size()
        t0 = time.monotonic()
        out = self._fn(*args, **kwargs)
        wall_us = int((time.monotonic() - t0) * 1e6)
        after = self._cache_size()
        if after is not None and before is not None:
            if after == before:
                # steady state — in-bucket dispatch, nothing compiled,
                # so the arg signature (the expensive half of
                # attribution) is never built; _last_sig stays at the
                # sig that triggered the last compile, which is exactly
                # the "previous" side a future recompile diffs against
                return out
            compiled = True
            sig = _signature(args, kwargs)
        else:
            # no _cache_size on this callable: signature-registry
            # fallback has to price the signature on every call
            sig = _signature(args, kwargs)
            compiled = sig not in self._sigs
        self._sigs.setdefault(sig, 0)
        self._sigs[sig] += 1
        if not compiled:
            self._last_sig = sig
            return out
        self.compiles += 1
        if not _listener_ok:
            # fallback mode: the wrapper owns the count + latency too
            # (call wall time — compile dominates a compiling call)
            native.counter_add("device_compiles")
            telemetry.record_phase("compile", wall_us)
        if self.warm:
            self.recompiles += 1
            entry = {
                "t_us": int(time.monotonic() * 1e6),
                "fn": self.name,
                "diff": sig_diff(self._last_sig, sig),
                "sig": sig,
                "prev": self._last_sig,
                "wall_us": wall_us,
            }
            native.counter_add(self._counter)
            _journal(entry)
            self._last_sig = sig
            if self._on_recompile is not None:
                self._on_recompile(entry)
            if self.strict:
                raise RecompileError(
                    f"{self.name} recompiled after warmup: "
                    f"{'; '.join(entry['diff'])}"
                )
            return out
        self.warm = True
        self._last_sig = sig
        return out


def watch(fn, name: str | None = None, strict: bool = False,
          counter: str = "device_recompiles", on_recompile=None) -> Watched:
    """Wrap a jitted callable with recompile attribution (see
    :class:`Watched`). The wrapper is transparent (same args/returns)
    and free when the kill-switch is off."""
    return Watched(fn, name=name, strict=strict, counter=counter,
                   on_recompile=on_recompile)


# ---------------------------------------------------------------------------
# device memory & transfer telemetry
# ---------------------------------------------------------------------------


def sample_device_mem() -> tuple:
    """One device-memory sample pushed into the native gauges (and from
    there into blackbox resource rings, postmortems and metrics_text):
    (bytes_in_use, live_buffers). Uses device.memory_stats() where the
    backend reports it (TPU/GPU); falls back to a jax.live_arrays()
    census (CPU — the census IS the live-buffer truth there)."""
    if not _enabled:
        return (0, 0)
    import jax

    arrs = jax.live_arrays()
    buffers = len(arrs)
    bytes_in_use = None
    try:
        stats = jax.devices()[0].memory_stats()
        if stats:
            bytes_in_use = int(stats.get("bytes_in_use", 0)) or None
    except Exception:  # noqa: BLE001 - backend without memory_stats
        bytes_in_use = None
    if bytes_in_use is None:
        bytes_in_use = int(sum(getattr(a, "nbytes", 0) for a in arrs))
    lib().eg_devprof_set_mem(bytes_in_use, buffers)
    return (bytes_in_use, buffers)


def start_sampler(period_ms: int = 1000) -> None:
    """Background device-memory sampler (daemon; idempotent): refreshes
    the native gauges every ``period_ms`` so the blackbox resource ring
    (eg_blackbox.h SamplerLoop reads the gauges on ITS cadence) and any
    scrape see a live trajectory, not just the last manual sample."""
    global _sampler_stop, _sampler_thread
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return
        stop = threading.Event()

        def loop():
            while not stop.wait(max(period_ms, 50) / 1000.0):
                try:
                    sample_device_mem()
                except Exception:  # pragma: no cover - keep sampling
                    pass

        t = threading.Thread(target=loop, name="eg-devprof-sampler",
                             daemon=True)
        t.start()
        _sampler_stop, _sampler_thread = stop, t


def stop_sampler() -> None:
    global _sampler_stop, _sampler_thread
    with _lock:
        if _sampler_stop is not None:
            _sampler_stop.set()
        _sampler_stop = _sampler_thread = None


def tree_bytes(tree) -> int:
    """Total array bytes across a pytree's leaves."""
    import jax

    # size * itemsize rather than .nbytes: jax.Array's nbytes property
    # re-derives the byte count through the sharding machinery (~2.5 us
    # per leaf) and this census rides every step's h2d hook
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        size = getattr(leaf, "size", None)
        dtype = getattr(leaf, "dtype", None)
        if size is not None and dtype is not None:
            total += int(size) * dtype.itemsize
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total


def count_h2d(tree) -> int:
    """Bump ``h2d_bytes`` by the byte size of a pytree about to cross
    host->device (train shard_batch / serve dispatch call sites).
    Returns the bytes counted (0 when the kill-switch is off)."""
    if not _enabled:
        return 0
    n = tree_bytes(tree)
    if n:
        native.counter_add("h2d_bytes", n)
    return n


def count_d2h(tree) -> int:
    """Bump ``d2h_bytes`` for a device->host materialization (fetched
    losses/metrics, served embedding rows)."""
    if not _enabled:
        return 0
    n = tree_bytes(tree)
    if n:
        native.counter_add("d2h_bytes", n)
    return n


# ---------------------------------------------------------------------------
# summaries (run_loop first-step line, scripts/devprof_dump.py)
# ---------------------------------------------------------------------------


def compile_summary(data: dict | None = None) -> dict:
    """One-line compile economics from a telemetry dump (default: this
    process): counts, total/percentile compile wall, memory high-water.
    The run_loop logs this after the first step so a relaunch with a
    warm compilation cache is visibly cheap."""
    data = data or telemetry.telemetry_json()
    h = data["hist"].get("phase:compile") or {"b": [0], "count": 0,
                                              "sum_us": 0}
    pct = telemetry.percentiles(h, (50, 99)) if h["count"] else {}
    res = data.get("resource", {})
    return {
        "compiles": data["counters"].get("device_compiles", 0),
        "recompiles": data["counters"].get("device_recompiles", 0),
        "serve_recompiles": data["counters"].get("serve_recompiles", 0),
        "compile_events": h["count"],
        "compile_ms_total": round(h["sum_us"] / 1000.0, 1),
        "compile_ms_p50": round(pct.get(50, 0.0) / 1000.0, 1),
        "compile_ms_p99": round(pct.get(99, 0.0) / 1000.0, 1),
        "h2d_bytes": data["counters"].get("h2d_bytes", 0),
        "d2h_bytes": data["counters"].get("d2h_bytes", 0),
        "device_mem_bytes": res.get("device_mem_bytes", 0),
        "device_mem_peak_bytes": res.get("device_mem_peak_bytes", 0),
        "device_buffers": res.get("device_buffers", 0),
    }
