"""euler_tpu: a TPU-native graph learning framework.

A ground-up rebuild of the capabilities of Alibaba Euler 1.x
(/root/reference) for TPU: a C++ host graph engine (weighted sampling,
random walks, feature gather over an immutable SoA store) feeding JAX/XLA
model compute through an async prefetch pipeline, with data-parallel
training over a jax.sharding.Mesh instead of parameter servers.
"""

from euler_tpu.graph.graph import Graph
from euler_tpu.graph.convert import convert, convert_dicts
from euler_tpu.graph.native import stats, stats_reset
from euler_tpu.graph.service import GraphService

__version__ = "0.1.0"

__all__ = [
    "Graph", "GraphService", "convert", "convert_dicts", "stats",
    "stats_reset",
]
