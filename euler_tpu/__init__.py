"""euler_tpu: a TPU-native graph learning framework.

A ground-up rebuild of the capabilities of Alibaba Euler 1.x
(/root/reference) for TPU: a C++ host graph engine (weighted sampling,
random walks, feature gather over an immutable SoA store) feeding JAX/XLA
model compute through an async prefetch pipeline, with data-parallel
training over a jax.sharding.Mesh instead of parameter servers.
"""

import os as _os

if _os.environ.get("JAX_PLATFORMS"):
    # Make JAX_PLATFORMS effective even when a site hook pre-registered
    # another backend at interpreter start (see
    # parallel/mesh.py honor_jax_platforms_env): without this, examples
    # and user scripts run with JAX_PLATFORMS=cpu still initialize the
    # ambient TPU backend — which blocks forever when the chip is
    # unreachable. Import-time, so it runs before any jax.devices().
    # The env var is authoritative here (a site hook's config value is
    # indistinguishable from a user's): code that wants a platform
    # DIFFERENT from the launch env should call
    # jax.config.update('jax_platforms', ...) after this import.
    import jax as _jax

    if _jax.config.jax_platforms != _os.environ["JAX_PLATFORMS"]:
        _jax.config.update("jax_platforms", _os.environ["JAX_PLATFORMS"])

from euler_tpu.graph.graph import Graph
from euler_tpu.graph.convert import convert, convert_dicts
from euler_tpu.graph.native import (
    counters,
    counters_reset,
    fault_clear,
    fault_config,
    fault_injected,
    reset_counters,
    stats,
    stats_reset,
)
from euler_tpu.graph.service import GraphService
from euler_tpu.telemetry import (
    metrics_text,
    scrape,
    set_telemetry,
    slow_spans,
    telemetry_json,
    telemetry_reset,
)
from euler_tpu.blackbox import (
    blackbox_json,
    postmortem_read,
    set_blackbox,
)
from euler_tpu.heat import (
    heat_json,
    heat_reset,
    heat_topk,
    set_heat,
)
from euler_tpu.devprof import (
    RecompileError,
    compile_summary,
    recompile_ledger,
    sample_device_mem,
    set_devprof,
    watch,
)
from euler_tpu.serving import (
    BusyError,
    DeadlineError,
    EmbedClient,
)
from euler_tpu.serve import EmbedServer

__version__ = "0.2.0"

__all__ = [
    "Graph", "GraphService", "convert", "convert_dicts", "stats",
    "stats_reset", "counters", "counters_reset", "reset_counters",
    "fault_config", "fault_clear", "fault_injected", "metrics_text",
    "scrape", "set_telemetry", "slow_spans", "telemetry_json",
    "telemetry_reset", "blackbox_json", "postmortem_read",
    "set_blackbox", "heat_json", "heat_topk", "heat_reset", "set_heat",
    "RecompileError", "compile_summary", "recompile_ledger",
    "sample_device_mem", "set_devprof", "watch",
    "EmbedServer", "EmbedClient", "BusyError", "DeadlineError",
]
