"""Synthetic benchmark datasets at reference scales.

The reference's data prep (reference examples/ppi_data.py:40-150 downloads
GraphSAGE-format PPI; reddit_data.py:42-58 converts DGL's reddit npz) pulls
real datasets over the network; this environment has zero egress, so these
generators emit synthetic graphs with the SAME scale constants (node count,
degree, feature/label dims — reference tf_euler/python/ppi_main.py:24-33 and
reddit_main.py:24-34) and the same .dat layout, making sampling + compute
cost representative while remaining fully reproducible.

Layout convention (matches the examples' training flags):
  float_feature slot 0 = labels (multi-/one-hot), slot 1 = input features.
"""

from __future__ import annotations

import json
import os

import numpy as np

PPI = dict(num_nodes=56944, avg_degree=15, feature_dim=50, label_dim=121,
           multilabel=True)
REDDIT = dict(num_nodes=232965, avg_degree=50, feature_dim=602, label_dim=41,
              multilabel=False)


def build_synthetic(
    out_dir: str,
    num_nodes: int,
    avg_degree: int,
    feature_dim: int,
    label_dim: int,
    multilabel: bool = True,
    num_partitions: int = 4,
    max_degree: int = 60,
    seed: int = 7,
) -> str:
    """Write a synthetic graph as .dat partitions + meta.json (cached: a
    'done' marker records the generation params and skips regeneration only
    when they match). Returns out_dir."""
    os.makedirs(out_dir, exist_ok=True)
    params = json.dumps(
        dict(num_nodes=num_nodes, avg_degree=avg_degree,
             feature_dim=feature_dim, label_dim=label_dim,
             multilabel=multilabel, num_partitions=num_partitions,
             max_degree=max_degree, seed=seed),
        sort_keys=True,
    )
    marker = os.path.join(out_dir, "done")
    wip = os.path.join(out_dir, "synthetic-in-progress")
    if os.path.exists(marker):
        with open(marker) as f:
            if f.read() == params:
                return out_dir
        # stale cache generated with different settings: rebuild
        for name in os.listdir(out_dir):
            if name.endswith(".dat") or name in ("done", "meta.json"):
                os.unlink(os.path.join(out_dir, name))
    elif os.path.exists(wip):
        # a previous synthetic build was interrupted mid-write: the .dat
        # partitions may be truncated — regenerate them
        for name in os.listdir(out_dir):
            if name.endswith(".dat") or name == "meta.json":
                os.unlink(os.path.join(out_dir, name))
    elif any(n.endswith(".dat") for n in os.listdir(out_dir)):
        # .dat partitions but no synthetic marker (neither done nor
        # in-progress): this is a real converted dataset — never overwrite
        # it, use it as-is.
        return out_dir
    with open(wip, "w") as f:
        f.write(params)
    from euler_tpu.graph.convert import pack_block

    rng = np.random.default_rng(seed)
    meta = {
        "node_type_num": 1,
        "edge_type_num": 1,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    paths = [
        os.path.join(out_dir, "part_%d.dat" % p)
        for p in range(num_partitions)
    ]
    outs = [open(p, "wb") for p in paths]
    degrees = rng.poisson(avg_degree, num_nodes).clip(1, max_degree)
    for nid in range(num_nodes):
        nbrs = rng.integers(0, num_nodes, degrees[nid])
        if multilabel:
            labels = rng.integers(0, 2, label_dim).astype(float)
        else:
            labels = np.zeros(label_dim)
            labels[rng.integers(0, label_dim)] = 1.0
        node = {
            "node_id": nid,
            "node_type": 0,
            "node_weight": 1.0,
            "neighbor": {"0": {str(int(d)): 1.0 for d in nbrs}},
            "uint64_feature": {},
            "float_feature": {
                "0": labels.tolist(),
                "1": rng.standard_normal(feature_dim).round(3).tolist(),
            },
            "binary_feature": {},
            "edge": [],
        }
        outs[nid % num_partitions].write(pack_block(node, meta))
    for o in outs:
        o.close()
    with open(marker, "w") as f:
        f.write(params)
    os.unlink(wip)
    return out_dir


def build_ppi(out_dir: str, **overrides) -> str:
    return build_synthetic(out_dir, **{**PPI, **overrides})


def build_reddit(out_dir: str, **overrides) -> str:
    return build_synthetic(out_dir, **{**REDDIT, **overrides})
