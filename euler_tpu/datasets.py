"""Synthetic benchmark datasets at reference scales.

The reference's data prep (reference examples/ppi_data.py:40-150 downloads
GraphSAGE-format PPI; reddit_data.py:42-58 converts DGL's reddit npz) pulls
real datasets over the network; this environment has zero egress, so these
generators emit synthetic graphs with the SAME scale constants (node count,
degree, feature/label dims — reference tf_euler/python/ppi_main.py:24-33 and
reddit_main.py:24-34) and the same .dat layout, making sampling + compute
cost representative while remaining fully reproducible.

Layout convention (matches the examples' training flags):
  float_feature slot 0 = labels (multi-/one-hot), slot 1 = input features.
"""

from __future__ import annotations

import json
import os

import numpy as np

PPI = dict(num_nodes=56944, avg_degree=15, feature_dim=50, label_dim=121,
           multilabel=True)
REDDIT = dict(num_nodes=232965, avg_degree=50, feature_dim=602, label_dim=41,
              multilabel=False)


def _cache_begin(out_dir: str, params: str,
                 protect_unmarked: bool = False) -> bool:
    """Shared done-marker protocol for every synthetic builder. True =
    a finished build with IDENTICAL params is already there (caller
    returns immediately). False = stale/partial/absent: stale outputs
    are cleared, the in-progress marker is written (so an interrupted
    build is detected and regenerated next time), and the caller must
    generate then call _cache_finish. ``protect_unmarked``: .dat
    partitions with NO marker at all are a real converted dataset —
    treated as cached rather than overwritten (build_synthetic's
    contract)."""
    os.makedirs(out_dir, exist_ok=True)
    marker = os.path.join(out_dir, "done")
    wip = os.path.join(out_dir, "synthetic-in-progress")
    if os.path.exists(marker):
        with open(marker) as f:
            if f.read() == params:
                return True
    elif (
        protect_unmarked
        and not os.path.exists(wip)
        and any(n.endswith(".dat") for n in os.listdir(out_dir))
    ):
        return True
    with open(wip, "w") as f:
        f.write(params)
    for name in os.listdir(out_dir):
        if name.endswith(".dat") or name in ("done", "meta.json"):
            os.unlink(os.path.join(out_dir, name))
    return False


def _cache_finish(out_dir: str, params: str) -> None:
    with open(os.path.join(out_dir, "done"), "w") as f:
        f.write(params)
    os.unlink(os.path.join(out_dir, "synthetic-in-progress"))


def build_synthetic(
    out_dir: str,
    num_nodes: int,
    avg_degree: int,
    feature_dim: int,
    label_dim: int,
    multilabel: bool = True,
    num_partitions: int = 4,
    max_degree: int = 60,
    seed: int = 7,
) -> str:
    """Write a synthetic graph as .dat partitions + meta.json (cached: a
    'done' marker records the generation params and skips regeneration only
    when they match). Returns out_dir."""
    params = json.dumps(
        dict(num_nodes=num_nodes, avg_degree=avg_degree,
             feature_dim=feature_dim, label_dim=label_dim,
             multilabel=multilabel, num_partitions=num_partitions,
             max_degree=max_degree, seed=seed),
        sort_keys=True,
    )
    if _cache_begin(out_dir, params, protect_unmarked=True):
        return out_dir
    from euler_tpu.graph.convert import pack_block

    rng = np.random.default_rng(seed)
    meta = {
        "node_type_num": 1,
        "edge_type_num": 1,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    paths = [
        os.path.join(out_dir, "part_%d.dat" % p)
        for p in range(num_partitions)
    ]
    outs = [open(p, "wb") for p in paths]
    degrees = rng.poisson(avg_degree, num_nodes).clip(1, max_degree)
    for nid in range(num_nodes):
        nbrs = rng.integers(0, num_nodes, degrees[nid])
        if multilabel:
            labels = rng.integers(0, 2, label_dim).astype(float)
        else:
            labels = np.zeros(label_dim)
            labels[rng.integers(0, label_dim)] = 1.0
        node = {
            "node_id": nid,
            "node_type": 0,
            "node_weight": 1.0,
            "neighbor": {"0": {str(int(d)): 1.0 for d in nbrs}},
            "uint64_feature": {},
            "float_feature": {
                "0": labels.tolist(),
                "1": rng.standard_normal(feature_dim).round(3).tolist(),
            },
            "binary_feature": {},
            "edge": [],
        }
        outs[nid % num_partitions].write(pack_block(node, meta))
    for o in outs:
        o.close()
    _cache_finish(out_dir, params)
    return out_dir


def build_planted(
    out_dir: str,
    num_nodes: int = 2000,
    num_communities: int = 4,
    feature_dim: int = 16,
    avg_degree: int = 10,
    intra_p: float = 0.9,
    noise: float = 1.0,
    num_partitions: int = 2,
    max_degree: int = 30,
    seed: int = 11,
    alpha: float | None = None,
):
    """Planted-community graph: the convergence gate for supervised GNNs.

    ``alpha`` switches the degree distribution from
    Poisson(avg_degree).clip(1, max_degree) to the heavy-tailed power
    law of ``powerlaw_degrees`` (d_cap = max_degree) — the form the
    max_degree-truncation cost study trains on: same planted labels,
    same centroids, but hub nodes whose slab rows must truncate.

    Each node belongs to one of ``num_communities`` hidden communities;
    its label (float_feature slot 0, one-hot) IS the community, its input
    features (slot 1) are the community centroid plus ``noise`` * N(0,1),
    and a fraction ``intra_p`` of its edges stay inside the community.
    With the default noise the single-node nearest-centroid accuracy is
    mediocre while averaging the ~``avg_degree`` mostly-intra-community
    neighbor features denoises by ~sqrt(degree) and makes the label nearly
    perfectly recoverable — exactly the function a neighborhood-aggregating
    GNN (GraphSAGE/GCN/GAT) should learn. Tests compute both
    nearest-centroid accuracies numerically from the returned arrays to
    derive the F1 target instead of hard-coding folklore numbers.

    Returns (out_dir, info) where info holds the generation arrays:
    ``communities`` [N], ``features`` [N, F], ``centroids`` [K, F] and
    ``neighbors`` (list of per-node neighbor id arrays). The graph is
    written as .dat partitions + meta.json (cached like build_synthetic);
    info is regenerated deterministically from the seed either way.
    """
    os.makedirs(out_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    centroids = rng.standard_normal((num_communities, feature_dim))
    centroids /= np.linalg.norm(centroids, axis=1, keepdims=True)
    communities = rng.integers(0, num_communities, num_nodes)
    features = (
        centroids[communities]
        + noise * rng.standard_normal((num_nodes, feature_dim))
    ).astype(np.float32)
    by_comm = [
        np.flatnonzero(communities == c) for c in range(num_communities)
    ]
    if alpha is None:
        degrees = rng.poisson(avg_degree, num_nodes).clip(1, max_degree)
    else:
        degrees = powerlaw_degrees(
            num_nodes, num_nodes * avg_degree, alpha, rng,
            d_cap=max_degree,
        )
    neighbors = []
    for nid in range(num_nodes):
        d = degrees[nid]
        intra = rng.random(d) < intra_p
        own = by_comm[communities[nid]]
        nbrs = np.where(
            intra,
            own[rng.integers(0, len(own), d)],
            rng.integers(0, num_nodes, d),
        )
        neighbors.append(nbrs)
    info = dict(
        communities=communities,
        features=features,
        centroids=centroids,
        neighbors=neighbors,
    )

    params = json.dumps(
        dict(kind="planted", num_nodes=num_nodes,
             num_communities=num_communities, feature_dim=feature_dim,
             avg_degree=avg_degree, intra_p=intra_p, noise=noise,
             num_partitions=num_partitions, max_degree=max_degree,
             seed=seed, alpha=alpha),
        sort_keys=True,
    )
    if _cache_begin(out_dir, params):
        return out_dir, info
    from euler_tpu.graph.convert import pack_block

    meta = {
        "node_type_num": 1,
        "edge_type_num": 1,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    outs = [
        open(os.path.join(out_dir, "part_%d.dat" % p), "wb")
        for p in range(num_partitions)
    ]
    for nid in range(num_nodes):
        labels = np.zeros(num_communities)
        labels[communities[nid]] = 1.0
        node = {
            "node_id": nid,
            "node_type": 0,
            "node_weight": 1.0,
            "neighbor": {
                "0": {str(int(d)): 1.0 for d in neighbors[nid]}
            },
            "uint64_feature": {},
            "float_feature": {
                "0": labels.tolist(),
                "1": features[nid].tolist(),
            },
            "binary_feature": {},
            "edge": [],
        }
        outs[nid % num_partitions].write(pack_block(node, meta))
    for o in outs:
        o.close()
    _cache_finish(out_dir, params)
    return out_dir, info


def powerlaw_degrees(
    num_nodes: int, num_edges: int, alpha: float, rng,
    d_min: int = 1, d_cap: int | None = None,
):
    """[num_nodes] int64 out-degrees from a discrete power law
    P(d) ~ d^-alpha (inverse-transform Pareto, d >= d_min, capped at
    ``d_cap`` or num_nodes/4), then scaled so the total lands within
    ~1% of ``num_edges``. Real Reddit's degree distribution is
    heavy-tailed with mean ~490 over 233k nodes; alpha in [1.6, 2.2]
    reproduces that max/mean shape (see scripts/reddit_heavytail.py)."""
    if alpha <= 1.0:
        raise ValueError(
            f"powerlaw_degrees needs alpha > 1 (got {alpha}): the "
            "inverse-transform exponent -1/(alpha-1) is undefined at 1 "
            "and flips sign below it (degenerating to all-d_min rows)"
        )
    if d_cap is None:
        d_cap = max(d_min + 1, num_nodes // 4)
    u = rng.random(num_nodes)
    d = d_min * (1.0 - u) ** (-1.0 / (alpha - 1.0))
    d = np.minimum(d, d_cap)
    # multiplicative rescale to the target edge count; iterate because
    # the cap bites harder as the scale grows
    for _ in range(16):
        total = d.sum()
        if abs(total - num_edges) <= 0.01 * num_edges:
            break
        d = np.minimum(np.maximum(d * (num_edges / total), d_min), d_cap)
    return np.maximum(d.round(), d_min).astype(np.int64)


def _powerlaw_params(num_nodes, num_edges, feature_dim, label_dim,
                     alpha, multilabel, num_partitions, seed,
                     placement="hash") -> str:
    """The cache-identity string build_powerlaw's done marker records —
    one constructor so external gates (scripts/tpu_checks.sh's
    heavytail step) and the builder can never disagree on it."""
    d = dict(kind="powerlaw", num_nodes=num_nodes, num_edges=num_edges,
             feature_dim=feature_dim, label_dim=label_dim, alpha=alpha,
             multilabel=multilabel, num_partitions=num_partitions,
             seed=seed, gen="unique-fill-v3-gumbel-hubs")
    if placement != "hash":
        # keyed only when non-default so every pre-PR done marker (and
        # the tpu_checks gate's reconstruction of it) stays valid
        d["placement"] = placement
    return json.dumps(d, sort_keys=True)


def heavytail_cache_dir() -> str:
    """Default build_powerlaw cache dir for the Reddit-scale graph —
    ONE resolver shared by bench.py's reddit_heavytail config,
    scripts/reddit_heavytail.py --full, and scripts/tpu_checks.sh's
    gate (a third hard-coded copy of the path is how the gate ends up
    checking a different directory than the bench builds in).
    EULER_TPU_HEAVYTAIL_CACHE overrides; else <repo>/.data/reddit_ht."""
    return os.environ.get(
        "EULER_TPU_HEAVYTAIL_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".data", "reddit_ht",
        ),
    )


def powerlaw_cache_ready(
    out_dir: str,
    num_nodes: int,
    num_edges: int,
    feature_dim: int,
    label_dim: int,
    alpha: float = 1.8,
    multilabel: bool = False,
    num_partitions: int = 4,
    seed: int = 17,
) -> bool:
    """True when ``out_dir`` holds a FINISHED build_powerlaw cache with
    EXACTLY these params (the done marker records them). A bare
    existence check is not enough: _cache_begin wipes and regenerates
    on any params mismatch, so a gate that only tests the marker file
    would wave through a stale cache and pay the full rebuild anyway —
    on a chip window, if the caller is scripts/tpu_checks.sh."""
    marker = os.path.join(out_dir, "done")
    if not os.path.exists(marker):
        return False
    with open(marker) as f:
        return f.read() == _powerlaw_params(
            num_nodes, num_edges, feature_dim, label_dim, alpha,
            multilabel, num_partitions, seed,
        )


def build_powerlaw(
    out_dir: str,
    num_nodes: int,
    num_edges: int,
    feature_dim: int,
    label_dim: int,
    alpha: float = 1.8,
    multilabel: bool = False,
    num_partitions: int = 4,
    seed: int = 17,
    progress_every: int = 0,
    placement: str = "hash",
) -> str:
    """Heavy-tailed synthetic graph at a REAL edge budget: power-law
    out-degrees (``powerlaw_degrees``) with targets drawn preferentially
    (p ~ degree), so in-degrees are heavy-tailed too — the degree shape
    build_synthetic's Poisson(avg_degree).clip(max_degree) deliberately
    avoids and real Reddit (~233k nodes x ~114M directed edges, mean
    ~490, hub degrees in the tens of thousands) actually has. Weights
    are 1.0 like real Reddit. This is the graph the max_degree
    truncation questions must be answered on: an untruncated device
    slab would be [N, max_observed_degree] and is not buildable, which
    is exactly the regime the exact (alias) device sampler exists for.

    Neighbors are drawn UNIQUE per source: naive with-replacement draws
    against a preferential target distribution collide so often that a
    120M-draw run landed only 74M distinct edges (measured 2026-07-31),
    35% under the real budget the graph exists to hit. Typical rows use
    draw/drop-duplicates/redraw rounds; HUB rows (where bounded redraws
    still fell 4.5% short in aggregate) switch to an exact weighted
    sample WITHOUT replacement via the Gumbel top-k race — so the
    achieved edge count tracks sum(degrees) ~ num_edges to <1%. Cached
    via the same done-marker protocol as build_synthetic. Returns
    out_dir.
    """
    os.makedirs(out_dir, exist_ok=True)
    params = _powerlaw_params(
        num_nodes, num_edges, feature_dim, label_dim, alpha, multilabel,
        num_partitions, seed, placement,
    )
    if _cache_begin(out_dir, params):
        return out_dir
    from euler_tpu.graph.convert import pack_block

    rng = np.random.default_rng(seed)
    degrees = powerlaw_degrees(num_nodes, num_edges, alpha, rng)
    # preferential targets: p ~ degree, drawn by inverse-CDF per node
    cum = np.cumsum(degrees.astype(np.float64))
    cum /= cum[-1]
    meta = {
        "node_type_num": 1,
        "edge_type_num": 1,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    # placement='degree' (eg_placement.h): buffer the node dicts and let
    # the converter's degree-aware placer route them + emit the
    # placement artifact — trades the streaming writer's O(1) memory for
    # the two-pass placement (fixture/bench scales; the hash default
    # keeps streaming for reddit-scale builds)
    if placement != "hash":
        from euler_tpu.graph.convert import _check_placement

        _check_placement(placement)
    buffered: list | None = [] if placement != "hash" else None
    outs = [] if buffered is not None else [
        open(os.path.join(out_dir, "part_%d.dat" % p), "wb")
        for p in range(num_partitions)
    ]
    # hub rows draw a large fraction of the skewed target mass; redraw
    # rounds stall once the heavy targets are all taken, so past this
    # degree use the exact O(N) Gumbel race instead (few thousand rows
    # at Reddit scale — ~2 ms each)
    hub_degree = max(2048, num_nodes // 64)
    log_w = np.log(degrees.astype(np.float64))
    for nid in range(num_nodes):
        d = int(degrees[nid])
        if d >= hub_degree:
            # exact weighted sample WITHOUT replacement (Gumbel top-k /
            # Efraimidis-Spirakis race): perturb log-weights with Gumbel
            # noise, keep the d largest — every row lands exactly d
            # unique neighbors with the preferential distribution.
            # Uniforms clipped away from 0: log(0) would emit a
            # divide-by-zero warning (the -inf key itself is harmless)
            u = np.maximum(rng.random(num_nodes), np.finfo(np.float64).tiny)
            g = log_w - np.log(-np.log(u))
            nbrs = np.argpartition(g, num_nodes - d)[num_nodes - d:]
        else:
            # unique-fill: redraw the duplicate shortfall (bounded
            # rounds; each round oversamples 25% for collisions)
            nbrs = np.unique(np.searchsorted(cum, rng.random(d)))
            for _ in range(8):
                short = d - nbrs.size
                if short <= 0:
                    break
                extra = np.searchsorted(
                    cum, rng.random(short + short // 4 + 4)
                )
                nbrs = np.union1d(nbrs, extra)
            if nbrs.size > d:
                # union1d sorts; a [:d] trim would keep only LOW ids —
                # drop the overshoot uniformly instead
                nbrs = rng.choice(nbrs, size=d, replace=False)
        if multilabel:
            labels = rng.integers(0, 2, label_dim).astype(float)
        else:
            labels = np.zeros(label_dim)
            labels[rng.integers(0, label_dim)] = 1.0
        node = {
            "node_id": nid,
            "node_type": 0,
            "node_weight": 1.0,
            "neighbor": {"0": {str(int(t)): 1.0 for t in nbrs}},
            "uint64_feature": {},
            "float_feature": {
                "0": labels.tolist(),
                "1": rng.standard_normal(feature_dim).round(3).tolist(),
            },
            "binary_feature": {},
            "edge": [],
        }
        if buffered is not None:
            buffered.append(node)
        else:
            outs[nid % num_partitions].write(pack_block(node, meta))
        if progress_every and nid and nid % progress_every == 0:
            print(
                "build_powerlaw: %d/%d nodes" % (nid, num_nodes),
                flush=True,
            )
    for o in outs:
        o.close()
    if buffered is not None:
        from euler_tpu.graph.convert import convert_dicts

        convert_dicts(
            buffered, meta, os.path.join(out_dir, "part"),
            num_partitions=num_partitions, placement=placement,
        )
    _cache_finish(out_dir, params)
    return out_dir


# real Reddit's published scale: 232,965 nodes, ~114.6M directed edges
# (mean degree ~492) — the shape scripts/reddit_heavytail.py measures
REDDIT_HEAVYTAIL = dict(
    num_nodes=232965, num_edges=114_600_000, feature_dim=602,
    label_dim=41, alpha=1.8, multilabel=False,
)


def nearest_centroid_accuracy(info: dict, use_neighbors: bool) -> float:
    """Fraction of nodes whose (optionally neighborhood-averaged) feature
    vector is nearest to its own community centroid — the numeric
    separability bound the convergence tests gate against."""
    feats = info["features"]
    if use_neighbors:
        agg = np.stack(
            [
                (feats[nid] + info["features"][nbrs].sum(0))
                / (1 + len(nbrs))
                for nid, nbrs in enumerate(info["neighbors"])
            ]
        )
    else:
        agg = feats
    pred = np.argmax(agg @ info["centroids"].T, axis=1)
    return float(np.mean(pred == info["communities"]))


def build_ppi(out_dir: str, **overrides) -> str:
    return build_synthetic(out_dir, **{**PPI, **overrides})


def build_reddit(out_dir: str, **overrides) -> str:
    return build_synthetic(out_dir, **{**REDDIT, **overrides})


# ---------------------------------------------------------------------------
# Real-dataset preparation (the transform halves of the reference's
# examples/ppi_data.py:40-150 and reddit_data.py:42-58, minus the network
# download — zero egress here; point these at data already on disk).
# Both write meta.json + part_<p>.dat partitions + {train,val,test}.id
# files ready for `python -m euler_tpu.ppi_main / reddit_main`.
# ---------------------------------------------------------------------------


def _write_graph(out_dir, meta, nodes_iter, id_lists, num_partitions):
    from euler_tpu.graph.convert import convert_dicts

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    convert_dicts(
        nodes_iter, meta, os.path.join(out_dir, "part"), num_partitions
    )
    # id files hold GRAPH node ids (what evaluate/save_embedding query) —
    # deliberate deviation from the reference, which writes the source
    # dataset's id_map values (ppi_data.py:150) and so can't evaluate the
    # graph it just built unless id_map is the identity.
    names = ["train.id", "val.id", "test.id"]
    for name, ids in zip(names, id_lists):
        with open(os.path.join(out_dir, name), "w") as f:
            f.writelines("%d\n" % i for i in ids)
    return out_dir


def prepare_ppi(prefix: str, out_dir: str, num_partitions: int = 1,
                normalize: bool = True) -> str:
    """GraphSAGE-format PPI on disk -> .dat partitions.

    ``prefix`` as in the GraphSAGE release: reads ``{prefix}-G.json``
    (node-link), ``{prefix}-feats.npy``, ``{prefix}-id_map.json``,
    ``{prefix}-class_map.json``. Mirrors reference
    examples/ppi_data.py:40-175: nodes lacking val/test annotations are
    dropped; node types are train=0/val=1/test=2; edges touching a
    val/test endpoint get type 1 ("train_removed"), others type 0;
    features are standardized by train-split statistics; float_feature
    slot 0 = the multilabel class vector, slot 1 = features.
    """
    with open(prefix + "-G.json") as f:
        g = json.load(f)
    feats = np.load(prefix + "-feats.npy").astype(np.float64)
    with open(prefix + "-id_map.json") as f:
        id_map = {int(k): int(v) for k, v in json.load(f).items()}
    with open(prefix + "-class_map.json") as f:
        class_map = {int(k): v for k, v in json.load(f).items()}

    node_ids = [n["id"] for n in g["nodes"]]
    attrs = {n["id"]: n for n in g["nodes"]}
    # node-link "links" reference positions in the "nodes" array
    # (networkx 1.x node_link_data, what the GraphSAGE release used)
    adj: dict[int, set] = {i: set() for i in node_ids}
    for link in g["links"]:
        s = node_ids[link["source"]]
        t = node_ids[link["target"]]
        adj[s].add(t)
        adj[t].add(s)

    kept = [i for i in node_ids if "val" in attrs[i] and "test" in attrs[i]]
    kept_set = set(kept)

    def ntype(i):
        return 1 if attrs[i]["val"] else (2 if attrs[i]["test"] else 0)

    if normalize:
        train_rows = np.array(
            [id_map[i] for i in kept if ntype(i) == 0], dtype=np.int64
        )
        mean = feats[train_rows].mean(axis=0)
        std = feats[train_rows].std(axis=0)
        std[std == 0] = 1.0
        feats = (feats - mean) / std

    meta = {
        "node_type_num": 3,
        "edge_type_num": 2,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,  # 0 labels, 1 features
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }

    def etype(a, b):
        # "train_removed": either endpoint is outside the train split
        return 1 if (ntype(a) or ntype(b)) else 0

    def nodes_iter():
        for i in kept:
            nbrs = [n for n in adj[i] if n in kept_set]
            labels = class_map[i]
            labels = (
                [float(x) for x in labels]
                if isinstance(labels, list)
                else [float(labels)]
            )
            yield {
                "node_id": i,
                "node_type": ntype(i),
                "node_weight": 1,
                "neighbor": {
                    str(t): {
                        str(n): 1 for n in nbrs if etype(i, n) == t
                    }
                    for t in range(2)
                },
                "uint64_feature": {},
                "float_feature": {
                    "0": labels,
                    "1": feats[id_map[i]].tolist(),
                },
                "binary_feature": {},
                "edge": [
                    {
                        "src_id": i,
                        "dst_id": n,
                        "edge_type": etype(i, n),
                        "weight": 1,
                        "uint64_feature": {},
                        "float_feature": {},
                        "binary_feature": {},
                    }
                    for n in nbrs
                ],
            }

    ids = [[i for i in kept if ntype(i) == t] for t in range(3)]
    return _write_graph(out_dir, meta, nodes_iter(), ids, num_partitions)


def prepare_reddit(data_dir: str, out_dir: str,
                   num_partitions: int = 1) -> str:
    """DGL reddit npz files on disk -> .dat partitions.

    Reads ``{data_dir}/reddit_self_loop_graph.npz`` (scipy CSR adjacency)
    and ``{data_dir}/reddit_data.npz`` (feature / node_ids / label /
    node_types). Mirrors reference examples/reddit_data.py:42-135: node
    type = node_types - 1 (train=0/val=1/test=2), all edges type 0,
    float_feature slot 0 = one-hot(label, 41), slot 1 = features.
    """
    import scipy.sparse as sp

    graph = sp.load_npz(
        os.path.join(data_dir, "reddit_self_loop_graph.npz")
    ).tocsr()
    data = np.load(os.path.join(data_dir, "reddit_data.npz"))
    feats = data["feature"]
    id_map = data["node_ids"].astype(np.int64)
    labels = data["label"].astype(np.int64)
    node_types = data["node_types"].astype(np.int64)
    num_nodes = graph.shape[0]
    num_classes = int(labels.max()) + 1

    meta = {
        "node_type_num": 3,
        "edge_type_num": 1,
        "node_uint64_feature_num": 0,
        "node_float_feature_num": 2,  # 0 labels, 1 features
        "node_binary_feature_num": 0,
        "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0,
        "edge_binary_feature_num": 0,
    }

    def nodes_iter():
        indptr, indices = graph.indptr, graph.indices
        for i in range(num_nodes):
            nbrs = indices[indptr[i]:indptr[i + 1]]
            onehot = [0.0] * num_classes
            onehot[int(labels[i])] = 1.0
            yield {
                "node_id": i,
                "node_type": int(node_types[i]) - 1,
                "node_weight": 1,
                "neighbor": {"0": {str(int(n)): 1 for n in nbrs}},
                "uint64_feature": {},
                "float_feature": {
                    "0": onehot,
                    "1": feats[i].tolist(),
                },
                "binary_feature": {},
                "edge": [
                    {
                        "src_id": i,
                        "dst_id": int(n),
                        "edge_type": 0,
                        "weight": 1,
                        "uint64_feature": {},
                        "float_feature": {},
                        "binary_feature": {},
                    }
                    for n in nbrs
                ],
            }

    ids = [
        [i for i in range(num_nodes) if node_types[i] - 1 == t]
        for t in range(3)
    ]
    return _write_graph(out_dir, meta, nodes_iter(), ids, num_partitions)


def main() -> None:
    """CLI: synthetic builders + real-data preparation.

    python -m euler_tpu.datasets ppi|reddit --out DIR          (synthetic)
    python -m euler_tpu.datasets prepare_ppi --prefix P --out DIR
    python -m euler_tpu.datasets prepare_reddit --src DIR --out DIR
    """
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("cmd", choices=[
        "ppi", "reddit", "prepare_ppi", "prepare_reddit"])
    ap.add_argument("--out", required=True)
    ap.add_argument("--prefix", help="GraphSAGE file prefix (prepare_ppi)")
    ap.add_argument("--src", help="DGL npz directory (prepare_reddit)")
    ap.add_argument("--partitions", type=int, default=1)
    args = ap.parse_args()
    if args.cmd == "ppi":
        print(build_ppi(args.out, num_partitions=args.partitions))
    elif args.cmd == "reddit":
        print(build_reddit(args.out, num_partitions=args.partitions))
    elif args.cmd == "prepare_ppi":
        if not args.prefix:
            ap.error("prepare_ppi needs --prefix")
        print(prepare_ppi(args.prefix, args.out, args.partitions))
    else:
        if not args.src:
            ap.error("prepare_reddit needs --src")
        print(prepare_reddit(args.src, args.out, args.partitions))


if __name__ == "__main__":
    main()
