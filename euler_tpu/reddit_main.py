"""Reddit preset CLI (reference tf_euler/python/reddit_main.py:24-34:
max_id 232965, 602-dim features, 41 softmax classes).

    python -m euler_tpu.reddit_main --data_dir <reddit .dat dir> [overrides]
"""

import sys

from euler_tpu.run_loop import main

REDDIT_DEFAULTS = [
    "--max_id", "232965",
    "--feature_idx", "1",
    "--feature_dim", "602",
    "--label_idx", "0",
    "--label_dim", "41",
    "--all_edge_type", "0,1",
    "--sigmoid_loss", "false",
]


def run(argv=None) -> int:
    argv = REDDIT_DEFAULTS + list(argv if argv is not None else sys.argv[1:])
    return main(argv)


if __name__ == "__main__":
    sys.exit(run())
