"""``python -m euler_tpu`` — the training CLI (reference
``python -m tf_euler``, tf_euler/python/__main__.py -> run_loop.main)."""

import sys

from euler_tpu.run_loop import main

sys.exit(main())
