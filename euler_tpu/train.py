"""Training / evaluation / embedding-export driver.

Reference equivalent: tf_euler/python/run_loop.py (run_train :95-140,
run_evaluate :143-171, run_save_embedding :174-219) — rebuilt for JAX:
MonitoredTrainingSession becomes an explicit loop over a jitted train step;
PS placement becomes mesh sharding (see parallel/mesh.py); the input
pipeline is the host sampler behind a prefetch queue.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import jax
import numpy as np
import optax

from euler_tpu.nn import metrics as metrics_lib
from euler_tpu.parallel import (
    batch_sharding,
    make_mesh,
    pad_tables_for_mesh,
    pipeline,
    prefetch,
    put_global,
    replicated_sharding,
    shard_batch,
    state_sharding,
)

log = logging.getLogger("euler_tpu")

OPTIMIZERS = {
    "sgd": optax.sgd,
    "momentum": lambda lr: optax.sgd(lr, momentum=0.9),
    "adagrad": optax.adagrad,
    "adam": optax.adam,
}


def get_optimizer(name: str, lr: float):
    """Reference tf_euler/python/optimizers.py registry."""
    return OPTIMIZERS[name](lr)


def _metric_value(name: str, acc) -> float:
    if name == "f1":
        return metrics_lib.f1_from_counts(acc)
    if name == "auc":
        return metrics_lib.auc_from_counts(acc)
    return float(acc[0] / max(acc[1], 1))  # running mean


def _metric_accumulate(name: str, acc, value):
    value = np.asarray(value)
    if name in ("f1", "auc"):
        return acc + value
    return np.array([acc[0] + float(value), acc[1] + 1.0])


def _metric_zero(name: str):
    if name == "f1":
        return np.zeros(3)
    if name == "auc":
        return np.zeros((2, metrics_lib.AUC_BINS))
    return np.zeros(2)


def train(
    model,
    graph,
    source_fn: Callable[[int], np.ndarray],
    num_steps: int,
    optimizer: str = "adam",
    learning_rate: float = 0.01,
    mesh=None,
    log_every: int = 100,
    seed: int = 42,
    prefetch_depth: int = 2,
    prefetch_threads: int = 2,
    sampler_depth: int = 2,
    state: Optional[dict] = None,
    log_fn=None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    profile_dir: Optional[str] = None,
    profile_steps: tuple = (10, 20),
    device_prefetch: bool = True,
    sync_every: Optional[int] = None,
    step_hook=None,
    phase_profile: Optional[bool] = None,
):
    """Train and return (state, history).

    step_hook(step) runs on the training thread after every dispatched
    step (run_loop's --metrics_every JSONL emitter rides here; the hook
    gates itself, so the per-step cost is one call + one modulo).

    phase_profile records the step-phase histograms (OBSERVABILITY.md
    "Step phases"): input_stall + sample inside the prefetch pipeline,
    h2d (host->device transfer), device (compute, FENCED per step via
    block_until_ready — attribution needs the fence, so async dispatch
    no longer runs ahead; host sampling still overlaps through the
    prefetch workers), host (optimizer/bookkeeping tail), and the
    whole-step wall. None (default) follows the telemetry kill-switch:
    profiling on when telemetry is on, and `telemetry=0` restores the
    fully-async unfenced loop.

    source_fn(step) -> int64 root-node batch (fixed size, divisible by the
    mesh size). All sampling runs in the prefetch workers.

    sampler_depth enables the native async pipeline on REMOTE graphs:
    instead of prefetch worker threads each blocking inside a full
    model.sample(), one driver thread keeps up to sampler_depth steps
    submitted through the engine's completion queue
    (model.sample_start -> eg_remote_sample_async; the hop chain runs as
    continuations on the client dispatcher pool) and finishes them in
    order (model.sample_finish). Step k+1..k+sampler_depth sampling
    overlaps step k's H2D + device compute with zero dedicated sampling
    threads, which is what drives input_stall_ms to ~0 (ROADMAP item 1,
    PERF.md "Pipelined sampling"). sampler_depth=0 disables the split
    and always uses the thread-pool prefetch; local in-process graphs
    ignore it (no wire to overlap — they stay on prefetch).

    Multi-process (jax.distributed initialized, process_count > 1):
    source_fn yields this process's LOCAL batch (global batch /
    process_count roots); each process samples its own subgraphs and the
    batches concatenate across processes onto the global mesh
    (shard_batch), with XLA all-reducing gradients across process
    boundaries inside the jitted step. State is initialised identically
    everywhere (same seed) and placed via put_global. checkpoint_dir
    must then be a path every process can reach (orbax coordinates the
    distributed save) or None.

    device_prefetch=True also issues the host->device copy from the
    prefetch workers, overlapping H2D of batch k+1 with compute of step k
    — at the cost of holding up to prefetch_depth+1 staged batches in
    device memory. Set False (one staged batch) for configs sized near the
    HBM limit.

    checkpoint_dir enables MonitoredTrainingSession-style periodic save +
    resume-from-latest (reference run_loop.py:132-138); profile_dir captures
    a JAX profiler trace over profile_steps (the reference's ProfilerHook,
    run_loop.py:124-126). Note with device_prefetch the copies for the
    first ~prefetch_depth profiled steps were issued before the trace
    starts and won't appear in it.
    """
    if mesh is None:
        mesh = make_mesh()
    n_mesh_devices = int(np.prod(mesh.devices.shape))
    cpu_virtual_mesh = (
        n_mesh_devices > 1
        and mesh.devices.reshape(-1)[0].platform == "cpu"
    )
    if sync_every is None:
        # Async dispatch depth must be 1 on a multi-device CPU (virtual)
        # mesh: XLA-CPU collectives BLOCK a shared pool thread inside the
        # all-reduce rendezvous, so device programs queued from later
        # steps can consume every pool thread while an earlier step's
        # rendezvous still waits for its last participant — a livelock
        # XLA resolves by aborting the process after 40 s. Real TPU
        # queues per-device streams in hardware; a modest sync there just
        # bounds queued-buffer memory.
        sync_every = 1 if cpu_virtual_mesh else 32
    opt = get_optimizer(optimizer, learning_rate)
    if state is None:
        state = model.init_state(
            jax.random.PRNGKey(seed), graph, source_fn(0), opt
        )
    rep = replicated_sharding(mesh)
    # Params/opt replicated; per-node tables row-sharded over the mesh's
    # 'model' axis when present (pure DP: everything replicated).
    state = pad_tables_for_mesh(state, mesh)
    shardings = state_sharding(mesh, state)
    state = put_global(state, shardings)

    ckpt = None
    start_step = 0
    if checkpoint_dir:
        from euler_tpu.checkpoint import Checkpointer

        ckpt = Checkpointer(checkpoint_dir)
        latest = ckpt.latest_step()
        if latest is not None:
            state = ckpt.restore(state, latest)
            state = put_global(state, shardings)
            start_step = latest
            (log_fn or log.info)(
                f"resumed from {checkpoint_dir} at step {latest}"
            )
        if checkpoint_every <= 0:
            checkpoint_every = max(num_steps // 10, 1)
    step_fn = jax.jit(
        model.make_train_step(opt),
        in_shardings=(shardings, batch_sharding(mesh)),
        out_shardings=(shardings, rep, rep),
        donate_argnums=(0,),
    )

    from euler_tpu import devprof

    if phase_profile is None:
        from euler_tpu.telemetry import telemetry_enabled

        try:
            phase_profile = telemetry_enabled()
        except Exception:
            phase_profile = False
    if phase_profile:
        from euler_tpu.telemetry import record_phase
    if device_prefetch and cpu_virtual_mesh:
        # XLA's CPU multi-device backend shares one in-process communicator:
        # device_put issued from prefetch worker threads can starve a
        # collective rendezvous inside a concurrently executing step (7 of 8
        # participants arrive, then a fatal 40s termination timeout). Real
        # TPU/GPU devices transfer asynchronously and don't have this
        # hazard; on a virtual CPU mesh, transfer on the consumer thread.
        device_prefetch = False

    def make_batch(step):
        # With device_prefetch, device_put runs here inside the prefetch
        # worker, so the host->device copy of batch k+1 overlaps device
        # compute of step k (the copy releases the GIL).
        t0 = time.perf_counter()
        batch = model.sample(graph, source_fn(step))
        if not phase_profile:
            if device_prefetch:
                batch = shard_batch(batch, mesh)
                devprof.count_h2d(batch)
            return batch
        # prefetch applies the start offset before calling: step is
        # already the absolute step index here
        t1 = time.perf_counter()
        record_phase("sample", (t1 - t0) * 1e6, step=step)
        if device_prefetch:
            batch = shard_batch(batch, mesh)
            devprof.count_h2d(batch)
            record_phase(
                "h2d", (time.perf_counter() - t1) * 1e6, step=step
            )
        return batch

    # Native async pipeline (remote graphs only): start_batch submits the
    # step's whole fan-out into the engine's completion queue and returns
    # immediately; finish_batch blocks on the handle and assembles the
    # batch. The split rides the same phase-recording contract as
    # make_batch — "sample" here is the time spent WAITING on the handle,
    # so a fully-hidden pipeline reads as sample ~ 0 in the phase table.
    use_pipeline = (
        sampler_depth > 0 and getattr(graph, "mode", None) == "remote"
    )

    def start_batch(step):
        return model.sample_start(graph, source_fn(step))

    def finish_batch(step, pending):
        t0 = time.perf_counter()
        batch = model.sample_finish(graph, pending)
        if not phase_profile:
            if device_prefetch:
                batch = shard_batch(batch, mesh)
                devprof.count_h2d(batch)
            return batch
        t1 = time.perf_counter()
        record_phase("sample", (t1 - t0) * 1e6, step=step)
        if device_prefetch:
            batch = shard_batch(batch, mesh)
            devprof.count_h2d(batch)
            record_phase(
                "h2d", (time.perf_counter() - t1) * 1e6, step=step
            )
        return batch

    name = model.metric_name
    history = []
    t0 = time.time()
    # Metrics stay on device inside the logging window — forcing them to
    # host every step would sync the pipeline and stall the prefetch overlap
    # (JAX dispatch is async; only materialize at the log boundary).
    window_metrics = []
    last_loss = None
    steps_done = start_step

    def flush():
        nonlocal window_metrics, t0
        acc = _metric_zero(name)
        for m in window_metrics:
            acc = _metric_accumulate(name, acc, m)
        loss_v = float(last_loss)
        # Metric/loss materialization is the training loop's d2h point.
        devprof.count_d2h((window_metrics, last_loss))
        mv = _metric_value(name, acc)
        dt = time.time() - t0
        sps = len(window_metrics) / dt
        history.append({"loss": loss_v, name: mv, "steps_per_sec": sps})
        (log_fn or log.info)(
            f"step={steps_done} loss={loss_v:.4f} "
            f"{name}={mv:.4f} steps/s={sps:.2f}"
        )
        window_metrics = []
        t0 = time.time()

    def seed_worker(widx: int):
        # Deterministic per-worker sampler streams: the native RNG is
        # thread-local, so each prefetch worker gets its own seeded stream
        # derived from the run seed (reference samplers are unseeded).
        # Multi-process data parallelism folds the process index in —
        # with identical streams every process would draw the SAME local
        # roots, silently collapsing the global batch to one process's.
        from euler_tpu.graph.native import lib

        lib().eg_seed(
            seed * 1_000_003 + jax.process_index() * 8_191 + widx + 1
        )

    profiling = False
    t_step = time.perf_counter()
    if use_pipeline:
        batches = pipeline(
            start_batch,
            finish_batch,
            num_steps - start_step,
            depth=sampler_depth,
            start=start_step,
            worker_init=seed_worker,
            profile=phase_profile,
            record_sample=False,  # finish_batch above records sample/h2d
        )
    else:
        batches = prefetch(
            make_batch,
            num_steps - start_step,
            prefetch_depth,
            prefetch_threads,
            start=start_step,
            worker_init=seed_worker,
            profile=phase_profile,
            record_sample=False,  # make_batch above records sample/h2d
        )
    for batch in batches:
        # phase brackets (input_stall was recorded inside prefetch):
        # h2d -> device (fenced) -> host tail; `step` spans body end to
        # body end so the sum check includes the inter-step stall
        cur = steps_done  # 0-based step index, matches prefetch labels
        if profile_dir and steps_done - start_step == profile_steps[0]:
            jax.profiler.start_trace(profile_dir)
            # Stamp the monotonic-clock marker so the device lanes of
            # this capture can be time-aligned with the host phase
            # events in the merged trace export (trace.py ingestion).
            from euler_tpu.trace import align_annotation

            with align_annotation():
                pass
            profiling = True
        if not device_prefetch:
            t_h2d = time.perf_counter()
            batch = shard_batch(batch, mesh)
            devprof.count_h2d(batch)
            if phase_profile:
                record_phase(
                    "h2d", (time.perf_counter() - t_h2d) * 1e6, step=cur
                )
        t_dev = time.perf_counter()
        state, last_loss, metric = step_fn(state, batch)
        if cur == start_step and devprof.devprof_enabled():
            # Relaunch-cost visibility: with the persistent compile
            # cache warm this line drops to ~0 ms on the second launch.
            cs = devprof.compile_summary()
            (log_fn or log.info)(
                f"first step dispatched: {cs['compile_events']} XLA "
                f"compile(s), {cs['compile_ms_total']:.0f} ms compile "
                "time"
            )
        if phase_profile:
            jax.block_until_ready(last_loss)
            t_host = time.perf_counter()
            record_phase("device", (t_host - t_dev) * 1e6, step=cur)
        window_metrics.append(metric)
        steps_done += 1
        if step_hook is not None:
            step_hook(steps_done)
        if sync_every and steps_done % sync_every == 0:
            jax.block_until_ready(last_loss)
        if profiling and steps_done - start_step >= profile_steps[1]:
            jax.block_until_ready(last_loss)
            jax.profiler.stop_trace()
            profiling = False
            (log_fn or log.info)(f"profiler trace written to {profile_dir}")
        if len(window_metrics) == log_every:
            flush()
        if ckpt and steps_done % checkpoint_every == 0:
            ckpt.save(steps_done, state)
        if phase_profile:
            now = time.perf_counter()
            record_phase("host", (now - t_host) * 1e6, step=cur)
            record_phase("step", (now - t_step) * 1e6, step=cur)
            t_step = now
    if window_metrics:  # final partial window
        flush()
    if profiling:
        jax.block_until_ready(last_loss)
        jax.profiler.stop_trace()
        (log_fn or log.info)(f"profiler trace written to {profile_dir}")
    if ckpt:
        # final save only when NEW steps ran: a re-launch that resumed at
        # num_steps (nothing left to train) must not re-save the step it
        # restored — orbax raises StepAlreadyExistsError on the collision
        if steps_done > start_step and steps_done % checkpoint_every != 0:
            ckpt.save(steps_done, state, force=True)
        ckpt.close()
    return state, history


def make_scan_train(model, optimizer, inner_steps: int, batch_size: int):
    """Fully-device training: ``inner_steps`` train steps per dispatch.

    Requires a device-sampling model (consts carry the adjacency slabs and
    the ``roots`` node sampler): roots are drawn on device, the fanout is
    sampled on device, and `lax.scan` chains the steps, so ONE host
    dispatch runs a whole chunk — host work and dispatch latency amortize
    to ~zero. This is the TPU-native training loop shape (the reference
    pays a host round trip per op per step through its AsyncOpKernels).

    Returns ``scan_fn(state, seed) -> (state, losses[inner_steps])`` to be
    jitted by the caller (donate state for buffer reuse). Note: roots are
    drawn from the replicated sampler identically on every device, so use
    this on a single chip or shard the scan externally; the per-step
    (host-rooted) path covers data-parallel meshes.
    """
    import jax.numpy as jnp

    from euler_tpu.graph import device as device_graph

    step = model.make_train_step(optimizer)

    def scan_fn(state, seed):
        base_key = jax.random.PRNGKey(seed)

        def body(state, i):
            key = jax.random.fold_in(base_key, i)
            roots = device_graph.sample_node(
                state["consts"]["roots"], key, batch_size
            )
            batch = {
                "roots": roots,
                "seed": jnp.full(
                    (batch_size,), seed * inner_steps + i, jnp.int32
                ),
            }
            state, loss, _ = step(state, batch)
            return state, loss

        return jax.lax.scan(body, state, jnp.arange(inner_steps))

    return scan_fn


def evaluate(
    model,
    graph,
    source_iter,
    state,
    mesh=None,
    log_fn=None,
):
    """Streaming evaluation over an iterator of root-node batches
    (reference run_loop.py:143-171).

    Multi-process: every process must iterate the SAME global batches
    (collectives run in lockstep); each samples only its contiguous
    1/process_count slice and shard_batch concatenates — the jitted
    metric is computed over the reassembled global batch, so the result
    is identical to single-process."""
    if mesh is None:
        mesh = make_mesh()
    rep = replicated_sharding(mesh)
    state = pad_tables_for_mesh(state, mesh)
    shardings = state_sharding(mesh, state)
    state = put_global(state, shardings)
    eval_fn = jax.jit(
        model.make_eval_step(),
        in_shardings=(shardings, batch_sharding(mesh)),
        out_shardings=(rep, rep),
    )
    name = model.metric_name
    acc = _metric_zero(name)
    losses = []
    n_proc = jax.process_count()
    for ids in source_iter:
        if n_proc > 1:
            ids = np.asarray(ids)
            if len(ids) % n_proc:
                raise ValueError(
                    f"eval batch {len(ids)} not divisible by "
                    f"{n_proc} processes"
                )
            per = len(ids) // n_proc
            ids = ids[jax.process_index() * per:][:per]
        batch = shard_batch(model.sample(graph, ids), mesh)
        loss, metric = eval_fn(state, batch)
        acc = _metric_accumulate(name, acc, metric)
        losses.append(float(loss))
    result = {name: _metric_value(name, acc), "loss": float(np.mean(losses))}
    (log_fn or log.info)(f"eval: {result}")
    return result


def save_embedding(
    model,
    graph,
    max_id: int,
    state,
    batch_size: int = 1024,
    mesh=None,
):
    """Export embeddings for ids 0..max_id as a [max_id+1, dim] array
    (reference run_loop.py:174-219 exports .npy + id file).

    Multi-process: each process samples its contiguous slice of every
    chunk; the output sharding is replicated there (XLA all-gathers over
    ICI) so every process returns the full matrix — a batch-sharded
    output would span non-addressable devices and be unfetchable."""
    if mesh is None:
        mesh = make_mesh()
    state = pad_tables_for_mesh(state, mesh)
    shardings = state_sharding(mesh, state)
    state = put_global(state, shardings)
    n_proc = jax.process_count()
    if batch_size % (n_proc or 1):
        raise ValueError(
            f"batch_size {batch_size} not divisible by {n_proc} processes"
        )
    embed_fn = jax.jit(
        model.make_embed_step(),
        in_shardings=(shardings, batch_sharding(mesh)),
        out_shardings=(
            replicated_sharding(mesh) if n_proc > 1
            else batch_sharding(mesh)
        ),
    )
    chunks = []
    ids = np.arange(max_id + 1, dtype=np.int64)
    pad = (-len(ids)) % batch_size
    padded = np.concatenate([ids, np.zeros(pad, dtype=np.int64)])
    per = batch_size // n_proc
    for i in range(0, len(padded), batch_size):
        chunk = padded[i : i + batch_size]
        if n_proc > 1:
            chunk = chunk[jax.process_index() * per:][:per]
        batch = shard_batch(model.sample_embed(graph, chunk), mesh)
        chunks.append(np.asarray(embed_fn(state, batch)))
    out = np.concatenate(chunks, axis=0)[: len(ids)]
    return out
