"""Training / evaluation / embedding-export CLI driver.

Reference equivalent: tf_euler/python/run_loop.py — same flag surface
(:36-92), same model names in the dispatch (:222-354), same three modes
(train :95-140, evaluate :143-171, save_embedding :174-219) — rebuilt for
the TPU stack:

* ``MonitoredTrainingSession`` -> euler_tpu.train.train (jitted step,
  orbax checkpoints in --model_dir, resume-from-latest).
* PS/worker ClusterSpec (run_loop.py:371-397) -> one process per TPU host
  with jax.distributed (--coordinator_addr/--num_processes/--process_id);
  within a process, data parallelism over the local device mesh.
* ``initialize_shared_graph`` (tf_euler base.py:64) -> --graph_mode=shared:
  every process serves its graph shard (GraphService) and connects a
  remote client over the flat-file --registry.

Usage:  python -m euler_tpu --data_dir ... --model graphsage_supervised ...
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Optional

import numpy as np

import euler_tpu
from euler_tpu import models
from euler_tpu.parallel import make_mesh
from euler_tpu import train as train_lib

log = logging.getLogger("euler_tpu")


# one truthy-string rule shared with Graph's config parsing — the CLI
# and config-string spellings must accept the same values
from euler_tpu.graph.graph import str2bool as _str2bool  # noqa: E402


def _int_list(v) -> list[int]:
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(x) for x in str(v).split(",") if x != ""]


def define_flags(parser: Optional[argparse.ArgumentParser] = None):
    """Flag surface of reference run_loop.py:36-92 (ZK flags replaced by
    the flat-file registry; PS flags by jax.distributed)."""
    p = parser or argparse.ArgumentParser(prog="euler_tpu")
    p.add_argument(
        "--mode",
        default="train",
        choices=["train", "evaluate", "save_embedding"],
    )
    # graph
    p.add_argument("--data_dir", default="")
    p.add_argument("--stream", type=_str2bool, default=False, help=(
        "with a remote --data_dir URL (gs://, s3://, ...), parse "
        "fetched partition bytes straight into the store instead of "
        "staging them to local disk first (zero local scratch; "
        "re-fetches each launch)"))
    p.add_argument("--graph_mode", default="local",
                   choices=["local", "remote", "shared"])
    p.add_argument("--registry", default="")
    p.add_argument("--rediscover_ms", type=int, default=None, help=(
        "mid-run registry re-LIST period for remote/shared clients "
        "(default: native 3000 ms with a registry; 0 disables) — how a "
        "shard restarted on a new address is re-learned mid-training"))
    p.add_argument("--backoff_ms", type=int, default=None, help=(
        "base of the jittered exponential retry backoff in the remote "
        "client (default: native 20 ms; 0 = hot retry)"))
    p.add_argument("--deadline_ms", type=int, default=None, help=(
        "overall wall-clock budget of ONE graph call spanning all its "
        "retries (default: timeout_ms * (retries+1))"))
    p.add_argument("--feature_cache_mb", type=int, default=None, help=(
        "byte budget (MB) of the remote client's dense-feature-row "
        "cache (remote/shared graph modes; native default 64, 0 "
        "disables). The graph is immutable after load, so cached rows "
        "never invalidate"))
    p.add_argument("--neighbor_cache_mb", type=int, default=None, help=(
        "byte budget (MB) of the remote client's neighbor-list cache "
        "(remote/shared modes; native default 16, 0 disables): hot "
        "nodes' adjacency slices are fetched once and sampled locally "
        "— distribution-identical to the shard engine (PERF.md "
        "'Locality')"))
    p.add_argument("--cache_policy", default=None,
                   choices=("freq", "fifo"), help=(
        "admission policy of both remote client caches (native default "
        "freq = TinyLFU-shaped over the heat sketch; fifo restores "
        "unconditional admission)"))
    p.add_argument("--placement", type=_str2bool, default=None, help=(
        "fetch the cluster's placement map at init and route ids "
        "through it, hash fallback when none exists (remote/shared "
        "modes; native default on; see convert.py --placement degree)"))
    p.add_argument("--strict", type=_str2bool, default=False, help=(
        "remote/shared graph modes: raise when a shard call fails after "
        "all transport retries instead of silently training on "
        "default-filled rows (failures are counted in rpc_errors either "
        "way)"))
    p.add_argument("--fault", default="", help=(
        "deterministic transport failpoint spec for chaos drills, e.g. "
        "'recv_frame:err@0.1,dial:delay@50' (remote/shared modes; see "
        "FAULTS.md)"))
    p.add_argument("--fault_seed", type=int, default=0, help=(
        "seed for --fault: the same seed replays the same injected-"
        "failure sequence at every failpoint"))
    p.add_argument("--service_host", default="", help=(
        "address this process's graph shard binds and advertises "
        "(shared mode). Empty = auto: the interface that routes to a "
        "tcp:// registry host, else 127.0.0.1"))
    p.add_argument("--service_workers", type=int, default=None, help=(
        "shared mode: handler pool size of this process's shard service "
        "(default: 2x cores). Connections beyond workers+pending get a "
        "BUSY reply clients fail over on (eg_admission.h)"))
    p.add_argument("--service_pending", type=int, default=None, help=(
        "shared mode: admitted-work headroom beyond the shard service's "
        "handler pool before new connections are answered BUSY "
        "(default 64)"))
    p.add_argument("--shards", default="",
                   help="comma list of host:port (remote mode)")
    p.add_argument("--train_node_type", type=int, default=0)
    p.add_argument("--all_node_type", type=int, default=-1)
    p.add_argument("--train_edge_type", default="0")
    p.add_argument("--all_edge_type", default="0,1,2")
    p.add_argument("--max_id", type=int, default=-1)
    p.add_argument("--feature_idx", type=int, default=-1)
    p.add_argument("--feature_dim", type=int, default=0)
    p.add_argument("--label_idx", type=int, default=-1)
    p.add_argument("--label_dim", type=int, default=0)
    p.add_argument("--num_classes", type=int, default=None)
    p.add_argument("--id_file", default="")
    # model
    p.add_argument("--model", default="graphsage_supervised")
    p.add_argument("--sigmoid_loss", type=_str2bool, default=True)
    p.add_argument("--xent_loss", type=_str2bool, default=True)
    p.add_argument("--dim", type=int, default=256)
    p.add_argument("--num_negs", type=int, default=5)
    p.add_argument("--order", type=int, default=1)
    p.add_argument("--walk_len", type=int, default=5)
    p.add_argument("--walk_p", type=float, default=1.0)
    p.add_argument("--walk_q", type=float, default=1.0)
    p.add_argument("--walk_trials", type=int, default=0, help=(
        "rejection-walk proposal budget per biased step on the device "
        "alias path (0 = library default); higher lowers the "
        "exhaustion-fallback rate at extreme p/q"))
    p.add_argument("--left_win_size", type=int, default=5)
    p.add_argument("--right_win_size", type=int, default=5)
    p.add_argument("--fanouts", default="10,10")
    p.add_argument(
        "--aggregator",
        default="mean",
        choices=["gcn", "mean", "meanpool", "maxpool", "attention"],
    )
    p.add_argument("--concat", type=_str2bool, default=True)
    p.add_argument(
        "--device_features", type=_str2bool, default=False,
        help="keep the dense feature/label tables HBM-resident and gather "
             "on device (graphsage/gcn/scalable/gat models); ships only "
             "node ids per step",
    )
    p.add_argument(
        "--device_sampling", type=_str2bool, default=False,
        help="also keep the ADJACENCY HBM-resident and sample fanouts/"
             "walks inside the jitted step (graphsage, "
             "graphsage_supervised, scalable_sage, gcn, scalable_gcn, "
             "gat, line, node2vec incl. biased p/q walks, lshne); the "
             "host ships only root ids per step. For feature models "
             "this implies --device_features; the shallow id-embedding "
             "models run it standalone",
    )
    p.add_argument(
        "--feature_dtype", default="",
        help="storage dtype for the device-resident dense feature tables "
             "(e.g. bfloat16: half the HBM footprint and gather bytes; "
             "rows cast back to float32 at the gather). Empty = float32",
    )
    p.add_argument("--use_residual", type=_str2bool, default=False)
    p.add_argument("--store_learning_rate", type=float, default=0.001)
    p.add_argument("--store_init_maxval", type=float, default=0.05)
    p.add_argument("--head_num", type=int, default=1)
    p.add_argument("--embedding_file", default="",
                   help="embedding.npy for model=saved_embedding "
                        "(default: <model_dir>/embedding.npy)")
    # training
    p.add_argument("--model_dir", default="ckpt")
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--optimizer", default="adam",
                   choices=sorted(train_lib.OPTIMIZERS))
    p.add_argument("--learning_rate", type=float, default=0.01)
    p.add_argument("--num_epochs", type=int, default=20)
    p.add_argument("--log_steps", type=int, default=20)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--num_devices", type=int, default=None,
                   help="devices in the data-parallel mesh (default: all)")
    p.add_argument("--model_parallel", type=int, default=1,
                   help="width of the 'model' mesh axis; >1 row-shards the "
                        "device-resident tables (consts, Scalable stores) "
                        "across it")
    p.add_argument("--max_degree", type=int, default=None, help=(
        "cap the device-sampling slab width (heaviest neighbors kept, "
        "renormalized) — heavy-tail graphs only; changes hub "
        "distributions, see PERF.md's truncation study"))
    p.add_argument("--alias_sampling", type=_str2bool, default=False,
                   help=(
                       "device-sample through exact flat-CSR alias "
                       "tables (O(edges) memory, no truncation) instead "
                       "of padded slabs — the recommended form for "
                       "power-law graphs like real Reddit"))
    p.add_argument("--metrics_every", type=int, default=0, help=(
        "append a telemetry snapshot line (counters + per-op client "
        "p50/p99 latency) to --metrics_file every N training steps; "
        "0 disables (OBSERVABILITY.md)"))
    p.add_argument("--metrics_file", default="", help=(
        "JSONL path for --metrics_every snapshots (default: "
        "<model_dir>/metrics.jsonl)"))
    p.add_argument("--telemetry", type=_str2bool, default=True, help=(
        "process-global latency-histogram/slow-span recording "
        "(eg_telemetry); 0 is the kill-switch — counters, span timers "
        "AND the step-phase profiler all honor it"))
    p.add_argument("--postmortem_dir", default="", help=(
        "arm the blackbox postmortem path (eg_blackbox): fatal signals "
        "(SIGSEGV/SIGBUS/SIGABRT/SIGFPE) AND unhandled Python "
        "exceptions write <dir>/postmortem.<pid>[.exception].json — "
        "flight-recorder rings, counters, resource history, backtrace "
        "— before the process dies; collect a dead cluster's dumps "
        "with scripts/postmortem.py (OBSERVABILITY.md 'Postmortems')"))
    p.add_argument("--blackbox", type=_str2bool, default=True, help=(
        "flight-recorder kill-switch: 0 stops ring recording AND "
        "suppresses postmortem dumps (counters/telemetry unaffected)"))
    p.add_argument("--trace_file", default="", help=(
        "write a merged Chrome-trace/Perfetto JSON here when training "
        "ends: per-step phase slices (input_stall/sample/h2d/device/"
        "host) + this client's slow-span journal + every live shard's "
        "scraped journal, flow-linked by wire-v3 trace ids — open in "
        "ui.perfetto.dev (OBSERVABILITY.md 'Step phases')"))
    p.add_argument("--prefetch_depth", type=int, default=2)
    p.add_argument("--prefetch_threads", type=int, default=2)
    p.add_argument("--sampler_depth", type=int, default=2, help=(
        "remote graphs: number of training steps whose sampling is kept "
        "in flight through the engine's async completion queue "
        "(eg_remote_sample_async) — step k+1..k+N fan-outs overlap step "
        "k's H2D+device compute with no dedicated sampler threads. 0 "
        "falls back to the thread-pool prefetch; ignored for local "
        "graphs (PERF.md 'Pipelined sampling')"))
    p.add_argument("--profile_dir", default="")
    p.add_argument("--devprof", type=_str2bool, default=True, help=(
        "device-plane observability kill-switch (eg_devprof): XLA "
        "compile/recompile counters + latency histogram, post-warmup "
        "recompile journaling with the offending shape diff, device-"
        "memory gauges in the blackbox resource ring, h2d/d2h byte "
        "counters; 0 disarms all of it (OBSERVABILITY.md 'Device "
        "plane')"))
    p.add_argument("--compile_cache", type=_str2bool, default=None, help=(
        "persistent XLA compilation cache "
        "(jax_compilation_cache_dir) so relaunches skip the 20-40 s "
        "TPU program compiles. Unset = auto: on for TPU/GPU backends, "
        "off on CPU. Cache dir: $JAX_COMPILATION_CACHE_DIR, else "
        "<model_dir>/jax_cache"))
    # serving (euler_tpu/serve.py; DEPLOY.md "Serving runbook")
    p.add_argument("--serve_after", type=_str2bool, default=False, help=(
        "train mode: after training saves its final checkpoint, "
        "immediately serve it — start the embedding inference server "
        "(euler_tpu.serve) on --serve_port and run until SIGTERM/"
        "SIGINT, draining on the way out. Serves with the TRAINING "
        "sampling config; `python -m euler_tpu.serve` serves an "
        "existing checkpoint with the inference config instead"))
    from euler_tpu.serving import add_serve_flags

    add_serve_flags(p)
    # multi-process (multi-host TPU) — replaces PS/worker flags
    p.add_argument("--coordinator_addr", default="")
    p.add_argument("--num_processes", type=int, default=1)
    p.add_argument("--process_id", type=int, default=0)
    return p


def check_serve_flags(args) -> None:
    """Reject serve-only flags on a run that will never serve — they
    would silently do nothing (the --stream/--fault loudness rule)."""
    from euler_tpu.serving import serve_flag_overrides

    if args.serve_after and args.mode != "train":
        raise ValueError(
            "--serve_after means train-then-serve and needs "
            f"--mode=train (got --mode={args.mode}); to serve an "
            "existing checkpoint use `python -m euler_tpu.serve`"
        )
    overrides = serve_flag_overrides(args)
    if overrides and not args.serve_after:
        raise ValueError(
            f"serve-only flags {', '.join(overrides)} do nothing in "
            f"--mode={args.mode} without --serve_after; add "
            "--serve_after=1 (train, then serve the checkpoint) or use "
            "`python -m euler_tpu.serve` against a saved --model_dir"
        )


def build_graph(args):
    """Local / remote / shared graph init (reference tf_euler base.py:35-91:
    initialize_graph / initialize_shared_graph)."""
    services = []
    if args.stream and args.graph_mode != "local":
        # the shard service stages deliberately (a long-lived serving
        # host wants the warm cache); dropping the flag silently would
        # leave a scratch-poor operator staging anyway and hitting
        # ENOSPC with no hint why
        raise ValueError(
            "--stream is only supported with --graph_mode=local "
            "(shared/remote services stage their shard to the local "
            "cache; see DEPLOY.md 'Remote data')"
        )
    if args.fault and args.graph_mode == "local":
        # same loudness rule as --stream: the failpoints live in the TCP
        # transport, so on a local graph the flag would silently do nothing
        raise ValueError(
            "--fault needs --graph_mode=remote or shared (failpoints sit "
            "in the transport; see FAULTS.md)"
        )
    if args.graph_mode == "local" and (
        args.feature_cache_mb is not None or args.strict
        or args.neighbor_cache_mb is not None
        or args.cache_policy is not None or args.placement is not None
    ):
        raise ValueError(
            "--feature_cache_mb/--neighbor_cache_mb/--cache_policy/"
            "--placement/--strict need --graph_mode=remote or shared "
            "(they configure the remote client's request path; a local "
            "graph reads its own memory)"
        )
    if args.graph_mode == "local":
        graph = euler_tpu.Graph(
            directory=args.data_dir, stream=args.stream
        )
    elif args.graph_mode == "remote":
        graph = euler_tpu.Graph(
            mode="remote",
            registry=args.registry or None,
            shards=args.shards.split(",") if args.shards else None,
            rediscover_ms=args.rediscover_ms,
            backoff_ms=args.backoff_ms,
            deadline_ms=args.deadline_ms,
            feature_cache_mb=args.feature_cache_mb,
            neighbor_cache_mb=args.neighbor_cache_mb,
            cache_policy=args.cache_policy,
            placement=args.placement,
            strict=args.strict or None,
            fault=args.fault or None,
            fault_seed=args.fault_seed if args.fault else None,
        )
    else:  # shared: serve this process's shard, then connect remote
        if not args.registry:
            raise ValueError("--graph_mode=shared needs --registry")
        import time

        from euler_tpu.graph import registry as registry_mod

        tcp_registry = args.registry.startswith("tcp://")
        if tcp_registry:
            # TCP coordination plane (no shared filesystem needed):
            # process 0 hosts the registry at the URL's port; every other
            # process waits for it to answer before registering its shard.
            host, port = registry_mod.parse_tcp_url(args.registry)
            if args.process_id == 0:
                services.append(registry_mod.RegistryServer(port=port))
            else:
                deadline = time.time() + 120.0
                while True:
                    try:
                        registry_mod.query(args.registry)
                        break
                    except ConnectionError:
                        if time.time() > deadline:
                            raise TimeoutError(
                                f"registry {args.registry} unreachable "
                                "after 120s (does process 0 run on "
                                f"{host}?)"
                            )
                        time.sleep(0.2)
        # The shard must advertise an address other hosts can dial: with a
        # remote tcp:// registry, default to the local interface that
        # routes toward the registry host (the reference's GetIP analog,
        # euler/common/net_util.cc:32); loopback only for single-host runs.
        service_host = args.service_host
        if not service_host:
            service_host = "127.0.0.1"
            if tcp_registry and host not in ("127.0.0.1", "localhost"):
                import socket as _socket

                probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
                try:
                    probe.connect((host, 9))  # no traffic; routing only
                    service_host = probe.getsockname()[0]
                finally:
                    probe.close()
        services.append(
            euler_tpu.GraphService(
                args.data_dir,
                shard_idx=args.process_id,
                shard_num=args.num_processes,
                host=service_host,
                registry=args.registry,
                workers=args.service_workers,
                pending=args.service_pending,
            )
        )
        if tcp_registry:
            # Entries are heartbeat-kept with a TTL, so LIST only ever
            # returns live shards — no extra probing needed (stale
            # entries from a killed run expire on their own).
            def live_shards() -> set:
                try:
                    return set(registry_mod.query(args.registry))
                except ConnectionError:
                    return set()

            stale_hint = ""
        else:
            # Flat-file registry: wait for every shard to register AND
            # accept connections before connecting. A liveness probe (TCP
            # connect) filters out stale entries left by a SIGKILLed prior
            # run with the same --registry — those would otherwise satisfy
            # a pure count check and produce a confusing connect failure
            # later.
            import socket

            # Dead verdicts are cached per filename with an expiry:
            # re-probing dead hosts every 0.1s poll would burn the
            # deadline on serial 1s connect timeouts, but a permanent
            # verdict would blacklist a shard whose single probe hit a
            # transient failure (dropped SYN, probe racing the listen()
            # call). Expired entries get re-probed, so a not-yet-listening
            # live shard is only deferred, never lost.
            dead: dict[str, float] = {}  # entry -> verdict expiry time
            DEAD_TTL = 5.0

            def _alive(entry: str) -> bool:
                # registry filename: "<shard>#<host>_<port>" (eg_service.cc)
                if dead.get(entry, 0.0) > time.time():
                    return False
                try:
                    host, port = entry.split("#", 1)[1].rsplit("_", 1)
                    with socket.create_connection((host, int(port)), 1.0):
                        dead.pop(entry, None)
                        return True
                except (OSError, ValueError):
                    dead[entry] = time.time() + DEAD_TTL
                    return False

            def live_shards() -> set:
                return {
                    f.split("#", 1)[0]
                    for f in os.listdir(args.registry)
                    if "#" in f and not f.endswith(".tmp") and _alive(f)
                }

            stale_hint = ("; stale entries from a killed run are ignored "
                          "— clear the registry dir")

        deadline = time.time() + 120.0
        while True:
            live = live_shards()
            if len(live) >= args.num_processes:
                break
            if time.time() > deadline:
                raise TimeoutError(
                    f"only live shards {sorted(live)} in "
                    f"{args.registry} after 120s "
                    f"(need {args.num_processes}{stale_hint})"
                )
            time.sleep(0.1)
        graph = euler_tpu.Graph(
            mode="remote", registry=args.registry,
            rediscover_ms=args.rediscover_ms,
            backoff_ms=args.backoff_ms,
            deadline_ms=args.deadline_ms,
            feature_cache_mb=args.feature_cache_mb,
            neighbor_cache_mb=args.neighbor_cache_mb,
            cache_policy=args.cache_policy,
            placement=args.placement,
            strict=args.strict or None,
            fault=args.fault or None,
            fault_seed=args.fault_seed if args.fault else None,
        )
    return graph, services


class SavedEmbedding(models.Model):
    """Frozen saved-embedding encoder + trainable classifier
    (reference run_loop.py:340-351)."""

    metric_name = "f1"

    def __init__(self, embedding: np.ndarray, label_idx, label_dim,
                 num_classes=None, sigmoid_loss=True):
        import flax.linen as nn
        import jax

        super().__init__()
        self.embedding = embedding.astype(np.float32)
        self.label_idx = label_idx
        self.label_dim = label_dim
        outer = self

        class _Module(nn.Module):
            @nn.compact
            def __call__(self, batch):
                logits = nn.Dense(num_classes or label_dim)(
                    jax.lax.stop_gradient(batch["emb"])
                )
                loss, preds = models.base.supervised_decoder(
                    logits, batch["labels"], sigmoid_loss
                )
                from euler_tpu.nn import metrics as m

                return models.ModelOutput(
                    embedding=batch["emb"],
                    loss=loss,
                    metric_name="f1",
                    metric=m.f1_counts(batch["labels"], preds),
                )

            def embed(self, batch):
                return batch["emb"]

        self.module = _Module()

    def sample(self, graph, inputs):
        ids = np.asarray(inputs, dtype=np.int64).reshape(-1)
        safe = np.clip(ids, 0, len(self.embedding) - 1)
        return {
            "emb": self.embedding[safe],
            "labels": graph.get_dense_feature(
                ids, [self.label_idx], [self.label_dim]
            ),
        }


def build_model(args, graph):
    """Model dispatch with the reference's model names
    (reference run_loop.py:222-354)."""
    fanouts = _int_list(args.fanouts)
    train_edge = _int_list(args.train_edge_type)
    all_edge = _int_list(args.all_edge_type)
    metapath = [list(train_edge if args.mode == "train" else all_edge)] * max(
        len(fanouts), 1
    )
    name = args.model
    common_sup = dict(
        label_idx=args.label_idx,
        label_dim=args.label_dim,
        num_classes=args.num_classes,
        sigmoid_loss=args.sigmoid_loss,
        feature_idx=args.feature_idx,
        feature_dim=args.feature_dim,
    )
    if name == "line":
        return models.LINE(
            node_type=args.all_node_type,
            edge_type=all_edge,
            max_id=args.max_id,
            dim=args.dim,
            xent_loss=args.xent_loss,
            num_negs=args.num_negs,
            order=args.order,
            device_sampling=args.device_sampling,
        )
    if name in ("randomwalk", "deepwalk", "node2vec"):
        return models.Node2Vec(
            node_type=args.all_node_type,
            edge_type=all_edge,
            max_id=args.max_id,
            dim=args.dim,
            xent_loss=args.xent_loss,
            num_negs=args.num_negs,
            walk_len=args.walk_len,
            walk_p=args.walk_p,
            walk_q=args.walk_q,
            left_win_size=args.left_win_size,
            right_win_size=args.right_win_size,
            device_sampling=args.device_sampling,
            walk_trials=args.walk_trials,
        )
    if name in ("gcn", "gcn_supervised"):
        # Full-neighbor GCN needs per-hop dense caps for static shapes.
        cap = max(fanouts) if fanouts else 10
        return models.SupervisedGCN(
            metapath=metapath,
            dim=args.dim,
            max_nodes_per_hop=[args.batch_size * (cap**h) for h in
                               range(1, len(metapath) + 1)],
            max_edges_per_hop=[args.batch_size * (cap ** (h + 1)) for h in
                               range(len(metapath))],
            aggregator=args.aggregator,
            max_id=args.max_id,
            use_residual=args.use_residual,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
            **common_sup,
        )
    if name == "scalable_gcn":
        return models.ScalableGCN(
            edge_type=metapath[0],
            num_layers=len(fanouts),
            dim=args.dim,
            max_id=args.max_id,
            # per-ROOT cap on unique 1-hop neighbors (the model multiplies
            # by the batch size at sample time)
            max_neighbors=fanouts[0],
            aggregator=args.aggregator,
            use_residual=args.use_residual,
            store_learning_rate=args.store_learning_rate,
            store_init_maxval=args.store_init_maxval,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
            train_node_type=args.train_node_type,
            **common_sup,
        )
    if name == "graphsage":
        return models.GraphSage(
            node_type=args.train_node_type,
            edge_type=train_edge,
            max_id=args.max_id,
            xent_loss=args.xent_loss,
            num_negs=args.num_negs,
            metapath=metapath,
            fanouts=fanouts,
            dim=args.dim,
            aggregator=args.aggregator,
            concat=args.concat,
            feature_idx=args.feature_idx,
            feature_dim=args.feature_dim,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
        )
    if name == "graphsage_supervised":
        return models.SupervisedGraphSage(
            metapath=metapath,
            fanouts=fanouts,
            dim=args.dim,
            aggregator=args.aggregator,
            concat=args.concat,
            max_id=args.max_id,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
            train_node_type=args.train_node_type,
            **common_sup,
        )
    if name == "scalable_sage":
        return models.ScalableSage(
            edge_type=metapath[0],
            fanout=fanouts[0],
            num_layers=len(fanouts),
            dim=args.dim,
            aggregator=args.aggregator,
            concat=args.concat,
            max_id=args.max_id,
            store_learning_rate=args.store_learning_rate,
            store_init_maxval=args.store_init_maxval,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
            train_node_type=args.train_node_type,
            **common_sup,
        )
    if name == "gat":
        return models.GAT(
            label_idx=args.label_idx,
            label_dim=args.label_dim,
            num_classes=args.num_classes,
            sigmoid_loss=args.sigmoid_loss,
            feature_idx=args.feature_idx,
            feature_dim=args.feature_dim,
            max_id=args.max_id,
            head_num=args.head_num,
            hidden_dim=args.dim,
            nb_num=5,
            device_features=args.device_features or args.device_sampling,
            feature_dtype=args.feature_dtype or None,
            device_sampling=args.device_sampling,
            train_node_type=args.train_node_type,
        )
    if name == "lshne":
        return models.LsHNE(
            node_type=-1,
            # one view, two 3-step homogeneous metapaths (per-step
            # edge-type LISTS — a flat [0,0,0] would be rejected by the
            # walk's metapath parser)
            path_patterns=[[[[0], [0], [0]], [[0], [0], [0]]]],
            max_id=args.max_id,
            dim=128,
            sparse_feature_dims=[args.max_id + 2],
            feature_ids=[args.feature_idx if args.feature_idx >= 0 else 0],
            device_sampling=args.device_sampling,
        )
    if name == "saved_embedding":
        emb = np.load(
            args.embedding_file
            or os.path.join(args.model_dir, "embedding.npy")
        )
        return SavedEmbedding(
            emb,
            args.label_idx,
            args.label_dim,
            args.num_classes,
            args.sigmoid_loss,
        )
    raise ValueError(f"unsupported model {name!r}")


def _num_steps(args) -> int:
    per_epoch = max((args.max_id + 1) // args.batch_size, 1)
    return per_epoch * args.num_epochs


def run_train(model, graph, args, mesh):
    import jax

    batch = args.batch_size * getattr(model, "batch_size_ratio", 1)
    # jax.distributed data parallelism: --batch_size stays the GLOBAL
    # batch (flag parity with the reference's per-cluster semantics);
    # each process samples its share and the shards concatenate onto the
    # global mesh in train_lib (shard_batch).
    n_proc = jax.process_count()
    if batch % n_proc:
        raise ValueError(
            f"--batch_size*ratio {batch} not divisible by "
            f"{n_proc} processes"
        )
    batch //= n_proc

    def source_fn(step):
        return np.asarray(graph.sample_node(batch, args.train_node_type))

    step_hook = None
    if args.metrics_every > 0:
        from euler_tpu.telemetry import append_metrics_line

        metrics_path = args.metrics_file or os.path.join(
            args.model_dir or ".", "metrics.jsonl"
        )
        os.makedirs(os.path.dirname(metrics_path) or ".", exist_ok=True)

        def step_hook(step, _path=metrics_path):
            if step % args.metrics_every == 0:
                append_metrics_line(_path, step)

    recorder = None
    if args.trace_file:
        from euler_tpu.trace import TraceRecorder

        recorder = TraceRecorder().start()
    try:
        state, history = train_lib.train(
            model,
            graph,
            source_fn,
            num_steps=_num_steps(args),
            optimizer=args.optimizer,
            learning_rate=args.learning_rate,
            mesh=mesh,
            log_every=args.log_steps,
            seed=args.seed,
            prefetch_depth=args.prefetch_depth,
            prefetch_threads=args.prefetch_threads,
            sampler_depth=args.sampler_depth,
            checkpoint_dir=args.model_dir or None,
            profile_dir=args.profile_dir or None,
            step_hook=step_hook,
        )
    finally:
        if recorder is not None:
            # export even on an interrupted run — the trace of a run
            # that died mid-step is exactly the one worth reading
            recorder.stop()
            from euler_tpu.trace import write_trace

            os.makedirs(
                os.path.dirname(args.trace_file) or ".", exist_ok=True
            )
            # --profile_dir device lanes merge in, time-aligned via the
            # eg_align marker train() stamped into the capture
            trace = write_trace(args.trace_file, recorder, graph,
                                profile_dir=args.profile_dir or None)
            log.info(
                "trace: %d events -> %s (open in ui.perfetto.dev)",
                len(trace["traceEvents"]), args.trace_file,
            )
    return state, history


def _restore_state(model, graph, args, mesh):
    import jax

    from euler_tpu.checkpoint import Checkpointer

    opt = train_lib.get_optimizer(args.optimizer, args.learning_rate)
    example = np.asarray(
        graph.sample_node(args.batch_size, args.train_node_type)
    )
    state = model.init_state(jax.random.PRNGKey(args.seed), graph, example,
                             opt)
    # Model-parallel training saved tables row-padded to the model axis;
    # the restore template must match those shapes (same --model_parallel
    # as training).
    from euler_tpu.parallel import pad_tables_for_mesh

    state = pad_tables_for_mesh(state, mesh)
    ckpt = Checkpointer(args.model_dir)
    try:
        if ckpt.latest_step() is not None:
            state = ckpt.restore(state)
        else:
            log.warning("no checkpoint in %s; using fresh params",
                        args.model_dir)
    finally:
        ckpt.close()
    return state


def run_evaluate(model, graph, args, mesh):
    state = _restore_state(model, graph, args, mesh)
    if args.id_file:
        ids = np.concatenate([
            np.loadtxt(f, dtype=np.int64).reshape(-1)
            for f in args.id_file.split(",")
        ])
    else:
        ids = np.arange(args.max_id + 1, dtype=np.int64)
    batch = args.batch_size
    # Wrap-pad to a full batch multiple so every jitted shape is static
    # (the reference streams exact ragged batches; with |ids| >> batch the
    # duplicated rows are a negligible fraction of the metric counts).
    # np.resize cycles ids, so this works even when len(ids) < pad.
    pad = (-len(ids)) % batch
    padded = np.resize(ids, len(ids) + pad) if pad else ids

    def batches():
        for i in range(0, len(padded), batch):
            yield padded[i : i + batch]

    result = train_lib.evaluate(model, graph, batches(), state, mesh=mesh)
    import jax

    if args.model_dir and jax.process_index() == 0:
        # persist the metrics next to the checkpoint so callers (dress
        # rehearsals, sweep scripts) can gate on them instead of
        # scraping logs
        import json

        os.makedirs(args.model_dir, exist_ok=True)
        with open(os.path.join(args.model_dir, "eval.json"), "w") as f:
            json.dump(
                {**result, "id_file": args.id_file, "model": args.model},
                f,
            )
    return result


def run_save_embedding(model, graph, args, mesh):
    state = _restore_state(model, graph, args, mesh)
    emb = train_lib.save_embedding(
        model, graph, args.max_id, state, batch_size=args.batch_size,
        mesh=mesh,
    )
    os.makedirs(args.model_dir, exist_ok=True)
    out = os.path.join(args.model_dir, "embedding.npy")
    np.save(out, emb)
    ids_out = os.path.join(args.model_dir, "id.txt")
    np.savetxt(ids_out, np.arange(args.max_id + 1, dtype=np.int64), fmt="%d")
    log.info("saved %s %s and %s", out, emb.shape, ids_out)
    return out


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    # Orbax/absl emit per-save INFO spam once a root handler exists.
    logging.getLogger("absl").setLevel(logging.WARNING)
    from euler_tpu.parallel import (
        honor_jax_platforms_env,
        probe_backend_or_die,
    )

    honor_jax_platforms_env()
    args = define_flags().parse_args(argv)
    check_serve_flags(args)
    # after parse_args (so --help / usage errors stay instant) and
    # before any jax use: a wedged TPU relay would otherwise hang
    # backend init forever at 0% CPU with no traceback — fail fast with
    # the recovery options
    probe_backend_or_die()
    if args.coordinator_addr:
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator_addr,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    if not args.telemetry:
        # kill-switch BEFORE any graph/service exists so not even the
        # discovery calls record histograms
        from euler_tpu.telemetry import set_telemetry

        set_telemetry(False)
    from euler_tpu import blackbox as blackbox_mod

    if not args.blackbox:
        blackbox_mod.set_blackbox(False)
    # device plane + compile cache: before any jit so the listener sees
    # every compile and the cache covers the first program
    from euler_tpu import devprof as devprof_mod

    devprof_mod.setup(enabled=args.devprof,
                      compile_cache=args.compile_cache,
                      model_dir=args.model_dir,
                      sample_ms=1000)
    if args.postmortem_dir:
        # arm BEFORE any graph/service exists, so even a crash during
        # load or discovery leaves a dump
        blackbox_mod.install(args.postmortem_dir,
                             shard=args.process_id)

    def _exception_postmortem():
        # crash-dump-on-unhandled-exception: the Python twin of the
        # fatal-signal path — same dump format (signal 0 =
        # "exception"), so an incident reads identically whether the
        # process died in native or Python code. The exception itself
        # still propagates (the traceback is the Python half of the
        # postmortem).
        if not args.postmortem_dir:
            return
        path = os.path.join(
            args.postmortem_dir,
            f"postmortem.{os.getpid()}.exception.json",
        )
        try:
            blackbox_mod.write_postmortem(path)
            log.error("unhandled exception; postmortem: %s", path)
        except Exception:
            log.exception("postmortem dump failed")

    try:
        graph, services = build_graph(args)
    except Exception:
        _exception_postmortem()
        raise
    try:
        mesh = make_mesh(args.num_devices, model_parallel=args.model_parallel)
        # multi-chip device sampling: keep the fused Pallas draw by
        # running it per-shard inside shard_map (plain pjit cannot
        # partition pallas_call) — no-op on non-TPU backends. Set OR
        # cleared every run: a stale mesh from a prior main() in the
        # same process must never route draws over the wrong mesh.
        from euler_tpu.graph import device as device_graph
        from euler_tpu.graph import pallas_sampling

        device_graph.set_kernel_mesh(
            mesh
            if (
                getattr(args, "device_sampling", False)
                and mesh.size > 1
                and pallas_sampling.sharded_available()
            )
            else None,
            "data",
        )
        model = build_model(args, graph)
        if (args.max_degree is not None or args.alias_sampling) and hasattr(
            model, "set_sampling_options"
        ):
            model.set_sampling_options(
                max_degree=args.max_degree, alias=args.alias_sampling
            )
        if args.mode == "train":
            run_train(model, graph, args, mesh)
            if args.serve_after:
                # train -> save -> immediately serve: the freshest
                # checkpoint goes live without a second process or a
                # re-parse of the data dir. Serves with the TRAINING
                # sampling config (train_edge metapaths) — documented
                # trade-off; `python -m euler_tpu.serve` is the
                # inference-config path. Blocks until SIGTERM/SIGINT,
                # then drains.
                from euler_tpu import serve as serve_mod

                serve_mod.run_serve(model, graph, args, mesh)
        elif args.mode == "evaluate":
            run_evaluate(model, graph, args, mesh)
        else:
            run_save_embedding(model, graph, args, mesh)
    except Exception:
        _exception_postmortem()
        raise
    finally:
        from euler_tpu.graph import device as device_graph

        device_graph.set_kernel_mesh(None)
        # transport + server survivability ledger (eg_counters_* ABI):
        # in shared mode this process also served its shard, so the
        # snapshot covers both sides — busy_rejects/handler_timeouts/
        # deadline_rejects next to the client's retries/failovers
        ledger = {k: v for k, v in euler_tpu.counters().items() if v}
        if ledger:
            log.info("transport/server counters: %s", ledger)
        for s in services:
            # GraphService: finish in-flight shard requests before the
            # teardown (the registry server has no drain phase)
            if hasattr(s, "drain"):
                s.drain()
            s.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
