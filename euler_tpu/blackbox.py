"""Python surface over the native blackbox (graph/_native/eg_blackbox).

The native layer keeps an always-on lock-free flight recorder (one ring
of fixed-slot events per thread, fed from the transport, admission,
dispatcher, and step-phase hook points), samples process resource
gauges (RSS, open fds, live threads, client cache bytes) into a
60-entry history ring, and — once :func:`install` has armed it — writes
an async-signal-safe postmortem dump on SIGSEGV/SIGBUS/SIGABRT/SIGFPE.
This module is the operator half:

    euler_tpu.postmortem_read(path)     parse dump file(s) back to dicts
    euler_tpu.blackbox.install(dir)     arm the fatal-signal dump path
    euler_tpu.blackbox.blackbox_json()  live rings + resource history
    euler_tpu.blackbox.history(g, s)    a live shard's resource ring
    euler_tpu.set_blackbox(False)       process-global kill-switch

plus :func:`write_postmortem` (the manual dump run_loop uses on an
unhandled exception) and :func:`record` for app-level events.

Postmortem file format (OBSERVABILITY.md "Postmortems"): line 1 is one
JSON document — signal, counters ledger, admission gauges, resource
history, raw rings, backtrace addresses; any following lines are the
backtrace_symbols_fd frames (outside the JSON because symbolization
cannot run inside a signal handler). :func:`postmortem_read` returns
the parsed document with those frames under ``backtrace_symbols``.
"""

from __future__ import annotations

import json
import os

from euler_tpu.graph.native import lib
from euler_tpu.telemetry import _json_abi

# Flight-recorder hook points — MUST match eg_blackbox.h BlackboxPoint.
POINTS = ("client_call", "server_recv", "server_reply", "dispatch",
          "phase", "app")


def install(postmortem_dir: str | None = None, shard: int = -1,
            sample_ms: int = 0) -> None:
    """Arm the postmortem path: install the fatal-signal handlers,
    start the resource sampler, and (when ``postmortem_dir`` is given)
    point the dump at ``<dir>/postmortem.<pid>.json``. Re-invocable —
    later calls update the directory/shard label. Raises RuntimeError
    when the directory is not writable (a typo'd dir must fail at init,
    not stay silent until the one crash that needed it)."""
    if postmortem_dir:
        try:
            os.makedirs(postmortem_dir, exist_ok=True)
        except OSError:
            pass  # the native writability probe reports it uniformly
    rc = lib().eg_blackbox_init(
        (postmortem_dir or "").encode(), int(shard), int(sample_ms)
    )
    if rc != 0:
        raise RuntimeError(lib().eg_last_error().decode())


def blackbox_enabled() -> bool:
    return lib().eg_blackbox_enabled() == 1


def set_blackbox(on: bool) -> None:
    """Process-global flight-recorder kill-switch (`blackbox=` config
    key): False stops ring recording everywhere AND suppresses the
    fatal-signal dump (the handler still re-raises, so the process
    dies with the same status either way)."""
    lib().eg_blackbox_set_enabled(1 if on else 0)


def blackbox_reset() -> None:
    """Zero the flight-recorder rings + drop ledger (the enabled flag,
    installed handlers and resource history survive)."""
    lib().eg_blackbox_reset()


def record(point: str = "app", op: int = 0, shard: int = -1,
           trace: int = 0, value: int = 0, outcome: int = 0) -> None:
    """One app-level flight-recorder event (same rings the native
    transport hooks write). Raises ValueError on an unknown point."""
    lib().eg_blackbox_record(
        POINTS.index(point), int(op), int(shard), int(trace), int(value),
        int(outcome),
    )


def blackbox_json() -> dict:
    """Live dump of this process's flight-recorder rings (oldest-first
    per ring) and resource gauges — what a postmortem would freeze,
    readable while everything is still fine."""
    return _json_abi(lambda buf, cap: lib().eg_blackbox_json(buf, cap))


def history(graph=None, shard: int | None = None) -> dict:
    """Resource-gauge history: this process's by default, a live
    shard's over the kHistory wire opcode when (graph, shard) name one.
    Returns {"shard": n, "resource": {latest}, "history": [samples]} —
    the live twin of a postmortem's frozen ``resource_history``."""
    if graph is None:
        return _json_abi(
            lambda buf, cap: lib().eg_blackbox_history(buf, cap)
        )
    if getattr(graph, "mode", None) != "remote":
        raise ValueError("history(graph=...) needs a mode='remote' graph "
                         "(a local graph IS this process)")
    h = graph._h
    return _json_abi(
        lambda buf, cap: lib().eg_remote_history(h, shard or 0, buf, cap)
    )


def write_postmortem(path: str) -> str:
    """Write a postmortem dump NOW (same format as the fatal-signal
    dump, signal 0 = "exception") — the manual path behind run_loop's
    crash-dump-on-unhandled-exception. Returns the path."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rc = lib().eg_blackbox_dump(path.encode())
    if rc != 0:
        raise RuntimeError(lib().eg_last_error().decode())
    return path


def postmortem_read(path: str) -> dict | list:
    """Parse postmortem dump(s).

    ``path`` may be one dump file (returns its dict) or a directory
    (returns every ``postmortem.*.json`` in it, oldest first — the
    cluster-collection form scripts/postmortem.py builds on). The
    backtrace_symbols_fd frames after the JSON line come back under
    ``backtrace_symbols``; ``trace`` fields in ring events are decimal
    strings (u64-exact), left as strings for the caller to int()."""
    if os.path.isdir(path):
        dumps = []
        for name in sorted(
            (f for f in os.listdir(path)
             if f.startswith("postmortem.") and f.endswith(".json")),
            key=lambda f: os.path.getmtime(os.path.join(path, f)),
        ):
            dumps.append(postmortem_read(os.path.join(path, name)))
        return dumps
    with open(path) as f:
        first = f.readline()
        rest = f.read()
    doc = json.loads(first)
    doc["path"] = path
    doc["backtrace_symbols"] = [
        line for line in rest.splitlines() if line.strip()
    ]
    return doc
