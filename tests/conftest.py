"""Test harness config: force JAX onto a virtual 8-device CPU mesh so every
sharding test runs without TPU hardware (the driver separately dry-runs the
multi-chip path)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment preloads jax (axon sitecustomize) with JAX_PLATFORMS=axon,
# so env vars alone are too late; the backend is still uninitialized at
# conftest time, so the config/XLA_FLAGS switch in force_cpu_devices takes
# effect here.
from euler_tpu.parallel import force_cpu_devices

force_cpu_devices(8)

import pytest

from tests.fixture_graph import FIXTURE_META, fixture_nodes, write_fixture


@pytest.fixture(scope="session")
def fixture_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("graph")
    write_fixture(str(d), num_partitions=2)
    return str(d)


@pytest.fixture(scope="session")
def graph(fixture_dir):
    import euler_tpu

    return euler_tpu.Graph(directory=fixture_dir)


@pytest.fixture(scope="session")
def meta():
    return dict(FIXTURE_META)


@pytest.fixture(scope="session")
def nodes():
    return fixture_nodes()
