"""Test harness config: force JAX onto a virtual 8-device CPU mesh so every
sharding test runs without TPU hardware (the driver separately dry-runs the
multi-chip path)."""

import os

# The environment preloads jax (axon sitecustomize) with JAX_PLATFORMS=axon,
# so env vars alone are too late; the backend is still uninitialized at
# conftest time, so config.update + XLA_FLAGS here take effect.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from tests.fixture_graph import FIXTURE_META, fixture_nodes, write_fixture


@pytest.fixture(scope="session")
def fixture_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("graph")
    write_fixture(str(d), num_partitions=2)
    return str(d)


@pytest.fixture(scope="session")
def graph(fixture_dir):
    import euler_tpu

    return euler_tpu.Graph(directory=fixture_dir)


@pytest.fixture(scope="session")
def meta():
    return dict(FIXTURE_META)


@pytest.fixture(scope="session")
def nodes():
    return fixture_nodes()
