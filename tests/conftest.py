"""Test harness config: force JAX onto a virtual 8-device CPU mesh so every
sharding test runs without TPU hardware (the driver separately dry-runs the
multi-chip path)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment preloads jax (axon sitecustomize) with JAX_PLATFORMS=axon,
# so env vars alone are too late; the backend is still uninitialized at
# conftest time, so the config/XLA_FLAGS switch in force_cpu_devices takes
# effect here.
from euler_tpu.parallel import force_cpu_devices

# EULER_TPU_TESTS_ON_TPU=1 keeps the real backend so the TPU-only suites
# (tests/test_pallas_sampling.py) can run on a chip; everything else in
# the suite still passes there but much slower, so target the run:
#   EULER_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_pallas_sampling.py
if os.environ.get("EULER_TPU_TESTS_ON_TPU") != "1":
    force_cpu_devices(8)

import pytest

from tests.fixture_graph import FIXTURE_META, fixture_nodes, write_fixture


@pytest.fixture(scope="session")
def fixture_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("graph")
    write_fixture(str(d), num_partitions=2)
    return str(d)


@pytest.fixture(scope="session")
def graph(fixture_dir):
    import euler_tpu

    return euler_tpu.Graph(directory=fixture_dir)


@pytest.fixture(scope="session")
def meta():
    return dict(FIXTURE_META)


@pytest.fixture(scope="session")
def nodes():
    return fixture_nodes()


def run_worker_processes(worker_src: str, per_proc_args, timeout=300):
    """Launch one python subprocess per args tuple running ``worker_src``
    and return each one's stdout. Shared by the multi-process distributed
    tests. Guarantees sibling cleanup: if any worker fails or times out,
    the rest are killed (a surviving worker would otherwise sit blocked
    in a jax.distributed collective holding its ports). Asserts rc==0
    with the worker's stderr tail as the message."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker_src, *map(str, args)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for args in per_proc_args
    ]
    outs = []
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=timeout)
            if (
                p.returncode != 0
                and "Multiprocess computations aren't implemented" in err
            ):
                # this jaxlib's CPU backend has no cross-process
                # collective support — an environment limit, not a
                # regression in the code under test (the same recipe
                # passes on backends that implement them)
                import pytest

                pytest.skip(
                    "CPU backend lacks multiprocess computations "
                    "(jax.distributed collectives unavailable)"
                )
            assert p.returncode == 0, f"worker {pid} failed:\n{err[-2500:]}"
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
