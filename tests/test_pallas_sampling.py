"""Fused Pallas sampling kernels vs the host engine and the XLA path.

The kernel-executing tests here require a real single-device TPU
backend: they exercise the ON-CORE PRNG's stream (statistical pinning
against the host engine) and the compiled kernels, which interpret
mode cannot attest. Run manually on a chip (the env var keeps
conftest.py from forcing the virtual CPU backend):

    EULER_TPU_TESTS_ON_TPU=1 python -m pytest tests/test_pallas_sampling.py -v

Everything BELOW the PRNG — layout, DMA addressing, rank/select across
registers, the chained kernel's data-dependent hop-2 DMAs, default/OOB
contracts — additionally runs on CPU in the default suite through
pallas' TPU interpret mode with injected uniforms, as EXACT-equality
tests: see tests/test_pallas_interpret.py.

The recorded on-chip run for this round is in PERF.md (step anatomy
section); the distribution check mirrors tests/test_device_graph.py's
statistical pinning of the XLA path against the host engine.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from euler_tpu.graph import pallas_sampling

MAX_ID = 16  # fixture ids are 10..16 (tests/fixture_graph.py TOPOLOGY)

tpu_only = pytest.mark.skipif(
    not pallas_sampling.available(),
    reason="needs a single-device TPU backend (pallas kernel path)",
)


# ---- activation guards (pure host logic, run everywhere) ----


def test_eligible_budgets():
    ps = pallas_sampling
    assert ps.eligible(5120, 10)            # PPI hop-2 draw
    assert ps.eligible(1, ps.MAX_COUNT)
    assert not ps.eligible(1, ps.MAX_COUNT + 1)
    assert not ps.eligible(204800, 10)      # [M, count] past the VMEM cap


def test_eligible2_budgets():
    ps = pallas_sampling
    assert ps.eligible2(512, 10, 10)            # the PPI recipe fanout
    assert ps.eligible2(1000, 4, 4, k1=4, k2=4)  # reddit recipe, wide slabs
    assert not ps.eligible2(512, ps.MAX_COUNT + 1, 4)
    assert not ps.eligible2(204800, 10, 10)     # hop-2 out past VMEM cap
    # hop-2 scratch at the MINIMUM stage (8 rows) must fit: k2*f1 <= 192,
    # else the kernel would fail VMEM allocation instead of falling back
    assert not ps.eligible2(128, 128, 2, k1=1, k2=4)
    assert ps.eligible2(128, 48, 2, k1=1, k2=4)


def test_pack_adjacency_hbm_budget():
    small = {
        "nbr": np.zeros((100, 8), np.int32),
        "cum": np.ones((100, 8), np.float32),
    }
    assert pallas_sampling.pack_adjacency(small) is not None
    # past the budget (this slab packs to exactly 100 KiB) — refused;
    # at the default 2 GB cap that's the 10M-node-graph case
    assert (
        pallas_sampling.pack_adjacency(small, max_bytes=100 * 1024 - 1)
        is None
    )
    # W=200 packs as K=2 (test_packed_layout_k_boundaries); only
    # W > MAX_W refuses (test_packed_layout_refuses_past_max_width)


def test_packed_layout_refuses_past_max_width():
    """Wider than MAX_W keeps the XLA path (layout coverage for every
    supported K lives in test_packed_layout_k_boundaries)."""
    ps = pallas_sampling
    too_wide = {
        "nbr": np.zeros((4, ps.MAX_W + 1), np.int32),
        "cum": np.ones((4, ps.MAX_W + 1), np.float32),
    }
    assert ps.pack_adjacency(too_wide) is None


@pytest.mark.parametrize("w,k", [(129, 2), (200, 2), (300, 3), (512, 4)])
def test_packed_layout_k_boundaries(w, k):
    """Every K the kernel supports (up to MAX_W/128 = 4), including the
    one-past-a-register width 129: node-major [K nbr rows, K cum rows]
    blocks with exact pad semantics (pure host numpy, runs
    everywhere)."""
    ps = pallas_sampling
    rng = np.random.default_rng(w)
    n = 6
    nbr = rng.integers(0, n, (n, w)).astype(np.int32)
    cum = np.sort(rng.random((n, w)).astype(np.float32), axis=1)
    cum[:, -1] = 1.0
    packed = ps.pack_adjacency({"nbr": nbr, "cum": cum})
    assert packed is not None and packed.shape == (2 * k * n, ps.LANES)
    blk = packed.reshape(n, 2 * k, ps.LANES)
    got_nbr = blk[:, :k].reshape(n, k * ps.LANES)
    got_cum = blk[:, k:].reshape(n, k * ps.LANES).view(np.float32)
    np.testing.assert_array_equal(got_nbr[:, :w], nbr)
    np.testing.assert_array_equal(got_cum[:, :w], cum)
    assert (got_cum[:, w:] == 1.0).all()
    assert (got_nbr[:, w:] == n - 1).all()


def test_pack_bakes_unsampleable_rows_to_default():
    """Zero-weight (unsampleable) rows default-fill their neighbor lanes
    at pack time — the kernel's replacement for the host path's
    `sampleable` mask — while sampleable rows keep their ids (pure host
    numpy, runs everywhere)."""
    ps = pallas_sampling
    n, w = 6, 4
    nbr = np.arange(n * w, dtype=np.int32).reshape(n, w)
    cum = np.tile(np.linspace(0.25, 1.0, w, dtype=np.float32), (n, 1))
    ok = np.array([True, False, True, True, False, True])
    packed = ps.pack_adjacency({"nbr": nbr, "cum": cum, "sampleable": ok})
    blk = packed.reshape(n, 2, ps.LANES)
    for i in range(n):
        if ok[i]:
            np.testing.assert_array_equal(blk[i, 0, :w], nbr[i])
        else:
            assert (blk[i, 0] == n - 1).all()  # every lane -> default id


def test_force_env_still_requires_tpu_backend(monkeypatch):
    """EULER_TPU_PALLAS_SAMPLING=1 must not activate the kernel where its
    TPU-only primitives cannot run (this suite's backend is CPU)."""
    monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", "1")
    if jax.default_backend() != "tpu":
        assert not pallas_sampling.available()
    monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", "0")
    assert not pallas_sampling.available()


def test_force_env_parsed_strictly(monkeypatch):
    """Only 0/1/false/true (case-insensitive) are honored; anything else
    warns and counts as unset instead of silently force-enabling."""
    for raw, want in [
        ("1", True), ("true", True), ("TRUE", True),
        ("0", False), ("false", False), ("False", False), (" FALSE ", False),
    ]:
        monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", raw)
        assert pallas_sampling._force_flag() is want, raw
    monkeypatch.delenv("EULER_TPU_PALLAS_SAMPLING", raising=False)
    assert pallas_sampling._force_flag() is None
    monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", "")
    assert pallas_sampling._force_flag() is None
    for bad in ("off", "no", "yes", "2"):
        monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", bad)
        with pytest.warns(UserWarning, match="not one of 0/1/false/true"):
            assert pallas_sampling._force_flag() is None


# ---- SPMD wiring (shard_map path; CPU-executable via draw_fn) ----

multi_device = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="needs >= 4 devices (CPU conftest mesh); on the single-chip "
    "TPU run these would test a 1-device mesh — vacuous for "
    "decorrelation, wrong for the divisibility fallback",
)


@multi_device
def test_force_env_multi_device_needs_kernel_mesh(monkeypatch):
    """Force=1 on a multi-device backend is honored only once a kernel
    mesh is registered: without one the direct route would run an
    unsharded pallas_call under pjit (silently wrong per-shard draws),
    so available() warns and stays False (code-review r4)."""
    from jax.sharding import Mesh

    from euler_tpu.graph import device as dg

    monkeypatch.setenv("EULER_TPU_PALLAS_SAMPLING", "1")
    monkeypatch.setattr(
        pallas_sampling, "_backend_ok", lambda require_single_device: True
    )
    assert dg.kernel_mesh() is None
    with pytest.warns(UserWarning, match="no kernel mesh"):
        assert not pallas_sampling.available()
    dg.set_kernel_mesh(Mesh(np.array(jax.devices()[:4]), ("data",)), "data")
    try:
        assert pallas_sampling.available()
    finally:
        dg.set_kernel_mesh(None)


def _xla_draw(adj_l, nodes_l, seed, count):
    """XLA stand-in with the kernel's exact call signature
    (adj, nodes, seed[2], count) — lets the shard_map wiring run on CPU
    meshes where the kernel's TPU primitives cannot."""
    import jax.numpy as jnp

    key = jax.random.PRNGKey(seed[0])
    nodes = jnp.asarray(nodes_l, jnp.int32)
    n_rows = adj_l["nbr"].shape[0]
    nodes = jnp.where(nodes < 0, n_rows - 1, jnp.minimum(nodes, n_rows - 1))
    cum = adj_l["cum"][nodes]
    u = jax.random.uniform(key, (*nodes.shape, count))
    idx = (u[..., None] >= cum[..., None, :]).sum(-1)
    idx = jnp.clip(idx, 0, adj_l["nbr"].shape[1] - 1)
    out = jnp.take_along_axis(adj_l["nbr"][nodes], idx, axis=-1)
    return jnp.where(
        adj_l["sampleable"][nodes][..., None], out, n_rows - 1
    )


@multi_device
def test_sharded_draw_wiring_distribution(graph, adj):
    """sample_neighbor_sharded on a 4-device mesh (XLA stand-in body):
    batch-sharded nodes, replicated adjacency, per-source draw
    frequencies match the host engine's weights — proving the shard_map
    specs and the reshape round-trip. (The module's graph/adj fixtures
    build on any backend; only the kernel-executing tests are
    TPU-gated.)"""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    g = graph
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    ids = np.arange(MAX_ID + 1)
    nodes = jnp.asarray(np.tile(ids, 4), jnp.int32)  # 68 rows -> 17/shard
    draws = 64

    f = jax.jit(
        lambda n, s: pallas_sampling.sample_neighbor_sharded(
            adj, n, s, draws, mesh, "data", draw_fn=_xla_draw
        )
    )
    out = np.concatenate(
        [np.asarray(f(nodes, jnp.asarray([c, c + 1]))) for c in range(16)],
        axis=1,
    )
    assert out.shape == (len(nodes), 16 * draws)
    nb, w, _, cnt = g.get_full_neighbor(ids, [0, 1])
    per_node = out.reshape(4, len(ids), -1).transpose(1, 0, 2).reshape(
        len(ids), -1
    )
    total = per_node.shape[1]
    off = 0
    for i, c in enumerate(cnt):
        c = int(c)
        nbrs, ws = nb[off:off + c], w[off:off + c]
        off += c
        if c == 0 or ws.sum() <= 0:
            assert (per_node[i] == MAX_ID + 1).all()
            continue
        expect = ws / ws.sum()
        for n_, p in zip(nbrs, expect):
            freq = (per_node[i] == n_).mean()
            assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / total) + 1e-3


@multi_device
def test_sharded_draw_decorrelates_shards(adj):
    """The same node replicated across the whole batch must NOT draw
    identical sequences on every shard — axis_index folds into the
    per-shard seed."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    nodes = jnp.full((64,), 10, jnp.int32)  # node with >1 neighbor
    out = np.asarray(
        pallas_sampling.sample_neighbor_sharded(
            adj, nodes, jnp.asarray([7, 8]), 32, mesh, "data",
            draw_fn=_xla_draw,
        )
    ).reshape(4, 16, 32)
    assert not (out[0] == out[1]).all()
    assert not (out[0] == out[2]).all()


@multi_device
def test_kernel_mesh_routing(adj, monkeypatch):
    """device.sample_neighbor routes through the sharded path when a
    kernel mesh is registered and the local draw is eligible, and falls
    back to the XLA chain when the batch does not divide the axis."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from euler_tpu.graph import device as dg

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    calls = []

    def fake_sharded(adj_, nodes, seed, count, mesh_, axis, draw_fn=None):
        calls.append((int(np.prod(nodes.shape)), count, axis))
        return jnp.zeros((*nodes.shape, count), jnp.int32)

    monkeypatch.setattr(
        pallas_sampling, "sample_neighbor_sharded", fake_sharded
    )
    dg.set_kernel_mesh(mesh, "data")
    try:
        out = dg.sample_neighbor(
            adj, jnp.zeros((8,), jnp.int32), jax.random.PRNGKey(0), 5
        )
        assert out.shape == (8, 5) and calls == [(8, 5, "data")]
        # 7 rows do not divide 4 shards -> XLA fallback, no sharded call
        out = dg.sample_neighbor(
            adj, jnp.zeros((7,), jnp.int32), jax.random.PRNGKey(0), 5
        )
        assert out.shape == (7, 5) and len(calls) == 1
    finally:
        dg.set_kernel_mesh(None)


def test_packed_consts_without_mesh_take_xla_chain_when_unavailable(
    monkeypatch,
):
    """Consts can carry a packed slab while available() is False (e.g.
    set_kernel_mesh(None) on a multi-device backend, or
    EULER_TPU_PALLAS_SAMPLING=0 set after packing): the direct-kernel
    branch must NOT fire — the unsharded pallas_call under pjit is the
    composition the module's SPMD note warns about (ADVICE r3)."""
    import jax.numpy as jnp

    from euler_tpu.graph import device as dg

    n, w = 4, 3
    nbr = np.tile(np.arange(1, w + 1, dtype=np.int32), (n, 1))
    cum = np.tile(
        np.array([0.25, 0.5, 1.0], np.float32), (n, 1)
    )
    adj = {
        "nbr": jnp.asarray(nbr),
        "cum": jnp.asarray(cum),
        "sampleable": jnp.ones((n,), bool),
        "packed": jnp.asarray(
            pallas_sampling.pack_adjacency({"nbr": nbr, "cum": cum})
        ),
    }
    kernel_calls = []
    monkeypatch.setattr(
        pallas_sampling,
        "sample_neighbor",
        lambda *a, **kw: kernel_calls.append(a) or None,
    )
    assert dg.kernel_mesh() is None
    monkeypatch.setattr(pallas_sampling, "available", lambda: False)
    out = dg.sample_neighbor(
        adj, jnp.zeros((5,), jnp.int32), jax.random.PRNGKey(0), 6
    )
    assert out.shape == (5, 6) and not kernel_calls  # XLA chain taken
    # converse: available() True routes the eligible draw to the kernel
    monkeypatch.setattr(pallas_sampling, "available", lambda: True)
    out = dg.sample_neighbor(
        adj, jnp.zeros((5,), jnp.int32), jax.random.PRNGKey(0), 6
    )
    assert kernel_calls and out is None  # the fake kernel was called


# ---- kernel tests (single-device TPU only) ----


@pytest.fixture(scope="module")
def graph(tmp_path_factory):
    import euler_tpu
    from tests.fixture_graph import write_fixture

    d = tmp_path_factory.mktemp("pallas_graph")
    write_fixture(str(d))
    return euler_tpu.Graph(directory=str(d))


@pytest.fixture(scope="module")
def adj(graph):
    from euler_tpu.graph import device as dg

    a = dg.build_adjacency(graph, [0, 1], MAX_ID)
    packed = pallas_sampling.pack_adjacency(a)
    assert packed is not None
    a["packed"] = packed
    return jax.device_put({k: jax.numpy.asarray(v) for k, v in a.items()})


@tpu_only
def test_packed_layout_roundtrip(adj):
    packed = np.asarray(adj["packed"])
    nbr = np.asarray(adj["nbr"])
    cum = np.asarray(adj["cum"])
    n, w = nbr.shape
    assert packed.shape == (2 * n, pallas_sampling.LANES)
    # unsampleable rows bake the default-node fill into the slab
    ok = np.asarray(adj["sampleable"]).astype(bool)
    np.testing.assert_array_equal(
        packed[0::2, :w], np.where(ok[:, None], nbr, n - 1)
    )
    np.testing.assert_array_equal(
        packed[1::2, :w].view(np.float32), cum
    )
    # pad lanes: unreachable (cum=1.0) and default-id filled
    assert (packed[1::2, w:].view(np.float32) == 1.0).all()
    assert (packed[0::2, w:] == n - 1).all()


@tpu_only
def test_shapes_and_default_fill(adj, graph):
    import jax.numpy as jnp

    from euler_tpu.graph import device as dg

    default = int(adj["nbr"].shape[0] - 1)
    # the default row must draw itself; real nodes must draw in-graph
    nodes = jnp.asarray([0, 1, default], jnp.int32)
    out = jax.jit(
        lambda n, k: dg.sample_neighbor(adj, n, k, 7)
    )(nodes, jax.random.PRNGKey(0))
    assert out.shape == (3, 7)
    assert (np.asarray(out[2]) == default).all()
    assert (np.asarray(out[:2]) <= default).all()


@tpu_only
def test_oob_ids_and_empty_input(adj):
    """Out-of-range ids must clamp to the default row (the XLA path's
    OOB-gather behavior) — in the kernel they are raw DMA offsets — and
    an empty node set must return an empty array, not start unawaited
    prologue DMAs."""
    import jax.numpy as jnp

    from euler_tpu.graph import device as dg

    default = int(adj["nbr"].shape[0] - 1)
    nodes = jnp.asarray([default + 1, default + 1000, -3], jnp.int32)
    out = jax.jit(
        lambda n, k: dg.sample_neighbor(adj, n, k, 5)
    )(nodes, jax.random.PRNGKey(1))
    # rows past the slab AND negative ids both land on the default row
    # (build_adjacency's "unknown ids sample the default node" contract;
    # the XLA path's numpy-style wrap sends -1 there too)
    assert (np.asarray(out) == default).all()

    empty = jax.jit(
        lambda n, k: dg.sample_neighbor(adj, n, k, 5)
    )(jnp.zeros((0,), jnp.int32), jax.random.PRNGKey(2))
    assert empty.shape == (0, 5)


@tpu_only
def test_distribution_matches_host_engine(adj, graph):
    """Empirical draw frequencies ≈ the host engine's normalized edge
    weights for every fixture node (the same gate the XLA path passes in
    tests/test_device_graph.py)."""
    import jax.numpy as jnp

    from euler_tpu.graph import device as dg

    ids = np.arange(MAX_ID + 1)
    nb, w, _, cnt = graph.get_full_neighbor(ids, [0, 1])
    per_call, calls = 128, 32          # kernel caps count at MAX_COUNT;
    draws = per_call * calls           # accumulate over folded keys
    f = jax.jit(lambda n, k: dg.sample_neighbor(adj, n, k, per_call))
    key = jax.random.PRNGKey(7)
    out = np.concatenate(
        [
            np.asarray(f(jnp.asarray(ids, jnp.int32),
                         jax.random.fold_in(key, c)))
            for c in range(calls)
        ],
        axis=1,
    )
    off = 0
    for i, c in enumerate(cnt):
        c = int(c)
        nbrs, ws = nb[off:off + c], w[off:off + c]
        off += c
        if c == 0 or ws.sum() <= 0:
            assert (out[i] == MAX_ID + 1).all()
            continue
        expect = ws / ws.sum()
        for n_, p in zip(nbrs, expect):
            freq = (out[i] == n_).mean()
            assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / draws) + 1e-3


@tpu_only
def test_wide_slab_draws_cross_register_boundary():
    """A W=200 (K=2) slab whose mass sits at slots 5 and 150 — one in
    each 128-lane register — must draw exactly those neighbors at their
    weights, proving the rank sum and the per-register select compose
    across the boundary."""
    import jax.numpy as jnp

    ps = pallas_sampling
    n, w = 8, 200
    nbr = np.tile(np.arange(w, dtype=np.int32), (n, 1)) + 1000
    cum = np.zeros((n, w), np.float32)
    cum[:, 5:150] = 0.3
    cum[:, 150:] = 1.0
    adj = {
        "nbr": jnp.asarray(nbr),
        "cum": jnp.asarray(cum),
        "sampleable": jnp.ones((n,), bool),
        "packed": jnp.asarray(
            ps.pack_adjacency({"nbr": nbr, "cum": cum})
        ),
    }
    draws = 128
    out = np.concatenate(
        [
            np.asarray(
                ps.sample_neighbor(
                    adj, jnp.arange(n, dtype=jnp.int32),
                    jnp.int32(seed), draws,
                )
            )
            for seed in range(16)
        ],
        axis=1,
    )
    vals, counts = np.unique(out, return_counts=True)
    assert set(vals) == {1005, 1150}, vals
    p150 = counts[vals == 1150][0] / out.size
    assert abs(p150 - 0.7) < 6 * np.sqrt(0.7 * 0.3 / out.size) + 1e-3


@tpu_only
def test_sharded_kernel_executes_on_hardware(adj, graph):
    """The REAL kernel inside shard_map on the chip (a 1-device mesh —
    the single-chip environment's honest version of the SPMD path; the
    wiring across >1 shard is pinned by the CPU tests above). Draw
    frequencies must match the host engine like the direct-call test."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    ids = np.arange(MAX_ID + 1)
    nodes = jnp.asarray(ids, jnp.int32)
    per_call, calls = 128, 16
    f = jax.jit(
        lambda n, s: pallas_sampling.sample_neighbor_sharded(
            adj, n, s, per_call, mesh, "data"
        )
    )
    out = np.concatenate(
        [np.asarray(f(nodes, jnp.asarray([c, c + 9]))) for c in range(calls)],
        axis=1,
    )
    nb, w, _, cnt = graph.get_full_neighbor(ids, [0, 1])
    total = per_call * calls
    off = 0
    for i, c in enumerate(cnt):
        c = int(c)
        nbrs, ws = nb[off:off + c], w[off:off + c]
        off += c
        if c == 0 or ws.sum() <= 0:
            assert (out[i] == MAX_ID + 1).all()
            continue
        expect = ws / ws.sum()
        for n_, p in zip(nbrs, expect):
            freq = (out[i] == n_).mean()
            assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / total) + 1e-3


@tpu_only
def test_fanout_routes_through_kernel_and_trains(adj, graph):
    """sample_fanout picks up the packed slab, and a device-sampling
    GraphSAGE step using it still descends."""
    import jax.numpy as jnp
    import optax

    from euler_tpu.graph import device as dg
    from euler_tpu.models import SupervisedGraphSage

    roots = jnp.asarray(graph.sample_node(8, -1), jnp.int32)
    hops = jax.jit(
        lambda r, k: dg.sample_fanout([adj, adj], r, k, [3, 2])
    )(roots, jax.random.PRNGKey(3))
    assert [h.shape[0] for h in hops] == [8, 24, 48]

    model = SupervisedGraphSage(
        label_idx=0, label_dim=4, metapath=[[0, 1]] * 2, fanouts=[3, 2],
        dim=16, feature_idx=0, feature_dim=2, max_id=MAX_ID,
        device_features=True, device_sampling=True,
    )
    opt = optax.adam(0.05)
    state = model.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    assert any(
        "packed" in a for a in state["consts"]["adj"].values()
    ), "available() TPU run must pack the slabs"
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    losses = []
    for i in range(30):
        batch = model.device_sample_batch(graph.sample_node(8, -1))
        state, loss, _ = step(state, batch)
        losses.append(float(loss))
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


@tpu_only
def test_chained_fanout_distribution_matches_host_engine(adj, graph):
    """sample_fanout2 on the chip: hop-1 marginals match the host
    engine's normalized weights, and hop-2 draws grouped by their
    ACTUAL hop-1 source match that source's distribution — the
    conditional check the chained kernel's data-dependent DMAs must
    get right (reference: two chained CompactNode::SampleNeighbor
    rounds, euler/core/compact_node.cc:42-101)."""
    import jax.numpy as jnp

    ids = np.arange(MAX_ID + 1)
    nb, w, _, cnt = graph.get_full_neighbor(ids, [0, 1])
    weights = {}
    off = 0
    for i, c in enumerate(cnt):
        c = int(c)
        nbrs, ws = nb[off:off + c], w[off:off + c]
        off += c
        if c and ws.sum() > 0:
            weights[i] = dict(zip(nbrs, ws / ws.sum()))
    f1, f2, calls = 16, 16, 24
    f = jax.jit(
        lambda r, s: pallas_sampling.sample_fanout2(
            adj, adj, r, s, f1, f2
        )
    )
    roots = jnp.asarray(ids, jnp.int32)
    h1_all, pairs = [], []          # pairs: (hop-2 source id, drawn id)
    for c in range(calls):
        h1, h2 = f(roots, jnp.asarray([c, 5 * c + 1]))
        h1, h2 = np.asarray(h1), np.asarray(h2)
        h1_all.append(h1)
        pairs.append(
            np.stack(
                [np.repeat(h1.reshape(-1), f2), h2.reshape(-1)], axis=1
            )
        )
    h1_all = np.concatenate(h1_all, axis=1)     # [n_ids, calls*f1]
    total1 = h1_all.shape[1]
    for i in range(len(ids)):
        if i not in weights:
            assert (h1_all[i] == MAX_ID + 1).all()
            continue
        for n_, p in weights[i].items():
            freq = (h1_all[i] == n_).mean()
            assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / total1) + 1e-3
    pairs = np.concatenate(pairs, axis=0)
    for i, dist in weights.items():
        drawn = pairs[pairs[:, 0] == i][:, 1]
        if len(drawn) < 512:        # too few hop-1 visits to pin
            continue
        for n_, p in dist.items():
            freq = (drawn == n_).mean()
            assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / len(drawn)) + 2e-3
    # every hop-2 row whose source is the default node stays default
    dflt = pairs[pairs[:, 0] == MAX_ID + 1][:, 1]
    assert len(dflt) and (dflt == MAX_ID + 1).all()


@tpu_only
def test_chained_sharded_kernel_executes_on_hardware(adj, graph):
    """The chained kernel inside shard_map on the chip (1-device mesh,
    like test_sharded_kernel_executes_on_hardware): shapes, in-graph
    picks, and hop-1 marginals for one well-connected node."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    roots = jnp.full((32,), 10, jnp.int32)
    f = jax.jit(
        lambda r, s: pallas_sampling.sample_fanout2_sharded(
            adj, adj, r, s, 8, 4, mesh, "data"
        )
    )
    h1, h2 = f(roots, jnp.asarray([3, 11]))
    assert h1.shape == (32, 8) and h2.shape == (256, 4)
    assert (np.asarray(h1) <= MAX_ID + 1).all()
    assert (np.asarray(h2) <= MAX_ID + 1).all()
    nb, w, _, cnt = graph.get_full_neighbor(np.array([10]), [0, 1])
    expect = dict(zip(nb[: int(cnt[0])], w[: int(cnt[0])]))
    total = sum(expect.values())
    draws = np.concatenate(
        [np.asarray(f(roots, jnp.asarray([c, c]))[0]).reshape(-1)
         for c in range(8)]
    )
    for n_, ww in expect.items():
        p = ww / total
        freq = (draws == n_).mean()
        assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / len(draws)) + 1e-3
