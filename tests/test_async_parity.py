"""Async whole-step sampling parity: eg_remote_sample_async vs the sync
path, on a reddit_heavytail-shaped fixture, plus the live input-stall
acceptance check.

Parity strategy: the async chain and sample_fanout run the SAME
NbrPrep/chunk/Finish phases against the same shards, so everything
deterministic must be BIT-identical — shapes, the root hop, default
fills, per-edge weights/types, neighbor-set membership. The draws
themselves go through server-side thread-local RNG (like the sync
path), so draw-for-draw equality across calls is not defined even
sync-vs-sync; there the contract is the reference's (SURVEY §4
sampler-distribution tests): empirical neighbor frequencies converge to
the same edge-weight distribution. Both halves are pinned here.

The acceptance test is ROADMAP item 1's exit criterion: against a live
2-shard SUBPROCESS cluster (server CPU not attributed to the client),
the sampler_depth=2 pipeline must drive the measured per-step consumer
stall under 5% of the device step it overlaps with.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NUM_SHARDS = 2
NUM_NODES = 400
METAPATH = [[0, 1], [0, 1]]
FANOUTS = [5, 3]


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """reddit_heavytail recipe at test scale (power-law out-degrees,
    preferential targets) behind 2 in-process shards + a local mirror
    for ground truth."""
    from euler_tpu.datasets import build_powerlaw

    data = str(tmp_path_factory.mktemp("async_parity_data"))
    build_powerlaw(data, num_nodes=NUM_NODES, num_edges=6000,
                   feature_dim=8, label_dim=3, alpha=1.8,
                   num_partitions=4, seed=23)
    reg = str(tmp_path_factory.mktemp("async_parity_reg"))
    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    local = Graph(directory=data)
    remote = Graph(mode="remote", registry=reg)
    yield local, remote
    remote.close()
    local.close()
    for s in services:
        s.stop()


def _truth(local, ids, etypes):
    """{src: {dst: (weight, type)}} ground truth from the local ragged
    full-neighbor lists."""
    ids = np.asarray(ids, dtype=np.int64)
    nbr, w, t, counts = local.get_full_neighbor(ids, etypes)
    out = {}
    off = 0
    for i, src in enumerate(ids):
        c = int(counts[i])
        row = {}
        for d, ww, tt in zip(nbr[off:off + c], w[off:off + c],
                             t[off:off + c]):
            row[int(d)] = (float(ww), int(tt))
        out[int(src)] = row
        off += c
    return out


def _check_hops(local, roots, hop_ids, hop_w, hop_t, default):
    """Every sampled (src, dst, w, t) is bit-exact against the local
    graph's edge data; dead-end rows are default-filled with zero
    weight."""
    frontier = np.asarray(roots)
    for h in range(len(FANOUTS)):
        fan = FANOUTS[h]
        dst = np.asarray(hop_ids[h + 1]).reshape(len(frontier), fan)
        w = np.asarray(hop_w[h]).reshape(len(frontier), fan)
        t = np.asarray(hop_t[h]).reshape(len(frontier), fan)
        truth = _truth(
            local, np.unique(frontier[frontier >= 0]), METAPATH[h]
        )
        for i, src in enumerate(frontier):
            src = int(src)
            row = truth.get(src, {})
            for j in range(fan):
                d = int(dst[i, j])
                if src < 0 or not row:
                    # dead end (or propagated default): default fill
                    assert d == default, (h, src, d)
                    assert w[i, j] == 0.0, (h, src, w[i, j])
                    continue
                assert d in row, (h, src, d)
                tw, tt = row[d]
                assert w[i, j] == np.float32(tw), (h, src, d)
                assert t[i, j] == tt, (h, src, d)
        frontier = dst.reshape(-1)


def test_async_structurally_bit_exact_vs_sync(cluster):
    """Shapes, root hop, per-edge weight/type payloads, neighbor-set
    membership, and default fills: identical contract for sync and
    async outputs, element-for-element checkable against the local
    graph."""
    local, remote = cluster
    rng = np.random.default_rng(3)
    roots = rng.integers(0, NUM_NODES, 64).astype(np.int64)

    s_out = remote.sample_fanout(roots, METAPATH, FANOUTS, default_node=-1)
    h = remote.sample_fanout_async(roots, METAPATH, FANOUTS,
                                   default_node=-1)
    assert h is not None
    a_out = h.take()

    for out in (s_out, a_out):
        hop_ids, hop_w, hop_t = out
        assert [len(x) for x in hop_ids] == [64, 64 * 5, 64 * 5 * 3]
        assert [len(x) for x in hop_w] == [64 * 5, 64 * 5 * 3]
        np.testing.assert_array_equal(np.asarray(hop_ids[0]), roots)
        _check_hops(local, roots, hop_ids, hop_w, hop_t, default=-1)


def test_async_deterministic_subgraph_bit_identical(tmp_path):
    """On the deterministic slice of the draw — sources whose typed
    neighbor list has exactly one candidate (fixture nodes 11, 13, 14
    for edge type 0), and sources with none (node 15) — sync and async
    must agree BIT-FOR-BIT call after call: no RNG is consulted for
    forced rows, so this is the strongest parity the server-side
    thread-local RNG permits."""
    from tests.fixture_graph import write_fixture

    data = str(tmp_path / "tiny")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path / "tiny_reg")
    os.makedirs(reg)
    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    remote = Graph(mode="remote", registry=reg)
    try:
        ids = np.array([11, 13, 14, 15], dtype=np.int64)
        fan = 4
        # 11 -0-> {12}, 13 -0-> {10}, 14 -0-> {15}; 15 has no out-edges
        expect = np.repeat(
            np.array([12, 10, 15, -1], dtype=np.int64), fan
        ).reshape(len(ids), fan)
        s_ids, s_w, _ = remote.sample_neighbor(ids, [0], fan,
                                               default_node=-1)
        np.testing.assert_array_equal(
            np.asarray(s_ids).reshape(len(ids), fan), expect
        )
        assert np.all(np.asarray(s_w).reshape(len(ids), fan)[3] == 0.0)
        for _ in range(3):
            h = remote.sample_fanout_async(ids, [[0]], [fan],
                                           default_node=-1)
            assert h is not None
            a_ids, a_w, _ = h.take()
            np.testing.assert_array_equal(
                np.asarray(a_ids[1]).reshape(len(ids), fan), expect
            )
            np.testing.assert_array_equal(
                np.asarray(a_w[0]).reshape(len(ids), fan),
                np.asarray(s_w).reshape(len(ids), fan),
            )
    finally:
        remote.close()
        for s in services:
            s.stop()


def test_async_distribution_matches_sync(cluster):
    """Sampler-distribution parity (the reference's
    compact_weighted_collection_test.cc technique): over many draws from
    one hub, async empirical neighbor frequencies match the sync path's
    and the true edge-weight distribution."""
    local, remote = cluster
    truth = _truth(local, np.arange(NUM_NODES), [0, 1])
    hub = max(truth, key=lambda s: len(truth[s]))
    assert len(truth[hub]) >= 5
    total_w = sum(w for w, _ in truth[hub].values())
    ids = np.full(256, hub, dtype=np.int64)
    fan = 8
    n_draws = 256 * fan * 4

    def freqs(async_mode):
        counts: dict = {}
        for _ in range(4):
            if async_mode:
                h = remote.sample_fanout_async(ids, [[0, 1]], [fan])
                out, _, _ = h.take()
                drawn = np.asarray(out[1])
            else:
                out, _, _ = remote.sample_neighbor(ids, [0, 1], fan)
                drawn = np.asarray(out)
            for d in drawn.ravel():
                counts[int(d)] = counts.get(int(d), 0) + 1
        return {d: c / n_draws for d, c in counts.items()}

    f_sync = freqs(False)
    f_async = freqs(True)
    for d, (w, _) in truth[hub].items():
        expect = w / total_w
        assert f_sync.get(d, 0.0) == pytest.approx(expect, abs=0.03), d
        assert f_async.get(d, 0.0) == pytest.approx(expect, abs=0.03), d
        assert f_async.get(d, 0.0) == pytest.approx(
            f_sync.get(d, 0.0), abs=0.03
        ), d


def _launch_shard(idx, data, reg):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [sys.executable, "-m", "euler_tpu.graph.service",
         "--data_dir", data, "--shard_idx", str(idx),
         "--shard_num", str(NUM_SHARDS), "--registry", reg],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )


def _wait_registered(idx, reg, timeout=90.0):
    import socket

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for f in os.listdir(reg):
            if f.startswith(f"{idx}#"):
                host, port = f.split("#", 1)[1].rsplit("_", 1)
                try:
                    with socket.create_connection((host, int(port)), 1.0):
                        return
                except OSError:
                    pass
        time.sleep(0.1)
    raise TimeoutError(f"shard {idx} never came up")


def test_acceptance_input_stall_under_threshold_live_cluster(tmp_path):
    """ROADMAP item 1 exit criterion on a live 2-shard SUBPROCESS
    cluster: with sampler_depth=2 the measured steady-state consumer
    stall must be under 5% of the (simulated, sample-time-calibrated)
    device step it overlaps — the same threshold bench.py's
    sampling_hidden_by_prefetch now reports."""
    from euler_tpu.datasets import build_powerlaw
    from euler_tpu.parallel import pipeline
    from euler_tpu.telemetry import (
        phase_hists,
        set_telemetry,
        telemetry_reset,
    )

    data = str(tmp_path / "data")
    os.makedirs(data)
    build_powerlaw(data, num_nodes=NUM_NODES, num_edges=6000,
                   feature_dim=8, label_dim=3, alpha=1.8,
                   num_partitions=4, seed=23)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    procs = [_launch_shard(s, data, reg) for s in range(NUM_SHARDS)]
    try:
        for s in range(NUM_SHARDS):
            _wait_registered(s, reg)
        set_telemetry(True)
        g = Graph(mode="remote", registry=reg)
        try:
            rng = np.random.default_rng(5)
            batch, steps = 64, 24

            # calibrate: a device step the size of one sync sample, so
            # "hidden" is a real race, not a huge denominator
            t0 = time.perf_counter()
            for _ in range(3):
                roots = rng.integers(0, NUM_NODES, batch).astype(np.int64)
                g.sample_fanout(roots, METAPATH, FANOUTS)
            device_s = max(0.002, (time.perf_counter() - t0) / 3)

            def start_fn(step):
                roots = rng.integers(0, NUM_NODES, batch).astype(np.int64)
                return roots, g.sample_fanout_async(
                    roots, METAPATH, FANOUTS
                )

            def finish_fn(step, pending):
                roots, h = pending
                if h is None:
                    return g.sample_fanout(roots, METAPATH, FANOUTS)
                return h.take()

            first = True
            for _ in pipeline(start_fn, finish_fn, steps, depth=2):
                if first:  # drop the pipeline-fill stall of step 0
                    telemetry_reset()
                    first = False
                time.sleep(device_s)  # simulated device compute

            stall = phase_hists().get("input_stall")
            assert stall and stall["count"] >= steps - 1, stall
            stall_ms = stall["sum_us"] / stall["count"] / 1000.0
            device_ms = device_s * 1e3
            assert stall_ms < 0.05 * device_ms, (
                f"input_stall {stall_ms:.3f} ms >= 5% of device step "
                f"{device_ms:.3f} ms — sampling not hidden"
            )
            ctr = native.counters()
            assert ctr["async_submits"] >= steps, ctr
        finally:
            g.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
