"""Metric doc-drift gate: the OBSERVABILITY.md glossary IS the metric
surface.

PRs 5-8 each added Prometheus families (`eg_*`); nothing stopped a new
family from shipping undocumented, or a doc entry from outliving its
metric. This gate pins both directions against a LIVE emission:

  * every family `metrics_text()` emits (local process AND a cluster
    scrape, so the scrape-only admission gauges are covered) must
    appear in the "## Metric glossary" section of OBSERVABILITY.md;
  * every `eg_*` family named in that glossary section must be emitted.

The glossary section is the single canonical table — families mentioned
elsewhere in the doc (runbooks, examples) don't count as documentation;
the table does.
"""

import pathlib
import re

import numpy as np
import pytest

import euler_tpu
from euler_tpu import telemetry as T
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("metric_docs_data"))
    write_fixture(d, num_partitions=2)
    return d


def emitted_families(text: str) -> set:
    """Family names as declared by the exposition's own HELP headers —
    the set a Prometheus server would discover."""
    return {
        m.group(1)
        for m in re.finditer(r"^# HELP (eg_[a-z0-9_]+) ", text,
                             re.MULTILINE)
    }


def documented_families() -> set:
    """eg_* tokens inside the canonical '## Metric glossary' section
    (and only there — prose mentions elsewhere are not documentation)."""
    doc = (REPO / "OBSERVABILITY.md").read_text()
    m = re.search(r"^## Metric glossary$(.*?)^## ", doc,
                  re.MULTILINE | re.DOTALL)
    assert m, "OBSERVABILITY.md lost its '## Metric glossary' section"
    return set(re.findall(r"\beg_[a-z0-9_]+\b", m.group(1)))


def test_every_emitted_family_is_documented_and_vice_versa(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = Graph(mode="remote", shards=[svc.address], retries=2,
                  timeout_ms=5000)
        try:
            T.telemetry_reset()
            # enough traffic that every data-dependent series family
            # (heat, cache classes, spread) has a nonzero emitter
            ids = np.array([1, 2, 3, 1, 2], dtype=np.int64)
            g.sample_neighbor(ids, [0, 1], 2)
            g.get_dense_feature(ids, [0], [4])
            g.get_dense_feature(ids, [0], [4])
            local = emitted_families(euler_tpu.metrics_text())
            scraped = emitted_families(euler_tpu.metrics_text(graph=g))
            emitted = local | scraped
        finally:
            g.close()
    finally:
        svc.stop()

    documented = documented_families()
    undocumented = sorted(emitted - documented)
    stale = sorted(documented - emitted)
    assert not undocumented, (
        f"metrics_text() emits families missing from the "
        f"OBSERVABILITY.md glossary: {undocumented} — add them to the "
        f"'## Metric glossary' table"
    )
    assert not stale, (
        f"the OBSERVABILITY.md glossary documents families "
        f"metrics_text() no longer emits: {stale} — remove them or "
        f"restore the metric"
    )


def test_gauge_families_require_the_scrape(data_dir):
    """The admission gauges only exist in a serving process's scrape —
    the gate above must actually be exercising that path (a local-only
    emission would quietly shrink the covered set)."""
    svc = GraphService(data_dir, 0, 1)
    try:
        g = Graph(mode="remote", shards=[svc.address], retries=2,
                  timeout_ms=5000)
        try:
            local = emitted_families(euler_tpu.metrics_text())
            scraped = emitted_families(euler_tpu.metrics_text(graph=g))
        finally:
            g.close()
    finally:
        svc.stop()
    assert "eg_workers" in scraped
    assert "eg_workers" not in local
