"""CPU execution of the Pallas sampling kernels via TPU interpret mode.

EULER_TPU_PALLAS_INTERPRET=1 routes pallas_call through pallas' TPU
interpreter (emulated DMAs/semaphores/SMEM on CPU), which executes the
REAL kernel bodies — the same programs the chip compiles — so layout,
DMA addressing, the cross-register rank/select, the chained hop-2
data-dependent DMAs, and the default/OOB contracts are all validated in
the default suite instead of waiting for hardware. The emulated core
PRNG returns zeros, so these tests inject uniforms (the kernels' ``u``
arguments), which upgrades the distributional TPU tests to EXACT ones:
identical uniforms must reproduce the XLA path's picks bit-for-bit
against the numpy reference below. What interpret mode cannot attest —
the real PRNG stream and performance — stays with the TPU-gated tests
in test_pallas_sampling.py and the bench.

Reference semantics: CompactNode::SampleNeighbor
(euler/core/compact_node.cc:42-101), first slot whose cumulative weight
exceeds u, default node for unsampleable/unknown rows.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from euler_tpu.graph import device as dg
from euler_tpu.graph import pallas_sampling as ps


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    monkeypatch.setenv("EULER_TPU_PALLAS_INTERPRET", "1")


def ref_pick(adj, nodes, u):
    """The XLA chain's pick semantics in plain numpy float32 — the
    oracle both kernels must match exactly for identical uniforms."""
    nbr = np.asarray(adj["nbr"])
    cum = np.asarray(adj["cum"])
    ok = np.asarray(adj["sampleable"]).astype(bool)
    n = nbr.shape[0]
    default = n - 1
    nodes = np.asarray(nodes)
    nodes = np.where(nodes < 0, default, np.minimum(nodes, default))
    u = np.asarray(u, np.float32)
    idx = (u[..., None] >= cum[nodes][..., None, :]).sum(-1)
    idx = np.clip(idx, 0, nbr.shape[1] - 1)
    out = np.take_along_axis(nbr[nodes], idx, axis=-1)
    return np.where(ok[nodes][..., None], out, default)


def make_adj(n, w, seed, unsampleable=()):
    """Random packed adjacency over n rows (row n-1 = default row,
    self-looped like build_adjacency's output)."""
    rng = np.random.default_rng(seed)
    nbr = rng.integers(0, n, (n, w)).astype(np.int32)
    cum = np.sort(rng.random((n, w)).astype(np.float32), axis=1)
    cum[:, -1] = 1.0
    ok = np.ones(n, bool)
    for i in unsampleable:
        ok[i] = False
        cum[i] = 1.0
    nbr[n - 1] = n - 1  # default row draws itself
    adj = {"nbr": nbr, "cum": cum, "sampleable": ok}
    packed = ps.pack_adjacency(adj)
    assert packed is not None
    adj["packed"] = packed
    return {k: jnp.asarray(v) for k, v in adj.items()}


def test_single_hop_exact_vs_reference(monkeypatch):
    """Multi-stage single-hop kernel (stage size forced to 8 so the
    double-buffered pipeline + tail padding run) with OOB ids and an
    unsampleable row — picks must equal the numpy oracle exactly."""
    monkeypatch.setattr(ps, "_MAX_R", 8)
    adj = make_adj(24, 7, seed=0, unsampleable=(3,))
    rng = np.random.default_rng(1)
    nodes = np.array(
        [0, 1, 3, 23, 22, -4, 30, 5, 6, 7, 8, 9, 10, 11, 2, 12, 13, 14],
        np.int32,
    )  # 18 ids -> 3 stages of 8 with padding
    u = rng.random((len(nodes), 5), dtype=np.float32)
    out = ps.sample_neighbor(
        adj, jnp.asarray(nodes), jnp.asarray([11, 13], jnp.int32), 5, u=u
    )
    np.testing.assert_array_equal(np.asarray(out), ref_pick(adj, nodes, u))


def test_single_hop_wide_slab_cross_register(monkeypatch):
    """K=2 slab (W=200): uniforms aimed at lanes on both sides of the
    128-lane register boundary must pick exactly the oracle's lanes."""
    adj = make_adj(10, 200, seed=2)
    nodes = np.arange(10, dtype=np.int32)
    # target low lanes, the boundary neighborhood, and high lanes
    cum = np.asarray(adj["cum"])
    u = np.stack(
        [cum[nodes, 3] - 1e-4, cum[nodes, 126] - 1e-4,
         cum[nodes, 128] - 1e-4, cum[nodes, 190] - 1e-4,
         np.full(10, 0.999, np.float32)],
        axis=1,
    ).astype(np.float32)
    out = ps.sample_neighbor(
        adj, jnp.asarray(nodes), jnp.asarray([5, 6], jnp.int32), 5, u=u
    )
    np.testing.assert_array_equal(np.asarray(out), ref_pick(adj, nodes, u))


def test_chained_two_hop_exact_vs_reference(monkeypatch):
    """The chained kernel's two hops — including the VMEM->SMEM pick
    copy and the data-dependent hop-2 DMAs, across multiple pipelined
    stages — must equal two oracle rounds exactly (heterogeneous
    adjacencies, OOB roots, unsampleable rows on both hops)."""
    monkeypatch.setattr(ps, "_MAX_R", 8)
    adj1 = make_adj(24, 6, seed=3, unsampleable=(5,))
    adj2 = make_adj(24, 9, seed=4, unsampleable=(7,))
    rng = np.random.default_rng(5)
    roots = np.array(
        [0, 5, 7, 23, -1, 40, 1, 2, 3, 4, 6, 8, 9, 10, 11, 12, 13, 14],
        np.int32,
    )  # 18 roots -> 3 stages of 8
    f1, f2 = 3, 2
    u1 = rng.random((len(roots), f1), dtype=np.float32)
    u2 = rng.random((len(roots) * f1, f2), dtype=np.float32)
    h1, h2 = ps.sample_fanout2(
        adj1, adj2, jnp.asarray(roots), jnp.asarray([21, 22], jnp.int32),
        f1, f2, u1=u1, u2=u2,
    )
    want1 = ref_pick(adj1, roots, u1)
    np.testing.assert_array_equal(np.asarray(h1), want1)
    want2 = ref_pick(adj2, want1.reshape(-1), u2)
    np.testing.assert_array_equal(np.asarray(h2), want2)


def test_chained_wide_slabs(monkeypatch):
    """K1=2 x K2=2 chained draw, single stage — the widest packed form
    both hops support together."""
    adj1 = make_adj(8, 160, seed=6)
    adj2 = make_adj(8, 140, seed=7)
    rng = np.random.default_rng(8)
    roots = np.arange(8, dtype=np.int32)
    u1 = rng.random((8, 2), dtype=np.float32)
    u2 = rng.random((16, 3), dtype=np.float32)
    h1, h2 = ps.sample_fanout2(
        adj1, adj2, jnp.asarray(roots), jnp.asarray([1, 2], jnp.int32),
        2, 3, u1=u1, u2=u2,
    )
    want1 = ref_pick(adj1, roots, u1)
    np.testing.assert_array_equal(np.asarray(h1), want1)
    np.testing.assert_array_equal(
        np.asarray(h2), ref_pick(adj2, want1.reshape(-1), u2)
    )


def test_chained_dma_race_detector_clean(monkeypatch):
    """The interpreter's DMA race detector must stay silent across the
    chained kernel's pipelined stages (double-buffered hop-1 rows,
    one-stage-behind hop-2 processing, single SMEM pick buffer)."""
    monkeypatch.setenv("EULER_TPU_PALLAS_INTERPRET", "races")
    monkeypatch.setattr(ps, "_MAX_R", 8)
    adj = make_adj(16, 5, seed=9)
    rng = np.random.default_rng(10)
    roots = np.arange(16, dtype=np.int32)
    u1 = rng.random((16, 2), dtype=np.float32)
    u2 = rng.random((32, 2), dtype=np.float32)
    h1, h2 = ps.sample_fanout2(
        adj, adj, jnp.asarray(roots), jnp.asarray([3, 4], jnp.int32),
        2, 2, u1=u1, u2=u2,
    )
    want1 = ref_pick(adj, roots, u1)
    np.testing.assert_array_equal(np.asarray(h1), want1)
    np.testing.assert_array_equal(
        np.asarray(h2), ref_pick(adj, want1.reshape(-1), u2)
    )


def test_empty_and_mismatched_inputs():
    adj = make_adj(8, 4, seed=11)
    h1, h2 = ps.sample_fanout2(
        adj, adj, jnp.zeros((0,), jnp.int32), jnp.asarray([1, 2]), 3, 2
    )
    assert h1.shape == (0, 3) and h2.shape == (0, 2)
    other = make_adj(9, 4, seed=12)
    with pytest.raises(ValueError, match="one id space"):
        ps.sample_fanout2(
            adj, other, jnp.zeros((4,), jnp.int32), jnp.asarray([1, 2]),
            2, 2,
        )
    with pytest.raises(ValueError, match="both u1 and u2"):
        ps.sample_fanout2(
            adj, adj, jnp.zeros((4,), jnp.int32), jnp.asarray([1, 2]),
            2, 2, u1=np.zeros((4, 2), np.float32),
        )


# ---- routing (no interpretation — fakes record the call) ----


def test_sample_fanout_routes_two_hop_to_chained_kernel(monkeypatch):
    monkeypatch.delenv("EULER_TPU_PALLAS_INTERPRET", raising=False)
    adj = make_adj(12, 4, seed=13)
    calls = []

    def fake(a1, a2, roots, seed, f1, f2):
        calls.append((int(roots.shape[0]), f1, f2))
        return (
            jnp.zeros((roots.shape[0], f1), jnp.int32),
            jnp.zeros((roots.shape[0] * f1, f2), jnp.int32),
        )

    monkeypatch.setattr(ps, "sample_fanout2", fake)
    monkeypatch.setattr(ps, "available", lambda: True)
    # the non-chained fallback loop would route its single-hop draws to
    # the kernel too (available() is forced True) — stub it to keep the
    # fallback XLA-executable on this CPU backend
    monkeypatch.setattr(
        ps,
        "sample_neighbor",
        lambda adj, nodes, seed, count, u=None: jnp.zeros(
            (*np.shape(nodes), count), jnp.int32
        ),
    )
    out = dg.sample_fanout(
        [adj, adj], jnp.arange(6, dtype=jnp.int32), jax.random.PRNGKey(0),
        [3, 2],
    )
    assert calls == [(6, 3, 2)]
    assert [int(np.prod(o.shape)) for o in out] == [6, 18, 36]
    # NOT two hops -> per-hop loop, chained kernel untouched
    dg.sample_fanout(
        [adj, adj, adj], jnp.arange(6, dtype=jnp.int32),
        jax.random.PRNGKey(0), [2, 2, 2],
    )
    assert len(calls) == 1
    # unpacked adjacency -> per-hop loop
    bare = {k: v for k, v in adj.items() if k != "packed"}
    dg.sample_fanout(
        [bare, bare], jnp.arange(6, dtype=jnp.int32),
        jax.random.PRNGKey(0), [3, 2],
    )
    assert len(calls) == 1


def test_sample_fanout_routes_through_mesh_when_registered(monkeypatch):
    if len(jax.devices()) < 4:
        pytest.skip("needs the CPU conftest mesh")
    from jax.sharding import Mesh

    monkeypatch.delenv("EULER_TPU_PALLAS_INTERPRET", raising=False)
    adj = make_adj(12, 4, seed=14)
    calls = []

    def fake_sharded(a1, a2, roots, seed, f1, f2, mesh, axis,
                     draw_fn=None):
        calls.append((int(roots.shape[0]), f1, f2, axis))
        return (
            jnp.zeros((roots.shape[0], f1), jnp.int32),
            jnp.zeros((roots.shape[0] * f1, f2), jnp.int32),
        )

    monkeypatch.setattr(ps, "sample_fanout2_sharded", fake_sharded)
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    dg.set_kernel_mesh(mesh, "data")
    try:
        out = dg.sample_fanout(
            [adj, adj], jnp.arange(8, dtype=jnp.int32),
            jax.random.PRNGKey(0), [3, 2],
        )
        assert calls == [(8, 3, 2, "data")]
        assert [int(np.prod(o.shape)) for o in out] == [8, 24, 48]
        # indivisible batch -> per-hop loop (which divides per draw or
        # falls back itself); the chained sharded route must not fire
        dg.sample_fanout(
            [adj, adj], jnp.arange(7, dtype=jnp.int32),
            jax.random.PRNGKey(0), [3, 2],
        )
        assert len(calls) == 1
    finally:
        dg.set_kernel_mesh(None)


def test_chained_sharded_wiring_cpu_mesh():
    """sample_fanout2_sharded's shard_map wiring on the CPU mesh with an
    XLA-executable draw_fn: per-shard seeds decorrelate and shapes
    reassemble (the kernel itself cannot run per-shard on CPU)."""
    if len(jax.devices()) < 4:
        pytest.skip("needs the CPU conftest mesh")
    from jax.sharding import Mesh

    adj = make_adj(12, 4, seed=15)
    seeds = []

    def draw_fn(a1, a2, roots, seed, f1, f2):
        # XLA stand-in: reference-pick via the XLA chain, seed recorded
        # through a shape trick (seed affects nothing here)
        return (
            jnp.broadcast_to(seed[0], (roots.shape[0], f1)).astype(
                jnp.int32
            ),
            jnp.zeros((roots.shape[0] * f1, f2), jnp.int32),
        )

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    h1, h2 = ps.sample_fanout2_sharded(
        adj, adj, jnp.arange(8, dtype=jnp.int32),
        jnp.asarray([5, 6], jnp.int32), 3, 2, mesh, "data",
        draw_fn=draw_fn,
    )
    assert h1.shape == (8, 3) and h2.shape == (24, 2)
    # 4 shards x 2 rows: each shard's folded seed differs
    per_shard = np.asarray(h1).reshape(4, 2, 3)
    assert len({int(s[0, 0]) for s in per_shard}) == 4


def test_interpret_params_parsing(monkeypatch):
    monkeypatch.delenv("EULER_TPU_PALLAS_INTERPRET", raising=False)
    assert ps.interpret_params() is False
    monkeypatch.setenv("EULER_TPU_PALLAS_INTERPRET", "0")
    assert ps.interpret_params() is False
    monkeypatch.setenv("EULER_TPU_PALLAS_INTERPRET", "1")
    p = ps.interpret_params()
    assert p is not False and not p.detect_races
    monkeypatch.setenv("EULER_TPU_PALLAS_INTERPRET", "races")
    assert ps.interpret_params().detect_races
