"""TCP shard registry: discovery without a shared filesystem.

Mirrors the reference's ZooKeeper semantics (ephemeral znodes
"<shard>#<ip:port>", zk_server_register.cc / zk_server_monitor.cc:50-64):
REG + heartbeat keeps an entry alive, entries of dead shards expire by TTL,
UNREG removes on clean stop, and a client's LIST sees only live shards.
"""

import socket
import struct
import time

import numpy as np
import pytest

from euler_tpu.graph.registry import RegistryServer, parse_tcp_url, query
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture


def _send_frame(sock, payload: bytes) -> bytes:
    sock.sendall(struct.pack("<I", len(payload)) + payload)
    (n,) = struct.unpack("<I", sock.recv(4, socket.MSG_WAITALL))
    return sock.recv(n, socket.MSG_WAITALL) if n else b""


@pytest.fixture()
def data_dir(tmp_path):
    d = str(tmp_path / "data")
    import os

    os.makedirs(d)
    write_fixture(d, num_partitions=2)
    return d


def test_parse_tcp_url():
    assert parse_tcp_url("tcp://h:91") == ("h", 91)
    assert parse_tcp_url("/some/dir") is None
    with pytest.raises(ValueError):
        parse_tcp_url("tcp://noport")


def test_registry_starts_and_lists_empty():
    with RegistryServer() as reg:
        assert reg.port > 0
        assert query(reg.address) == {}


def test_query_unreachable_raises():
    with pytest.raises(ConnectionError):
        query("tcp://127.0.0.1:1", timeout_ms=200)


def test_service_registers_and_unregisters(data_dir):
    with RegistryServer() as reg:
        svc = GraphService(data_dir, 0, 1, registry=reg.address)
        entries = query(reg.address)
        assert entries == {0: [svc.address]}
        svc.stop()
        assert query(reg.address) == {}  # UNREG on clean stop


def test_entries_expire_without_heartbeat():
    """An entry REGed once (no heartbeats) vanishes after the TTL — the
    ephemeral-znode analog for a SIGKILLed shard."""
    with RegistryServer(ttl_ms=300) as reg:
        with socket.create_connection(("127.0.0.1", reg.port), 2) as s:
            # reply advertises the TTL so registrants can pace heartbeats
            assert _send_frame(s, b"REG 3 10.0.0.9:7777") == b"OK 300"
        assert query(reg.address) == {3: ["10.0.0.9:7777"]}
        time.sleep(0.45)
        assert query(reg.address) == {}


def test_heartbeat_adapts_to_short_ttl(data_dir):
    """The service paces heartbeats to the TTL the registry returns in
    the REG reply, so even a sub-second TTL doesn't flap a live shard."""
    with RegistryServer(ttl_ms=800) as reg:
        with GraphService(data_dir, 0, 1, registry=reg.address):
            deadline = time.time() + 2.5  # several TTLs
            while time.time() < deadline:
                assert 0 in query(reg.address)
                time.sleep(0.1)


def test_malformed_tcp_url_fails_fast(data_dir):
    """A tcp:// string without a port must error as a bad URL, not fall
    through to the flat-file-directory branch."""
    import euler_tpu

    with pytest.raises(RuntimeError, match="bad tcp registry url"):
        GraphService(data_dir, 0, 1, registry="tcp://hostonly")
    with pytest.raises(RuntimeError, match="bad tcp registry url"):
        euler_tpu.Graph(mode="remote", registry="tcp://hostonly")


def test_end_to_end_remote_graph_via_tcp_registry(data_dir):
    """Shards on two 'hosts' + client discover each other with no shared
    directory: the multi-host mode the flat-file registry can't do."""
    import euler_tpu

    with RegistryServer() as reg:
        with GraphService(data_dir, 0, 2, registry=reg.address), \
             GraphService(data_dir, 1, 2, registry=reg.address):
            g = euler_tpu.Graph(mode="remote", registry=reg.address)
            assert g.num_shards == 2
            local = euler_tpu.Graph(directory=data_dir)
            assert g.num_nodes == local.num_nodes
            ids = g.sample_node(32, -1)
            assert len(ids) == 32
            nbr, w, t = g.sample_neighbor(ids, [0, 1], 4)
            assert nbr.shape == (32, 4)
            # feature parity through the remote path
            f_remote = g.get_dense_feature(ids, [0], [2])
            f_local = local.get_dense_feature(ids, [0], [2])
            np.testing.assert_allclose(f_remote, f_local)
            g.close()
            local.close()


def test_run_loop_shared_mode_tcp_registry(data_dir, tmp_path):
    """run_loop --graph_mode=shared --registry tcp://... : process 0 hosts
    the registry in-process and trains against its own shard."""
    from euler_tpu.run_loop import main

    port = RegistryServer(port=0)  # grab a free port number, then release
    free = port.port
    port.stop()
    rc = main([
        "--data_dir", data_dir, "--model_dir", str(tmp_path / "ck"),
        "--model", "graphsage_supervised", "--mode", "train",
        "--graph_mode", "shared", "--registry", f"tcp://127.0.0.1:{free}",
        "--num_processes", "1", "--num_epochs", "2",
        "--max_id", "16", "--feature_idx", "0", "--feature_dim", "2",
        "--label_idx", "2", "--label_dim", "3", "--train_edge_type", "0,1",
        "--all_edge_type", "0,1", "--fanouts", "3,2", "--dim", "8",
        "--batch_size", "8", "--log_steps", "2",
    ])
    assert rc == 0


def test_registry_survives_hostile_connections():
    """The TCP registry parses commands from the network; garbage at the
    framing layer AND well-framed malformed command payloads must never
    kill it or poison its state (same bar as the shard-service fuzz in
    tests/test_remote.py)."""
    import os
    import random

    with RegistryServer(host="127.0.0.1") as reg:
        rng = random.Random(1)
        for _ in range(150):
            s = socket.socket()
            s.settimeout(2)
            try:
                s.connect(("127.0.0.1", reg.port))
                mode = rng.randrange(5)
                if mode == 0:  # raw garbage at the framing layer
                    s.sendall(os.urandom(rng.randrange(1, 200)))
                elif mode == 1:  # random claimed length + partial body
                    s.sendall(
                        struct.pack("<I", rng.randrange(0, 1 << 31))
                        + os.urandom(50)
                    )
                elif mode == 2:  # well-framed random command payload
                    p = os.urandom(rng.randrange(1, 120))
                    s.sendall(struct.pack("<I", len(p)) + p)
                elif mode == 3:  # well-framed malformed REG line: the
                    # command parser itself must reject it
                    p = b"REG " + os.urandom(60) + b"\n"
                    s.sendall(struct.pack("<I", len(p)) + p)
                else:  # huge claimed length, then hang up
                    s.sendall(struct.pack("<I", 0x7FFFFFFF))
                if mode in (2, 3):  # framed commands get a reply (or a
                    # clean drop); unframed modes never will — just close
                    try:
                        s.recv(64)
                    except OSError:
                        pass
            finally:
                s.close()
        # alive, and no hostile garbage registered as a shard
        assert query(reg.address) == {}
