"""TCP shard registry: discovery without a shared filesystem.

Mirrors the reference's ZooKeeper semantics (ephemeral znodes
"<shard>#<ip:port>", zk_server_register.cc / zk_server_monitor.cc:50-64):
REG + heartbeat keeps an entry alive, entries of dead shards expire by TTL,
UNREG removes on clean stop, and a client's LIST sees only live shards.
"""

import socket
import struct
import time

import numpy as np
import pytest

from euler_tpu.graph.registry import RegistryServer, parse_tcp_url, query
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture


def _send_frame(sock, payload: bytes) -> bytes:
    sock.sendall(struct.pack("<I", len(payload)) + payload)
    (n,) = struct.unpack("<I", sock.recv(4, socket.MSG_WAITALL))
    return sock.recv(n, socket.MSG_WAITALL) if n else b""


@pytest.fixture()
def data_dir(tmp_path):
    d = str(tmp_path / "data")
    import os

    os.makedirs(d)
    write_fixture(d, num_partitions=2)
    return d


def test_parse_tcp_url():
    assert parse_tcp_url("tcp://h:91") == ("h", 91)
    assert parse_tcp_url("/some/dir") is None
    with pytest.raises(ValueError):
        parse_tcp_url("tcp://noport")


def test_registry_starts_and_lists_empty():
    with RegistryServer() as reg:
        assert reg.port > 0
        assert query(reg.address) == {}


def test_query_unreachable_raises():
    with pytest.raises(ConnectionError):
        query("tcp://127.0.0.1:1", timeout_ms=200)


def test_service_registers_and_unregisters(data_dir):
    with RegistryServer() as reg:
        svc = GraphService(data_dir, 0, 1, registry=reg.address)
        entries = query(reg.address)
        assert entries == {0: [svc.address]}
        svc.stop()
        assert query(reg.address) == {}  # UNREG on clean stop


def test_entries_expire_without_heartbeat():
    """An entry REGed once (no heartbeats) vanishes after the TTL — the
    ephemeral-znode analog for a SIGKILLed shard."""
    with RegistryServer(ttl_ms=300) as reg:
        with socket.create_connection(("127.0.0.1", reg.port), 2) as s:
            # reply advertises the TTL so registrants can pace heartbeats
            assert _send_frame(s, b"REG 3 10.0.0.9:7777") == b"OK 300"
        assert query(reg.address) == {3: ["10.0.0.9:7777"]}
        time.sleep(0.45)
        assert query(reg.address) == {}


def test_heartbeat_adapts_to_short_ttl(data_dir):
    """The service paces heartbeats to the TTL the registry returns in
    the REG reply, so even a sub-second TTL doesn't flap a live shard."""
    with RegistryServer(ttl_ms=800) as reg:
        with GraphService(data_dir, 0, 1, registry=reg.address):
            deadline = time.time() + 2.5  # several TTLs
            while time.time() < deadline:
                assert 0 in query(reg.address)
                time.sleep(0.1)


def test_malformed_tcp_url_fails_fast(data_dir):
    """A tcp:// string without a port must error as a bad URL, not fall
    through to the flat-file-directory branch."""
    import euler_tpu

    with pytest.raises(RuntimeError, match="bad tcp registry url"):
        GraphService(data_dir, 0, 1, registry="tcp://hostonly")
    with pytest.raises(RuntimeError, match="bad tcp registry url"):
        euler_tpu.Graph(mode="remote", registry="tcp://hostonly")


def test_end_to_end_remote_graph_via_tcp_registry(data_dir):
    """Shards on two 'hosts' + client discover each other with no shared
    directory: the multi-host mode the flat-file registry can't do."""
    import euler_tpu

    with RegistryServer() as reg:
        with GraphService(data_dir, 0, 2, registry=reg.address), \
             GraphService(data_dir, 1, 2, registry=reg.address):
            g = euler_tpu.Graph(mode="remote", registry=reg.address)
            assert g.num_shards == 2
            local = euler_tpu.Graph(directory=data_dir)
            assert g.num_nodes == local.num_nodes
            ids = g.sample_node(32, -1)
            assert len(ids) == 32
            nbr, w, t = g.sample_neighbor(ids, [0, 1], 4)
            assert nbr.shape == (32, 4)
            # feature parity through the remote path
            f_remote = g.get_dense_feature(ids, [0], [2])
            f_local = local.get_dense_feature(ids, [0], [2])
            np.testing.assert_allclose(f_remote, f_local)
            g.close()
            local.close()


def test_run_loop_shared_mode_tcp_registry(data_dir, tmp_path):
    """run_loop --graph_mode=shared --registry tcp://... : process 0 hosts
    the registry in-process and trains against its own shard."""
    from euler_tpu.run_loop import main

    port = RegistryServer(port=0)  # grab a free port number, then release
    free = port.port
    port.stop()
    rc = main([
        "--data_dir", data_dir, "--model_dir", str(tmp_path / "ck"),
        "--model", "graphsage_supervised", "--mode", "train",
        "--graph_mode", "shared", "--registry", f"tcp://127.0.0.1:{free}",
        "--num_processes", "1", "--num_epochs", "2",
        "--max_id", "16", "--feature_idx", "0", "--feature_dim", "2",
        "--label_idx", "2", "--label_dim", "3", "--train_edge_type", "0,1",
        "--all_edge_type", "0,1", "--fanouts", "3,2", "--dim", "8",
        "--batch_size", "8", "--log_steps", "2",
    ])
    assert rc == 0


def test_device_sampling_trains_against_tcp_registry_shards(data_dir):
    """The round-2 gap closed: device-resident sampling (adjacency +
    samplers exported to HBM) composes with a SHARDED graph — the export
    rides the kNodeWeight/kNodeType RPCs and get_full_neighbor scatters,
    so the whole-graph-in-one-process restriction is gone."""
    import euler_tpu
    import jax
    import numpy as np
    import optax

    from euler_tpu.models import SupervisedGraphSage

    with RegistryServer() as reg:
        with GraphService(data_dir, 0, 2, registry=reg.address), \
             GraphService(data_dir, 1, 2, registry=reg.address):
            g = euler_tpu.Graph(mode="remote", registry=reg.address)
            assert g.num_shards == 2
            model = SupervisedGraphSage(
                label_idx=0, label_dim=4, metapath=[[0, 1]] * 2,
                fanouts=[3, 2], dim=16, feature_idx=0, feature_dim=2,
                max_id=16, device_features=True, device_sampling=True,
            )
            assert model.device_sampling
            opt = optax.adam(0.05)
            state = model.init_state(
                jax.random.PRNGKey(0), g, g.sample_node(8, -1), opt
            )
            step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
            losses = []
            for _ in range(30):
                batch = model.device_sample_batch(g.sample_node(8, -1))
                state, loss, _ = step(state, batch)
                losses.append(float(loss))
            assert np.isfinite(losses).all()
            assert np.mean(losses[-10:]) < np.mean(losses[:10])
            g.close()


def test_node_weights_raises_when_shard_unreachable(data_dir):
    """Export queries must FAIL LOUDLY on a dead shard: a weight silently
    read as 0 would make build_node_sampler drop that shard's every node
    from the root sampler — biased training with no error anywhere."""
    import euler_tpu

    s0 = GraphService(data_dir, 0, 2)
    s1 = GraphService(data_dir, 1, 2)
    g = euler_tpu.Graph(
        mode="remote", shards=[s0.address, s1.address],
        retries=0, timeout_ms=300, quarantine_ms=100,
    )
    assert np.abs(g.node_weights([10, 11, 12])).sum() > 0  # healthy
    s1.stop()
    with pytest.raises(RuntimeError, match="unreachable"):
        g.node_weights([10, 11, 12])  # 11 routes to the dead shard
    # rows that never touch the dead shard still answer
    assert g.node_weights([10, 12]).shape == (2,)
    g.close()
    s0.stop()


def _poll(predicate, deadline_s: float, every_s: float = 0.1) -> bool:
    end = time.time() + deadline_s
    while time.time() < end:
        if predicate():
            return True
        time.sleep(every_s)
    return predicate()


def test_shard_restart_on_new_port_is_rediscovered(data_dir):
    """Mid-run re-discovery (reference ZK watch semantics,
    rpc_manager.h:77-80 / zk_server_monitor.cc:252-260): a shard that
    dies and comes back on a NEW port serves the same client again —
    quarantine alone could never do this, the old pool only knows the
    dead address."""
    import euler_tpu

    ids_shard1 = [11, 13, 15]  # (id % 2) % 2 == 1 with P=S=2
    with RegistryServer(ttl_ms=500) as reg:
        s0 = GraphService(data_dir, 0, 2, registry=reg.address)
        s1 = GraphService(data_dir, 1, 2, registry=reg.address)
        g = euler_tpu.Graph(
            mode="remote", registry=reg.address, rediscover_ms=150,
            timeout_ms=1000, quarantine_ms=300, retries=1,
        )
        baseline = g.get_dense_feature(ids_shard1, [0], [2])
        assert np.abs(baseline).sum() > 0
        old_port = s1.port
        s1.stop()
        # hold the old port so the restarted shard cannot reuse it
        blocker = socket.socket()
        blocker.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        blocker.bind(("127.0.0.1", old_port))
        blocker.listen(1)
        try:
            s1b = GraphService(data_dir, 1, 2, registry=reg.address)
            assert s1b.port != old_port
            # the client must re-learn the new address and serve shard-1
            # rows again (zeros while only the dead address is known)
            assert _poll(
                lambda: np.allclose(
                    g.get_dense_feature(ids_shard1, [0], [2]), baseline
                ),
                deadline_s=8.0,
            ), "client never re-discovered the restarted shard"
            s1b.stop()
        finally:
            blocker.close()
        g.close()
        s0.stop()


@pytest.mark.slow
def test_repeated_shard_restart_cycles_under_load(data_dir):
    """Resilience soak: four kill/restart cycles of alternating shards
    while the same client keeps querying — INCLUDING during the window
    when the shard is dead (those queries must degrade to defaults, not
    wedge or poison the pool) — then rediscovery + quarantine +
    heartbeat TTL must converge the client back to full data EVERY
    cycle, no client rebuild."""
    import euler_tpu

    with RegistryServer(ttl_ms=400) as reg:
        svcs = {
            i: GraphService(data_dir, i, 2, registry=reg.address)
            for i in range(2)
        }
        g = euler_tpu.Graph(
            mode="remote", registry=reg.address, rediscover_ms=100,
            timeout_ms=800, quarantine_ms=200, retries=1,
        )
        try:
            ids = list(range(10, 17))
            baseline = g.get_dense_feature(ids, [0], [2])
            assert np.abs(baseline).sum() > 0
            for cycle in range(4):
                s = cycle % 2
                svcs[s].stop()
                # queries against the half-dead cluster: dead-shard rows
                # degrade to zeros, the call itself must come back
                during = g.get_dense_feature(ids, [0], [2])
                assert during.shape == baseline.shape
                svcs[s] = GraphService(data_dir, s, 2, registry=reg.address)
                assert _poll(
                    lambda: np.allclose(
                        g.get_dense_feature(ids, [0], [2]), baseline
                    ),
                    deadline_s=10.0,
                ), f"client never reconverged after restart cycle {cycle}"
        finally:
            g.close()
            for svc in svcs.values():
                svc.stop()


def test_registry_restart_self_heals(data_dir):
    """The TCP registry is soft state: when it dies and comes back (same
    address), shard heartbeats re-REG on their next beat and the client's
    periodic re-LIST keeps discovering — training never needs a rebuild.
    (Blast radius documented in DEPLOY.md.)"""
    import euler_tpu

    reg = RegistryServer(ttl_ms=600)
    port = reg.port
    with GraphService(data_dir, 0, 2, registry=reg.address) as s0, \
         GraphService(data_dir, 1, 2, registry=reg.address):
        g = euler_tpu.Graph(
            mode="remote", registry=reg.address, rediscover_ms=150,
            timeout_ms=1000, quarantine_ms=300,
        )
        ids = [10, 11, 12, 13]
        baseline = g.get_dense_feature(ids, [0], [2])
        reg.stop()
        # queries keep working while the registry is down: discovery is
        # only a control plane, the data plane is direct to shards
        np.testing.assert_allclose(
            g.get_dense_feature(ids, [0], [2]), baseline
        )
        reg2 = RegistryServer(port=port, ttl_ms=600)
        # shards re-REG on their next heartbeat (redial on send failure)
        assert _poll(
            lambda: set(query(reg2.address)) == {0, 1}, deadline_s=8.0
        ), "shards never re-registered with the restarted registry"
        np.testing.assert_allclose(
            g.get_dense_feature(ids, [0], [2]), baseline
        )
        g.close()
        reg2.stop()
    del s0


def test_registry_survives_hostile_connections():
    """The TCP registry parses commands from the network; garbage at the
    framing layer AND well-framed malformed command payloads must never
    kill it or poison its state (same bar as the shard-service fuzz in
    tests/test_remote.py)."""
    import os
    import random

    with RegistryServer(host="127.0.0.1") as reg:
        rng = random.Random(1)
        for _ in range(150):
            s = socket.socket()
            s.settimeout(2)
            try:
                s.connect(("127.0.0.1", reg.port))
                mode = rng.randrange(5)
                if mode == 0:  # raw garbage at the framing layer
                    s.sendall(os.urandom(rng.randrange(1, 200)))
                elif mode == 1:  # random claimed length + partial body
                    s.sendall(
                        struct.pack("<I", rng.randrange(0, 1 << 31))
                        + os.urandom(50)
                    )
                elif mode == 2:  # well-framed random command payload
                    p = os.urandom(rng.randrange(1, 120))
                    s.sendall(struct.pack("<I", len(p)) + p)
                elif mode == 3:  # well-framed malformed REG line: the
                    # command parser itself must reject it
                    p = b"REG " + os.urandom(60) + b"\n"
                    s.sendall(struct.pack("<I", len(p)) + p)
                else:  # huge claimed length, then hang up
                    s.sendall(struct.pack("<I", 0x7FFFFFFF))
                if mode in (2, 3):  # framed commands get a reply (or a
                    # clean drop); unframed modes never will — just close
                    try:
                        s.recv(64)
                    except OSError:
                        pass
            finally:
                s.close()
        # alive, and no hostile garbage registered as a shard
        assert query(reg.address) == {}
