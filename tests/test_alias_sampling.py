"""The exact (flat-CSR alias) device sampler vs the host engine.

build_adjacency's padded slab is [N, max_observed_degree]: on power-law
graphs (real Reddit: mean degree ~490, hub degrees in the tens of
thousands) it is only buildable max_degree-TRUNCATED, which changes the
sampling support — a semantics deviation from the reference, which
draws exactly over all neighbors (CompactNode::SampleNeighbor,
euler/core/compact_node.cc:42-101). build_alias_adjacency restores the
exact semantics at O(E) memory and O(1) draws; these tests pin it to
the host engine on the fixture AND on a power-law graph where the slab
genuinely truncates (the regime it exists for).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from euler_tpu.graph import device

MAX_ID = 16


@pytest.fixture(scope="module")
def aadj(graph):
    return device.build_alias_adjacency(graph, [0, 1], MAX_ID)


def test_alias_tables_encode_exact_row_distributions(graph, aadj):
    """The Walker-table identity, checked row by row in numpy: each
    slot contributes prob/deg to its own neighbor and (1-prob)/deg to
    its alias, and the total per neighbor must equal w_i / sum(w) — an
    EXACT construction check of the native eg_build_alias_csr, not a
    statistical one."""
    ids = np.arange(MAX_ID + 1)
    nb, w, _, cnt = graph.get_full_neighbor(ids, [0, 1])
    off_host = 0
    for i, c in enumerate(cnt):
        c = int(c)
        nbrs, ws = nb[off_host:off_host + c], w[off_host:off_host + c]
        off_host += c
        o, d = int(aadj["off"][i]), int(aadj["deg"][i])
        assert d == c
        if c == 0:
            continue
        got = {}
        for s in range(o, o + c):
            p = float(aadj["prob"][s])
            assert 0.0 <= p <= 1.0 + 1e-6
            got[int(aadj["nbr"][s])] = got.get(int(aadj["nbr"][s]), 0.0) + p
            a = int(aadj["alias"][s])
            got[a] = got.get(a, 0.0) + (1.0 - p)
        total = ws.sum()
        if total <= 0:
            assert not aadj["sampleable"][i]
            continue
        assert aadj["sampleable"][i]
        for n_, ww in zip(nbrs, ws):
            assert got.get(int(n_), 0.0) / c == pytest.approx(
                ww / total, abs=1e-6
            )
        # nothing outside the true neighbor set carries mass
        assert set(got) <= set(int(x) for x in nbrs)


def test_alias_draw_matches_host_distribution(graph, aadj):
    """Same statistical bar as the slab path's distribution test."""
    node = 10
    nb, w, _, cnt = graph.get_full_neighbor([node], [0, 1])
    nb, w = nb[: int(cnt[0])], w[: int(cnt[0])]
    draws = np.asarray(
        device.sample_neighbor(
            aadj, np.full(200, node), jax.random.PRNGKey(1), 100
        )
    ).ravel()
    expect = w / w.sum()
    for n_, p in zip(nb, expect):
        freq = (draws == n_).mean()
        assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / draws.size) + 1e-3


def test_alias_default_oob_unsampleable_contract(graph, aadj):
    """Unknown ids, the default row, and zero-weight rows behave exactly
    like the slab path: default node out."""
    default = MAX_ID + 1
    out = np.asarray(
        device.sample_neighbor(
            aadj,
            jnp.asarray([default, default + 5, -2], jnp.int32),
            jax.random.PRNGKey(0),
            6,
        )
    )
    assert (out == default).all()


def test_alias_fanout_and_walk_compose(graph, aadj):
    """sample_fanout and random_walk route per-draw through the alias
    dispatch (the "off" key) inside jit."""
    roots = jnp.asarray(graph.sample_node(8, -1), jnp.int32)
    hops = jax.jit(
        lambda r, k: device.sample_fanout([aadj, aadj], r, k, [3, 2])
    )(roots, jax.random.PRNGKey(3))
    assert [int(h.shape[0]) for h in hops] == [8, 24, 48]
    assert all(int(h.max()) <= MAX_ID + 1 for h in hops)
    walk = jax.jit(
        lambda r, k: device.random_walk(aadj, r, k, 4)
    )(roots, jax.random.PRNGKey(4))
    assert walk.shape == (8, 5)


# ---- the regime the alias sampler exists for: a power-law graph whose
# slab form must truncate ----


@pytest.fixture(scope="module")
def powerlaw(tmp_path_factory):
    import euler_tpu
    from euler_tpu.datasets import build_powerlaw

    d = str(tmp_path_factory.mktemp("powerlaw"))
    build_powerlaw(
        d, num_nodes=1200, num_edges=48_000, feature_dim=4, label_dim=3,
        alpha=1.7, num_partitions=2, seed=23,
    )
    return euler_tpu.Graph(directory=d)


def test_powerlaw_graph_is_heavy_tailed(powerlaw):
    g = powerlaw
    ids = np.arange(1200)
    _, _, _, cnt = g.get_full_neighbor(ids, [0])
    # a real tail, not Poisson (whose max/mean at this scale is ~2);
    # dict-dedup of duplicate targets trims hubs hardest, so the loaded
    # ratio sits under the drawn one
    assert cnt.max() > 5 * cnt.mean() > 0


def test_alias_exact_where_slab_truncates(powerlaw):
    """THE heavy-tail gate (VERDICT r3 next-#4): on a graph whose
    padded slab must truncate (max_degree=32 << hub degree), the
    truncated slab provably narrows the hub's support while the alias
    sampler reproduces the host engine's exact distribution over ALL
    its neighbors."""
    g = powerlaw
    n = 1200
    ids = np.arange(n)
    _, _, _, cnt = g.get_full_neighbor(ids, [0])
    hub = int(np.argmax(cnt))
    hub_deg_all = int(cnt[hub])
    w_cap = 32
    assert hub_deg_all > 3 * w_cap  # the slab genuinely truncates
    nb, w, _, c = g.get_full_neighbor([hub], [0])
    nb, w = nb[: int(c[0])], w[: int(c[0])]

    with pytest.warns(UserWarning, match="truncated"):
        slab = device.build_adjacency(g, [0], n - 1, max_degree=w_cap)
    aadj = device.build_alias_adjacency(g, [0], n - 1)
    assert int(aadj["deg"][hub]) == hub_deg_all

    draws_slab = np.asarray(
        device.sample_neighbor(
            slab, np.full(128, hub), jax.random.PRNGKey(5), 64
        )
    ).ravel()
    draws_alias = np.asarray(
        device.sample_neighbor(
            aadj, np.full(128, hub), jax.random.PRNGKey(5), 64
        )
    ).ravel()
    # the truncated slab cannot leave its W heaviest; the alias draw
    # must cover (nearly all of) the full neighbor list
    assert len(np.unique(draws_slab)) <= w_cap
    assert len(np.unique(draws_alias)) > 2 * w_cap
    assert set(np.unique(draws_alias)) <= set(nb.tolist())
    # and its frequencies match the host engine's exact distribution
    expect = w / w.sum()
    total = draws_alias.size
    for n_, p in zip(nb, expect):
        freq = (draws_alias == n_).mean()
        assert abs(freq - p) < 6 * np.sqrt(p * (1 - p) / total) + 1e-3


def test_alias_memory_is_o_edges_not_o_slab(powerlaw):
    """The reason the alias form scales: bytes ~ 12/edge, vs the padded
    slab's N * max_observed_degree * 8."""
    g = powerlaw
    n = 1200
    aadj = device.build_alias_adjacency(g, [0], n - 1)
    e = aadj["nbr"].shape[0]
    alias_bytes = (
        aadj["nbr"].nbytes + aadj["alias"].nbytes + aadj["prob"].nbytes
    )
    assert alias_bytes == 12 * e
    _, _, _, cnt = g.get_full_neighbor(np.arange(n), [0])
    slab_bytes = (n + 1) * int(cnt.max()) * 8
    assert alias_bytes < slab_bytes / 3  # heavy tail: slab pays hub width


def test_model_alias_option_trains(powerlaw):
    """set_sampling_options(alias=True) swaps the model's device
    adjacencies to the exact form and a device-sampling GraphSAGE step
    still descends on the heavy-tail graph."""
    import optax

    from euler_tpu.models import SupervisedGraphSage

    g = powerlaw
    n = 1200
    model = SupervisedGraphSage(
        label_idx=0, label_dim=3, metapath=[[0]] * 2, fanouts=[3, 2],
        dim=16, feature_idx=1, feature_dim=4, max_id=n - 1,
        sigmoid_loss=False, device_features=True, device_sampling=True,
    )
    model.set_sampling_options(alias=True)
    with pytest.raises(ValueError, match="exact"):
        model.set_sampling_options(alias=True, max_degree=64)
    opt = optax.adam(0.05)
    state = model.init_state(
        jax.random.PRNGKey(0), g, g.sample_node(16, -1), opt
    )
    assert all(
        "off" in a for a in state["consts"]["adj"].values()
    ), "alias option must build CSR-alias adjacencies"
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    losses = []
    for _ in range(30):
        batch = model.device_sample_batch(g.sample_node(16, -1))
        state, loss, _ = step(state, batch)
        losses.append(float(loss))
    assert np.mean(losses[-10:]) < np.mean(losses[:10])


def test_slab_walking_models_reject_alias_option():
    """Full-neighborhood families walk the 2-D slab; the alias form has
    no slab — set_sampling_options must fail fast, not crash at trace
    time (code-review r4)."""
    from euler_tpu.models import ScalableGCN, SupervisedGCN

    gcn = SupervisedGCN(
        label_idx=0, label_dim=3, metapath=[[0], [0]], dim=8,
        max_nodes_per_hop=[16, 32], max_edges_per_hop=[64, 128],
        feature_idx=1, feature_dim=4, max_id=99,
    )
    with pytest.raises(ValueError, match="slab"):
        gcn.set_sampling_options(alias=True)
    sgcn = ScalableGCN(
        label_idx=0, label_dim=3, edge_type=[0], num_layers=2, dim=8,
        max_id=99, max_neighbors=8, feature_idx=1, feature_dim=4,
    )
    with pytest.raises(ValueError, match="slab"):
        sgcn.set_sampling_options(alias=True)


def test_powerlaw_alpha_validation():
    from euler_tpu.datasets import powerlaw_degrees

    rng = np.random.default_rng(0)
    for bad in (1.0, 0.5, -2.0):
        with pytest.raises(ValueError, match="alpha > 1"):
            powerlaw_degrees(100, 1000, bad, rng)
