"""2-D (data x model) mesh: row-sharded device tables.

The TPU-native analog of the reference's PS-sharded embedding tables
(reference tf_euler/python/utils/embedding.py:22-67): consts and Scalable
stores shard over the 'model' axis, params replicate, batch shards over
'data'. Runs on the conftest's 8-device CPU mesh.
"""

import numpy as np
import pytest

import jax


def _model(device_features=True, **over):
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
        device_features=device_features,
    )
    kw.update(over)
    return SupervisedGraphSage(**kw)


def test_mesh_shapes():
    from euler_tpu.parallel import make_mesh

    mesh = make_mesh(8, model_parallel=2)
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(6, model_parallel=4)


def test_table_rows_padded_to_model_axis(graph):
    import optax

    from euler_tpu.parallel import make_mesh, pad_tables_for_mesh

    mesh = make_mesh(8, model_parallel=4)
    model = _model()
    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(4), optax.adam(0.01)
    )
    rows = state["consts"]["features"].shape[0]
    assert rows == 18  # max_id + 2, not divisible by 4
    padded = pad_tables_for_mesh(state, mesh)
    assert padded["consts"]["features"].shape[0] == 20
    # params untouched
    assert jax.tree.structure(padded["params"]) == jax.tree.structure(
        state["params"]
    )


def test_train_model_parallel_matches_data_parallel(graph):
    """Same seed + same sampled batch: a model_parallel=2 step must produce
    the same loss as pure DP (sharding changes layout, not math)."""
    import optax

    from euler_tpu.parallel import (
        batch_sharding,
        make_mesh,
        pad_tables_for_mesh,
        replicated_sharding,
        shard_batch,
        state_sharding,
    )

    model = _model()
    opt = optax.adam(0.01)
    roots = np.asarray(graph.sample_node(8, -1))
    batch = model.sample(graph, roots)
    losses = []
    for mp in (1, 2):
        mesh = make_mesh(8, model_parallel=mp)
        state = model.init_state(jax.random.PRNGKey(0), graph, roots, opt)
        state = pad_tables_for_mesh(state, mesh)
        shardings = state_sharding(mesh, state)
        state = jax.device_put(state, shardings)
        rep = replicated_sharding(mesh)
        step = jax.jit(
            model.make_train_step(opt),
            in_shardings=(shardings, batch_sharding(mesh)),
            out_shardings=(shardings, rep, rep),
        )
        _, loss, _ = step(state, shard_batch(batch, mesh))
        losses.append(float(loss))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)


def test_train_loop_with_model_parallel(graph):
    from euler_tpu import train as train_lib
    from euler_tpu.parallel import make_mesh

    model = _model()
    state, hist = train_lib.train(
        model,
        graph,
        lambda s: graph.sample_node(8, -1),
        num_steps=12,
        mesh=make_mesh(8, model_parallel=2),
        learning_rate=0.05,
        log_every=6,
    )
    assert len(hist) == 2
    assert np.isfinite(hist[-1]["loss"])
    # scalable store model end-to-end on the 2-D mesh
    from euler_tpu.models import ScalableSage

    sm = ScalableSage(
        label_idx=2, label_dim=3, edge_type=[0, 1], fanout=3,
        num_layers=2, dim=8, max_id=16, feature_idx=0, feature_dim=2,
        device_features=True,
    )
    state2, hist2 = train_lib.train(
        sm,
        graph,
        lambda s: graph.sample_node(8, -1),
        num_steps=8,
        mesh=make_mesh(8, model_parallel=2),
        learning_rate=0.05,
        log_every=4,
    )
    assert np.isfinite(hist2[-1]["loss"])


def test_cli_scalable_checkpoint_roundtrip_model_parallel(fixture_dir, tmp_path):
    """Train a Scalable model with --model_parallel 4 (18-row tables pad to
    20) then evaluate with the same flags: restore must accept the padded
    store shapes (regression: unpadded restore template)."""
    from euler_tpu.run_loop import main

    ck = str(tmp_path / "ck")
    common = [
        "--data_dir", fixture_dir, "--model_dir", ck,
        "--model", "scalable_sage", "--device_features", "true",
        "--model_parallel", "4",
        "--max_id", "16", "--feature_idx", "0", "--feature_dim", "2",
        "--label_idx", "2", "--label_dim", "3", "--train_edge_type", "0,1",
        "--all_edge_type", "0,1", "--fanouts", "3,2", "--dim", "8",
        "--batch_size", "8", "--num_epochs", "2", "--log_steps", "4",
    ]
    assert main(common + ["--mode", "train"]) == 0
    assert main(common + ["--mode", "evaluate"]) == 0
    assert main(common + ["--mode", "save_embedding"]) == 0
