"""Self-gate for scripts/check_contracts.py (STATIC_ANALYSIS.md
"Cross-layer contracts").

Two directions, mirroring test_static_analysis.py:

- HEAD is clean: every pass runs violation-free against the real tree,
  so the analyzer gates verify.sh without a baseline file.
- Every pass FIRES: each parity pass is proven to detect a seeded drift
  fixture (renamed ABI fn, duplicated opcode, undocumented counter,
  undocumented config key, unguarded annotated field, tracked build
  artifact). A pass that silently stops matching its surface would rot
  into a vacuous gate — these pin the detection itself.

The drift fixtures copy the minimal real file set into tmp_path and
mutate it, so they stay faithful to the current tree's shapes instead
of freezing a synthetic snapshot.
"""

from __future__ import annotations

import importlib.util
import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(ROOT, "scripts")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS, name + ".py")
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault(name, mod)
    spec.loader.exec_module(mod)
    return mod


cc = _load("check_contracts")
cn = sys.modules["check_native"]  # loaded transitively by check_contracts

NATIVE_REL = os.path.join("euler_tpu", "graph", "_native")


def run_pass(root, name):
    chk = cc.Checker(root)
    cc.PASS_FUNCS[name](chk)
    chk.audit_stale_escapes({cc.RULE_OF_PASS[name]})
    return chk.violations


@pytest.fixture()
def tree(tmp_path):
    """Minimal copy of the real tree that every pass can run against."""
    root = str(tmp_path)
    native_src = os.path.join(ROOT, NATIVE_REL)
    native_dst = os.path.join(root, NATIVE_REL)
    os.makedirs(native_dst)
    for f in os.listdir(native_src):
        if f.endswith((".h", ".cc")):
            shutil.copy(os.path.join(native_src, f), native_dst)
    for rel in (
        os.path.join("euler_tpu", "graph", "native.py"),
        os.path.join("euler_tpu", "graph", "graph.py"),
        os.path.join("euler_tpu", "run_loop.py"),
        "README.md",
        "FAULTS.md",
        ".gitignore",
    ):
        dst = os.path.join(root, rel)
        os.makedirs(os.path.dirname(dst) or root, exist_ok=True)
        shutil.copy(os.path.join(ROOT, rel), dst)
    return root


def mutate(root, rel, old, new):
    path = os.path.join(root, rel)
    with open(path) as f:
        text = f.read()
    assert old in text, f"fixture drift: {old!r} not found in {rel}"
    with open(path, "w") as f:
        f.write(text.replace(old, new, 1))


# ---------------------------------------------------------------------------
# HEAD is clean
# ---------------------------------------------------------------------------


def test_head_is_clean_per_pass():
    for name in cc.PASSES:
        vs = run_pass(ROOT, name)
        assert vs == [], (
            f"pass `{name}` dirty on HEAD:\n"
            + "\n".join(f"{v.path}:{v.line}: {v.message}" for v in vs)
        )


def test_cli_exits_zero_on_head():
    r = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "check_contracts.py")],
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


def test_cli_list_passes():
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(SCRIPTS, "check_contracts.py"),
            "--list-passes",
        ],
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0
    for name in cc.PASSES:
        assert name in r.stdout


# ---------------------------------------------------------------------------
# Each pass fires on seeded drift
# ---------------------------------------------------------------------------


def test_abi_fires_on_renamed_binding(tree):
    # native.py binds a name whose symbol no longer exists; the real
    # symbol eg_create loses its binding — both directions must fire.
    mutate(
        tree,
        os.path.join("euler_tpu", "graph", "native.py"),
        "_sig(L.eg_create,",
        "_sig(L.eg_create_renamed,",
    )
    vs = run_pass(tree, "abi")
    msgs = "\n".join(v.message for v in vs)
    assert any(v.rule == "abi-parity" for v in vs)
    assert "eg_create_renamed" in msgs  # binding with no symbol
    assert "`eg_create`" in msgs  # symbol with no binding


def test_abi_fires_on_arity_mismatch(tree):
    mutate(
        tree,
        os.path.join("euler_tpu", "graph", "native.py"),
        "_sig(L.eg_remote_ping, c.c_int, [p, c.c_int])",
        "_sig(L.eg_remote_ping, c.c_int, [p])",
    )
    vs = run_pass(tree, "abi")
    assert any(
        v.rule == "abi-parity" and "eg_remote_ping" in v.message for v in vs
    )


def test_wire_fires_on_duplicate_opcode(tree):
    mutate(
        tree,
        os.path.join(NATIVE_REL, "eg_wire.h"),
        "kPing = 1,",
        "kPing = 1,\n  kPingDupe = 1,",
    )
    vs = run_pass(tree, "wire")
    assert any(
        v.rule == "wire-parity" and "duplicate" in v.message.lower()
        for v in vs
    )


def test_wire_fires_on_missing_encoder(tree):
    # drop the PingShard encoder added for exactly this contract: the
    # opcode keeps its Dispatch case but loses its client side
    mutate(
        tree,
        os.path.join(NATIVE_REL, "eg_remote.cc"),
        "req.U8(kPing);",
        "req.U8(kStats);",
    )
    vs = run_pass(tree, "wire")
    assert any(
        v.rule == "wire-parity" and "kPing" in v.message for v in vs
    )


def test_ledger_fires_on_undocumented_counter(tree):
    # delete the `crashes` glossary row: a real counter loses its docs
    path = os.path.join(tree, "FAULTS.md")
    with open(path) as f:
        lines = f.readlines()
    kept = [ln for ln in lines if not ln.startswith("| `crashes`")]
    assert len(kept) < len(lines), "fixture drift: crashes row not found"
    with open(path, "w") as f:
        f.writelines(kept)
    vs = run_pass(tree, "ledger")
    assert any(
        v.rule == "ledger-parity" and "`crashes`" in v.message for v in vs
    )


def test_ledger_fires_on_phantom_glossary_row(tree):
    mutate(
        tree,
        "FAULTS.md",
        "| `crashes`",
        "| `made_up_counter` | never |\n| `crashes`",
    )
    vs = run_pass(tree, "ledger")
    assert any(
        v.rule == "ledger-parity" and "made_up_counter" in v.message
        for v in vs
    )


def test_config_fires_on_undocumented_key(tree):
    mutate(
        tree,
        os.path.join(NATIVE_REL, "eg_admission.cc"),
        'key == "linger_ms"',
        'key == "secret_knob"',
    )
    vs = run_pass(tree, "config")
    assert any(
        v.rule == "config-parity" and "secret_knob" in v.message for v in vs
    )


def test_config_fires_on_documented_noop(tree):
    mutate(
        tree,
        "README.md",
        "| `max_conns` |",
        "| `bogus_knob` | 0 | documented but parsed nowhere |\n| `max_conns` |",
    )
    vs = run_pass(tree, "config")
    assert any(
        v.rule == "config-parity" and "bogus_knob" in v.message for v in vs
    )


def test_lock_fires_on_unguarded_field(tree):
    # a new function touching an EG_GUARDED_BY(mu_) field with no guard
    with open(os.path.join(tree, NATIVE_REL, "eg_admission.cc"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "int DriftProbe(AdmissionServer* s) {\n"
            "  return stop_ ? 1 : 0;\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert any(
        v.rule == "guarded-by" and "`stop_`" in v.message for v in vs
    )


def test_lock_clean_when_guard_held(tree):
    with open(os.path.join(tree, NATIVE_REL, "eg_admission.cc"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "int GuardedProbe(AdmissionServer* s) {\n"
            "  std::lock_guard<PosixMutex> l(mu_);\n"
            "  return stop_ ? 1 : 0;\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert vs == [], "\n".join(v.message for v in vs)


def test_lock_fires_on_unguarded_async_slot_state(tree):
    # the async completion queue's slot state is the handoff point
    # between dispatcher completion threads and the Python driver —
    # a new reader skipping async_mu_ is exactly the race the TSAN
    # round was run to exclude (SANITIZERS.md). The pass scopes field
    # uses to the annotating stem, so the probe lands in eg_async.h
    with open(os.path.join(tree, NATIVE_REL, "eg_async.h"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "int AsyncDriftProbe(AsyncSampleOp* op) {\n"
            "  return op->state == AsyncSampleOp::kDone ? 1 : 0;\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert any(
        v.rule == "guarded-by" and "`state`" in v.message for v in vs
    )


def test_lock_fires_on_unguarded_epoch_table_state(tree):
    # the epoch keep-window (held_) is the handoff between the flip
    # publisher (loader thread) and Pin() on every handler thread — a
    # new reader skipping mu_ sees a half-mutated vector mid-flip,
    # exactly the race the epoch TSAN round excludes (SANITIZERS.md).
    # EpochSnapshot refcounts (pins/superseded/drain_counted) are
    # atomics by design; held_ is the part the mutex protects.
    with open(os.path.join(tree, NATIVE_REL, "eg_epoch.h"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "inline size_t EpochDriftProbe(EpochTable* t) {\n"
            "  return t->held_.size();\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert any(
        v.rule == "guarded-by" and "`held_`" in v.message for v in vs
    )


def test_lock_fires_on_unlocked_requires_call(tree):
    # calling an EG_REQUIRES(mu) helper without holding mu
    with open(os.path.join(tree, NATIVE_REL, "eg_heat.cc"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "void DriftCall(Heat::TopTable* t) {\n"
            "  RebuildIndex(t);\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert any(
        v.rule == "guarded-by" and "RebuildIndex" in v.message for v in vs
    )


def test_lock_escape_waives_with_reason(tree):
    with open(os.path.join(tree, NATIVE_REL, "eg_admission.cc"), "a") as f:
        f.write(
            "\nnamespace eg {\n"
            "int WaivedProbe(AdmissionServer* s) {\n"
            "  // eg-lint: allow(guarded-by) startup-only read before any "
            "thread exists\n"
            "  return stop_ ? 1 : 0;\n"
            "}\n"
            "}  // namespace eg\n"
        )
    vs = run_pass(tree, "lock")
    assert vs == [], "\n".join(v.message for v in vs)


def test_artifacts_fires_on_tracked_object_and_gitignore_gap(tree):
    # eg_epoch.o is the historic stale-object incident ROADMAP recorded;
    # now that eg_epoch.cc is a real source (the snapshot-epoch engine),
    # its object is a legitimate make product — tracked-in-git is still
    # a violation, but the ORPHAN rule must stay quiet for it. A
    # sourceless object probes the orphan rule instead.
    subprocess.run(
        ["git", "init", "-q"], cwd=tree, check=True, capture_output=True
    )
    built = os.path.join(tree, NATIVE_REL, "eg_epoch.o")
    with open(built, "wb") as f:
        f.write(b"\x7fELF")
    subprocess.run(
        ["git", "add", "-f", os.path.join(NATIVE_REL, "eg_epoch.o")],
        cwd=tree,
        check=True,
        capture_output=True,
    )
    orphan = os.path.join(tree, NATIVE_REL, "eg_ghost.o")
    with open(orphan, "wb") as f:
        f.write(b"\x7fELF")
    mutate(tree, ".gitignore", ".sanitize/\n", "")
    vs = run_pass(tree, "artifacts")
    msgs = "\n".join(f"{v.path}: {v.message}" for v in vs)
    assert any(v.rule == "artifact-hygiene" for v in vs)
    assert "eg_epoch.o" in msgs  # tracked artifact
    assert "eg_ghost.o" in msgs  # orphan object (no matching .cc)
    assert ".sanitize/" in msgs  # .gitignore gap
    # the source-present object must NOT be called an orphan any more
    epoch_msgs = [
        v.message for v in vs
        if "eg_epoch.o" in v.path or "eg_epoch.o" in v.message
    ]
    assert epoch_msgs and not any("orphan" in m for m in epoch_msgs), (
        epoch_msgs
    )


def test_stale_contract_escape_is_flagged(tree):
    # an allow(config-parity) escape on a line that violates nothing
    mutate(
        tree,
        os.path.join(NATIVE_REL, "eg_remote.cc"),
        'if (cfg.count("num_partitions"))',
        '// eg-lint: allow(config-parity) testing staleness\n'
        '  if (true)  // num_partitions parse removed by fixture\n'
        '  if (cfg.count("num_partitions"))',
    )
    # the original escape above the moved parse still matches it, so
    # seed a DIFFERENT stale one: append an escaped line touching nothing
    chk = cc.Checker(tree)
    cc.PASS_FUNCS["config"](chk)
    chk.audit_stale_escapes({"config-parity"})
    assert any(
        v.rule == "allow-escape" and "stale" in v.message for v in chk.violations
    )


# ---------------------------------------------------------------------------
# check_native --escapes (satellite: stale-escape audit)
# ---------------------------------------------------------------------------


def test_check_native_escapes_clean_on_head():
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(SCRIPTS, "check_native.py"),
            "--escapes",
        ],
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "none stale" in r.stdout


def test_check_native_flags_stale_escape():
    text = (
        "#include <mutex>\n"
        "namespace eg {\n"
        "void F() {\n"
        "  // eg-lint: allow(raw-lock) suppresses nothing: no raw lock here\n"
        "  int x = 0;\n"
        "  (void)x;\n"
        "}\n"
        "}  // namespace eg\n"
    )
    stale = []
    cn.lint_text(text, "eg_fake.cc", stale_out=stale)
    assert stale, "unused own-rule escape must be reported stale"
    assert any("raw-lock" in v.message for v in stale)


def test_external_rule_escape_not_stale():
    text = (
        "namespace eg {\n"
        "void F() {\n"
        "  // eg-lint: allow(config-parity) audited by check_contracts\n"
        "  int x = 0;\n"
        "  (void)x;\n"
        "}\n"
        "}  // namespace eg\n"
    )
    stale = []
    cn.lint_text(text, "eg_fake.cc", stale_out=stale)
    assert stale == [], "contract-rule escapes are not check_native's to audit"


# ---------------------------------------------------------------------------
# sanitize.sh round records (satellite: evidence trail)
# ---------------------------------------------------------------------------


def test_sanitizer_round_records_are_wellformed():
    import json

    path = os.path.join(ROOT, "evidence", "sanitizer_rounds", "rounds.jsonl")
    assert os.path.exists(path), "no recorded sanitizer rounds (run scripts/sanitize.sh)"
    rows = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert rows
    for r in rows:
        assert r["flavor"] in ("tsan", "asan")
        assert r["verdict"] in ("PASS", "FAIL")
        assert isinstance(r["reports_first_party"], int)
    # at least one recorded PASS round of each flavor backs SANITIZERS.md
    assert any(r["flavor"] == "tsan" and r["verdict"] == "PASS" for r in rows)
