"""Full distributed stack, no shared filesystem: the multi-host story.

One test wires every distribution plane together the way a real 2-host
pod would run (reference equivalent: ZooKeeper discovery + per-worker
graph shards + TF parameter servers, run_loop.py:371-397 and
scripts/dist_tf_euler.sh):

  coordination plane  jax.distributed over a TCP coordinator
  data plane          per-process C++ graph-service shard, discovered
                      through the TCP registry (no shared directory)
  training plane      one global 4-device mesh; per-process host
                      samplers feed process-local batch shards; XLA
                      all-reduces gradients across process boundaries

Each process serves shard `pid` of the fixture, connects a REMOTE
client (so every graph query exercises partition routing + cross-shard
scatter/gather over TCP), trains SupervisedGraphSage for 3 steps, and
reports a digest of its replicated params — which must be bit-identical
across processes.
"""

import textwrap

import numpy as np

_WORKER = textwrap.dedent(
    """
    import os, sys
    pid, n_proc, coord_port, reg_url, fixture = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
        sys.argv[5],
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{coord_port}", num_processes=n_proc, process_id=pid
    )
    import numpy as np
    import euler_tpu
    from euler_tpu.graph.service import GraphService
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.parallel import (
        batch_sharding, make_mesh, replicated_sharding,
    )

    # data plane: serve THIS process's shard, register over TCP
    svc = GraphService(
        data_dir=fixture, shard_idx=pid, shard_num=n_proc,
        registry=reg_url,
    )
    # wait until EVERY shard has registered before connecting (the same
    # discovery wait run_loop does in shared mode, run_loop.py:268)
    import time
    from euler_tpu.graph import registry as registry_mod
    deadline = time.time() + 60
    while True:
        shards = registry_mod.query(reg_url)
        if len(shards) >= n_proc:
            break
        if time.time() > deadline:
            raise TimeoutError(f"only {sorted(shards)} registered")
        time.sleep(0.1)
    # remote client: discovers both shards from the TCP registry
    graph = euler_tpu.Graph(mode="remote", registry=reg_url)
    assert graph.num_nodes == 7  # sees the WHOLE graph across shards

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    mesh = make_mesh()
    assert len(jax.devices()) == 2 * n_proc
    opt = train_lib.get_optimizer("adam", 0.05)
    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), opt
    )
    rep = replicated_sharding(mesh)
    state = jax.device_put(state, rep)
    step = jax.jit(
        model.make_train_step(opt),
        in_shardings=(rep, batch_sharding(mesh)),
        out_shardings=(rep, rep, rep),
        donate_argnums=(0,),
    )
    bshard = batch_sharding(mesh)
    rng = np.random.default_rng(100 + pid)
    losses = []
    for i in range(3):
        # per-process sampling through the REMOTE client: global
        # weighted sampling proportional to per-shard weight sums
        roots = graph.sample_node(8, -1)
        local = model.sample(graph, roots)
        batch = jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(bshard, x),
            local,
        )
        state, loss, metric = step(state, batch)
        losses.append(float(loss))
    leaves = jax.tree.leaves(
        jax.tree.map(
            lambda x: np.asarray(
                jax.device_get(x.addressable_data(0))
            ).ravel(),
            state["params"],
        )
    )
    digest = float(sum(np.sum(np.abs(l)) for l in leaves))
    print(f"RESULT pid={pid} losses={losses} digest={digest:.10f}",
          flush=True)
    graph.close()
    svc.stop()
    """
)


def test_full_stack_two_process_no_shared_fs(fixture_dir):
    import ast

    from euler_tpu.graph.registry import RegistryServer
    from tests.conftest import free_port, run_worker_processes

    reg = RegistryServer(host="127.0.0.1")
    try:
        coord_port = free_port()
        outs = run_worker_processes(
            _WORKER,
            [(pid, 2, coord_port, reg.address, fixture_dir)
             for pid in range(2)],
        )
        results = [
            [l for l in out.splitlines() if l.startswith("RESULT")][0]
            for out in outs
        ]
        r0 = results[0].split("pid=0 ")[1]
        r1 = results[1].split("pid=1 ")[1]
        assert r0 == r1, f"\n{results[0]}\n{results[1]}"
        losses = ast.literal_eval(
            r0.split("losses=")[1].split(" digest=")[0]
        )
        assert all(np.isfinite(l) for l in losses)
    finally:
        reg.stop()
