"""Locality layer (ISSUE 9 / ROADMAP item 5): the degree-aware
partitioner + placement-map routing, TinyLFU-shaped cache admission, and
the client-side neighbor-list cache.

What is pinned here, mostly with EXACT arithmetic:

  * convert.py input validation — partitions < 1 and duplicate node_ids
    fail loudly instead of silently overwriting rows;
  * the greedy degree-descending placement respects its balance cap,
    places every node, and strictly beats hash partitioning's edge-cut
    on the hub-heavy fixture;
  * a corrupt / ambiguous / inconsistent placement artifact fails the
    shard start loudly — misrouting must never be silent;
  * TinyLFU admit/reject decisions against a hand-computed sketch
    state: the exact `cache_admit_rejects` ledger of a
    cold-candidate-vs-hot-victim sequence, stripe collisions derived by
    replicating the native key mix in Python;
  * exact neighbor-list cache counter arithmetic: promotion fires at
    the pinned sketch threshold, every later call is a local hit, and
    the heat fan-out ledger identity (ids_on_wire == requested -
    deduped - cache_hits) holds with the neighbor cache in the loop.
"""

import os

import numpy as np
import pytest

import euler_tpu
from euler_tpu import heat as H
from euler_tpu.graph import native
from euler_tpu.graph.convert import (
    convert_dicts,
    degree_placement,
    write_placement,
)
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.test_remote_dedup_cache import (
    NUM_PARTITIONS,
    NUM_SHARDS,
    PL_META,
    powerlaw_nodes,
)

M64 = (1 << 64) - 1


@pytest.fixture(autouse=True)
def _clean_state():
    native.reset_counters()
    H.heat_reset()
    yield
    native.reset_counters()
    H.heat_reset()


# ---------------------------------------------------------------------------
# convert.py input validation
# ---------------------------------------------------------------------------


def test_convert_rejects_partitions_below_one(tmp_path):
    for bad in (0, -3):
        with pytest.raises(ValueError, match="num_partitions"):
            convert_dicts(powerlaw_nodes(), PL_META,
                          str(tmp_path / "part"), num_partitions=bad)


def test_convert_rejects_duplicate_node_ids(tmp_path):
    nodes = powerlaw_nodes()
    nodes.append(dict(nodes[3]))  # second copy of node_id 3
    for placement in ("hash", "degree"):
        with pytest.raises(ValueError, match="duplicate node_id 3"):
            convert_dicts(nodes, PL_META, str(tmp_path / placement),
                          num_partitions=2, placement=placement)


def test_convert_rejects_unknown_placement(tmp_path):
    with pytest.raises(ValueError, match="placement"):
        convert_dicts(powerlaw_nodes(), PL_META, str(tmp_path / "part"),
                      num_partitions=2, placement="zoned")


# ---------------------------------------------------------------------------
# the degree-aware partitioner: balance + strict edge-cut win over hash
# ---------------------------------------------------------------------------


def test_degree_placement_balance_and_coverage():
    nodes = powerlaw_nodes()
    placed = degree_placement(nodes, NUM_PARTITIONS)
    assert set(placed) == {int(n["node_id"]) for n in nodes}
    assert all(0 <= p < NUM_PARTITIONS for p in placed.values())
    cap = -(-int(len(nodes) * 1.2) // NUM_PARTITIONS)
    loads = [0] * NUM_PARTITIONS
    for p in placed.values():
        loads[p] += 1
    assert max(loads) <= cap, loads


def test_degree_placement_beats_hash_edge_cut():
    """The partitioner's whole point, measured on the static graph: the
    fraction of directed edges whose endpoints land on different SHARDS
    (partition % NUM_SHARDS) must be strictly below hash partitioning's
    on the hub-heavy fixture."""
    nodes = powerlaw_nodes()
    placed = degree_placement(nodes, NUM_PARTITIONS)

    def edge_cut(shard_of):
        cross = total = 0
        for n in nodes:
            u = int(n["node_id"])
            for group in (n.get("neighbor") or {}).values():
                for dst in group:
                    total += 1
                    if shard_of(u) != shard_of(int(dst)):
                        cross += 1
        return cross / total

    hash_cut = edge_cut(lambda i: (i % NUM_PARTITIONS) % NUM_SHARDS)
    place_cut = edge_cut(lambda i: placed[i] % NUM_SHARDS)
    assert place_cut < hash_cut, (place_cut, hash_cut)


# ---------------------------------------------------------------------------
# corrupt / ambiguous placement artifacts fail the shard start loudly
# ---------------------------------------------------------------------------


@pytest.fixture()
def hash_data(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    convert_dicts(powerlaw_nodes(), PL_META, data + "/part",
                  num_partitions=NUM_PARTITIONS)
    return data


def test_service_rejects_garbage_placement_artifact(hash_data):
    with open(os.path.join(hash_data, "part.placement"), "wb") as f:
        f.write(b"JUNKJUNKJUNKJUNKJUNK")
    with pytest.raises(RuntimeError, match="magic"):
        GraphService(hash_data, 0, NUM_SHARDS)


def test_service_rejects_truncated_placement_artifact(hash_data):
    placed = {i: i % NUM_PARTITIONS for i in range(10)}
    path = os.path.join(hash_data, "part.placement")
    write_placement(path, placed, NUM_PARTITIONS)
    blob = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(blob[:-7])  # cut mid-array: count now exceeds payload
    with pytest.raises(RuntimeError, match="placement"):
        GraphService(hash_data, 0, NUM_SHARDS)


def test_service_rejects_partition_count_mismatch(hash_data):
    # artifact claims 3 partitions, the dir holds NUM_PARTITIONS (4)
    placed = {i: i % 3 for i in range(10)}
    write_placement(os.path.join(hash_data, "part.placement"), placed, 3)
    with pytest.raises(RuntimeError, match="partitions"):
        GraphService(hash_data, 0, NUM_SHARDS)


def test_service_rejects_ambiguous_placement_artifacts(hash_data):
    placed = {i: i % NUM_PARTITIONS for i in range(10)}
    write_placement(os.path.join(hash_data, "a.placement"), placed,
                    NUM_PARTITIONS)
    write_placement(os.path.join(hash_data, "b.placement"), placed,
                    NUM_PARTITIONS)
    with pytest.raises(RuntimeError, match="multiple"):
        GraphService(hash_data, 0, NUM_SHARDS)


# ---------------------------------------------------------------------------
# TinyLFU admission: exact admit/reject ledger vs a hand-computed sketch
# ---------------------------------------------------------------------------


def _fnv_spec(fids, dims):
    """Python twin of FeatureCache::SpecHash (FNV-1a over fids+dims)."""
    h = 0xCBF29CE484222325
    for v in list(fids) + list(dims):
        for b in range(4):
            h ^= (v >> (8 * b)) & 0xFF
            h = (h * 0x100000001B3) & M64
    return h


def _mix(spec, nid):
    """Python twin of FeatureCache::Mix (splitmix64 finalizer)."""
    z = (spec ^ ((nid + 0x9E3779B97F4A7C15) & M64)) & M64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & M64
    return (z ^ (z >> 31)) & M64


def _stripe_colliding_ids(spec, stripe, want, limit=100000):
    out = []
    for nid in range(limit):
        if _mix(spec, nid) % 16 == stripe:
            out.append(nid)
            if len(out) == want:
                return out
    raise AssertionError("not enough colliding ids")


@pytest.fixture(scope="module")
def lfu_cluster(tmp_path_factory):
    """Single-shard cluster over a wide id space (ids 0..2999), so
    stripe-colliding id sets exist for any spec."""
    from scripts.remote_bench import PL_META as BENCH_META
    from scripts.remote_bench import powerlaw_fixture_nodes

    data = str(tmp_path_factory.mktemp("lfu_data"))
    convert_dicts(powerlaw_fixture_nodes(3000, 6, 8), BENCH_META,
                  data + "/part", num_partitions=1)
    svc = GraphService(data, 0, 1)
    yield svc
    svc.stop()


def test_tinylfu_exact_admit_reject_ledger(lfu_cluster):
    """Drive ONE cache stripe to capacity with hot rows (sketch est 3
    each), then offer cold candidates. Hand-computed TinyLFU verdicts:
      * candidate est 1 vs victim est 3  -> reject
      * candidate est 2, 3 vs victim 3  -> reject (admission is STRICT:
        ties keep the resident row, which already paid its fetch)
      * candidate est 4 vs victim 3     -> admit (one victim evicted)
    cache_admit_rejects must equal exactly the rejects above, and the
    admitted row must hit on its next access."""
    # 1 MB budget / 16 stripes = 65536 B per stripe; a 512-float row
    # costs 512*4 + 96 = 2144 B, so exactly 30 rows fill a stripe
    g = Graph(mode="remote", shards=[lfu_cluster.address], retries=2,
              timeout_ms=5000, feature_cache_mb=1, neighbor_cache_mb=0)
    try:
        spec = _fnv_spec([0], [512])
        ids = _stripe_colliding_ids(spec, stripe=0, want=32)
        warm, x, y = ids[:30], ids[30], ids[31]
        euler_tpu.telemetry_reset()
        H.heat_reset()
        native.reset_counters()
        warm_arr = np.array(warm, dtype=np.int64)
        for _ in range(3):  # each call feeds every unique id once
            g.get_dense_feature(warm_arr, [0], [512])
        c = native.counters()
        assert c["cache_misses"] == 30, c   # cold fill
        assert c["cache_hits"] == 60, c     # calls 2-3 all hit
        assert c["cache_admit_rejects"] == 0, c
        # cold candidate X: est 1 < victim est 3 -> rejected, once
        g.get_dense_feature(np.array([x], dtype=np.int64), [0], [512])
        c = native.counters()
        assert c["cache_admit_rejects"] == 1, c
        # warming candidate Y: est 1, 2, 3 rejected (strict >), est 4
        # admitted; the 5th access is a hit served from the cache
        for _ in range(4):
            g.get_dense_feature(np.array([y], dtype=np.int64), [0], [512])
        c = native.counters()
        assert c["cache_admit_rejects"] == 4, c  # 1 (X) + 3 (Y)
        native.reset_counters()
        g.get_dense_feature(np.array([y], dtype=np.int64), [0], [512])
        c = native.counters()
        assert c["cache_hits"] == 1 and c["cache_misses"] == 0, c
    finally:
        g.close()


def test_fifo_policy_restores_unconditional_admission(lfu_cluster):
    """cache_policy=fifo: the same cold-candidate sequence admits every
    row (evicting hot victims) and never counts a rejection."""
    g = Graph(mode="remote", shards=[lfu_cluster.address], retries=2,
              timeout_ms=5000, feature_cache_mb=1, neighbor_cache_mb=0,
              cache_policy="fifo")
    try:
        spec = _fnv_spec([0], [512])
        ids = _stripe_colliding_ids(spec, stripe=0, want=31)
        euler_tpu.telemetry_reset()
        H.heat_reset()
        native.reset_counters()
        warm = np.array(ids[:30], dtype=np.int64)
        for _ in range(3):
            g.get_dense_feature(warm, [0], [512])
        g.get_dense_feature(np.array([ids[30]], dtype=np.int64), [0],
                            [512])
        c = native.counters()
        assert c["cache_admit_rejects"] == 0, c
        # the candidate displaced the FIFO head: re-requesting it hits
        native.reset_counters()
        g.get_dense_feature(np.array([ids[30]], dtype=np.int64), [0],
                            [512])
        assert native.counters()["cache_hits"] == 1
    finally:
        g.close()


def test_bad_cache_policy_rejected(lfu_cluster):
    with pytest.raises(RuntimeError, match="cache_policy"):
        Graph(mode="remote", shards=[lfu_cluster.address], retries=1,
              timeout_ms=2000, cache_policy="lru")


def test_cache_policy_rejected_on_local_mode(tmp_path):
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), cache_policy="fifo")
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), neighbor_cache_mb=8)
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), placement=True)


# ---------------------------------------------------------------------------
# neighbor-list cache: exact promotion/hit arithmetic + ledger identity
# ---------------------------------------------------------------------------


@pytest.fixture()
def nbr_cluster(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    convert_dicts(powerlaw_nodes(), PL_META, data + "/part",
                  num_partitions=NUM_PARTITIONS)
    svcs = [GraphService(data, s, NUM_SHARDS) for s in range(NUM_SHARDS)]
    local = Graph(directory=data)
    yield local, svcs
    local.close()
    for s in svcs:
        s.stop()


def test_neighbor_cache_exact_promotion_arithmetic(nbr_cluster):
    """One hub sampled repeatedly: each call feeds the sketch once (one
    unique id), so the estimate after call k is exactly k. Promotion is
    pinned at est >= 8 (kNbrPromoteMinFreq): calls 1..8 miss (call 8
    fetches the full slice), calls 9..12 sample locally — so over 12
    calls nbr_cache_misses == 8 and nbr_cache_hits == 4, and the heat
    fan-out identity holds with the neighbor cache in the loop."""
    local, svcs = nbr_cluster
    g = Graph(mode="remote", shards=[s.address for s in svcs], retries=2,
              timeout_ms=5000)
    try:
        euler_tpu.telemetry_reset()
        H.heat_reset()
        native.reset_counters()
        ids = np.full(50, 0, dtype=np.int64)  # hub 0, duplicated
        for _ in range(12):
            g.sample_neighbor(ids, [0, 1], 4)
        c = native.counters()
        assert c["nbr_cache_misses"] == 8, c
        assert c["nbr_cache_hits"] == 4, c
        f = H.heat_json()["fanout"]["sample_neighbor"]
        assert f["ids_on_wire"] == (f["ids_requested"] - f["ids_deduped"]
                                    - f["cache_hits"]), f
        assert f["cache_hits"] == 4, f
    finally:
        g.close()


def test_neighbor_cache_hits_match_engine_distribution(nbr_cluster):
    """Locally-sampled draws (cache hits) must match the host engine's
    neighbor distribution — the sampler-distribution half of the
    acceptance criteria — and duplicate rows stay independent."""
    local, svcs = nbr_cluster
    g = Graph(mode="remote", shards=[s.address for s in svcs], retries=2,
              timeout_ms=5000)
    try:
        H.heat_reset()
        native.reset_counters()
        hub = 0
        ids = np.full(200, hub, dtype=np.int64)
        for _ in range(9):  # past the promotion point: draws now local
            g.sample_neighbor(ids, [0, 1], 4)
        assert native.counters()["nbr_cache_hits"] >= 1
        r_nbr, r_w, r_t = g.sample_neighbor(ids, [0, 1], 8)
        l_nbr, _, _ = local.sample_neighbor(ids, [0, 1], 8)
        r_nbr, l_nbr = np.asarray(r_nbr), np.asarray(l_nbr)
        distinct = {tuple(row) for row in r_nbr.tolist()}
        assert len(distinct) > 1, "duplicate rows shared one sample"
        values = np.unique(np.concatenate([r_nbr.ravel(), l_nbr.ravel()]))
        for v in values:
            rf = (r_nbr == v).mean()
            lf = (l_nbr == v).mean()
            assert abs(rf - lf) < 0.05, (v, rf, lf)
        # weights/types carried through the local draw match the
        # engine's vocabulary for this hub
        l_full = local.get_full_neighbor([hub], [0, 1])
        assert set(np.asarray(r_nbr).ravel()) <= set(
            np.asarray(l_full[0]).tolist()
        )
    finally:
        g.close()


def test_neighbor_cache_disabled_stays_on_wire(nbr_cluster):
    local, svcs = nbr_cluster
    g = Graph(mode="remote", shards=[s.address for s in svcs], retries=2,
              timeout_ms=5000, neighbor_cache_mb=0)
    try:
        H.heat_reset()
        native.reset_counters()
        ids = np.full(50, 0, dtype=np.int64)
        for _ in range(12):
            g.sample_neighbor(ids, [0, 1], 4)
        c = native.counters()
        assert c["nbr_cache_hits"] == 0, c
        assert c["nbr_cache_misses"] == 0, c  # disabled: never probed
    finally:
        g.close()
