"""`.dat` format interoperability with the reference converter.

Runs the reference's own json2dat (tools/bin/json2dat.py, loaded with a
py2->py3 struct shim) on its checked-in testdata graph and asserts our
converter produces byte-identical output — the format contract that lets
reference-converted datasets load directly into this engine (and vice
versa). The reference code executes in a SUBPROCESS, not in the test
process: the mount is untrusted content, and isolation bounds what it can
reach (it still shares the filesystem/user, but cannot tamper with the
asserting interpreter). Skips if the read-only reference checkout is not
mounted.
"""

import json
import os
import subprocess
import sys

import pytest

REF = "/root/reference"
TESTDATA = os.path.join(REF, "tf_euler/python/euler_ops/testdata")
REF_CONVERTER = os.path.join(REF, "tools/bin/json2dat.py")

pytestmark = pytest.mark.skipif(
    not os.path.exists(REF_CONVERTER), reason="reference not mounted"
)

# Runs in a subprocess: exec the py2-era reference converter under py3
# (drop py2 print statements — only in its CLI help/usage paths, not the
# packing logic — and shim struct.pack to encode str for 's' formats),
# then convert argv[2]/argv[3] into argv[4].
_DRIVER = r"""
import struct as _struct
import sys

converter_path, meta_path, input_path, out_path = sys.argv[1:5]


class _PackShim:
    def __getattr__(self, name):
        return getattr(_struct, name)

    @staticmethod
    def pack(fmt, *args):
        coerced = [a.encode() if isinstance(a, str) else a for a in args]
        return _struct.pack(fmt, *coerced)


src = open(converter_path).read()
lines = []
skip_until_quote = False
for line in src.splitlines():
    stripped = line.strip()
    if skip_until_quote:
        if "'''" in stripped:
            skip_until_quote = False
        continue
    if stripped.startswith("print '''"):
        skip_until_quote = "'''" not in stripped[len("print '''"):]
        indent = line[: len(line) - len(line.lstrip())]
        lines.append(indent + "pass  # py2 print dropped")
        continue
    if stripped.startswith("print ") and not stripped.startswith("print ("):
        indent = line[: len(line) - len(line.lstrip())]
        lines.append(indent + "pass  # py2 print dropped")
        continue
    lines.append(line)

module = type(sys)("ref_json2dat")
module.struct = _PackShim()
exec(compile("\n".join(lines), converter_path, "exec"), module.__dict__)
module.struct = _PackShim()  # its own `import struct` rebound the global
module.Converter(meta_path, input_path, out_path).do()
"""


def _run_reference_converter(out_path: str) -> None:
    subprocess.run(
        [
            sys.executable, "-c", _DRIVER, REF_CONVERTER,
            os.path.join(TESTDATA, "meta.json"),
            os.path.join(TESTDATA, "graph.json"),
            out_path,
        ],
        check=True, timeout=60, capture_output=True,
    )


def test_dat_bytes_identical_to_reference_converter(tmp_path):
    ref_out = str(tmp_path / "ref.dat")
    _run_reference_converter(ref_out)
    ref_bytes = open(ref_out, "rb").read()
    assert len(ref_bytes) > 0

    from euler_tpu.graph.convert import convert

    ours = convert(
        os.path.join(TESTDATA, "meta.json"),
        os.path.join(TESTDATA, "graph.json"),
        str(tmp_path / "ours"),
        1,
    )
    our_bytes = open(ours[0], "rb").read()
    assert our_bytes == ref_bytes


def test_reference_testdata_loads_into_engine(tmp_path):
    """The reference's 6-node fixture graph converts and loads; spot-check
    structure against the JSON source."""
    import euler_tpu

    ours = euler_tpu.convert(
        os.path.join(TESTDATA, "meta.json"),
        os.path.join(TESTDATA, "graph.json"),
        str(tmp_path / "g"),
        1,
    )
    meta = json.load(open(os.path.join(TESTDATA, "meta.json")))
    with open(os.path.join(TESTDATA, "graph.json")) as f:
        nodes = [json.loads(line) for line in f if line.strip()]
    g = euler_tpu.Graph(files=[ours[0]])
    assert g.num_nodes == len(nodes)
    assert g.node_type_num == int(meta["node_type_num"])
    assert g.edge_type_num == int(meta["edge_type_num"])
    for node in nodes:
        nid = int(node["node_id"])
        want = sorted(
            int(k)
            for et in node["neighbor"]
            for k in node["neighbor"][et]
        )
        nbr, w, t, counts = g.get_full_neighbor(
            [nid], list(range(g.edge_type_num)), sorted=True
        )
        assert sorted(int(x) for x in nbr) == want
        types = g.node_types([nid])
        assert int(types[0]) == int(node["node_type"])
