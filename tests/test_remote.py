"""Distributed (remote) mode tests: real local shards, no mocks.

Strategy per SURVEY §4: the reference exercises multi-shard semantics with
fake RPC layers (reference euler/client/graph_test.cc:547-560 MockRpcClient)
plus one real-coordination e2e (rpc_client_end2end_test.cc launching a local
ZooKeeper). Our wire stack is cheap enough to spawn REAL service shards on
ephemeral localhost ports for every test, so the whole matrix runs
in-process: scatter/gather merge order, weighted cross-shard global
sampling, partition routing, replica failover, registry lifecycle.
"""

import os

import numpy as np
import pytest

from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import TOPOLOGY, write_fixture

NUM_SHARDS = 2
NUM_PARTITIONS = 4


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """(local graph, remote graph, services, registry dir)."""
    data = str(tmp_path_factory.mktemp("remote_data"))
    write_fixture(data, num_partitions=NUM_PARTITIONS)
    reg = str(tmp_path_factory.mktemp("registry"))
    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    local = Graph(directory=data)
    remote = Graph(mode="remote", registry=reg)
    yield local, remote, services, reg
    for s in services:
        s.stop()


def deep_eq(a, b):
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(deep_eq(x, y) for x, y in zip(a, b))
    return np.array_equal(np.asarray(a), np.asarray(b))


def test_discovery_and_meta(cluster):
    local, remote, services, _ = cluster
    assert remote.num_shards == NUM_SHARDS
    assert remote.num_partitions == NUM_PARTITIONS
    assert remote.num_nodes == local.num_nodes
    assert remote.num_edges == local.num_edges
    np.testing.assert_allclose(
        remote.type_weight_sums(), local.type_weight_sums()
    )
    np.testing.assert_allclose(
        remote.type_weight_sums(edges=True),
        local.type_weight_sums(edges=True),
    )


def test_sharded_loading_is_disjoint_and_complete(cluster):
    local, remote, _, _ = cluster
    # Each shard owns partitions p % num_shards == shard_idx
    # (reference euler/core/graph_engine.cc:90-107); routing
    # (id % P) % S covers every node exactly once.
    ids = sorted(TOPOLOGY)
    owned = [
        {i for i in ids if (i % NUM_PARTITIONS) % NUM_SHARDS == s}
        for s in range(NUM_SHARDS)
    ]
    assert set().union(*owned) == set(ids)
    assert sum(len(o) for o in owned) == len(ids)
    # and the remote view resolves every id (routing is consistent)
    assert (np.asarray(remote.node_types(ids)) >= 0).all()


def test_node_types_routing(cluster):
    local, remote, _, _ = cluster
    ids = np.array(sorted(TOPOLOGY) + [999, 12345], dtype=np.int64)
    np.testing.assert_array_equal(
        remote.node_types(ids), local.node_types(ids)
    )


def test_full_neighbor_merge_matches_local(cluster):
    local, remote, _, _ = cluster
    ids = np.array(sorted(TOPOLOGY) * 3, dtype=np.int64)
    for sorted_flag in (False, True):
        l = local.get_full_neighbor(ids, [0, 1], sorted=sorted_flag)
        r = remote.get_full_neighbor(ids, [0, 1], sorted=sorted_flag)
        assert deep_eq(l, r)


def test_features_match_local(cluster):
    local, remote, _, _ = cluster
    ids = np.array(sorted(TOPOLOGY) + [999], dtype=np.int64)
    np.testing.assert_allclose(
        remote.get_dense_feature(ids, [0, 1], [2, 1]),
        local.get_dense_feature(ids, [0, 1], [2, 1]),
    )
    assert deep_eq(
        remote.get_sparse_feature(ids, [0, 1]),
        local.get_sparse_feature(ids, [0, 1]),
    )
    assert deep_eq(
        remote.get_binary_feature(ids, [0]),
        local.get_binary_feature(ids, [0]),
    )


def test_edge_features_match_local(cluster):
    local, remote, _, _ = cluster
    src, dst, t = local.sample_edge(64, -1)
    np.testing.assert_allclose(
        remote.get_edge_dense_feature(src, dst, t, [0], [1]),
        local.get_edge_dense_feature(src, dst, t, [0], [1]),
    )
    assert deep_eq(
        remote.get_edge_sparse_feature(src, dst, t, [0]),
        local.get_edge_sparse_feature(src, dst, t, [0]),
    )


def test_topk_matches_local(cluster):
    local, remote, _, _ = cluster
    ids = np.array(sorted(TOPOLOGY), dtype=np.int64)
    assert deep_eq(
        remote.get_top_k_neighbor(ids, [0, 1], 3),
        local.get_top_k_neighbor(ids, [0, 1], 3),
    )


def test_sample_neighbor_validity(cluster):
    _, remote, _, _ = cluster
    ids = np.array([10, 12, 14, 16] * 8, dtype=np.int64)
    nbr, w, t = remote.sample_neighbor(ids, [0, 1], 4)
    nbr = np.asarray(nbr).reshape(len(ids), 4)
    for i, nid in enumerate(ids):
        _, _, groups = TOPOLOGY[int(nid)]
        allowed = set().union(*[set(g) for g in groups.values()]) or {-1}
        assert set(nbr[i].tolist()) <= allowed


def test_cross_shard_weighted_sample_node_distribution(cluster):
    local, remote, _, _ = cluster
    # Empirical frequency ~ node weight (reference
    # compact_weighted_collection_test.cc technique), across shards.
    n = 40000
    ids = np.asarray(remote.sample_node(n, -1))
    weights = {nid: w for nid, (t, w, _) in TOPOLOGY.items()}
    total = sum(weights.values())
    counts = {nid: (ids == nid).sum() / n for nid in weights}
    for nid, w in weights.items():
        assert counts[nid] == pytest.approx(w / total, abs=0.02), nid
    # typed sampling stays within the type
    t0 = np.asarray(remote.sample_node(2000, 0))
    types = {nid: t for nid, (t, w, _) in TOPOLOGY.items()}
    assert all(types[int(i)] == 0 for i in t0)


def test_cross_shard_weighted_sample_edge_distribution(cluster):
    _, remote, _, _ = cluster
    src, dst, t = remote.sample_edge(20000, -1)
    src, dst, t = np.asarray(src), np.asarray(dst), np.asarray(t)
    # every sampled edge exists with the right type
    for s, d, ty in zip(src[:200], dst[:200], t[:200]):
        assert int(d) in TOPOLOGY[int(s)][2][int(ty)]
    # empirical edge frequency ~ edge weight
    ew = {}
    for s, (_, _, groups) in TOPOLOGY.items():
        for ty, nbrs in groups.items():
            for d, w in nbrs.items():
                ew[(s, d, ty)] = w
    total = sum(ew.values())
    for (s, d, ty), w in ew.items():
        freq = ((src == s) & (dst == d) & (t == ty)).mean()
        assert freq == pytest.approx(w / total, abs=0.02)


def test_sample_node_with_src_typed(cluster):
    local, remote, _, _ = cluster
    src = np.array([10, 11, 12, 13], dtype=np.int64)  # types 0,1,0,1
    out = np.asarray(remote.sample_node_with_src(src, 64))
    types = {nid: t for nid, (t, w, _) in TOPOLOGY.items()}
    src_types = [types[int(s)] for s in src]
    for i, st in enumerate(src_types):
        assert all(types[int(x)] == st for x in out[i])


def test_random_walk_remote(cluster):
    local, remote, _, _ = cluster
    ids = np.array([10, 12, 14, 16] * 4, dtype=np.int64)
    for p, q in [(1.0, 1.0), (4.0, 0.25)]:
        w = np.asarray(remote.random_walk(ids, [0, 1], 4, p=p, q=q))
        assert w.shape == (len(ids), 5)
        np.testing.assert_array_equal(w[:, 0], ids)
        # every transition is a real edge (or a default fill after dead end)
        for row in w:
            for a, b in zip(row[:-1], row[1:]):
                if a < 0 or b < 0:
                    continue
                _, _, groups = TOPOLOGY[int(a)]
                nbrs = set().union(*[set(g) for g in groups.values()])
                assert int(b) in nbrs, (a, b)


def test_fanout_remote(cluster):
    _, remote, _, _ = cluster
    ids = np.array([10, 12, 16], dtype=np.int64)
    hop_ids, hop_w, hop_t = remote.sample_fanout(ids, [[0, 1], [0, 1]], [3, 2])
    assert [len(h) for h in hop_ids] == [3, 9, 18]
    assert [len(w) for w in hop_w] == [9, 18]


def test_replica_failover(cluster, tmp_path):
    local, _, services, _ = cluster
    # shard 0: one dead replica + the live one; retry + quarantine must
    # transparently reroute (reference rpc_client.cc:29-49 MoveToBadHost).
    dead = "127.0.0.1:9"  # discard port: connection refused immediately
    shards = [[dead, services[0].address], [services[1].address]]
    r = Graph(mode="remote", shards=shards, retries=3, timeout_ms=2000)
    ids = np.array(sorted(TOPOLOGY), dtype=np.int64)
    np.testing.assert_array_equal(r.node_types(ids), local.node_types(ids))
    # after the first failure the bad host is quarantined: repeat calls work
    for _ in range(5):
        np.testing.assert_allclose(
            r.get_dense_feature(ids, [0], [2]),
            local.get_dense_feature(ids, [0], [2]),
        )


def test_registry_lifecycle(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    svc = GraphService(data, 0, 1, registry=reg)
    files = os.listdir(reg)
    assert len(files) == 1 and files[0].startswith("0#")
    svc.stop()
    assert os.listdir(reg) == []  # ephemeral-znode-style cleanup


def test_service_survives_malformed_and_hostile_frames(fixture_dir):
    """The shard service parses frames from the network; malformed or
    adversarial requests must get an error reply (or a dropped
    connection) — never kill the service or force a huge allocation.
    Covers: random garbage, huge claimed lengths, truncated frames, and
    well-framed requests whose count fields demand multi-GB results
    (opcodes: 3=kSampleNode, 6=kSampleNeighbor, 9=kDenseFeature —
    euler_tpu/graph/_native/eg_wire.h:27-35)."""
    import os
    import random
    import socket
    import struct

    import euler_tpu
    from euler_tpu.graph.service import GraphService

    reg = fixture_dir + "_fuzz_reg"
    os.makedirs(reg, exist_ok=True)
    svc = GraphService(
        data_dir=fixture_dir, shard_idx=0, shard_num=1, registry=reg
    )
    try:
        port = int(svc.address.rsplit(":", 1)[1])

        def send_raw(data, expect_reply=False):
            s = socket.socket()
            s.settimeout(3)
            try:
                s.connect(("127.0.0.1", port))
                s.sendall(data)
                if expect_reply:
                    hdr = s.recv(4)
                    assert len(hdr) == 4, "service dropped a valid frame"
                    (ln,) = struct.unpack("<I", hdr)
                    body = b""
                    while len(body) < ln:
                        chunk = s.recv(ln - len(body))
                        assert chunk, "short reply"
                        body += chunk
                    return body
                # no reply expected: just close — the server either
                # errored the frame or is still waiting for bytes that
                # will never come; both paths are exercised by the
                # post-fuzz liveness check
            finally:
                s.close()

        def frame(payload):
            return struct.pack("<I", len(payload)) + payload

        # hostile-but-well-framed: result sizes in the terabytes
        int_max = 2**31 - 1
        hostile = [
            # kSampleNode count=INT_MAX
            frame(struct.pack("<Bii", 3, int_max, -1)),
            # kSampleNeighbor: 1 id, 1 etype, count=INT_MAX
            frame(
                struct.pack("<Bq", 6, 1) + struct.pack("<Q", 10)
                + struct.pack("<q", 1) + struct.pack("<i", 0)
                + struct.pack("<iQ", int_max, 0)
            ),
            # kDenseFeature: 1 id, 1 fid, dims=[INT_MAX]
            frame(
                struct.pack("<Bq", 9, 1) + struct.pack("<Q", 10)
                + struct.pack("<q", 1) + struct.pack("<i", 0)
                + struct.pack("<q", 1) + struct.pack("<i", int_max)
            ),
        ]
        for payload in hostile:
            body = send_raw(payload, expect_reply=True)
            assert body[0] == 1, "hostile request must get error status"

        # garbage fuzz: random frames, huge lengths, truncations
        rng = random.Random(0)
        for _ in range(200):
            mode = rng.randrange(4)
            if mode == 0:
                send_raw(
                    struct.pack("<I", rng.randrange(0, 1 << 31))
                    + os.urandom(rng.randrange(0, 200))
                )
            elif mode == 1:
                send_raw(struct.pack("<I", 0))
            elif mode == 2:
                send_raw(struct.pack("<I", 0x7FFFFFFF))
            else:
                send_raw(frame(os.urandom(rng.randrange(1, 100))))

        # the service must still answer a real client correctly
        g = euler_tpu.Graph(mode="remote", registry=reg)
        ids = g.sample_node(16, -1)
        assert set(int(i) for i in ids) <= set(range(10, 17))
        g.close()
    finally:
        svc.stop()
