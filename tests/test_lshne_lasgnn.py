"""LsHNE + LasGNN tests on the heterogeneous fixture graph."""

import numpy as np

from euler_tpu import train as train_lib


def test_lshne_trains(graph):
    from euler_tpu.models import LsHNE

    # Two views; view 0 walks edge-type 0 then 1 then 0 (metapath), view 1
    # walks type {0,1} uniformly. Fixture sparse feature slot 0 holds ids
    # (max value 17), slot 1 holds constant 7.
    model = LsHNE(
        node_type=-1,
        path_patterns=[
            [[[0], [1], [0]]],
            [[[0, 1], [0, 1], [0, 1]]],
        ],
        max_id=16,
        dim=8,
        sparse_feature_dims=[32, 32],
        feature_ids=[0, 1],
        num_negs=4,
        src_type_num=2,
    )

    def source_fn(step):
        return graph.sample_node(8, -1)

    state, hist = train_lib.train(
        model, graph, source_fn, num_steps=10, learning_rate=0.01,
        log_every=5,
    )
    assert np.isfinite(hist[-1]["loss"])
    assert 0.0 < hist[-1]["mrr"] <= 1.0
    emb = train_lib.save_embedding(model, graph, 16, state, batch_size=8)
    assert emb.shape == (17, 8)
    assert np.isfinite(emb).all()


def test_lshne_mask_excludes_dead_pairs(graph):
    from euler_tpu.models import LsHNE

    model = LsHNE(
        node_type=-1,
        path_patterns=[[[[0], [1]]]],
        max_id=16,
        dim=4,
        sparse_feature_dims=[32],
        feature_ids=[0],
        num_negs=2,
        src_type_num=2,
    )
    # node 15 has no neighbors: its walks are all -1 -> every pair masked
    batch = model.sample(graph, np.array([15, 15]))
    assert batch["views"][0]["mask"].sum() == 0
    # node 16 has neighbors: some pairs valid
    batch = model.sample(graph, np.array([16, 16]))
    assert batch["views"][0]["mask"].sum() > 0


def test_lasgnn_trains(graph):
    from euler_tpu.models import LasGNN

    model = LasGNN(
        metapaths_of_groups=[
            [[[0], [0, 1]]],              # target group: 1 metapath
            [[[0], [0, 1]], [[1], [0, 1]]],  # context group: 2 metapaths
        ],
        fanouts=[2, 2],
        dim=8,
        feature_ixs=[0, 1],
        feature_dims=[32, 32],
        group_sizes=[1, 2],
        max_id=16,
    )

    rng = np.random.default_rng(0)

    def source_fn(step):
        ids = graph.sample_node(8, -1)
        ctx = graph.sample_node(16, -1).reshape(8, 2)
        return {
            "label": rng.integers(0, 2, (8, 1)).astype(np.float32),
            "groups": [ids.reshape(8, 1), ctx],
        }

    state, hist = train_lib.train(
        model, graph, source_fn, num_steps=8, learning_rate=0.01,
        log_every=4,
    )
    assert np.isfinite(hist[-1]["loss"])
    assert 0.0 <= hist[-1]["auc"] <= 1.0


def test_auc_metric():
    import jax.numpy as jnp

    from euler_tpu.nn import metrics

    # perfectly separable scores -> AUC 1
    labels = jnp.array([0, 0, 1, 1])
    scores = jnp.array([0.1, 0.2, 0.8, 0.9])
    counts = metrics.auc_counts(labels, scores)
    assert abs(metrics.auc_from_counts(counts) - 1.0) < 1e-6
    # random scores -> AUC ~0.5 over accumulation
    rng = np.random.default_rng(0)
    acc = np.zeros((2, metrics.AUC_BINS))
    for _ in range(20):
        lab = jnp.asarray(rng.integers(0, 2, 256))
        sc = jnp.asarray(rng.random(256))
        acc = acc + np.asarray(metrics.auc_counts(lab, sc))
    assert abs(metrics.auc_from_counts(acc) - 0.5) < 0.03
    # anti-separable -> ~0
    counts = metrics.auc_counts(
        jnp.array([1, 1, 0, 0]), jnp.array([0.1, 0.2, 0.8, 0.9])
    )
    assert metrics.auc_from_counts(counts) < 1e-6


def test_sparse_sage_encoder_public():
    """SparseSageEncoder is a first-class public encoder (reference
    encoders.py:522-560): standalone towers own their embedding tables;
    shared_embeddings ties tables across towers (LasGNN's pattern)."""
    import jax
    import jax.numpy as jnp

    from euler_tpu.nn import SparseSageEncoder

    fanouts, dim, fdims = (3, 2), 8, (11, 5)
    enc = SparseSageEncoder(fanouts, dim, feature_dims=fdims)
    B = 4
    sizes = [B, B * 3, B * 3 * 2]
    hops = [
        [
            (jnp.ones((n, 2), jnp.int32), jnp.ones((n, 2)))
            for _ in fdims
        ]
        for n in sizes
    ]
    params = enc.init(jax.random.PRNGKey(0), hops)
    out = enc.apply(params, hops)
    assert out.shape == (B, dim)
    assert jnp.isfinite(out).all()
    # per-slot tables sized feature_dim + 2 at embedding_dim 16
    flat = jax.tree_util.tree_leaves_with_path(params)
    emb_shapes = sorted(
        tuple(x.shape) for p, x in flat
        if any("sparse_embeddings" in str(k) for k in p)
    )
    assert emb_shapes == [(7, 16), (13, 16)]
