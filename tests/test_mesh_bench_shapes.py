"""Multi-device execution at BENCH-LIKE shapes.

The driver dryrun (__graft_entry__.dryrun_multichip) proves the
mesh/jit/sharding composition compiles and runs — at a 16-node fixture
graph with batch 2*n_devices. These tests run the SAME composition at
the reddit recipe's per-step shapes (batch 1000, fanouts [4,4], dim 64,
feature_dim 602 — reference examples/sage_reddit.py:80-97) on the
conftest's 8-device CPU mesh, so a sharding bug that only appears at
real shapes (table-row padding over the model axis, real gather/matmul
tile sizes) fails here rather than on a pod. Slow-marked: a few
hundred MB of tables and a real compile.
"""

import numpy as np
import pytest

import jax

pytestmark = pytest.mark.slow

BATCH = 1000          # reddit recipe batch: 250/dev on the data=4 axis
FANOUTS = [4, 4]
DIM = 64
FEATURE_DIM = 602
LABEL_DIM = 41
NUM_NODES = 20000     # step shapes are the bench's; graph scaled to CI


@pytest.fixture(scope="module")
def bench_graph(tmp_path_factory):
    import euler_tpu
    from euler_tpu.datasets import build_synthetic

    d = str(tmp_path_factory.mktemp("bench_shapes"))
    build_synthetic(
        d, num_nodes=NUM_NODES, avg_degree=50, feature_dim=FEATURE_DIM,
        label_dim=LABEL_DIM, multilabel=False,
    )
    return euler_tpu.Graph(directory=d)


def _model(**over):
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=0, label_dim=LABEL_DIM, metapath=[[0]] * 2,
        fanouts=FANOUTS, dim=DIM, feature_idx=1, feature_dim=FEATURE_DIM,
        max_id=NUM_NODES - 1, sigmoid_loss=False, device_features=True,
    )
    kw.update(over)
    return SupervisedGraphSage(**kw)


def _mesh_state(model, graph, opt, state_check=None):
    from euler_tpu.parallel import (
        make_mesh, pad_tables_for_mesh, state_sharding,
    )

    mesh = make_mesh(8, model_parallel=2)
    state = model.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(BATCH, -1), opt
    )
    if state_check is not None:
        state_check(state)
    state = pad_tables_for_mesh(state, mesh)
    sh = state_sharding(mesh, state)
    state = jax.device_put(state, sh)
    return mesh, state, sh


def _run_steps(model, graph, n_steps=3, lr=0.03, state_check=None):
    """Three full train steps at bench shapes on the 8-device mesh;
    returns the per-step losses. ``state_check`` runs against the
    freshly-initialised host state (pre-padding/sharding)."""
    from euler_tpu import train as train_lib
    from euler_tpu.parallel import (
        batch_sharding, replicated_sharding, shard_batch,
    )

    opt = train_lib.get_optimizer("adam", lr)
    mesh, state, sh = _mesh_state(model, graph, opt, state_check)
    rep = replicated_sharding(mesh)
    step_fn = jax.jit(
        model.make_train_step(opt),
        in_shardings=(sh, batch_sharding(mesh)),
        out_shardings=(sh, rep, rep),
    )
    losses = []
    for i in range(n_steps):
        roots = graph.sample_node(BATCH, -1)
        batch = shard_batch(model.sample(graph, roots), mesh)
        state, loss, _ = step_fn(state, batch)
        losses.append(float(jax.device_get(loss)))
    return losses


def test_host_path_bench_shapes_on_mesh(bench_graph):
    losses = _run_steps(_model(), bench_graph)
    assert all(np.isfinite(l) for l in losses)
    # 41-class CE starts near ln(41) ~ 3.7; a step that executed must
    # have produced a real loss, not zeros from an unexecuted buffer
    assert losses[0] > 1.0


def test_device_sampling_bench_shapes_on_mesh(bench_graph):
    losses = _run_steps(_model(device_sampling=True), bench_graph)
    assert all(np.isfinite(l) for l in losses)
    assert losses[0] > 1.0


def test_alias_sampling_bench_shapes_on_mesh(bench_graph):
    """The exact (heavy-tail) alias sampler under the same mesh: the
    flat-CSR alias consts replicate, draws stay inside the jitted step."""
    model = _model(device_sampling=True)
    model.set_sampling_options(alias=True)
    losses = _run_steps(model, bench_graph)
    assert all(np.isfinite(l) for l in losses)
    assert losses[0] > 1.0


def test_biased_alias_walk_on_mesh(bench_graph):
    """Round-5 exact biased walks under the 8-device mesh: Node2Vec with
    sorted alias consts (rejection-sampled p/q walk inside the jitted
    step), batch sharded over 'data', walk consts replicated."""
    from euler_tpu.models import Node2Vec

    model = Node2Vec(
        node_type=-1, edge_type=[0], max_id=NUM_NODES - 1, dim=16,
        walk_len=2, walk_p=0.25, walk_q=4.0, device_sampling=True,
        device_features=True, feature_idx=-1,
    )
    model.set_sampling_options(alias=True)
    k = model.adj_key([0], sorted=True)

    def check(state):  # alias form, not a slab
        assert "off" in state["consts"]["adj"][k]

    losses = _run_steps(
        model, bench_graph, lr=0.01, state_check=check
    )
    assert all(np.isfinite(l) for l in losses)
    # an unexecuted/zeroed replicated loss buffer would be finite 0.0;
    # the NCE loss over real pairs is decidedly positive
    assert losses[0] > 0.5
