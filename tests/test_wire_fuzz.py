"""Malformed-frame and wire-version fuzzing against a live service.

The wire layer's parsing rules (eg_wire.h) are pinned from the client
side by the remote suites; this file attacks the SERVER with raw
sockets — truncated frames, oversized declared lengths, unknown ops,
truncated/stale envelopes — and asserts the service's survivability
contract: hostile bytes are rejected and counted (`frames_rejected`),
no handler thread dies, no handler slot sticks, and the same service
keeps answering well-formed requests on the very next exchange.

Plus the cross-version compatibility pins (the eg_wire.h negotiation
contract): an old-wire client against a new server and a new client
against an (emulated) old server both work — negotiated down, counted
in `wire_downgrades` — and a FUTURE wire version gets a clean
kStatusBadVersion error, never a hang or a crash.
"""

import os
import socket
import struct
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from tests.fixture_graph import write_fixture

OK, ERR, BUSY, DEADLINE, BADVERSION = 0, 1, 2, 3, 4
ENVELOPE = 0xE7
PING = 1


@pytest.fixture(autouse=True)
def _clean_slate():
    native.fault_clear()
    native.reset_counters()
    yield
    native.fault_clear()
    native.reset_counters()


@pytest.fixture(scope="module")
def service(tmp_path_factory):
    from euler_tpu.graph.service import GraphService

    data = str(tmp_path_factory.mktemp("fuzz_data"))
    write_fixture(data, num_partitions=2)
    # short io timeout so the wedged-mid-frame test frees its handler
    # slot in test time, not the 5 s production default
    svc = GraphService(data, 0, 1, options="io_timeout_ms=400")
    yield svc
    svc.stop()


def _dial(svc) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", svc.port), 5.0)
    s.settimeout(5.0)
    return s


def _send_frame(s: socket.socket, payload: bytes) -> None:
    s.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(s: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 4:
        chunk = s.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    out = b""
    while len(out) < n:
        chunk = s.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        out += chunk
    return out


def _envelope(version: int, deadline_ms: int, body: bytes) -> bytes:
    return struct.pack("<BBq", ENVELOPE, version, deadline_ms) + body


def _assert_ping_works(svc) -> None:
    """The liveness probe every fuzz case ends on: a fresh well-formed
    exchange must still be served."""
    with _dial(svc) as s:
        _send_frame(s, _envelope(2, 5000, bytes([PING])))
        reply = _recv_frame(s)
    assert reply[0] == OK, reply


def test_truncated_frame_then_close_keeps_serving(service):
    with _dial(service) as s:
        s.sendall(struct.pack("<I", 100) + b"short")  # 5 of 100 bytes
    # the handler sees EOF mid-frame and releases the connection; the
    # service must keep answering
    _assert_ping_works(service)


def test_oversized_declared_length_rejected_and_counted(service):
    native.reset_counters()
    with _dial(service) as s:
        s.sendall(struct.pack("<I", (1 << 30) + 1))  # > kMaxFrame
        # server refuses the frame and closes; nothing to read
        assert s.recv(1) == b""
    assert native.counters()["frames_rejected"] >= 1
    _assert_ping_works(service)


def test_unknown_op_answers_error_on_a_healthy_connection(service):
    with _dial(service) as s:
        _send_frame(s, bytes([0x63]))  # op 99: not a real op
        reply = _recv_frame(s)
        assert reply[0] == ERR
        assert b"unknown op 99" in reply
        # SAME connection, next exchange: the handler neither died nor
        # stuck — a v1 ping still answers
        _send_frame(s, bytes([PING]))
        assert _recv_frame(s)[0] == OK


def test_stale_wire_version_gets_clean_versioned_error(service):
    native.reset_counters()
    with _dial(service) as s:
        _send_frame(s, _envelope(99, 5000, bytes([PING])))
        reply = _recv_frame(s)
        assert reply[0] == BADVERSION
        assert b"wire version 99" in reply
        # the connection survives a refused version: a correct v2
        # envelope on the same socket is served
        _send_frame(s, _envelope(2, 5000, bytes([PING])))
        assert _recv_frame(s)[0] == OK
    assert native.counters()["frames_rejected"] >= 1
    _assert_ping_works(service)


def test_truncated_envelope_rejected_and_counted(service):
    native.reset_counters()
    with _dial(service) as s:
        _send_frame(s, bytes([ENVELOPE, 2]))  # marker + version, no header
        reply = _recv_frame(s)
        assert reply[0] == ERR
        assert b"envelope" in reply
    assert native.counters()["frames_rejected"] >= 1
    _assert_ping_works(service)


def test_wedged_mid_frame_frees_handler_slot(service):
    """A client that starts a frame and stalls must not pin its handler
    past the socket timeout: the slot frees (handler_timeouts) and the
    service keeps answering everyone else meanwhile."""
    native.reset_counters()
    wedge = _dial(service)
    try:
        wedge.sendall(struct.pack("<I", 64) + b"partial")  # then stall
        # while the wedge ages toward its 400 ms SO_RCVTIMEO, other
        # clients are served
        _assert_ping_works(service)
        deadline = time.monotonic() + 10.0
        while (native.counters()["handler_timeouts"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert native.counters()["handler_timeouts"] >= 1
    finally:
        wedge.close()
    _assert_ping_works(service)


def test_fuzz_barrage_no_handler_death(service):
    """A burst of hostile payloads followed by a correctness probe: the
    fixed pool absorbed all of it (thread count stable, queries exact)."""
    def threads() -> int:
        return len(os.listdir("/proc/self/task"))

    before = threads()
    hostile = [
        b"",                                  # empty payload
        bytes([0x00]),                        # op 0
        bytes([0xFF]) * 32,                   # garbage ops + args
        bytes([PING]) + b"trailing-garbage",  # over-long ping
        _envelope(2, -5, bytes([PING])),      # negative deadline = none
        _envelope(2, 0, bytes([6])),          # deadline 0: expired or ok,
                                              # must answer either way
        struct.pack("<BBq", ENVELOPE, 2, 2**62) + bytes([PING]),
    ]
    for payload in hostile:
        with _dial(service) as s:
            _send_frame(s, payload)
            try:
                _recv_frame(s)  # any well-framed status is acceptable
            except ConnectionError:
                pass  # a drop is acceptable; a wedge/crash is not
    _assert_ping_works(service)
    assert threads() == before

    # exactness after the barrage: a real query over a real client
    from euler_tpu.graph.graph import Graph

    g = Graph(mode="remote", shards=[service.address], retries=2,
              timeout_ms=2000)
    try:
        t = g.node_types(np.array([10, 11, 12, 13], dtype=np.int64))
        np.testing.assert_array_equal(t, [0, 1, 0, 1])
    finally:
        g.close()


# ---------------------------------------------------------------------------
# cross-version compatibility (the eg_wire.h negotiation contract)
# ---------------------------------------------------------------------------


def test_old_client_against_new_server(service, tmp_path):
    """A wire-v1 client (no envelopes, no deadlines) against a current
    server: served exactly, no special-casing needed."""
    from euler_tpu.graph.graph import Graph

    g = Graph(mode="remote", shards=[service.address], wire_version=1,
              retries=2, timeout_ms=2000)
    try:
        t = g.node_types(np.array([10, 11, 12, 13], dtype=np.int64))
        np.testing.assert_array_equal(t, [0, 1, 0, 1])
        row = g.get_dense_feature(np.array([10], dtype=np.int64), [0], [2])
        assert row.shape == (1, 2)
    finally:
        g.close()


def test_new_client_negotiates_down_against_old_server(tmp_path):
    """A current client against a wire-v1 server (emulated by the
    wire_version=1 service option, which answers envelopes with the
    stock pre-envelope unknown-op error): the first exchange on the
    replica downgrades it (wire_downgrades), the request is resent raw
    on the same connection, and every query is exact from then on."""
    from euler_tpu.graph.graph import Graph
    from euler_tpu.graph.service import GraphService

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    svc = GraphService(data, 0, 1, options="wire_version=1")
    try:
        native.reset_counters()
        g = Graph(mode="remote", shards=[svc.address], retries=2,
                  timeout_ms=2000)
        try:
            t = g.node_types(np.array([10, 11, 12, 13], dtype=np.int64))
            np.testing.assert_array_equal(t, [0, 1, 0, 1])
            ctr = native.counters()
            assert ctr["wire_downgrades"] == 1, ctr  # once per replica
            assert ctr["retries"] == 0, ctr  # downgrade is not a retry
            assert ctr["calls_failed"] == 0, ctr
        finally:
            g.close()
    finally:
        svc.stop()


def test_wire_version_rejects_garbage_values():
    from euler_tpu.graph.graph import Graph
    from euler_tpu.graph.service import GraphService

    with pytest.raises((RuntimeError, ValueError)):
        Graph(mode="remote", shards=["127.0.0.1:1"], wire_version=7)
    with pytest.raises(RuntimeError, match="wire_version"):
        GraphService("/nonexistent", options="wire_version=7")
    with pytest.raises(RuntimeError, match="unknown service option"):
        GraphService("/nonexistent", options="wrokers=2")
