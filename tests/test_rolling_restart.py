"""Rolling-restart drill + connection-storm shedding: the tier-1 pins of
the server-side survivability layer (_native/eg_admission, DEPLOY.md
"Rolling restart runbook").

Two halves:

* **Rolling restart** — train SupervisedGraphSage over a live 2-shard
  TCP cluster (separate OS processes) while EACH shard in sequence is
  SIGTERM-drained (deregister -> finish in-flight -> close; the service
  main() wires SIGTERM to Service::Drain) and restarted on a new port.
  The run must complete with **zero failed calls** — every call during a
  shard's downtime survives on retries until re-discovery learns the new
  address — and the final loss must match a restart-free run within the
  chaos-soak tolerance.

* **Connection storm** — a 2-worker service with a tiny pending budget
  against 32 concurrent clients: admission must shed the overflow with
  BUSY replies (`busy_rejects`), every shed client must still complete
  via the fail-fast failover/retry path, server-side dispatch latency
  must stay bounded (load waits in the queue, not inside handlers), and
  the fixed handler pool must not leak a single thread.
"""

import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from tests.fixture_graph import TOPOLOGY, write_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NUM_SHARDS = 2
NUM_PARTITIONS = 4
STEPS = 26
# shard 0 drains+restarts around step 6, shard 1 around step 16 — in
# sequence, never both down at once (the rolling-restart invariant)
RESTARTS = {6: 0, 16: 1}


@pytest.fixture(autouse=True)
def _clean_slate():
    from euler_tpu.telemetry import set_telemetry, telemetry_reset

    native.fault_clear()
    native.reset_counters()
    telemetry_reset()
    set_telemetry(True)
    yield
    native.fault_clear()
    native.reset_counters()
    telemetry_reset()


def _launch_shard(idx: int, data: str, reg: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [sys.executable, "-m", "euler_tpu.graph.service",
         "--data_dir", data, "--shard_idx", str(idx),
         "--shard_num", str(NUM_SHARDS), "--registry", reg],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )


def _wait_registered(idx: int, reg: str, timeout: float = 90.0) -> None:
    """Wait until shard idx has a registry entry that accepts
    connections (the run_loop liveness-filter shape)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for f in os.listdir(reg):
            if not f.startswith(f"{idx}#"):
                continue
            host, port = f.split("#", 1)[1].rsplit("_", 1)
            try:
                with socket.create_connection((host, int(port)), 1.0):
                    return
            except OSError:
                continue
        time.sleep(0.1)
    raise TimeoutError(f"shard {idx} never came up in {reg}")


def test_rolling_restart_drill_zero_failed_calls(tmp_path):
    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=NUM_PARTITIONS)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    opt = train_lib.get_optimizer("adam", 0.05)
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    roots = np.array(sorted(TOPOLOGY), dtype=np.int64)

    def run(graph, hook=None):
        native.lib().eg_seed(1234)
        state = model.init_state(jax.random.PRNGKey(0), graph, roots, opt)
        losses = []
        for i in range(STEPS):
            if hook is not None:
                hook(i)
            batch = model.sample(graph, roots)
            state, loss, _ = step(state, batch)
            losses.append(float(loss))
        return losses

    procs = {}
    try:
        for s in range(NUM_SHARDS):
            procs[s] = _launch_shard(s, data, reg)
        for s in range(NUM_SHARDS):
            _wait_registered(s, reg)

        # ---- restart-free reference run ----
        g = euler_tpu.Graph(mode="remote", registry=reg, retries=8,
                            timeout_ms=2000, backoff_ms=2)
        assert g.num_shards == NUM_SHARDS
        clean = run(g)
        g.close()

        # ---- drill run: SIGTERM-drain + restart each shard in turn ----
        # generous per-call budget: a call issued while its shard is
        # restarting must keep retrying until re-discovery learns the
        # new address — calls_failed == 0 is the acceptance bar
        native.reset_counters()
        # neighbor_cache_mb=0: locally-sampled hub hops (PR 9) can hide
        # a restarting shard so completely that zero calls ever retry —
        # great for training, wrong for THIS drill, whose whole point
        # is to exercise the transport recovery machinery under load
        g = euler_tpu.Graph(
            mode="remote", registry=reg, retries=40, timeout_ms=2000,
            backoff_ms=10, quarantine_ms=200, deadline_ms=90000,
            rediscover_ms=250, neighbor_cache_mb=0,
        )

        def rolling(i):
            shard = RESTARTS.get(i)
            if shard is None:
                return
            p = procs[shard]
            p.send_signal(signal.SIGTERM)
            rc = p.wait(timeout=60)
            # the SIGTERM path is a drain + clean exit, not a crash
            assert rc == 0, f"shard {shard} exited {rc} on SIGTERM"
            # drain deregistered the shard before closing: its flat-file
            # entry must already be gone when the process is
            stale = [f for f in os.listdir(reg)
                     if f.startswith(f"{shard}#")]
            assert stale == [], stale
            procs[shard] = _launch_shard(shard, data, reg)
            _wait_registered(shard, reg)

        drilled = run(g, rolling)
        counters = native.counters()
        g.close()

        # survivability contract: the drill is INVISIBLE to training —
        # no call failed, no row degraded, and the loss landed where the
        # restart-free run landed
        assert counters["calls_failed"] == 0, counters
        assert counters["rpc_errors"] == 0, counters
        assert all(np.isfinite(x) for x in clean + drilled)
        clean_final = float(np.mean(clean[-5:]))
        drill_final = float(np.mean(drilled[-5:]))
        assert drill_final < drilled[0], (drilled[0], drill_final)
        assert abs(drill_final - clean_final) < 0.4, (clean_final,
                                                     drill_final)
        # the drill really exercised the recovery machinery
        assert counters["retries"] >= 1, counters
        assert counters["rediscoveries"] >= 1, counters
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            p.wait()


def _thread_count() -> int:
    return len(os.listdir("/proc/self/task"))


def test_connection_storm_sheds_busy_and_every_call_completes(tmp_path):
    """workers=2, pending=2, 32 concurrent clients: admission must shed
    (busy_rejects > 0) yet every client call completes via fail-fast
    failover/retry, handler latency stays bounded (the queue absorbs the
    wait, not the handlers), and the fixed pool leaks no thread."""
    from euler_tpu.graph.graph import Graph
    from euler_tpu.graph.service import GraphService

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=NUM_PARTITIONS)

    svc = GraphService(data, 0, 1, workers=2, pending=2)
    addr = svc.address
    try:
        # every request stalls 15 ms in the worker (pre-dispatch), so
        # two workers saturate immediately and the 32 dials below MUST
        # overflow the pending budget — deterministic shedding pressure
        # without making any single call slow enough to time out
        native.fault_config("handler_stall:delay@15", 3)
        native.reset_counters()
        native.stats_reset()
        baseline_threads = _thread_count()

        ids = np.array([10, 11, 12, 13], dtype=np.int64)
        n_clients = 32
        barrier = threading.Barrier(n_clients)
        errors = []
        durations = []
        lock = threading.Lock()

        def client(k):
            try:
                barrier.wait(timeout=60)
                t0 = time.monotonic()
                g = Graph(mode="remote", shards=[addr], retries=8,
                          timeout_ms=5000, backoff_ms=1,
                          deadline_ms=60000, dispatch_workers=2)
                try:
                    for _ in range(3):
                        t = g.node_types(ids)
                        np.testing.assert_array_equal(t, [0, 1, 0, 1])
                finally:
                    g.close()
                with lock:
                    durations.append(time.monotonic() - t0)
            except Exception as e:  # pragma: no cover - failure detail
                with lock:
                    errors.append(f"client {k}: {e!r}")

        threads = [threading.Thread(target=client, args=(k,))
                   for k in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads), "storm wedged"
        assert errors == [], errors[:5]

        ctr = native.counters()
        # the storm overflowed the bounded queue and was shed...
        assert ctr["busy_rejects"] > 0, ctr
        # ...but shedding cost nobody their answer
        assert ctr["calls_failed"] == 0, ctr
        assert ctr["rpc_errors"] == 0, ctr

        # the same verdict must be reachable REMOTELY: scrape the live
        # server over the STATS opcode (eg_telemetry) and assert the
        # shedding + admission state off the wire, the way a cluster
        # operator would — not just via this process's counters
        import euler_tpu

        native.fault_clear()  # the scrape itself must not stall
        g = Graph(mode="remote", shards=[addr], retries=4,
                  timeout_ms=5000)
        try:
            scraped = euler_tpu.scrape(g, 0)
        finally:
            g.close()
        assert scraped["counters"]["busy_rejects"] == ctr["busy_rejects"]
        gauges = scraped["gauges"]
        assert gauges["workers"] == 2, gauges
        assert gauges["draining"] == 0, gauges
        assert 0 <= gauges["queue_depth"], gauges
        # the storm left latency evidence: the server handler histogram
        # saw every node_type dispatch the clients measured
        served = scraped["hist"]["server_handler:node_type"]["count"]
        assert served >= n_clients * 3, served
        # handler latency stayed bounded: the wait lives in the
        # admission queue, never inside a dispatch (p99==max here)
        span = native.stats().get("service_request")
        assert span is not None and span["max_us"] < 500_000, span
        # the fixed pool is fixed: no handler thread outlives the storm
        deadline = time.monotonic() + 30.0
        while (_thread_count() > baseline_threads
               and time.monotonic() < deadline):
            time.sleep(0.1)
        assert _thread_count() <= baseline_threads, (
            _thread_count(), baseline_threads)
    finally:
        native.fault_clear()
        svc.stop()
