"""Self-gating tests for the native invariant linter (scripts/check_native.py).

Two directions, so the gate can fail for either reason:
- the clean tree stays clean — the native code cannot regress past the
  crash-class rules the sanitizer/fuzz rounds taught us (SANITIZERS.md);
- every rule demonstrably fires on a seeded-violation fixture — the
  linter cannot rot into a vacuous pass.
"""

import importlib.util
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
_SPEC = importlib.util.spec_from_file_location(
    "check_native", REPO / "scripts" / "check_native.py"
)
check_native = importlib.util.module_from_spec(_SPEC)
# dataclasses resolves the module through sys.modules when annotations are
# postponed (PEP 563), so register before exec
sys.modules["check_native"] = check_native
_SPEC.loader.exec_module(check_native)


def lint(text, name="snippet.cc", rules=None):
    return check_native.lint_text(text, name, rules)


def only_rule(violations, rule):
    assert violations, f"expected a {rule} violation, linter stayed silent"
    assert {v.rule for v in violations} == {rule}, violations
    return violations


# ---------------------------------------------------------------------------
# the clean tree is clean (and stays that way)
# ---------------------------------------------------------------------------


def test_native_tree_is_clean():
    files = check_native.default_targets(str(REPO))
    assert len(files) >= 40, files  # all .cc and .h of _native
    # the fault layer, the remote hot-path additions (persistent
    # dispatcher + feature cache), the server survivability layer
    # (bounded admission), the telemetry subsystem, the step-phase
    # profiler, the blackbox flight recorder, the data-plane heat
    # profiler, the locality layer (placement routing + the
    # frequency-aware caches), and the async completion-queue sampler
    # (eg_async) must be under the gate, not grandfathered around it
    names = {pathlib.Path(f).name for f in files}
    assert {
        "eg_fault.cc", "eg_fault.h", "eg_dispatch.cc", "eg_dispatch.h",
        "eg_cache.cc", "eg_cache.h", "eg_admission.cc", "eg_admission.h",
        "eg_telemetry.cc", "eg_telemetry.h", "eg_phase.cc", "eg_phase.h",
        "eg_blackbox.cc", "eg_blackbox.h", "eg_heat.cc", "eg_heat.h",
        "eg_placement.cc", "eg_placement.h",
        "eg_devprof.cc", "eg_devprof.h", "eg_async.h",
        "eg_epoch.cc", "eg_epoch.h",
    } <= names, names
    violations = []
    for f in files:
        violations.extend(check_native.lint_file(f))
    assert violations == [], "\n".join(map(str, violations))


def test_cli_exit_codes(tmp_path):
    clean = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_native.py")],
        capture_output=True,
        text=True,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = tmp_path / "bad.cc"
    bad.write_text("void f() {\n  mu_.lock();\n}\n")
    dirty = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_native.py"), str(bad)],
        capture_output=True,
        text=True,
    )
    assert dirty.returncode == 1
    # diagnostics carry file:line so they are jump-to-able
    assert f"{bad}:2: [raw-lock]" in dirty.stdout


# ---------------------------------------------------------------------------
# each rule fires on a minimal seeded violation (file:line asserted)
# ---------------------------------------------------------------------------


def test_abi_barrier_fires():
    snippet = (
        'extern "C" {\n'
        "int eg_boom(void* h) {\n"
        "  return do_work(h);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert (v.path, v.line) == ("snippet.cc", 2)
    assert "eg_boom" in v.message


def test_abi_barrier_accepts_guarded_function():
    snippet = (
        'extern "C" {\n'
        "int eg_fine(void* h) {\n"
        "  try {\n"
        "    return do_work(h);\n"
        "  } catch (...) {\n"
        "    return -1;\n"
        "  }\n"
        "}\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_abi_barrier_ignores_non_extern_functions():
    snippet = "namespace eg {\nint helper() { return 1; }\n}\n"
    assert lint(snippet) == []


def test_ptr_arith_bounds_fires():
    snippet = (
        "bool Read(const char* p, const char* end, size_t n) {\n"
        "  if (p + n * sizeof(int) > end) return false;\n"
        "  return true;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "ptr-arith-bounds")
    assert (v.path, v.line) == ("snippet.cc", 2)


def test_ptr_arith_bounds_accepts_division_form():
    # the ByteCursor idiom: compare the count, not the advanced pointer
    snippet = (
        "bool Read(const char* p, const char* end, size_t n) {\n"
        "  if (n > remaining() / sizeof(int)) return false;\n"
        "  p += n * sizeof(int);\n"
        "  return true;\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_thread_catch_fires_on_std_thread():
    snippet = "void Spawn() {\n  std::thread([] { work(); }).detach();\n}\n"
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert (v.path, v.line) == ("snippet.cc", 2)


def test_thread_catch_fires_on_thread_vector_emplace():
    snippet = (
        "void Fan(int n) {\n"
        "  std::vector<std::thread> ts;\n"
        "  for (int s = 0; s < n; ++s)\n"
        "    ts.emplace_back([s] { work(s); });\n"
        "  for (auto& t : ts) t.join();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 4


def test_thread_catch_accepts_guarded_lambda():
    snippet = (
        "void Spawn() {\n"
        "  std::thread([] {\n"
        "    try {\n"
        "      work();\n"
        "    } catch (...) {\n"
        "    }\n"
        "  }).detach();\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_wire_count_alloc_fires():
    snippet = (
        "void Decode(WireReader* r, std::vector<int>* out) {\n"
        "  int32_t n = r->I32();\n"
        "  out->resize(n);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert (v.path, v.line) == ("snippet.cc", 3)
    assert "`n`" in v.message and "line 2" in v.message


def test_wire_count_alloc_accepts_bounded_count():
    snippet = (
        "void Decode(WireReader* r, std::vector<int>* out) {\n"
        "  int32_t n = r->I32();\n"
        "  if (n < 0 || static_cast<uint64_t>(n) > r->remaining() / 4) return;\n"
        "  out->resize(n);\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_wire_count_alloc_fires_on_sized_vector_construction():
    snippet = (
        "void Handle(WireReader* r) {\n"
        "  int32_t count = r->I32();\n"
        "  std::vector<uint64_t> out(count);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert v.line == 3


def test_raw_lock_fires():
    snippet = "void Handle() {\n  mu_.lock();\n  work();\n  mu_.unlock();\n}\n"
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_raw_lock_accepts_raii_guard():
    snippet = (
        "void Handle() {\n"
        "  std::lock_guard<std::mutex> l(mu_);\n"
        "  work();\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_thread_rng_fires():
    snippet = "int Draw() {\n  srand(42);\n  return rand() % 10;\n}\n"
    violations = only_rule(lint(snippet), "thread-rng")
    assert [v.line for v in violations] == [2, 3]


def test_thread_rng_accepts_thread_rng():
    snippet = "int Draw() {\n  return ThreadRng().NextLess(10);\n}\n"
    assert lint(snippet) == []


# ---------------------------------------------------------------------------
# fault-layer shapes: the eg_fault.cc/capi surface stays under the same gate
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_fault_config_shape():
    """The failpoint ABI parses operator-typed spec strings — exactly the
    kind of entry point where a stray stoi/stod throw would cross the C
    ABI. A guardless eg_fault_config-shaped function must be caught."""
    snippet = (
        'extern "C" {\n'
        "int eg_fault_config(const char* spec, uint64_t seed) {\n"
        "  return Configure(spec, seed) ? 0 : -1;\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_fault_config" in v.message


def test_thread_catch_fires_on_heartbeat_loop_shape():
    """The heartbeat thread now hosts a failpoint (FaultHit can sleep and
    its redial path allocates) — its entry lambda stays under the
    thread-catch rule like every other service thread."""
    snippet = (
        "void Start() {\n"
        "  heartbeat_thread_ = std::thread([this]() mutable {\n"
        "    while (!stop_) {\n"
        "      if (FaultHit(kFaultHeartbeat) || !RegistrySend(fd, line))\n"
        "        Redial();\n"
        "    }\n"
        "  });\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


def test_wire_count_alloc_fires_on_config_derived_count():
    """A fault-spec-driven allocation (e.g. sizing a table from a parsed
    limit read out of a wire config frame) is the same crash class as any
    wire-derived count: bound before resize."""
    snippet = (
        "void Install(WireReader* r) {\n"
        "  int32_t npoints = r->I32();\n"
        "  points_.resize(npoints);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert "npoints" in v.message


# ---------------------------------------------------------------------------
# admission-layer shapes: the bounded-admission server (eg_admission.cc)
# stays under the same gate as the rest of the transport
# ---------------------------------------------------------------------------


def test_thread_catch_fires_on_poller_and_worker_pool_shapes():
    """The admission layer spawns a poller std::thread AND a
    vector<std::thread> worker pool — both entry shapes must stay under
    thread-catch (an escaping exception is std::terminate for the whole
    shard service)."""
    snippet = (
        "void Start(int n) {\n"
        "  poller_ = std::thread([this] { PollerLoop(); });\n"
        "  std::vector<std::thread> workers_;\n"
        "  for (int i = 0; i < n; ++i)\n"
        "    workers_.emplace_back([this] { WorkerLoop(); });\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "thread-catch")
    assert [v.line for v in violations] == [2, 5]


def test_wire_count_alloc_fires_on_envelope_derived_count():
    """An admission worker sizing anything from an envelope-decoded
    integer (e.g. a stamped deadline misused as a buffer size) is the
    same bound-before-alloc crash class the wire reader rules pin."""
    snippet = (
        "void Serve(WireReader* r) {\n"
        "  int64_t budget = r->I64();\n"
        "  std::vector<char> scratch(budget);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert "budget" in v.message


def test_raw_lock_fires_on_admission_queue_shape():
    """The ready-queue handoff (poller push / worker pop) must stay
    RAII-locked: a raw lock around the condvar queue is exactly where an
    early return leaks a held mutex under load."""
    snippet = (
        "void Push(int fd) {\n"
        "  mu_.lock();\n"
        "  ready_.push_back(fd);\n"
        "  mu_.unlock();\n"
        "  ready_cv_.notify_one();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


# ---------------------------------------------------------------------------
# phase-profiler shapes: the eg_phase ABI + recorder stay under the gate
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_phase_record_shape():
    """The step-phase ABI is called from Python training threads every
    step — a guardless eg_phase_record-shaped entry point would carry
    any native exception straight across ctypes (std::terminate)."""
    snippet = (
        'extern "C" {\n'
        "void eg_phase_record(int phase, uint64_t us) {\n"
        "  eg::PhaseStats::Global().Record(phase, us);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_phase_record" in v.message


def test_raw_lock_fires_on_phase_snapshot_shape():
    """A phase-histogram snapshot that raw-locks around its read is the
    same leak-on-early-return class the journal lock rules pin."""
    snippet = (
        "void SnapshotPhases() {\n"
        "  mu_.lock();\n"
        "  CopyCells();\n"
        "  mu_.unlock();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


# ---------------------------------------------------------------------------
# blackbox shapes: the flight-recorder/postmortem layer (eg_blackbox)
# stays under the gate — the signal-handler path is a prime candidate
# for exactly the crash classes these rules pin
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_blackbox_record_shape():
    """The flight-recorder ABI is on the hot path of every finished
    RPC and every step phase — a guardless eg_blackbox_record-shaped
    entry point would carry a native exception straight across ctypes
    (std::terminate, which is itself a SIGABRT the blackbox would then
    try to dump: a recursion nobody wants)."""
    snippet = (
        'extern "C" {\n'
        "void eg_blackbox_record(int point, int op, uint64_t trace) {\n"
        "  eg::Blackbox::Global().Record(point, op, trace);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_blackbox_record" in v.message


def test_raw_lock_fires_on_signal_handler_dump_shape():
    """A fatal-signal dump path that takes a mutex is a deadlock the
    moment the crashing thread already holds it — the raw-lock rule
    catches the shape (the REAL dump path must stay atomics + write(2)
    only; even an RAII guard would be wrong there, and that design
    constraint is what OBSERVABILITY.md 'Postmortems' documents)."""
    snippet = (
        "void DumpToFd(int fd, int sig) {\n"
        "  mu_.lock();\n"
        "  WriteRings(fd);\n"
        "  mu_.unlock();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_thread_catch_fires_on_resource_sampler_shape():
    """The background resource sampler parses /proc forever — its
    entry lambda stays under thread-catch like every service thread (a
    dead sampler must freeze the history, not the process)."""
    snippet = (
        "void Install() {\n"
        "  std::thread([this] { SamplerLoop(); }).detach();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


def test_wire_count_alloc_fires_on_postmortem_derived_count():
    """A postmortem/scrape reader sizing a buffer from a file-derived
    ring head is the same bound-before-alloc crash class as any wire
    count — a truncated dump must not OOM the collector."""
    snippet = (
        "void LoadRings(WireReader* r) {\n"
        "  int64_t head = r->I64();\n"
        "  std::vector<Event> events(head);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert "head" in v.message


# ---------------------------------------------------------------------------
# heat shapes: the data-plane access profiler (eg_heat) stays under the
# gate — it sits on the hot path of every remote query AND inside the
# server dispatch, exactly where these crash classes cost the most
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_heat_record_shape():
    """The heat feed ABI runs per batch on the query hot path — a
    guardless eg_heat_record-shaped entry point would carry a native
    exception straight across ctypes (std::terminate)."""
    snippet = (
        'extern "C" {\n'
        "void eg_heat_record(int side, int op, const uint64_t* ids,\n"
        "                    int64_t n) {\n"
        "  eg::Heat::Global().Record(side, op, ids, n);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_heat_record" in v.message


def test_raw_lock_fires_on_topk_update_shape():
    """The space-saving tracker serializes its table under a mutex once
    per batch; a raw lock there leaks the mutex on any early return —
    and this update loop HAS early returns (tracked-id fast path)."""
    snippet = (
        "void UpdateTop(TopTable* t, uint64_t id) {\n"
        "  t->mu.lock();\n"
        "  if (FindSlot(*t, id) >= 0) return;\n"
        "  t->mu.unlock();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_wire_count_alloc_fires_on_heat_table_reader_shape():
    """A heat-scrape reader sizing its table from a wire-derived top-K
    count is the same bound-before-alloc crash class as any wire count
    — a malformed kHeat reply must not OOM the collector."""
    snippet = (
        "void ReadTopK(WireReader* r) {\n"
        "  int64_t k = r->I64();\n"
        "  std::vector<TopEntry> table(k);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert "k" in v.message


def test_thread_rng_fires_on_sketch_hash_seed_shape():
    """Sketch row seeds must come from fixed constants (or
    eg::ThreadRng) — rand() is process-global, racy under the
    dispatcher workers that feed the sketch concurrently, and would
    make the count-min estimates irreproducible across runs."""
    snippet = (
        "uint64_t RowSeed(int d) {\n"
        "  return static_cast<uint64_t>(rand()) * d;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-rng")
    assert v.line == 2


def test_ptr_arith_bounds_fires_on_cms_indexing_shape():
    """A sketch reader bounds-checking cell offsets with the
    overflow-prone `p + n * sizeof(T) > end` form would pass a corrupt
    huge width and read out of the fixed cell pool."""
    snippet = (
        "bool CheckCells(const char* p, const char* end, int64_t width) {\n"
        "  return p + width * sizeof(uint64_t) > end;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "ptr-arith-bounds")
    assert v.line == 2


def test_thread_catch_fires_on_heat_decay_thread_shape():
    """A background decay/aging thread over the sketch (a likely future
    extension) stays under thread-catch like every service thread — a
    dead decay loop must freeze the sketch, not the process."""
    snippet = (
        "void StartDecay() {\n"
        "  std::thread([this] { DecayLoop(); }).detach();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


# ---------------------------------------------------------------------------
# locality shapes: placement routing + the frequency-aware caches
# (eg_placement, eg_cache NeighborCache) stay under the gate — the
# placement parser eats wire/file bytes and the caches sit inside every
# remote query, exactly where these crash classes cost the most
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_placement_route_shape():
    """The routing ABI runs per probe batch (heat_dump edge-cut); a
    guardless eg_remote_route-shaped entry point would carry a native
    exception straight across ctypes (std::terminate)."""
    snippet = (
        'extern "C" {\n'
        "void eg_remote_route(void* h, const uint64_t* ids, int n,\n"
        "                     int32_t* out) {\n"
        "  static_cast<RemoteGraph*>(h)->RouteShards(ids, n, out);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_remote_route" in v.message


def test_wire_count_alloc_fires_on_placement_parse_shape():
    """The placement parser sizes its probe table from a blob-declared
    count — the same bound-before-alloc crash class as any wire count;
    a corrupt artifact must not OOM every client that fetches it."""
    snippet = (
        "bool Parse(WireReader* r, PlacementMap* out) {\n"
        "  int64_t count = r->I64();\n"
        "  std::vector<Slot> slots(count * 2);\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert "count" in v.message


def test_raw_lock_fires_on_cache_admission_shape():
    """The TinyLFU admission path holds the stripe mutex across the
    victim comparison and has an early return on rejection — a raw
    lock there leaks the stripe on exactly that return."""
    snippet = (
        "void Put(uint64_t key) {\n"
        "  st.mu.lock();\n"
        "  if (!CacheAdmit(policy_, key, victim)) return;\n"
        "  st.mu.unlock();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_ptr_arith_bounds_fires_on_placement_blob_shape():
    """A blob reader bounds-checking entry offsets with the
    overflow-prone `p + n * 12 > end` form would pass a corrupt huge
    count and read past the artifact."""
    snippet = (
        "bool CheckEntries(const char* p, const char* end, int64_t n) {\n"
        "  return p + n * sizeof(Slot) > end;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "ptr-arith-bounds")
    assert v.line == 2


def test_thread_rng_fires_on_local_draw_shape():
    """The neighbor cache's local sampler must draw from eg::ThreadRng
    like the engine does — rand() is process-global, racy under the
    dispatcher workers, and would break distribution-parity replays."""
    snippet = (
        "size_t DrawIndex(size_t n) {\n"
        "  return static_cast<size_t>(rand()) % n;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-rng")
    assert v.line == 2


def test_thread_catch_fires_on_placement_refresh_shape():
    """A background map-refresh thread (a likely future extension for
    epoch'd placement) stays under thread-catch like every service
    thread — a dead refresher must freeze the map, not the process."""
    snippet = (
        "void StartRefresh() {\n"
        "  std::thread([this] { RefreshLoop(); }).detach();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


# ---------------------------------------------------------------------------
# async completion-queue shapes: the eg_async sampler (PR 18) stays
# under the gate — the continuation chain runs on dispatcher threads
# where every one of these crash classes is fatal to the whole process
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_async_submit_shape():
    """eg_remote_sample_async is called from the train pipeline's
    driver thread every step — a guardless entry point would carry a
    native exception (pool full races, bad-arg asserts) straight
    across ctypes as std::terminate."""
    snippet = (
        'extern "C" {\n'
        "int eg_remote_sample_async(void* h, const uint64_t* ids, int n) {\n"
        "  return static_cast<eg::RemoteGraph*>(h)->SampleFanoutAsync(ids, n);\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_remote_sample_async" in v.message


def test_raw_lock_fires_on_completion_publish_shape():
    """The kDone publish (completion thread) and the Poll/Take read
    (driver thread) meet on async_mu_ — a raw lock there leaks the
    mutex on any early return and wedges every in-flight op behind
    it."""
    snippet = (
        "void PublishDone(AsyncSampleOp* op) {\n"
        "  async_mu_.lock();\n"
        "  op->state = kDone;\n"
        "  async_mu_.unlock();\n"
        "  async_cv_.notify_all();\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_thread_catch_fires_on_async_drain_thread_shape():
    """A dedicated completion-drain thread (a likely future extension
    past the SubmitDetached continuation model) is a service thread
    like any other: its entry lambda needs a top-level catch, or one
    escaped exception takes down the trainer mid-epoch."""
    snippet = (
        "void StartDrain() {\n"
        "  std::thread([this] { DrainCompletions(); }).detach();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


# ---------------------------------------------------------------------------
# snapshot-epoch shapes: the eg_epoch engine (delta loads + RCU flips)
# stays under the gate — the flip publish and the delta reader are the
# two places a crash-class slip corrupts a SERVING snapshot in place
# ---------------------------------------------------------------------------


def test_abi_barrier_fires_on_load_deltas_shape():
    """eg_load_deltas is called from Graph.load_delta on the training
    thread — a guardless entry point would carry a parse/merge
    exception (bad delta file, bad_alloc on a huge blob) straight
    across ctypes as std::terminate instead of an error string."""
    snippet = (
        'extern "C" {\n'
        "int eg_load_deltas(void* h, const char* paths) {\n"
        "  return eg::LoadEngineWithDeltas(h, paths) ? 0 : -1;\n"
        "}\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "abi-barrier")
    assert "eg_load_deltas" in v.message


def test_raw_lock_fires_on_epoch_flip_publish_shape():
    """The flip publish (loader thread) and Pin (every handler thread)
    meet on epoch_mu_ — a raw lock there leaks the mutex on any early
    return and wedges every reader behind a half-published epoch."""
    snippet = (
        "uint64_t Flip(std::shared_ptr<Engine> next) {\n"
        "  epoch_mu_.lock();\n"
        "  snaps_.push_back(std::move(next));\n"
        "  epoch_mu_.unlock();\n"
        "  return snaps_.back()->epoch;\n"
        "}\n"
    )
    violations = only_rule(lint(snippet), "raw-lock")
    assert [v.line for v in violations] == [2, 4]


def test_wire_count_alloc_fires_on_delta_reader_shape():
    """The delta-file reader allocates arrays sized by counts read
    from the file — an unchecked count is the bad_alloc/OOM class the
    EGD1 parser must divide-guard exactly like the wire decoders."""
    snippet = (
        "bool ReadArr(WireReader* r, std::vector<uint64_t>* out) {\n"
        "  int64_t n = r->I64();\n"
        "  out->resize(n);\n"
        "  return true;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "wire-count-alloc")
    assert v.line == 3


def test_thread_catch_fires_on_delta_loader_thread_shape():
    """A background delta-loader thread (prefetching the next delta's
    merge off the handler path) is a service thread: its entry lambda
    needs a top-level catch, or one malformed delta file takes down
    the serving shard instead of counting delta_loads_failed."""
    snippet = (
        "void StartLoader() {\n"
        "  std::thread([this] { LoadPendingDelta(); }).detach();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "thread-catch")
    assert v.line == 2


def test_ptr_arith_bounds_fires_on_delta_apply_shape():
    """Applying a delta walks the packed dat_blob with counted records
    — the overflow-prone `p + n * size > end` bounds form is exactly
    the round-2 crash class; the divide form is the fix."""
    snippet = (
        "bool ApplyRecords(const char* p, const char* end, size_t n) {\n"
        "  if (p + n * sizeof(uint64_t) > end) return false;\n"
        "  return true;\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "ptr-arith-bounds")
    assert v.line == 2


# ---------------------------------------------------------------------------
# the escape hatch: visible, reasoned, typo-proof
# ---------------------------------------------------------------------------


def test_allow_escape_suppresses_with_reason():
    snippet = (
        "void Decode(WireReader* r, std::vector<int>* out) {\n"
        "  int32_t n = r->I32();\n"
        "  // eg-lint: allow(wire-count-alloc) bounded by caller contract\n"
        "  out->resize(n);\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_allow_escape_on_same_line():
    snippet = (
        "void Handle() {\n"
        "  mu_.lock();  // eg-lint: allow(raw-lock) handing off to C callback\n"
        "}\n"
    )
    assert lint(snippet) == []


def test_allow_escape_without_reason_is_a_violation():
    snippet = (
        "void Handle() {\n"
        "  // eg-lint: allow(raw-lock)\n"
        "  mu_.lock();\n"
        "}\n"
    )
    (v,) = only_rule(lint(snippet), "allow-escape")
    assert "no reason" in v.message


def test_allow_escape_for_wrong_rule_does_not_suppress():
    snippet = (
        "void Handle() {\n"
        "  // eg-lint: allow(thread-rng) wrong rule named here\n"
        "  mu_.lock();\n"
        "}\n"
    )
    rules = {v.rule for v in lint(snippet)}
    assert "raw-lock" in rules


def test_allow_escape_unknown_rule_is_a_violation():
    snippet = "void f() {\n  // eg-lint: allow(not-a-rule) whatever\n  g();\n}\n"
    (v,) = only_rule(lint(snippet), "allow-escape")
    assert "unknown rule" in v.message


# ---------------------------------------------------------------------------
# regression pins: the exact crash classes from SANITIZERS.md stay caught
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "line",
    [
        "if (p_ + n * sizeof(T) > end_) return false;",
        "if (end_ < p_ + n * sizeof(T)) return false;",
        "while (cur + count * sizeof(uint64_t) <= limit) step();",
    ],
)
def test_round2_bounds_crash_class_variants(line):
    snippet = f"bool F(size_t n) {{\n  {line}\n  return true;\n}}\n"
    only_rule(lint(snippet), "ptr-arith-bounds")


def test_rules_are_individually_selectable():
    snippet = "void Handle() {\n  mu_.lock();\n  srand(1);\n}\n"
    assert {v.rule for v in lint(snippet)} == {"raw-lock", "thread-rng"}
    assert {v.rule for v in lint(snippet, rules=["raw-lock"])} == {"raw-lock"}
