"""Multi-process training over jax.distributed (the coordination plane).

The reference's distributed training is TF parameter servers coordinated
by ZooKeeper (reference tf_euler/python/run_loop.py:371-397); here the
equivalent is N OS processes, each with its own host sampler and local
devices, joined into ONE global mesh by jax.distributed — gradients
all-reduce across process boundaries inside the jitted step. This test
runs 2 real processes (2 virtual CPU devices each → a 4-device global
data mesh) training SupervisedGraphSage on the shared fixture, and
asserts the replicated states stay bit-identical across processes — the
property the reference needs SyncExitHook + PS round-trips for.
"""

import textwrap

import numpy as np

_WORKER = textwrap.dedent(
    """
    import os, sys
    pid, n_proc, port, fixture = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{port}", num_processes=n_proc, process_id=pid
    )
    import numpy as np
    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.parallel import (
        batch_sharding, make_mesh, replicated_sharding,
    )

    # every process loads the full fixture (local graph mode — the
    # sharded-service mode is covered by tests/test_remote.py)
    graph = euler_tpu.Graph(directory=fixture)
    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    mesh = make_mesh()  # all 4 global devices, data axis
    assert len(jax.devices()) == 2 * n_proc, jax.devices()
    opt = train_lib.get_optimizer("adam", 0.05)
    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), opt
    )
    rep = replicated_sharding(mesh)
    state = jax.device_put(state, rep)
    step = jax.jit(
        model.make_train_step(opt),
        in_shardings=(rep, batch_sharding(mesh)),
        out_shardings=(rep, rep, rep),
        donate_argnums=(0,),
    )
    # global batch 16, each process samples ITS 8 roots (seeded per
    # process so the halves differ, like independent host samplers)
    rng = np.random.default_rng(100 + pid)
    bshard = batch_sharding(mesh)
    losses = []
    for i in range(3):
        local = model.sample(graph, rng.integers(0, 17, 8))
        batch = jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(bshard, x),
            local,
        )
        state, loss, metric = step(state, batch)
        losses.append(float(loss))
    # the replicated params must be identical across processes: hash a
    # deterministic flatten of the local view
    leaves = jax.tree.leaves(
        jax.tree.map(
            lambda x: np.asarray(
                jax.device_get(x.addressable_data(0))
            ).ravel(),
            state["params"],
        )
    )
    digest = float(sum(np.sum(np.abs(l)) for l in leaves))
    print(f"RESULT pid={pid} losses={losses} digest={digest:.10f}",
          flush=True)
    """
)


def test_two_process_data_parallel_training(fixture_dir):
    import ast

    from tests.conftest import free_port, run_worker_processes

    port = free_port()
    outs = run_worker_processes(
        _WORKER, [(pid, 2, port, fixture_dir) for pid in range(2)]
    )
    results = [
        [l for l in out.splitlines() if l.startswith("RESULT")][0]
        for out in outs
    ]
    # same losses and same param digest on both processes: the global
    # all-reduce kept the replicated state in sync
    r0 = results[0].split("pid=0 ")[1]
    r1 = results[1].split("pid=1 ")[1]
    assert r0 == r1, f"\n{results[0]}\n{results[1]}"
    losses = ast.literal_eval(r0.split("losses=")[1].split(" digest=")[0])
    assert all(np.isfinite(l) for l in losses)
