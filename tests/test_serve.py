"""eg_serve tier-1 pins: micro-batching, shedding, deadlines, SLO math,
serve telemetry, the TCP frontend, and the concurrent-traffic parity
contract (served rows bit-identical to the direct forward).

The EmbedServer tests run GraphSAGE over the local fixture graph; the
storm test runs the whole stack — EmbedServer + EmbedFrontend over a
live in-process 2-shard GraphService cluster — under 16 concurrent
clients (scripts/serve_drill.py is the same shape as a standalone
gate)."""

import threading
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from euler_tpu.serving import (
    BusyError,
    DeadlineError,
    MicroBatcher,
    SLOTracker,
)
from tests.fixture_graph import TOPOLOGY


@pytest.fixture(autouse=True)
def _clean_slate():
    from euler_tpu.telemetry import set_telemetry, telemetry_reset

    native.reset_counters()
    telemetry_reset()
    set_telemetry(True)
    yield
    native.reset_counters()
    telemetry_reset()
    set_telemetry(True)


def _sage():
    from euler_tpu.models import SupervisedGraphSage

    return SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )


@pytest.fixture()
def server(graph):
    import jax

    from euler_tpu.serve import EmbedServer
    from euler_tpu.train import get_optimizer

    model = _sage()
    state = model.init_state(
        jax.random.PRNGKey(3), graph, np.arange(8),
        get_optimizer("adam", 0.01),
    )
    srv = EmbedServer(
        model, graph, state, max_batch=8, max_wait_us=2000,
        queue_cap=16, slo_ms=500.0,
    ).start()
    yield srv
    srv.close()


# ---------------------------------------------------------------- SLO math


def test_slo_tracker_exact_percentiles():
    t = SLOTracker(target_ms=10.0, window=100)
    for ms in range(1, 101):  # 1..100 ms
        t.record(ms * 1000.0)
    # nearest-rank over 1..100: p50 = 50th value, p99 = 99th
    assert t.percentile(50) == 50.0
    assert t.percentile(99) == 99.0
    r = t.report()
    assert r["count"] == 100
    assert r["p50_ms"] == 50.0 and r["p99_ms"] == 99.0
    assert r["violations"] == 90  # 11..100 exceed the 10ms target
    assert r["ok"] is False


def test_slo_tracker_window_wraps_and_ok():
    t = SLOTracker(target_ms=100.0, window=4)
    for us in (900e3, 900e3, 1e3, 2e3, 3e3, 4e3):
        t.record(us)
    # the two 900ms outliers fell out of the 4-sample window
    r = t.report()
    assert r["p99_ms"] == 4.0
    assert r["ok"] is True  # window p99 under target
    assert r["violations"] == 2  # lifetime count still remembers them
    assert t.report()["count"] == 6


def test_slo_tracker_empty():
    r = SLOTracker(target_ms=5.0).report()
    assert r == {"target_ms": 5.0, "count": 0, "p50_ms": 0.0,
                 "p99_ms": 0.0, "violations": 0, "ok": True}


# ----------------------------------------------------------- MicroBatcher


def _rows_for(uids: np.ndarray) -> np.ndarray:
    # fake embed: row i = [id, id] so scatter order is checkable
    return np.stack([np.array([i, i], dtype=np.float32) for i in uids])


def test_batcher_coalesces_and_dedups():
    dispatches = []

    def embed(uids):
        dispatches.append(sorted(uids.tolist()))
        return _rows_for(uids)

    mb = MicroBatcher(embed, max_batch=8, max_wait_us=50_000,
                      queue_cap=16).start()
    try:
        outs: dict = {}
        reqs = {0: [1, 2], 1: [2, 3], 2: [3, 1, 4]}

        def go(k):
            outs[k] = mb.submit(reqs[k])

        ts = [threading.Thread(target=go, args=(k,)) for k in reqs]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # all three coalesced into ONE dispatch over the deduped union
        assert dispatches == [[1, 2, 3, 4]]
        assert native.counters()["serve_requests"] == 3
        assert native.counters()["serve_batches"] == 1
        for k, ids in reqs.items():
            np.testing.assert_array_equal(
                outs[k], _rows_for(np.array(ids))
            )
    finally:
        mb.close()


def test_batcher_flushes_on_max_batch():
    """max_batch unique ids pending flushes immediately — no waiting
    out the coalescing window."""
    seen = threading.Event()

    def embed(uids):
        seen.set()
        return _rows_for(uids)

    # window is 10s: only the unique-id trigger can flush this fast
    mb = MicroBatcher(embed, max_batch=2, max_wait_us=10_000_000,
                      queue_cap=16).start()
    try:
        t = threading.Thread(target=mb.submit, args=([7, 8],))
        t.start()
        assert seen.wait(5.0), "batch never flushed on max_batch"
        t.join()
    finally:
        mb.close()


def test_batcher_busy_shedding_at_queue_cap():
    entered, release = threading.Event(), threading.Event()

    def embed(uids):
        entered.set()
        release.wait(10.0)
        return _rows_for(uids)

    mb = MicroBatcher(embed, max_batch=8, max_wait_us=0,
                      queue_cap=1).start()
    try:
        t1 = threading.Thread(target=mb.submit, args=([1],))
        t1.start()
        assert entered.wait(5.0)  # r1 popped, dispatcher wedged
        t2 = threading.Thread(target=mb.submit, args=([2],))
        t2.start()
        deadline = time.monotonic() + 5.0
        while len(mb._queue) < 1:  # r2 queued (cap reached)
            assert time.monotonic() < deadline
            time.sleep(0.001)
        with pytest.raises(BusyError, match="queue at capacity"):
            mb.submit([3])
        assert native.counters()["serve_busy_rejects"] == 1
        release.set()
        t1.join()
        t2.join()
        # shed request never reached the device
        assert native.counters()["serve_requests"] == 3
        assert native.counters()["serve_batches"] == 2
    finally:
        release.set()
        mb.close()


def test_batcher_deadline_expires_before_dispatch():
    calls = []

    def embed(uids):
        calls.append(uids.tolist())
        return _rows_for(uids)

    # long window + 1ms deadline: the request expires while coalescing
    mb = MicroBatcher(embed, max_batch=8, max_wait_us=300_000,
                      queue_cap=16).start()
    try:
        with pytest.raises(DeadlineError, match="deadline expired"):
            mb.submit([5], deadline_ms=1.0)
        assert native.counters()["serve_deadline_rejects"] == 1
        assert calls == []  # never dispatched to the device
    finally:
        mb.close()


def test_batcher_close_drains_queue():
    done = []

    def embed(uids):
        time.sleep(0.01)
        done.extend(uids.tolist())
        return _rows_for(uids)

    mb = MicroBatcher(embed, max_batch=1, max_wait_us=0,
                      queue_cap=64).start()
    outs = []
    ts = [
        threading.Thread(target=lambda i=i: outs.append(mb.submit([i])))
        for i in range(6)
    ]
    for t in ts:
        t.start()
    time.sleep(0.005)
    mb.close()  # must dispatch everything already admitted
    for t in ts:
        t.join()
    assert sorted(done) == [0, 1, 2, 3, 4, 5]
    assert len(outs) == 6
    with pytest.raises(RuntimeError, match="stopped"):
        mb.submit([9])


def test_batcher_embed_error_propagates_to_requests():
    def embed(uids):
        raise ValueError("device fell over")

    mb = MicroBatcher(embed, max_batch=8, max_wait_us=0,
                      queue_cap=16).start()
    try:
        with pytest.raises(ValueError, match="device fell over"):
            mb.submit([1, 2])
    finally:
        mb.close()


# ------------------------------------------------- EmbedServer (+parity)


def test_serve_parity_concurrent_mixed_traffic(server):
    """The tentpole pin: rows served out of coalesced, deduped, padded
    mixed-traffic batches are BIT-identical to the no-batching direct
    forward, per id, regardless of co-batched neighbors."""
    ids = sorted(TOPOLOGY)  # 10..16
    direct = {i: server.embed_direct(i) for i in ids}
    reqs = [
        [10, 14, 12], [14], [16, 10], [11, 12, 13, 15], [12, 12, 14],
        [16], [11, 15], [13, 10, 16],
    ]
    outs: list = [None] * len(reqs)

    def go(k):
        outs[k] = server.embed(reqs[k])

    ts = [threading.Thread(target=go, args=(k,)) for k in range(len(reqs))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for k, req in enumerate(reqs):
        assert outs[k].shape == (len(req), 8)
        assert outs[k].dtype == np.float32
        for row, nid in zip(outs[k], req):
            np.testing.assert_array_equal(row, direct[nid])
    # coalescing actually happened
    assert native.counters()["serve_batches"] < len(reqs)


def test_serve_oversize_request_chunks(graph):
    """One request with more unique ids than max_batch still serves
    (the callback chunks across fixed-bucket dispatches) with per-row
    parity."""
    import jax

    from euler_tpu.serve import EmbedServer
    from euler_tpu.train import get_optimizer

    model = _sage()
    state = model.init_state(
        jax.random.PRNGKey(3), graph, np.arange(8),
        get_optimizer("adam", 0.01),
    )
    with EmbedServer(model, graph, state, max_batch=4) as srv:
        ids = sorted(TOPOLOGY)  # 7 unique > max_batch=4
        rows = srv.embed(ids)
        assert rows.shape == (7, 8)
        for row, nid in zip(rows, ids):
            np.testing.assert_array_equal(row, srv.embed_direct(nid))


def test_serve_stats_shape(server):
    server.embed([1, 2, 3])
    s = server.stats()
    assert s["slo"]["count"] == 1
    assert set(s["serve_phases"]) >= {"queue_wait", "sample",
                                      "dispatch", "total"}
    for ph in s["serve_phases"].values():
        assert ph["count"] >= 1 and ph["p99_us"] >= ph["p50_us"] >= 0
    assert s["counters"]["serve_requests"] == 1
    assert s["batch"]["dispatches"] == 1
    assert s["batch"]["mean_unique_ids"] == 3.0


def test_serve_histograms_reach_every_surface(server):
    """Zero-plumbing criterion: one serve request and the serve families
    appear in telemetry_json() and metrics_text() untouched."""
    from euler_tpu import telemetry as T

    server.embed([4, 7])
    hists = T.serve_hists()
    assert {"queue_wait", "sample", "dispatch", "total"} <= set(hists)
    assert all(h["count"] == 1 for h in hists.values())
    # total >= queue_wait + dispatch in accumulated time
    assert (hists["total"]["sum_us"]
            >= hists["dispatch"]["sum_us"])
    text = T.metrics_text()
    assert "# HELP eg_serve_phase_us " in text
    assert 'eg_serve_phase_us_count{phase="total"}' in text
    assert "# HELP eg_serve_batch_ids " in text
    batch = T.telemetry_json()["hist"]["serve_batch"]
    assert batch["count"] == 1 and batch["sum_us"] == 2  # 2 unique ids


def test_serve_kill_switch_leaves_hot_path_histogram_free(server):
    from euler_tpu import telemetry as T
    from euler_tpu.telemetry import set_telemetry

    set_telemetry(False)
    server.embed([1, 2])
    assert all(h["count"] == 0 for h in T.serve_hists().values())
    assert T.telemetry_json()["hist"]["serve_batch"]["count"] == 0
    set_telemetry(True)
    server.embed([1, 2])
    assert T.serve_hists()["total"]["count"] == 1


def test_serve_rejects_device_sampling_models(graph):
    import jax

    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.serve import EmbedServer
    from euler_tpu.train import get_optimizer

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
        device_sampling=True, device_features=True,
    )
    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8),
        get_optimizer("adam", 0.01),
    )
    with pytest.raises(ValueError, match="device_sampling"):
        EmbedServer(model, graph, state)


def test_serve_sample_cache_bounded_and_deterministic(graph):
    import jax

    from euler_tpu.serve import EmbedServer
    from euler_tpu.train import get_optimizer

    model = _sage()
    state = model.init_state(
        jax.random.PRNGKey(3), graph, np.arange(8),
        get_optimizer("adam", 0.01),
    )
    with EmbedServer(model, graph, state, max_batch=4,
                     sample_cache=2) as srv:
        a = srv.embed_direct(5)
        # evict id 5, then resample it: the id-derived seed makes the
        # fresh draw identical to the cached one
        for nid in (1, 2, 3):
            srv.embed_direct(nid)
        assert len(srv._cache) == 2
        np.testing.assert_array_equal(a, srv.embed_direct(5))


# ----------------------------------------------------------- frontend


def test_frontend_roundtrip_and_errors(server):
    from euler_tpu.serving import EmbedClient, EmbedFrontend

    fe = EmbedFrontend(server, port=0)
    try:
        c = EmbedClient(fe.address)
        rows = c.embed([3, 6, 3])
        assert rows.shape == (3, 8) and rows.dtype == np.float32
        # wire is bit-exact, duplicates preserved
        np.testing.assert_array_equal(rows[0], server.embed_direct(3))
        np.testing.assert_array_equal(rows[0], rows[2])
        s = c.stats()
        assert s["ok"] and s["slo"]["count"] >= 1
        assert c.ping() == {"ok": True, "draining": False}
        with pytest.raises(RuntimeError, match="embed needs ids"):
            c.embed([])
        c.close()
    finally:
        fe.stop()


def test_frontend_connection_cap_sheds_busy(server):
    from euler_tpu.serving import EmbedClient, EmbedFrontend

    fe = EmbedFrontend(server, port=0, max_conns=1)
    try:
        c1 = EmbedClient(fe.address)
        assert c1.ping()["ok"]  # holds the only slot
        c2 = EmbedClient(fe.address)
        with pytest.raises(BusyError):
            c2.ping()
        assert native.counters()["serve_busy_rejects"] >= 1
        c2.close()
        c1.close()
    finally:
        fe.stop()


def test_frontend_drain_refuses_new_connections(server):
    from euler_tpu.serving import EmbedClient, EmbedFrontend

    fe = EmbedFrontend(server, port=0)
    addr = fe.address
    fe.drain(grace_s=1.0)
    with pytest.raises((ConnectionError, OSError, BusyError)):
        EmbedClient(addr, timeout_s=2.0).ping()
    fe.stop()


# ----------------------------------------------------------- console


def test_console_embed_command(server, capsys):
    from euler_tpu.console import Console
    from euler_tpu.serving import EmbedFrontend

    fe = EmbedFrontend(server, port=0)
    try:
        con = Console()
        con.execute(f'embed {fe.address} "1, 2"')
        out = capsys.readouterr().out
        assert "1:" in out and "2:" in out and "dim=8" in out
    finally:
        fe.stop()


# ----------------------------------------------------- run_loop flags


def test_run_loop_rejects_serve_flags_without_serve_after():
    from euler_tpu import run_loop

    p = run_loop.define_flags()
    a = p.parse_args(["--data_dir", "/tmp/x", "--serve_slo_ms", "50"])
    with pytest.raises(ValueError, match="--serve_slo_ms.*--serve_after"):
        run_loop.check_serve_flags(a)
    a = p.parse_args(["--data_dir", "/tmp/x", "--mode", "evaluate",
                      "--serve_after", "1"])
    with pytest.raises(ValueError, match="--mode=train"):
        run_loop.check_serve_flags(a)
    # clean configs pass
    run_loop.check_serve_flags(p.parse_args(["--data_dir", "/tmp/x"]))
    run_loop.check_serve_flags(p.parse_args(
        ["--data_dir", "/tmp/x", "--serve_after", "1",
         "--serve_port", "9777"]
    ))


# ----------------------------------------------------------- the storm


def test_serve_storm_over_live_cluster(tmp_path):
    """16 concurrent clients against the full stack — frontend +
    micro-batcher + remote 2-shard graph: every client completes with
    retries, shedding shows on the live scrape, p99 stays bounded, and
    served rows stay bit-identical to the direct path."""
    import jax

    import euler_tpu
    from euler_tpu.graph.service import GraphService
    from euler_tpu.serve import EmbedServer
    from euler_tpu.serving import EmbedClient, EmbedFrontend
    from euler_tpu.train import get_optimizer
    from tests.fixture_graph import write_fixture

    data = str(tmp_path / "data")
    reg = str(tmp_path / "reg")
    import os

    os.makedirs(data)
    os.makedirs(reg)
    write_fixture(data, num_partitions=4)
    services = [GraphService(data, s, 2, registry=reg) for s in range(2)]
    server = frontend = None
    try:
        remote = euler_tpu.Graph(mode="remote", registry=reg, retries=4)
        model = _sage()
        state = model.init_state(
            jax.random.PRNGKey(3), remote, np.arange(8),
            get_optimizer("adam", 0.01),
        )
        server = EmbedServer(
            model, remote, state, max_batch=8, max_wait_us=1000,
            queue_cap=2, slo_ms=5000.0,
        ).start()
        frontend = EmbedFrontend(server, port=0, max_conns=24)
        server.embed_direct(1)  # compile outside the measured window

        ids = sorted(TOPOLOGY)
        per_client = 8
        completed: dict = {}

        def client(cid):
            import random

            rng = random.Random(cid)
            c = EmbedClient(frontend.address)
            done = retries = 0
            try:
                while done < per_client:
                    pick = rng.sample(ids, rng.randint(1, 3))
                    try:
                        rows = c.embed(pick)
                    except BusyError:
                        retries += 1
                        time.sleep(0.002)
                        continue
                    assert rows.shape == (len(pick), 8)
                    done += 1
                completed[cid] = retries
            finally:
                c.close()

        ts = [threading.Thread(target=client, args=(i,), daemon=True)
              for i in range(16)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)

        scrape = EmbedClient(frontend.address)
        stats = scrape.stats()
        assert len(completed) == 16, "a client thread died"
        assert stats["slo"]["count"] >= 16 * per_client
        assert stats["slo"]["p99_ms"] <= 5000.0
        assert stats["counters"]["serve_busy_rejects"] > 0, (
            "queue_cap=2 under 16 clients must shed"
        )
        assert (stats["counters"]["serve_batches"]
                < stats["counters"]["serve_requests"])
        # parity survived the storm
        row = scrape.embed([ids[3]])[0]
        np.testing.assert_array_equal(row, server.embed_direct(ids[3]))
        scrape.close()
    finally:
        if frontend is not None:
            frontend.drain(grace_s=2.0)
        if server is not None:
            server.close()
        if frontend is not None:
            frontend.stop()
        for s in services:
            s.drain()
            s.stop()


# ------------------------------------------------- end-to-end CLI glue

_CLI_COMMON = [
    "--max_id", "16", "--feature_idx", "0", "--feature_dim", "2",
    "--label_idx", "2", "--label_dim", "3", "--train_edge_type", "0,1",
    "--all_edge_type", "0,1", "--fanouts", "3,2", "--dim", "8",
    "--batch_size", "8", "--num_epochs", "2", "--log_steps", "100",
    "--model", "graphsage_supervised",
]


def test_serve_cli_glue_restores_and_serves(fixture_dir, tmp_path):
    """The `python -m euler_tpu.serve` wiring without the signal loop:
    train a checkpoint via run_loop, then build the server from the
    same flag surface (restore_serving_state + build_server +
    run_serve(block=False)) and round-trip an embed."""
    from euler_tpu import run_loop, serve
    from euler_tpu.parallel import make_mesh
    from euler_tpu.serving import EmbedClient

    ck = str(tmp_path / "ck")
    base = ["--data_dir", fixture_dir, "--model_dir", ck] + _CLI_COMMON
    assert run_loop.main(base + ["--mode", "train"]) == 0

    args = run_loop.define_flags().parse_args(
        base + ["--serve_port", "0", "--serve_max_batch", "4",
                "--serve_slo_ms", "500"]
    )
    args.mode = "evaluate"  # what serve.main() forces
    graph, services = run_loop.build_graph(args)
    server = frontend = None
    try:
        mesh = make_mesh(args.num_devices)
        model = run_loop.build_model(args, graph)
        server, frontend = serve.run_serve(
            model, graph, args, mesh, block=False
        )
        c = EmbedClient(frontend.address)
        rows = c.embed([10, 16])
        assert rows.shape == (2, 8) and rows.dtype == np.float32
        np.testing.assert_array_equal(rows[0], server.embed_direct(10))
        c.close()
    finally:
        if frontend is not None:
            frontend.drain(grace_s=1.0)
        if server is not None:
            server.close()
        if frontend is not None:
            frontend.stop()
        for s in services:
            if hasattr(s, "drain"):
                s.drain()
            s.stop()

    # serving an untrained --model_dir fails LOUDLY at startup
    args2 = run_loop.define_flags().parse_args(
        ["--data_dir", fixture_dir,
         "--model_dir", str(tmp_path / "never")] + _CLI_COMMON
    )
    args2.mode = "evaluate"
    graph2, services2 = run_loop.build_graph(args2)
    try:
        model2 = run_loop.build_model(args2, graph2)
        with pytest.raises(ValueError, match="no checkpoint in"):
            serve.build_server(model2, graph2, args2,
                               make_mesh(args2.num_devices))
    finally:
        for s in services2:
            s.stop()


def test_serve_after_trains_then_serves_until_sigterm(fixture_dir,
                                                      tmp_path):
    """run_loop --serve_after=1 end-to-end in a subprocess: train, save,
    serve on the flagged port, answer a live embed, drain on SIGTERM,
    exit 0."""
    import os
    import re
    import signal
    import socket
    import subprocess
    import sys

    from euler_tpu.serving import EmbedClient

    with socket.create_server(("127.0.0.1", 0)) as s:
        port = s.getsockname()[1]  # free-port probe (tiny reuse race)
    ck = str(tmp_path / "ck")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "-m", "euler_tpu",
         "--data_dir", fixture_dir, "--model_dir", ck, "--mode", "train",
         "--serve_after", "1", "--serve_port", str(port),
         "--serve_max_batch", "4"] + _CLI_COMMON,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env,
    )
    try:
        deadline = time.monotonic() + 180
        client = None
        while time.monotonic() < deadline:
            assert proc.poll() is None, (
                f"run_loop died early:\n{proc.stdout.read()}"
            )
            try:
                client = EmbedClient(f"127.0.0.1:{port}", timeout_s=5)
                break
            except OSError:
                time.sleep(0.25)
        assert client is not None, "server never came up"
        rows = client.embed([12, 15, 12])
        assert rows.shape == (3, 8)
        np.testing.assert_array_equal(rows[0], rows[2])
        assert client.stats()["slo"]["count"] >= 1
        client.close()
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, f"non-zero exit:\n{out}"
        assert re.search(r"serve SLO at exit", out)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
