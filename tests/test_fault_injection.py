"""Deterministic failpoints (_native/eg_fault) + failure counters.

Every failure path in the remote stack used to be reachable only by real
process kills; these tests drive each one through the seeded failpoint
layer and pin the exact counter arithmetic: each counter increments
precisely when its failpoint fires, the injected-fault ledger matches,
and a fault seed replays the identical failure sequence (the property
the chaos soak in test_chaos_soak.py builds on).

The injector is process-global (like the stats it feeds), so every test
clears it on the way out — a leaked failpoint would chaos-test the rest
of the suite.
"""

import time

import numpy as np
import pytest

from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService

COUNTER_NAMES = {
    "dials_failed", "retries", "quarantines", "failovers", "calls_failed",
    "deadlines_exceeded", "frames_rejected", "rediscoveries",
    "heartbeat_misses",
    # remote hot-path efficiency ledger (PR 3): dedup/cache/chunking
    # wins plus op-level shard failures
    "ids_deduped", "cache_hits", "cache_misses", "rpc_chunks", "rpc_errors",
    # server-side survivability ledger (PR 4): bounded admission, wedge
    # timeouts, deadline refusals, drains, wire downgrades
    "busy_rejects", "busy_failovers", "handler_timeouts",
    "deadline_rejects", "draining", "wire_downgrades",
    # training input pipeline ledger (PR 6): prefetch production/drop
    # accounting and dead-worker visibility
    "prefetch_produced", "prefetch_dropped", "prefetch_worker_errors",
    # postmortem ledger (PR 7): fires of the seeded crash failpoint,
    # counted before the raise so the dump's snapshot includes them
    "crashes",
    # locality ledger (PR 9): neighbor-list cache hits/misses, TinyLFU
    # admission rejections, and placement-map fallbacks to hash routing
    "nbr_cache_hits", "nbr_cache_misses", "cache_admit_rejects",
    "placement_fallbacks",
    # serving ledger (PR 11): admitted embed requests, admission sheds
    # (batcher queue cap + frontend connection cap), deadline expiries
    # caught before dispatch, and coalesced device dispatches
    "serve_requests", "serve_busy_rejects", "serve_deadline_rejects",
    "serve_batches",
    # device-plane ledger (PR 15): XLA compiles/recompiles, the serve
    # compile-storm guard, and host<->device transfer bytes
    "device_compiles", "device_recompiles", "serve_recompiles",
    "h2d_bytes", "d2h_bytes",
    # async-sampler ledger (PR 18): completion-queue submissions, the
    # high-water mark of concurrently running ops, and hop/slice
    # continuations re-enqueued by job completions
    "async_submits", "async_inflight_peak", "async_continuations",
    # snapshot-epoch ledger (PR 19): delta flips, retired-epoch drains,
    # stale cache generations evicted on touch, and refused delta loads
    "epoch_flips", "epoch_drains", "epoch_stale_hits_evicted",
    "delta_loads_failed",
}
FAULT_NAMES = {
    "dial", "send_frame", "recv_frame", "service_reply", "registry_reply",
    "heartbeat", "accept", "handler_stall", "busy_force", "crash",
    "delta_load", "epoch_flip",
}


@pytest.fixture(autouse=True)
def _clean_faults():
    """No failpoint may outlive its test (process-global injector)."""
    native.fault_clear()
    native.reset_counters()
    yield
    native.fault_clear()
    native.reset_counters()


@pytest.fixture(scope="module")
def shard(tmp_path_factory):
    """One live shard on an ephemeral port + its flat-file registry."""
    from tests.fixture_graph import write_fixture

    data = str(tmp_path_factory.mktemp("fault_data"))
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path_factory.mktemp("fault_reg"))
    svc = GraphService(data, 0, 1, registry=reg)
    yield svc, reg
    svc.stop()


def nonzero(d):
    return {k: v for k, v in d.items() if v}


# ---------------------------------------------------------------------------
# surface: spec grammar, names, Python round-trip
# ---------------------------------------------------------------------------


def test_counters_round_trip_to_python():
    import euler_tpu

    snap = euler_tpu.counters()
    assert set(snap) == COUNTER_NAMES
    assert all(isinstance(v, int) for v in snap.values())
    euler_tpu.counters_reset()
    assert nonzero(euler_tpu.counters()) == {}


def test_fault_ledger_names():
    assert set(native.fault_injected()) == FAULT_NAMES


@pytest.mark.parametrize(
    "bad",
    [
        "bogus:err@0.5",          # unknown point
        "dial",                   # no action
        "dial:explode@1",         # unknown action
        "dial:err@0.0",           # probability out of (0,1]
        "dial:err@2.0",
        "dial:err@x",
        "dial:delay@-5",
        "dial:err@0.5#x",         # bad limit
        "dial:err@0.5,dial:err@0.5",  # duplicate point
    ],
)
def test_malformed_specs_raise_and_install_nothing(bad):
    with pytest.raises(ValueError):
        native.fault_config(bad, 1)
    assert nonzero(native.fault_injected()) == {}


def test_valid_spec_forms_accepted():
    native.fault_config(
        "dial:err@1.0#2,send_frame:delay@10,recv_frame:delay@5@0.5#3", 9
    )
    native.fault_config("", 0)  # empty spec clears


def test_graph_rejects_fault_on_local_mode(shard, tmp_path):
    svc, reg = shard
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), fault="dial:err@0.5")


# ---------------------------------------------------------------------------
# each counter increments exactly when its failpoint fires
# ---------------------------------------------------------------------------


def test_dial_fault_counts_exactly(shard):
    svc, reg = shard
    # Init performs exactly one kInfo Call; dial:err@1.0#2 fails the
    # first two attempts, the third dials clean — each number is forced.
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1, fault="dial:err@1.0#2", fault_seed=1)
    try:
        assert native.fault_injected()["dial"] == 2
        ctr = native.counters()
        assert ctr["dials_failed"] == 2
        assert ctr["retries"] == 2
        assert ctr["quarantines"] == 2
        assert ctr["failovers"] == 1
        assert ctr["calls_failed"] == 0
    finally:
        g.close()


def test_send_frame_fault_counts_exactly(shard):
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1)
    try:
        ids = np.array([10, 11, 12, 13], dtype=np.int64)
        g.node_types(ids)  # warm the pooled connection
        native.fault_config("send_frame:err@1.0#1", 5)
        native.counters_reset()
        t = g.node_types(ids)
        np.testing.assert_array_equal(t, [0, 1, 0, 1])  # retried through
        assert native.fault_injected()["send_frame"] == 1
        ctr = native.counters()
        assert ctr["retries"] == 1
        assert ctr["quarantines"] == 1
        assert ctr["failovers"] == 1
        assert ctr["dials_failed"] == 0  # the redial succeeded
    finally:
        g.close()


def test_recv_frame_fault_counts_exactly(shard):
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1)
    try:
        ids = np.array([10, 11], dtype=np.int64)
        g.node_types(ids)
        # the in-process shard shares the injector, and recv_frame fires
        # only once a frame has begun arriving — so the one fire lands
        # deterministically on the shard reading the request (the request
        # header always precedes the reply header); the client sees its
        # connection die mid-exchange and must fail over
        native.fault_config("recv_frame:err@1.0#1", 5)
        native.counters_reset()
        t = g.node_types(ids)
        np.testing.assert_array_equal(t, [0, 1])
        assert native.fault_injected()["recv_frame"] == 1
        ctr = native.counters()
        assert ctr["retries"] == 1, ctr
        assert ctr["quarantines"] == 1, ctr
        assert ctr["failovers"] == 1, ctr
    finally:
        g.close()


def test_deadline_spans_all_retries(shard):
    svc, reg = shard
    # recv always fails; generous retries but a 150 ms overall budget.
    # Without the per-call deadline this would grind through 10 backoff
    # sleeps; with it the call must abort quickly and say so.
    g = Graph(mode="remote", registry=reg, retries=10, timeout_ms=2000,
              backoff_ms=400, deadline_ms=150)
    try:
        g.node_types(np.array([10], dtype=np.int64))  # warm up, no faults
        native.fault_config("recv_frame:err@1.0", 3)
        native.counters_reset()
        t0 = time.monotonic()
        t = g.node_types(np.array([10], dtype=np.int64))
        elapsed = time.monotonic() - t0
        assert elapsed < 1.5, "deadline did not bound the retry loop"
        assert t[0] == -1  # degraded to default, not wedged
        ctr = native.counters()
        assert ctr["deadlines_exceeded"] == 1
        assert ctr["calls_failed"] == 1
    finally:
        native.fault_clear()
        g.close()


def test_frames_rejected_on_error_status_reply(shard):
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=1, timeout_ms=2000)
    try:
        native.counters_reset()
        # a request whose result cannot fit a reply frame gets an error
        # status from the shard (OversizedResult) — the client must count
        # the refusal, not silently zero-fill
        out = g.get_dense_feature(
            np.array([10], dtype=np.int64), [0], [2 ** 29]
        )
        assert float(np.abs(out).sum()) == 0.0
        assert native.counters()["frames_rejected"] >= 1
    finally:
        g.close()


def test_delay_fault_injects_latency_without_failing(shard):
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=1, timeout_ms=2000)
    try:
        ids = np.array([10, 11], dtype=np.int64)
        g.node_types(ids)
        native.fault_config("send_frame:delay@80", 11)
        native.counters_reset()
        t0 = time.monotonic()
        t = g.node_types(ids)
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(t, [0, 1])  # slow, not wrong
        assert elapsed >= 0.08
        assert native.fault_injected()["send_frame"] >= 1
        assert native.counters()["retries"] == 0  # delay is not a failure
    finally:
        g.close()


def test_service_reply_fault_forces_client_retry(shard):
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1)
    try:
        ids = np.array([10, 11, 12, 13], dtype=np.int64)
        g.node_types(ids)
        # the shard runs in-process here, so its failpoints and the
        # client's share one injector — exactly one computed reply is
        # dropped on the floor before send
        native.fault_config("service_reply:err@1.0#1", 5)
        native.counters_reset()
        t = g.node_types(ids)
        np.testing.assert_array_equal(t, [0, 1, 0, 1])
        assert native.fault_injected()["service_reply"] == 1
        assert native.counters()["retries"] >= 1
    finally:
        g.close()


def test_heartbeat_fault_counts_misses_and_survives(tmp_path):
    from euler_tpu.graph import registry as registry_mod
    from tests.fixture_graph import write_fixture

    import os

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = registry_mod.RegistryServer(host="127.0.0.1", ttl_ms=600)
    svc = None
    try:
        svc = GraphService(data, 0, 1, registry=reg.address)
        # beats run every max(ttl/3, 150) = 200 ms; force the next two to
        # miss — each miss must redial and re-REG so the entry stays live
        native.fault_config("heartbeat:err@1.0#2", 21)
        native.counters_reset()
        deadline = time.monotonic() + 5.0
        while (native.fault_injected()["heartbeat"] < 2
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert native.fault_injected()["heartbeat"] == 2
        assert native.counters()["heartbeat_misses"] == 2
        # despite two missed beats the shard never expired from LIST
        time.sleep(0.7)  # > ttl: only the redial re-REGs keep it alive
        assert 0 in registry_mod.query(reg.address)
    finally:
        native.fault_clear()
        if svc is not None:
            svc.stop()
        reg.stop()


def test_registry_reply_fault_fails_one_list(tmp_path):
    from euler_tpu.graph import registry as registry_mod

    reg = registry_mod.RegistryServer(host="127.0.0.1", ttl_ms=5000)
    try:
        registry_mod.query(reg.address)  # clean LIST works
        native.fault_config("registry_reply:err@1.0#1", 3)
        with pytest.raises(ConnectionError):
            registry_mod.query(reg.address)
        assert native.fault_injected()["registry_reply"] == 1
        registry_mod.query(reg.address)  # next LIST answers again
    finally:
        native.fault_clear()
        reg.stop()


def test_dispatcher_chunked_call_retries_through_faults(shard):
    """The persistent-dispatcher + chunked path must keep every transport
    guarantee of the old per-call-thread path: a chunk whose send fails
    retries through a redial, the counters account for it exactly, and
    the merged result is still correct."""
    svc, reg = shard
    # chunk_ids=2 forces the 6-unique-id request below into 3 chunks on
    # the single shard; cache off so the second call re-issues them
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1, chunk_ids=2, feature_cache_mb=0)
    try:
        ids = np.array([10, 11, 12, 13, 14, 15], dtype=np.int64)
        g.node_types(ids)  # warm pooled connections, pre-fault
        native.fault_config("send_frame:err@1.0#1", 7)
        native.counters_reset()
        t = g.node_types(ids)
        np.testing.assert_array_equal(t, [0, 1, 0, 1, 0, 1])
        assert native.fault_injected()["send_frame"] == 1
        ctr = native.counters()
        assert ctr["rpc_chunks"] == 3, ctr      # ceil(6 / 2) chunks issued
        assert ctr["retries"] == 1, ctr         # exactly the faulted chunk
        assert ctr["failovers"] == 1, ctr
        assert ctr["rpc_errors"] == 0, ctr      # the retry succeeded
    finally:
        g.close()


def test_rpc_errors_counts_exhausted_shard_call(shard):
    """When every retry of a chunk fails, the op-level failure (rows
    degraded to defaults) must be visible in rpc_errors — the counter
    the old ForShards bool-discard made impossible to observe."""
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=1, timeout_ms=2000,
              backoff_ms=1, deadline_ms=300)
    try:
        one = np.array([10], dtype=np.int64)
        g.node_types(one)  # warm up pre-fault
        native.fault_config("send_frame:err@1.0", 13)  # every send fails
        native.counters_reset()
        t = g.node_types(one)
        assert t[0] == -1  # degraded to default, not wedged
        ctr = native.counters()
        assert ctr["rpc_errors"] == 1, ctr
        assert ctr["calls_failed"] == 1, ctr
    finally:
        native.fault_clear()
        g.close()


# ---------------------------------------------------------------------------
# server-side survivability failpoints (eg_admission.cc): BUSY shedding,
# handler stalls -> deadline replies, accept-path drops — each counted
# exactly
# ---------------------------------------------------------------------------


def test_busy_force_fail_fast_failover(shard):
    """A forced-BUSY admission answer must trigger the client's
    fail-fast path: immediate redial, no retry burned, no backoff
    slept, no quarantine of the (alive, just shedding) server."""
    svc, reg = shard
    # armed BEFORE the client exists: Init's kInfo call dials fresh, so
    # each of the three forced BUSYs lands on a new connection
    native.fault_config("busy_force:err@1.0#3", 7)
    native.reset_counters()
    g = Graph(mode="remote", registry=reg, retries=2, timeout_ms=2000)
    try:
        t = g.node_types(np.array([10, 11], dtype=np.int64))
        np.testing.assert_array_equal(t, [0, 1])
        assert native.fault_injected()["busy_force"] == 3
        ctr = native.counters()
        assert ctr["busy_rejects"] == 3, ctr
        assert ctr["busy_failovers"] == 3, ctr
        assert ctr["retries"] == 0, ctr       # BUSY burns no attempt
        assert ctr["quarantines"] == 0, ctr   # and no quarantine
        assert ctr["calls_failed"] == 0, ctr
    finally:
        g.close()


def test_handler_stall_delay_forces_deadline_reply(shard):
    """A stalled handler must answer DEADLINE instead of computing a
    dead answer: the stall outlives the client's stamped budget, the
    server refuses pre-dispatch (deadline_rejects), and the client ends
    the call at once (deadlines_exceeded) instead of re-queueing work
    nobody will read."""
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=5, timeout_ms=2000,
              backoff_ms=1, deadline_ms=150)
    try:
        one = np.array([10], dtype=np.int64)
        g.node_types(one)  # warm up: pooled conn, negotiated v2
        native.fault_config("handler_stall:delay@400#1", 9)
        native.reset_counters()
        t0 = time.monotonic()
        t = g.node_types(one)
        elapsed = time.monotonic() - t0
        assert t[0] == -1  # degraded to default, not wedged
        assert elapsed < 1.5, "DEADLINE reply did not end the call"
        assert native.fault_injected()["handler_stall"] == 1
        ctr = native.counters()
        assert ctr["deadline_rejects"] == 1, ctr   # server side
        assert ctr["deadlines_exceeded"] == 1, ctr  # client side
        assert ctr["calls_failed"] == 1, ctr
        assert ctr["retries"] == 0, ctr  # no retry of dead work
    finally:
        native.fault_clear()
        g.close()


def test_accept_fault_drops_connection_and_client_retries(shard):
    """accept:err drops the freshly-accepted connection on the floor —
    the client sees a mid-exchange reset on a connection that dialed
    fine, and must recover through the ordinary retry path."""
    svc, reg = shard
    native.fault_config("accept:err@1.0#1", 11)
    native.reset_counters()
    g = Graph(mode="remote", registry=reg, retries=3, timeout_ms=2000,
              backoff_ms=1)
    try:
        t = g.node_types(np.array([10, 11], dtype=np.int64))
        np.testing.assert_array_equal(t, [0, 1])
        assert native.fault_injected()["accept"] == 1
        ctr = native.counters()
        assert ctr["retries"] == 1, ctr
        assert ctr["quarantines"] == 1, ctr
        assert ctr["failovers"] == 1, ctr
        assert ctr["dials_failed"] == 0, ctr  # the connect itself worked
    finally:
        g.close()


# ---------------------------------------------------------------------------
# determinism: the seed owns the failure sequence
# ---------------------------------------------------------------------------


def _failure_pattern(reg, seed, n=48):
    """Per-call success/failure pattern of n sequential single-id queries
    under send_frame:err@0.5 with zero retries. The in-process shard
    shares the injector, so the stream's draws interleave client
    request-sends and shard reply-sends — but on a single connection that
    interleaving is itself fixed, so the observable pattern is a pure
    function of the seed."""
    g = Graph(mode="remote", registry=reg, retries=0, timeout_ms=2000,
              quarantine_ms=1)
    try:
        one = np.array([10], dtype=np.int64)
        g.node_types(one)  # warm-up before the faults arm
        native.fault_config("send_frame:err@0.5", seed)
        return tuple(int(g.node_types(one)[0]) == 0 for _ in range(n))
    finally:
        native.fault_clear()
        g.close()


def test_same_seed_replays_identical_failure_sequence(shard):
    svc, reg = shard
    a1 = _failure_pattern(reg, seed=1234)
    a2 = _failure_pattern(reg, seed=1234)
    b = _failure_pattern(reg, seed=99)
    assert a1 == a2, "same seed must replay the same injected failures"
    assert a1 != b, "a different seed must explore a different sequence"
    assert any(a1) and not all(a1), "p=0.5 must mix successes and failures"


# ---------------------------------------------------------------------------
# async whole-step sampling (eg_remote_sample_async): a shard fault that
# lands mid-continuation must degrade exactly like the sync path — same
# counter arithmetic, same strict= contract — and the handle must still
# complete (a faulted op that never reaches kDone would wedge take())
# ---------------------------------------------------------------------------


METAPATH = [[0, 1], [0, 1]]
FANOUTS = [3, 2]


def test_async_fault_degrades_exactly_like_sync(shard):
    """Total send blackout during a 2-hop fan-out: the sync call and the
    async op run the SAME NbrPrep/chunk/Finish phases, so under an
    identical fault seed they must produce the identical degraded result
    and the identical op-level failure ledger."""
    svc, reg = shard
    ids = np.array([10, 12, 14, 16], dtype=np.int64)

    def run(async_mode):
        # fresh client per run: both start from an un-quarantined pool
        # and a cold neighbor cache, so the fault stream sees the same
        # call sequence (cache off => every hop goes to the wire)
        g = Graph(mode="remote", registry=reg, retries=0, timeout_ms=2000,
                  backoff_ms=1, neighbor_cache_mb=0)
        try:
            g.sample_fanout(ids, METAPATH, FANOUTS)  # warm connections
            native.fault_config("send_frame:err@1.0", 31)
            native.counters_reset()
            if async_mode:
                h = g.sample_fanout_async(ids, METAPATH, FANOUTS)
                assert h is not None, "async submit refused"
                out = h.take()
            else:
                out = g.sample_fanout(ids, METAPATH, FANOUTS)
            ctr = native.counters()
            native.fault_clear()
            return out, ctr
        finally:
            native.fault_clear()
            g.close()

    (s_ids, s_w, s_t), s_ctr = run(async_mode=False)
    (a_ids, a_w, a_t), a_ctr = run(async_mode=True)
    # identical degraded output (default-filled rows included)
    for a, b in zip(s_ids, a_ids):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(s_w, a_w):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # identical op-level failure arithmetic: every chunk failed in both
    assert s_ctr["rpc_errors"] >= 1
    assert a_ctr["rpc_errors"] == s_ctr["rpc_errors"], (s_ctr, a_ctr)
    assert a_ctr["calls_failed"] == s_ctr["calls_failed"], (s_ctr, a_ctr)
    # and the async ledger accounted for the op
    assert a_ctr["async_submits"] == 1
    assert s_ctr["async_submits"] == 0


def test_async_strict_raises_at_take_and_recovers(shard):
    """strict=1: a shard failure inside an async op must surface as the
    same RuntimeError the sync path raises — deferred to take(), the
    first point the caller touches the result — and the pending error is
    consumed so the next healthy call proceeds."""
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=0, timeout_ms=2000,
              backoff_ms=1, neighbor_cache_mb=0, strict=True)
    try:
        ids = np.array([10, 12], dtype=np.int64)
        g.sample_fanout(ids, METAPATH, FANOUTS)  # healthy: strict silent
        native.fault_config("send_frame:err@1.0", 33)
        h = g.sample_fanout_async(ids, METAPATH, FANOUTS)
        assert h is not None
        with pytest.raises(RuntimeError, match="shard"):
            h.take()
        native.fault_clear()
        # error consumed: a following healthy async op succeeds
        h2 = g.sample_fanout_async(ids, METAPATH, FANOUTS)
        out_ids, _, _ = h2.take()
        assert [len(x) for x in out_ids] == [2, 6, 12]
    finally:
        native.fault_clear()
        g.close()


def test_async_handle_completes_under_delay_fault(shard):
    """A delay fault stretches the continuation chain without failing
    it: poll() reports running, take() blocks until done, and the
    result is correct — the op is slow, not wrong."""
    svc, reg = shard
    g = Graph(mode="remote", registry=reg, retries=1, timeout_ms=2000,
              backoff_ms=1, neighbor_cache_mb=0)
    try:
        ids = np.array([10, 12], dtype=np.int64)
        g.sample_fanout(ids, METAPATH, FANOUTS)  # warm
        native.fault_config("send_frame:delay@60", 35)
        native.counters_reset()
        h = g.sample_fanout_async(ids, METAPATH, FANOUTS)
        assert h is not None
        out_ids, out_w, _ = h.take()
        assert [len(x) for x in out_ids] == [2, 6, 12]
        ctr = native.counters()
        assert ctr["retries"] == 0  # delay is not a failure
        assert ctr["async_continuations"] >= 1
    finally:
        native.fault_clear()
        g.close()
