"""Metric units against independently computed values.

The model suites exercise these only as "finite and in [0,1]"; here each
metric is pinned to a hand-computed numpy (or sklearn, when available)
value on randomized inputs, so a silent formula regression cannot hide
behind a still-descending loss. AUC's own edge cases live in
tests/test_lshne_lasgnn.py::test_auc_metric.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from euler_tpu.nn import metrics  # noqa: E402

try:
    import sklearn  # noqa: F401

    HAVE_SKLEARN = True
except ImportError:  # keep the numpy-only tests running without it
    HAVE_SKLEARN = False


def test_micro_f1_matches_numpy():
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 2, (64, 7))
    preds = rng.integers(0, 2, (64, 7))
    counts = metrics.f1_counts(jnp.asarray(labels), jnp.asarray(preds))
    tp = np.sum((labels == 1) & (preds == 1))
    fp = np.sum((labels == 0) & (preds == 1))
    fn = np.sum((labels == 1) & (preds == 0))
    expect = 2 * tp / (2 * tp + fp + fn)
    assert abs(metrics.f1_from_counts(counts) - expect) < 1e-5
    # accumulation across batches == one big batch
    c1 = metrics.f1_counts(jnp.asarray(labels[:32]), jnp.asarray(preds[:32]))
    c2 = metrics.f1_counts(jnp.asarray(labels[32:]), jnp.asarray(preds[32:]))
    assert abs(metrics.f1_from_counts(c1 + c2) - expect) < 1e-5


@pytest.mark.skipif(not HAVE_SKLEARN, reason="sklearn not installed")
def test_f1_matches_sklearn():
    from sklearn.metrics import f1_score

    rng = np.random.default_rng(2)
    labels = rng.integers(0, 2, (100, 5))
    preds = rng.integers(0, 2, (100, 5))
    counts = metrics.f1_counts(jnp.asarray(labels), jnp.asarray(preds))
    expect = f1_score(labels, preds, average="micro")
    assert abs(metrics.f1_from_counts(counts) - expect) < 1e-6


def test_mrr_matches_hand_ranks():
    # positive score 0.9 vs negatives [0.95, 0.5, 0.2] -> rank 2
    # positive score 0.8 vs negatives [0.9, 0.85, 0.8] -> ties count
    #   against the positive: rank 1 + 3 = 4
    logits = jnp.asarray([[[0.9]], [[0.8]]])
    negs = jnp.asarray([[[0.95, 0.5, 0.2]], [[0.9, 0.85, 0.8]]])
    expect = np.mean([1.0 / 2.0, 1.0 / 4.0])
    assert abs(float(metrics.mrr(logits, negs)) - expect) < 1e-6
    # all negatives below the positive -> MRR exactly 1
    assert float(
        metrics.mrr(jnp.asarray([[[1.0]]]), jnp.asarray([[[0.1, 0.2]]]))
    ) == 1.0


def test_accuracy_matches_numpy():
    rng = np.random.default_rng(3)
    labels = rng.random((50, 4))
    preds = rng.random((50, 4))
    expect = np.mean(labels.argmax(-1) == preds.argmax(-1))
    got = float(metrics.accuracy(jnp.asarray(labels), jnp.asarray(preds)))
    assert abs(got - expect) < 1e-6


@pytest.mark.skipif(not HAVE_SKLEARN, reason="sklearn not installed")
def test_streaming_auc_close_to_sklearn():
    """Bucketed streaming AUC must track exact sklearn AUC within the
    histogram resolution (200 bins -> sub-1% on smooth score dists)."""
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(4)
    # genuinely overlapping distributions: the comparison is only
    # non-vacuous if the exact AUC sits strictly inside (0.5, 1.0)
    labels = rng.integers(0, 2, 4000)
    scores = np.clip(
        0.6 * rng.random(4000) + 0.3 * labels, 0.0, 0.999
    )
    acc = np.zeros((2, metrics.AUC_BINS))
    for lo in range(0, 4000, 500):  # streamed in batches
        acc = acc + np.asarray(
            metrics.auc_counts(
                jnp.asarray(labels[lo:lo + 500]),
                jnp.asarray(scores[lo:lo + 500]),
            )
        )
    expect = roc_auc_score(labels, scores)
    assert 0.55 < expect < 0.97  # guard: stays non-vacuous under reseeds
    assert abs(metrics.auc_from_counts(acc) - expect) < 0.01
