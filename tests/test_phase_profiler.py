"""Step-phase profiler: input-stall attribution, prefetch gauges, and
the merged Perfetto trace export (OBSERVABILITY.md "Step phases").

The determinism spine is the same as test_telemetry's: PR-2's seeded
`handler_stall:delay@25` failpoint pins EXACT log2 bucket placement —
a 25 ms stall in the sampler must land in `sample` and `input_stall`
bucket 15 ([16384, 32768) µs) and NEVER in `device`.
"""

import io
import json
import time
from contextlib import redirect_stdout

import numpy as np
import pytest

from euler_tpu import telemetry as T
from euler_tpu import trace as TR
from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from euler_tpu.parallel import prefetch
from tests.fixture_graph import write_fixture

IDS = np.array([10, 11, 12, 13], dtype=np.int64)
STALL_BUCKET = 15  # 25 ms -> [16384, 32768) µs


@pytest.fixture(autouse=True)
def _clean_slate():
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    T.set_telemetry(True)
    T.set_trace_sink(None)
    yield
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    T.set_telemetry(True)
    T.set_trace_sink(None)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("phase_data"))
    write_fixture(d, num_partitions=2)
    return d


def _graph(svcs, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("timeout_ms", 5000)
    return Graph(mode="remote", shards=[s.address for s in svcs], **kw)


# ---------------------------------------------------------------------------
# the phase enum + bucket arithmetic pins
# ---------------------------------------------------------------------------


def test_phase_names_pin_the_native_enum_order():
    """record_phase() indexes the native enum by PHASES order — each
    name must land in its own histogram cell."""
    for i, name in enumerate(T.PHASES):
        T.record_phase(name, 10 * (i + 1))
    hists = T.phase_hists()
    assert set(hists) == set(T.PHASES)
    for i, name in enumerate(T.PHASES):
        assert hists[name]["count"] == 1, name
        assert hists[name]["sum_us"] == 10 * (i + 1), name


def test_record_phase_exact_bucket_and_reset():
    T.record_phase("input_stall", 25_000)
    h = T.phase_hists()["input_stall"]
    assert h["b"][STALL_BUCKET] == 1 and h["count"] == 1
    T.telemetry_reset()  # must clear phase cells too
    assert T.phase_hists()["input_stall"]["count"] == 0


def test_prefetch_gauge_value_histograms():
    T.record_prefetch_gauges(3, 2)
    T.record_prefetch_gauges(0, 1)
    data = T.telemetry_json()
    depth, busy = data["hist"]["prefetch_depth"], data["hist"]["prefetch_busy"]
    assert depth["count"] == 2 and depth["sum_us"] == 3
    assert busy["count"] == 2 and busy["sum_us"] == 3
    assert depth["b"][0] == 1  # the zero-depth dequeue
    assert depth["b"][T.bucket_of(3)] == 1


# ---------------------------------------------------------------------------
# stall attribution under a seeded failpoint (the ISSUE's acceptance
# drill): delay lands in sample/input_stall, NEVER in device
# ---------------------------------------------------------------------------


def test_seeded_stall_lands_in_sample_and_input_stall_never_device(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            g.node_types(IDS)  # dial/warm outside the pinned window
            native.fault_config("handler_stall:delay@25", 7)
            T.telemetry_reset()
            # synchronous prefetch path: the consumer IS the producer,
            # so each of the 3 steps is one full 25 ms stall — exact
            # counts in bucket 15 on BOTH phase histograms
            steps = 3
            for _ in prefetch(
                lambda s: g.node_types(IDS), steps, depth=0, num_threads=1
            ):
                pass
            native.fault_clear()
            hists = T.phase_hists()
            for phase in ("sample", "input_stall"):
                h = hists[phase]
                assert h["count"] == steps, (phase, h)
                assert h["b"][STALL_BUCKET] == steps, (phase, h["b"])
            assert hists["device"]["count"] == 0, hists["device"]
            # mean stall (the ROADMAP input_stall_ms metric) moved by
            # at least the injected 25 ms
            snap = T.snapshot()
            assert snap["input_stall_ms"] >= 25.0
            assert snap["phases"]["sample"]["p50_us"] >= 16384
        finally:
            g.close()
    finally:
        svc.stop()


def test_threaded_prefetch_attributes_stall_and_leaves_device_alone(
    data_dir,
):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            g.node_types(IDS)
            native.fault_config("handler_stall:delay@25", 7)
            T.telemetry_reset()
            native.reset_counters()
            steps = 4
            got = list(prefetch(
                lambda s: (s, g.node_types(IDS))[0], steps,
                depth=1, num_threads=2,
            ))
            native.fault_clear()
            assert got == list(range(steps))
            hists = T.phase_hists()
            sample = hists["sample"]
            assert sample["count"] == steps
            # every produce stalled >= 25 ms: nothing below bucket 15
            assert sum(sample["b"][:STALL_BUCKET]) == 0, sample["b"]
            # the consumer stalled on at least the first batch; the
            # delay shows up in input_stall, not device
            stall = hists["input_stall"]
            assert stall["count"] == steps
            assert sum(stall["b"][STALL_BUCKET:]) >= 1, stall["b"]
            assert hists["device"]["count"] == 0
            # pipeline gauges: one dequeue sample per consumed step
            data = T.telemetry_json()
            assert data["hist"]["prefetch_depth"]["count"] == steps
            assert data["hist"]["prefetch_busy"]["count"] == steps
            assert native.counters()["prefetch_produced"] == steps
        finally:
            g.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# prefetch pipeline ledger: dropped batches + the kill-switch
# ---------------------------------------------------------------------------


def test_abandoned_iterator_counts_dropped_batches():
    native.reset_counters()
    it = prefetch(lambda s: s, 10, depth=3, num_threads=2)
    assert next(it) == 0
    time.sleep(0.05)  # let workers fill the depth window
    it.close()
    ctr = native.counters()
    assert ctr["prefetch_dropped"] >= 1, ctr
    assert ctr["prefetch_produced"] >= ctr["prefetch_dropped"]


def test_kill_switch_disables_phase_recording_and_ledger():
    T.set_telemetry(False)
    try:
        native.reset_counters()
        got = list(prefetch(lambda s: s, 4, depth=2, num_threads=2))
        assert got == [0, 1, 2, 3]
        T.record_phase("device", 1000)  # native gate drops it too
        data = T.telemetry_json()
        assert all(h["count"] == 0 for h in data["hist"].values())
        assert native.counters()["prefetch_produced"] == 0
    finally:
        T.set_telemetry(True)


# ---------------------------------------------------------------------------
# exposition surfaces: Prometheus families, JSONL snapshot, console
# ---------------------------------------------------------------------------


def test_metrics_text_renders_phase_and_prefetch_families():
    T.record_phase("input_stall", 25_000)
    T.record_prefetch_gauges(2, 1)
    text = T.metrics_text()
    assert ('eg_step_phase_us_bucket{phase="input_stall",le="32768"} 1'
            in text)
    assert 'eg_step_phase_us_count{phase="device"} 0' in text
    assert "eg_prefetch_queue_depth_sum 2" in text
    assert "eg_prefetch_workers_busy_count 1" in text
    assert 'eg_counter_total{name="prefetch_worker_errors"} 0' in text


def test_snapshot_carries_phases_and_prefetch_means():
    T.record_phase("input_stall", 2_000)
    T.record_phase("input_stall", 4_000)
    T.record_phase("device", 500)
    T.record_prefetch_gauges(4, 2)
    snap = T.snapshot(step=3)
    assert snap["input_stall_ms"] == 3.0  # mean of 2 ms + 4 ms
    assert snap["phases"]["input_stall"]["count"] == 2
    assert snap["phases"]["device"]["count"] == 1
    assert snap["prefetch"] == {
        "mean_queue_depth": 4.0, "mean_workers_busy": 2.0,
    }


def test_console_stats_phases():
    from euler_tpu.console import Console

    T.record_phase("input_stall", 25_000)
    T.record_prefetch_gauges(1, 1)
    native.counter_add("prefetch_worker_errors", 2)
    buf = io.StringIO()
    with redirect_stdout(buf):
        Console().do_stats(["phases"])
    out = buf.getvalue()
    assert "input_stall" in out
    assert "queue depth" in out
    assert "'prefetch_worker_errors': 2" in out


# ---------------------------------------------------------------------------
# trace recorder + merged Perfetto export
# ---------------------------------------------------------------------------


def test_trace_recorder_captures_phase_events_with_thread_lanes():
    rec = TR.TraceRecorder(capacity=8).start()
    try:
        T.record_phase("sample", 100, step=1)
        T.record_phase("device", 50, step=1)
        for i in range(10):
            T.record_phase("host", 10, step=i)
    finally:
        rec.stop()
    events = rec.events()
    assert len(events) == 8  # ring capacity
    assert rec.dropped == 4
    trace = TR.chrome_trace(events, [])
    evs = TR.validate_chrome_trace(trace)
    slices = [e for e in evs if e["ph"] == "X"]
    assert all(e["dur"] >= 0 and e["pid"] == TR.PID_TRAIN for e in slices)
    # stopped: further records don't land
    T.record_phase("sample", 100)
    assert len(rec.events()) == 8


def test_span_end_us_is_stamped_on_the_monotonic_clock():
    before = TR.now_us()
    T.record_span(1234, op=5)
    span = T.slow_spans()[0]
    assert before <= span["end_us"] <= TR.now_us()
    assert span["total_us"] == 1234


def test_merged_trace_correlates_client_and_server_by_trace_id(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            T.telemetry_reset()
            rec = TR.TraceRecorder().start()
            # a seeded 5 ms stall beats the journal floor on both sides
            native.fault_config("handler_stall:delay@5", 3)
            for _ in prefetch(
                lambda s: g.node_types(IDS), 3, depth=1, num_threads=2
            ):
                pass
            native.fault_clear()
            rec.stop()
            # the server journals its span right after replying — give
            # the racing worker a moment, like test_telemetry does
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if any(s["side"] == "server" for s in T.slow_spans()):
                    break
                time.sleep(0.01)
            trace = TR.chrome_trace(
                rec.events(), TR.gather_span_sources(g)
            )
            events = TR.validate_chrome_trace(trace)
            # phase slices and rpc slices share the timeline
            assert any(e.get("cat") == "phase" for e in events)
            correlated = TR.correlated_trace_ids(trace)
            assert correlated, [
                e for e in events if e.get("cat") == "rpc"
            ]
            # flow arrows exist for the correlated ids
            flows = {e["id"] for e in events if e["ph"] in ("s", "f")}
            assert correlated <= flows
            # round-trips through JSON untouched
            reread = json.loads(json.dumps(trace))
            assert TR.correlated_trace_ids(reread) == correlated
        finally:
            g.close()
    finally:
        svc.stop()


def test_trace_dump_smoke_end_to_end():
    """The scripts/trace_dump.py --smoke gate as a tier-1 member: a
    live 2-shard cluster's merged export is valid Chrome-trace JSON
    whose slow-span slices carry matching wire-v3 trace ids on both
    sides (the ISSUE acceptance line)."""
    from scripts.trace_dump import run_smoke

    assert run_smoke() == 0
