"""Snapshot epochs (_native/eg_epoch): delta loads, coordinated flips,
and generation-keyed invalidation — the mutable-graph contract.

Three properties pinned here, each the exit criterion of one leg of the
rolling-refresh runbook (DEPLOY.md "Rolling graph refresh"):

1. Whole-step snapshot consistency: an async sampling op PINS the epoch
   current at submit, so a flip that lands mid-continuation (the
   sampler_depth=2 ring keeps two steps in flight) must NOT leak new-
   snapshot rows into an old step — pre-flip submits return pre-flip
   rows bit-for-bit even when taken after the flip (kEpochKeep=2 holds
   the superseded snapshot until its pins drain).

2. Exact counter arithmetic per failpoint: `delta_load` (fires before
   the file is read) and `epoch_flip` (fires after the merged engine is
   built, exercising the staged-delta rollback) each count exactly one
   `delta_loads_failed`, leave the serving epoch untouched, and leave
   the shard able to apply the SAME delta afterwards — a refused load
   must stage nothing.

3. Delta hygiene: contradictory or duplicate edits are refused LOUDLY
   at both layers — convert.make_delta (duplicate node/edge records in
   an input) and the native DeltaFile::Validate (remove+re-emit of one
   key, duplicate removed ids, non-monotonic seq), each leaving the
   serving snapshot at its old epoch.

Plus the local closure property the whole design rests on: a flipped
snapshot is bit-identical to a fresh load of base + the same delta
chain (`Graph(directory=..., delta=...)`).
"""

import copy
import os
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from euler_tpu.graph.convert import make_delta, pack_block, pack_delta
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import FIXTURE_META, fixture_nodes, write_fixture


@pytest.fixture(autouse=True)
def _clean():
    """Failpoints and counters are process-global; no test may leak."""
    native.fault_clear()
    native.reset_counters()
    yield
    native.fault_clear()
    native.reset_counters()


# ---------------------------------------------------------------------------
# delta builders (diff the fixture against a mutated copy)
# ---------------------------------------------------------------------------


def _retarget_14(nodes):
    """14's single type-0 edge moves 15 -> 16: the one mutation the
    deterministic-parity tests lean on (one candidate before AND after,
    so no RNG is consulted and bit-parity is defined)."""
    n14 = next(n for n in nodes if n["node_id"] == 14)
    n14["neighbor"]["0"] = {"16": 2.0}
    for e in n14["edge"]:
        if e["edge_type"] == 0 and e["dst_id"] == 15:
            e["dst_id"] = 16
            e["uint64_feature"] = {"0": [14 * 100 + 16]}
            e["binary_feature"] = {"0": "e14-16"}
    return nodes


def _minimal_new_nodes():
    return _retarget_14([copy.deepcopy(n) for n in fixture_nodes()])


def _rich_new_nodes():
    """The full mutation menu in one delta: node removal (15), feature
    + weight change (10), edge removal (12-1->13), edge retarget (14),
    node addition (17)."""
    nodes = {n["node_id"]: copy.deepcopy(n) for n in fixture_nodes()}
    del nodes[15]
    nodes[10]["node_weight"] = 9.0
    nodes[10]["float_feature"]["0"] = [123.5, 7.25]
    n12 = nodes[12]
    n12["neighbor"]["1"].pop("13")
    n12["edge"] = [
        e for e in n12["edge"]
        if not (e["dst_id"] == 13 and e["edge_type"] == 1)
    ]
    _retarget_14(list(nodes.values()))
    nodes[17] = {
        "node_id": 17,
        "node_type": 1,
        "node_weight": 1.5,
        "neighbor": {"0": {"10": 3.0}},
        "uint64_feature": {"0": [17, 18], "1": [7]},
        "float_feature": {
            "0": [8.5, 4.25],
            "1": [1.0, 2.0, 3.0],
            "2": [0.0, 0.0, 0.0],
        },
        "binary_feature": {"0": "n17"},
        "edge": [{
            "src_id": 17, "dst_id": 10, "edge_type": 0, "weight": 3.0,
            "uint64_feature": {"0": [17 * 100 + 10]},
            "float_feature": {"0": [0.3]},
            "binary_feature": {"0": "e17-10"},
        }],
    }
    return list(nodes.values())


def _write_delta(path, new_nodes, seq=1):
    rm_n, rm_e, blob = make_delta(fixture_nodes(), new_nodes, FIXTURE_META)
    with open(path, "wb") as f:
        f.write(pack_delta(seq, rm_n, rm_e, blob))
    return path


def _one_shard(tmp_path):
    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    svc = GraphService(data, 0, 1, registry=reg)
    g = Graph(mode="remote", registry=reg)
    return svc, g


# ---------------------------------------------------------------------------
# local closure: a flip is bit-identical to a fresh merged load
# ---------------------------------------------------------------------------


def test_local_flip_bit_identical_to_fresh_merged_load(tmp_path):
    data = str(tmp_path / "g")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    dpath = _write_delta(str(tmp_path / "part.delta.1"), _rich_new_nodes())

    g = Graph(directory=data)
    fresh = None
    try:
        assert g.epoch() == 0
        nbr, _, _, cnt = g.get_full_neighbor(
            np.array([14], dtype=np.int64), [0]
        )
        assert list(np.asarray(nbr)[: int(cnt[0])]) == [15]

        assert g.load_delta(dpath) == 1
        assert g.epoch() == 1

        # every mutation landed
        ids = np.arange(10, 18, dtype=np.int64)
        types = g.node_types(ids)
        assert int(types[ids.tolist().index(15)]) == -1      # removed
        assert int(types[ids.tolist().index(17)]) == 1       # added
        np.testing.assert_allclose(
            g.get_dense_feature(np.array([10], dtype=np.int64), [0], [2])[0],
            [123.5, 7.25],
        )
        nbr, w, _, cnt = g.get_full_neighbor(
            np.array([14], dtype=np.int64), [0]
        )
        assert list(np.asarray(nbr)[: int(cnt[0])]) == [16]  # retargeted
        assert float(np.asarray(w)[0]) == 2.0
        nbr, _, _, cnt = g.get_full_neighbor(
            np.array([12], dtype=np.int64), [1]
        )
        assert list(np.asarray(nbr)[: int(cnt[0])]) == [14]  # (12,13,1) gone

        # the closure: flipped == fresh merged load, bit for bit
        fresh = Graph(directory=data, delta=dpath)
        assert fresh.epoch() == 1
        np.testing.assert_array_equal(g.node_types(ids),
                                      fresh.node_types(ids))
        np.testing.assert_array_equal(
            g.get_dense_feature(ids, [0], [2]),
            fresh.get_dense_feature(ids, [0], [2]),
        )
        for et in ([0], [1], [0, 1]):
            a = g.get_full_neighbor(ids, et)
            b = fresh.get_full_neighbor(ids, et)
            for x, y in zip(a, b):
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    finally:
        if fresh is not None:
            fresh.close()
        g.close()


# ---------------------------------------------------------------------------
# whole-step consistency: flip mid-flight under the depth-2 async ring
# ---------------------------------------------------------------------------


def test_epoch_flip_under_depth2_async_is_whole_step_consistent(tmp_path):
    from euler_tpu.parallel import pipeline

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    dpath = _write_delta(str(tmp_path / "part.delta.1"),
                         _minimal_new_nodes())
    services = [GraphService(data, s, 2, registry=reg) for s in range(2)]
    remote = Graph(mode="remote", registry=reg)
    try:
        # forced rows only: 11 -0-> {12}, 13 -0-> {10}, 14 -0-> {15 pre /
        # 16 post}, 15 has none — the strongest parity the server-side
        # RNG permits (test_async_parity's deterministic-slice trick)
        ids = np.array([11, 13, 14, 15], dtype=np.int64)
        fan = 4
        pre = np.repeat(
            np.array([12, 10, 15, -1], dtype=np.int64), fan
        ).reshape(len(ids), fan)
        post = np.repeat(
            np.array([12, 10, 16, -1], dtype=np.int64), fan
        ).reshape(len(ids), fan)

        steps, flip_at = 8, 3
        flipped = [False]
        expect = {}

        def start_fn(step):
            h = remote.sample_fanout_async(ids, [[0]], [fan],
                                           default_node=-1)
            assert h is not None
            # the epoch pinned at SUBMIT decides the step's rows
            expect[step] = post if flipped[0] else pre
            if step == flip_at:
                # flip both shards while this step (and, at depth 2,
                # the previous one) is still in flight
                for s in range(2):
                    assert remote.load_delta(dpath, shard=s) == 1
                flipped[0] = True
            return h

        def finish_fn(step, h):
            a_ids, _, _ = h.take()
            got = np.asarray(a_ids[1]).reshape(len(ids), fan)
            np.testing.assert_array_equal(
                got, expect[step],
                err_msg=f"step {step} leaked rows across the flip",
            )
            return got

        for _ in pipeline(start_fn, finish_fn, steps, depth=2):
            pass
        assert flipped[0] and len(expect) == steps

        # the client learned the flip passively from v4 reply stamps
        assert remote.shard_epoch(0) == 1
        assert remote.shard_epoch(1) == 1
        assert remote.epoch() == 1
        assert remote.cache_gen >= 1

        # ledger: one flip per shard, and every retired epoch drains
        # once its pins release (poke with a sync sample while polling)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            ctr = native.counters()
            if ctr["epoch_drains"] == ctr["epoch_flips"] == 2:
                break
            remote.sample_neighbor(ids, [0], fan, default_node=-1)
            time.sleep(0.05)
        ctr = native.counters()
        assert ctr["epoch_flips"] == 2, ctr
        assert ctr["epoch_drains"] == 2, ctr
        assert ctr["delta_loads_failed"] == 0, ctr
    finally:
        remote.close()
        for s in services:
            s.stop()


# ---------------------------------------------------------------------------
# failpoints: exact counter arithmetic, nothing staged on refusal
# ---------------------------------------------------------------------------


def test_delta_load_failpoint_counts_exactly_one_refusal(tmp_path):
    svc, g = _one_shard(tmp_path)
    try:
        dpath = _write_delta(str(tmp_path / "part.delta.1"),
                             _minimal_new_nodes())
        native.fault_config("delta_load:err@1.0#1", 7)
        with pytest.raises(RuntimeError):
            g.load_delta(dpath, shard=0)
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1, ctr
        assert ctr["epoch_flips"] == 0 and ctr["epoch_drains"] == 0, ctr
        assert native.fault_injected()["delta_load"] == 1
        assert g.shard_epoch(0) == 0  # still serving the base snapshot

        # limit #1 exhausted: the SAME delta now applies cleanly
        assert g.load_delta(dpath, shard=0) == 1
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1, ctr
        assert ctr["epoch_flips"] == 1, ctr
    finally:
        g.close()
        svc.stop()


def test_epoch_flip_failpoint_rolls_back_staged_delta(tmp_path):
    svc, g = _one_shard(tmp_path)
    try:
        dpath = _write_delta(str(tmp_path / "part.delta.1"),
                             _minimal_new_nodes())
        # fires AFTER the merged engine is built: the staged delta must
        # roll back, or the retry below would refuse seq 1 as stale
        native.fault_config("epoch_flip:err@1.0#1", 9)
        with pytest.raises(RuntimeError):
            g.load_delta(dpath, shard=0)
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1, ctr
        assert ctr["epoch_flips"] == 0, ctr
        assert native.fault_injected()["epoch_flip"] == 1
        assert g.shard_epoch(0) == 0
        # old snapshot still serves: 14 -0-> 15, pre-delta
        nbr, _, _ = g.sample_neighbor(
            np.array([14], dtype=np.int64), [0], 2, default_node=-1
        )
        assert set(np.asarray(nbr).ravel()) == {15}

        assert g.load_delta(dpath, shard=0) == 1  # rollback left seq free
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1 and ctr["epoch_flips"] == 1
        nbr, _, _ = g.sample_neighbor(
            np.array([14], dtype=np.int64), [0], 2, default_node=-1
        )
        assert set(np.asarray(nbr).ravel()) == {16}
    finally:
        g.close()
        svc.stop()


def test_non_monotonic_seq_refused_and_counted(tmp_path):
    svc, g = _one_shard(tmp_path)
    try:
        dpath = _write_delta(str(tmp_path / "part.delta.1"),
                             _minimal_new_nodes())
        assert g.load_delta(dpath, shard=0) == 1
        with pytest.raises(RuntimeError):
            g.load_delta(dpath, shard=0)  # seq 1 again: stale
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1, ctr
        assert ctr["epoch_flips"] == 1, ctr
        assert g.shard_epoch(0) == 1
    finally:
        g.close()
        svc.stop()


# ---------------------------------------------------------------------------
# delta hygiene: contradictory / duplicate edits refused loudly
# ---------------------------------------------------------------------------


def test_make_delta_rejects_duplicate_node_records():
    new = [copy.deepcopy(n) for n in fixture_nodes()]
    new.append(copy.deepcopy(new[0]))
    with pytest.raises(ValueError, match="duplicate node_id"):
        make_delta(fixture_nodes(), new, FIXTURE_META)


def test_make_delta_rejects_duplicate_edge_records():
    new = [copy.deepcopy(n) for n in fixture_nodes()]
    n10 = next(n for n in new if n["node_id"] == 10)
    n10["edge"].append(copy.deepcopy(n10["edge"][0]))
    with pytest.raises(ValueError, match="duplicate edge record"):
        make_delta(fixture_nodes(), new, FIXTURE_META)


@pytest.mark.parametrize(
    "payload, msg",
    [
        # remove edge (10,11,0) AND re-emit node 10 still carrying it
        (
            lambda: pack_delta(
                1, [], [(10, 11, 0)],
                pack_block(
                    next(n for n in fixture_nodes()
                         if n["node_id"] == 10),
                    FIXTURE_META,
                ),
            ),
            "both removed and re-emitted",
        ),
        # remove node 15 AND re-emit its record in the same delta
        (
            lambda: pack_delta(
                1, [15], [],
                pack_block(
                    next(n for n in fixture_nodes()
                         if n["node_id"] == 15),
                    FIXTURE_META,
                ),
            ),
            "both removed and present",
        ),
        (lambda: pack_delta(1, [15, 15], [], b""),
         "duplicate removed node"),
    ],
)
def test_native_validate_refuses_contradictory_delta(tmp_path, payload, msg):
    data = str(tmp_path / "g")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    path = str(tmp_path / "part.delta.1")
    with open(path, "wb") as f:
        f.write(payload())
    g = Graph(directory=data)
    try:
        with pytest.raises(RuntimeError, match=msg):
            g.load_delta(path)
        assert g.epoch() == 0  # refusal staged nothing
        ctr = native.counters()
        assert ctr["delta_loads_failed"] == 1, ctr
        assert ctr["epoch_flips"] == 0, ctr
    finally:
        g.close()
