"""Sparse (COO segment-op) aggregator + GCNEncoder path tests — exercises
the full-neighbor pipeline end to end: get_multi_hop_neighbor -> MultiHop.adj
-> GCNEncoder."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from euler_tpu import ops
from euler_tpu.nn import sparse_aggregators
from euler_tpu.nn.encoders import GCNEncoder


def _toy_adj():
    # 2 self nodes, 3 neighbor nodes; node 0 -> {0, 1}, node 1 -> {2};
    # one padding edge pointing at slot 0.
    return {
        "src": jnp.array([0, 0, 1, 0], dtype=jnp.int32),
        "dst": jnp.array([0, 1, 2, 0], dtype=jnp.int32),
        "w": jnp.array([1.0, 1.0, 1.0, 0.0]),
        "mask": jnp.array([1.0, 1.0, 1.0, 0.0]),
    }


def test_gcn_aggregator_mean_semantics():
    self_emb = jnp.array([[1.0, 0.0], [0.0, 1.0]])
    neigh_emb = jnp.array([[2.0, 0.0], [4.0, 0.0], [0.0, 6.0]])
    adj = _toy_adj()
    agg = sparse_aggregators.GCNAggregator(dim=2, activation=None)
    params = agg.init(jax.random.PRNGKey(0), (self_emb, neigh_emb, adj))
    # Pre-dense aggregation: node0 = self + mean(n0,n1) = [1,0]+[3,0];
    # node1 = [0,1]+[0,6]. Verify via identity-kernel application.
    params = jax.tree.map(
        lambda p: jnp.eye(2) if p.shape == (2, 2) else p, params
    )
    out = agg.apply(params, (self_emb, neigh_emb, adj))
    np.testing.assert_allclose(out, [[4.0, 0.0], [0.0, 7.0]], atol=1e-5)


def test_padding_edges_do_not_contribute():
    self_emb = jnp.ones((2, 4))
    neigh_emb = jnp.ones((3, 4)) * 100.0
    adj = _toy_adj()
    # zero out ALL real edges; only the padding edge remains
    adj = dict(adj, mask=jnp.array([0.0, 0.0, 0.0, 0.0]))
    agg = sparse_aggregators.MeanAggregator(dim=4, activation=None)
    params = agg.init(jax.random.PRNGKey(0), (self_emb, neigh_emb, adj))
    out = agg.apply(params, (self_emb, neigh_emb, adj))
    # with no real edges the neighbor term must be exactly zero, so the
    # output equals the self projection alone
    self_only = agg.apply(
        params, (self_emb, jnp.zeros_like(neigh_emb), adj)
    )
    np.testing.assert_allclose(out, self_only, atol=1e-6)


def test_segment_softmax_masks_padding():
    logits = jnp.array([1.0, 2.0, 3.0, 100.0])
    seg = jnp.array([0, 0, 1, 1])
    mask = jnp.array([1.0, 1.0, 1.0, 0.0])
    p = sparse_aggregators.segment_softmax(logits, seg, 2, mask)
    np.testing.assert_allclose(p[3], 0.0)
    np.testing.assert_allclose(p[0] + p[1], 1.0, atol=1e-6)
    np.testing.assert_allclose(p[2], 1.0, atol=1e-6)


@pytest.mark.parametrize("aggregator", ["gcn", "mean", "attention"])
def test_gcn_encoder_full_pipeline(graph, aggregator):
    """ops.get_multi_hop_neighbor -> MultiHop.adj -> GCNEncoder, jitted."""
    roots = np.array([10, 16], dtype=np.int64)
    roots, hops = ops.get_multi_hop_neighbor(
        graph,
        roots,
        [[0, 1], [0, 1]],
        max_nodes_per_hop=[8, 8],
        max_edges_per_hop=[16, 32],
    )
    feats = [graph.get_dense_feature(roots, [0], [2])] + [
        graph.get_dense_feature(h.nodes, [0], [2]) for h in hops
    ]
    adjs = [h.adj for h in hops]
    enc = GCNEncoder(num_layers=2, dim=8, aggregator=aggregator)
    params = enc.init(jax.random.PRNGKey(0), feats, adjs)
    out = jax.jit(enc.apply)(params, feats, adjs)
    assert out.shape == (2, 8)
    assert np.isfinite(np.asarray(out)).all()
