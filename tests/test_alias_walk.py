"""Exact device-side biased (p/q) walks via alias + rejection sampling
(device.alias_biased_random_walk) — the heavy-tail replacement for the
truncated-slab walk, restoring the reference's exact node2vec semantics
(reference euler/client/graph.cc:120-151 BuildWeights over FULL neighbor
lists) on graphs where the sorted slab must truncate.

Covers: the rejection step's distribution matches the analytic d_tx
target at sampling-noise TVD (where the truncated slab sits at ~0.35);
walk mechanics (shapes, dead ends, step-0 semantics); and the enforced
truncation guard — Node2Vec with device sampling on a truncated sorted
slab must NOT silently use the distorted route.
"""

import os
import shutil
import tempfile
import warnings

import numpy as np
import pytest

import euler_tpu
from euler_tpu.graph import device as dg


@pytest.fixture(scope="module")
def powerlaw_graph():
    """Small heavy-tail graph + host-side full rows (the exactness
    oracle). The workdir goes the moment the graph is up (the native
    load copies the bytes; /tmp must not accumulate graph dirs)."""
    from euler_tpu.datasets import build_powerlaw

    d = tempfile.mkdtemp(prefix="alias_walk_")
    try:
        n, e = 800, 24_000
        build_powerlaw(d, num_nodes=n, num_edges=e, feature_dim=4,
                       label_dim=3, alpha=1.6, seed=5)
        g = euler_tpu.Graph(directory=d)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    nbr, w, _, cnt = g.get_full_neighbor(np.arange(n), [0])
    rows, off = [], 0
    for c in cnt:
        rows.append((nbr[off:off + c], w[off:off + c]))
        off += c
    return g, rows, cnt, n


def _exact_dist(rows, x, v, p, q):
    """Analytic node2vec step distribution from v with parent x, the
    reference's branch order (parent-adjacency beats the parent match on
    self-loops, euler/client/graph.cc:126-140)."""
    x_full = rows[x][0]
    ids, w = rows[v]
    scale = np.where(
        np.isin(ids, x_full), 1.0,
        np.where(ids == x, 1.0 / p, 1.0 / q),
    )
    pr = w * scale
    return ids, pr / pr.sum()


def _empirical_tvd(adj, rows, x, v, p, q, draws=40_000, trials=None):
    import jax

    ids, pr = _exact_dist(rows, x, v, p, q)
    step = jax.jit(
        lambda cur, par, key: dg._alias_biased_step(
            adj, cur, par, key, p, q, trials or dg.DEFAULT_WALK_TRIALS
        )
    )
    got = np.asarray(step(
        np.full(draws, v, np.int32), np.full(draws, x, np.int32),
        jax.random.PRNGKey(123),
    ))
    uy, uc = np.unique(got, return_counts=True)
    emp = {int(a): b / draws for a, b in zip(uy, uc)}
    support = {int(y) for y in ids}
    return 0.5 * (
        sum(abs(emp.get(int(y), 0.0) - pe) for y, pe in zip(ids, pr))
        + sum(pv for y, pv in emp.items() if y not in support)
    )


def test_rejection_step_matches_exact_distribution(powerlaw_graph):
    """Hub-parent steps — the class the truncated slab distorts at mean
    TVD ~0.35 (PERF.md walk study) — must match the analytic target at
    the sampling-noise floor on the rejection path."""
    g, rows, cnt, n = powerlaw_graph
    adj = dg.build_alias_adjacency(g, [0], n - 1, sorted=True)
    rng = np.random.default_rng(7)
    # >= : the top quantile can BE the max degree (ties at a cap)
    hubs = np.flatnonzero(cnt >= np.quantile(cnt[cnt > 0], 0.99))
    assert len(hubs) > 0
    checked = 0
    for p, q in ((0.25, 4.0), (4.0, 0.25), (0.5, 2.0)):
        x = int(rng.choice(hubs))
        x_full = rows[x][0]
        v = int(rng.choice(x_full))
        if len(rows[v][0]) == 0:
            continue
        tvd = _empirical_tvd(adj, rows, x, v, p, q)
        # noise floor for S<=a few hundred support at 40k draws is
        # ~0.02-0.04; the truncated slab sits an order of magnitude
        # above this on the same step class
        assert tvd < 0.06, f"p={p} q={q}: TVD {tvd:.3f}"
        checked += 1
    assert checked >= 2


def test_rejection_step_self_loop_precedence(powerlaw_graph):
    """A candidate that IS the parent while the parent has a self-loop
    classifies d_tx=1 (weight w), matching the reference merge's branch
    order — exercised on a purpose-built tiny graph fixture."""
    # the shared fixture has no self-loops; build a 4-node graph with
    # one: 0 -> {0, 1, 2}, 1 -> {0, 2}, 2 -> {0}, 3 isolated
    d = tempfile.mkdtemp(prefix="selfloop_")
    meta = {"node_type_num": 1, "edge_type_num": 1,
            "node_uint64_feature_num": 0, "node_float_feature_num": 0,
            "node_binary_feature_num": 0, "edge_uint64_feature_num": 0,
            "edge_float_feature_num": 0, "edge_binary_feature_num": 0}
    topo = {0: {0: 1.0, 1: 2.0, 2: 1.0}, 1: {0: 1.0, 2: 3.0},
            2: {0: 1.0}, 3: {}}
    nodes = [
        {
            "node_id": nid, "node_type": 0, "node_weight": 1.0,
            "neighbor": {"0": {str(t): w for t, w in nbrs.items()}},
            "uint64_feature": {}, "float_feature": {},
            "binary_feature": {},
            "edge": [
                {"src_id": nid, "dst_id": t, "edge_type": 0,
                 "weight": w, "uint64_feature": {},
                 "float_feature": {}, "binary_feature": {}}
                for t, w in nbrs.items()
            ],
        }
        for nid, nbrs in topo.items()
    ]
    try:
        euler_tpu.convert_dicts(
            nodes, meta, os.path.join(d, "part"), num_partitions=1
        )
        g = euler_tpu.Graph(directory=d)
    finally:
        shutil.rmtree(d, ignore_errors=True)
    nbr, w, _, cnt = g.get_full_neighbor(np.arange(4), [0])
    rows, off = [], 0
    for c in cnt:
        rows.append((nbr[off:off + c], w[off:off + c]))
        off += c
    adj = dg.build_alias_adjacency(g, [0], 3, sorted=True)
    # walk at node 0 with parent 0 (its own self-loop): candidate 0 is
    # parent AND parent-adjacent -> d_tx=1 precedence (weight w, not
    # w/p); p chosen to make the difference visible
    p, q = 0.25, 4.0
    tvd = _empirical_tvd(adj, rows, x=0, v=0, p=p, q=q, draws=30_000)
    assert tvd < 0.03
    # and the analytic target itself reflects the precedence: weight of
    # the self-loop candidate is w (1.0), not w/p (4.0)
    ids, pr = _exact_dist(rows, 0, 0, p, q)
    i0 = int(np.flatnonzero(ids == 0)[0])
    # all of 0's candidates {0,1,2} are neighbors of parent 0 -> all
    # d_tx=1 -> target proportional to raw weights 1,2,1
    np.testing.assert_allclose(pr, rows[0][1] / rows[0][1].sum())
    assert pr[i0] == pytest.approx(0.25)
    # HOST engine parity on the same fixture: 2-step walks from 0,
    # conditioned on the self-loop step (c1 == 0) — the c2 distribution
    # must match the same adjacency-first target
    k = 40_000
    paths = g.random_walk(np.zeros(k, np.int64), [0], 2, p, q, 4)
    taken = paths[paths[:, 1] == 0]
    assert len(taken) > 3000  # self-loop has weight 1/4 of node 0's row
    c2, counts = np.unique(taken[:, 2], return_counts=True)
    emp = {int(a): b / len(taken) for a, b in zip(c2, counts)}
    host_tvd = 0.5 * sum(
        abs(emp.get(int(y), 0.0) - pe) for y, pe in zip(ids, pr)
    )
    assert host_tvd < 0.03, f"host self-loop precedence off: {host_tvd:.3f}"


def test_alias_walk_mechanics(powerlaw_graph):
    import jax

    g, rows, cnt, n = powerlaw_graph
    adj = dg.build_alias_adjacency(g, [0], n - 1, sorted=True)
    roots = np.arange(16, dtype=np.int32)
    paths = np.asarray(jax.jit(
        lambda r, k: dg.alias_biased_random_walk(adj, r, k, 4, 0.5, 2.0)
    )(roots, jax.random.PRNGKey(0)))
    assert paths.shape == (16, 5)
    assert (paths[:, 0] == roots).all()
    # every transition is a real edge (or a dead-end default fill)
    default = n  # max_id + 1
    for b in range(16):
        for t in range(4):
            src, dst = int(paths[b, t]), int(paths[b, t + 1])
            if src == default or dst == default:
                continue
            assert dst in set(rows[src][0].tolist())
    # dead ends chain into the default row and stay there
    dead = np.flatnonzero(cnt == 0)
    if len(dead):
        pd = np.asarray(dg.alias_biased_random_walk(
            adj, np.asarray([dead[0]], np.int32),
            jax.random.PRNGKey(1), 3, 0.25, 4.0,
        ))
        assert (pd[0, 1:] == default).all()


def test_node2vec_truncation_guard(powerlaw_graph):
    """VERDICT round-4 weakness: --device_sampling Node2Vec on a graph
    whose sorted slab truncates silently sampled a distribution at mean
    TVD 0.35. The guard must reroute the walk adjacency to the exact
    alias form (warning), and the model must train through it."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import Node2Vec

    g, rows, cnt, n = powerlaw_graph
    model = Node2Vec(
        node_type=-1, edge_type=[0], max_id=n - 1, dim=8,
        walk_len=2, walk_p=0.25, walk_q=4.0, device_sampling=True,
        device_features=True, use_id=True, feature_idx=-1,
    )
    model.set_sampling_options(max_degree=32)  # forces truncation
    opt = train_lib.get_optimizer("adam", 0.01)
    roots = g.sample_node(8, -1)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        state = model.init_state(jax.random.PRNGKey(0), g, roots, opt)
    assert any("alias+rejection" in str(w.message) for w in rec), (
        "truncation guard must warn loudly"
    )
    k = model.adj_key([0], sorted=True)
    assert "off" in state["consts"]["adj"][k], (
        "guard must switch the walk adjacency to the exact alias form"
    )
    step = jax.jit(model.make_train_step(opt))
    batch = model.sample(g, roots)
    state, loss, _ = step(state, batch)
    assert np.isfinite(float(loss))


def test_sampling_alias_option_builds_sorted_alias(powerlaw_graph):
    """set_sampling_options(alias=True) + biased walks builds the
    id-sorted alias form directly (no slab, no warning)."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import Node2Vec

    g, rows, cnt, n = powerlaw_graph
    model = Node2Vec(
        node_type=-1, edge_type=[0], max_id=n - 1, dim=8,
        walk_len=2, walk_p=2.0, walk_q=0.5, device_sampling=True,
        device_features=True, use_id=True, feature_idx=-1,
    )
    model.set_sampling_options(alias=True)
    opt = train_lib.get_optimizer("adam", 0.01)
    roots = g.sample_node(8, -1)
    state = model.init_state(jax.random.PRNGKey(0), g, roots, opt)
    k = model.adj_key([0], sorted=True)
    adj = state["consts"]["adj"][k]
    assert "off" in adj
    # sorted contract: every CSR row is id-sorted
    offs, degs, nbrs = (np.asarray(adj[x]) for x in ("off", "deg", "nbr"))
    for i in range(0, n, 97):
        row = nbrs[offs[i]:offs[i] + degs[i]]
        assert (np.diff(row) >= 0).all()
    state, loss, _ = jax.jit(model.make_train_step(opt))(
        state, model.sample(g, roots)
    )
    assert np.isfinite(float(loss))


def test_node2vec_scan_train(powerlaw_graph):
    """Whole-chunk device training (make_scan_train) composes with the
    rejection-sampled walk: roots drawn on device, walks + pairs +
    negatives inside one lax.scan dispatch."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import Node2Vec

    g, rows, cnt, n = powerlaw_graph
    model = Node2Vec(
        node_type=-1, edge_type=[0], max_id=n - 1, dim=8,
        walk_len=2, walk_p=0.25, walk_q=4.0, device_sampling=True,
        device_features=True, feature_idx=-1,
    )
    model.set_sampling_options(alias=True)
    opt = train_lib.get_optimizer("adam", 0.01)
    state = model.init_state(
        jax.random.PRNGKey(0), g, g.sample_node(16, -1), opt
    )
    scan = jax.jit(
        train_lib.make_scan_train(model, opt, 5, 16), donate_argnums=(0,)
    )
    state, l1 = scan(state, 1)
    state, l2 = scan(state, 2)
    l2 = np.asarray(jax.device_get(l2))
    assert l2.shape == (5,)
    assert np.isfinite(l2).all() and (l2 > 0).all()
