"""eg_telemetry: latency histograms, wire-propagated trace spans, and
the STATS cluster scrape (OBSERVABILITY.md).

Everything here is deterministic: PR-2's seeded failpoint delays pin
exact log2 bucket placement, the span-record C ABI pins the journal's
eviction order with exact microsecond values, and the scrape is
compared field-by-field against the in-process dump it must mirror.
"""

import json
import time

import numpy as np
import pytest

import euler_tpu
from euler_tpu import telemetry as T
from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture

IDS = np.array([10, 11, 12, 13], dtype=np.int64)
NODE_TYPE_OP = 5  # eg_wire.h WireOp kNodeType


@pytest.fixture(autouse=True)
def _clean_slate():
    native.fault_clear()
    native.reset_counters()
    native.stats_reset()
    T.telemetry_reset()
    T.set_telemetry(True)
    yield
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    T.set_telemetry(True)
    T.set_slow_capacity(32)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("telemetry_data"))
    write_fixture(d, num_partitions=2)
    return d


def _graph(svcs, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("timeout_ms", 5000)
    return Graph(mode="remote", shards=[s.address for s in svcs], **kw)


def _wait_spans(pred, timeout=5.0):
    """Journal snapshot once pred(spans) holds. The server records its
    span AFTER sending the reply, so a client that just got its answer
    can race the serving worker's journal write by a few microseconds —
    deterministic content, asynchronous arrival."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        spans = T.slow_spans()
        if pred(spans):
            return spans
        time.sleep(0.01)
    return T.slow_spans()


# ---------------------------------------------------------------------------
# bucket arithmetic
# ---------------------------------------------------------------------------


def test_bucket_arithmetic_pins_the_log2_layout():
    # bucket 0 = [0,1µs); bucket b = [2^(b-1), 2^b)µs; last unbounded
    assert T.bucket_of(0) == 0
    assert T.bucket_of(1) == 1
    assert T.bucket_of(2) == 2
    assert T.bucket_of(3) == 2
    assert T.bucket_of(4) == 3
    assert T.bucket_of(16384) == 15
    assert T.bucket_of(25_000) == 15      # the 25 ms failpoint bucket
    assert T.bucket_of(32_767) == 15
    assert T.bucket_of(32_768) == 16
    assert T.bucket_of(60_000_000) == 26  # 60 s inside the fixed range
    assert T.bucket_of(1 << 26) == T.NUM_BUCKETS - 1  # overflow bucket
    assert T.bucket_of(1 << 40) == T.NUM_BUCKETS - 1
    edges = T.bucket_edges_us()
    assert len(edges) == T.NUM_BUCKETS - 1
    assert edges[0] == 1 and edges[-1] == 1 << 26


def test_percentiles_interpolate_within_buckets():
    hist = {"b": [0] * T.NUM_BUCKETS, "count": 0, "sum_us": 0}
    hist["b"][15] = 100  # all samples in [16384, 32768)
    pct = T.percentiles(hist, (50, 99))
    assert 16384 <= pct[50] <= 32768
    assert pct[50] < pct[99] <= 32768
    assert T.percentiles({"b": [0] * T.NUM_BUCKETS}, (50,)) == {}


# ---------------------------------------------------------------------------
# deterministic bucket placement + cross-process trace correlation
# (the ISSUE's acceptance drill)
# ---------------------------------------------------------------------------


def test_failpoint_delay_lands_exact_bucket_and_trace_matches(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            # every dispatch stalls 25 ms in the worker pre-dispatch;
            # wire + engine cost on loopback stays far under the
            # bucket's 7.7 ms of headroom, so both sides must land in
            # bucket 15 = [16384, 32768) µs
            native.fault_config("handler_stall:delay@25", 7)
            T.telemetry_reset()
            t = g.node_types(IDS)
            np.testing.assert_array_equal(t, [0, 1, 0, 1])
            native.fault_clear()

            data = T.telemetry_json()
            server = data["hist"]["server_handler:node_type"]
            client = data["hist"]["client_call:node_type"]
            assert sum(server["b"]) == 1
            assert server["b"][15] == 1, server["b"]
            assert sum(client["b"]) == 1
            assert client["b"][15] == 1, client["b"]

            # the SAME request in both journals, correlated by the v3
            # wire-propagated trace id
            spans = _wait_spans(lambda ss: any(
                s["side"] == "server" and s["op"] == "node_type"
                for s in ss))
            cli = [s for s in spans
                   if s["side"] == "client" and s["op"] == "node_type"]
            srv = [s for s in spans
                   if s["side"] == "server" and s["op"] == "node_type"]
            assert len(cli) == 1 and len(srv) == 1, spans
            assert cli[0]["trace"] != 0
            assert cli[0]["trace"] == srv[0]["trace"]
            assert srv[0]["handler_us"] >= 25_000
            assert cli[0]["total_us"] >= srv[0]["handler_us"]
            assert cli[0]["shard"] == 0 and srv[0]["shard"] == 0
            assert cli[0]["outcome"] == "ok" == srv[0]["outcome"]
        finally:
            g.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# histogram-count == ledger cross-checks
# ---------------------------------------------------------------------------


def test_histogram_counts_match_call_and_dispatch_ledgers(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            native.stats_reset()
            T.telemetry_reset()
            n_calls = 7
            for _ in range(n_calls):
                g.node_types(IDS)
            data = T.telemetry_json()
            # client: one histogram sample per ConnPool::Call — a
            # single-shard node_types() is exactly one call
            client = data["hist"]["client_call:node_type"]
            assert sum(client["b"]) == n_calls == client["count"]
            # server: Σ handler samples across ALL ops == the span
            # timer's service_request count (two independent recording
            # mechanisms, one dispatch each)
            served = sum(
                h["count"] for key, h in data["hist"].items()
                if key.startswith("server_handler:")
            )
            assert served == native.stats()["service_request"]["count"]
            # sums are µs-coherent: mean must sit inside the bucket span
            assert client["sum_us"] >= sum(client["b"]) * 0
            pct = T.percentiles(client)
            assert pct[50] <= pct[99]
        finally:
            g.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# STATS scrape vs in-process parity (live 2-shard cluster)
# ---------------------------------------------------------------------------


def test_stats_scrape_matches_in_process_dump(data_dir):
    svcs = [GraphService(data_dir, s, 2) for s in range(2)]
    try:
        g = _graph(svcs)
        try:
            T.telemetry_reset()
            native.reset_counters()
            for _ in range(5):
                g.node_types(IDS)
                g.sample_neighbor(IDS, [0, 1], 3)
            for s in range(2):
                scraped = euler_tpu.scrape(g, s)
                # in-process shards: the scrape travels the real wire
                # but reads the same process globals — counters must be
                # byte-identical to the local snapshot
                assert scraped["counters"] == native.counters()
                assert scraped["shard"] == s
                gauges = scraped["gauges"]
                assert gauges["workers"] >= 1
                assert gauges["draining"] == 0
                assert gauges["conns"] >= 1  # the scraping conn itself
                # histogram parity on a family the scrape cannot touch
                # (its own stats-op sample lands after the reply was
                # built): any already-recorded op compares exactly
                local = T.telemetry_json()["hist"]
                for key in ("server_handler:node_type",
                            "client_call:sample_neighbor"):
                    assert scraped["hist"][key]["b"] == local[key]["b"], key
            # euler_tpu.slow_spans(graph, shard) drains the same journal
            remote_spans = euler_tpu.slow_spans(g, 0)
            assert remote_spans
            assert remote_spans[0]["total_us"] >= remote_spans[-1][
                "total_us"]
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()


# ---------------------------------------------------------------------------
# cross-version trace-id downgrade (both directions)
# ---------------------------------------------------------------------------


def test_new_client_against_v1_server_downgrades_trace(data_dir):
    # wire_version=1 service: answers envelopes with the stock
    # pre-envelope unknown-op error -> client pins v1, no trace rides
    svc = GraphService(data_dir, 0, 1, options="wire_version=1")
    try:
        native.reset_counters()
        g = _graph([svc])
        try:
            T.telemetry_reset()
            np.testing.assert_array_equal(g.node_types(IDS), [0, 1, 0, 1])
            assert native.counters()["wire_downgrades"] == 1
            spans = _wait_spans(
                lambda ss: any(s["side"] == "server" for s in ss))
            srv = [s for s in spans if s["side"] == "server"]
            cli = [s for s in spans if s["side"] == "client"]
            # the client still journals with its own trace ids...
            assert cli and all(s["trace"] != 0 for s in cli)
            # ...but a v1 peer cannot receive them
            assert srv and all(s["trace"] == 0 for s in srv)
        finally:
            g.close()
    finally:
        svc.stop()


def test_v1_client_against_new_server_serves_without_trace(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc], wire_version=1)
        try:
            T.telemetry_reset()
            np.testing.assert_array_equal(g.node_types(IDS), [0, 1, 0, 1])
            spans = _wait_spans(
                lambda ss: any(s["side"] == "server" for s in ss))
            srv = [s for s in spans if s["side"] == "server"]
            assert srv and all(s["trace"] == 0 for s in srv)
        finally:
            g.close()
    finally:
        svc.stop()


def test_v2_server_pins_deadline_envelope_without_trace(data_dir):
    # wire_version=2 service: a pre-telemetry build — refuses the v3
    # trace envelope with kStatusBadVersion; the client must pin v2 on
    # the same connection (one downgrade, zero retries, exact answers)
    svc = GraphService(data_dir, 0, 1, options="wire_version=2")
    try:
        native.reset_counters()
        g = _graph([svc])
        try:
            T.telemetry_reset()
            np.testing.assert_array_equal(g.node_types(IDS), [0, 1, 0, 1])
            ctr = native.counters()
            assert ctr["wire_downgrades"] == 1, ctr
            assert ctr["retries"] == 0, ctr
            assert ctr["calls_failed"] == 0, ctr
            spans = _wait_spans(
                lambda ss: any(s["side"] == "server" for s in ss))
            srv = [s for s in spans if s["side"] == "server"]
            assert srv and all(s["trace"] == 0 for s in srv)
        finally:
            g.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# slow-span ring journal
# ---------------------------------------------------------------------------


def test_ring_journal_keeps_slowest_and_evicts_fastest():
    T.set_slow_capacity(3)
    T.telemetry_reset()
    for us in (10, 50, 30, 5, 100, 40):
        T.record_span(us, op=NODE_TYPE_OP, trace=us)
    spans = T.slow_spans()
    # capacity 3: {10,50,30} filled, 5 rejected under the floor, 100
    # evicts 10, 40 evicts 30 — slowest-first order pins the eviction
    assert [s["total_us"] for s in spans] == [100, 50, 40], spans
    assert [s["trace"] for s in spans] == [100, 50, 40]
    # shrinking capacity keeps the slowest survivors
    T.set_slow_capacity(2)
    assert [s["total_us"] for s in T.slow_spans()] == [100, 50]


# ---------------------------------------------------------------------------
# telemetry=0 kill-switch
# ---------------------------------------------------------------------------


def test_kill_switch_records_nothing(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        # the config key rides the graph string and flips the
        # process-global switch before any call
        g = _graph([svc], telemetry=False)
        try:
            assert not T.telemetry_enabled()
            for _ in range(4):
                g.node_types(IDS)
            data = T.telemetry_json()
            assert all(h["count"] == 0 for h in data["hist"].values())
            assert data["slow_spans"] == []
            assert data["enabled"] == 0
            # counters and span timers predate the subsystem and must
            # keep working under the kill-switch
            assert native.stats()["service_request"]["count"] >= 4
        finally:
            g.close()
        T.set_telemetry(True)
        g = _graph([svc])
        try:
            g.node_types(IDS)
            data = T.telemetry_json()
            assert data["hist"]["client_call:node_type"]["count"] == 1
        finally:
            g.close()
    finally:
        svc.stop()


def test_telemetry_keys_rejected_on_local_graphs(data_dir):
    with pytest.raises(ValueError, match="telemetry"):
        Graph(directory=data_dir, telemetry=False)
    with pytest.raises(ValueError, match="slow_spans"):
        Graph(directory=data_dir, slow_spans=8)


def test_slow_spans_config_key_resizes_journal(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc], slow_spans=2)
        try:
            T.telemetry_reset()
            for _ in range(6):
                g.node_types(IDS)
            # 6 calls -> 12 candidate spans (client + server), journal
            # holds exactly the configured 2
            assert len(T.slow_spans()) == 2
        finally:
            g.close()
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# Prometheus exposition + JSONL emission
# ---------------------------------------------------------------------------

ALL_OPS = [
    "ping", "info", "sample_node", "sample_edge", "node_type",
    "sample_neighbor", "full_neighbor", "topk_neighbor", "dense_feature",
    "edge_dense_feature", "sparse_feature", "edge_sparse_feature",
    "binary_feature", "edge_binary_feature", "node_weight",
    "sample_neighbor_uniq", "stats",
]


def _parse_exposition(text: str) -> dict:
    """Minimal Prometheus text parser: {series_line: value}; raises on
    malformed lines — the validity check."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            if line.startswith("#"):
                parts = line.split()
                assert parts[0] == "#" and parts[1] in ("HELP", "TYPE"), line
            continue
        series, value = line.rsplit(" ", 1)
        float(value)  # must parse
        assert series.count("{") <= 1, line
        out[series] = float(value)
    return out


def test_metrics_text_is_valid_and_covers_every_op(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            T.telemetry_reset()
            g.node_types(IDS)
            text = euler_tpu.metrics_text()
            series = _parse_exposition(text)
            # every RPC op appears in BOTH per-op histogram families,
            # traffic or not
            for op in ALL_OPS:
                for fam in ("eg_client_call_latency_us",
                            "eg_server_handler_latency_us"):
                    key = f'{fam}_count{{op="{op}"}}'
                    assert key in series, key
            assert series['eg_client_call_latency_us_count{op="node_type"}'] == 1
            # histogram buckets are cumulative and end at +Inf == count
            inf = 'eg_client_call_latency_us_bucket{op="node_type",le="+Inf"}'
            assert series[inf] == 1
            # counters + scalar families present
            assert 'eg_counter_total{name="retries"}' in series
            assert "eg_dial_latency_us_count" in series
            # the per-shard form labels every series with its shard
            sharded = euler_tpu.metrics_text(graph=g)
            s_series = _parse_exposition(sharded)
            key = ('eg_server_handler_latency_us_count'
                   '{shard="0",op="node_type"}')
            assert key in s_series, list(s_series)[:5]
            assert 'eg_workers{shard="0"}' in s_series
        finally:
            g.close()
    finally:
        svc.stop()


def test_snapshot_jsonl_emitter(tmp_path, data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = _graph([svc])
        try:
            T.telemetry_reset()
            native.reset_counters()
            g.node_types(IDS)
            path = str(tmp_path / "metrics.jsonl")
            T.append_metrics_line(path, step=10)
            g.node_types(IDS)
            T.append_metrics_line(path, step=20)
            lines = [json.loads(x) for x in open(path)]
            assert [x["step"] for x in lines] == [10, 20]
            assert lines[0]["ops"]["node_type"]["count"] == 1
            assert lines[1]["ops"]["node_type"]["count"] == 2
            assert lines[1]["ops"]["node_type"]["p99_us"] > 0
            assert "counters" in lines[0]
        finally:
            g.close()
    finally:
        svc.stop()
