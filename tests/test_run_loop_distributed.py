"""Multi-process data-parallel training through the REAL CLI.

VERDICT r3 next-#5: the full-stack distributed test (bespoke worker
script) proved the planes compose; this one proves the *shipped driver*
does — two OS processes each run ``python -m euler_tpu`` with the
jax.distributed flags (--coordinator_addr/--num_processes/--process_id,
the reference's PS/worker ClusterSpec analog, reference
tf_euler/python/run_loop.py:371-397 + scripts/dist_tf_euler.sh), in
--graph_mode=shared: each process serves its own graph shard
(reference initialize_shared_graph, tf_euler base.py:64), discovers the
other over the TCP registry, trains SupervisedGraphSage data-parallel
over one global 4-device mesh (XLA all-reduces gradients across the
process boundary), and must reach the SAME planted-community
convergence gate as a single-process run of the identical recipe —
loss/F1 parity in the statistical sense the random samplers allow.
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

STEP_RE = re.compile(
    r"step=(\d+) loss=([\d.eE+-]+) f1=([\d.eE+-]+)"
)

# one recipe, both topologies: 300 steps of batch-128 GraphSAGE on the
# 2000-node planted-community graph (mirrors test_convergence's gate)
RECIPE = [
    "--mode", "train",
    "--model", "graphsage_supervised",
    "--max_id", "1999",
    "--label_idx", "0", "--label_dim", "4",
    "--feature_idx", "1", "--feature_dim", "16",
    "--sigmoid_loss", "false",
    "--fanouts", "10,10", "--dim", "32", "--aggregator", "mean",
    "--batch_size", "128", "--num_epochs", "20",  # 15 steps/epoch -> 300
    "--learning_rate", "0.01", "--log_steps", "100",
    "--all_edge_type", "0", "--train_edge_type", "0",
    "--train_node_type", "-1",
]


@pytest.fixture(scope="module")
def planted_dir(tmp_path_factory):
    from euler_tpu.datasets import build_planted, nearest_centroid_accuracy

    d = tmp_path_factory.mktemp("planted_cli")
    out_dir, info = build_planted(str(d))
    feat_acc = nearest_centroid_accuracy(info, use_neighbors=False)
    hop1_acc = nearest_centroid_accuracy(info, use_neighbors=True)
    return out_dir, feat_acc, hop1_acc


def _run_cli(args, timeout=420):
    """One ``python -m euler_tpu`` process on 2 virtual CPU devices.
    Returns the Popen (caller communicates)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    return subprocess.Popen(
        [sys.executable, "-m", "euler_tpu", *map(str, args)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env,
    )


def _final_window(err: str):
    """(loss, f1) of the last logged training window."""
    matches = STEP_RE.findall(err)
    assert matches, f"no train-step log lines in:\n{err[-2000:]}"
    step, loss, f1 = matches[-1]
    return float(loss), float(f1)


def test_run_loop_two_process_matches_single(planted_dir, tmp_path):
    from tests.conftest import free_port

    out_dir, feat_acc, hop1_acc = planted_dir

    # single-process baseline, same global batch, local graph.
    # --model_dir= (empty) disables checkpointing: the default ("ckpt",
    # CWD-relative) would resume from whatever an earlier run left
    # there, and multihost orbax coordination is not this test's
    # subject.
    p = _run_cli(["--data_dir", out_dir, "--graph_mode", "local",
                  "--model_dir", "", *RECIPE])
    out, err = p.communicate(timeout=420)
    assert p.returncode == 0, f"single-process run failed:\n{err[-2500:]}"
    loss1, f1_1 = _final_window(err)

    # two processes through the shipped flags: TCP registry hosted by
    # process 0, per-process graph shards, jax.distributed collectives
    coord = f"127.0.0.1:{free_port()}"
    reg = f"tcp://127.0.0.1:{free_port()}"
    procs = [
        _run_cli([
            "--data_dir", out_dir, "--graph_mode", "shared",
            "--registry", reg,
            "--coordinator_addr", coord,
            "--num_processes", "2", "--process_id", pid,
            "--model_dir", "",
            *RECIPE,
        ])
        for pid in range(2)
    ]
    outs = []
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=420)
            if (
                p.returncode != 0
                and "Multiprocess computations aren't implemented" in err
            ):
                # environment limit, not a code regression: this
                # jaxlib's CPU backend has no cross-process collectives
                # (same guard as conftest.run_worker_processes)
                pytest.skip(
                    "CPU backend lacks multiprocess computations "
                    "(jax.distributed collectives unavailable)"
                )
            assert p.returncode == 0, (
                f"worker {pid} failed:\n{err[-2500:]}"
            )
            outs.append((out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    finals = [_final_window(err) for _, err in outs]
    # replicated training state: both processes log identical numbers
    assert np.isclose(finals[0][0], finals[1][0], rtol=1e-4), finals
    assert np.isclose(finals[0][1], finals[1][1], rtol=1e-4), finals
    loss2, f1_2 = finals[0]

    # both topologies must clear the planted-community learning gate ...
    for label, f1 in (("1-process", f1_1), ("2-process", f1_2)):
        assert f1 > feat_acc + 0.2, (
            f"{label} final-window f1 {f1:.3f} vs single-node feature "
            f"bound {feat_acc:.3f}: aggregation is not learning"
        )
    # ... and agree with each other (independent sampler streams leave
    # statistical wiggle; converged windows agree much tighter than this)
    assert abs(f1_1 - f1_2) < 0.08, (f1_1, f1_2)
    assert abs(loss1 - loss2) < 0.25, (loss1, loss2)
