"""scripts/perf_gate.py: trajectory parsing + the smoke-to-smoke
regression verdict (warn-only default, --strict enforcement)."""

import json
import subprocess
import sys

import pytest

from scripts import perf_gate


def test_trajectory_parses_the_repo_bench_rounds():
    rows = perf_gate.load_trajectory()
    assert len(rows) >= 5
    by_round = {r["round"]: r for r in rows}
    # round 1 failed (rc=1, no headline) and must still appear
    assert by_round[1]["value"] is None
    for n in (2, 3, 4, 5):
        assert by_round[n]["value"] > 1e6, by_round[n]
        assert by_round[n]["unit"] == "edges/s"


def test_trajectory_markdown_table_shape():
    md = perf_gate.trajectory_markdown(perf_gate.load_trajectory())
    lines = md.splitlines()
    assert lines[0].startswith("| round |")
    assert len(lines) >= 7  # header + rule + >=5 rounds
    # the best round is bolded exactly once
    assert sum("**" in line for line in lines) == 1


def test_verdict_branches():
    history = [
        {"unix": 1, "values": {"bench_smoke": 2_000_000.0}},
        {"unix": 2, "values": {"bench_smoke": 3_000_000.0}},
    ]
    # ok: within tolerance of the best prior (3.0M)
    (res,) = perf_gate.verdict({"bench_smoke": 2_500_000.0}, history, 0.25)
    assert res[1] == "ok"
    # regression: below best * (1 - tol)
    (res,) = perf_gate.verdict({"bench_smoke": 2_000_000.0}, history, 0.25)
    assert res[1] == "regression"
    # baseline: no prior rounds for this config
    (res,) = perf_gate.verdict({"remote_smoke": 1.0}, history, 0.25)
    assert res[1] == "baseline"
    # failed smoke run: recorded as baseline-with-note, never a crash
    (res,) = perf_gate.verdict({"bench_smoke": None}, history, 0.25)
    assert res[1] == "baseline"


def test_history_roundtrip(tmp_path):
    path = str(tmp_path / "hist.jsonl")
    assert perf_gate.load_history(path) == []
    perf_gate.append_history({"unix": 1, "values": {"x": 2.0}}, path)
    perf_gate.append_history({"unix": 2, "values": {"x": 3.0}}, path)
    rows = perf_gate.load_history(path)
    assert [r["values"]["x"] for r in rows] == [2.0, 3.0]


def test_cli_table_only_runs_no_benches():
    proc = subprocess.run(
        [sys.executable, "scripts/perf_gate.py", "--table"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.startswith("| round |")


@pytest.mark.parametrize("strict,expected_rc", [(False, 0), (True, 1)])
def test_strict_flag_gates_a_regression(tmp_path, monkeypatch, capsys,
                                        strict, expected_rc):
    """Warn-only by default, --strict exits nonzero — with the smoke
    runners stubbed so the test costs milliseconds."""
    hist = str(tmp_path / "hist.jsonl")
    perf_gate.append_history(
        {"unix": 1, "values": {"remote_smoke": 10_000_000.0}}, hist
    )
    monkeypatch.setattr(perf_gate, "run_smoke_remote",
                        lambda timeout_s: {"value": 1_000_000.0})
    argv = ["perf_gate.py", "--skip-bench", "--history", hist,
            "--no-record"]
    if strict:
        argv.append("--strict")
    monkeypatch.setattr(sys, "argv", argv)
    assert perf_gate.main() == expected_rc
    out = capsys.readouterr().out
    assert "REGRESSION" in out


@pytest.mark.parametrize("listed,expected_rc", [(False, 0), (True, 1)])
def test_strict_configs_gate_only_named_configs(tmp_path, monkeypatch,
                                                capsys, listed,
                                                expected_rc):
    """--strict-configs enforces per config: a regression in a listed
    config fails, the same regression in an unlisted one stays a
    warning — the verify.sh shape (host bench gates, remote noise
    doesn't)."""
    hist = str(tmp_path / "hist.jsonl")
    perf_gate.append_history(
        {"unix": 1, "values": {"remote_smoke": 10_000_000.0}}, hist
    )
    monkeypatch.setattr(perf_gate, "run_smoke_remote",
                        lambda timeout_s: {"value": 1_000_000.0})
    configs = "remote_smoke" if listed else "bench_smoke"
    monkeypatch.setattr(sys, "argv", [
        "perf_gate.py", "--skip-bench", "--history", hist, "--no-record",
        "--strict-configs", configs,
    ])
    assert perf_gate.main() == expected_rc
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    if not listed:
        assert "[warn-only config]" in out


def test_verdict_json_is_append_only(tmp_path, monkeypatch):
    """A run records its smoke values into the history for the next
    round's comparison (unless --no-record)."""
    hist = str(tmp_path / "hist.jsonl")
    monkeypatch.setattr(perf_gate, "run_smoke_remote",
                        lambda timeout_s: {"value": 5_000_000.0})
    monkeypatch.setattr(
        sys, "argv",
        ["perf_gate.py", "--skip-bench", "--history", hist],
    )
    assert perf_gate.main() == 0
    (row,) = perf_gate.load_history(hist)
    assert row["values"] == {"remote_smoke": 5_000_000.0}
    assert json.loads(open(hist).read().strip())