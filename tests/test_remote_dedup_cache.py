"""Remote hot path: duplicate-id coalescing, the client-side feature-row
cache, request chunking, and strict shard-failure surfacing.

The seed motivation (ISSUE 3): on power-law graphs hubs carry most edge
mass, so a fanout batch repeats the same ids thousands of times and the
pre-PR client re-sent every duplicate per hop and refetched hot feature
rows endlessly. These tests pin, against a REAL 2-shard local cluster on
a hub-heavy fixture:

  * parity — every dedup'd/cached op returns exactly what the embedded
    host engine returns (deterministic ops), and the dedup'd sampler
    matches the host engine's neighbor distribution while keeping
    duplicate rows independent (the kSampleNeighborUniq reps contract);
  * exact counter arithmetic for ids_deduped / cache_hits /
    cache_misses / rpc_chunks;
  * the ISSUE's acceptance criterion: a 2-hop fanout + feature batch on
    the power-law fixture cuts ids-on-wire by >= 5x, verified from the
    counter ledger;
  * strict= raises through the C ABI when a shard is unreachable, while
    the default path degrades to defaults and counts rpc_errors.
"""

import numpy as np
import pytest

import euler_tpu
from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService

NUM_SHARDS = 2
NUM_PARTITIONS = 4
NUM_NODES = 60
HUBS = 6  # low ids get the overwhelming share of in-edges

PL_META = {
    "node_type_num": 2,
    "edge_type_num": 2,
    "node_uint64_feature_num": 1,
    "node_float_feature_num": 2,
    "node_binary_feature_num": 1,
    "edge_uint64_feature_num": 1,
    "edge_float_feature_num": 1,
    "edge_binary_feature_num": 1,
}


def powerlaw_nodes():
    """Hub-heavy deterministic graph: every node's out-edges point mostly
    at the first HUBS ids (zipf-ish), so any fanout batch is dominated by
    duplicate hub ids — the Reddit-scale shape at fixture size."""
    rng = np.random.default_rng(7)
    nodes = []
    for nid in range(NUM_NODES):
        deg = 3 + int(rng.integers(0, 4))
        # ~80% of edge mass onto hubs, the rest uniform
        dsts = []
        for _ in range(deg):
            if rng.random() < 0.8:
                dsts.append(int(rng.integers(0, HUBS)))
            else:
                dsts.append(int(rng.integers(0, NUM_NODES)))
        groups: dict = {}
        for d in dsts:
            t = d % 2
            groups.setdefault(t, {})[d] = groups.get(t, {}).get(d, 0.0) + 1.0
        edges = [
            {
                "src_id": nid, "dst_id": d, "edge_type": t, "weight": w,
                "uint64_feature": {"0": [nid * 1000 + d]},
                "float_feature": {"0": [w * 0.5]},
                "binary_feature": {"0": "e%d-%d" % (nid, d)},
            }
            for t, g in groups.items()
            for d, w in g.items()
        ]
        nodes.append(
            {
                "node_id": nid,
                "node_type": nid % 2,
                "node_weight": 1.0 + (nid % 5),
                "neighbor": {
                    str(t): {str(d): w for d, w in g.items()}
                    for t, g in groups.items()
                },
                "uint64_feature": {"0": [nid, nid + 1]},
                "float_feature": {
                    "0": [nid * 0.5, nid * 0.25, float(nid % 3)],
                    "1": [1.0 + nid],
                },
                "binary_feature": {"0": "n%d" % nid},
                "edge": edges,
            }
        )
    return nodes


@pytest.fixture(scope="module")
def pl_cluster(tmp_path_factory):
    """(local graph, registry dir, services, data dir) over the
    power-law fixture."""
    data = str(tmp_path_factory.mktemp("pl_data"))
    euler_tpu.convert_dicts(
        powerlaw_nodes(), PL_META, data + "/part",
        num_partitions=NUM_PARTITIONS,
    )
    reg = str(tmp_path_factory.mktemp("pl_reg"))
    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    local = Graph(directory=data)
    yield local, reg, services, data
    for s in services:
        s.stop()


@pytest.fixture(autouse=True)
def _clean_counters():
    native.reset_counters()
    yield
    native.reset_counters()


def hub_heavy_ids(n=600, seed=3):
    """An id batch shaped like a fanout result: mostly duplicate hubs."""
    rng = np.random.default_rng(seed)
    ids = np.where(
        rng.random(n) < 0.8,
        rng.integers(0, HUBS, n),
        rng.integers(0, NUM_NODES, n),
    )
    return ids.astype(np.int64)


# ---------------------------------------------------------------------------
# parity: dedup + cache + chunking return exactly the host engine's answers
# ---------------------------------------------------------------------------


def test_deterministic_ops_parity_with_duplicates(pl_cluster):
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg, chunk_ids=7)
    try:
        ids = hub_heavy_ids()
        for _ in range(2):  # second pass serves dense rows from the cache
            np.testing.assert_array_equal(
                remote.node_types(ids), local.node_types(ids)
            )
            np.testing.assert_allclose(
                remote.get_dense_feature(ids, [0, 1], [3, 1]),
                local.get_dense_feature(ids, [0, 1], [3, 1]),
            )
            np.testing.assert_allclose(
                remote.node_weights(ids), local.node_weights(ids)
            )
            l = local.get_full_neighbor(ids, [0, 1])
            r = remote.get_full_neighbor(ids, [0, 1])
            for a, b in zip(l, r):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            lt = local.get_top_k_neighbor(ids, [0, 1], 3)
            rt = remote.get_top_k_neighbor(ids, [0, 1], 3)
            for a, b in zip(lt, rt):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            ls = local.get_sparse_feature(ids, [0])
            rs = remote.get_sparse_feature(ids, [0])
            for (lv, lc), (rv, rc) in zip(ls, rs):
                np.testing.assert_array_equal(lv, rv)
                np.testing.assert_array_equal(lc, rc)
            lb = local.get_binary_feature(ids, [0])
            rb = remote.get_binary_feature(ids, [0])
            assert lb == rb
    finally:
        remote.close()


def test_sample_neighbor_dedup_distribution_and_independence(pl_cluster):
    """The kSampleNeighborUniq contract: a hub id repeated many times
    gets draws matching the host engine's neighbor distribution AND the
    duplicate rows stay independent (each row is a fresh reps-block, not
    a copy of one shared sample)."""
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg)
    try:
        hub = 0
        reps = 300
        ids = np.full(reps, hub, dtype=np.int64)
        count = 8
        native.lib().eg_seed(11)
        r_nbr, _, _ = remote.sample_neighbor(ids, [0, 1], count)
        r_nbr = np.asarray(r_nbr)
        # duplicates are NOT identical copies: with >= 2 distinct
        # neighbors, 300 iid 8-draw rows collide completely only with
        # vanishing probability
        distinct_rows = {tuple(row) for row in r_nbr.tolist()}
        assert len(distinct_rows) > 1, "duplicate rows shared one sample"
        # empirical marginal matches the host engine's distribution
        native.lib().eg_seed(11)
        l_nbr, _, _ = local.sample_neighbor(ids, [0, 1], count)
        l_nbr = np.asarray(l_nbr)
        values = np.unique(np.concatenate([r_nbr.ravel(), l_nbr.ravel()]))
        for v in values:
            rf = (r_nbr == v).mean()
            lf = (l_nbr == v).mean()
            assert abs(rf - lf) < 0.05, (v, rf, lf)
    finally:
        remote.close()


# ---------------------------------------------------------------------------
# exact counter arithmetic
# ---------------------------------------------------------------------------


def test_dedup_and_cache_counter_arithmetic(pl_cluster):
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg)
    try:
        ids = np.array([0, 1, 0, 2, 1, 0, 3, 0], dtype=np.int64)
        uniq = len(set(ids.tolist()))  # 4
        dups = len(ids) - uniq         # 4
        native.reset_counters()
        remote.get_dense_feature(ids, [0], [3])
        c = native.counters()
        assert c["ids_deduped"] == dups, c
        assert c["cache_misses"] == uniq, c  # cold cache: every unique fetched
        assert c["cache_hits"] == 0, c
        remote.get_dense_feature(ids, [0], [3])  # identical call: all cached
        c = native.counters()
        assert c["cache_hits"] == uniq, c
        assert c["cache_misses"] == uniq, c      # unchanged
        assert c["ids_deduped"] == 2 * dups, c
        # node_types dedups too (no cache: types ride the wire each call)
        native.reset_counters()
        remote.node_types(ids)
        c = native.counters()
        assert c["ids_deduped"] == dups, c
        assert c["cache_hits"] == 0 and c["cache_misses"] == 0, c
    finally:
        remote.close()


def test_cache_disabled_and_coalesce_disabled(pl_cluster):
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg, feature_cache_mb=0,
                   coalesce=False)
    try:
        ids = hub_heavy_ids(200)
        native.reset_counters()
        for _ in range(2):
            np.testing.assert_allclose(
                remote.get_dense_feature(ids, [0], [3]),
                local.get_dense_feature(ids, [0], [3]),
            )
        c = native.counters()
        # the pre-PR wire shape: nothing deduped, nothing cached
        assert c["ids_deduped"] == 0, c
        assert c["cache_hits"] == 0 and c["cache_misses"] == 0, c
    finally:
        remote.close()


def test_chunking_arithmetic_and_parity(pl_cluster):
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg, chunk_ids=8,
                   feature_cache_mb=0)
    try:
        ids = np.arange(NUM_NODES, dtype=np.int64)  # all unique
        native.reset_counters()
        np.testing.assert_array_equal(
            remote.node_types(ids), local.node_types(ids)
        )
        c = native.counters()
        # every id unique: per-shard unique counts are the shard row
        # counts; each shard's request splits into ceil(m/8) chunks
        per_shard = [0] * NUM_SHARDS
        for i in ids:
            per_shard[(int(i) % NUM_PARTITIONS) % NUM_SHARDS] += 1
        want = sum(-(-m // 8) for m in per_shard if m > 8)
        assert c["rpc_chunks"] == want, (c, per_shard)
    finally:
        remote.close()


def test_cache_stays_capacity_bounded(pl_cluster):
    """A 1 MB budget cannot hold 20 specs x 60 rows x ~2 KB: insertions
    must evict (oldest rows miss again on re-request) instead of
    growing without bound."""
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg, feature_cache_mb=1)
    try:
        ids = np.arange(NUM_NODES, dtype=np.int64)
        # dims are request-side: the engine zero-pads short rows, so a
        # 512-float request makes each cached row ~2 KB; each rep is a
        # distinct (fids, dims) spec, i.e. a distinct cache key set
        for rep in range(20):
            remote.get_dense_feature(ids, [0], [512 + rep])
        native.reset_counters()
        # the first spec's rows are the oldest everywhere: a bounded FIFO
        # must have evicted (essentially) all of them by now
        remote.get_dense_feature(ids, [0], [512])
        c = native.counters()
        assert c["cache_misses"] >= NUM_NODES * 0.5, c
        # and correctness never degraded while evicting
        np.testing.assert_allclose(
            remote.get_dense_feature(ids, [0], [3]),
            local.get_dense_feature(ids, [0], [3]),
        )
    finally:
        remote.close()


# ---------------------------------------------------------------------------
# the acceptance criterion: >= 5x ids-on-wire reduction, counter-verified
# ---------------------------------------------------------------------------


def test_fanout_feature_batch_cuts_ids_on_wire_5x(pl_cluster):
    """ISSUE 3 acceptance: on the power-law fixture, a 2-hop fanout +
    feature batch shows ids_deduped/cache_hits accounting for a >= 5x
    reduction in ids-on-wire vs the pre-PR ledger (which sent every id)."""
    local, reg, _, _ = pl_cluster
    remote = Graph(mode="remote", registry=reg)
    try:
        batch, f1, f2 = 64, 10, 10
        steps = 8
        requested = 0
        native.reset_counters()
        for step in range(steps):
            roots = np.asarray(local.sample_node(batch, -1))
            hop_ids, _, _ = remote.sample_fanout(
                roots, [[0, 1], [0, 1]], [f1, f2]
            )
            # ids put on the wire pre-PR: every hop input id...
            requested += batch + batch * f1
            feats = remote.get_dense_feature(hop_ids[2], [0], [3])
            # ...plus every feature row id
            requested += batch * f1 * f2
            assert feats.shape == (batch * f1 * f2, 3)
        c = native.counters()
        sent = requested - c["ids_deduped"] - c["cache_hits"]
        assert sent > 0
        reduction = requested / sent
        assert reduction >= 5.0, (
            f"ids-on-wire reduction {reduction:.2f}x < 5x "
            f"(requested={requested}, sent={sent}, ledger={c})"
        )
    finally:
        remote.close()


# ---------------------------------------------------------------------------
# strict= surfaces shard failure; default degrades + counts
# ---------------------------------------------------------------------------


def test_strict_raises_on_dead_shard_and_recovers(pl_cluster):
    """strict=1: a shard that dies after init must surface as an error
    (through the C ABI side channel) instead of silently yielding
    default rows — and the pending error is consumed, so the next
    healthy call proceeds."""
    local, reg, services, data = pl_cluster
    # a private shard-1 service: killing it must not disturb the shared
    # module cluster (Init needs every shard up, so it starts alive)
    svc1 = GraphService(data, 1, NUM_SHARDS)
    g = Graph(
        mode="remote", shards=[[services[0].address], [svc1.address]],
        retries=0, timeout_ms=500, strict=True,
    )
    try:
        bad_ids = np.array(
            [i for i in range(NUM_NODES)
             if (i % NUM_PARTITIONS) % NUM_SHARDS == 1],
            dtype=np.int64,
        )
        np.testing.assert_array_equal(  # healthy: strict stays silent
            g.node_types(bad_ids), local.node_types(bad_ids)
        )
        svc1.stop()
        native.reset_counters()
        with pytest.raises(RuntimeError, match="shard 1"):
            g.node_types(bad_ids)
        assert native.counters()["rpc_errors"] >= 1
        # the pending error is consumed: a following healthy call works
        good = np.array([0], dtype=np.int64)
        assert (int(good[0]) % NUM_PARTITIONS) % NUM_SHARDS == 0
        np.testing.assert_array_equal(
            g.node_types(good), local.node_types(good)
        )
    finally:
        g.close()
        svc1.stop()


def test_default_mode_degrades_but_counts_rpc_errors(pl_cluster):
    local, reg, services, data = pl_cluster
    svc1 = GraphService(data, 1, NUM_SHARDS)
    g = Graph(
        mode="remote", shards=[[services[0].address], [svc1.address]],
        retries=0, timeout_ms=500,
    )
    try:
        svc1.stop()
        bad = np.array([1], dtype=np.int64)  # (1 % 4) % 2 == 1 -> shard 1
        native.reset_counters()
        t = g.node_types(bad)
        assert t[0] == -1  # silent default (the pre-strict contract)
        assert native.counters()["rpc_errors"] >= 1
    finally:
        g.close()
        svc1.stop()


def test_strict_rejected_on_local_mode(tmp_path):
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), strict=True)
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=str(tmp_path), feature_cache_mb=32)


# ---------------------------------------------------------------------------
# placement-map routing (ISSUE 9): bit-identical results + pinned
# distributions vs hash routing, and the old-server compat fallback
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def placed_cluster(tmp_path_factory):
    """The SAME power-law node set as pl_cluster, partitioned by the
    degree-aware placer instead of hash — shards serve the placement
    artifact, clients route by it."""
    data = str(tmp_path_factory.mktemp("placed_data"))
    euler_tpu.convert_dicts(
        powerlaw_nodes(), PL_META, data + "/part",
        num_partitions=NUM_PARTITIONS, placement="degree",
    )
    services = [
        GraphService(data, s, NUM_SHARDS) for s in range(NUM_SHARDS)
    ]
    local = Graph(directory=data)
    yield local, services, data
    local.close()
    for s in services:
        s.stop()


def test_placement_routing_bit_identical_features(placed_cluster):
    """The parity half of the acceptance criteria: every deterministic
    op answered through placement routing returns exactly what the
    embedded host engine returns — misrouted ids would surface as
    default rows here, so equality IS the routing proof."""
    local, services, _ = placed_cluster
    remote = Graph(
        mode="remote", shards=[s.address for s in services],
        retries=2, timeout_ms=5000, chunk_ids=7,
    )
    try:
        assert remote.has_placement
        ids = hub_heavy_ids()
        # the map must actually change routing on this fixture (ids
        # whose placed partition differs from hash), or the A/B above
        # proves nothing
        hash_shards = (
            ids.view(np.uint64) % np.uint64(NUM_PARTITIONS)
        ) % np.uint64(NUM_SHARDS)
        assert (remote.shard_of(ids) != hash_shards.astype(np.int32)).any()
        for _ in range(2):  # second pass serves dense rows from caches
            np.testing.assert_array_equal(
                remote.node_types(ids), local.node_types(ids)
            )
            np.testing.assert_allclose(
                remote.get_dense_feature(ids, [0, 1], [3, 1]),
                local.get_dense_feature(ids, [0, 1], [3, 1]),
            )
            np.testing.assert_allclose(
                remote.node_weights(ids), local.node_weights(ids)
            )
            l = local.get_full_neighbor(ids, [0, 1])
            r = remote.get_full_neighbor(ids, [0, 1])
            for a, b in zip(l, r):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            lt = local.get_top_k_neighbor(ids, [0, 1], 3)
            rt = remote.get_top_k_neighbor(ids, [0, 1], 3)
            for a, b in zip(lt, rt):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            ls = local.get_sparse_feature(ids, [0])
            rs = remote.get_sparse_feature(ids, [0])
            for (lv, lc), (rv, rc) in zip(ls, rs):
                np.testing.assert_array_equal(lv, rv)
                np.testing.assert_array_equal(lc, rc)
            lb = local.get_binary_feature(ids, [0])
            rb = remote.get_binary_feature(ids, [0])
            assert lb == rb
    finally:
        remote.close()


def test_placement_routing_sampler_distribution(placed_cluster):
    """The distribution half: sampled neighbors through placement
    routing match the host engine's marginals (duplicate rows still
    independent) — same bar the hash-routing test above holds."""
    local, services, _ = placed_cluster
    remote = Graph(
        mode="remote", shards=[s.address for s in services],
        retries=2, timeout_ms=5000,
    )
    try:
        hub = 0
        ids = np.full(300, hub, dtype=np.int64)
        r_nbr, _, _ = remote.sample_neighbor(ids, [0, 1], 8)
        l_nbr, _, _ = local.sample_neighbor(ids, [0, 1], 8)
        r_nbr, l_nbr = np.asarray(r_nbr), np.asarray(l_nbr)
        distinct = {tuple(row) for row in r_nbr.tolist()}
        assert len(distinct) > 1, "duplicate rows shared one sample"
        values = np.unique(np.concatenate([r_nbr.ravel(), l_nbr.ravel()]))
        for v in values:
            rf = (r_nbr == v).mean()
            lf = (l_nbr == v).mean()
            assert abs(rf - lf) < 0.05, (v, rf, lf)
    finally:
        remote.close()


def test_placement_client_vs_mapless_server_degrades_to_hash(pl_cluster):
    """The acceptance compat pin: a client ASKING for a placement map
    (the default) against a cluster without one — a genuine old server
    answers the byte-identical stock error — degrades to hash routing
    with correct results, counting placement_fallbacks."""
    local, reg, _, _ = pl_cluster
    native.reset_counters()
    remote = Graph(mode="remote", registry=reg)
    try:
        assert not remote.has_placement
        assert native.counters()["placement_fallbacks"] == 1
        ids = hub_heavy_ids()
        # hash routing intact end to end
        hash_shards = (
            ids.view(np.uint64) % np.uint64(NUM_PARTITIONS)
        ) % np.uint64(NUM_SHARDS)
        np.testing.assert_array_equal(
            remote.shard_of(ids), hash_shards.astype(np.int32)
        )
        np.testing.assert_array_equal(
            remote.node_types(ids), local.node_types(ids)
        )
        np.testing.assert_allclose(
            remote.get_dense_feature(ids, [0], [3]),
            local.get_dense_feature(ids, [0], [3]),
        )
    finally:
        remote.close()


def test_placement_disabled_never_asks(pl_cluster):
    """placement=0 is a real kill-switch: no kPlacement exchange at
    init, so no fallback is counted either."""
    _, reg, _, _ = pl_cluster
    native.reset_counters()
    remote = Graph(mode="remote", registry=reg, placement=False)
    try:
        assert not remote.has_placement
        assert native.counters()["placement_fallbacks"] == 0
    finally:
        remote.close()
