"""Chaos soak: train over a live multi-shard TCP cluster while seeded
failpoints fire, plus a real shard SIGKILL + restart mid-run.

This is the capstone of the failpoint layer (_native/eg_fault, FAULTS.md):
the transport faults that production serves daily — refused dials, slow
links, mid-frame resets, a shard dying and coming back on a new port —
are injected deterministically into a real 2-shard cluster (each shard a
separate OS process, so the training process's injector touches ONLY the
client paths and the ledger arithmetic stays exact), and the run must:

  * complete, with every loss finite;
  * converge to a final loss within tolerance of the fault-free run
    (retry + backoff + quarantine + re-discovery absorb the chaos);
  * account for every injected fault in the exported failure counters.

Fault-sequence determinism (same seed => same injected-failure pattern)
is pinned per failpoint in test_fault_injection.py; here the seed makes
the soak reproducible in the aggregate. Counts still vary a little with
scheduling (retries draw more hits), so the ledger checks are exact
inequalities, not equalities.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from euler_tpu.graph import native
from tests.fixture_graph import TOPOLOGY, write_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NUM_SHARDS = 2
NUM_PARTITIONS = 4
STEPS = 36
KILL_STEP = 12     # SIGKILL shard 1 before this step...
RESTART_STEP = 14  # ...and bring it back (new port) before this one
# client-path faults only: dial refusals, slow sends, mid-frame resets.
# Probabilities low enough that retries=8 makes per-call success ~certain
# once the cluster is up; the shard kill supplies the real failures.
FAULT_SPEC = "dial:err@0.2,send_frame:delay@3@0.3,recv_frame:err@0.15"
FAULT_SEED = 20260804


@pytest.fixture(autouse=True)
def _clean_faults():
    native.fault_clear()
    native.counters_reset()
    yield
    native.fault_clear()
    native.counters_reset()


def _launch_shard(idx: int, data: str, reg: str,
                  extra: list | None = None) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    return subprocess.Popen(
        [sys.executable, "-m", "euler_tpu.graph.service",
         "--data_dir", data, "--shard_idx", str(idx),
         "--shard_num", str(NUM_SHARDS), "--registry", reg,
         *(extra or [])],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )


def _wait_registered(idx: int, reg: str, timeout: float = 90.0) -> None:
    """Wait until shard idx has a registry entry that actually accepts
    connections. A SIGKILLed prior incarnation leaves its stale file
    behind — the dial probe is what rejects it, exactly like
    run_loop.build_graph's liveness filter."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for f in os.listdir(reg):
            if not f.startswith(f"{idx}#"):
                continue
            host, port = f.split("#", 1)[1].rsplit("_", 1)
            try:
                with socket.create_connection((host, int(port)), 1.0):
                    return
            except OSError:
                continue
        time.sleep(0.1)
    raise TimeoutError(f"shard {idx} never came up in {reg}")


def test_chaos_soak_trains_through_faults_and_shard_restart(tmp_path):
    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=NUM_PARTITIONS)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    opt = train_lib.get_optimizer("adam", 0.05)
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    roots = np.array(sorted(TOPOLOGY), dtype=np.int64)

    def run(graph, hook=None):
        native.lib().eg_seed(1234)
        state = model.init_state(jax.random.PRNGKey(0), graph, roots, opt)
        losses = []
        for i in range(STEPS):
            if hook is not None:
                hook(i)
            batch = model.sample(graph, roots)
            state, loss, _ = step(state, batch)
            losses.append(float(loss))
        return losses

    procs = {}
    try:
        for s in range(NUM_SHARDS):
            procs[s] = _launch_shard(s, data, reg)
        for s in range(NUM_SHARDS):
            _wait_registered(s, reg)

        # ---- fault-free reference run ----
        g = euler_tpu.Graph(mode="remote", registry=reg, retries=8,
                            timeout_ms=2000, backoff_ms=2)
        assert g.num_shards == NUM_SHARDS
        clean = run(g)
        g.close()

        # ---- chaos run: seeded failpoints + shard kill/restart ----
        native.counters_reset()
        g = euler_tpu.Graph(
            mode="remote", registry=reg, retries=8, timeout_ms=2000,
            backoff_ms=2, rediscover_ms=300,
            fault=FAULT_SPEC, fault_seed=FAULT_SEED,
        )

        def chaos(i):
            if i == KILL_STEP:
                procs[1].send_signal(signal.SIGKILL)
                procs[1].wait()
            if i == RESTART_STEP:
                procs[1] = _launch_shard(1, data, reg)
                _wait_registered(1, reg)
                # let re-discovery learn the NEW port and route around
                # the stale entry before the tail of the run; id 13 lives
                # on shard 1 ((13 % 4) % 2 == 1), type 1 when reachable
                probe = np.array([13], dtype=np.int64)
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if int(g.node_types(probe)[0]) == 1:
                        return
                    time.sleep(0.2)
                raise TimeoutError("restarted shard never rejoined")

        faulted = run(g, chaos)
        injected = native.fault_injected()
        counters = native.counters()
        g.close()

        # the run completed, every loss finite, and it actually trained
        assert all(np.isfinite(x) for x in clean + faulted)
        clean_final = float(np.mean(clean[-5:]))
        fault_final = float(np.mean(faulted[-5:]))
        assert fault_final < faulted[0], (faulted[0], fault_final)
        assert abs(fault_final - clean_final) < 0.4, (clean_final,
                                                      fault_final)

        # every configured failpoint demonstrably fired
        assert injected["dial"] > 0, injected
        assert injected["send_frame"] > 0, injected
        assert injected["recv_frame"] > 0, injected

        # ledger: the counters account for every injected fault. The
        # training process runs no service, so its dial/send/recv hooks
        # sit exclusively in ConnPool::Call — each injected dial fault is
        # a counted failed dial, each failing fault quarantines a replica
        # and is followed by a retry or a counted failed call. Real
        # failures from the shard kill only push the counters higher.
        failing = injected["dial"] + injected["recv_frame"]
        assert counters["dials_failed"] >= injected["dial"], (injected,
                                                              counters)
        assert counters["quarantines"] >= failing, (injected, counters)
        assert (counters["retries"] + counters["calls_failed"]
                >= failing), (injected, counters)
        # the kill/restart path was really exercised
        assert counters["failovers"] >= 1, counters
        assert counters["rediscoveries"] >= 1, counters
    finally:
        native.fault_clear()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            p.wait()


def test_chaos_soak_async_pipeline_survives_shard_restart(tmp_path):
    """The sampler_depth=2 soak: the same SIGKILL + restart chaos, but
    every step's fan-out runs through the async completion queue with
    two steps in flight (model.sample_start / sample_finish — the split
    train.py uses for sampler_depth=2). The kill lands while a
    continuation chain is mid-flight, so this pins the property the sync
    soak can't reach: a shard dying BETWEEN hops of an already-submitted
    op degrades that op like the sync path and never wedges take()."""
    from collections import deque

    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=NUM_PARTITIONS)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    opt = train_lib.get_optimizer("adam", 0.05)
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    roots = np.array(sorted(TOPOLOGY), dtype=np.int64)
    DEPTH = 2

    procs = {}
    try:
        for s in range(NUM_SHARDS):
            procs[s] = _launch_shard(s, data, reg)
        for s in range(NUM_SHARDS):
            _wait_registered(s, reg)

        import euler_tpu

        native.counters_reset()
        # neighbor cache OFF: the fixture is tiny enough that the
        # init_state warm-up would cache every hop's lists and let all
        # async slices finish inline — wire-bound continuations are the
        # machinery under test, so force every hop onto the wire
        g = euler_tpu.Graph(
            mode="remote", registry=reg, retries=8, timeout_ms=2000,
            backoff_ms=2, rediscover_ms=300, neighbor_cache_mb=0,
            fault=FAULT_SPEC, fault_seed=FAULT_SEED,
        )

        def chaos(i):
            if i == KILL_STEP:
                procs[1].send_signal(signal.SIGKILL)
                procs[1].wait()
            if i == RESTART_STEP:
                procs[1] = _launch_shard(1, data, reg)
                _wait_registered(1, reg)
                probe = np.array([13], dtype=np.int64)
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if int(g.node_types(probe)[0]) == 1:
                        return
                    time.sleep(0.2)
                raise TimeoutError("restarted shard never rejoined")

        native.lib().eg_seed(1234)
        state = model.init_state(jax.random.PRNGKey(0), g, roots, opt)
        losses = []
        inflight = deque()
        submitted = 0
        # depth-2 ring: chaos fires at SUBMIT time, so the kill hits
        # while the previous step's continuation chain is still running
        while len(losses) < STEPS:
            while submitted < STEPS and len(inflight) < DEPTH:
                chaos(submitted)
                inflight.append(model.sample_start(g, roots))
                submitted += 1
            batch = model.sample_finish(g, inflight.popleft())
            state, loss, _ = step(state, batch)
            losses.append(float(loss))
        counters = native.counters()
        injected = native.fault_injected()
        g.close()

        # completed through the chaos: every loss finite, net training
        assert all(np.isfinite(x) for x in losses)
        assert float(np.mean(losses[-5:])) < losses[0], losses
        # the steps really went through the completion queue
        assert counters["async_submits"] >= STEPS, counters
        assert counters["async_inflight_peak"] >= 1, counters
        # with the cache off every step's hop-0 slice is wire-bound,
        # so each submit re-enqueues at least one continuation
        assert counters["async_continuations"] >= STEPS, counters
        # chaos demonstrably fired and was absorbed by the same
        # retry/failover machinery as the sync soak
        assert injected["dial"] > 0 or injected["recv_frame"] > 0, injected
        assert counters["retries"] + counters["calls_failed"] >= 1, counters
    finally:
        native.fault_clear()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            p.wait()


def test_chaos_soak_epoch_flips_race_async_faults_and_restart(tmp_path):
    """The snapshot-epoch capstone (FAULTS.md "Graph refresh"): a
    rolling delta refresh lands WHILE the depth-2 async ring has steps
    in flight and client-path faults fire, then a SIGKILL drops one
    shard's freshly-flipped epoch entirely. The restarted incarnation
    comes back at epoch 0 (a delta lives only in the epoch table of the
    process that merged it), refuses its first re-apply through a
    server-side `delta_load` failpoint, and applies it on retry — and
    the ledger accounts for every epoch, including the dropped one: the
    client completed three load_delta calls but the surviving processes
    can only show two flips; the difference IS the kill."""
    from collections import deque

    import jax

    import euler_tpu
    from euler_tpu import telemetry as T
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage
    from tests.test_epoch import _minimal_new_nodes, _write_delta

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=NUM_PARTITIONS)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    dpath = _write_delta(str(tmp_path / "part.delta.1"),
                         _minimal_new_nodes())

    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    opt = train_lib.get_optimizer("adam", 0.05)
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    roots = np.array(sorted(TOPOLOGY), dtype=np.int64)
    DEPTH = 2
    FLIP0_STEP, FLIP1_STEP = 8, 10  # both < KILL_STEP: shard 1's flip
    # is merged, announced, observed — then DROPPED by the SIGKILL

    procs = {}
    applied_ok = 0
    try:
        for s in range(NUM_SHARDS):
            procs[s] = _launch_shard(s, data, reg)
        for s in range(NUM_SHARDS):
            _wait_registered(s, reg)

        native.counters_reset()
        g = euler_tpu.Graph(
            mode="remote", registry=reg, retries=8, timeout_ms=2000,
            backoff_ms=2, rediscover_ms=300, neighbor_cache_mb=0,
            fault=FAULT_SPEC, fault_seed=FAULT_SEED,
        )

        def load_clean(shard):
            # the control-plane call runs fault-free: a client-side
            # recv fault AFTER the server merged would retry the same
            # seq and be refused as stale — by design load_delta is
            # NOT idempotent, so the runbook (and this soak) keeps the
            # one-line control call off the chaotic path
            nonlocal applied_ok
            native.fault_clear()
            try:
                assert g.load_delta(dpath, shard=shard) == 1
                applied_ok += 1
            finally:
                native.fault_config(FAULT_SPEC, FAULT_SEED)

        def chaos(i):
            if i == FLIP0_STEP:
                load_clean(0)
            if i == FLIP1_STEP:
                load_clean(1)
            if i == KILL_STEP:
                procs[1].send_signal(signal.SIGKILL)
                procs[1].wait()
            if i == RESTART_STEP:
                # fresh incarnation: epoch 0 again, and its FIRST
                # delta load refused by a server-side failpoint
                procs[1] = _launch_shard(
                    1, data, reg,
                    extra=["--fault", "delta_load:err@1.0#1",
                           "--fault_seed", "3"],
                )
                _wait_registered(1, reg)
                probe = np.array([13], dtype=np.int64)
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if int(g.node_types(probe)[0]) == 1:
                        break
                    time.sleep(0.2)
                else:
                    raise TimeoutError("restarted shard never rejoined")
                native.fault_clear()
                try:
                    with pytest.raises(RuntimeError):
                        g.load_delta(dpath, shard=1)  # failpoint fires
                    load_clean(1)  # limit #1 exhausted: re-apply lands
                finally:
                    native.fault_config(FAULT_SPEC, FAULT_SEED)

        native.lib().eg_seed(1234)
        state = model.init_state(jax.random.PRNGKey(0), g, roots, opt)
        losses = []
        inflight = deque()
        submitted = 0
        while len(losses) < STEPS:
            while submitted < STEPS and len(inflight) < DEPTH:
                chaos(submitted)
                inflight.append(model.sample_start(g, roots))
                submitted += 1
            batch = model.sample_finish(g, inflight.popleft())
            state, loss, _ = step(state, batch)
            losses.append(float(loss))
        counters = native.counters()
        injected = native.fault_injected()

        # survived and trained through flips + faults + kill
        assert all(np.isfinite(x) for x in losses)
        assert float(np.mean(losses[-5:])) < losses[0], losses
        assert counters["async_submits"] >= STEPS, counters
        assert injected["dial"] > 0 or injected["recv_frame"] > 0, injected

        # end state: both shards serve epoch 1, the client observed the
        # raises passively and bumped its cache generation for each
        assert applied_ok == 3  # shard 0, shard 1, shard 1 re-applied
        assert g.shard_epoch(0) == 1, g.shard_epoch(0)
        assert g.shard_epoch(1) == 1, g.shard_epoch(1)
        assert g.epoch() == 1
        assert g.cache_gen >= 2, g.cache_gen
        # the retargeted row serves post-delta data (14 lives on shard 0)
        nbr, _, _ = g.sample_neighbor(
            np.array([14], dtype=np.int64), [0], 2, default_node=-1
        )
        assert set(np.asarray(nbr).ravel()) == {16}, nbr

        # per-shard ledger over the STATS scrape: every SURVIVING
        # process shows exactly one flip (+ the restart's one refused
        # load), and every retired epoch drained. applied_ok == 3 vs
        # 1 + 1 scraped flips: the missing flip is the SIGKILLed
        # incarnation's — the dropped epoch, accounted for.
        deadline = time.monotonic() + 10.0
        scrapes = {}
        while time.monotonic() < deadline:
            scrapes = {s: T.scrape(g, s)["counters"]
                       for s in range(NUM_SHARDS)}
            if all(c["epoch_drains"] == c["epoch_flips"] == 1
                   for c in scrapes.values()):
                break
            g.sample_neighbor(np.array([14], dtype=np.int64), [0], 2)
            time.sleep(0.1)
        for s, c in scrapes.items():
            assert c["epoch_flips"] == 1, (s, c)
            assert c["epoch_drains"] == 1, (s, c)
        assert scrapes[0]["delta_loads_failed"] == 0, scrapes[0]
        assert scrapes[1]["delta_loads_failed"] == 1, scrapes[1]
        g.close()
    finally:
        native.fault_clear()
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        for p in procs.values():
            p.wait()
