"""End-to-end GraphSAGE training tests on the fixture graph + an 8-device
CPU mesh (the conftest forces JAX_PLATFORMS=cpu with 8 virtual devices)."""

import numpy as np
import pytest

import jax


@pytest.fixture(scope="module")
def sage_model():
    from euler_tpu.models import SupervisedGraphSage

    # Fixture nodes: dense feature slot 0 (dim 2) as input features, slot 2
    # (dim 3, multi-hot) as labels for a 3-class toy problem.
    return SupervisedGraphSage(
        label_idx=2,
        label_dim=3,
        metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2],
        dim=8,
        feature_idx=0,
        feature_dim=2,
        max_id=16,
    )


def test_sample_shapes(graph, sage_model):
    batch = sage_model.sample(graph, np.array([10, 12, 14, 16]))
    assert batch["labels"].shape == (4, 3)
    hops = batch["hops"]
    assert hops[0]["dense"].shape == (4, 2)
    assert hops[1]["dense"].shape == (12, 2)
    assert hops[2]["dense"].shape == (24, 2)


def test_train_loop_runs_and_learns(graph, sage_model):
    from euler_tpu import train as train_lib

    def source_fn(step):
        return graph.sample_node(16, -1)

    state, history = train_lib.train(
        sage_model,
        graph,
        source_fn,
        num_steps=60,
        learning_rate=0.05,
        log_every=10,
    )
    assert len(history) == 6
    # loss trends down on this trivially learnable toy target (individual
    # windows are noisy: 16-node batches, unseeded sampling)
    assert min(h["loss"] for h in history[1:]) < history[0]["loss"]


def test_train_multidevice_equals_semantics(graph, sage_model):
    """The 8-device data-parallel step must produce finite loss and valid f1
    counts with a batch sharded over all devices."""
    from euler_tpu import train as train_lib
    from euler_tpu.parallel import make_mesh

    assert len(jax.devices()) == 8
    mesh = make_mesh(8)

    def source_fn(step):
        return graph.sample_node(16, -1)  # 2 rows per device

    state, history = train_lib.train(
        sage_model, graph, source_fn, num_steps=10, mesh=mesh, log_every=5
    )
    assert np.isfinite(history[-1]["loss"])
    assert 0.0 <= history[-1]["f1"] <= 1.0


def test_evaluate_and_save_embedding(graph, sage_model):
    from euler_tpu import train as train_lib

    def source_fn(step):
        return graph.sample_node(16, -1)

    state, _ = train_lib.train(
        sage_model, graph, source_fn, num_steps=5, log_every=5
    )
    result = train_lib.evaluate(
        sage_model, graph, [graph.sample_node(16, -1) for _ in range(3)], state
    )
    assert "f1" in result and np.isfinite(result["loss"])
    emb = train_lib.save_embedding(
        sage_model, graph, max_id=16, state=state, batch_size=8
    )
    assert emb.shape == (17, 8)
    assert np.isfinite(emb).all()


def test_unsupervised_graphsage(graph):
    from euler_tpu import train as train_lib
    from euler_tpu.models import GraphSage

    model = GraphSage(
        node_type=-1,
        edge_type=[0, 1],
        max_id=16,
        metapath=[[0, 1]],
        fanouts=[3],
        dim=8,
        num_negs=4,
        feature_idx=0,
        feature_dim=2,
    )

    def source_fn(step):
        return graph.sample_node(16, -1)

    state, history = train_lib.train(
        model, graph, source_fn, num_steps=10, log_every=5
    )
    assert np.isfinite(history[-1]["loss"])
    assert 0.0 < history[-1]["mrr"] <= 1.0


def test_device_features_match_host_gather(graph):
    """device_features=True (HBM-resident tables + on-device gather) must be
    numerically identical to the host-gather path on the same sampled ids."""
    import jax
    import numpy as np
    import optax
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    m_host = SupervisedGraphSage(**kw)
    m_dev = SupervisedGraphSage(**kw, device_features=True)
    roots = np.array([10, 12, 14, 16], dtype=np.int64)
    ids_per_hop, _, _ = graph.sample_fanout(
        roots, m_host.metapath, m_host.fanouts, m_host.default_node
    )
    host_batch = {
        "hops": [
            {"dense": graph.get_dense_feature(ids, [0], [2])}
            for ids in ids_per_hop
        ],
        "labels": graph.get_dense_feature(roots, [2], [3]),
    }
    dev_batch = {
        "hops": [
            {"gids": np.clip(ids, 0, 17).astype(np.int32)}
            for ids in ids_per_hop
        ]
    }
    opt = optax.adam(0.01)
    state = m_dev.init_state(jax.random.PRNGKey(7), graph, roots, opt)
    assert set(state["consts"]) == {"features", "labels"}
    out_dev = m_dev.module.apply(
        {"params": state["params"]}, dev_batch, state["consts"]
    )
    out_host = m_host.module.apply({"params": state["params"]}, host_batch)
    np.testing.assert_allclose(
        np.asarray(out_dev.loss), np.asarray(out_host.loss), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out_dev.embedding),
        np.asarray(out_host.embedding),
        rtol=1e-5,
    )
    # and a full train step through the generic machinery runs
    step = jax.jit(m_dev.make_train_step(opt), donate_argnums=(0,))
    batch = m_dev.sample(graph, roots)
    state2, loss, metric = step(state, batch)
    assert np.isfinite(float(loss))
    assert "consts" in state2


def test_feature_dtype_bfloat16(graph, monkeypatch):
    """feature_dtype='bfloat16' stores the feature table half-size in HBM;
    rows are cast back to float32 at the gather (base.gather_consts), so
    model math sees only the storage rounding. On the fixture (feature
    values exactly representable in bfloat16) the result is identical to
    the float32 path; labels must stay float32 regardless."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
        device_features=True,
    )
    roots = np.array([10, 12, 14, 16], dtype=np.int64)
    opt = optax.adam(0.01)

    m32 = SupervisedGraphSage(**kw)
    s32 = m32.init_state(jax.random.PRNGKey(7), graph, roots, opt)

    m16 = SupervisedGraphSage(**kw, feature_dtype="bfloat16")
    s16 = m16.init_state(jax.random.PRNGKey(7), graph, roots, opt)
    assert s16["consts"]["features"].dtype == jnp.bfloat16
    assert s16["consts"]["labels"].dtype == jnp.float32

    batch = m16.sample(graph, roots)
    out32 = m32.module.apply(
        {"params": s32["params"]}, batch, s32["consts"]
    )
    out16 = m16.module.apply(
        {"params": s32["params"]}, batch, s16["consts"]
    )
    assert out16.embedding.dtype == jnp.float32  # cast back at the gather
    np.testing.assert_allclose(
        np.asarray(out16.loss), np.asarray(out32.loss), rtol=1e-6
    )

    # env-var spelling reaches build_consts too
    monkeypatch.setenv("EULER_TPU_FEATURE_DTYPE", "bfloat16")
    m_env = SupervisedGraphSage(**kw)
    s_env = m_env.init_state(jax.random.PRNGKey(7), graph, roots, opt)
    assert s_env["consts"]["features"].dtype == jnp.bfloat16
    monkeypatch.delenv("EULER_TPU_FEATURE_DTYPE")

    # a bogus dtype fails loudly, naming the knob
    with pytest.raises(ValueError, match="feature_dtype"):
        SupervisedGraphSage(**kw, feature_dtype="bf16").build_consts(graph)
