"""The biased-walk truncation distortion study is evidence the docs
cite (PERF.md: mean TVD vs the exact node2vec distribution at each
slab cap) — pin its machinery so the recorded numbers stay
reproducible: distortion must be large where truncation bites hard,
shrink as W grows, and the affected-step share must be the majority on
a heavy-tail graph."""

import pytest

pytestmark = pytest.mark.slow


def test_walk_distortion_shrinks_with_cap_but_stays_real():
    import sys, os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    from reddit_heavytail import walk_study

    out = walk_study(
        pairs_per_cap=60, caps=(32, 256), num_nodes=2000,
        num_edges=120_000,
    )
    w32, w256 = out["caps"]["W32"], out["caps"]["W256"]
    # graph really is heavy-tailed and the affected class is the
    # majority of steps
    assert out["graph"]["max_degree"] > 5 * out["graph"]["mean_degree"]
    assert w32["edge_mass_from_truncated_rows"] > 0.5
    # distortion is severe at a tight cap, decreases with W, and is
    # still material at the wide cap (the PERF.md claim)
    assert w32["mean_tvd"] > w256["mean_tvd"] > 0.05
    assert w32["mean_tvd"] > 0.4
    assert 0 < w256["mean_exact_mass_misclassified"] < 1
    # the exact alias+rejection walk sits at the sampling-noise floor —
    # an order of magnitude under every slab cap on the same step class
    ar = out["alias_rejection"]
    assert ar["mean_tvd"] < 0.08
    assert ar["mean_tvd"] * 4 < w256["mean_tvd"]
