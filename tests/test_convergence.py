"""Convergence gates on the planted-community graph.

Trend-only loss checks can't catch a model that compiles, descends, and
still fails to learn what a GNN should learn. These tests train each
supervised model family to convergence on a graph whose labels are a
known function of neighborhood structure (euler_tpu.datasets.build_planted)
and gate the micro-F1 against targets COMPUTED from the generator arrays:

  feat_acc  — nearest-centroid accuracy on raw node features (what a
              featureless-of-graph classifier can reach, ~0.56)
  hop1_acc  — the same after averaging each node's 1-hop neighborhood
              (~0.94): the separability a single aggregation layer exposes

A converged 2-hop GNN must clearly beat feat_acc and approach hop1_acc.
Reference bar being mirrored: supervised GraphSAGE recovers PPI
micro-F1 0.6-0.8 / Reddit 0.93-0.95 (BASELINE.md) — unavailable offline,
so the planted graph provides the known-achievable target instead.

Also bounds the ScalableGCN historical-embedding staleness: its converged
F1 must match plain GCN's within a small tolerance (VERDICT round 1
weak #6 — quantify the stale-store approximation).
"""

import numpy as np
import pytest

MARGIN = 0.08  # slack below hop1_acc: finite training + eval sampling noise


@pytest.fixture(scope="module")
def planted(tmp_path_factory):
    from euler_tpu.datasets import build_planted, nearest_centroid_accuracy

    d = tmp_path_factory.mktemp("planted")
    out_dir, info = build_planted(str(d))
    info["out_dir"] = out_dir
    feat_acc = nearest_centroid_accuracy(info, use_neighbors=False)
    hop1_acc = nearest_centroid_accuracy(info, use_neighbors=True)
    # generator sanity: aggregation must be the thing that makes the task
    # solvable, else the gates below prove nothing
    assert feat_acc < 0.7
    assert hop1_acc > 0.9
    import euler_tpu

    graph = euler_tpu.Graph(directory=out_dir)
    return graph, info, feat_acc, hop1_acc


NUM_NODES = 2000
NUM_CLASSES = 4
FEATURE_DIM = 16


def _train_and_eval(model, graph, num_steps=300, batch=128, lr=0.01,
                    seed=3):
    from euler_tpu import train as train_lib

    def source_fn(step):
        return graph.sample_node(batch, -1)

    state, _ = train_lib.train(
        model, graph, source_fn,
        num_steps=num_steps, learning_rate=lr, optimizer="adam",
        log_every=100, seed=seed,
    )
    # batch sizes must divide the conftest's 8-device mesh: 400 = 8 * 50
    ids = np.arange(NUM_NODES, dtype=np.int64)
    batches = [ids[i:i + 400] for i in range(0, NUM_NODES, 400)]
    result = train_lib.evaluate(model, graph, batches, state)
    return result["f1"]


def test_graphsage_learns_neighborhood_labels(planted):
    from euler_tpu.models import SupervisedGraphSage

    graph, info, feat_acc, hop1_acc = planted
    model = SupervisedGraphSage(
        label_idx=0, label_dim=NUM_CLASSES,
        metapath=[[0], [0]], fanouts=[10, 10], dim=32,
        feature_idx=1, feature_dim=FEATURE_DIM, max_id=NUM_NODES - 1,
        sigmoid_loss=False,
    )
    f1 = _train_and_eval(model, graph)
    assert f1 > feat_acc + 0.2, (
        f"GraphSAGE f1 {f1:.3f} is no better than single-node features "
        f"({feat_acc:.3f}): aggregation is not learning"
    )
    assert f1 > hop1_acc - MARGIN, (
        f"GraphSAGE f1 {f1:.3f} below the 1-hop separability bound "
        f"{hop1_acc:.3f} - {MARGIN}"
    )


def test_graphsage_device_sampling_learns(planted):
    """The HBM-resident sampling path must reach the same convergence
    gate as the host path — same distribution, same learning outcome."""
    from euler_tpu.models import SupervisedGraphSage

    graph, info, feat_acc, hop1_acc = planted
    model = SupervisedGraphSage(
        label_idx=0, label_dim=NUM_CLASSES,
        metapath=[[0], [0]], fanouts=[10, 10], dim=32,
        feature_idx=1, feature_dim=FEATURE_DIM, max_id=NUM_NODES - 1,
        sigmoid_loss=False, device_features=True, device_sampling=True,
    )
    f1 = _train_and_eval(model, graph)
    assert f1 > feat_acc + 0.2, (
        f"device-sampling f1 {f1:.3f} vs feature bound {feat_acc:.3f}"
    )
    assert f1 > hop1_acc - MARGIN, (
        f"device-sampling f1 {f1:.3f} below 1-hop bound "
        f"{hop1_acc:.3f} - {MARGIN}"
    )


def test_scan_train_learns(planted):
    """The fully-device scanned loop (roots sampled on device, K steps
    per dispatch) must ALSO converge — it is the bench's headline path."""
    import jax
    import numpy as np

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    graph, info, feat_acc, hop1_acc = planted
    model = SupervisedGraphSage(
        label_idx=0, label_dim=NUM_CLASSES,
        metapath=[[0], [0]], fanouts=[10, 10], dim=32,
        feature_idx=1, feature_dim=FEATURE_DIM, max_id=NUM_NODES - 1,
        sigmoid_loss=False, device_features=True, device_sampling=True,
    )
    opt = train_lib.get_optimizer("adam", 0.01)
    state = model.init_state(
        jax.random.PRNGKey(3), graph, graph.sample_node(128, -1), opt
    )
    scan = jax.jit(
        train_lib.make_scan_train(model, opt, inner_steps=50,
                                  batch_size=128),
        donate_argnums=(0,),
    )
    for chunk in range(6):  # 300 steps
        state, losses = scan(state, chunk)
    ids = np.arange(NUM_NODES, dtype=np.int64)
    batches = [ids[i:i + 400] for i in range(0, NUM_NODES, 400)]
    f1 = train_lib.evaluate(model, graph, batches, state)["f1"]
    assert f1 > hop1_acc - MARGIN, (
        f"scan-train f1 {f1:.3f} below 1-hop bound {hop1_acc:.3f}"
    )


def test_gat_learns_neighborhood_labels(planted):
    from euler_tpu.models import GAT

    graph, info, feat_acc, hop1_acc = planted
    model = GAT(
        label_idx=0, label_dim=NUM_CLASSES,
        feature_idx=1, feature_dim=FEATURE_DIM, max_id=NUM_NODES - 1,
        head_num=2, hidden_dim=32, nb_num=10,
        sigmoid_loss=False,
    )
    f1 = _train_and_eval(model, graph)
    # GAT here is single-layer attention over the 1-hop neighborhood: gate
    # against clearly-beats-features; the hop1 bound is its ceiling
    assert f1 > feat_acc + 0.2, (
        f"GAT f1 {f1:.3f} vs single-node feature bound {feat_acc:.3f}"
    )


def test_gcn_and_scalable_gcn_converge_within_tolerance(planted):
    """Plain full-neighbor GCN and ScalableGCN (stale historical stores)
    must both learn the planted labels, and the stale-store approximation
    must cost at most 0.05 F1 at convergence."""
    from euler_tpu.models import ScalableGCN, SupervisedGCN

    graph, info, feat_acc, hop1_acc = planted
    gcn = SupervisedGCN(
        label_idx=0, label_dim=NUM_CLASSES,
        metapath=[[0], [0]], dim=32,
        # static pad caps sized for the eval batches (400 roots, full
        # 2-hop expansion of an avg-degree-10 graph)
        max_nodes_per_hop=[4096, 4096],
        max_edges_per_hop=[16384, 32768],
        feature_idx=1, feature_dim=FEATURE_DIM, max_id=NUM_NODES - 1,
        sigmoid_loss=False,
    )
    f1_gcn = _train_and_eval(gcn, graph, batch=96)
    assert f1_gcn > feat_acc + 0.2, (
        f"GCN f1 {f1_gcn:.3f} vs feature bound {feat_acc:.3f}"
    )

    scal = ScalableGCN(
        label_idx=0, label_dim=NUM_CLASSES,
        edge_type=[0], num_layers=2, dim=32,
        max_id=NUM_NODES - 1, max_neighbors=10,
        feature_idx=1, feature_dim=FEATURE_DIM,
        sigmoid_loss=False,
    )
    f1_scal = _train_and_eval(scal, graph, batch=96)
    assert f1_scal > feat_acc + 0.2, (
        f"ScalableGCN f1 {f1_scal:.3f} vs feature bound {feat_acc:.3f}"
    )
    assert f1_scal > f1_gcn - 0.05, (
        f"stale-store ScalableGCN f1 {f1_scal:.3f} degrades more than "
        f"0.05 below plain GCN {f1_gcn:.3f}"
    )

    # the device full-neighborhood path (adjacency slab, no host dedup)
    # must converge equivalently
    scal_dev = ScalableGCN(
        label_idx=0, label_dim=NUM_CLASSES,
        edge_type=[0], num_layers=2, dim=32,
        max_id=NUM_NODES - 1, max_neighbors=10,
        feature_idx=1, feature_dim=FEATURE_DIM,
        sigmoid_loss=False, device_features=True, device_sampling=True,
    )
    f1_dev = _train_and_eval(scal_dev, graph, batch=96)
    assert f1_dev > f1_gcn - 0.05, (
        f"device-sampling ScalableGCN f1 {f1_dev:.3f} degrades more "
        f"than 0.05 below plain GCN {f1_gcn:.3f}"
    )


def _embedding_community_accuracy(emb, communities):
    """Nearest-centroid community recovery in embedding space: centroids
    fit from the TRUE communities on even nodes, accuracy on odd nodes.
    Random = 1/NUM_CLASSES = 0.25."""
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-9)
    n = len(communities)
    train = np.arange(n) % 2 == 0
    centroids = np.stack(
        [
            emb[train & (communities == c)].mean(0)
            for c in range(NUM_CLASSES)
        ]
    )
    pred = (emb[~train] @ centroids.T).argmax(1)
    return float((pred == communities[~train]).mean())


_UNSUP_WORKER = """
import sys
import numpy as np
import euler_tpu
from euler_tpu import models
from euler_tpu import train as train_lib

family, out_dir, n_nodes = sys.argv[1], sys.argv[2], int(sys.argv[3])
graph = euler_tpu.Graph(directory=out_dir)
steps = 400 if family == "line2" else 300
if family == "line2":
    m = models.LINE(node_type=-1, edge_type=[0], max_id=n_nodes - 1,
                    dim=32, order=2, num_negs=5)
elif family.startswith("node2vec_biased"):
    m = models.Node2Vec(
        node_type=-1, edge_type=[0], max_id=n_nodes - 1, dim=32,
        walk_len=3, walk_p=0.5, walk_q=2.0, num_negs=5,
        device_sampling=family != "node2vec_biased",
    )
    if family.endswith("_alias"):
        # round-5 exact rejection-sampled walk over flat-CSR alias
        # tables — must learn the same structure as the slab walk
        m.set_sampling_options(alias=True)
else:
    m = models.GraphSage(
        node_type=-1, edge_type=[0], max_id=n_nodes - 1,
        metapath=[[0], [0]], fanouts=[5, 5], dim=32, num_negs=5,
        use_id=True, embedding_dim=32,
    )
state, hist = train_lib.train(
    m, graph, lambda s: graph.sample_node(128, -1),
    num_steps=steps, learning_rate=0.05, optimizer="adam", log_every=200,
)
emb = train_lib.save_embedding(m, graph, n_nodes - 1, state,
                               batch_size=400)
np.save(sys.argv[4], emb)
print("MRR", hist[-1]["mrr"], flush=True)
"""


@pytest.mark.parametrize(
    "family,acc_floor",
    [
        ("line2", 0.7),
        ("node2vec_biased", 0.9),
        ("node2vec_biased_device", 0.9),
        ("node2vec_biased_alias", 0.9),
        ("unsup_sage", 0.55),
    ],
)
def test_unsupervised_embeddings_recover_communities(planted, family,
                                                     acc_floor, tmp_path):
    """Unsupervised gates: loss/MRR trends can't catch an embedding that
    descends without learning structure. On the planted-community graph
    (intra_p=0.9) the community must be recoverable from the LEARNED
    embeddings alone (no input features — id embeddings trained purely
    from graph structure): nearest-centroid accuracy far above the 0.25
    random baseline. Floors are calibrated ~0.1 under single-seed
    observed values (LINE 0.89, biased Node2Vec 1.00, unsup GraphSage
    0.73). The device variant runs the same biased walk (d_tx
    reweighting) inside the jitted step.

    Each family trains in its OWN subprocess: back-to-back trainings in
    one process can starve an XLA-CPU collective rendezvous past its
    hard 40 s abort on this oversubscribed 8-virtual-device host."""
    import os
    import subprocess
    import sys

    graph, info, _, _ = planted
    comm = info["communities"]
    out_npy = str(tmp_path / "emb.npy")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    r = subprocess.run(
        [sys.executable, "-c", _UNSUP_WORKER, family, info["out_dir"],
         str(NUM_NODES), out_npy],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    mrr = float(r.stdout.split("MRR")[1].strip())
    assert mrr > 0.5, r.stdout
    emb = np.load(out_npy)
    acc = _embedding_community_accuracy(emb, comm)
    assert acc > acc_floor, (
        f"{family}: embedding community accuracy {acc:.3f} below "
        f"{acc_floor} (random = 0.25)"
    )
