"""Device-resident sampling must match the host engine's distributions.

The host engine's samplers are distribution-tested in
tests/test_graph_engine.py; these tests hold the HBM-side implementations
(euler_tpu/graph/device.py) to the same statistical standard on the same
fixture, plus structural checks (padding rows, truncation, fanout
chaining through dead ends).
"""

import numpy as np
import pytest

from euler_tpu.graph import device

MAX_ID = 16  # fixture ids go up to 16


@pytest.fixture(scope="module")
def adj01(graph):
    return device.build_adjacency(graph, [0, 1], MAX_ID)


def test_adjacency_shapes_and_padding(graph, adj01):
    n_rows = MAX_ID + 2
    assert adj01["nbr"].shape == adj01["cum"].shape
    assert adj01["nbr"].shape[0] == n_rows
    # default row (max_id + 1) must be a dead end pointing at itself
    assert (adj01["nbr"][MAX_ID + 1] == MAX_ID + 1).all()
    # cumulative rows end at exactly 1 so u<1 always lands in-row
    assert (adj01["cum"][:, -1] == 1.0).all()


def test_neighbor_sets_match_host(graph, adj01):
    """Every device-sampled neighbor is a true neighbor of its node."""
    import jax

    nodes = graph.sample_node(64, -1)
    out = np.asarray(
        device.sample_neighbor(
            adj01, nodes, jax.random.PRNGKey(0), 8
        )
    )
    for i, n in enumerate(nodes):
        nbr, _, _, _ = graph.get_full_neighbor([n], [0, 1])
        allowed = set(nbr.tolist()) or {MAX_ID + 1}
        assert set(out[i].tolist()) <= allowed, f"node {n}"


def test_neighbor_distribution_matches_weights(graph, adj01, nodes):
    """Empirical draw frequency tracks edge weights (CompactNode
    semantics) — same bar as the host engine's distribution test."""
    import jax

    node = 10  # fixture node with weighted neighbors
    nbr, w, _, _ = graph.get_full_neighbor([node], [0, 1])
    draws = np.asarray(
        device.sample_neighbor(
            adj01, np.full(200, node), jax.random.PRNGKey(1), 100
        )
    ).reshape(-1)
    freq = {int(i): float((draws == i).mean()) for i in nbr}
    probs = w / w.sum()
    for i, p in zip(nbr, probs):
        assert abs(freq[int(i)] - p) < 0.02, (i, freq[int(i)], p)


def test_node_sampler_distribution(graph):
    import jax

    sampler = device.build_node_sampler(graph, -1, MAX_ID)
    draws = np.asarray(
        device.sample_node(sampler, jax.random.PRNGKey(2), 20000)
    )
    ids = np.arange(MAX_ID + 1, dtype=np.int64)
    weights = graph.node_weights(ids)
    probs = weights / weights.sum()
    for i in ids[weights > 0]:
        assert abs((draws == i).mean() - probs[i]) < 0.02


def test_node_sampler_typed(graph):
    import jax

    sampler = device.build_node_sampler(graph, 1, MAX_ID)
    draws = np.asarray(
        device.sample_node(sampler, jax.random.PRNGKey(3), 2000)
    )
    types = graph.node_types(np.unique(draws))
    assert (types == 1).all()


def test_fanout_chains_through_dead_ends(graph, adj01):
    """A hop landing on the default node keeps yielding the default node,
    like the host sample_fanout's default_node fill."""
    import jax

    # build a sampler over type-0 edges only; fixture node 15's type-0
    # group may be empty -> default, and hop 2 from default stays default
    adj0 = device.build_adjacency(graph, [0], MAX_ID)
    hops = device.sample_fanout(
        [adj0, adj0], np.array([15]), jax.random.PRNGKey(4), [4, 2]
    )
    assert len(hops) == 3
    h1, h2 = np.asarray(hops[1]), np.asarray(hops[2]).reshape(4, 2)
    for i, n in enumerate(h1):
        if n == MAX_ID + 1:
            assert (h2[i] == MAX_ID + 1).all()


def test_metapath_walk_respects_step_types(graph):
    """A heterogeneous walk alternating type-0 and type-1 adjacencies must
    only traverse edges of the step's type (device analog of the host
    metapath random_walk)."""
    import jax

    adj0 = device.build_adjacency(graph, [0], MAX_ID)
    adj1 = device.build_adjacency(graph, [1], MAX_ID)
    roots = graph.sample_node(32, 0)
    paths = np.asarray(
        device.random_walk(
            [adj0, adj1], roots, jax.random.PRNGKey(0), 2
        )
    )
    default = MAX_ID + 1
    for row in paths:
        a, b, c = row
        if b != default:
            nbr, _, _, _ = graph.get_full_neighbor([a], [0])
            assert b in nbr
        if c != default:
            nbr, _, _, _ = graph.get_full_neighbor([b], [1])
            assert c in nbr


def test_typed_negatives_match_src_type(graph, meta):
    """Each source's negatives come from its OWN node type's weighted
    sampler (native sample_node_with_src semantics), with the right
    marginal distribution."""
    import jax

    ts = device.build_typed_node_sampler(
        graph, meta["node_type_num"], MAX_ID
    )
    src = graph.sample_node(64, -1)
    negs = np.asarray(
        device.sample_node_with_src(ts, src, jax.random.PRNGKey(0), 50)
    )
    src_types = graph.node_types(src)
    for i in range(len(src)):
        assert (graph.node_types(negs[i]) == src_types[i]).all()
    # distribution within one type follows node weights
    t0 = np.flatnonzero(src_types == 0)
    draws = negs[t0].reshape(-1)
    ids = np.arange(MAX_ID + 1)
    w = graph.node_weights(ids)
    w[graph.node_types(ids) != 0] = 0
    probs = w / w.sum()
    for i in ids[w > 0]:
        assert abs((draws == i).mean() - probs[i]) < 0.03


def test_two_level_sampler_multi_segment_exact(graph, monkeypatch):
    """SEG shrunk to 4 so the tiny fixture spans several segments: the
    two-level draw (segment pick x within-segment bisect) must reproduce
    the host sampling weights — the default-SEG distribution tests only
    ever exercise one segment."""
    import jax

    monkeypatch.setattr(device, "SEG", 4)
    sampler = device.build_node_sampler(graph, -1, MAX_ID)
    assert sampler["seg_cum"].shape[0] > 1
    draws = np.asarray(
        device.sample_node(sampler, jax.random.PRNGKey(5), 20000)
    )
    ids = np.arange(MAX_ID + 1, dtype=np.int64)
    weights = graph.node_weights(ids)
    probs = weights / weights.sum()
    for i in ids[weights > 0]:
        assert abs((draws == i).mean() - probs[i]) < 0.02


def test_two_level_typed_negatives_multi_segment(graph, meta, monkeypatch):
    """Same segment-boundary coverage for the typed negative sampler:
    SEG=2 forces every type across multiple sub-segments, and each
    source must still draw its own type at the host weights."""
    import jax

    monkeypatch.setattr(device, "SEG", 2)
    ts = device.build_typed_node_sampler(graph, meta["node_type_num"], MAX_ID)
    assert ts["seg_cum"].shape[0] > ts["off"].shape[0] - 1
    # the 0.03 gate below is tight enough that the SRC draw must be
    # pinned: inheriting whatever thread-RNG state earlier tests left
    # behind made this pass or fail with suite composition
    from euler_tpu.graph.native import lib as native_lib

    native_lib().eg_seed(182)
    src = graph.sample_node(64, -1)
    negs = np.asarray(
        device.sample_node_with_src(ts, src, jax.random.PRNGKey(1), 64)
    )
    src_types = graph.node_types(src)
    for i in range(len(src)):
        assert (graph.node_types(negs[i]) == src_types[i]).all()
    for t in range(meta["node_type_num"]):
        rows = np.flatnonzero(src_types == t)
        if not len(rows):
            continue
        draws = negs[rows].reshape(-1)
        ids = np.arange(MAX_ID + 1)
        w = graph.node_weights(ids)
        w[graph.node_types(ids) != t] = 0
        probs = w / w.sum()
        for i in ids[w > 0]:
            assert abs((draws == i).mean() - probs[i]) < 0.03


def test_two_level_sampler_beyond_float32_cliff():
    """>2^24 comparably-weighted nodes — the regime where a FLAT float32
    cumulative provably collides (adjacent values equal, tail nodes
    silently unsampleable; the round-2 design warned and bailed here).
    The two-level layout keeps every within-segment step representable
    and the tail region draws at its exact probability."""
    import jax

    m = (1 << 24) + (1 << 20)  # 17.8M equal-weight nodes
    tail = 1 << 20

    class EqualWeightGraph:
        def node_weights(self, ids):
            return np.ones(len(ids), np.float32)

        def node_types(self, ids):
            return np.zeros(len(ids), np.int32)

    # the flat cumulative this layout replaces DOES collide at this size
    flat_tail = (
        (np.arange(m - tail, m, dtype=np.float64) + 1) / m
    ).astype(np.float32)
    assert (np.diff(flat_tail) == 0).any()

    sampler = device.build_node_sampler(EqualWeightGraph(), -1, m - 1)
    # two-level: segment steps stay representable (strictly increasing)
    seg = sampler["cum"][: (m // device.SEG) * device.SEG]
    assert (np.diff(seg.reshape(-1, device.SEG), axis=1) > 0).all()
    draws = np.asarray(
        device.sample_node(sampler, jax.random.PRNGKey(7), 4096)
    )
    p_tail = tail / m
    got = (draws >= m - tail).mean()
    assert abs(got - p_tail) < 6 * np.sqrt(p_tail * (1 - p_tail) / 4096)
    # the very tail is reachable, not probability-0
    assert draws.max() >= m - tail


def test_typed_negatives_clamp_out_of_range_types(graph):
    """Sources whose node type is outside the sampler's configured range
    clamp into it (like the TypedDense towers) — never the degenerate
    all-default-negatives path."""
    import jax

    ts = device.build_typed_node_sampler(graph, 1, MAX_ID)  # only type 0
    src = graph.sample_node(16, 1)  # type-1 sources
    negs = np.asarray(
        device.sample_node_with_src(ts, src, jax.random.PRNGKey(0), 8)
    )
    assert (negs != MAX_ID + 1).all()  # real nodes, not the default
    assert (graph.node_types(negs.reshape(-1)) == 0).all()


def test_device_sparse_tables_match_host_gather(graph):
    """consts['sparse'] rows gathered at gids must equal the host-side
    padded sparse gather for the same nodes."""
    from euler_tpu import ops
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.models.base import gather_consts

    m = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1]], fanouts=[3],
        dim=16, feature_idx=0, feature_dim=2, max_id=MAX_ID,
        sparse_feature_idx=[0], sparse_feature_max_ids=[40],
        sparse_max_len=4, device_features=True,
    )
    consts = m.build_consts(graph)
    ids = np.arange(MAX_ID + 1, dtype=np.int64)
    host = ops.get_sparse_feature(graph, ids, [0], 4, default_values=[41])
    feats = gather_consts({"gids": ids.astype(np.int32)}, consts)
    dev_ids, dev_mask = feats["sparse"][0]
    np.testing.assert_array_equal(np.asarray(dev_ids), host[0][0])
    np.testing.assert_array_equal(np.asarray(dev_mask), host[0][1])


def test_zero_weight_neighbors_exist_but_never_sample(tmp_path):
    """A node whose edges all weigh 0: the host engine returns the
    neighbors from GetFullNeighbor (they EXIST — the full-neighborhood
    GCN aggregates them) but can never sample them. The slab must encode
    both: nbr/deg keep the neighbors, sample_neighbor yields default."""
    import jax

    import euler_tpu
    from euler_tpu.graph.convert import convert_dicts

    meta = {
        "node_type_num": 1, "edge_type_num": 1,
        "node_uint64_feature_num": 0, "node_float_feature_num": 0,
        "node_binary_feature_num": 0, "edge_uint64_feature_num": 0,
        "edge_float_feature_num": 0, "edge_binary_feature_num": 0,
    }
    nodes = [
        {"node_id": 0, "node_type": 0, "node_weight": 1.0,
         "neighbor": {"0": {"1": 0.0, "2": 0.0}},  # all-zero weights
         "uint64_feature": {}, "float_feature": {}, "binary_feature": {},
         "edge": []},
        {"node_id": 1, "node_type": 0, "node_weight": 1.0,
         "neighbor": {"0": {"2": 1.0}}, "uint64_feature": {},
         "float_feature": {}, "binary_feature": {}, "edge": []},
        {"node_id": 2, "node_type": 0, "node_weight": 1.0,
         "neighbor": {"0": {}}, "uint64_feature": {},
         "float_feature": {}, "binary_feature": {}, "edge": []},
    ]
    convert_dicts(nodes, meta, str(tmp_path / "part"), 1)
    g = euler_tpu.Graph(directory=str(tmp_path))
    adj = device.build_adjacency(g, [0], 2)
    # existence: both zero-weight neighbors are in the slab
    assert adj["deg"][0] == 2
    assert set(adj["nbr"][0, :2].tolist()) == {1, 2}
    # sampling: node 0 yields only the default node (host semantics)
    out = np.asarray(
        device.sample_neighbor(
            adj, np.array([0, 1]), jax.random.PRNGKey(0), 16
        )
    )
    assert (out[0] == 3).all()   # default = max_id + 1
    assert (out[1] == 2).all()
    g.close()


def test_truncation_keeps_heaviest(graph):
    with pytest.warns(UserWarning, match="truncated"):
        adj = device.build_adjacency(graph, [0, 1], MAX_ID, max_degree=1)
    node = 10
    nbr, w, _, _ = graph.get_full_neighbor([node], [0, 1])
    heaviest = int(nbr[np.argmax(w)])
    assert adj["nbr"][node, 0] == heaviest


def test_supervised_sage_device_sampling_trains(graph):
    """device_sampling=True: batch is roots+seed only; fanout, feature
    gather, labels, loss all happen inside the jitted step (8-dev mesh
    via conftest)."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    m = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=16, feature_idx=0, feature_dim=2,
        max_id=MAX_ID, device_features=True, device_sampling=True,
    )
    batch = m.sample(graph, graph.sample_node(8, -1))
    assert set(batch) == {"roots", "seed"}
    state, hist = train_lib.train(
        m, graph, lambda s: graph.sample_node(8, -1),
        num_steps=8, learning_rate=0.01, optimizer="adam", log_every=4,
    )
    res = train_lib.evaluate(m, graph, [np.arange(16)], state)
    assert np.isfinite(res["loss"])


def test_scan_train_runs_fully_on_device(graph):
    """make_scan_train: K steps per dispatch, roots sampled on device;
    losses must be finite and the state must advance."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    m = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=16, feature_idx=0, feature_dim=2,
        max_id=MAX_ID, device_features=True, device_sampling=True,
    )
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    scan = jax.jit(
        train_lib.make_scan_train(m, opt, inner_steps=5, batch_size=8),
        donate_argnums=(0,),
    )
    p0 = np.asarray(
        jax.tree_util.tree_leaves(state["params"])[0]
    ).copy()
    state, losses = scan(state, 0)
    state, losses = scan(state, 1)
    losses = np.asarray(losses)
    assert losses.shape == (5,)
    assert np.isfinite(losses).all()
    p1 = np.asarray(jax.tree_util.tree_leaves(state["params"])[0])
    assert not np.allclose(p0, p1)  # training actually moved the params


def test_device_sampling_model_parallel_mesh(graph):
    """The sampler consts must survive a (data x model) mesh: adjacency /
    root-sampler arrays replicate (never padded/row-sharded), tables
    shard — regression for the searchsorted-corruption hazard."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.parallel import (
        make_mesh, pad_tables_for_mesh, state_sharding,
    )

    m = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=16, feature_idx=0, feature_dim=2,
        max_id=MAX_ID, device_features=True, device_sampling=True,
    )
    mesh = make_mesh(8, model_parallel=2)
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    roots_len = state["consts"]["roots"]["cum"].shape[0]
    state = pad_tables_for_mesh(state, mesh)
    # sampler arrays unpadded, feature table padded to the model axis
    assert state["consts"]["roots"]["cum"].shape[0] == roots_len
    assert state["consts"]["features"].shape[0] % 2 == 0
    shardings = state_sharding(mesh, state)
    state = jax.device_put(state, shardings)
    step = jax.jit(
        m.make_train_step(opt),
        in_shardings=(shardings, None),
        out_shardings=(shardings, None, None),
    )
    batch = m.sample(graph, graph.sample_node(8, -1))
    state, loss, metric = step(state, batch)
    assert np.isfinite(float(loss))


def test_unsup_negs_sampler_survives_model_parallel(graph):
    """consts['negs'] (the unsupervised negative sampler) must replicate
    unpadded under model parallelism: zero-padding would unsort its
    cumulative weights and silently corrupt every negative draw."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu import models
    from euler_tpu.parallel import (
        make_mesh, pad_tables_for_mesh, state_sharding,
    )

    m = models.GraphSage(
        node_type=-1, edge_type=[0, 1], max_id=MAX_ID,
        metapath=[[0, 1]], fanouts=[3], dim=16, num_negs=3,
        feature_idx=0, feature_dim=2,
        device_features=True, device_sampling=True,
    )
    mesh = make_mesh(8, model_parallel=2)
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    negs_len = state["consts"]["negs"]["cum"].shape[0]
    state = pad_tables_for_mesh(state, mesh)
    assert state["consts"]["negs"]["cum"].shape[0] == negs_len
    cum = np.asarray(state["consts"]["negs"]["cum"])
    assert (np.diff(cum) >= 0).all(), "cum must stay sorted"
    shardings = state_sharding(mesh, state)
    state = jax.device_put(state, shardings)
    step = jax.jit(
        m.make_train_step(opt),
        in_shardings=(shardings, None),
        out_shardings=(shardings, None, None),
    )
    state, loss, _ = step(state, m.sample(graph, graph.sample_node(8, -1)))
    assert np.isfinite(float(loss))


def test_device_sampling_with_use_id(graph):
    """use_id composes with device_sampling (the gids double as embedding
    ids); sparse features are rejected up front."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage

    m = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1]], fanouts=[3],
        dim=16, feature_idx=0, feature_dim=2, max_id=MAX_ID, use_id=True,
        device_features=True, device_sampling=True,
    )
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    step = jax.jit(m.make_train_step(opt), donate_argnums=(0,))
    state, loss, _ = step(state, m.sample(graph, graph.sample_node(8, -1)))
    assert np.isfinite(float(loss))

    # sparse features ride device-resident padded tables (consts["sparse"])
    m2 = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1]], fanouts=[3],
        dim=16, feature_idx=0, feature_dim=2, max_id=MAX_ID,
        sparse_feature_idx=[0], sparse_feature_max_ids=[40],
        device_features=True, device_sampling=True,
    )
    state = m2.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    assert "sparse" in state["consts"]
    step = jax.jit(m2.make_train_step(opt), donate_argnums=(0,))
    state, loss, _ = step(
        state, m2.sample(graph, graph.sample_node(8, -1))
    )
    assert np.isfinite(float(loss))


@pytest.mark.parametrize(
    "family",
    ["unsup_sage", "gat", "scalable_sage", "scalable_gcn", "line",
     "node2vec", "lshne"],
)
def test_device_sampling_model_families(graph, family):
    """device_sampling generalizes across families: unsupervised GraphSAGE
    (device positives + typed negatives), GAT (device attention
    neighborhood), ScalableSage (device 1-hop + store scatter), LINE
    (device positives), Node2Vec (device walks -> skip-gram pairs). Each
    trains via the standard loop AND the fully-device scanned loop."""
    import jax

    from euler_tpu import train as train_lib
    from euler_tpu import models

    if family == "unsup_sage":
        m = models.GraphSage(
            node_type=-1, edge_type=[0, 1], max_id=MAX_ID,
            metapath=[[0, 1], [0, 1]], fanouts=[3, 2], dim=16,
            num_negs=3, feature_idx=0, feature_dim=2,
            device_features=True, device_sampling=True,
        )
    elif family == "gat":
        m = models.GAT(
            label_idx=2, label_dim=3, feature_idx=0, feature_dim=2,
            max_id=MAX_ID, head_num=2, hidden_dim=16, nb_num=4,
            edge_type=[0, 1],
            device_features=True, device_sampling=True,
        )
    elif family == "line":
        m = models.LINE(
            node_type=-1, edge_type=[0, 1], max_id=MAX_ID, dim=16,
            order=2, num_negs=3, device_sampling=True,
        )
    elif family == "node2vec":
        m = models.Node2Vec(
            node_type=-1, edge_type=[0, 1], max_id=MAX_ID, dim=16,
            walk_len=3, left_win_size=1, right_win_size=1, num_negs=3,
            device_sampling=True,
        )
    elif family == "lshne":
        m = models.LsHNE(
            node_type=-1,
            path_patterns=[
                [[[0], [1], [0]]],
                [[[0, 1], [0, 1], [0, 1]]],
            ],
            max_id=MAX_ID, dim=8, sparse_feature_dims=[32, 32],
            feature_ids=[0, 1], num_negs=4, src_type_num=2,
            device_sampling=True,
        )
    elif family == "scalable_gcn":
        m = models.ScalableGCN(
            label_idx=2, label_dim=3, edge_type=[0, 1], num_layers=2,
            dim=16, max_id=MAX_ID, max_neighbors=6, feature_idx=0,
            feature_dim=2, device_features=True, device_sampling=True,
        )
    else:
        m = models.ScalableSage(
            label_idx=2, label_dim=3, edge_type=[0, 1], fanout=3,
            num_layers=2, dim=16, max_id=MAX_ID, feature_idx=0,
            feature_dim=2, device_features=True, device_sampling=True,
        )
    batch = m.sample(graph, graph.sample_node(8, -1))
    assert set(batch) == {"roots", "seed"}
    state, _ = train_lib.train(
        m, graph, lambda s: graph.sample_node(8, -1),
        num_steps=6, learning_rate=0.01, optimizer="adam", log_every=3,
    )
    res = train_lib.evaluate(m, graph, [np.arange(16)], state)
    assert np.isfinite(res["loss"])

    # fully-device scanned loop
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    scan = jax.jit(
        train_lib.make_scan_train(m, opt, inner_steps=4, batch_size=8),
        donate_argnums=(0,),
    )
    state, losses = scan(state, 0)
    assert np.isfinite(np.asarray(losses)).all()


def _analytic_biased_joint(adj, root, p, q):
    """Exact P(c1, c2) for a 2-step node2vec walk from `root`, computed
    with numpy from the slab arrays: step 1 plain weighted, step 2
    reweighted by d_tx w.r.t. parent=root (1 shared neighbor — winning
    over 1/p on a root self-loop, the reference merge's branch order;
    1/p return; 1/q otherwise) — reference graph.cc:120-151 semantics."""
    nbr, cum, deg = (
        np.asarray(adj["nbr"]), np.asarray(adj["cum"]),
        np.asarray(adj["deg"]),
    )

    def row_probs(v):
        d = deg[v]
        w = np.diff(cum[v][:d], prepend=0.0)
        return nbr[v][:d], w / w.sum()

    joint = {}
    c1s, p1s = row_probs(root)
    root_nbrs = set(nbr[root][: deg[root]].tolist())
    for c1, p1 in zip(c1s, p1s):
        cands, w2 = row_probs(int(c1))
        scale = np.array(
            [
                1.0 if c in root_nbrs
                else (1.0 / p if c == root else 1.0 / q)
                for c in cands
            ]
        )
        w2 = w2 * scale
        w2 = w2 / w2.sum()
        for c2, pr in zip(cands, w2):
            joint[(int(c1), int(c2))] = (
                joint.get((int(c1), int(c2)), 0.0) + p1 * pr
            )
    return joint


@pytest.mark.parametrize("pq", [(4.0, 0.25), (0.25, 4.0)])
def test_biased_walk_matches_analytic_distribution(graph, pq):
    """The device node2vec-biased walk must reproduce the d_tx-reweighted
    distribution exactly (same bar as the host engine's biased-walk
    distribution test): empirical 2-step joint vs the analytic joint
    computed from the same slab."""
    import jax

    p, q = pq
    adj = device.build_adjacency(graph, [0, 1], MAX_ID, sorted=True)
    root = 10
    n = 40000
    walks = np.asarray(
        device.biased_random_walk(
            adj, np.full(n, root), jax.random.PRNGKey(5), 2, p, q
        )
    )
    assert (walks[:, 0] == root).all()
    expected = _analytic_biased_joint(adj, root, p, q)
    pairs, counts = np.unique(walks[:, 1:], axis=0, return_counts=True)
    seen = {
        (int(a), int(b)): c / n for (a, b), c in zip(pairs, counts)
    }
    # every observed pair is a legal transition, and frequencies match
    assert set(seen) <= set(expected), set(seen) - set(expected)
    for pair, prob in expected.items():
        assert abs(seen.get(pair, 0.0) - prob) < 0.02, (pair, prob, seen)


def test_biased_walk_rows_must_be_sorted(graph):
    """Unsorted slabs give wrong membership tests; the sorted builder is
    what makes them searchable. Sanity: the sorted variant's real slots
    are ascending per row."""
    adj = device.build_adjacency(graph, [0, 1], MAX_ID, sorted=True)
    nbr, deg = np.asarray(adj["nbr"]), np.asarray(adj["deg"])
    for v in range(nbr.shape[0]):
        row = nbr[v][: deg[v]]
        assert (np.diff(row) >= 0).all(), (v, row)


def test_node2vec_biased_device_sampling_trains(graph):
    """Node2Vec with p/q != 1 runs the biased walk on device end-to-end
    (this configuration raised before)."""
    import jax

    from euler_tpu import models
    from euler_tpu import train as train_lib

    m = models.Node2Vec(
        node_type=-1, edge_type=[0, 1], max_id=MAX_ID, dim=16,
        walk_len=3, walk_p=4.0, walk_q=0.25, left_win_size=1,
        right_win_size=1, num_negs=3, device_sampling=True,
    )
    batch = m.sample(graph, graph.sample_node(8, -1))
    assert set(batch) == {"roots", "seed"}
    state, hist = train_lib.train(
        m, graph, lambda s: graph.sample_node(8, -1),
        num_steps=6, learning_rate=0.01, log_every=3,
    )
    assert np.isfinite(hist[-1]["loss"])

    # fully-device scanned loop
    opt = train_lib.get_optimizer("adam", 0.01)
    state = m.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    scan = jax.jit(
        train_lib.make_scan_train(m, opt, inner_steps=4, batch_size=8),
        donate_argnums=(0,),
    )
    state, losses = scan(state, 0)
    assert np.isfinite(np.asarray(losses)).all()


def _assert_hops_match_host(h_hops, d_hops, roots):
    """Hop-by-hop equality of the device multi_hop_neighbor COO against
    the host expansion: same sorted unique node sets, same (src node id,
    dst node id) edge MULTIsets (multiplicity included). Shared with the
    random-graph suite (tests/test_device_graph_random.py)."""
    cur_ids = roots
    for h, (hh, dh) in enumerate(zip(h_hops, d_hops)):
        assert np.array_equal(
            np.asarray(dh["nodes"]), hh.nodes.astype(np.int32)
        ), f"hop {h} node sets differ"
        h_mask = hh.adj["mask"] > 0
        h_edges = sorted(
            zip(
                cur_ids[hh.adj_src[h_mask]].tolist(),
                hh.nodes[hh.adj_dst[h_mask]].tolist(),
            )
        )
        d_mask = np.asarray(dh["mask"]) > 0
        d_src = np.asarray(cur_ids)[np.asarray(dh["src"])[d_mask]]
        d_dst = np.asarray(dh["nodes"])[np.asarray(dh["dst"])[d_mask]]
        assert sorted(zip(d_src.tolist(), d_dst.tolist())) == h_edges, (
            f"hop {h} edge multisets differ"
        )
        cur_ids = hh.nodes


def test_multi_hop_neighbor_matches_host_exactly(graph, adj01):
    """The device full-neighbor expansion is deterministic, so it must
    reproduce the host ops.get_multi_hop_neighbor exactly: same sorted
    unique node sets, same (src_id, dst_id) edge sets."""
    from euler_tpu import ops

    roots = np.array([10, 11, 16], dtype=np.int64)
    caps = [8, 12]
    h_roots, h_hops = ops.get_multi_hop_neighbor(
        graph, roots, [[0, 1], [0, 1]],
        max_nodes_per_hop=caps, max_edges_per_hop=[64, 256],
        default_node=MAX_ID + 1,
    )
    d_hops = device.multi_hop_neighbor([adj01, adj01], roots, caps)
    _assert_hops_match_host(h_hops, d_hops, roots)
    # dedup overflow: cap smaller than the unique count drops the
    # largest-id nodes instead of raising
    tight = device.multi_hop_neighbor([adj01], roots, [2])
    kept = np.asarray(tight[0]["nodes"])
    full = np.unique(
        np.asarray(h_hops[0].nodes[: h_hops[0].num_nodes])
    )
    assert np.array_equal(kept, np.sort(full)[:2].astype(np.int32))


def test_supervised_gcn_device_matches_host_loss(graph):
    """Same params, same roots: the device-expanded SupervisedGCN step
    must produce the host path's loss (full-neighbor GCN has no sampling
    randomness)."""
    import jax

    from euler_tpu import models

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]], dim=8,
        max_nodes_per_hop=[8, 12], max_edges_per_hop=[64, 256],
        feature_idx=0, feature_dim=2, max_id=MAX_ID,
    )
    mh = models.SupervisedGCN(**kw)
    md = models.SupervisedGCN(
        **kw, device_features=True, device_sampling=True
    )
    roots = np.array([10, 11, 16], dtype=np.int64)

    state_h = mh.init_state(
        jax.random.PRNGKey(0), graph, roots,
        __import__("optax").adam(0.01),
    )
    state_d = md.init_state(
        jax.random.PRNGKey(0), graph, roots,
        __import__("optax").adam(0.01),
    )
    # same module structure -> transplant host params into the device run
    out_h = mh.module.apply(
        {"params": state_h["params"]}, mh.sample(graph, roots)
    )
    out_d = md.module.apply(
        {"params": state_h["params"]},
        md.sample(graph, roots),
        state_d["consts"],
    )
    np.testing.assert_allclose(
        float(out_h.loss), float(out_d.loss), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(out_h.embedding), np.asarray(out_d.embedding),
        rtol=1e-4, atol=1e-5,
    )


def test_lasgnn_device_sampling_trains(graph):
    """LasGNN's structured batch (label + node-id groups) also runs the
    device path: host ships only labels/ids/seed, the per-group
    heterogeneous metapath fanouts and sparse-feature gathers happen
    inside the jitted step."""
    from euler_tpu import models
    from euler_tpu import train as train_lib

    m = models.LasGNN(
        metapaths_of_groups=[
            [[[0], [0, 1]]],
            [[[0], [0, 1]], [[1], [0, 1]]],
        ],
        fanouts=[2, 2],
        dim=8,
        feature_ixs=[0, 1],
        feature_dims=[32, 32],
        group_sizes=[1, 2],
        max_id=MAX_ID,
        device_sampling=True,
    )
    rng = np.random.default_rng(0)

    def source_fn(step):
        ids = graph.sample_node(8, -1)
        ctx = graph.sample_node(16, -1).reshape(8, 2)
        return {
            "label": rng.integers(0, 2, (8, 1)).astype(np.float32),
            "groups": [ids.reshape(8, 1), ctx],
        }

    batch = m.sample(graph, source_fn(0))
    assert set(batch) == {"label", "group0", "group1", "seed"}
    assert batch["group1"].dtype == np.int32

    state, hist = train_lib.train(
        m, graph, source_fn, num_steps=6, learning_rate=0.01,
        log_every=3,
    )
    assert np.isfinite(hist[-1]["loss"])
    assert 0.0 <= hist[-1]["auc"] <= 1.0
    emb = train_lib.save_embedding(m, graph, MAX_ID, state, batch_size=8)
    assert emb.shape == (MAX_ID + 1, 8)
    assert np.isfinite(emb).all()


def test_remote_graph_export_matches_local(graph, tmp_path):
    """Device-graph export composes with remote mode (round 3): the
    samplers ride the kNodeWeight/kNodeType RPCs and the adjacency rides
    get_full_neighbor, so a sharded service exports byte-identical slabs
    to the embedded engine's."""
    from euler_tpu.graph.service import GraphService
    import euler_tpu

    from tests.fixture_graph import write_fixture

    d = str(tmp_path / "g")
    import os

    os.makedirs(d)
    write_fixture(d, num_partitions=2)
    with GraphService(d, 0, 2) as s0, GraphService(d, 1, 2) as s1:
        remote = euler_tpu.Graph(
            mode="remote", shards=[s0.address, s1.address]
        )
        for nt in (-1, 0, 1):
            rs = device.build_node_sampler(remote, nt, MAX_ID)
            ls = device.build_node_sampler(graph, nt, MAX_ID)
            np.testing.assert_array_equal(rs["ids"], ls["ids"])
            np.testing.assert_allclose(rs["cum"], ls["cum"], rtol=1e-6)
        rt = device.build_typed_node_sampler(remote, 2, MAX_ID)
        lt = device.build_typed_node_sampler(graph, 2, MAX_ID)
        for k in ("ids", "off", "types"):
            np.testing.assert_array_equal(rt[k], lt[k])
        np.testing.assert_allclose(rt["cum"], lt["cum"], rtol=1e-6)
        ra = device.build_adjacency(remote, [0, 1], MAX_ID)
        la = device.build_adjacency(graph, [0, 1], MAX_ID)
        for k in ("nbr", "deg", "sampleable"):
            np.testing.assert_array_equal(ra[k], la[k])
        np.testing.assert_allclose(ra["cum"], la["cum"], rtol=1e-6)
        # the exact alias form (incl. the id-sorted rows the rejection
        # walk bisects) exports identically through the sharded client
        raa = device.build_alias_adjacency(remote, [0, 1], MAX_ID,
                                           sorted=True)
        laa = device.build_alias_adjacency(graph, [0, 1], MAX_ID,
                                           sorted=True)
        for k in ("off", "deg", "nbr", "alias", "sampleable"):
            np.testing.assert_array_equal(raa[k], laa[k])
        np.testing.assert_allclose(raa["prob"], laa["prob"], rtol=1e-6)
        remote.close()
