"""Console REPL tests (reference tools/console/console.cc command surface)
driven through Console.execute on the fixture graph."""

import pytest

from euler_tpu.console import Console


@pytest.fixture()
def console(fixture_dir, capsys):
    c = Console()
    assert c.execute(f'con "directory={fixture_dir}"')
    out = capsys.readouterr().out
    assert "connected:" in out
    return c


def test_help_lists_commands(capsys):
    c = Console()
    c.execute("help")
    out = capsys.readouterr().out
    for cmd in ("con", "nf", "ef", "nb", "sn", "walk"):
        assert cmd in out


def test_nf_dense(console, capsys):
    console.execute('nf dense "10, 12" "0:2"')
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2
    assert out[0].startswith("node 10:")


def test_nf_sparse_and_binary(console, capsys):
    console.execute('nf sparse "10, 12" "0"')
    out = capsys.readouterr().out
    assert "node 10 slot 0:" in out
    console.execute('nf binary "10" "0"')
    out = capsys.readouterr().out
    assert "node 10 slot 0: b" in out


def test_nb_lists_neighbors(console, capsys, graph):
    console.execute('nb "10" "0, 1"')
    out = capsys.readouterr().out
    nbr, w, t, counts = graph.get_full_neighbor([10], [0, 1])
    assert out.startswith("node 10: [")
    for nid in nbr:
        assert str(int(nid)) in out


def test_sn_and_walk(console, capsys):
    console.execute("sn 4 0")
    ids = eval(capsys.readouterr().out.strip())
    assert len(ids) == 4
    console.execute('walk "10" "0, 1" 3')
    out = capsys.readouterr().out.strip()
    assert out.startswith("10 ->")
    assert out.count("->") == 3


def test_unknown_command_keeps_repl_alive(console, capsys):
    assert console.execute("frobnicate")
    assert "invalid command" in capsys.readouterr().err
    assert not console.execute("quit")


def test_error_does_not_kill_repl(console, capsys):
    assert console.execute('nf dense "not_an_int" "0"')
    assert "error:" in capsys.readouterr().err


def test_stats_blackbox_shows_recorder_and_resources(console, capsys):
    """`stats blackbox` prints the flight-recorder state and the live
    resource gauges (OBSERVABILITY.md 'Postmortems')."""
    from euler_tpu import blackbox as B

    B.blackbox_reset()
    B.set_blackbox(True)
    B.record("app", value=123)
    console.execute("stats blackbox")
    out = capsys.readouterr().out
    assert "blackbox on" in out
    assert "rss" in out and "fds" in out and "threads" in out
    assert "app" in out  # the recorded event's point in a ring tail
    B.blackbox_reset()


def test_stats_heat_shows_topk_and_ledger(console, capsys):
    """`stats heat` prints the hot-vertex table (fed here through the
    app-level record primitive) and the cache-class rows
    (OBSERVABILITY.md 'Data-plane heat')."""
    import numpy as np

    from euler_tpu import heat as H

    H.heat_reset()
    H.set_heat(True)
    H.record_heat(np.array([7, 7, 7, 8, 8, 9], dtype=np.int64),
                  op="dense_feature")
    console.execute("stats heat")
    out = capsys.readouterr().out
    assert "heat on" in out
    assert "client top-3" in out
    # hottest first: id 7 (count 3) leads the table
    lines = [ln for ln in out.splitlines() if ln.strip().startswith("1 ")]
    assert lines and " 7 " in lines[0]
    H.heat_reset()


def test_stats_bare_lists_subcommands(console, capsys):
    """Bare `stats` advertises the full subcommand roster — the help
    text stopped being updated after the telemetry PR, so this pins
    every surface added since."""
    console.execute("stats")
    out = capsys.readouterr().out
    for sub in ("hist", "phases", "slow", "blackbox", "heat", "reset"):
        assert sub in out, (sub, out)
    console.execute("help stats")
    help_out = capsys.readouterr().out
    assert "stats [hist|phases|slow|blackbox|heat|reset]" in help_out


def test_stats_span_timers(console, capsys):
    """The native span-timer subsystem records ops and resets."""
    import euler_tpu

    euler_tpu.stats_reset()
    console.execute("sn 4 0")
    console.execute('nb "10" "0"')
    capsys.readouterr()
    snap = euler_tpu.stats()
    assert snap["sample_node"]["count"] >= 1
    assert snap["full_neighbor"]["count"] >= 1
    assert snap["sample_node"]["total_ms"] >= 0.0
    console.execute("stats")
    out = capsys.readouterr().out
    assert "sample_node" in out and "avg_us" in out
    console.execute("stats reset")
    capsys.readouterr()
    assert "sample_node" not in euler_tpu.stats()
