"""Ops-layer tests (reference analog: euler_ops/*_test.py)."""

import numpy as np

from euler_tpu import ops
from tests.fixture_graph import TOPOLOGY


def test_multi_hop_exact(graph):
    roots, hops = ops.get_multi_hop_neighbor(graph, [10, 16], [[0], [0, 1]])
    np.testing.assert_array_equal(roots, [10, 16])
    h0 = hops[0]
    # type-0 neighbors of 10: {11,12}; of 16: {10,11,12} -> unique {10,11,12}
    np.testing.assert_array_equal(h0.nodes, [10, 11, 12])
    assert h0.num_edges == 5
    # every edge maps correctly
    for s, d, w in zip(h0.adj_src, h0.adj_dst, h0.adj_w):
        root = roots[s]
        dst = h0.nodes[d]
        assert dst in TOPOLOGY[root][2].get(0, {})
        assert TOPOLOGY[root][2][0][dst] == w


def test_multi_hop_padded(graph):
    roots, hops = ops.get_multi_hop_neighbor(
        graph,
        [10, 16],
        [[0], [0, 1]],
        max_nodes_per_hop=[8, 16],
        max_edges_per_hop=[8, 32],
        default_node=-1,
    )
    h0, h1 = hops
    assert h0.nodes.shape == (8,) and h0.adj_src.shape == (8,)
    assert h1.nodes.shape == (16,) and h1.adj_w.shape == (32,)
    # padding nodes are default, padding edges have zero weight
    np.testing.assert_array_equal(h0.nodes[h0.num_nodes :], [-1] * (8 - h0.num_nodes))
    assert (h0.adj_w[h0.num_edges :] == 0).all()
    # second hop: every real edge goes from a hop-1 unique node to one of
    # its actual topological neighbors
    for s, d in zip(h1.adj_src[: h1.num_edges], h1.adj_dst[: h1.num_edges]):
        assert int(s) < h0.num_nodes
        src_node = int(h0.nodes[int(s)])
        dst_node = int(h1.nodes[int(d)])
        assert any(
            dst_node in g for g in TOPOLOGY[src_node][2].values()
        ), (src_node, dst_node)
    # adj dict form exposes a correct padding mask
    adj = h1.adj
    assert adj["mask"].sum() == h1.num_edges
    assert set(adj) == {"src", "dst", "w", "mask"}


def test_multi_hop_cap_overflow(graph):
    try:
        ops.get_multi_hop_neighbor(
            graph, [16], [[0, 1]], max_nodes_per_hop=[2], max_edges_per_hop=[32]
        )
        assert False, "expected ValueError"
    except ValueError as e:
        assert "cap" in str(e)


def test_sparse_feature_padded(graph):
    out = ops.get_sparse_feature(
        graph, [10, 15, 999], [0, 1], max_len=3, default_values=[99, 88]
    )
    ids0, mask0 = out[0]
    np.testing.assert_array_equal(ids0[0], [10, 11, 99])
    np.testing.assert_array_equal(mask0[0], [1, 1, 0])
    np.testing.assert_array_equal(ids0[2], [99, 99, 99])
    np.testing.assert_array_equal(mask0[2], [0, 0, 0])
    ids1, mask1 = out[1]
    np.testing.assert_array_equal(ids1[1], [7, 88, 88])


def test_gen_pair_count_and_content():
    paths = np.array([[1, 2, 3, 4]])
    pairs = ops.gen_pair(paths, 1, 1)
    assert pairs.shape == (1, ops.walk.pair_count(4, 1, 1), 2)
    assert pairs.shape[1] == 6
    expected = {(1, 2), (2, 1), (2, 3), (3, 2), (3, 4), (4, 3)}
    got = {tuple(p) for p in pairs[0]}
    assert got == expected


def test_gen_pair_matches_reference_order():
    # Reference kernel order: j-major, left (j-1, j-2, ...) then right.
    paths = np.array([[5, 6, 7]])
    pairs = ops.gen_pair(paths, 2, 1)
    expected = [
        (5, 6),          # j=0 right
        (6, 5), (6, 7),  # j=1 left(1) then right
        (7, 6), (7, 5),  # j=2 left(1), left(2)
    ]
    assert [tuple(p) for p in pairs[0]] == expected
    assert pairs.shape[1] == ops.walk.pair_count(3, 2, 1)


def test_walk_to_pairs_pipeline(graph):
    walks = ops.random_walk(graph, [10, 16, 13], [0, 1], 3)
    pairs = ops.gen_pair(walks, 1, 1)
    assert pairs.shape == (3, ops.walk.pair_count(4, 1, 1), 2)
