"""Device-sampling structural invariants on a RANDOM weighted graph.

tests/test_device_graph.py pins semantics on the 7-node hand-built
fixture; this module re-checks the slab build, the XLA draw path, and
the packed kernel layout at an irregular scale the fixture cannot
produce — poisson degrees, forced dead ends, zero-weight (unsampleable)
rows, a 150-degree hub that forces K=2 packing, and exponential edge
weights — against the host engine as ground truth. Everything here is
CPU-runnable (slab construction and packing are host numpy; the XLA
draw path runs on the virtual CPU mesh).
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

N = 300
AVG_DEG = 6
HUB = 5          # forced degree-150 node: slab wider than 1 register
DEAD_STRIDE = 17   # nid % 17 == 0 -> degree 0 (dead end)
ZEROW_STRIDE = 13  # nid % 13 == 0 (and not dead) -> all-zero weights

META = {
    "node_type_num": 2,
    "edge_type_num": 2,
    "node_uint64_feature_num": 0,
    "node_float_feature_num": 1,
    "node_binary_feature_num": 0,
    "edge_uint64_feature_num": 0,
    "edge_float_feature_num": 0,
    "edge_binary_feature_num": 0,
}


def _random_nodes(rng):
    nodes = []
    for nid in range(N):
        if nid % DEAD_STRIDE == 0:
            deg = 0
        elif nid == HUB:
            deg = 150
        else:
            deg = int(np.clip(rng.poisson(AVG_DEG), 1, 40))
        dsts = (
            rng.choice(N, size=deg, replace=False).astype(int)
            if deg else np.zeros(0, int)
        )
        if deg and nid % ZEROW_STRIDE == 0:
            ws = {int(d): 0.0 for d in dsts}
        else:
            ws = {
                int(d): float(rng.exponential() + 1e-3) for d in dsts
            }
        nodes.append({
            "node_id": nid,
            "node_type": nid % 2,
            "node_weight": float(rng.uniform(0.5, 2.0)),
            "neighbor": {
                "0": {str(d): w for d, w in ws.items()},
                "1": {},
            },
            "float_feature": {"0": [float(nid)]},
            "edge": [
                {
                    "src_id": nid, "dst_id": d, "edge_type": 0,
                    "weight": w,
                }
                for d, w in ws.items()
            ],
        })
    return nodes


@pytest.fixture(scope="module")
def graph(tmp_path_factory):
    import euler_tpu

    d = str(tmp_path_factory.mktemp("rand_graph"))
    euler_tpu.convert_dicts(
        _random_nodes(np.random.default_rng(11)), META,
        os.path.join(d, "part"), num_partitions=2,
    )
    return euler_tpu.Graph(directory=d)


@pytest.fixture(scope="module")
def adj(graph):
    from euler_tpu.graph import device

    return device.build_adjacency(graph, [0], N - 1)


def _host_rows(graph, ids):
    """{id: (nbr array, weight array)} over edge type 0 from the host
    engine (the ground truth the slabs must reproduce)."""
    nb, w, _, cnt = graph.get_full_neighbor(ids, [0])
    rows, off = {}, 0
    for i, c in zip(ids, cnt):
        c = int(c)
        rows[int(i)] = (nb[off:off + c], w[off:off + c])
        off += c
    return rows


def test_slab_rows_match_host_everywhere(graph, adj):
    ids = np.arange(N)
    rows = _host_rows(graph, ids)
    W = adj["nbr"].shape[1]
    assert W >= 150  # the hub widened the slab past one register
    default = adj["nbr"].shape[0] - 1  # max_id + 1, the padding node
    saw_unsampleable = 0
    for i in ids:
        nb, w = rows[int(i)]
        deg = int(adj["deg"][i])
        assert deg == min(len(nb), W)
        np.testing.assert_array_equal(adj["nbr"][i, :deg], nb[:deg])
        assert (adj["nbr"][i, deg:] == default).all()
        if len(nb) and w.sum() > 0:
            assert adj["sampleable"][i]
            exp = np.cumsum(w[:deg]) / w.sum()
            np.testing.assert_allclose(
                adj["cum"][i, :deg], np.minimum(exp, 1.0), atol=1e-5
            )
            assert adj["cum"][i, deg - 1] == 1.0
        elif len(nb):
            # zero-weight row: neighbors exist but sampling mass is zero
            assert not adj["sampleable"][i]
            saw_unsampleable += 1
    assert saw_unsampleable > 0  # the generator's ZEROW rows made it in


def test_dead_end_rows_draw_default(graph, adj):
    """Real degree-0 rows (nid % 17 == 0) and zero-weight rows must draw
    the default node through the XLA path."""
    from euler_tpu.graph import device

    deg = np.asarray(adj["deg"])[:N]
    ok = np.asarray(adj["sampleable"])[:N]
    targets = np.flatnonzero((deg == 0) | ~ok)
    assert len(targets) >= N // DEAD_STRIDE  # genuinely exercised
    default = adj["nbr"].shape[0] - 1
    out = np.asarray(
        device.sample_neighbor(
            {k: jax.numpy.asarray(v) for k, v in adj.items()},
            jax.numpy.asarray(targets[:64], jax.numpy.int32),
            jax.random.PRNGKey(0), 7,
        )
    )
    assert (out == default).all()


def test_draw_distribution_matches_weights(graph, adj):
    """Empirical XLA-path draw frequencies ≈ the host's NON-uniform
    normalized weights on random sampleable nodes + the hub (6-sigma
    bound, same discipline as the fixture tests)."""
    from euler_tpu.graph import device

    rng = np.random.default_rng(3)
    ok = np.flatnonzero(
        np.asarray(adj["sampleable"])[:N] & (np.asarray(adj["deg"])[:N] > 0)
    )
    picks = rng.choice(ok, size=min(10, len(ok)), replace=False)
    picks = np.unique(np.append(picks, HUB))
    rows = _host_rows(graph, picks)
    draws = 4000
    adj_j = {k: jax.numpy.asarray(v) for k, v in adj.items()}
    out = np.asarray(
        device.sample_neighbor(
            adj_j, jax.numpy.asarray(picks, jax.numpy.int32),
            jax.random.PRNGKey(5), draws,
        )
    )
    checked_nonuniform = False
    for r, i in enumerate(picks):
        nb, w = rows[int(i)]
        p = w / w.sum()
        if p.std() > 0.01:
            checked_nonuniform = True
        for n_, pi in zip(nb, p):
            freq = (out[r] == n_).mean()
            bound = 6 * np.sqrt(pi * (1 - pi) / draws) + 1e-3
            assert abs(freq - pi) < bound, (i, n_, freq, pi)
    assert checked_nonuniform  # exponential weights: not a uniform retest


def test_multi_hop_matches_host_on_random_graph(graph, adj):
    """The deterministic device full-neighbor expansion reproduces the
    host ops.get_multi_hop_neighbor exactly at irregular scale — same
    sorted unique node sets, same edge multisets — with dead ends and
    the 150-degree hub in play."""
    from euler_tpu import ops
    from euler_tpu.graph import device
    from tests.test_device_graph import _assert_hops_match_host

    roots = np.array([HUB, 1, 2, 35, 170], dtype=np.int64)
    # guard the tricky cases the roots claim to cover: a dead-end root
    # and the multi-register hub
    assert int(adj["deg"][170]) == 0 and 170 % DEAD_STRIDE == 0
    assert int(adj["deg"][HUB]) == 150
    caps = [256, 1024]
    h_roots, h_hops = ops.get_multi_hop_neighbor(
        graph, roots, [[0], [0]],
        max_nodes_per_hop=caps, max_edges_per_hop=[4096, 65536],
        default_node=N,
    )
    d_hops = device.multi_hop_neighbor([adj, adj], roots, caps)
    _assert_hops_match_host(h_hops, d_hops, roots)


def test_typed_negatives_distribution_at_scale(graph):
    """sample_node_with_src draws each source's negatives from ITS node
    type's weighted global sampler; at 300 nodes with non-uniform node
    weights the per-type marginals must match the host-side weights."""
    from euler_tpu.graph import device

    ts = device.build_typed_node_sampler(graph, 2, N - 1)
    src = np.asarray([4, 7], dtype=np.int64)  # one even-, one odd-type id
    types = np.asarray(ts["types"])[src]
    assert types[0] != types[1]
    draws = 30000
    out = np.asarray(
        device.sample_node_with_src(
            ts, jax.numpy.asarray(src, jax.numpy.int32),
            jax.random.PRNGKey(2), draws,
        )
    )
    ids_all = np.asarray(ts["ids"])
    cum_all = np.asarray(ts["cum"])
    off = np.asarray(ts["off"])
    for r in range(len(src)):
        t = int(types[r])
        seg = slice(int(off[t]), int(off[t + 1]))
        ids_t, cum_t = ids_all[seg], cum_all[seg]
        probs = np.diff(cum_t, prepend=0.0)
        # negatives stay within the source's type segment
        assert set(out[r].tolist()) <= set(ids_t.tolist())
        # spot-check the heaviest ten marginals
        top = np.argsort(probs)[::-1][:10]
        for j in top:
            freq = (out[r] == ids_t[j]).mean()
            bound = 6 * np.sqrt(probs[j] * (1 - probs[j]) / draws) + 1e-3
            assert abs(freq - probs[j]) < bound, (r, ids_t[j])


@pytest.mark.parametrize("pq", [(4.0, 0.25), (0.25, 4.0)])
def test_biased_walk_analytic_on_random_graph(graph, pq):
    """The node2vec-biased device walk reproduces the analytic
    d_tx-reweighted 2-step joint on the RANDOM graph — exercising the
    sorted-slab binary-search membership test at irregular degrees and
    non-uniform weights (the fixture version of this test covers only
    7 nodes)."""
    from euler_tpu.graph import device
    from tests.test_device_graph import _analytic_biased_joint

    p, q = pq
    adj = device.build_adjacency(graph, [0], N - 1, sorted=True)
    deg = np.asarray(adj["deg"])
    ok = np.asarray(adj["sampleable"])
    nbr = np.asarray(adj["nbr"])

    # the analytic model assumes every step-1 candidate has a live row:
    # pick a mid-degree root whose neighbors are all sampleable
    root = None
    for v in range(N):
        if not (ok[v] and 3 <= deg[v] <= 12):
            continue
        c1s = nbr[v][: deg[v]]
        if all(ok[c] and deg[c] > 0 for c in c1s):
            root = v
            break
    assert root is not None, "random graph lacks a clean root (reseed)"

    n = 40000
    walks = np.asarray(
        device.biased_random_walk(
            adj, np.full(n, root), jax.random.PRNGKey(9), 2, p, q
        )
    )
    assert (walks[:, 0] == root).all()
    expected = _analytic_biased_joint(adj, root, p, q)
    pairs, counts = np.unique(walks[:, 1:], axis=0, return_counts=True)
    seen = {
        (int(a), int(b)): c / n for (a, b), c in zip(pairs, counts)
    }
    assert set(seen) <= set(expected), set(seen) - set(expected)
    for pair, prob in expected.items():
        bound = 6 * np.sqrt(prob * (1 - prob) / n) + 1e-3
        assert abs(seen.get(pair, 0.0) - prob) < bound, (pair, prob)


def test_packed_layout_matches_slabs(adj):
    """pack_adjacency invariants at irregular degrees with K=2 (the hub
    forces a 2-register slab): real lanes mirror nbr/cum, unsampleable
    rows bake the default fill, pad lanes are (default id, cum 1.0)."""
    from euler_tpu.graph import pallas_sampling as ps

    packed = ps.pack_adjacency(adj)
    assert packed is not None
    n, w = adj["nbr"].shape
    k = packed.shape[0] // (2 * n)
    assert k == 2  # the hub pushed W past one 128-lane register
    blk = packed.reshape(n, 2 * k, ps.LANES)
    nbr_lanes = blk[:, :k].reshape(n, k * ps.LANES)
    cum_lanes = blk[:, k:].reshape(n, k * ps.LANES).view(np.float32)
    ok = np.asarray(adj["sampleable"]).astype(bool)
    assert not ok.all()  # unsampleable baking genuinely exercised
    exp_nbr = np.where(ok[:, None], adj["nbr"], n - 1)
    np.testing.assert_array_equal(nbr_lanes[:, :w], exp_nbr)
    np.testing.assert_array_equal(cum_lanes[:, :w], adj["cum"])
    assert (nbr_lanes[:, w:] == n - 1).all()
    assert (cum_lanes[:, w:] == 1.0).all()


def test_two_level_root_sampler_distribution_at_scale(graph, monkeypatch):
    """Random-graph analog of the fixture-level multi-segment test:
    non-uniform node weights, SEG shrunk to 16 so the 300-node sampler
    spans ~19 segments — the two-level draw (segment pick x in-segment
    bisect) must reproduce every node's weight share."""
    from euler_tpu.graph import device

    monkeypatch.setattr(device, "SEG", 16)
    s = device.build_node_sampler(graph, -1, N - 1)
    assert s["seg_cum"].shape[0] > 10
    draws = np.asarray(
        device.sample_node(s, jax.random.PRNGKey(3), 60000)
    )
    ids = np.arange(N)
    w = graph.node_weights(ids)
    probs = w / w.sum()
    for i in ids[w > 0]:
        p = probs[i]
        assert (
            abs((draws == i).mean() - p)
            < 6 * np.sqrt(p * (1 - p) / 60000) + 1e-3
        ), i
