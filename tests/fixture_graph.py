"""Tiny deterministic heterogeneous fixture graph shared by all tests.

Same role as the reference's 6-node test graph
(reference tf_euler/python/euler_ops/testdata/graph.json + base_test.py:36-53):
every op test loads this via the converter + native engine.

7 nodes (ids 10..16), 2 node types, 2 edge types, dense/sparse/binary
features on nodes and edges.
"""

import os

import euler_tpu

FIXTURE_META = {
    "node_type_num": 2,
    "edge_type_num": 2,
    "node_uint64_feature_num": 2,
    "node_float_feature_num": 3,
    "node_binary_feature_num": 1,
    "edge_uint64_feature_num": 1,
    "edge_float_feature_num": 1,
    "edge_binary_feature_num": 1,
}

# node id -> (type, weight, {edge_type: {dst: weight}})
TOPOLOGY = {
    10: (0, 1.0, {0: {11: 1.0, 12: 3.0}, 1: {13: 2.0}}),
    11: (1, 2.0, {0: {12: 2.0}}),
    12: (0, 3.0, {1: {13: 1.0, 14: 4.0}}),
    13: (1, 4.0, {0: {10: 1.0}}),
    14: (0, 5.0, {0: {15: 2.0}, 1: {11: 1.0}}),
    15: (1, 6.0, {}),
    16: (0, 1.0, {0: {10: 2.0, 11: 1.0, 12: 1.0}, 1: {13: 1.0, 15: 2.0}}),
}


def dense_f0(nid):
    return [nid * 0.5, nid * 0.25]


def fixture_nodes():
    nodes = []
    for nid, (ntype, w, nbrs) in TOPOLOGY.items():
        edges = []
        for t, group in nbrs.items():
            for dst, ew in group.items():
                edges.append(
                    {
                        "src_id": nid,
                        "dst_id": dst,
                        "edge_type": t,
                        "weight": ew,
                        "uint64_feature": {"0": [nid * 100 + dst]},
                        "float_feature": {"0": [ew * 0.1]},
                        "binary_feature": {"0": "e%d-%d" % (nid, dst)},
                    }
                )
        nodes.append(
            {
                "node_id": nid,
                "node_type": ntype,
                "node_weight": w,
                "neighbor": {
                    str(t): {str(d): w2 for d, w2 in g.items()}
                    for t, g in nbrs.items()
                },
                "uint64_feature": {"0": [nid, nid + 1], "1": [7]},
                "float_feature": {
                    "0": dense_f0(nid),
                    "1": [1.0, 2.0, 3.0],
                    # slot 2: a 3-class multi-hot label (nid mod 3 one-hot,
                    # plus class 2 for even ids) for supervised-model tests
                    "2": [
                        1.0 if nid % 3 == 0 else 0.0,
                        1.0 if nid % 3 == 1 else 0.0,
                        1.0 if nid % 2 == 0 else 0.0,
                    ],
                },
                "binary_feature": {"0": "n%d" % nid},
                "edge": edges,
            }
        )
    return nodes


def write_fixture(directory, num_partitions=2):
    return euler_tpu.convert_dicts(
        fixture_nodes(),
        FIXTURE_META,
        os.path.join(directory, "part"),
        num_partitions=num_partitions,
    )
