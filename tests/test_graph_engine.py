"""Native graph engine tests.

Mirrors the reference's op-level test strategy
(reference tf_euler/python/euler_ops/*_test.py: exact assertions on
neighbors/features, distributional assertions on samplers) plus the
C++ weighted-collection distribution tests
(reference euler/common/compact_weighted_collection_test.cc).
"""

import numpy as np

from tests.fixture_graph import TOPOLOGY, dense_f0


def test_counts(graph):
    assert graph.num_nodes == 7
    assert graph.num_edges == sum(
        len(g) for _, _, nbrs in TOPOLOGY.values() for g in nbrs.values()
    )
    assert graph.node_type_num == 2
    assert graph.edge_type_num == 2
    assert graph.feature_num(0) == 2  # node u64
    assert graph.feature_num(1) == 3  # node f32
    assert graph.feature_num(2) == 1  # node binary
    assert graph.feature_num(4) == 1  # edge f32


def test_node_types(graph):
    types = graph.node_types([10, 11, 12, 13, 14, 15, 16, 999])
    np.testing.assert_array_equal(types, [0, 1, 0, 1, 0, 1, 0, -1])


def test_type_weight_sums(graph):
    wsum = graph.type_weight_sums()
    # type 0: nodes 10,12,14,16 -> 1+3+5+1; type 1: 11,13,15 -> 2+4+6
    np.testing.assert_allclose(wsum, [10.0, 12.0])


def test_full_neighbor_sorted_merge(graph):
    nbr, w, t, counts = graph.get_full_neighbor([10, 15, 16], [0, 1], sorted=True)
    np.testing.assert_array_equal(counts, [3, 0, 5])
    # node 10 over both types merged by id: 11(w1,t0), 12(w3,t0), 13(w2,t1)
    np.testing.assert_array_equal(nbr[:3], [11, 12, 13])
    np.testing.assert_allclose(w[:3], [1.0, 3.0, 2.0])
    np.testing.assert_array_equal(t[:3], [0, 0, 1])
    # node 16: 10,11,12 (t0) and 13,15 (t1), merged ascending
    np.testing.assert_array_equal(nbr[3:], [10, 11, 12, 13, 15])
    np.testing.assert_array_equal(t[3:], [0, 0, 0, 1, 1])


def test_full_neighbor_type_filter(graph):
    nbr, w, t, counts = graph.get_full_neighbor([10], [1])
    np.testing.assert_array_equal(counts, [1])
    np.testing.assert_array_equal(nbr, [13])
    np.testing.assert_array_equal(t, [1])


def test_sample_neighbor_distribution(graph):
    n = 20000
    ids, w, t = graph.sample_neighbor([10] * n, [0], 1)
    ids = ids.reshape(-1)
    counts = {v: int((ids == v).sum()) for v in (11, 12)}
    assert counts[11] + counts[12] == n
    # weights 1:3
    assert abs(counts[12] / n - 0.75) < 0.02
    # weights returned match the sampled edge
    w = w.reshape(-1)
    assert set(np.unique(w[ids == 11])) == {1.0}
    assert set(np.unique(w[ids == 12])) == {3.0}


def test_sample_neighbor_multi_type_distribution(graph):
    n = 30000
    ids, _, t = graph.sample_neighbor([10] * n, [0, 1], 1)
    ids = ids.reshape(-1)
    # distribution over union: 11:1, 12:3, 13:2 (total 6)
    for v, p in ((11, 1 / 6), (12, 3 / 6), (13, 2 / 6)):
        assert abs((ids == v).mean() - p) < 0.02


def test_sample_neighbor_default_fill(graph):
    ids, w, t = graph.sample_neighbor([15, 999], [0, 1], 3, default_node=-1)
    np.testing.assert_array_equal(ids, -np.ones((2, 3), dtype=np.int64))
    np.testing.assert_array_equal(w, np.zeros((2, 3), dtype=np.float32))
    np.testing.assert_array_equal(t, -np.ones((2, 3), dtype=np.int32))


def test_sample_node_distribution(graph):
    n = 30000
    ids = graph.sample_node(n, 0)
    types = graph.node_types(ids)
    assert set(np.unique(types)) == {0}
    # weight-proportional within type 0: 10:1,12:3,14:5,16:1 of 10
    for v, p in ((10, 0.1), (12, 0.3), (14, 0.5), (16, 0.1)):
        assert abs((ids == v).mean() - p) < 0.02
    # global: type proportions 10:12
    ids = graph.sample_node(n, -1)
    types = graph.node_types(ids)
    assert abs((types == 0).mean() - 10 / 22) < 0.02


def test_sample_edge(graph):
    src, dst, t = graph.sample_edge(1000, 1)
    assert set(np.unique(t)) == {1}
    # all sampled edges exist in type-1 topology
    for s, d in zip(src[:50], dst[:50]):
        assert d in TOPOLOGY[s][2].get(1, {})


def test_sample_node_with_src_types(graph):
    negs = graph.sample_node_with_src([10, 11], 8)
    assert negs.shape == (2, 8)
    assert set(np.unique(graph.node_types(negs[0]))) == {0}
    assert set(np.unique(graph.node_types(negs[1]))) == {1}


def test_top_k_neighbor(graph):
    ids, w, t = graph.get_top_k_neighbor([16, 15], [0, 1], 3, default_node=-1)
    # node 16 weights: 10:2, 11:1, 12:1, 13:1, 15:2 -> top3 = {10,15} + one of the 1s
    assert ids[0, 0] in (10, 15) and ids[0, 1] in (10, 15)
    np.testing.assert_allclose(w[0, :2], [2.0, 2.0])
    assert w[0, 2] == 1.0
    # node 15 has no neighbors: all defaults
    np.testing.assert_array_equal(ids[1], [-1, -1, -1])
    np.testing.assert_array_equal(t[1], [-1, -1, -1])


def test_dense_feature(graph):
    f = graph.get_dense_feature([10, 14], [0, 1], [2, 3])
    np.testing.assert_allclose(f[0], dense_f0(10) + [1.0, 2.0, 3.0])
    np.testing.assert_allclose(f[1], dense_f0(14) + [1.0, 2.0, 3.0])
    # missing node -> zeros; short feature -> zero pad
    f = graph.get_dense_feature([999, 10], [0], [4])
    np.testing.assert_allclose(f[0], [0, 0, 0, 0])
    np.testing.assert_allclose(f[1], dense_f0(10) + [0, 0])


def test_sparse_feature(graph):
    out = graph.get_sparse_feature([10, 11, 999], [0, 1])
    vals0, counts0 = out[0]
    np.testing.assert_array_equal(counts0, [2, 2, 0])
    np.testing.assert_array_equal(vals0, [10, 11, 11, 12])
    vals1, counts1 = out[1]
    np.testing.assert_array_equal(counts1, [1, 1, 0])
    np.testing.assert_array_equal(vals1, [7, 7])


def test_binary_feature(graph):
    (rows,) = graph.get_binary_feature([10, 15, 999], [0])
    assert rows == [b"n10", b"n15", b""]


def test_edge_features(graph):
    f = graph.get_edge_dense_feature([10, 12], [12, 14], [0, 1], [0], [1])
    np.testing.assert_allclose(f, [[0.3], [0.4]], atol=1e-6)
    out = graph.get_edge_sparse_feature([10], [12], [0], [0])
    vals, counts = out[0]
    np.testing.assert_array_equal(vals, [1012])
    np.testing.assert_array_equal(counts, [1])
    (rows,) = graph.get_edge_binary_feature([10, 999], [12, 1], [0, 0], [0])
    assert rows == [b"e10-12", b""]


def test_random_walk_validity(graph):
    walks = graph.random_walk([10, 16], [0, 1], 5)
    assert walks.shape == (2, 6)
    for row in walks:
        for a, b in zip(row[:-1], row[1:]):
            if b == -1:
                continue  # dead end fill
            nbrs = TOPOLOGY[a][2]
            assert any(b in g for g in nbrs.values())
    # isolated node walks straight to defaults
    walks = graph.random_walk([15], [0, 1], 3)
    np.testing.assert_array_equal(walks[0], [15, -1, -1, -1])


def test_random_walk_biased(graph):
    # Large p suppresses returning to the parent: from 13 the only neighbor
    # is 10; from 10 with parent 13, neighbors are 11,12,13 — with p=1e6 the
    # walk should essentially never step back to 13.
    walks = graph.random_walk([13] * 2000, [0, 1], 2, p=1e6, q=1.0)
    assert (walks[:, 1] == 10).all()
    back = (walks[:, 2] == 13).mean()
    assert back < 0.01
    # and with tiny p it should almost always return
    walks = graph.random_walk([13] * 2000, [0, 1], 2, p=1e-6, q=1.0)
    assert (walks[:, 2] == 13).mean() > 0.99


def test_sample_fanout(graph):
    ids, ws, ts = graph.sample_fanout([10, 16], [[0], [0, 1]], [2, 3])
    assert [a.shape for a in ids] == [(2,), (4,), (12,)]
    # hop-1 samples are type-0 neighbors of the roots
    for root, picks in ((10, ids[1][:2]), (16, ids[1][2:])):
        for v in picks:
            assert v in TOPOLOGY[root][2].get(0, {})
    # hop-2 samples are neighbors (any type) of hop-1 nodes, or default fill
    for i, parent in enumerate(ids[1]):
        for v in ids[2][i * 3 : (i + 1) * 3]:
            if v == -1:
                continue
            assert any(v in g for g in TOPOLOGY[parent][2].values())


def test_shard_loading(fixture_dir):
    import euler_tpu

    g0 = euler_tpu.Graph(directory=fixture_dir, shard_idx=0, shard_num=2)
    g1 = euler_tpu.Graph(directory=fixture_dir, shard_idx=1, shard_num=2)
    assert g0.num_nodes + g1.num_nodes == 7
    ids0 = set(int(i) for i in g0.sample_node(1000, -1))
    ids1 = set(int(i) for i in g1.sample_node(1000, -1))
    assert ids0.isdisjoint(ids1)
    g0.close()
    g1.close()


def test_alias_sampling_uniformity(graph):
    # Regression guard on the alias table itself: global type-1 node sampling
    # matches node weights 11:2, 13:4, 15:6.
    ids = graph.sample_node(30000, 1)
    for v, p in ((11, 2 / 12), (13, 4 / 12), (15, 6 / 12)):
        assert abs((ids == v).mean() - p) < 0.02


def test_corrupt_dat_never_crashes(tmp_path):
    """Malformed graph data must raise a clean RuntimeError from the
    native loader — never crash the process. Sweeps EVERY single-byte
    flip of the fixture .dat plus truncations, in a subprocess so a
    segfault fails the test instead of killing the runner. (The block
    framing check mirrors the reference loader, reference
    euler/core/graph_builder.cc:211-222; payload bytes that pass framing
    may legitimately load as different-but-well-formed data.) An EMPTY
    .dat stays loadable: a partition can hold zero blocks."""
    import subprocess
    import sys
    import textwrap

    child = textwrap.dedent(
        """
        import os, sys, tempfile
        import euler_tpu
        from tests.fixture_graph import write_fixture

        base = tempfile.mkdtemp()
        write_fixture(base, num_partitions=1)
        dats = [f for f in os.listdir(base) if f.endswith(".dat")]
        assert len(dats) == 1, dats
        path = os.path.join(base, dats[0])
        orig = open(path, "rb").read()

        def attempt(data, label):
            with open(path, "wb") as f:
                f.write(data)
            print("attempt", label, flush=True)  # last line names a crash
            try:
                g = euler_tpu.Graph(directory=base)
                g.close()
                return "loaded"
            except RuntimeError:
                return "rejected"

        rejected = loaded = 0
        for i in range(len(orig)):
            data = bytearray(orig); data[i] ^= 0xFF
            r = attempt(bytes(data), f"flip@{i}")
            rejected += r == "rejected"; loaded += r == "loaded"
        # adversarial count fields: overwrite random aligned int32s with
        # the values that historically crashed loaders (negative counts,
        # INT_MAX) — single-byte flips cannot produce e.g. exactly -1
        import random
        import struct

        rng = random.Random(7)
        for trial in range(400):
            off = rng.randrange(0, len(orig) - 4) & ~3
            val = rng.choice([-1, -2, 2**31 - 1, -(2**31), 2**20 + 1])
            data = bytearray(orig)
            data[off:off + 4] = struct.pack("<i", val)
            attempt(bytes(data), f"int32@{off}={val}")
        for n in (0, 1, 7, len(orig) // 3, len(orig) - 1):
            attempt(orig[:n], f"trunc@{n}")
        assert attempt(b"", "empty") == "loaded"  # zero-block partition
        # framing/structural bytes must reject; payload bytes (feature
        # values, weights, ids) legally load as different-but-well-formed
        # data — the property under test is only "load or raise"
        assert rejected > 100 and loaded > 0, (rejected, loaded)
        print(f"SWEPT {len(orig)} flips: rejected={rejected} "
              f"loaded={loaded}")
        """
    )
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    r = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True,
        timeout=240, env=env,
    )
    assert r.returncode == 0, (
        f"loader crashed (rc={r.returncode}) at: "
        f"{r.stdout.strip().splitlines()[-1:]}\n{r.stderr[-1500:]}"
    )
    assert "SWEPT" in r.stdout


def test_corrupt_bytes_streamed_never_crash(tmp_path):
    """The streamed ingest (eg_load_buffers) must reject malformed
    bytes as cleanly as the file loader: byte flips (strided — the
    parser is shared with the file path, which sweeps every offset),
    the historical crash-class int32 overwrites, truncations, and the
    empty buffer, in a crash-isolated subprocess."""
    import subprocess
    import sys
    import textwrap

    child = textwrap.dedent(
        """
        import os, random, struct, sys, tempfile
        import euler_tpu
        from tests.fixture_graph import write_fixture

        base = tempfile.mkdtemp()
        write_fixture(base, num_partitions=1)
        dats = [f for f in os.listdir(base) if f.endswith(".dat")]
        path = os.path.join(base, dats[0])
        orig = open(path, "rb").read()

        def attempt(data, label):
            with open(path, "wb") as f:
                f.write(data)
            print("attempt", label, flush=True)  # last line names a crash
            try:
                g = euler_tpu.Graph(files=[path], stream=True)
                g.close()
                return "loaded"
            except RuntimeError:
                return "rejected"

        rejected = loaded = 0
        for i in range(0, len(orig), 3):
            data = bytearray(orig); data[i] ^= 0xFF
            r = attempt(bytes(data), f"flip@{i}")
            rejected += r == "rejected"; loaded += r == "loaded"
        rng = random.Random(11)
        for trial in range(200):
            off = rng.randrange(0, len(orig) - 4) & ~3
            val = rng.choice([-1, -2, 2**31 - 1, -(2**31), 2**20 + 1])
            data = bytearray(orig)
            data[off:off + 4] = struct.pack("<i", val)
            attempt(bytes(data), f"int32@{off}={val}")
        for n in (0, 1, 7, len(orig) // 3, len(orig) - 1):
            attempt(orig[:n], f"trunc@{n}")
        assert attempt(b"", "empty") == "loaded"
        assert rejected > 30 and loaded > 0, (rejected, loaded)
        print(f"SWEPT streamed: rejected={rejected} loaded={loaded}")
        """
    )
    import os

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    r = subprocess.run(
        [sys.executable, "-c", child], capture_output=True, text=True,
        timeout=240, env=env,
    )
    assert r.returncode == 0, (
        f"streamed loader crashed (rc={r.returncode}) at: "
        f"{r.stdout.strip().splitlines()[-1:]}\n{r.stderr[-1500:]}"
    )
    assert "SWEPT" in r.stdout
