"""Checkpoint / resume tests (reference behavior: MonitoredTrainingSession
checkpoint_dir, run_loop.py:132-138 — training resumes from the latest
checkpoint and produces identical state structure)."""

import numpy as np
import pytest


@pytest.fixture()
def model():
    from euler_tpu.models import SupervisedGraphSage

    return SupervisedGraphSage(
        label_idx=2,
        label_dim=3,
        metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2],
        dim=8,
        feature_idx=0,
        feature_dim=2,
        max_id=16,
    )


def _source(graph, batch=8):
    def fn(step):
        return np.asarray(graph.sample_node(batch, -1))

    return fn


def test_save_and_resume(model, graph, tmp_path):
    from euler_tpu.train import train

    ckpt_dir = str(tmp_path / "ckpt")
    state1, _ = train(
        model,
        graph,
        _source(graph),
        num_steps=6,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=3,
        log_every=100,
    )

    from euler_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(ckpt_dir)
    assert ckpt.latest_step() == 6

    # Resuming with the same dir continues from step 6: only 4 more steps
    # run even though num_steps=10.
    calls = []

    def counting_source(step):
        calls.append(step)
        return np.asarray(graph.sample_node(8, -1))

    state2, _ = train(
        model,
        graph,
        counting_source,
        num_steps=10,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=100,
        log_every=100,
    )
    # init_state probes source_fn(0) once; the loop then runs steps 6..9
    # (prefetch workers may call out of order).
    assert sorted(c for c in calls if c >= 6) == [6, 7, 8, 9]
    assert Checkpointer(ckpt_dir).latest_step() == 10


def test_restore_matches_saved(model, graph, tmp_path):
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(5, state, force=True)
    ckpt.wait()
    restored = Checkpointer(str(tmp_path / "c")).restore(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        state,
        restored,
    )


def test_consts_excluded_from_checkpoint(graph, tmp_path):
    """Device-resident graph tables must not be serialized; restore carries
    them over from the live state and works across the device_features
    flag (saved trees are identical either way)."""
    import jax
    import numpy as np
    import optax
    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    m = SupervisedGraphSage(**kw, device_features=True)
    opt = optax.adam(0.01)
    roots = np.array([10, 12, 14, 16], dtype=np.int64)
    state = m.init_state(jax.random.PRNGKey(0), graph, roots, opt)
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(1, state)
    ckpt.wait()
    restored = ckpt.restore(state, 1)
    assert set(restored) == set(state)
    np.testing.assert_array_equal(
        np.asarray(restored["consts"]["features"]),
        np.asarray(state["consts"]["features"]),
    )
    # a host-path model (no consts) can restore from the same checkpoint
    m2 = SupervisedGraphSage(**kw)
    state2 = m2.init_state(jax.random.PRNGKey(1), graph, roots, opt)
    restored2 = ckpt.restore(state2, 1)
    assert "consts" not in restored2
    ckpt.close()

# ---- restore hardening (loud failures instead of orbax tracebacks) ----


def test_restore_empty_dir_raises_actionable(model, graph, tmp_path):
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    empty = str(tmp_path / "never_trained")
    with pytest.raises(ValueError, match="no checkpoint in .*never_trained"):
        Checkpointer(empty).restore(state)
    # the message tells the operator what to do, not just what broke
    with pytest.raises(ValueError, match="--model_dir"):
        Checkpointer(empty).restore(state)


def test_restore_missing_step_lists_available(model, graph, tmp_path):
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(5, state, force=True)
    ckpt.wait()
    with pytest.raises(
        ValueError, match=r"no checkpoint for step 7 .*available steps: \[5\]"
    ):
        ckpt.restore(state, step=7)
    ckpt.close()


def test_restore_structure_mismatch_raises_actionable(model, graph, tmp_path):
    """A checkpoint saved under one model/optimizer config must fail a
    mismatched restore with a message naming both ends of the contract,
    not an orbax stack trace."""
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(3, state, force=True)
    ckpt.wait()
    # a different architecture -> different param tree (orbax silently
    # pads/truncates same-tree shape drift, so the loud path is keyed on
    # tree structure, which is what a wrong --model_dir actually hits)
    other = _parity_model("gcn")
    state_gcn = other.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    with pytest.raises(
        ValueError, match="does not match the provided state_like structure"
    ):
        ckpt.restore(state_gcn, step=3)
    ckpt.close()


# ---- checkpoint -> forward parity in a fresh process ----

# Child re-creates the graph and model from scratch (different PRNG key on
# purpose: restore must overwrite everything that matters), restores the
# checkpoint, and embeds the same seeded batch. Constructor kwargs are
# duplicated in _parity_model below — keep the two in sync.
_PARITY_CHILD = """
import sys
import numpy as np
import jax
import euler_tpu
from euler_tpu.graph import native
from euler_tpu.checkpoint import Checkpointer
from euler_tpu.models import SupervisedGCN, SupervisedGraphSage
from euler_tpu.train import get_optimizer

fixture_dir, ckpt_dir, kind, out = sys.argv[1:5]
graph = euler_tpu.Graph(directory=fixture_dir)
if kind == "graphsage":
    model = SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]], fanouts=[3, 2],
        dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
else:
    model = SupervisedGCN(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]], dim=8,
        max_nodes_per_hop=[16, 16], max_edges_per_hop=[64, 64],
        feature_idx=0, feature_dim=2, max_id=16, use_id=True,
    )
ids = np.arange(8, dtype=np.int64)
state = model.init_state(
    jax.random.PRNGKey(99), graph, ids, get_optimizer("adam", 0.01)
)
state = Checkpointer(ckpt_dir).restore(state)
native.lib().eg_seed(555)
blocks = model.sample_embed(graph, ids)
rows = jax.jit(model.make_embed_step())(state, blocks)
np.save(out, np.asarray(jax.block_until_ready(rows)))
"""


def _parity_model(kind):
    from euler_tpu.models import SupervisedGCN, SupervisedGraphSage

    if kind == "graphsage":
        return SupervisedGraphSage(
            label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
            fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
        )
    return SupervisedGCN(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]], dim=8,
        max_nodes_per_hop=[16, 16], max_edges_per_hop=[64, 64],
        feature_idx=0, feature_dim=2, max_id=16, use_id=True,
    )


@pytest.mark.parametrize("kind", ["graphsage", "gcn"])
def test_fresh_process_restore_forward_parity(kind, graph, fixture_dir,
                                              tmp_path):
    """Params saved at step N and restored in a FRESH process must produce
    bit-identical embeddings to the in-memory state — the serving
    contract (serve.py loads checkpoints it never trained)."""
    import os
    import subprocess
    import sys

    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.graph import native
    from euler_tpu.train import get_optimizer

    model = _parity_model(kind)
    ids = np.arange(8, dtype=np.int64)
    state = model.init_state(
        jax.random.PRNGKey(7), graph, ids, get_optimizer("adam", 0.01)
    )
    ckpt_dir = str(tmp_path / "ck")
    ckpt = Checkpointer(ckpt_dir)
    ckpt.save(2, state, force=True)
    ckpt.wait()
    ckpt.close()

    # in-memory reference: same seeded sample, same jitted program shape
    native.lib().eg_seed(555)
    blocks = model.sample_embed(graph, ids)
    want = np.asarray(
        jax.block_until_ready(jax.jit(model.make_embed_step())(state, blocks))
    )

    out = str(tmp_path / "child.npy")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _PARITY_CHILD, fixture_dir, ckpt_dir, kind,
         out],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert proc.returncode == 0, (
        f"child failed:\n{proc.stdout}\n{proc.stderr}"
    )
    got = np.load(out)
    assert got.dtype == want.dtype and got.shape == want.shape
    np.testing.assert_array_equal(got, want)  # bit-identical, not allclose
