"""Checkpoint / resume tests (reference behavior: MonitoredTrainingSession
checkpoint_dir, run_loop.py:132-138 — training resumes from the latest
checkpoint and produces identical state structure)."""

import numpy as np
import pytest


@pytest.fixture()
def model():
    from euler_tpu.models import SupervisedGraphSage

    return SupervisedGraphSage(
        label_idx=2,
        label_dim=3,
        metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2],
        dim=8,
        feature_idx=0,
        feature_dim=2,
        max_id=16,
    )


def _source(graph, batch=8):
    def fn(step):
        return np.asarray(graph.sample_node(batch, -1))

    return fn


def test_save_and_resume(model, graph, tmp_path):
    from euler_tpu.train import train

    ckpt_dir = str(tmp_path / "ckpt")
    state1, _ = train(
        model,
        graph,
        _source(graph),
        num_steps=6,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=3,
        log_every=100,
    )

    from euler_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(ckpt_dir)
    assert ckpt.latest_step() == 6

    # Resuming with the same dir continues from step 6: only 4 more steps
    # run even though num_steps=10.
    calls = []

    def counting_source(step):
        calls.append(step)
        return np.asarray(graph.sample_node(8, -1))

    state2, _ = train(
        model,
        graph,
        counting_source,
        num_steps=10,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=100,
        log_every=100,
    )
    # init_state probes source_fn(0) once; the loop then runs steps 6..9.
    assert [c for c in calls if c >= 6] == [6, 7, 8, 9]
    assert Checkpointer(ckpt_dir).latest_step() == 10


def test_restore_matches_saved(model, graph, tmp_path):
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(5, state, force=True)
    ckpt.wait()
    restored = Checkpointer(str(tmp_path / "c")).restore(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        state,
        restored,
    )
