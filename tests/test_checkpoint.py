"""Checkpoint / resume tests (reference behavior: MonitoredTrainingSession
checkpoint_dir, run_loop.py:132-138 — training resumes from the latest
checkpoint and produces identical state structure)."""

import numpy as np
import pytest


@pytest.fixture()
def model():
    from euler_tpu.models import SupervisedGraphSage

    return SupervisedGraphSage(
        label_idx=2,
        label_dim=3,
        metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2],
        dim=8,
        feature_idx=0,
        feature_dim=2,
        max_id=16,
    )


def _source(graph, batch=8):
    def fn(step):
        return np.asarray(graph.sample_node(batch, -1))

    return fn


def test_save_and_resume(model, graph, tmp_path):
    from euler_tpu.train import train

    ckpt_dir = str(tmp_path / "ckpt")
    state1, _ = train(
        model,
        graph,
        _source(graph),
        num_steps=6,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=3,
        log_every=100,
    )

    from euler_tpu.checkpoint import Checkpointer

    ckpt = Checkpointer(ckpt_dir)
    assert ckpt.latest_step() == 6

    # Resuming with the same dir continues from step 6: only 4 more steps
    # run even though num_steps=10.
    calls = []

    def counting_source(step):
        calls.append(step)
        return np.asarray(graph.sample_node(8, -1))

    state2, _ = train(
        model,
        graph,
        counting_source,
        num_steps=10,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=100,
        log_every=100,
    )
    # init_state probes source_fn(0) once; the loop then runs steps 6..9
    # (prefetch workers may call out of order).
    assert sorted(c for c in calls if c >= 6) == [6, 7, 8, 9]
    assert Checkpointer(ckpt_dir).latest_step() == 10


def test_restore_matches_saved(model, graph, tmp_path):
    import jax

    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.train import get_optimizer

    state = model.init_state(
        jax.random.PRNGKey(0), graph, np.arange(8), get_optimizer("adam", 0.01)
    )
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(5, state, force=True)
    ckpt.wait()
    restored = Checkpointer(str(tmp_path / "c")).restore(state)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        state,
        restored,
    )


def test_consts_excluded_from_checkpoint(graph, tmp_path):
    """Device-resident graph tables must not be serialized; restore carries
    them over from the live state and works across the device_features
    flag (saved trees are identical either way)."""
    import jax
    import numpy as np
    import optax
    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.models import SupervisedGraphSage

    kw = dict(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )
    m = SupervisedGraphSage(**kw, device_features=True)
    opt = optax.adam(0.01)
    roots = np.array([10, 12, 14, 16], dtype=np.int64)
    state = m.init_state(jax.random.PRNGKey(0), graph, roots, opt)
    ckpt = Checkpointer(str(tmp_path / "ck"))
    ckpt.save(1, state)
    ckpt.wait()
    restored = ckpt.restore(state, 1)
    assert set(restored) == set(state)
    np.testing.assert_array_equal(
        np.asarray(restored["consts"]["features"]),
        np.asarray(state["consts"]["features"]),
    )
    # a host-path model (no consts) can restore from the same checkpoint
    m2 = SupervisedGraphSage(**kw)
    state2 = m2.init_state(jax.random.PRNGKey(1), graph, roots, opt)
    restored2 = ckpt.restore(state2, 1)
    assert "consts" not in restored2
    ckpt.close()
