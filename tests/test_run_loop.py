"""CLI driver tests (reference run_loop.py modes + model dispatch)."""

import os

import numpy as np
import pytest

from euler_tpu.run_loop import build_model, define_flags, main

COMMON = [
    "--max_id", "16", "--feature_idx", "0", "--feature_dim", "2",
    "--label_idx", "2", "--label_dim", "3", "--train_edge_type", "0,1",
    "--all_edge_type", "0,1", "--fanouts", "3,2", "--dim", "8",
    "--batch_size", "8", "--num_epochs", "4", "--log_steps", "2",
]


def _args(fixture_dir, model_dir, *extra):
    return ["--data_dir", fixture_dir, "--model_dir", model_dir] + COMMON + \
        list(extra)


def test_train_eval_save_cycle(fixture_dir, tmp_path):
    ck = str(tmp_path / "ck")
    assert main(_args(fixture_dir, ck, "--model", "graphsage_supervised",
                      "--mode", "train")) == 0
    assert os.path.isdir(ck)
    assert main(_args(fixture_dir, ck, "--model", "graphsage_supervised",
                      "--mode", "evaluate")) == 0
    assert main(_args(fixture_dir, ck, "--model", "graphsage_supervised",
                      "--mode", "save_embedding")) == 0
    emb = np.load(os.path.join(ck, "embedding.npy"))
    assert emb.shape == (17, 8)
    ids = np.loadtxt(os.path.join(ck, "id.txt"), dtype=np.int64)
    assert len(ids) == 17
    # relaunching train against the finished checkpoint resumes at
    # num_steps, trains 0 new steps, and must exit cleanly instead of
    # re-saving the restored step (orbax StepAlreadyExistsError)
    assert main(_args(fixture_dir, ck, "--model", "graphsage_supervised",
                      "--mode", "train")) == 0
    # frozen saved-embedding classifier trains from the export (fresh
    # checkpoint dir; the embedding comes from the previous run's export)
    assert main(_args(fixture_dir, str(tmp_path / "ck_cls"),
                      "--model", "saved_embedding", "--mode", "train",
                      "--num_epochs", "2",
                      "--embedding_file",
                      os.path.join(ck, "embedding.npy"))) == 0


def test_shared_graph_mode(fixture_dir, tmp_path):
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    rc = main(_args(fixture_dir, str(tmp_path / "ck2"),
                    "--model", "graphsage_supervised", "--mode", "train",
                    "--graph_mode", "shared", "--registry", reg,
                    "--num_processes", "1", "--num_epochs", "2"))
    assert rc == 0
    assert os.listdir(reg) == []  # service stopped + deregistered


def test_gcn_device_sampling_cli(fixture_dir, tmp_path):
    """--device_sampling reaches the full-neighbor GCN: train + evaluate
    run with the multi-hop expansion on device."""
    ck = str(tmp_path / "ck_gcn_dev")
    assert main(_args(fixture_dir, ck, "--model", "gcn",
                      "--mode", "train", "--device_sampling", "true",
                      "--num_epochs", "2")) == 0
    assert main(_args(fixture_dir, ck, "--model", "gcn",
                      "--mode", "evaluate", "--device_sampling",
                      "true")) == 0


def test_feature_dtype_cli(fixture_dir, tmp_path, graph):
    """--feature_dtype bfloat16 is threaded to the model as a real kwarg
    (no process-global state) and the run trains end-to-end."""
    args = define_flags().parse_args(
        COMMON + ["--model", "graphsage_supervised",
                  "--device_features", "true",
                  "--feature_dtype", "bfloat16"]
    )
    model = build_model(args, graph)
    assert model.feature_dtype == "bfloat16"
    assert "EULER_TPU_FEATURE_DTYPE" not in os.environ

    ck = str(tmp_path / "ck_bf16")
    assert main(_args(fixture_dir, ck, "--model", "graphsage_supervised",
                      "--mode", "train", "--device_features", "true",
                      "--feature_dtype", "bfloat16",
                      "--num_epochs", "2")) == 0
    assert "EULER_TPU_FEATURE_DTYPE" not in os.environ


@pytest.mark.parametrize(
    "name",
    ["line", "node2vec", "graphsage", "graphsage_supervised",
     "scalable_sage", "scalable_gcn", "gat", "gcn"],
)
def test_model_dispatch(name, graph):
    args = define_flags().parse_args(
        COMMON + ["--model", name, "--all_node_type", "-1"]
    )
    model = build_model(args, graph)
    batch = model.sample(graph, np.asarray(graph.sample_node(8, -1)))
    assert isinstance(batch, dict) and batch


def test_walk_trials_cli(graph):
    """--walk_trials is threaded to the Node2Vec module (the rejection
    walk's per-step proposal budget on the device alias path)."""
    args = define_flags().parse_args(
        COMMON + ["--model", "node2vec", "--all_node_type", "-1",
                  "--walk_p", "0.25", "--walk_q", "4.0",
                  "--walk_trials", "16", "--device_sampling", "true",
                  "--device_features", "true", "--feature_idx", "-1"]
    )
    model = build_model(args, graph)
    assert model.module.walk_trials == 16


def test_train_streamed_remote_data(fixture_dir, tmp_path, monkeypatch):
    """--stream true trains off a remote URL with zero local staging
    (the scratch-poor-host path; DEPLOY.md 'Remote data')."""
    import fsspec

    fs = fsspec.filesystem("memory")
    for name in os.listdir(fixture_dir):
        with open(os.path.join(fixture_dir, name), "rb") as f:
            data = f.read()
        with fs.open(f"/rl_stream/{name}", "wb") as f:
            f.write(data)
    cache = str(tmp_path / "never_staged")
    monkeypatch.setenv("EULER_TPU_CACHE", cache)
    try:
        rc = main(_args("memory://rl_stream", str(tmp_path / "ck_stream"),
                        "--stream", "true",
                        "--model", "graphsage_supervised",
                        "--mode", "train"))
        assert rc == 0
        assert not os.path.exists(cache)
    finally:
        fs.rm("/rl_stream", recursive=True)


def test_stream_rejected_outside_local_mode(fixture_dir, tmp_path):
    """--stream must never be dropped silently: shared/remote modes
    stage deliberately, so the flag errors out loudly there."""
    with pytest.raises(ValueError, match="graph_mode=local"):
        main(_args(fixture_dir, str(tmp_path / "ck"),
                   "--stream", "true", "--graph_mode", "shared",
                   "--registry", str(tmp_path / "reg"),
                   "--model", "graphsage_supervised", "--mode", "train"))


def test_metrics_every_writes_jsonl(fixture_dir, tmp_path):
    """--metrics_every=N appends one telemetry snapshot line per N
    training steps to the JSONL file (OBSERVABILITY.md emission), the
    snapshots carry the step-phase histograms + input_stall_ms, and
    --trace_file exports a valid Chrome trace with the phase slices."""
    import json

    from euler_tpu import telemetry as T

    T.telemetry_reset()
    mf = str(tmp_path / "metrics.jsonl")
    tf = str(tmp_path / "run_trace.json")
    assert main(_args(fixture_dir, str(tmp_path / "ck_metrics"),
                      "--model", "graphsage_supervised", "--mode", "train",
                      "--num_epochs", "2",
                      "--metrics_every", "2", "--metrics_file", mf,
                      "--trace_file", tf)) == 0
    lines = [json.loads(x) for x in open(mf)]
    assert lines, "no metrics emitted"
    assert all(rec["step"] % 2 == 0 for rec in lines)
    assert all("counters" in rec and "ops" in rec for rec in lines)
    # the step-phase profiler reported through the same snapshots
    last = lines[-1]
    assert {"input_stall", "sample", "device", "host",
            "step"} <= set(last["phases"]), last["phases"]
    assert last["input_stall_ms"] >= 0.0
    assert last["prefetch"]["mean_queue_depth"] >= 0.0
    # per-step step-phase counts: every step recorded every loop phase
    # (the snapshot hook fires mid-body, before that step's host/step
    # records land — hence the ±1)
    steps = last["phases"]["step"]["count"]
    assert steps >= last["step"] - 1
    assert steps <= last["phases"]["device"]["count"] <= steps + 1
    # the trace file is a valid Chrome trace whose phase lanes cover
    # the training loop (h2d rides the prefetch workers here:
    # device_prefetch on a 1-device CPU mesh stays enabled)
    from euler_tpu.trace import validate_chrome_trace

    with open(tf) as f:
        events = validate_chrome_trace(json.load(f))
    names = {e["name"] for e in events if e.get("cat") == "phase"}
    assert {"input_stall", "sample", "h2d", "device", "host",
            "step"} <= names, names
