"""eg_heat: the data-plane access profiler (OBSERVABILITY.md
"Data-plane heat").

Everything here is exact arithmetic: the space-saving top-K table is
pinned against ground-truth Counter values (exactness whenever K covers
the stream's distinct ids), the count-min estimates against the
eps = e/width overestimate bound, the client ids ledger against the
`ids_on_wire = ids_requested - ids_deduped - cache_hits` identity, and
the cache-efficacy class buckets against the cache_hits/cache_misses
counters they must sum to.
"""

import collections

import numpy as np
import pytest

import euler_tpu
from euler_tpu import heat as H
from euler_tpu import telemetry as T
from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture


@pytest.fixture(autouse=True)
def _clean_slate():
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()  # resets histograms + spans + phases + heat
    T.set_telemetry(True)
    H.set_heat(True)
    H.set_heat_topk(128)
    yield
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    T.set_telemetry(True)
    H.set_heat(True)
    H.set_heat_topk(128)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("heat_data"))
    write_fixture(d, num_partitions=2)
    return d


@pytest.fixture(scope="module")
def heavytail_dir(tmp_path_factory):
    """A reddit_heavytail-shaped fixture at test scale: power-law
    out-degrees with preferential targets (the datasets.REDDIT_HEAVYTAIL
    recipe's alpha), so the access streams below have a real heavy
    tail."""
    from euler_tpu.datasets import build_powerlaw

    d = str(tmp_path_factory.mktemp("heat_heavytail"))
    build_powerlaw(d, num_nodes=400, num_edges=6000, feature_dim=8,
                   label_dim=3, alpha=1.8, num_partitions=4, seed=23)
    return d


def _graph(svcs, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("timeout_ms", 5000)
    return Graph(mode="remote", shards=[s.address for s in svcs], **kw)


def _zipf_stream(num_ids: int, length: int, alpha: float = 1.3,
                 seed: int = 3) -> np.ndarray:
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, num_ids + 1, dtype=np.float64)
    probs = ranks ** (-alpha)
    probs /= probs.sum()
    return rng.choice(num_ids, size=length, p=probs).astype(np.int64)


# ---------------------------------------------------------------------------
# sketch exactness: space-saving + count-min against ground truth
# ---------------------------------------------------------------------------


def test_space_saving_exact_when_k_covers_distinct():
    """With K >= the number of distinct ids, space-saving degenerates to
    exact counting: every id tracked, counts exact, err == 0."""
    stream = _zipf_stream(100, 5000)
    H.record_heat(stream, op="dense_feature")
    truth = collections.Counter(stream.tolist())
    top = H.heat_topk()
    assert len(top) == len(truth)
    for e in top:
        assert e["count"] == truth[e["id"]], e
        assert e["err"] == 0, e
    # hottest-first ordering
    counts = [e["count"] for e in top]
    assert counts == sorted(counts, reverse=True)


def test_space_saving_bounds_beyond_capacity():
    """K smaller than the distinct-id count: every tracked id satisfies
    count >= true >= count - err, and every id hotter than N/K is
    guaranteed tracked (the space-saving heavy-hitter guarantee)."""
    H.set_heat_topk(16)
    stream = _zipf_stream(300, 8000, alpha=1.5)
    H.record_heat(stream)
    truth = collections.Counter(stream.tolist())
    top = H.heat_topk()
    assert len(top) == 16
    tracked = {e["id"]: e for e in top}
    for e in top:
        true = truth[e["id"]]
        assert e["count"] >= true, e
        assert e["count"] - e["err"] <= true, e
    n = len(stream)
    for id_, c in truth.items():
        if c > n / 16:
            assert id_ in tracked, (id_, c)


def test_cms_estimates_within_epsilon(heavytail_dir):
    """Count-min point estimates: est >= true ALWAYS (structural — the
    sketch only ever adds), and est <= true + eps * N per query with
    probability 1 - e^-depth (~86% at depth 2). The stream is seeded,
    so the empirical within-budget fraction is deterministic; pinning
    it well above the theoretical floor catches any regression in the
    hash spreading without asserting a bound the sketch never
    promised."""
    # an access stream shaped by the heavytail fixture's degree skew
    g = euler_tpu.Graph(directory=heavytail_dir)
    _, _, _, deg = g.get_full_neighbor(np.arange(400), [0])
    g.close()
    rng = np.random.default_rng(7)
    probs = deg.astype(np.float64) + 1.0
    probs /= probs.sum()
    stream = rng.choice(400, size=20000, p=probs).astype(np.int64)
    H.record_heat(stream)
    truth = collections.Counter(stream.tolist())
    data = H.heat_json()
    eps = H.cms_epsilon(data)
    total = data["sketch"]["total"]["client"]
    assert total == len(stream)
    budget = eps * total
    within = 0
    for id_ in range(400):
        est = H.estimate(id_)
        assert est >= truth[id_], (id_, est, truth[id_])
        if est <= truth[id_] + budget:
            within += 1
        else:
            # even a colliding estimate stays a small additive error on
            # this stream, nowhere near a hot id's count
            assert est <= truth[id_] + 20 * budget, (id_, est, truth[id_])
    assert within / 400 >= 0.95, (within, budget)


def test_topk_capacity_resize_resets_tables():
    H.record_heat(np.arange(50, dtype=np.int64))
    assert len(H.heat_topk()) == 50
    H.set_heat_topk(8)
    assert H.heat_topk() == []
    H.record_heat(np.zeros(3, dtype=np.int64))
    top = H.heat_topk()
    assert len(top) == 1 and top[0]["count"] == 3


def test_kill_switches_record_nothing():
    H.set_heat(False)
    H.record_heat(np.arange(10, dtype=np.int64))
    assert H.heat_topk() == []
    assert H.heat_json()["sketch"]["total"]["client"] == 0
    H.set_heat(True)
    # the master telemetry switch gates heat too
    T.set_telemetry(False)
    H.record_heat(np.arange(10, dtype=np.int64))
    assert H.heat_topk() == []
    T.set_telemetry(True)
    H.record_heat(np.arange(10, dtype=np.int64))
    assert len(H.heat_topk()) == 10


def test_op_name_table_matches_native():
    """heat.OP_NAMES must mirror the native kWireOpNames slot order —
    the ids ledger keys are built from it on the native side."""
    H.record_heat([1, 2, 3], op="sample_neighbor")
    H.record_heat([4], op="heat", side="server")
    ids = H.heat_json()["ids"]
    assert ids == {"client:sample_neighbor": 3, "server:heat": 1}


# ---------------------------------------------------------------------------
# live-cluster exactness: server top-K, ids ledger, cache classes
# ---------------------------------------------------------------------------


def test_server_topk_matches_ground_truth_on_cluster(heavytail_dir):
    """Capstone pin: a 2-shard cluster served a deterministic
    heavy-tailed id stream; the servers' merged top-K table must match
    the exact per-unique-id-per-call ground truth (client coalescing
    means each call feeds its DISTINCT ids once)."""
    svcs = [GraphService(heavytail_dir, s, 2) for s in range(2)]
    try:
        g = _graph(svcs, feature_cache_mb=0)  # cache off: every unique
        try:                                  # id reaches the servers
            T.telemetry_reset()
            truth: collections.Counter = collections.Counter()
            rng = np.random.default_rng(11)
            for step in range(6):
                stream = _zipf_stream(400, 512, alpha=1.6,
                                      seed=int(rng.integers(1 << 30)))
                g.node_types(stream)
                truth.update(set(stream.tolist()))
            top = H.heat_topk(side="server")
            assert top, "server table empty"
            # K (128) covers the heavy tail here, so every tracked id
            # hot enough to be unambiguous is EXACT
            for e in top:
                assert e["count"] - e["err"] <= truth[e["id"]] <= e["count"]
            exact = [e for e in top if e["err"] == 0]
            assert exact, top
            for e in exact:
                assert e["count"] == truth[e["id"]], (e, truth[e["id"]])
            # the hottest id overall is the hottest id in truth
            hottest_truth = max(truth.values())
            assert top[0]["count"] >= hottest_truth
            # the same table over the wire (kHeat) names this shard
            d0 = H.heat_json(g, 0)
            assert d0["shard"] == 0
            assert d0["topk"]["server"] == H.heat_json()["topk"]["server"]
            assert d0["conns"], d0  # requesting-conn attribution present
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()


def test_ids_ledger_identity_and_cache_class_sums(data_dir):
    """The acceptance identity, measured not derived: per op
    ids_on_wire == ids_requested - ids_deduped - cache_hits, and the
    cache-efficacy class buckets sum to the cache_hits/cache_misses
    counters."""
    svcs = [GraphService(data_dir, s, 2) for s in range(2)]
    try:
        g = _graph(svcs, feature_cache_mb=8)
        try:
            T.telemetry_reset()
            native.reset_counters()
            ids = np.array([1, 2, 3, 1, 2, 3, 4, 4, 5], dtype=np.int64)
            g.get_dense_feature(ids, [0], [4])   # all misses
            g.get_dense_feature(ids, [0], [4])   # all unique ids hit
            g.sample_neighbor(ids, [0, 1], 3)
            d = H.heat_json()
            ctr = native.counters()
            for op in ("dense_feature", "sample_neighbor"):
                f = d["fanout"][op]
                assert f["ids_on_wire"] == (f["ids_requested"]
                                            - f["ids_deduped"]
                                            - f["cache_hits"]), (op, f)
            fdf = d["fanout"]["dense_feature"]
            assert fdf["ids_requested"] == 18
            assert fdf["ids_deduped"] == 8        # 4 dups per call
            assert fdf["cache_hits"] == 5         # second call all-hit
            assert fdf["cache_hits"] == ctr["cache_hits"]
            cc = d["cache_class"]
            assert sum(cc["hit"]) == ctr["cache_hits"]
            assert sum(cc["miss"]) == ctr["cache_misses"]
            # sample_neighbor never touches the cache
            assert d["fanout"]["sample_neighbor"]["cache_hits"] == 0
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()


def test_cache_evictions_land_in_classes(data_dir):
    """A cache far smaller than the working set must evict, and every
    eviction lands in a frequency-class bucket."""
    svcs = [GraphService(data_dir, 0, 1)]
    try:
        # 1 MB budget across 16 stripes with ~1.1 KB rows (256 floats):
        # ~55 rows per stripe, so 3000 distinct rows must evict
        g = _graph(svcs, feature_cache_mb=1)
        try:
            T.telemetry_reset()
            native.reset_counters()
            for lo in range(0, 3000, 500):
                ids = np.arange(lo, lo + 500, dtype=np.int64)
                g.get_dense_feature(ids, [0], [256])
            cc = H.heat_json()["cache_class"]
            assert sum(cc["evict"]) > 0, cc
            assert sum(cc["miss"]) == native.counters()["cache_misses"]
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()


def test_heat_spread_and_metrics_families(data_dir):
    """The shards-touched spread histograms ride the shared hist map
    (count == calls), and the eg_heat_* Prometheus families render from
    the same dump."""
    svcs = [GraphService(data_dir, s, 2) for s in range(2)]
    try:
        g = _graph(svcs)
        try:
            T.telemetry_reset()
            ids = np.array([10, 11, 12, 13], dtype=np.int64)
            for _ in range(3):
                g.sample_neighbor(ids, [0, 1], 2)
            hist = euler_tpu.telemetry_json()["hist"]
            key = "heat_spread:sample_neighbor"
            assert key in hist, sorted(k for k in hist
                                       if k.startswith("heat"))
            assert hist[key]["count"] == 3
            d = H.heat_json()
            assert d["fanout"]["sample_neighbor"]["calls"] == 3
            assert d["shard_bytes"], d  # bytes attributed per shard
            text = euler_tpu.metrics_text()
            assert 'eg_heat_ids_total{side="client"' in text
            assert "eg_heat_topk_share" in text
            assert "eg_heat_shard_spread" in text
        finally:
            g.close()
    finally:
        for s in svcs:
            s.stop()


# ---------------------------------------------------------------------------
# config keys
# ---------------------------------------------------------------------------


def test_heat_keys_rejected_on_local_graphs(data_dir):
    with pytest.raises(ValueError, match="heat="):
        Graph(directory=data_dir, heat=True)
    with pytest.raises(ValueError, match="heat_topk="):
        Graph(directory=data_dir, heat_topk=64)


def test_heat_config_keys_reach_the_switches(data_dir):
    svcs = [GraphService(data_dir, 0, 1)]
    try:
        g = _graph(svcs, heat=False, heat_topk=32)
        try:
            assert not H.heat_enabled()
            ids = np.array([1, 2, 3], dtype=np.int64)
            g.node_types(ids)
            assert H.heat_topk() == []
        finally:
            g.close()
        # service options flip it back on
        g2 = _graph(svcs, heat=True)
        try:
            g2.node_types(np.array([1, 2, 3], dtype=np.int64))
            assert H.heat_topk(side="server")
        finally:
            g2.close()
    finally:
        for s in svcs:
            s.stop()


def test_bad_heat_topk_fails_loudly(data_dir):
    svcs = [GraphService(data_dir, 0, 1)]
    try:
        with pytest.raises(RuntimeError, match="heat_topk"):
            _graph(svcs, heat_topk=1 << 20)
        with pytest.raises(RuntimeError, match="heat_topk"):
            GraphService(data_dir, 0, 1, options="heat_topk=0")
    finally:
        for s in svcs:
            s.stop()


def test_service_option_heat_kill_switch(data_dir):
    svc = GraphService(data_dir, 0, 1, options="heat=0")
    try:
        assert not H.heat_enabled()
    finally:
        svc.stop()
    H.set_heat(True)


# ---------------------------------------------------------------------------
# skew-report arithmetic (scripts/heat_dump.py helpers)
# ---------------------------------------------------------------------------


def test_zipf_fit_recovers_exponent():
    counts = [int(1e6 * r ** -1.4) for r in range(1, 65)]
    top = [{"id": i, "count": c, "err": 0} for i, c in enumerate(counts)]
    fit = H.zipf_fit(top)
    assert abs(fit["alpha"] - 1.4) < 0.02, fit
    assert fit["r2"] > 0.999


def test_cache_hit_ceiling_arithmetic():
    # 3 ids, counts 10/5/1, total 16: pinning the top 2 yields
    # (10-1)+(5-1) = 13 hits of 16 accesses
    top = [{"id": 1, "count": 10, "err": 0},
           {"id": 2, "count": 5, "err": 0},
           {"id": 3, "count": 1, "err": 0}]
    ce = H.cache_hit_ceiling(top, 16, 2)
    assert ce["projected_hit_rate"] == round(13 / 16, 4)
    # capacity beyond the table extrapolates (monotone, bounded)
    big = H.cache_hit_ceiling(top, 16, 100)
    assert big["projected_hit_rate"] >= ce["projected_hit_rate"]
    assert big["projected_hit_rate"] <= 1.0
