"""eg_blackbox: the always-on flight recorder, fatal-signal postmortem
dumps, and cluster incident collection (OBSERVABILITY.md "Postmortems").

Exact-arithmetic where the machinery allows it: ring eviction order is
pinned slot-by-slot, the seeded `crash` failpoint's ledger is audited
against the dead shard's own postmortem, and the merged incident
timeline must correlate the client journal with the postmortem rings by
the fatal call's wire-v3 trace id. Crash paths run in subprocesses (a
SIGSEGV, even a handled one, must never ride the test process).
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import euler_tpu
from euler_tpu import blackbox as B
from euler_tpu import telemetry as T
from euler_tpu.graph import native
from euler_tpu.graph.graph import Graph
from euler_tpu.graph.service import GraphService
from tests.fixture_graph import write_fixture

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RING_SLOTS = 256  # eg_blackbox.h kBbRingSlots, pinned by the wrap test


@pytest.fixture(autouse=True)
def _clean_slate():
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    B.blackbox_reset()
    B.set_blackbox(True)
    yield
    native.fault_clear()
    native.reset_counters()
    T.telemetry_reset()
    B.blackbox_reset()
    B.set_blackbox(True)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("blackbox_data"))
    write_fixture(d, num_partitions=2)
    return d


def _subprocess(code: str, timeout=120.0):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)


# ---------------------------------------------------------------------------
# flight recorder ring semantics
# ---------------------------------------------------------------------------


def test_ring_eviction_is_oldest_first_under_wraparound():
    """Writing slots+44 events leaves exactly the newest `slots`, read
    back oldest-first — the window is [head - slots, head), no
    reordering, no gaps."""
    total = RING_SLOTS + 44
    for i in range(total):
        B.record("app", value=i)
    d = B.blackbox_json()
    my_rings = [r for r in d["rings"] if r["head"] >= total]
    assert my_rings, d["rings"]
    ring = my_rings[0]
    assert ring["head"] == total
    values = [e["value"] for e in ring["events"]]
    assert values == list(range(total - RING_SLOTS, total))


def test_record_points_roster_matches_native_names():
    for point in B.POINTS:
        B.record(point, value=1)
    d = B.blackbox_json()
    seen = {e["point"] for r in d["rings"] for e in r["events"]}
    assert set(B.POINTS) <= seen, seen


def test_kill_switch_records_nothing():
    B.set_blackbox(False)
    for i in range(10):
        B.record("app", value=i)
    d = B.blackbox_json()
    assert d["enabled"] == 0
    assert all(r["head"] == 0 for r in d["rings"]), d["rings"]


def test_client_and_server_hooks_feed_the_rings(data_dir):
    """Remote traffic against an in-process shard lands client_call,
    server_recv and server_reply events — with the SAME trace id on
    both sides of one exchange (the correlation the postmortem merge
    keys on)."""
    svc = GraphService(data_dir, 0, 1)
    try:
        g = Graph(mode="remote", shards=[svc.address], retries=2)
        try:
            g.node_types(np.array([10, 11], dtype=np.int64))
        finally:
            g.close()
    finally:
        svc.stop()
    d = B.blackbox_json()
    evs = [e for r in d["rings"] for e in r["events"]]
    by_point: dict = {}
    for e in evs:
        by_point.setdefault(e["point"], []).append(e)
    for point in ("client_call", "server_recv", "server_reply",
                  "dispatch"):
        assert by_point.get(point), f"no {point} events: {sorted(by_point)}"
    client_traces = {e["trace"] for e in by_point["client_call"]
                     if int(e["trace"])}
    server_traces = {e["trace"] for e in by_point["server_recv"]
                     if int(e["trace"])}
    assert client_traces & server_traces
    # wire bytes ride the value field on rpc points
    assert any(e["value"] > 0 for e in by_point["client_call"])


# ---------------------------------------------------------------------------
# resource gauges
# ---------------------------------------------------------------------------


def test_resource_gauges_in_metrics_text_with_plausible_bounds():
    text = euler_tpu.metrics_text()

    def value_of(fam):
        (line,) = [ln for ln in text.splitlines()
                   if ln.startswith(fam + " ")]
        return float(line.split()[-1])

    assert value_of("eg_rss_bytes") > 0
    assert value_of("eg_open_fds") >= 3  # stdin/stdout/stderr at least
    assert value_of("eg_threads") >= 1
    assert value_of("eg_cache_bytes") >= 0


def test_history_scrape_opcode_against_live_shard(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = Graph(mode="remote", shards=[svc.address], retries=2)
        try:
            h = B.history(g, 0)
        finally:
            g.close()
    finally:
        svc.stop()
    assert h["shard"] == 0
    assert h["resource"]["rss_bytes"] > 0
    assert h["resource"]["open_fds"] >= 3
    # in-process shard: no Install ran, so the ring may be empty — the
    # latest live sample above is the contract; a real shard process
    # (service.py --postmortem_dir) fills `history` too
    assert isinstance(h["history"], list)


def test_cache_bytes_gauge_tracks_the_feature_cache(data_dir):
    svc = GraphService(data_dir, 0, 1)
    try:
        g = Graph(mode="remote", shards=[svc.address], retries=2,
                  feature_cache_mb=8)
        try:
            g.get_dense_feature(np.array([10, 11, 12], dtype=np.int64),
                                [0], [2])
            with_rows = B.blackbox_json()["resource"]["cache_bytes"]
            assert with_rows > 0, with_rows
        finally:
            g.close()
        # graph teardown returns its resident bytes to the gauge
        after = B.blackbox_json()["resource"]["cache_bytes"]
        assert after < with_rows
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# manual dumps + postmortem_read
# ---------------------------------------------------------------------------


def test_manual_dump_roundtrip(tmp_path):
    B.install(str(tmp_path), shard=3, sample_ms=100)
    B.record("app", value=42, trace=777)
    path = B.write_postmortem(str(tmp_path / "postmortem.1.json"))
    doc = euler_tpu.postmortem_read(path)
    assert doc["kind"] == "postmortem"
    assert doc["signal"] == 0 and doc["signal_name"] == "exception"
    assert doc["shard"] == 3
    # the counter ledger matches the live roster exactly (same names)
    assert set(doc["counters"]) == set(euler_tpu.counters())
    evs = [e for r in doc["rings"] for e in r["events"]]
    assert any(e["value"] == 42 and e["trace"] == "777" for e in evs)
    assert doc["resource_history"], "sampler never seeded the ring"
    dumps = euler_tpu.postmortem_read(str(tmp_path))
    assert [d["path"] for d in dumps] == [path]


def test_install_rejects_unwritable_dir():
    with pytest.raises(RuntimeError, match="not writable"):
        B.install("/proc/definitely-not-writable")


# ---------------------------------------------------------------------------
# fatal-signal path (subprocesses: the dump must survive what kills it)
# ---------------------------------------------------------------------------


def test_fatal_signal_writes_postmortem_and_reraises(tmp_path):
    pm = str(tmp_path)
    p = _subprocess(f"""
import os, signal
from euler_tpu import blackbox as B
B.install({pm!r}, shard=5, sample_ms=100)
B.record("app", value=9)
os.kill(os.getpid(), signal.SIGSEGV)
""")
    assert p.returncode == -signal.SIGSEGV, (p.returncode, p.stderr)
    (doc,) = euler_tpu.postmortem_read(pm)
    assert doc["signal_name"] == "SIGSEGV"
    assert doc["shard"] == 5
    assert doc["backtrace"], "no backtrace addresses captured"
    assert doc["backtrace_symbols"], "no symbolized frames after the JSON"
    evs = [e for r in doc["rings"] for e in r["events"]]
    assert any(e["value"] == 9 for e in evs)


def test_blackbox_disabled_writes_nothing(tmp_path):
    """blackbox=0 is a real kill-switch: the handler still re-raises
    (same exit status) but writes NO dump."""
    pm = str(tmp_path)
    p = _subprocess(f"""
import os, signal
from euler_tpu import blackbox as B
B.install({pm!r}, shard=5, sample_ms=100)
B.set_blackbox(False)
os.kill(os.getpid(), signal.SIGSEGV)
""")
    assert p.returncode == -signal.SIGSEGV, (p.returncode, p.stderr)
    assert euler_tpu.postmortem_read(pm) == []


def test_crash_failpoint_at_dial_raises_chosen_signal(tmp_path):
    """crash:delay@6 raises SIGABRT at the client's dial point (the
    grammar's signal-selection form), and the dump still lands."""
    pm = str(tmp_path)
    p = _subprocess(f"""
import euler_tpu
from euler_tpu import blackbox as B
B.install({pm!r}, shard=-1, sample_ms=100)
euler_tpu.fault_config("crash:delay@6@1#1", 3)
try:
    euler_tpu.Graph(mode="remote", shards=["127.0.0.1:1"], retries=0,
                    timeout_ms=200)
except Exception:
    pass
""")
    assert p.returncode == -signal.SIGABRT, (p.returncode, p.stderr)
    (doc,) = euler_tpu.postmortem_read(pm)
    assert doc["signal_name"] == "SIGABRT"
    assert doc["counters"]["crashes"] == 1


def test_run_loop_dumps_on_unhandled_exception(tmp_path):
    """The Python twin of the signal path: run_loop with
    --postmortem_dir writes an .exception.json dump when training dies
    on an unhandled exception (here: a nonexistent data_dir)."""
    pm = str(tmp_path / "pm")
    p = _subprocess(f"""
import sys
from euler_tpu import run_loop
sys.argv = ["run_loop", "--mode", "train",
            "--data_dir", {str(tmp_path / 'missing')!r},
            "--postmortem_dir", {pm!r}]
try:
    run_loop.main(sys.argv[1:])
except Exception:
    sys.exit(3)
""")
    assert p.returncode == 3, (p.returncode, p.stderr)
    dumps = euler_tpu.postmortem_read(pm)
    assert len(dumps) == 1 and dumps[0]["signal_name"] == "exception"
    assert dumps[0]["path"].endswith(".exception.json")


# ---------------------------------------------------------------------------
# the incident: seeded crash on a live cluster -> one merged timeline
# ---------------------------------------------------------------------------


def _launch_shard(idx, data, reg, fault=None, pmdir=None):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "euler_tpu.graph.service",
           "--data_dir", data, "--shard_idx", str(idx),
           "--shard_num", "2", "--registry", reg]
    if fault:
        cmd += ["--fault", fault, "--fault_seed", "11"]
    if pmdir:
        cmd += ["--postmortem_dir", pmdir]
    return subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL, env=env)


def _wait_up(idx, reg, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for f in os.listdir(reg):
            if not f.startswith(f"{idx}#"):
                continue
            host, port = f.split("#", 1)[1].rsplit("_", 1)
            try:
                with socket.create_connection((host, int(port)), 1.0):
                    return
            except OSError:
                continue
        time.sleep(0.1)
    raise TimeoutError(f"shard {idx} never registered in {reg}")


def test_crash_incident_merges_into_one_timeline(tmp_path):
    """Acceptance (ISSUE 7): a seeded `crash` failpoint on a live
    2-shard cluster yields a postmortem per dead shard whose counter
    ledger matches the injection count and whose flight-recorder tail
    carries the fatal call's trace id; scripts/postmortem.py merges the
    dumps with the client trace into one timeline keyed by that id."""
    from euler_tpu import trace as trace_mod
    from scripts.postmortem import correlated_fatal_ids, merge_trace

    data = str(tmp_path / "data")
    os.makedirs(data)
    write_fixture(data, num_partitions=2)
    reg = str(tmp_path / "reg")
    os.makedirs(reg)
    pm = str(tmp_path / "pm")
    os.makedirs(pm)

    procs = [_launch_shard(0, data, reg)]
    try:
        procs.append(_launch_shard(1, data, reg))
        _wait_up(0, reg)
        _wait_up(1, reg)
        g = Graph(mode="remote", registry=reg, retries=1,
                  timeout_ms=1500, backoff_ms=10, rediscover_ms=200)
        try:
            ids = np.array(sorted([10, 11, 12, 13, 15, 17]),
                           dtype=np.int64)
            g.node_types(ids)  # cluster warm, both shards answering

            # restart shard 1 armed to die on its next request
            procs[1].terminate()
            procs[1].wait(timeout=30)
            for f in list(os.listdir(reg)):
                if f.startswith("1#"):
                    os.unlink(os.path.join(reg, f))
            procs[1] = _launch_shard(1, data, reg, fault="crash:err@1#1",
                                     pmdir=pm)
            _wait_up(1, reg)
            time.sleep(0.5)  # re-discovery picks up the new port

            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                g.node_types(ids)
                if any(f.startswith("postmortem.")
                       for f in os.listdir(pm)):
                    break
                time.sleep(0.2)

            dumps = euler_tpu.postmortem_read(pm)
            assert len(dumps) == 1, [d["path"] for d in dumps]
            dump = dumps[0]
            # ledger matches the seeded injection count exactly:
            # crash:err@1#1 fires once, counted before the raise
            assert dump["signal_name"] == "SIGSEGV"
            assert dump["counters"]["crashes"] == 1, dump["counters"]
            assert dump["shard"] == 1
            # the client OBSERVED the kill: its call to the dead shard
            # exhausted retries (non-strict mode degrades, but counts)
            client = euler_tpu.counters()
            assert client["calls_failed"] >= 1 or client["rpc_errors"] >= 1

            # the fatal call's trace id sits in the recorder tail AND
            # in this client's journal
            fatal_traces = {
                int(e["trace"])
                for ring in dump["rings"] for e in ring["events"]
                if e["point"] == "server_recv" and int(e["trace"])
            }
            assert fatal_traces, dump["rings"]
            client_traces = {s["trace"] for s in T.slow_spans()
                            if s["side"] == "client"}
            assert fatal_traces & client_traces

            # merge: client trace + postmortems -> one timeline keyed
            # by the fatal trace id
            trace_path = str(tmp_path / "client.trace.json")
            client_trace = trace_mod.write_trace(trace_path, None, g)
            merged = merge_trace(dumps, client_trace)
            trace_mod.validate_chrome_trace(merged)
            linked = correlated_fatal_ids(merged)
            assert linked, "no client<->postmortem correlation"
            assert {int(t, 16) for t in linked} & fatal_traces
        finally:
            g.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)


# ---------------------------------------------------------------------------
# metrics_dump --watch rides out an unreachable shard (satellite)
# ---------------------------------------------------------------------------


def test_watch_skips_unreachable_shard_and_recovers(data_dir):
    import io

    from scripts.metrics_dump import watch_cluster

    svcs = [GraphService(data_dir, s, 2) for s in range(2)]
    g = None
    try:
        g = Graph(mode="remote", shards=[s.address for s in svcs],
                  retries=0, timeout_ms=500, backoff_ms=0)
        g.sample_node(4, -1)
        buf = io.StringIO()
        watch_cluster(g, 0.01, iterations=1, out=buf)
        # shard 1 dies mid-watch: the watch notes and continues
        svcs[1].stop()
        watch_cluster(g, 0.01, iterations=1, out=buf)
        out = buf.getvalue()
        assert "unreachable — skipped" in out, out
        # the surviving shard was still scraped in the same iteration
        lines = [ln for ln in out.splitlines() if "shard 0" in ln]
        assert any("served" in ln for ln in lines), out
    finally:
        if g is not None:
            g.close()
        for s in svcs:
            s.stop()
