"""The full user workflow (train -> evaluate -> save_embedding through
the shipped run_loop CLI) on a HEAVY-TAILED graph with the exact alias
device sampler — the round-4 path a real-degree-Reddit user takes. The
slab form would need max_degree tuning here (hub degrees are ~15x the
mean); alias needs none and keeps reference sampling semantics.
"""

import os

import numpy as np
import pytest

from euler_tpu.run_loop import main

pytestmark = pytest.mark.slow

N = 2500


@pytest.fixture(scope="module")
def powerlaw_dir(tmp_path_factory):
    from euler_tpu.datasets import build_powerlaw

    d = str(tmp_path_factory.mktemp("ht_cli"))
    build_powerlaw(d, num_nodes=N, num_edges=150_000, feature_dim=8,
                   label_dim=3, alpha=1.6, seed=23)
    return d


def _args(data_dir, model_dir, *extra):
    return [
        "--data_dir", data_dir, "--model_dir", model_dir,
        "--model", "graphsage_supervised",
        "--max_id", str(N - 1), "--feature_idx", "1", "--feature_dim", "8",
        "--label_idx", "0", "--label_dim", "3", "--sigmoid_loss", "0",
        "--fanouts", "4,4", "--dim", "16", "--batch_size", "256",
        "--num_epochs", "2", "--log_steps", "10",
        "--device_sampling", "1", "--alias_sampling", "1",
    ] + list(extra)


def test_train_eval_save_cycle_alias_heavytail(powerlaw_dir, tmp_path):
    ck = str(tmp_path / "ck_ht")
    assert main(_args(powerlaw_dir, ck, "--mode", "train")) == 0
    assert os.path.isdir(ck)
    assert main(_args(powerlaw_dir, ck, "--mode", "evaluate")) == 0
    assert main(_args(powerlaw_dir, ck, "--mode", "save_embedding")) == 0
    emb = np.load(os.path.join(ck, "embedding.npy"))
    assert emb.shape[0] == N  # one row per id in 0..max_id
    assert np.isfinite(emb).all()
