"""Prefetch pipeline failure paths + synthetic dataset cache lifecycle."""

import os

import pytest

from euler_tpu.parallel import prefetch


def test_prefetch_orders_batches():
    got = list(prefetch(lambda s: s * 10, 20, depth=3, num_threads=4))
    assert got == [s * 10 for s in range(20)]


def test_prefetch_worker_error_propagates():
    def make_batch(step):
        if step == 5:
            raise ValueError("boom at 5")
        return step

    it = prefetch(make_batch, 10, depth=2, num_threads=3)
    got = []
    with pytest.raises(ValueError, match="boom at 5"):
        for b in it:
            got.append(b)
    assert got == [0, 1, 2, 3, 4]


def test_prefetch_worker_death_is_scrape_visible():
    """A worker that dies AFTER init used to surface only as the
    consumer's exception at that step. It must also bump the
    prefetch_worker_errors counter and journal an error span, so a dead
    worker shows up in any metrics scrape even while the consumer is
    still mid-step (OBSERVABILITY.md 'Step phases')."""
    from euler_tpu import telemetry as T
    from euler_tpu.graph import native

    native.reset_counters()
    T.telemetry_reset()
    T.set_telemetry(True)
    try:
        def make_batch(step):
            if step == 3:
                raise RuntimeError("worker died at 3")
            return step

        with pytest.raises(RuntimeError, match="worker died at 3"):
            list(prefetch(make_batch, 8, depth=2, num_threads=2))
        ctr = native.counters()
        assert ctr["prefetch_worker_errors"] == 1, ctr
        assert ctr["prefetch_produced"] >= 3
        spans = T.slow_spans()
        assert any(s["outcome"] == "error" for s in spans), spans
        # the counter rides the ordinary exposition too
        assert ('eg_counter_total{name="prefetch_worker_errors"} 1'
                in T.metrics_text())
    finally:
        native.reset_counters()
        T.telemetry_reset()


def test_prefetch_worker_init_error_counts_too():
    from euler_tpu.graph import native

    native.reset_counters()
    try:
        def bad_init(widx):
            raise RuntimeError("init blew up")

        with pytest.raises(RuntimeError, match="init blew up"):
            list(prefetch(lambda s: s, 4, depth=2, num_threads=2,
                          worker_init=bad_init))
        assert native.counters()["prefetch_worker_errors"] >= 1
    finally:
        native.reset_counters()


def test_prefetch_worker_init_error_raises_not_hangs():
    """A failing worker_init must surface to the consumer instead of
    killing every worker silently and blocking forever on the queue."""

    def bad_init(widx):
        raise RuntimeError("native lib load failed")

    it = prefetch(lambda s: s, 4, depth=2, num_threads=2,
                  worker_init=bad_init)
    with pytest.raises(RuntimeError, match="native lib load failed"):
        list(it)


def test_synthetic_interrupted_build_regenerates(tmp_path):
    """part_*.dat present with the in-progress sentinel (a build killed
    mid-write) must be rebuilt, not returned as a real converted dataset."""
    from euler_tpu.datasets import build_synthetic

    kw = dict(num_nodes=20, avg_degree=3, feature_dim=4, label_dim=2,
              multilabel=True, num_partitions=2)
    d = str(tmp_path)
    build_synthetic(d, **kw)
    assert os.path.exists(os.path.join(d, "done"))
    assert not os.path.exists(os.path.join(d, "synthetic-in-progress"))

    # simulate an interrupted rebuild: sentinel present, done removed,
    # one partition truncated
    os.unlink(os.path.join(d, "done"))
    with open(os.path.join(d, "synthetic-in-progress"), "w") as f:
        f.write("params")
    part = os.path.join(d, "part_0.dat")
    with open(part, "r+b") as f:
        f.truncate(10)

    build_synthetic(d, **kw)
    assert os.path.getsize(part) > 10
    assert os.path.exists(os.path.join(d, "done"))
    assert not os.path.exists(os.path.join(d, "synthetic-in-progress"))

    import euler_tpu

    g = euler_tpu.Graph(directory=d)
    assert g.num_nodes == 20


def test_synthetic_real_dataset_never_overwritten(tmp_path):
    """.dat files with no synthetic marker at all are a real converted
    dataset: build_synthetic must leave them untouched."""
    from euler_tpu.datasets import build_synthetic

    d = str(tmp_path)
    part = os.path.join(d, "part_0.dat")
    os.makedirs(d, exist_ok=True)
    with open(part, "wb") as f:
        f.write(b"real data")

    out = build_synthetic(d, num_nodes=10, avg_degree=2, feature_dim=2,
                          label_dim=2)
    assert out == d
    assert open(part, "rb").read() == b"real data"
    assert not os.path.exists(os.path.join(d, "done"))
