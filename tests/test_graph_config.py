"""Client config-file / inline-config / lazy-init surface
(reference euler/client/graph.cc:163-185 NewGraph(config_file) +
graph_config.cc:33-56 key=value loader + init=lazy)."""

import pytest

from euler_tpu.graph.graph import Graph, parse_config


def test_parse_inline_string():
    cfg = parse_config("mode=local;shard_idx=1;directory=/d;x = 7")
    assert cfg == {"mode": "local", "shard_idx": 1, "directory": "/d",
                   "x": 7}


def test_parse_ini_file(tmp_path):
    p = tmp_path / "g.ini"
    p.write_text(
        "# euler client config\n"
        "[graph]\n"
        "mode = local\n"
        "directory = /data/g\n"
        "shard_num = 4\n"
        "; trailing comment\n"
    )
    cfg = parse_config(str(p))
    assert cfg == {"mode": "local", "directory": "/data/g", "shard_num": 4}


def test_parse_rejects_garbage(tmp_path):
    p = tmp_path / "bad.ini"
    p.write_text("not a key value line\n")
    with pytest.raises(ValueError, match="key=value"):
        parse_config(str(p))


def test_graph_from_config_file(fixture_dir, tmp_path):
    p = tmp_path / "g.ini"
    p.write_text(f"mode = local\ndirectory = {fixture_dir}\n")
    g = Graph(config=str(p))
    assert g.num_nodes == 7
    g.close()


def test_kwargs_override_config(fixture_dir, tmp_path):
    p = tmp_path / "g.ini"
    p.write_text("mode = local\ndirectory = /nonexistent\n")
    g = Graph(config=str(p), directory=fixture_dir)
    assert g.num_nodes == 7
    g.close()


def test_lazy_init_defers_load(fixture_dir):
    g = Graph(directory=fixture_dir, init="lazy")
    assert g._handle is None  # nothing loaded yet
    ids = g.sample_node(4, -1)  # first use connects
    assert len(ids) == 4
    assert g._handle is not None
    g.close()


def test_lazy_init_close_without_use(fixture_dir):
    g = Graph(directory=fixture_dir, init="lazy")
    g.close()  # must not connect just to close
    assert g._handle is None


def test_lazy_init_from_config_string(fixture_dir):
    g = Graph(config=f"directory={fixture_dir};init=lazy")
    assert g._handle is None
    assert g.num_edges > 0
    g.close()


def test_lazy_init_error_surfaces_on_first_use(tmp_path):
    g = Graph(directory=str(tmp_path / "empty"), init="lazy")
    with pytest.raises(RuntimeError, match="load failed"):
        g.sample_node(1, -1)


def test_bad_init_value(fixture_dir):
    with pytest.raises(ValueError, match="eager.*lazy|lazy.*eager"):
        Graph(directory=fixture_dir, init="sometimes")


def test_mode_case_insensitive(fixture_dir):
    # the reference writes Local/Remote capitalized in configs
    g = Graph(config=f"mode=Local;directory={fixture_dir}")
    assert g.num_nodes == 7
    g.close()


def test_unknown_config_key_rejected(fixture_dir):
    with pytest.raises(ValueError, match="timout_ms"):
        Graph(config=f"directory={fixture_dir};timout_ms=20000")


def test_config_path_containing_equals(tmp_path, fixture_dir):
    d = tmp_path / "run=3"
    d.mkdir()
    p = d / "g.ini"
    p.write_text(f"directory = {fixture_dir}\n")
    g = Graph(config=str(p))  # existing path wins over inline parse
    assert g.num_nodes == 7
    g.close()


def test_config_list_values_strip_spaces(fixture_dir):
    import os

    files = ", ".join(
        os.path.join(fixture_dir, f)
        for f in sorted(os.listdir(fixture_dir))
        if f.endswith(".dat")
    )
    g = Graph(config=f"files={files}")
    assert g.num_nodes == 7
    g.close()


def test_use_after_close_raises(fixture_dir):
    g = Graph(directory=fixture_dir)
    g.close()
    with pytest.raises(RuntimeError, match="closed"):
        g.sample_node(1, -1)


def test_lazy_concurrent_first_use_connects_once(fixture_dir):
    import threading

    g = Graph(directory=fixture_dir, init="lazy")
    connects = []
    real = g._connect

    def counting():
        connects.append(1)
        real()

    g._connect = counting
    threads = [
        threading.Thread(target=lambda: g.sample_node(4, -1))
        for _ in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert connects == [1]
    g.close()


def test_rediscover_ms_via_config_file(tmp_path):
    """rediscover_ms rides the same config-file surface as every other
    client knob (and stays a known key, not a silently-dropped typo)."""
    from euler_tpu.graph.registry import RegistryServer
    from euler_tpu.graph.service import GraphService
    from tests.fixture_graph import write_fixture

    d = str(tmp_path / "g")
    import os

    os.makedirs(d)
    write_fixture(d, num_partitions=1)
    with RegistryServer() as reg, GraphService(d, 0, 1,
                                               registry=reg.address):
        p = tmp_path / "client.ini"
        p.write_text(
            f"mode = remote\nregistry = {reg.address}\n"
            "rediscover_ms = 0\n"      # explicit off through the file
        )
        g = Graph(config=str(p))
        assert g.num_shards == 1
        assert len(g.sample_node(4, -1)) == 4
        g.close()


def test_directory_and_files_together_rejected(fixture_dir):
    """files= next to directory= used to be silently ignored (the load
    dispatch consumes directory= first) — it must be a loud error, same
    principle as the stream=/remote rejection."""
    with pytest.raises(ValueError, match="not both"):
        Graph(directory=fixture_dir, files=[fixture_dir + "/part_0.dat"])
    # via config string too (the merge happens after config resolution)
    with pytest.raises(ValueError, match="not both"):
        Graph(config=f"directory={fixture_dir};files=a.dat,b.dat")


def test_fault_kwarg_rejected_on_local_graph(fixture_dir):
    """fault= names transport failpoints; a local graph has no transport,
    so accepting it would silently inject nothing."""
    with pytest.raises(ValueError, match="remote"):
        Graph(directory=fixture_dir, fault="dial:err@0.5")
