"""fsspec staging of remote graph directories (remote_fs.py).

The reference streams partitions off HDFS (euler/common/hdfs_file_io.cc);
here any fsspec URL is staged shard-aware to a local cache and loaded via
the native local path. fsspec's process-global memory:// filesystem stands
in for gs://--the staging code path is scheme-agnostic.
"""

import os

import fsspec
import numpy as np
import pytest

from euler_tpu.graph import remote_fs
from tests.fixture_graph import write_fixture


@pytest.fixture()
def mem_graph_url(tmp_path):
    """Fixture graph uploaded into the fsspec memory filesystem."""
    src = tmp_path / "src"
    src.mkdir()
    write_fixture(str(src), num_partitions=4)
    fs = fsspec.filesystem("memory")
    root = "memory://fixture_graph"
    for name in os.listdir(src):
        with open(src / name, "rb") as f:
            data = f.read()
        with fs.open(f"/fixture_graph/{name}", "wb") as f:
            f.write(data)
    with fs.open("/fixture_graph/meta.json", "wb") as f:
        f.write(b"{}")
    yield root
    fs.rm("/fixture_graph", recursive=True)


def test_is_remote_path():
    assert remote_fs.is_remote_path("gs://bucket/dir")
    assert remote_fs.is_remote_path("memory://x")
    assert not remote_fs.is_remote_path("/data/graph")
    assert not remote_fs.is_remote_path("file:///data/graph")


def test_partition_index_matches_native_rule():
    assert remote_fs.partition_index("part_3.dat") == 3
    assert remote_fs.partition_index("graph.dat") == -1
    assert remote_fs.partition_index("a_12.dat") == 12


def test_stage_directory_downloads_all(mem_graph_url, tmp_path):
    out = remote_fs.stage_directory(
        mem_graph_url, cache_dir=str(tmp_path / "cache")
    )
    names = sorted(os.listdir(out))
    assert names == [
        "meta.json", "part_0.dat", "part_1.dat", "part_2.dat", "part_3.dat"
    ]


def test_stage_directory_shard_selection(mem_graph_url, tmp_path):
    """Shard k stages exactly the partitions p % shard_num == k, the
    native Engine::Load rule."""
    out = remote_fs.stage_directory(
        mem_graph_url, cache_dir=str(tmp_path / "cache"),
        shard_idx=1, shard_num=2,
    )
    dats = sorted(n for n in os.listdir(out) if n.endswith(".dat"))
    assert dats == ["part_1.dat", "part_3.dat"]


def test_stage_is_idempotent_and_cached(mem_graph_url, tmp_path, monkeypatch):
    cache = str(tmp_path / "cache")
    out1 = remote_fs.stage_directory(mem_graph_url, cache_dir=cache)

    calls = []
    real = remote_fs._fetch

    def counting(fs, remote, local):
        calls.append(remote)
        return real(fs, remote, local)

    monkeypatch.setattr(remote_fs, "_fetch", counting)
    out2 = remote_fs.stage_directory(mem_graph_url, cache_dir=cache)
    assert out1 == out2
    assert calls == []  # everything already cached at the right size


def test_graph_loads_from_memory_url(mem_graph_url, tmp_path):
    import euler_tpu

    g = euler_tpu.Graph(
        directory=mem_graph_url, cache_dir=str(tmp_path / "cache")
    )
    assert g.num_nodes > 0
    ids = g.sample_node(16, -1)
    assert len(ids) == 16
    nbr, w, t = g.sample_neighbor(ids, [0, 1], 4)
    assert nbr.shape == (16, 4)
    g.close()


def test_graph_sharded_load_from_memory_url(tmp_path, mem_graph_url):
    """Two shards staged from the URL cover the whole graph disjointly."""
    import euler_tpu

    cache = str(tmp_path / "cache")
    g0 = euler_tpu.Graph(
        directory=mem_graph_url, shard_idx=0, shard_num=2, cache_dir=cache
    )
    g1 = euler_tpu.Graph(
        directory=mem_graph_url, shard_idx=1, shard_num=2, cache_dir=cache
    )
    full = euler_tpu.Graph(
        directory=mem_graph_url, cache_dir=cache
    )
    assert g0.num_nodes + g1.num_nodes == full.num_nodes
    for g in (g0, g1, full):
        g.close()


def test_stage_files_mixed_local_and_remote(mem_graph_url, tmp_path):
    local = str(tmp_path / "local.dat")
    open(local, "wb").close()
    out = remote_fs.stage_files(
        [local, mem_graph_url + "/part_0.dat"],
        cache_dir=str(tmp_path / "cache"),
    )
    assert out[0] == local
    assert os.path.exists(out[1])
    assert out[1].endswith("part_0.dat")


def test_missing_driver_error():
    with pytest.raises(RuntimeError, match="driver|fsspec"):
        remote_fs.stage_directory("definitelynotascheme9://bucket/x")


def test_file_scheme_loads_as_local(tmp_path):
    """file:// URLs are stripped to plain paths for the native loader."""
    import euler_tpu

    src = tmp_path / "g"
    src.mkdir()
    write_fixture(str(src), num_partitions=2)
    g = euler_tpu.Graph(directory=f"file://{src}")
    assert g.num_nodes > 0
    g.close()


def test_stage_files_refetches_on_size_change(mem_graph_url, tmp_path):
    cache = str(tmp_path / "cache")
    url = mem_graph_url + "/part_0.dat"
    (local,) = remote_fs.stage_files([url], cache_dir=cache)
    old = os.path.getsize(local)
    fs = fsspec.filesystem("memory")
    with fs.open("/fixture_graph/part_0.dat", "ab") as f:
        f.write(b"xxxx")
    (local2,) = remote_fs.stage_files([url], cache_dir=cache)
    assert local2 == local
    assert os.path.getsize(local2) == old + 4


def test_service_stages_remote_data_dir(mem_graph_url, tmp_path, monkeypatch):
    """A shard server given a remote data_dir stages it before loading
    (the shared multi-host mode of run_loop)."""
    import euler_tpu
    from euler_tpu.graph.service import GraphService

    monkeypatch.setenv("EULER_TPU_CACHE", str(tmp_path / "cache"))
    with GraphService(mem_graph_url, shard_idx=0, shard_num=1) as svc:
        g = euler_tpu.Graph(mode="remote", shards=[svc.address])
        assert g.num_nodes == 7
        g.close()


def test_stage_removes_files_gone_from_remote(mem_graph_url, tmp_path):
    """Repartitioned dataset at the same URL must not leave stale
    partitions mixed into the staged directory."""
    cache = str(tmp_path / "cache")
    out = remote_fs.stage_directory(mem_graph_url, cache_dir=cache)
    fs = fsspec.filesystem("memory")
    fs.rm("/fixture_graph/part_3.dat")
    out2 = remote_fs.stage_directory(mem_graph_url, cache_dir=cache)
    assert out2 == out
    dats = sorted(n for n in os.listdir(out2) if n.endswith(".dat"))
    assert dats == ["part_0.dat", "part_1.dat", "part_2.dat"]


def test_remote_mode_does_not_stage_directory(tmp_path, monkeypatch):
    """mode='remote' must not download directory= data it never reads."""
    import euler_tpu

    def boom(*a, **k):
        raise AssertionError("stage_directory called in remote mode")

    monkeypatch.setattr(remote_fs, "stage_directory", boom)
    with pytest.raises(RuntimeError):
        # fails on connecting to the bogus shard, NOT on staging
        euler_tpu.Graph(
            mode="remote",
            directory="memory://never-read",
            shards=["127.0.0.1:1"],
            retries=0,
            timeout_ms=50,
        )


def test_stream_load_zero_local_disk(mem_graph_url, tmp_path):
    """stream=True parses fetched bytes directly: no staging directory is
    ever created (the host needs RAM for the store, zero local disk)."""
    import euler_tpu

    cache = str(tmp_path / "never_created")
    g = euler_tpu.Graph(
        directory=mem_graph_url, stream=True, cache_dir=cache
    )
    assert g.num_nodes > 0
    assert not os.path.exists(cache)
    ids = g.sample_node(8, -1)
    nbr, w, t = g.sample_neighbor(ids, [0, 1], 4)
    assert nbr.shape == (8, 4)
    g.close()


def test_stream_load_equals_staged_load(mem_graph_url, tmp_path):
    """The streamed store is identical to the staged-then-loaded store:
    same nodes, same full neighbor lists, regardless of fetch order."""
    import numpy as np

    import euler_tpu

    gs = euler_tpu.Graph(directory=mem_graph_url, stream=True)
    gd = euler_tpu.Graph(
        directory=mem_graph_url, cache_dir=str(tmp_path / "cache")
    )
    assert gs.num_nodes == gd.num_nodes
    ids = np.arange(gs.num_nodes, dtype=np.uint64)
    for etypes in ([0], [1], [0, 1]):
        ns, ws, _, cs = gs.get_full_neighbor(ids, etypes)
        nd, wd, _, cd = gd.get_full_neighbor(ids, etypes)
        np.testing.assert_array_equal(cs, cd)
        np.testing.assert_array_equal(ns, nd)
        np.testing.assert_array_equal(ws, wd)
    gs.close()
    gd.close()


def test_stream_sharded_load(mem_graph_url):
    """Shard selection applies to streamed partitions exactly like
    staged ones: two shards cover the graph disjointly."""
    import euler_tpu

    g0 = euler_tpu.Graph(
        directory=mem_graph_url, stream=True, shard_idx=0, shard_num=2
    )
    g1 = euler_tpu.Graph(
        directory=mem_graph_url, stream=True, shard_idx=1, shard_num=2
    )
    full = euler_tpu.Graph(directory=mem_graph_url, stream=True)
    assert g0.num_nodes + g1.num_nodes == full.num_nodes
    for g in (g0, g1, full):
        g.close()


def test_stream_via_config_string(mem_graph_url):
    import euler_tpu

    g = euler_tpu.Graph(
        config=f"directory={mem_graph_url};stream=true"
    )
    assert g.num_nodes > 0
    g.close()


def test_stream_corrupt_buffer_names_partition(mem_graph_url):
    """A parse failure in a streamed buffer surfaces as a Python error
    naming the partition, never a crash across the C ABI."""
    import euler_tpu

    fs = fsspec.filesystem("memory")
    with fs.open("/fixture_graph/part_1.dat", "wb") as f:
        f.write(b"\x00\x01garbage-not-a-graph")
    with pytest.raises(RuntimeError, match="part_1.dat"):
        euler_tpu.Graph(directory=mem_graph_url, stream=True)


def test_suffixless_dat_belongs_to_shard_zero(tmp_path):
    """A .dat without the _<p> suffix is partition 0 under sharding —
    the native rule (eg_engine.cc Engine::Load) — in BOTH ingest modes,
    so a streamed or staged shard 0 matches the local loader exactly."""
    import euler_tpu
    from tests.fixture_graph import write_fixture

    src = tmp_path / "src"
    src.mkdir()
    write_fixture(str(src), num_partitions=2)
    fs = fsspec.filesystem("memory")
    os.rename(src / "part_0.dat", src / "plain.dat")  # suffix-less
    for name in os.listdir(src):
        with open(src / name, "rb") as f:
            data = f.read()
        with fs.open(f"/suffixless/{name}", "wb") as f:
            f.write(data)
    url = "memory://suffixless"
    try:
        local0 = euler_tpu.Graph(
            directory=str(src), shard_idx=0, shard_num=2
        )
        stream0 = euler_tpu.Graph(
            directory=url, stream=True, shard_idx=0, shard_num=2
        )
        staged0 = euler_tpu.Graph(
            directory=url, shard_idx=0, shard_num=2,
            cache_dir=str(tmp_path / "cache"),
        )
        stream1 = euler_tpu.Graph(
            directory=url, stream=True, shard_idx=1, shard_num=2
        )
        assert stream0.num_nodes == local0.num_nodes
        assert staged0.num_nodes == local0.num_nodes
        full = euler_tpu.Graph(directory=url, stream=True)
        assert stream0.num_nodes + stream1.num_nodes == full.num_nodes
        full.close()
        for g in (local0, stream0, staged0, stream1):
            g.close()
    finally:
        fs.rm("/suffixless", recursive=True)


def test_stream_explicit_file_list(mem_graph_url, tmp_path):
    """files= + stream=True fetches each file's bytes directly (no
    staging copy) and builds the same store as the staged path."""
    import euler_tpu

    urls = [mem_graph_url + f"/part_{i}.dat" for i in range(4)]
    cache = str(tmp_path / "never_created")
    gs = euler_tpu.Graph(files=urls, stream=True, cache_dir=cache)
    gd = euler_tpu.Graph(files=urls, cache_dir=str(tmp_path / "cache"))
    assert not os.path.exists(cache)
    assert gs.num_nodes == gd.num_nodes
    assert gs.num_edges == gd.num_edges
    gs.close()
    gd.close()


def test_read_files_rejects_duplicate_urls(tmp_path):
    """Duplicate URLs would reach the native name-sorted merge as equal
    keys (unspecified relative order => nondeterministic store); both
    the streamed and staged list paths must refuse them up front."""
    import pytest

    from euler_tpu.graph import remote_fs

    f = tmp_path / "a.dat"
    f.write_bytes(b"x")
    with pytest.raises(ValueError, match="duplicate"):
        remote_fs.read_files([str(f), str(f)])
    with pytest.raises(ValueError, match="duplicate"):
        remote_fs.stage_files([str(f), str(f)])
    # unique lists still pass straight through
    assert remote_fs.stage_files([str(f)]) == [str(f)]
