"""Real-dataset preparation on miniature fixture files.

prepare_ppi / prepare_reddit are the transform halves of the reference's
examples/ppi_data.py:40-175 and reddit_data.py:42-135 (download step
dropped): GraphSAGE node-link JSON / DGL npz on disk -> .dat partitions +
split id files. These tests build tiny inputs in the exact source formats
and verify the loaded graph's types, adjacency, labels, and normalized
features against hand-computed values.
"""

import json
import os

import numpy as np
import pytest

from euler_tpu.datasets import prepare_ppi, prepare_reddit

# miniature PPI in GraphSAGE release format: 6 nodes (4 train, 1 val,
# 1 test), labels are 3-dim multilabel vectors
PPI_NODES = [
    {"id": 0, "val": False, "test": False},
    {"id": 1, "val": False, "test": False},
    {"id": 2, "val": False, "test": False},
    {"id": 3, "val": False, "test": False},
    {"id": 4, "val": True, "test": False},
    {"id": 5, "val": False, "test": True},
]
# links are INDICES into the nodes array (networkx 1.x node_link_data)
PPI_LINKS = [
    {"source": 0, "target": 1},
    {"source": 1, "target": 2},
    {"source": 2, "target": 3},
    {"source": 3, "target": 4},   # touches val -> train_removed
    {"source": 0, "target": 5},   # touches test -> train_removed
]
PPI_CLASSES = {str(i): [float(i % 2), 1.0, 0.0] for i in range(6)}


@pytest.fixture()
def ppi_prefix(tmp_path):
    prefix = str(tmp_path / "ppi")
    with open(prefix + "-G.json", "w") as f:
        json.dump({"nodes": PPI_NODES, "links": PPI_LINKS}, f)
    rng = np.random.default_rng(0)
    np.save(prefix + "-feats.npy", rng.standard_normal((6, 4)))
    with open(prefix + "-id_map.json", "w") as f:
        json.dump({str(i): i for i in range(6)}, f)
    with open(prefix + "-class_map.json", "w") as f:
        json.dump(PPI_CLASSES, f)
    return prefix


def test_prepare_ppi(ppi_prefix, tmp_path):
    import euler_tpu

    out = prepare_ppi(ppi_prefix, str(tmp_path / "out"), num_partitions=2)
    g = euler_tpu.Graph(directory=out)
    assert g.num_nodes == 6
    # node types: 4 train, 1 val, 1 test
    types = g.node_types(np.arange(6))
    assert list(types) == [0, 0, 0, 0, 1, 2]
    # edge typing: 1<->2 is train (type 0); 3<->4 and 0<->5 train_removed
    nbr, _, _, counts = g.get_full_neighbor([1], [0])
    assert set(nbr.tolist()) == {0, 2}
    nbr, _, _, _ = g.get_full_neighbor([3], [1])
    assert set(nbr.tolist()) == {4}
    nbr, _, _, _ = g.get_full_neighbor([0], [1])
    assert set(nbr.tolist()) == {5}
    # labels in slot 0
    labels = g.get_dense_feature([2, 3], [0], [3])
    np.testing.assert_allclose(labels[0], [0.0, 1.0, 0.0])
    np.testing.assert_allclose(labels[1], [1.0, 1.0, 0.0])
    # features standardized by TRAIN-split stats: train rows of the
    # transformed matrix must have mean ~0 / std ~1
    feats = g.get_dense_feature(np.arange(4), [1], [4])
    np.testing.assert_allclose(feats.mean(axis=0), 0.0, atol=1e-6)
    np.testing.assert_allclose(feats.std(axis=0), 1.0, atol=1e-6)
    # split id files
    train = np.loadtxt(os.path.join(out, "train.id"), dtype=np.int64)
    assert list(train) == [0, 1, 2, 3]
    g.close()


def test_prepare_ppi_drops_unannotated(ppi_prefix, tmp_path):
    """Nodes without val/test attrs are dropped like the reference's
    networkx-workaround loop (ppi_data.py:67-74)."""
    with open(ppi_prefix + "-G.json") as f:
        g_data = json.load(f)
    g_data["nodes"].append({"id": 6})  # no annotations
    with open(ppi_prefix + "-G.json", "w") as f:
        json.dump(g_data, f)
    # extend the side arrays so indices stay valid
    feats = np.load(ppi_prefix + "-feats.npy")
    np.save(ppi_prefix + "-feats.npy",
            np.vstack([feats, np.zeros((1, 4))]))
    with open(ppi_prefix + "-id_map.json", "w") as f:
        json.dump({str(i): i for i in range(7)}, f)
    with open(ppi_prefix + "-class_map.json", "w") as f:
        json.dump({**PPI_CLASSES, "6": [0.0, 0.0, 0.0]}, f)

    import euler_tpu

    out = prepare_ppi(ppi_prefix, str(tmp_path / "out2"))
    g = euler_tpu.Graph(directory=out)
    assert g.num_nodes == 6  # node 6 dropped
    g.close()


def test_prepare_reddit(tmp_path):
    import scipy.sparse as sp

    import euler_tpu

    # miniature DGL-format reddit: 5 nodes, ring adjacency + self loops
    n = 5
    rows, cols = [], []
    for i in range(n):
        for j in (i, (i + 1) % n, (i - 1) % n):
            rows.append(i)
            cols.append(j)
    adj = sp.coo_matrix(
        (np.ones(len(rows)), (rows, cols)), shape=(n, n)
    ).tocsr()
    src = str(tmp_path / "src")
    os.makedirs(src)
    sp.save_npz(os.path.join(src, "reddit_self_loop_graph.npz"), adj)
    rng = np.random.default_rng(1)
    np.savez(
        os.path.join(src, "reddit_data.npz"),
        feature=rng.standard_normal((n, 8)).astype(np.float32),
        node_ids=np.arange(n),
        label=np.array([0, 3, 1, 3, 2]),
        node_types=np.array([1, 1, 2, 3, 1]),  # 1-based in DGL dump
    )

    out = prepare_reddit(src, str(tmp_path / "out"), num_partitions=2)
    g = euler_tpu.Graph(directory=out)
    assert g.num_nodes == n
    assert list(g.node_types(np.arange(n))) == [0, 0, 1, 2, 0]
    # ring + self loop adjacency preserved
    nbr, _, _, _ = g.get_full_neighbor([1], [0])
    assert set(nbr.tolist()) == {0, 1, 2}
    # labels one-hot over max(label)+1 = 4 classes
    labels = g.get_dense_feature([1, 4], [0], [4])
    np.testing.assert_allclose(labels[0], [0, 0, 0, 1.0])
    np.testing.assert_allclose(labels[1], [0, 0, 1.0, 0])
    val = np.loadtxt(os.path.join(out, "val.id"), dtype=np.int64)
    assert val == 2
    g.close()


@pytest.mark.slow
def test_ppi_dress_rehearsal_at_scale(tmp_path):
    """The full real-data pipeline — GraphSAGE-release-format files ->
    prepare_ppi -> ppi_main training -> id-file evaluation — at a scale
    past the miniature fixtures (thousands of nodes, tens of thousands
    of links, both partitions populated). The full 56944-node run is
    recorded in README; this keeps the path regression-tested in the
    suite (~15 s)."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    import ppi_dress_rehearsal as rehearsal

    # the real recipe's batch/dim (reference examples/sage.py:80-98):
    # at dim 32 the 121 independent label functions can't be represented
    # and val F1 plateaus a hair above the trivial baseline; at the
    # recipe's dim 256 the gate clears by ~0.18 in 25 steps
    summary = rehearsal.run(
        num_nodes=3000, num_links=40000, epochs=5, batch_size=512,
        dim=256, workdir=str(tmp_path),
    )
    assert summary["train_rc"] == 0
    assert summary["evaluate_rc"] == 0
    s = summary["splits"]
    assert s["train"] > s["val"] > 0 and s["test"] > 0
    # learning gate (VERDICT r3 next-#6): replica labels are a linear
    # function of the features, so the trained model's val micro-F1 must
    # clear the best label-marginal-only predictor (all-positive,
    # 2p/(1+p) — computed from the written labels, not folklore) by a
    # real margin. The recorded full-size run reached 0.919.
    val_f1 = summary["val_metrics"]["f1"]
    assert val_f1 > s["allpos_f1"] + 0.1, (
        f"val micro-F1 {val_f1:.3f} vs all-positive baseline "
        f"{s['allpos_f1']:.3f}: prepare->train->evaluate is not learning"
    )


@pytest.mark.slow
def test_reddit_dress_rehearsal_at_scale(tmp_path):
    """DGL-npz-format files -> prepare_reddit -> reddit_main training ->
    id-file evaluation, past the miniature fixtures (thousands of nodes,
    602-dim features come from the full-size run recorded in README)."""
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "scripts",
    ))
    import reddit_dress_rehearsal as rehearsal

    # 20k nodes: below ~15k the ~66%-train split cannot identify the
    # 602-dim x 41-class label map (a dim-64 net interpolates the train
    # nodes without generalizing — val stays at chance while train F1
    # climbs); at 20k / 3 epochs val clears majority-chance ~5x
    summary = rehearsal.run(
        num_nodes=20000, avg_degree=10, epochs=3, batch_size=200,
        workdir=str(tmp_path),
    )
    assert summary["train_rc"] == 0
    assert summary["evaluate_rc"] == 0
    s = summary["splits"]
    assert s["train"] > s["test"] > s["val"] > 0
    # learning gate (VERDICT r3 next-#6): 41-class labels are argmax of
    # a linear map of the features; the val metric must clear the
    # majority-class baseline (computed from the written labels) by a
    # real margin. The recorded full-size run reached 0.409 vs 0.024
    # chance after one epoch.
    val_metric = summary["val_metrics"]["f1"]
    assert val_metric > s["majority_acc"] + 0.1, (
        f"val metric {val_metric:.3f} vs majority-class baseline "
        f"{s['majority_acc']:.3f}: prepare->train->evaluate is not "
        "learning"
    )
