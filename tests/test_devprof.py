"""eg_devprof tier-1 pins: exact recompile arithmetic under injected
shape drift, kill-switch silence, the serve compile-storm guard
(counter + strict raise, on a live micro-batched drill), transfer-byte
counters, device-memory gauges, the merged host+device trace export,
and the metrics_text families.

Counter discipline: ``device_compiles`` is GLOBAL (auxiliary compiles
— a stray jnp.ones — bump it too), so tests pin the per-watched-
function ``device_recompiles`` arithmetic exactly and only assert
monotonicity for the global count."""

import numpy as np
import pytest

from euler_tpu import devprof
from euler_tpu.graph import native


@pytest.fixture(autouse=True)
def _clean_slate():
    from euler_tpu.telemetry import set_telemetry, telemetry_reset

    devprof.install()
    native.reset_counters()
    telemetry_reset()
    devprof.devprof_reset()
    set_telemetry(True)
    devprof.set_devprof(True)
    yield
    native.reset_counters()
    telemetry_reset()
    devprof.devprof_reset()
    set_telemetry(True)
    devprof.set_devprof(True)


def _counters():
    return native.counters()


# ------------------------------------------------------- recompile ledger


def test_recompile_exact_arithmetic_under_shape_drift():
    import jax
    import jax.numpy as jnp

    step = devprof.watch(
        jax.jit(lambda x: (x * 2.0).sum()), name="drift_step"
    )
    x = jnp.ones((8, 2), jnp.float32)
    step(x).block_until_ready()  # warmup compile: NOT a recompile
    step(x).block_until_ready()  # in-bucket: no compile at all
    assert _counters()["device_recompiles"] == 0
    assert devprof.recompile_ledger() == []

    # injected drift: off-bucket batch -> exactly ONE journaled recompile
    step(jnp.ones((5, 2), jnp.float32)).block_until_ready()
    assert _counters()["device_recompiles"] == 1
    led = devprof.recompile_ledger()
    assert len(led) == 1
    assert led[0]["fn"] == "drift_step"
    assert led[0]["diff"] == ["leaf0: (8, 2) float32 -> (5, 2) float32"]

    # the drifted shape is now cached: repeating it compiles nothing
    step(jnp.ones((5, 2), jnp.float32)).block_until_ready()
    assert _counters()["device_recompiles"] == 1
    assert len(devprof.recompile_ledger()) == 1


def test_dtype_drift_is_attributed():
    import jax
    import jax.numpy as jnp

    step = devprof.watch(jax.jit(lambda x: x.sum()), name="dtype_step")
    step(jnp.ones((4,), jnp.float32)).block_until_ready()
    step(jnp.ones((4,), jnp.int32)).block_until_ready()
    led = devprof.recompile_ledger()
    assert len(led) == 1
    assert led[0]["diff"] == ["leaf0: (4,) float32 -> (4,) int32"]


def test_compile_counters_and_histogram_flow():
    import jax
    import jax.numpy as jnp

    from euler_tpu import telemetry as T

    before = _counters()["device_compiles"]
    f = devprof.watch(jax.jit(lambda x: x + 1), name="hist_step")
    f(jnp.ones((3, 3))).block_until_ready()
    data = T.telemetry_json()
    assert _counters()["device_compiles"] > before
    h = data["hist"].get("phase:compile")
    assert h is not None and h["count"] >= 1
    s = devprof.compile_summary(data)
    assert s["compile_events"] >= 1 and s["compile_ms_total"] > 0


def test_strict_raises_after_computing():
    import jax
    import jax.numpy as jnp

    step = devprof.watch(
        jax.jit(lambda x: x.sum()), name="strict_step", strict=True
    )
    step(jnp.ones((6,))).block_until_ready()
    with pytest.raises(devprof.RecompileError, match="strict_step"):
        step(jnp.ones((7,)))
    # the breach was still counted + journaled before the raise
    assert _counters()["device_recompiles"] == 1
    assert devprof.recompile_ledger()[0]["fn"] == "strict_step"


def test_mark_warm_declares_out_of_band_warmup():
    import jax
    import jax.numpy as jnp

    step = devprof.watch(jax.jit(lambda x: x * x), name="warm_step")
    step.mark_warm()
    # first tracked call compiles, but warmup was declared done -> it
    # journals as a recompile (the serve out-of-band warmup contract)
    step(jnp.ones((2, 2))).block_until_ready()
    assert _counters()["device_recompiles"] == 1


# ----------------------------------------------------------- kill-switch


def test_killswitch_writes_nothing():
    import jax
    import jax.numpy as jnp

    devprof.set_devprof(False)
    step = devprof.watch(jax.jit(lambda x: x - 1), name="off_step")
    step(jnp.ones((4,))).block_until_ready()
    step(jnp.ones((9,))).block_until_ready()  # would be a recompile
    c = _counters()
    assert c["device_compiles"] == 0
    assert c["device_recompiles"] == 0
    assert devprof.recompile_ledger() == []
    assert devprof.count_h2d(jnp.ones((16,))) == 0
    assert devprof.count_d2h(jnp.ones((16,))) == 0
    assert c["h2d_bytes"] == 0 and c["d2h_bytes"] == 0
    assert devprof.sample_device_mem() == (0, 0)


# ------------------------------------------------- transfers and memory


def test_transfer_byte_arithmetic():
    import jax.numpy as jnp

    batch = {"a": jnp.ones((8, 4), jnp.float32),
             "b": jnp.ones((8,), jnp.int32)}
    n = devprof.count_h2d(batch)
    assert n == 8 * 4 * 4 + 8 * 4
    assert _counters()["h2d_bytes"] == n
    m = devprof.count_d2h(batch["a"])
    assert m == 8 * 4 * 4
    assert _counters()["d2h_bytes"] == m


def test_device_mem_gauges_reach_resource_section():
    import jax.numpy as jnp

    from euler_tpu import telemetry as T

    keep = jnp.ones((128, 64), jnp.float32)  # held ref -> census sees it
    nbytes, buffers = devprof.sample_device_mem()
    assert nbytes >= keep.nbytes and buffers >= 1
    res = T.telemetry_json()["resource"]
    assert res["device_mem_bytes"] == nbytes
    assert res["device_mem_peak_bytes"] >= nbytes
    assert res["device_buffers"] == buffers
    # peak is monotone: a smaller re-sample must not lower it
    native.lib().eg_devprof_set_mem(1, 1)
    res2 = T.telemetry_json()["resource"]
    assert res2["device_mem_bytes"] == 1
    assert res2["device_mem_peak_bytes"] >= nbytes
    # telemetry_reset clears the gauges (fresh run = fresh high-water)
    T.telemetry_reset()
    res3 = T.telemetry_json()["resource"]
    assert res3["device_mem_peak_bytes"] == 0


# ------------------------------------------------------ serve guard drill


def _sage():
    from euler_tpu.models import SupervisedGraphSage

    return SupervisedGraphSage(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]],
        fanouts=[3, 2], dim=8, feature_idx=0, feature_dim=2, max_id=16,
    )


def _server(graph, **kw):
    import jax

    from euler_tpu.serve import EmbedServer
    from euler_tpu.train import get_optimizer

    model = _sage()
    state = model.init_state(
        jax.random.PRNGKey(3), graph, np.arange(8),
        get_optimizer("adam", 0.01),
    )
    return EmbedServer(
        model, graph, state, max_batch=8, max_wait_us=2000,
        queue_cap=16, slo_ms=500.0, **kw,
    ).start()


def test_serve_bucket_contract_holds_and_guard_counts(graph):
    srv = _server(graph)
    try:
        srv.embed([1, 2, 3])  # warmup: ONE compile of the padded bucket
        srv.embed([4])
        srv.embed([5, 6, 7, 8, 9])
        c = _counters()
        assert c["serve_recompiles"] == 0  # fixed bucket: no recompiles
        # live drill: break the bucket contract -> BOTH counters fire
        # and the journal names the serve forward with the shape diff
        srv.max_batch = 4
        srv.embed([10, 11])
        c = _counters()
        assert c["serve_recompiles"] == 1
        assert c["device_recompiles"] == 1
        led = devprof.recompile_ledger()
        assert led and led[-1]["fn"] == "embed_step"
        assert any("8," in d and "4," in d for d in led[-1]["diff"])
        assert srv.stats()["devprof"]["serve_recompiles"] == 1
    finally:
        srv.close()


def test_serve_strict_bucket_raises_on_live_drill(graph):
    srv = _server(graph, strict_bucket=True)
    try:
        srv.embed([1, 2])  # warmup
        srv.max_batch = 4  # bucket contract broken
        with pytest.raises(devprof.RecompileError, match="embed_step"):
            srv.embed([3])
        assert _counters()["serve_recompiles"] == 1
    finally:
        srv.close()


def test_serve_slo_gauges_render(graph):
    from euler_tpu import telemetry as T

    srv = _server(graph)
    try:
        srv.embed([1, 2, 3])
        srv.slo.push_gauges()
        slo = T.telemetry_json()["serve_slo"]
        assert slo["count"] >= 1
        assert slo["p99_us"] >= slo["p50_us"] > 0
        text = T.metrics_text()
        assert 'eg_serve_slo_ms{quantile="p50"}' in text
        assert 'eg_serve_slo_ms{quantile="p99"}' in text
        assert "eg_serve_slo_violations_total" in text
    finally:
        srv.close()


# --------------------------------------------------- merged trace export


def test_merged_trace_has_aligned_device_lanes(tmp_path):
    import jax
    import jax.numpy as jnp

    from euler_tpu import trace as trace_mod

    f = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((128, 128), jnp.float32)
    f(x).block_until_ready()  # compile outside the capture

    from euler_tpu.telemetry import record_phase

    rec = trace_mod.TraceRecorder().start()
    prof = str(tmp_path / "prof")
    t0 = trace_mod.now_us()
    jax.profiler.start_trace(prof)
    with trace_mod.align_annotation():
        pass
    for step in range(2):
        import time as _time

        t_dev = _time.perf_counter()
        jax.block_until_ready(f(x))
        record_phase("device", (_time.perf_counter() - t_dev) * 1e6,
                     step=step)
    jax.profiler.stop_trace()
    t1 = trace_mod.now_us()
    rec.stop()

    out = str(tmp_path / "trace.json")
    trace = trace_mod.write_trace(out, rec, profile_dir=prof)
    events = trace_mod.validate_chrome_trace(trace)
    dev = [e for e in events if e.get("cat") == "device"
           and e.get("ph") == "X"]
    host = [e for e in events if e.get("cat") == "phase"
            and e["name"] == "device"]
    assert dev and host
    assert all(e["pid"] >= trace_mod.PID_DEVICE_BASE for e in dev)
    assert all(e["pid"] == trace_mod.PID_TRAIN for e in host)
    # time alignment: every device slice falls inside the capture
    # window on the HOST clock (the eg_align marker did its job —
    # unaligned profiler timestamps sit ~minutes off)
    pad = 2_000_000
    assert all(t0 - pad <= e["ts"] <= t1 + pad for e in dev), dev[:3]
    # and the kernel slices overlap the host device-phase slices
    lo = min(e["ts"] for e in host)
    hi = max(e["ts"] + e["dur"] for e in host)
    assert any(lo - pad <= e["ts"] <= hi + pad for e in dev)


def test_ingest_missing_or_unstamped_dir(tmp_path):
    from euler_tpu import trace as trace_mod

    assert trace_mod.ingest_profiler_dir(str(tmp_path / "nope")) == []


# --------------------------------------------------------- config surface


def test_devprof_config_key_local_mode(fixture_dir):
    import euler_tpu

    g = euler_tpu.Graph(directory=fixture_dir, devprof="0")
    try:
        assert devprof.devprof_enabled() is False
    finally:
        devprof.set_devprof(True)
        g.close()
    g = euler_tpu.Graph(directory=fixture_dir, devprof="1")
    try:
        assert devprof.devprof_enabled() is True
    finally:
        g.close()


def test_compile_summary_keys():
    s = devprof.compile_summary()
    for k in ("compiles", "recompiles", "serve_recompiles",
              "compile_events", "compile_ms_total", "compile_ms_p50",
              "compile_ms_p99", "h2d_bytes", "d2h_bytes",
              "device_mem_bytes", "device_mem_peak_bytes",
              "device_buffers"):
        assert k in s, k
