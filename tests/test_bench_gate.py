"""bench.py robustness units: the plausibility gate and the killable
backend probe. These are the driver-facing contracts (BENCH_r{N}.json is
recorded unattended), so they get their own tests even though bench.py
is a script, not part of the package.
"""

import importlib.util
import os

import numpy as np
import pytest

_BENCH_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bench.py"
)


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", _BENCH_PY
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_implausible_rejects_wedged_timings(bench):
    # the observed wedge: 2.3 us/step "measured" while the backend was
    # completing dispatches without executing them
    assert bench._implausible(0.0023, 0.5)
    assert bench._implausible(0.0, 0.5)


def test_implausible_rejects_garbage_losses(bench):
    assert bench._implausible(1.0, float("nan"))
    assert bench._implausible(1.0, np.asarray([0.1, np.inf]))


def test_implausible_accepts_real_measurements(bench):
    # the empty-body scan floor (0.133 ms) and real step times pass
    assert bench._implausible(0.133, 0.5) is None
    assert bench._implausible(1.27, np.asarray([0.7])) is None
    assert bench._implausible(28.6, 0.69) is None  # CPU-fallback step


def test_probe_backend_kills_hung_init(bench, monkeypatch):
    """A backend init that hangs must be killed at the timeout and
    reported, never block the bench process."""
    from euler_tpu.parallel import mesh

    monkeypatch.setattr(
        mesh, "_PROBE_SRC", "import time; time.sleep(60)"
    )
    platform, err = bench.probe_backend(
        attempts=2, timeout_s=0.5, backoff_s=0.0
    )
    assert platform is None
    assert "timed out" in err and "attempt 2" in err


def test_probe_backend_reports_failing_init(bench, monkeypatch):
    from euler_tpu.parallel import mesh

    monkeypatch.setattr(
        mesh, "_PROBE_SRC", "import sys; sys.exit(3)"
    )
    platform, err = bench.probe_backend(
        attempts=1, timeout_s=10.0, backoff_s=0.0
    )
    assert platform is None and "rc=3" in err


def test_probe_backend_returns_platform(bench, monkeypatch):
    from euler_tpu.parallel import mesh

    monkeypatch.setattr(mesh, "_PROBE_SRC", "print('cpu')")
    platform, err = bench.probe_backend(
        attempts=1, timeout_s=30.0, backoff_s=0.0
    )
    assert platform == "cpu" and err is None


def test_watchdog_emits_json_on_hang():
    """A wedged backend after a successful probe blocks the process in a
    C-level wait; the watchdog thread must still print the
    driver-parseable failure line and hard-exit."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ, EULER_TPU_BENCH_DEADLINE="2", JAX_PLATFORMS="")
    r = subprocess.run(
        [sys.executable, _BENCH_PY, "--probe-attempts", "1",
         "--probe-timeout", "5", "--configs", "ppi"],
        capture_output=True, text=True, timeout=90, env=env,
        cwd=os.path.dirname(_BENCH_PY),
    )
    assert r.returncode == 2
    j = json.loads(r.stdout.strip().splitlines()[-1])
    assert "watchdog" in j["error"] and j["value"] == 0.0


def test_probe_or_die_fails_fast_and_reprobes(monkeypatch):
    """probe_backend_or_die: comma-list platforms with a TPU first still
    probe; a FAILED probe is not cached (callers can re-check after the
    relay recovers); explicit-CPU runs skip instantly."""
    import pytest as _pytest

    from euler_tpu.parallel import mesh

    monkeypatch.setattr(mesh, "_probed_ok", False)
    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    monkeypatch.setattr(mesh, "_PROBE_SRC", "import time; time.sleep(60)")
    with _pytest.raises(RuntimeError, match="unreachable"):
        mesh.probe_backend_or_die(timeout_s=0.5)
    monkeypatch.setattr(mesh, "_PROBE_SRC", "print('tpu')")
    mesh.probe_backend_or_die(timeout_s=30)  # re-probes, now passes
    assert mesh._probed_ok
    monkeypatch.setattr(mesh, "_probed_ok", False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(mesh, "_PROBE_SRC", "import time; time.sleep(60)")
    mesh.probe_backend_or_die(timeout_s=0.5)  # skipped: CPU-pinned
    assert not mesh._probed_ok


def test_bank_write_atomic(bench, tmp_path):
    p = str(tmp_path / "x.json")
    bench._bank_write(p, {"a": 1})
    bench._bank_write(p, {"a": 2})
    import json

    assert json.load(open(p)) == {"a": 2}
    assert not os.path.exists(p + ".tmp")


def test_spawn_config_banks_child_failure_as_final(bench, tmp_path):
    """The child process banks even its failure line (marked final), so
    the parent distinguishes 'config failed' from 'child wedged before
    banking anything'."""
    r, timed_out = bench._spawn_config(
        "no_such_config", "cpu", 120.0, str(tmp_path), None
    )
    assert r is not None and not timed_out
    assert r["value"] == 0.0 and "KeyError" in r["error"]
    assert r["detail"]["banked"] == "final"


def test_spawn_config_kills_wedged_child(bench, tmp_path):
    """A child that banks nothing within its deadline is SIGKILLed and
    reported as None — the parent's cue to fall back / move on (the
    round-4 wedge mode: successful probe, then a blocked backend init
    eating the whole window)."""
    t0 = __import__("time").monotonic()
    r, timed_out = bench._spawn_config("ppi", "cpu", 3.0, str(tmp_path), None)
    dt = __import__("time").monotonic() - t0
    assert r is None and timed_out
    assert dt < 30, f"kill took {dt:.0f}s"


def test_heavytail_config_has_no_shape_literals(bench):
    """The reddit_heavytail graph shape comes from
    datasets.REDDIT_HEAVYTAIL at run time (run_config merges it in); a
    shape literal re-appearing in CONFIGS would shadow the authoritative
    constant, silently invalidate the shared ~2 GB cache, and measure a
    different graph than PERF.md describes."""
    from euler_tpu.datasets import REDDIT_HEAVYTAIL

    cfg = bench.CONFIGS["reddit_heavytail"]
    assert cfg.get("powerlaw") and cfg.get("alias_sampling")
    overlap = set(cfg) & set(REDDIT_HEAVYTAIL)
    assert not overlap, f"shape keys must live in datasets only: {overlap}"
    # and the merge supplies everything run_config's build needs
    merged = {**cfg, **REDDIT_HEAVYTAIL}
    for key in ("num_nodes", "num_edges", "feature_dim", "label_dim",
                "alpha", "multilabel", "batch", "fanouts", "dim", "lr",
                "warmup", "measure"):
        assert key in merged, key


def test_default_configs_gated_on_heavytail_cache(bench, tmp_path,
                                                  monkeypatch):
    """The no-flag config list includes the 113.7M-edge flagship ONLY
    when its cache is finished with current params — an absent cache
    must never trigger an implicit multi-minute rebuild mid-window."""
    monkeypatch.setenv("EULER_TPU_HEAVYTAIL_CACHE", str(tmp_path / "no"))
    assert bench.default_configs() == "reddit,ppi"

    from euler_tpu.datasets import (
        REDDIT_HEAVYTAIL, heavytail_cache_dir, powerlaw_cache_ready,
    )

    real = os.path.join(os.path.dirname(_BENCH_PY), ".data", "reddit_ht")
    monkeypatch.setenv("EULER_TPU_HEAVYTAIL_CACHE", real)
    if powerlaw_cache_ready(heavytail_cache_dir(), **REDDIT_HEAVYTAIL):
        assert bench.default_configs() == "reddit_heavytail,reddit,ppi"
    else:
        assert bench.default_configs() == "reddit,ppi"
