"""Model-zoo training smoke + semantics tests on the fixture graph.

Every model must: train N steps with finite loss, produce a sane metric,
and (where meaningful) export embeddings. Mirrors the reference's model
dispatch coverage (reference tf_euler/python/run_loop.py:222-354).
"""

import numpy as np
import pytest

from euler_tpu import train as train_lib


def _run(model, graph, steps=10, batch=16, lr=0.02, **kw):
    def source_fn(step):
        return graph.sample_node(batch, -1)

    state, history = train_lib.train(
        model, graph, source_fn, num_steps=steps, learning_rate=lr,
        log_every=max(steps // 2, 1), **kw
    )
    assert history, "no history logged"
    for h in history:
        assert np.isfinite(h["loss"]), history
    return state, history


def test_line_first_and_second_order(graph):
    from euler_tpu.models import LINE

    for order in (1, 2):
        model = LINE(
            node_type=-1, edge_type=[0, 1], max_id=16, dim=8, order=order,
            num_negs=4,
        )
        state, hist = _run(model, graph)
        assert 0 < hist[-1]["mrr"] <= 1.0
        emb = train_lib.save_embedding(model, graph, 16, state, batch_size=8)
        assert emb.shape == (17, 8)
    # first-order LINE shares target/context towers; second-order does not
    m1 = LINE(node_type=-1, edge_type=[0], max_id=16, dim=8, order=1)
    m2 = LINE(node_type=-1, edge_type=[0], max_id=16, dim=8, order=2)
    import jax

    p1 = m1.module.init(jax.random.PRNGKey(0), m1.sample(graph, [10, 11]))
    p2 = m2.module.init(jax.random.PRNGKey(0), m2.sample(graph, [10, 11]))
    assert "context" not in p1["params"]
    assert "context" in p2["params"]


def test_node2vec(graph):
    from euler_tpu.models import Node2Vec

    model = Node2Vec(
        node_type=-1, edge_type=[0, 1], max_id=16, dim=8,
        walk_len=3, walk_p=2.0, walk_q=0.5, num_negs=3,
    )
    # pair count per root for walk_len 3 (path len 4), windows 1/1 -> 6
    assert model.batch_size_ratio == 6
    state, hist = _run(model, graph, batch=8)
    assert 0 < hist[-1]["mrr"] <= 1.0


def test_supervised_gcn(graph):
    from euler_tpu.models import SupervisedGCN

    # use_id gives the encoder memorization capacity (the fixture's dense
    # features are deliberately low-rank), so the toy labels are learnable.
    model = SupervisedGCN(
        label_idx=2, label_dim=3, metapath=[[0, 1], [0, 1]], dim=8,
        max_nodes_per_hop=[16, 16], max_edges_per_hop=[64, 64],
        feature_idx=0, feature_dim=2, max_id=16, use_id=True,
    )
    state, hist = _run(model, graph, steps=80, lr=0.02)
    assert 0.0 <= hist[-1]["f1"] <= 1.0
    # full-neighbor GCN must learn the toy labels: last-window f1 clearly
    # above the first window's
    assert hist[-1]["f1"] > hist[0]["f1"] + 0.05


def test_scalable_gcn_stores_update(graph):
    from euler_tpu.models import ScalableGCN

    model = ScalableGCN(
        label_idx=2, label_dim=3, edge_type=[0, 1], num_layers=2, dim=8,
        max_id=16, max_neighbors=16, feature_idx=0, feature_dim=2,
    )
    opt = train_lib.get_optimizer("adam", 0.02)
    import jax

    state = model.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(8, -1), opt
    )
    stores_before = np.asarray(state["stores"][0]).copy()
    state, hist = _run(model, graph, steps=12, batch=8, state=state)
    assert 0.0 <= hist[-1]["f1"] <= 1.0
    stores_after = np.asarray(state["stores"][0])
    # write-back must have changed visited rows
    assert not np.allclose(stores_before, stores_after)
    # gradient stores accumulate at neighbor rows then clear at node rows;
    # after steps they should not be all-zero in general
    assert np.isfinite(stores_after).all()


def test_scalable_sage(graph):
    from euler_tpu.models import ScalableSage

    model = ScalableSage(
        label_idx=2, label_dim=3, edge_type=[0, 1], fanout=4, num_layers=2,
        dim=8, max_id=16, feature_idx=0, feature_dim=2,
    )
    state, hist = _run(model, graph, steps=12, batch=8)
    assert 0.0 <= hist[-1]["f1"] <= 1.0
    res = train_lib.evaluate(
        model, graph, [graph.sample_node(8, -1)], state
    )
    assert np.isfinite(res["loss"])


def test_gat(graph):
    from euler_tpu.models import GAT

    model = GAT(
        label_idx=2, label_dim=3, feature_idx=0, feature_dim=2, max_id=16,
        head_num=2, hidden_dim=16, nb_num=4, edge_type=0,
    )
    state, hist = _run(model, graph, steps=15)
    assert 0.0 <= hist[-1]["f1"] <= 1.0


def test_gat_sample_shapes(graph):
    from euler_tpu.models import GAT

    model = GAT(
        label_idx=2, label_dim=3, feature_idx=0, feature_dim=2, max_id=16,
        nb_num=4,
    )
    batch = model.sample(graph, np.array([10, 12]))
    assert batch["seq"].shape == (2, 5, 2)  # self + 4 neighbors
    # position 0 is the root's own features
    np.testing.assert_allclose(batch["seq"][0, 0], [5.0, 2.5])


@pytest.mark.parametrize(
    "name",
    ["graphsage_supervised", "graphsage", "gcn", "scalable_gcn",
     "scalable_sage", "gat"],
)
def test_device_features_models_train(name, graph):
    """Every model that supports device_features trains through the generic
    machinery with HBM-resident tables, and the step carries consts."""
    from tests.test_run_loop import COMMON
    import jax
    import optax

    from euler_tpu.run_loop import build_model, define_flags

    args = define_flags().parse_args(
        COMMON + ["--model", name, "--all_node_type", "-1",
                  "--device_features", "true"]
    )
    model = build_model(args, graph)
    assert model.device_features
    opt = optax.adam(0.01)
    roots = np.asarray(graph.sample_node(8, -1))
    state = model.init_state(jax.random.PRNGKey(0), graph, roots, opt)
    assert "features" in state["consts"]
    step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
    batch = model.sample(graph, roots)
    state, loss, metric = step(state, batch)
    assert np.isfinite(float(loss))
    assert "consts" in state
    # eval + embed paths run too
    loss2, _ = jax.jit(model.make_eval_step())(state, model.sample(graph, roots))
    assert np.isfinite(float(loss2))
    emb = jax.jit(model.make_embed_step())(
        state, model.sample_embed(graph, roots)
    )
    assert np.isfinite(np.asarray(emb)).all()
