"""Headline benchmark: supervised GraphSAGE throughput on one TPU chip.

Mirrors the reference's flagship recipes on synthetic graphs at the real
datasets' scale (the real data is not downloadable in this zero-egress
environment; the synthetic graphs match node count / degree / feature and
label dims, making the sampling + compute cost representative):

  ppi     reference examples/sage.py:80-98 — batch 512, fanouts [10,10],
          dim 256, Adam 0.01 on a 56944-node, 50-feature, 121-label graph
          (constants from reference tf_euler/python/ppi_main.py:24-33).
  reddit  reference examples/sage_reddit.py:80-97 — batch 1000, fanouts
          [4,4], dim 64, Adam 0.03 on a 232965-node, 602-feature,
          41-class graph (reference tf_euler/python/reddit_main.py:24-34),
          exercising the device-resident feature table at real dims.
  reddit_heavytail  the same recipe on a power-law graph at real
          Reddit's EDGE budget (~114.6M directed edges, mean degree
          ~490, heavy tail — datasets.build_powerlaw), device sampling
          via the EXACT flat-CSR alias sampler (reference semantics:
          CompactNode samples over ALL neighbors,
          euler/core/compact_node.cc:42-101; the padded slab is
          max_degree-truncated or unbuildable at these degrees). Not in
          the default config list: the first build writes a ~1.9 GB
          graph (cached; EULER_TPU_HEAVYTAIL_CACHE overrides the
          location, default <repo>/.data/reddit_ht — shared with
          scripts/reddit_heavytail.py --full). Opt in with
          --configs reddit_heavytail.

Prints one JSON line per config; with the default config list the LAST
line is always the headline
  {"metric": "edges/sec/chip", "value": N, "unit": "edges/s",
   "vs_baseline": r, "detail": {...}}
where "edges" counts sampled neighbor draws consumed per step
(batch * (f1 + f1*f2)), the standard GNN throughput metric, and
vs_baseline divides by BASELINE_TARGET = 2e6 edges/s/chip — the
BASELINE.md north-star proxy (2x an assumed 1M edges/s for the
reference's 8xV100-era distributed setup; the reference repo publishes
no number, see BASELINE.md).

Robustness contract (the driver records this output unattended):
- TPU backend init is probed in a killable subprocess with bounded
  retries/backoff, so a hung or busy chip can never hang this process or
  leave a child holding it.
- EVERY config's measurement runs in its OWN killable subprocess that
  BANKS its JSON result to disk (<repo>/.bench_bank/<config>.json,
  override EULER_TPU_BENCH_BANK) the moment it exists — the host-path
  number is banked mid-config before the device-sampling section starts,
  so a relay that wedges AFTER a successful probe (the round-4 failure
  mode: good probe, then backend init blocked 19 min at 0% CPU) costs at
  most one config's remaining work, never the whole window. The parent
  process never initializes a backend itself; a wedged child is
  SIGKILLed at its per-config deadline and the parent falls back to CPU
  for that config and the rest.
- If the TPU never comes up, the benchmark still runs on CPU and reports
  the measured number with an "error" field naming the TPU failure.
- Any other failure still prints the headline JSON line with "error".

detail.breakdown reports the step-time split measured directly:
host-sample ms/batch (graph engine time inside prefetch workers),
device-step ms (blocking step on a resident batch), pipelined wall
ms/step, and the input stall (wall - device) — pipelined wall close to
device-step means the prefetch pipeline hides host sampling, the design
claim of euler_tpu/parallel/prefetch.py. A JAX profiler trace of the
measured window is saved to EULER_TPU_PROFILE_DIR (default
/tmp/euler_tpu_bench_trace) when tracing is available.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_TARGET = 2_000_000.0  # edges/s/chip; see module docstring

# A wedged chip/tunnel can "complete" dispatches without executing them
# (observed 2026-07-30: 2.3 us/step reported right before the backend
# went UNAVAILABLE mid-run). Gate every throughput number on physical
# plausibility before it can become the headline: an empty-body scan
# step alone measures 0.133 ms on this chip (PERF.md step anatomy), so
# any train step under 30 us is not a measurement.
MIN_CREDIBLE_STEP_MS = 0.03


def _implausible(step_ms: float, loss) -> str | None:
    """Non-None (reason) when a measured step time or loss cannot be a
    real execution; callers must drop the number from the headline."""
    if step_ms < MIN_CREDIBLE_STEP_MS:
        return (
            f"step {step_ms * 1e3:.1f}us < {MIN_CREDIBLE_STEP_MS * 1e3:.0f}us"
            " floor: backend likely wedged (dispatches not executing)"
        )
    if loss is not None and not np.isfinite(float(np.asarray(loss).ravel()[-1])):
        return "non-finite loss: execution produced garbage"
    return None

CONFIGS = {
    "ppi": dict(
        num_nodes=56944, avg_degree=15, feature_dim=50, label_dim=121,
        multilabel=True, batch=512, fanouts=(10, 10), dim=256, lr=0.01,
        warmup=5, measure=30,
    ),
    "reddit": dict(
        num_nodes=232965, avg_degree=50, feature_dim=602, label_dim=41,
        multilabel=False, batch=1000, fanouts=(4, 4), dim=64, lr=0.03,
        warmup=3, measure=15,
    ),
    # the same recipe with a bfloat16 feature table: Reddit's 602-dim
    # rows are the wide-gather case the reduced-precision table exists
    # for (the feature gathers are the post-kernel bottleneck, PERF.md
    # step anatomy) — compare against the reddit line for the f32/bf16
    # A/B. Reference analog: PS-side feature storage,
    # tf_euler/python/utils/embedding.py:22-67.
    "reddit_bf16": dict(
        num_nodes=232965, avg_degree=50, feature_dim=602, label_dim=41,
        multilabel=False, batch=1000, fanouts=(4, 4), dim=64, lr=0.03,
        warmup=3, measure=15, feature_dtype="bfloat16",
        cache_as="reddit",  # identical graph: share the on-disk cache
    ),
    # real-degree Reddit: power-law out/in-degrees at the real edge
    # budget (unique-fill + Gumbel-top-k hub rows land the achieved
    # count <1% under num_edges; measured 0.8% under at this recipe).
    # Graph-shape params come from datasets.REDDIT_HEAVYTAIL at run
    # time (run_config merges them in), the single source also used by
    # scripts/reddit_heavytail.py --full, so the two share a cache by
    # construction.
    "reddit_heavytail": dict(
        batch=1000, fanouts=(4, 4), dim=64, lr=0.03,
        warmup=3, measure=15, powerlaw=True, alias_sampling=True,
    ),
    # Tiny host-path-only config for the perf-regression gate
    # (scripts/perf_gate.py; verify.sh): small enough to finish in a
    # couple of minutes on CPU, big enough that the sampling + compute
    # pipeline is real. host_only skips the device-sampling /
    # kernel-A/B sections. Not comparable to the full configs above —
    # the gate compares smoke-to-smoke across rounds.
    "smoke": dict(
        num_nodes=3000, avg_degree=8, feature_dim=16, label_dim=4,
        multilabel=True, batch=128, fanouts=(5, 5), dim=32, lr=0.01,
        warmup=2, measure=8, host_only=True,
    ),
    # The sharded REMOTE path (scripts/remote_bench.py): edges/s of a
    # 2-hop fanout + feature batch against a local 2-shard cluster,
    # before/after the dedup + cache + dispatcher optimizations, with
    # the ids-on-wire counter ledger. No model training, no TPU — this
    # measures the remote client, the ROADMAP's serve-millions tier.
    # Not in the default list (the single-chip configs are the
    # headline); opt in with --configs remote.
    "remote": dict(remote=True),
}

def detect_pallas_kernel(state) -> bool:
    """True when the fused Pallas draw kernel is active for this train
    state (packed slab consts present — the on/off decision is made at
    init_state time by add_sampling_consts -> available()). ONE copy of
    the detection, shared with scripts/batch_sweep.py."""
    return bool(
        any(
            "packed" in a
            for a in state.get("consts", {}).get("adj", {}).values()
        )
    )


def kernel_ab(model, opt, graph, batch_size: int, chunk_steps: int,
              kernel_steps_per_sec: float, chunks: int = 4,
              put=None) -> dict:
    """Measure the SAME config with the Pallas kernel forced off and
    return {xla_path_steps_per_sec, kernel_step_speedup} (or
    {ab_error}). Shared by run_config's headline A/B and the batch
    sweep's per-point A/B — the env-toggle save/run/restore protocol
    must not fork. Caller must free its own kernel-path state first:
    this uploads a second full state (slabs + params + opt).

    put: optional sharding for the XLA-path state (run_config passes
    its replicated mesh sharding). The kernel-path measurement places
    state_ds on `rep`; without the matching device_put here a
    multi-chip mesh would compare different placements."""
    import jax

    from euler_tpu import train as train_lib

    out = {}
    prior = os.environ.get("EULER_TPU_PALLAS_SAMPLING")
    os.environ["EULER_TPU_PALLAS_SAMPLING"] = "0"
    try:
        state_x = model.init_state(
            jax.random.PRNGKey(0), graph,
            graph.sample_node(batch_size, -1), opt,
        )
        if put is not None:
            state_x = jax.device_put(state_x, put)
        scan_x = jax.jit(
            train_lib.make_scan_train(model, opt, chunk_steps, batch_size),
            donate_argnums=(0,),
        )
        state_x, lx = scan_x(state_x, 0)
        jax.block_until_ready(lx)
        t0 = time.perf_counter()
        for c in range(1, chunks + 1):
            state_x, lx = scan_x(state_x, c)
        jax.block_until_ready(lx)
        x_dt = time.perf_counter() - t0
        x_ms = x_dt / (chunks * chunk_steps) * 1e3
        bogus = _implausible(x_ms, lx)
        if bogus:
            out["ab_error"] = f"measurement rejected: {bogus}"
        else:
            x_sps = chunks * chunk_steps / x_dt
            out["xla_path_steps_per_sec"] = round(x_sps, 2)
            out["kernel_step_speedup"] = round(
                kernel_steps_per_sec / x_sps, 3
            )
        del state_x
    except Exception as e:
        out["ab_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        if prior is None:
            os.environ.pop("EULER_TPU_PALLAS_SAMPLING", None)
        else:
            os.environ["EULER_TPU_PALLAS_SAMPLING"] = prior
    return out


def probe_backend(attempts: int, timeout_s: float, backoff_s: float):
    """Initialize the ambient (TPU) backend in a killable subprocess
    (euler_tpu.parallel.probe_backend_once — the ONE probe shared with
    the training path's probe_backend_or_die, so relay-wedge handling
    cannot drift between measurement and training), retrying with
    backoff. Returns (platform, None) on success or (None, error
    string) after all attempts fail; a timed-out child is killed, so a
    hung backend init can neither block this process nor leave a child
    holding the chip."""
    from euler_tpu.parallel import probe_backend_once

    errs = []
    for a in range(attempts):
        if a:
            time.sleep(backoff_s)
        platform, err = probe_backend_once(timeout_s)
        if platform is not None:
            return platform, None
        errs.append(f"attempt {a + 1}: {err}")
    return None, "; ".join(errs)


def _failure_line(name: str, error: str) -> dict:
    """The driver-parseable headline shape for a run that produced no
    measurement (shared by the per-config except path and the watchdog so
    the schema cannot drift between them)."""
    return {
        "metric": (
            "edges/sec/chip" if name == "ppi" else f"{name}_edges/sec/chip"
        ),
        "value": 0.0,
        "unit": "edges/s",
        "vs_baseline": 0.0,
        "error": error,
    }


def _chip_peaks():
    """(peak_flops, peak_hbm_bytes_per_s) for this chip from its public
    spec sheet, or (None, None) when unknown. Env overrides
    EULER_TPU_PEAK_TFLOPS / EULER_TPU_PEAK_HBM_GBPS take precedence (set
    both to teach the bench a new chip without a code change)."""
    import jax

    env_f = os.environ.get("EULER_TPU_PEAK_TFLOPS")
    env_b = os.environ.get("EULER_TPU_PEAK_HBM_GBPS")
    peak_f = float(env_f) * 1e12 if env_f else None
    peak_b = float(env_b) * 1e9 if env_b else None
    if peak_f is not None and peak_b is not None:
        return peak_f, peak_b
    kind = jax.devices()[0].device_kind.lower()
    # bf16 peak / HBM BW per chip (public TPU spec sheets)
    table = {
        "v5 lite": (197e12, 819e9),
        "v5litepod": (197e12, 819e9),
        "v5e": (197e12, 819e9),
        "v5p": (459e12, 2765e9),
        "v6 lite": (918e12, 1640e9),  # device_kind "TPU v6 lite"
        "v6e": (918e12, 1640e9),
        "v4": (275e12, 1228e9),
    }
    for k, (f, b) in table.items():
        if k in kind:
            return (peak_f or f), (peak_b or b)
    return peak_f, peak_b


def _roofline(compiled, step_time_ms: float):
    """Achieved-vs-peak utilization from XLA's compile-time cost model:
    {flops_per_step, hbm_bytes_per_step, achieved_tflops,
    achieved_hbm_gbps, mfu, hbm_util}. The numbers are ANALYTICAL
    (operand/output byte counts and op FLOPs from cost_analysis(), not
    hardware counters) — right order of magnitude for a roofline
    statement, not a profiler replacement. Scan/while bodies are counted
    once by the cost model, so a scanned dispatch is already per-step.
    Empty dict when the backend offers no cost analysis."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0))
        byts = float(ca.get("bytes accessed", 0.0))
    except Exception:
        return {}
    if flops <= 0 and byts <= 0:
        return {}
    out = {
        "flops_per_step": round(flops, 1),
        "hbm_bytes_per_step": round(byts, 1),
        "source": "xla_cost_analysis",
    }
    t = step_time_ms / 1e3
    if t <= 0:
        return out
    peak_f, peak_b = _chip_peaks()
    out["achieved_tflops"] = round(flops / t / 1e12, 4)
    out["achieved_hbm_gbps"] = round(byts / t / 1e9, 2)
    if peak_f:
        out["mfu"] = round(flops / t / peak_f, 5)
    if peak_b:
        out["hbm_util"] = round(byts / t / peak_b, 5)
    return out


def _timed(fn, out_list):
    """Wrap fn to append its wall duration (ms) to out_list (thread-safe:
    list.append is atomic)."""

    def wrapper(*args):
        t0 = time.perf_counter()
        result = fn(*args)
        out_list.append((time.perf_counter() - t0) * 1e3)
        return result

    return wrapper


def run_config(name: str, cfg: dict, trace_dir: str | None, bank=None):
    """Train supervised GraphSAGE at cfg's scale, measuring pipelined
    throughput plus the host/device step-time split. Returns the result
    JSON dict. ``bank``, when given, is called with the host-path-only
    result BEFORE the device-sampling section starts (and callers bank
    the final dict themselves) — a wedge mid-config then loses the
    device-sampling delta, not the whole config."""
    if cfg.get("remote"):
        # the remote-client benchmark: no jax, no model — delegate to
        # scripts/remote_bench.py (one measurement implementation shared
        # with the verify.sh smoke gate, so the two cannot drift)
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "remote_bench",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "remote_bench.py"),
        )
        remote_bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(remote_bench)
        return remote_bench.run_remote_bench()
    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.datasets import build_synthetic
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.parallel import (
        batch_sharding,
        make_mesh,
        prefetch,
        replicated_sharding,
        shard_batch,
    )

    if cfg.get("powerlaw"):
        # graph shape from the one authoritative constant (shared with
        # scripts/reddit_heavytail.py; a drifted copy here would
        # silently invalidate the ~2 GB cache and measure a different
        # graph than PERF.md describes)
        from euler_tpu.datasets import REDDIT_HEAVYTAIL

        cfg = {**cfg, **REDDIT_HEAVYTAIL}

    platform = jax.devices()[0].platform
    warmup, measure = cfg["warmup"], cfg["measure"]
    if platform == "cpu":  # fallback mode: keep the wall time bounded
        warmup, measure = min(warmup, 2), min(measure, 10)
    batch_size, fanouts, dim = cfg["batch"], list(cfg["fanouts"]), cfg["dim"]

    if cfg.get("powerlaw"):
        from euler_tpu.datasets import build_powerlaw, heavytail_cache_dir

        cache = heavytail_cache_dir()
        build_powerlaw(
            cache,
            num_nodes=cfg["num_nodes"],
            num_edges=cfg["num_edges"],
            feature_dim=cfg["feature_dim"],
            label_dim=cfg["label_dim"],
            alpha=cfg["alpha"],
            multilabel=cfg["multilabel"],
            progress_every=50000,
        )
    else:
        cache = os.environ.get(
            "EULER_TPU_BENCH_CACHE", "/tmp/euler_tpu_bench"
        ) + "_" + cfg.get("cache_as", name)
        build_synthetic(
            cache,
            num_nodes=cfg["num_nodes"],
            avg_degree=cfg["avg_degree"],
            feature_dim=cfg["feature_dim"],
            label_dim=cfg["label_dim"],
            multilabel=cfg["multilabel"],
        )
    graph = euler_tpu.Graph(directory=cache)

    model = SupervisedGraphSage(
        label_idx=0,
        label_dim=cfg["label_dim"],
        metapath=[[0]] * len(fanouts),
        fanouts=fanouts,
        dim=dim,
        feature_idx=1,
        feature_dim=cfg["feature_dim"],
        max_id=cfg["num_nodes"] - 1,
        device_features=True,
        feature_dtype=cfg.get("feature_dtype"),
    )

    mesh = make_mesh()
    n_chips = len(mesh.devices.reshape(-1))
    opt = train_lib.get_optimizer("adam", cfg["lr"])
    state = model.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(batch_size, -1), opt
    )
    rep = replicated_sharding(mesh)
    state = jax.device_put(state, rep)
    step_fn = jax.jit(
        model.make_train_step(opt),
        in_shardings=(rep, batch_sharding(mesh)),
        out_shardings=(rep, rep, rep),
        donate_argnums=(0,),
    )

    sample_ms: list[float] = []
    sample_fn = _timed(
        lambda: model.sample(graph, graph.sample_node(batch_size, -1)),
        sample_ms,
    )

    def make_batch(step):
        # H2D transfer in the prefetch worker: upload of batch k+1
        # overlaps device compute of step k
        return shard_batch(sample_fn(), mesh)

    from euler_tpu.telemetry import phase_hists, telemetry_reset

    tracing = False
    it = prefetch(make_batch, warmup + measure, depth=3, num_threads=4)
    losses = []
    last_batch = None
    for i, batch in enumerate(it):
        if i == warmup:
            jax.block_until_ready(state)
            sample_ms.clear()  # keep only measured-window samples
            telemetry_reset()  # measured-window phase hists only
            if trace_dir:
                try:
                    jax.profiler.start_trace(trace_dir)
                    tracing = True
                except Exception as e:
                    trace_dir = f"unavailable: {e}"
            t0 = time.perf_counter()
        state, loss, metric = step_fn(state, batch)
        losses.append(loss)
        last_batch = batch
    jax.block_until_ready(losses[-1])
    dt = time.perf_counter() - t0
    if tracing:
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            trace_dir = f"unavailable: {e}"

    # Pure device step time: blocking steps on an already-resident batch —
    # no sampling or H2D in the timed region.
    device_times = []
    for _ in range(5):
        t1 = time.perf_counter()
        state, loss, metric = step_fn(state, last_batch)
        jax.block_until_ready(loss)
        device_times.append(time.perf_counter() - t1)
    device_step_ms = float(np.median(device_times)) * 1e3
    # achieved-vs-peak for the host-path device step (lower() hits the
    # jit cache — no recompile; donation is irrelevant, nothing executes)
    try:
        host_roofline = _roofline(
            step_fn.lower(state, last_batch).compile(), device_step_ms
        )
    except Exception:
        host_roofline = {}

    step_wall_ms = dt / measure * 1e3
    host_sample_ms = float(np.mean(sample_ms)) if sample_ms else 0.0
    # Prefer the DIRECTLY measured consumer stall (the prefetch
    # pipeline's input_stall phase histogram over the measured window)
    # over the wall-minus-device derivation — the derived number folds
    # in host bookkeeping that is not input starvation.
    stall_h = phase_hists().get("input_stall")
    measured_stall_ms = (
        stall_h["sum_us"] / stall_h["count"] / 1000.0
        if stall_h and stall_h["count"] else None
    )
    edges_per_step = batch_size * (
        fanouts[0] + fanouts[0] * (fanouts[1] if len(fanouts) > 1 else 0)
    )
    sps = measure / dt
    edges_per_sec = edges_per_step * sps / n_chips

    host_bogus = _implausible(step_wall_ms, losses[-1])
    if host_bogus:
        # the host-path window is this metric's floor; if even it is
        # fake, the whole config's numbers are untrustworthy — and there
        # is no point burning the device-sampling window on it
        return {
            **_failure_line(name, f"measurement rejected: {host_bogus}"),
            "detail": {"config": name, "platform": platform},
        }

    def _mk_result(ds: dict) -> dict:
        e_s, s_s = edges_per_sec, sps
        if ds.get("edges_per_sec", 0) > e_s and "implausible" not in ds:
            e_s, s_s = ds["edges_per_sec"], ds["steps_per_sec"]
        return {
            "metric": (
                f"{name}_edges/sec/chip" if name != "ppi" else "edges/sec/chip"
            ),
            "value": round(e_s, 1),
            "unit": "edges/s",
            "vs_baseline": round(e_s / BASELINE_TARGET, 3),
            "detail": {
                "config": name,
                "steps_per_sec": round(s_s, 2),
                "batch": batch_size,
                "fanouts": fanouts,
                "dim": dim,
                "chips": n_chips,
                "platform": platform,
                "final_loss": round(float(np.asarray(losses[-1])), 4),
                "device_sampling": ds,
                "host_path_edges_per_sec": round(
                    edges_per_step * (measure / dt) / n_chips, 1
                ),
                "breakdown": {
                    "host_sample_ms_per_batch": round(host_sample_ms, 2),
                    "device_step_ms": round(device_step_ms, 2),
                    "pipelined_step_wall_ms": round(step_wall_ms, 2),
                    "input_stall_ms": round(
                        measured_stall_ms
                        if measured_stall_ms is not None
                        else max(0.0, step_wall_ms - device_step_ms), 2
                    ),
                    # this path runs a LOCAL graph: the async completion
                    # queue (sampler_depth, remote-only) never engages —
                    # the remote per-depth sweep lives in
                    # scripts/remote_bench.py (PERF.md "Pipelined
                    # sampling")
                    "sampler_depth": 0,
                    # hidden = the measured consumer stall is noise
                    # relative to the device step (< 5% of it) — the
                    # ROADMAP item-1 acceptance threshold, replacing the
                    # old wall<1.2x-device heuristic that a slow host
                    # tail could fail even with zero input starvation
                    "sampling_hidden_by_prefetch": bool(
                        (measured_stall_ms
                         if measured_stall_ms is not None
                         else max(0.0, step_wall_ms - device_step_ms))
                        < 0.05 * device_step_ms
                    ),
                    # achieved vs peak (mfu / hbm_util) — the denominator
                    # for "is the step actually fast"; see PERF.md
                    "roofline": host_roofline,
                },
                "trace_dir": trace_dir,
            },
        }

    if bank is not None:
        partial = _mk_result({})
        partial["detail"]["banked"] = "host_path_only"
        bank(partial)

    # Device-sampling path: adjacency in HBM, roots + fanout sampled
    # inside the jitted step, lax.scan chaining CHUNK steps per dispatch
    # (euler_tpu/graph/device.py + train.make_scan_train). This is the
    # framework's intended fast path for graphs that fit in HBM; the
    # host-path numbers above remain in the breakdown for comparison.
    ds = {}
    if cfg.get("host_only"):
        return _mk_result(ds)
    try:
        model_ds = SupervisedGraphSage(
            label_idx=0,
            label_dim=cfg["label_dim"],
            metapath=[[0]] * len(fanouts),
            fanouts=fanouts,
            dim=dim,
            feature_idx=1,
            feature_dim=cfg["feature_dim"],
            max_id=cfg["num_nodes"] - 1,
            device_features=True,
            device_sampling=True,
            feature_dtype=cfg.get("feature_dtype"),
        )
        if cfg.get("alias_sampling"):
            # exact flat-CSR alias sampler: the only buildable device
            # form at heavy-tail degrees (the slab's width would be the
            # max observed degree), and reference-exact at any degree
            model_ds.set_sampling_options(alias=True)
        t_up = time.perf_counter()
        state_ds = model_ds.init_state(
            jax.random.PRNGKey(0), graph,
            graph.sample_node(batch_size, -1), opt,
        )
        # record whether the fused Pallas draw kernel is active — on
        # single-chip TPU it should be
        ds["pallas_kernel"] = detect_pallas_kernel(state_ds)
        state_ds = jax.device_put(state_ds, rep)
        chunk_steps = 50
        scan = jax.jit(
            train_lib.make_scan_train(
                model_ds, opt, chunk_steps, batch_size
            ),
            donate_argnums=(0,),
        )
        state_ds, l0 = scan(state_ds, 0)  # compile + warmup chunk
        jax.block_until_ready(l0)
        upload_s = time.perf_counter() - t_up
        chunks = 2 if platform == "cpu" else 10

        def _param_digest(st):
            # cheap execution witness: Adam moves every param every step,
            # so a timed window that leaves this digest bit-identical
            # did not execute (the 2026-07-30 wedge mode acks dispatches
            # without running them, and one observed variant returned a
            # plausible-looking stale loss buffer)
            leaf = jax.tree.leaves(st["params"])[0]
            return float(np.asarray(jax.device_get(leaf)).sum())

        seed_c = 0
        bogus = None
        for attempt in range(3):  # transient relay wedges recover
            pre_digest = _param_digest(state_ds)  # syncs pre-window
            t2 = time.perf_counter()
            last = None
            for _ in range(chunks):
                seed_c += 1
                state_ds, last = scan(state_ds, seed_c)
            jax.block_until_ready(last)
            ds_dt = time.perf_counter() - t2
            step_wall_ms_ds = ds_dt / (chunks * chunk_steps) * 1e3
            bogus = _implausible(step_wall_ms_ds, last)
            if not bogus and _param_digest(state_ds) == pre_digest:
                bogus = (
                    "params bit-identical across the timed window: "
                    "dispatches not executing"
                )
            if not bogus:
                break
            time.sleep(5.0)
        ds_sps = chunks * chunk_steps / ds_dt
        ds["steps_per_sec"] = round(ds_sps, 2)
        ds["edges_per_sec"] = round(edges_per_step * ds_sps / n_chips, 1)
        ds["step_wall_ms"] = round(step_wall_ms_ds, 4)
        ds["setup_s"] = round(upload_s, 2)
        ds["final_loss"] = round(float(np.asarray(last)[-1]), 4)
        try:
            # XLA's cost model counts a while/scan BODY ONCE (it does not
            # multiply by trip count) — verified: this dispatch's flops ~=
            # the single-step host path's — so the scanned dispatch needs
            # no chunk_steps division to be per-step
            ds["roofline"] = _roofline(
                scan.lower(state_ds, 0).compile(), ds["step_wall_ms"]
            )
        except Exception:
            pass
        if bogus:
            ds["implausible"] = bogus
        del state_ds

        # Kernel A/B on the headline config: rerun the same scanned loop
        # with the fused Pallas draw kernel forced off, so the recorded
        # JSON carries the kernel's step-level contribution (TPU only;
        # ppi only — Reddit's table setup is too slow to do twice).
        if (
            name == "ppi"
            and platform == "tpu"
            and ds.get("pallas_kernel")
            and "implausible" not in ds
        ):
            ds.update(kernel_ab(
                model_ds, opt, graph, batch_size, chunk_steps,
                ds["steps_per_sec"], chunks=4, put=rep,
            ))
    except Exception as e:  # never lose the host-path number
        ds["error"] = f"{type(e).__name__}: {e}"[:300]

    return _mk_result(ds)


# Per-config wall-time caps (seconds, TPU base — x3 on CPU): the
# subprocess running a config is SIGKILLed at its cap, so one wedged
# config can never eat the following configs' window. heavytail gets
# headroom for the 1.37 GB alias-table upload through the tunnel.
CONFIG_CAPS = {
    "smoke": 300.0,
    "ppi": 900.0,
    "reddit": 900.0,
    "reddit_bf16": 900.0,
    "reddit_heavytail": 1500.0,
    "remote": 900.0,
}


def _bank_write(path: str, obj: dict) -> None:
    """Atomic JSON write (tmp + rename): the parent may read the file
    right after killing the writer, and a torn half-written JSON would
    turn a banked partial result into nothing."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def _run_one(name: str, bank_file: str, platform: str | None,
             trace_dir: str | None) -> None:
    """Child mode: measure ONE config in this process, bank the result
    (host-path partial first, final overwrite) to bank_file. stdout
    stays JSON-free — the parent owns the driver-facing stream."""
    if platform == "cpu":
        from euler_tpu.parallel import force_cpu_devices

        force_cpu_devices(1)
    else:
        from euler_tpu.parallel import honor_jax_platforms_env

        honor_jax_platforms_env()
    # persistent XLA compile cache: a relaunched config (or the next
    # round's run) reuses compiles instead of repaying 20-40 s each
    from euler_tpu.parallel import enable_compile_cache

    enable_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"
    ))
    try:
        result = run_config(
            name, CONFIGS[name], trace_dir,
            bank=lambda obj: _bank_write(bank_file, obj),
        )
    except Exception as e:  # noqa: BLE001 — bank the failure line too
        result = _failure_line(name, f"{type(e).__name__}: {e}")
    result.setdefault("detail", {})["banked"] = "final"
    _bank_write(bank_file, result)


def _spawn_config(name: str, platform: str | None, timeout_s: float,
                  bank_dir: str, trace_dir: str | None):
    """Run one config in a killable subprocess; return (result,
    timed_out) where result is its banked JSON (final, or the mid-config
    host-path partial if the child died after banking it) or None when
    nothing was banked, and timed_out reports whether the child hit its
    deadline (the parent's cue that the backend wedged even when a
    partial was rescued). The child is its own session so a SIGKILL
    reaps any grandchildren with it."""
    import signal
    import subprocess

    bank_file = os.path.join(bank_dir, f"{name}.json")
    try:
        os.remove(bank_file)  # stale banks must not pass as this run's
    except OSError:
        pass
    cmd = [
        sys.executable, "-u", os.path.abspath(__file__),
        "--run-one", name, "--bank-file", bank_file,
    ]
    if platform:
        cmd += ["--platform", platform]
    if trace_dir:
        cmd += ["--trace-dir", trace_dir]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.DEVNULL, start_new_session=True,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    timed_out = False
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            proc.kill()
        proc.wait()
    result = None
    if os.path.exists(bank_file):
        try:
            with open(bank_file) as f:
                result = json.load(f)
        except ValueError:
            result = None
    if result is not None and result.get("detail", {}).get("banked") != "final":
        how = (
            f"killed at the {timeout_s:.0f}s config deadline"
            if timed_out else f"child exited rc={proc.returncode}"
        )
        result["error"] = (
            f"{how} mid-config; host-path partial measurement banked "
            "(device-sampling section lost — relay wedge?)"
        )
    return result, timed_out


def default_configs() -> str:
    """No-flag config list: reddit,ppi — plus reddit_heavytail (the
    113.7M-edge exact-alias flagship) whenever its cache is already
    built with current params. Pure file check, no backend contact;
    an absent or stale cache is never rebuilt implicitly, so the
    rebuild cost cannot land on an unsuspecting bench window."""
    configs = "reddit,ppi"
    try:
        from euler_tpu.datasets import (
            REDDIT_HEAVYTAIL, heavytail_cache_dir, powerlaw_cache_ready,
        )

        if powerlaw_cache_ready(heavytail_cache_dir(), **REDDIT_HEAVYTAIL):
            configs = "reddit_heavytail," + configs
            print(json.dumps({"note": "reddit_heavytail cache ready; "
                              "added to default configs"}),
                  file=sys.stderr)
    except Exception:
        pass
    return configs


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--configs", default=None,
        help="comma list from %s; when ppi (the headline) is included it "
        "is always printed last. Default: reddit,ppi — plus "
        "reddit_heavytail (the 113.7M-edge exact-alias flagship) "
        "whenever its graph cache is already built with current params "
        "(the driver's no-flag run then covers it for free; an absent "
        "or stale cache is never rebuilt implicitly)" % sorted(CONFIGS),
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="run only the tiny host-path 'smoke' config (the "
        "scripts/perf_gate.py regression probe; smoke-to-smoke "
        "comparable across rounds, NOT comparable to the full configs)",
    )
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("EULER_TPU_PROBE_ATTEMPTS", 3)))
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get("EULER_TPU_PROBE_TIMEOUT", 150)))
    ap.add_argument("--probe-backoff", type=float, default=20.0)
    ap.add_argument(
        "--deadline", type=float, default=None,
        help="total wall budget in seconds, scaled x3 on CPU fallback "
        "(unlike EULER_TPU_BENCH_DEADLINE, which is honored as-is)",
    )
    # child-mode flags (internal: the parent spawns `--run-one <config>`)
    ap.add_argument("--run-one", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--bank-file", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--platform", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--trace-dir", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.run_one:
        _run_one(args.run_one, args.bank_file, args.platform, args.trace_dir)
        return

    # None = not passed (take defaults); an explicit empty string stays
    # an explicit request to run nothing
    if args.smoke and args.configs is None:
        args.configs = "smoke"
    configs = (
        args.configs if args.configs is not None else default_configs()
    )
    names = [n.strip() for n in configs.split(",") if n.strip()]
    # headline last so the driver's last-line parse records it
    names.sort(key=lambda n: n == "ppi")

    bank_dir = os.environ.get(
        "EULER_TPU_BENCH_BANK",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_bank"),
    )
    os.makedirs(bank_dir, exist_ok=True)

    tpu_error = None
    platform = None
    # one gate for "JAX_PLATFORMS could resolve to the chip": the probe
    # branch and the CPU-deadline scaling must never disagree. First
    # element of a comma list decides, matching probe_backend_or_die
    # ("tpu,cpu" still inits TPU first).
    tpu_possible = os.environ.get(
        "JAX_PLATFORMS", ""
    ).split(",")[0].strip() in ("", "axon", "tpu")
    if tpu_possible:
        platform, tpu_error = probe_backend(
            args.probe_attempts, args.probe_timeout, args.probe_backoff
        )
        if platform is None:
            # fall back to CPU: a measured number with an error note beats
            # no number (round-1 failure mode)
            tpu_error = f"TPU backend unavailable ({tpu_error}); CPU fallback"
            print(json.dumps({"note": tpu_error}), file=sys.stderr)

    # CPU is legitimately ~an order of magnitude slower than the chip —
    # whether via probe fallback (tpu_error) or an explicit
    # JAX_PLATFORMS=cpu run; a healthy-but-slow CPU run must not be
    # reported as a wedged backend, so the default (and --deadline)
    # budget scales up. An explicit, parseable EULER_TPU_BENCH_DEADLINE
    # env var is honored as-is (back-compat). CPU three ways: probe
    # failed, JAX_PLATFORMS forced a non-TPU backend, or the probe
    # succeeded but the ambient backend IS cpu (TPU-less machine).
    on_cpu = (
        tpu_error is not None
        or not tpu_possible
        or platform not in ("tpu", "axon")
    )
    env_deadline = os.environ.get("EULER_TPU_BENCH_DEADLINE")
    deadline = None
    scale_cpu = True
    if args.deadline is not None and args.deadline > 0:
        deadline = args.deadline
    elif env_deadline is not None:
        try:
            deadline = float(env_deadline)
            scale_cpu = False
        except ValueError:
            deadline = None
        if deadline is not None and deadline <= 0:
            deadline = None
    if deadline is None:
        # per-config budget with headroom; 2400 preserved for the
        # historical two-config default
        deadline, scale_cpu = max(2400.0, 1200.0 * len(names)), True
    if on_cpu and scale_cpu:
        deadline *= 3.0
    t_end = time.monotonic() + deadline

    def _watchdog_exit(config: str) -> None:
        # headline ("ppi") metric shape so the driver's last-line parse
        # always sees the contract, but the error names the config that
        # was actually on the clock
        print(json.dumps(_failure_line(
            "ppi",
            f"bench watchdog: exceeded {deadline:.0f}s during config "
            f"{config} (backend hang mid-run?)",
        )), flush=True)
        sys.exit(2)

    trace_dir = os.environ.get(
        "EULER_TPU_PROFILE_DIR", "/tmp/euler_tpu_bench_trace"
    )
    history = os.path.join(bank_dir, "history.jsonl")

    def _emit(result: dict) -> dict:
        with open(history, "a") as f:
            f.write(json.dumps(
                {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                 **result}
            ) + "\n")
        return result

    # Children inherit the ambient platform (None — honoring whatever
    # JAX_PLATFORMS says) until a probe failure or a mid-run child wedge
    # forces the CPU backend for everything after, so one wedge cannot
    # cascade. Probe-failure fallback forces CPU outright.
    child_platform = "cpu" if tpu_error is not None else None
    cap_scale = 3.0 if on_cpu else 1.0
    tpu_live = not on_cpu

    def _go_cpu(note: str) -> None:
        # mid-run downgrade: force CPU for the remaining configs and,
        # when the budget was sized for a TPU run, extend it to the
        # CPU-scaled budget a CPU run would have had from the start —
        # a healthy-but-slow CPU fallback must not be misreported as a
        # watchdog "backend hang" (the external tpu_checks deadline
        # already covers 3x the base)
        nonlocal child_platform, cap_scale, tpu_live, tpu_error, t_end
        tpu_error = note
        print(json.dumps({"note": note}), file=sys.stderr)
        child_platform, cap_scale = "cpu", 3.0
        if tpu_live and scale_cpu:
            t_end += deadline * 2.0
        tpu_live = False

    headline = None
    for name in names:
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            _watchdog_exit(name)
        cap = CONFIG_CAPS.get(name, 900.0) * cap_scale
        result, timed_out = _spawn_config(
            name, child_platform, min(cap, remaining), bank_dir,
            trace_dir if name == "ppi" else None,
        )
        if result is None and tpu_live:
            # TPU child died with nothing banked: relay wedge before the
            # first measurement (the round-4 "good probe, wedged init"
            # mode). Retry this config on CPU — partial window beats
            # empty window.
            _go_cpu(
                f"TPU config subprocess for {name} produced no result "
                f"within {min(cap, remaining):.0f}s (relay wedge after "
                "successful probe); CPU fallback"
            )
            remaining = t_end - time.monotonic()
            if remaining > 60:
                result, timed_out = _spawn_config(
                    name, "cpu",
                    min(CONFIG_CAPS.get(name, 900.0) * 3.0, remaining),
                    bank_dir, trace_dir if name == "ppi" else None,
                )
        elif timed_out and tpu_live:
            # the child wedged but its host-path partial was rescued:
            # keep that (it IS a TPU measurement) and stop trusting the
            # relay for the remaining configs
            _go_cpu(
                f"TPU config subprocess for {name} hit its "
                f"{min(cap, remaining):.0f}s deadline after banking a "
                "partial result (relay wedge mid-config); CPU fallback "
                "for the remaining configs"
            )
        if result is None:
            if time.monotonic() >= t_end:
                _watchdog_exit(name)
            result = _failure_line(
                name, "config subprocess produced no banked result"
            )
        if tpu_error and "error" not in result:
            result["error"] = tpu_error
        _emit(result)
        if name == "ppi":
            headline = result
        else:
            print(json.dumps(result), flush=True)
    if headline is not None:
        print(json.dumps(headline), flush=True)
        if "error" in headline and headline["value"] == 0.0:
            sys.exit(1)


if __name__ == "__main__":
    main()
