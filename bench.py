"""Headline benchmark: supervised GraphSAGE throughput on one TPU chip.

Mirrors the reference's flagship recipe (reference examples/sage.py:80-98:
batch 512, fanouts [10,10], dim 256, Adam) on a synthetic PPI-scale graph
(56944 nodes, ~15 avg degree, 50-dim features, 121 labels — the PPI
constants from reference tf_euler/python/ppi_main.py:24-33). The real PPI
dataset is not downloadable in this zero-egress environment; the synthetic
graph matches its scale so the sampling + compute cost is representative.

Prints one JSON line:
  {"metric": "edges/sec/chip", "value": N, "unit": "edges/s", "vs_baseline": r}

"edges" counts sampled neighbor draws consumed per step
(batch * (f1 + f1*f2) = 512 * 110), the standard GNN throughput metric.
vs_baseline divides by BASELINE_TARGET = 2e6 edges/s/chip — the BASELINE.md
north-star proxy (2x an assumed 1M edges/s for the reference's 8xV100-era
distributed setup; the reference repo publishes no number, see BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINE_TARGET = 2_000_000.0  # edges/s/chip; see module docstring

NUM_NODES = 56944
AVG_DEGREE = 15
FEATURE_DIM = 50
LABEL_DIM = 121
BATCH = 512
FANOUTS = [10, 10]
DIM = 256
WARMUP = 5
MEASURE = 30


def build_synthetic_graph(cache_dir: str) -> str:
    """Write a synthetic PPI-scale graph as .dat partitions (cached)."""
    from euler_tpu.datasets import build_synthetic

    return build_synthetic(
        cache_dir,
        num_nodes=NUM_NODES,
        avg_degree=AVG_DEGREE,
        feature_dim=FEATURE_DIM,
        label_dim=LABEL_DIM,
        multilabel=True,
    )


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from euler_tpu.parallel import honor_jax_platforms_env

    honor_jax_platforms_env()
    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.parallel import make_mesh, prefetch, shard_batch

    cache = os.environ.get(
        "EULER_TPU_BENCH_CACHE", "/tmp/euler_tpu_bench_graph"
    )
    build_synthetic_graph(cache)
    graph = euler_tpu.Graph(directory=cache)

    model = SupervisedGraphSage(
        label_idx=0,
        label_dim=LABEL_DIM,
        metapath=[[0], [0]],
        fanouts=FANOUTS,
        dim=DIM,
        feature_idx=1,
        feature_dim=FEATURE_DIM,
        max_id=NUM_NODES - 1,
        device_features=True,
    )

    mesh = make_mesh()
    n_chips = len(mesh.devices.reshape(-1))
    opt = train_lib.get_optimizer("adam", 0.01)
    state = model.init_state(
        jax.random.PRNGKey(0), graph, graph.sample_node(BATCH, -1), opt
    )
    from euler_tpu.parallel import batch_sharding, replicated_sharding

    rep = replicated_sharding(mesh)
    state = jax.device_put(state, rep)
    step_fn = jax.jit(
        model.make_train_step(opt),
        in_shardings=(rep, batch_sharding(mesh)),
        out_shardings=(rep, rep, rep),
        donate_argnums=(0,),
    )

    def make_batch(step):
        # transfer in the prefetch worker: H2D of batch k+1 overlaps
        # device compute of step k
        return shard_batch(
            model.sample(graph, graph.sample_node(BATCH, -1)), mesh
        )

    edges_per_step = BATCH * (FANOUTS[0] + FANOUTS[0] * FANOUTS[1])

    it = prefetch(make_batch, WARMUP + MEASURE, depth=3, num_threads=4)
    losses = []
    for i, batch in enumerate(it):
        if i == WARMUP:
            jax.block_until_ready(state)
            t0 = time.time()
        state, loss, metric = step_fn(state, batch)
        losses.append(loss)
    jax.block_until_ready(losses[-1])
    dt = time.time() - t0
    sps = MEASURE / dt
    edges_per_sec = edges_per_step * sps / n_chips
    print(
        json.dumps(
            {
                "metric": "edges/sec/chip",
                "value": round(edges_per_sec, 1),
                "unit": "edges/s",
                "vs_baseline": round(edges_per_sec / BASELINE_TARGET, 3),
                "detail": {
                    "steps_per_sec": round(sps, 2),
                    "batch": BATCH,
                    "fanouts": FANOUTS,
                    "dim": DIM,
                    "chips": n_chips,
                    "platform": jax.devices()[0].platform,
                    "final_loss": float(np.asarray(losses[-1])),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
