"""Packaging for euler_tpu (reference analog: tools/pip/setup.py +
tools/pip/build_wheel.sh, which ship the C++ engine inside a binary
wheel). The native graph engine is compiled by `make` during build_py so
wheels carry libeuler_graph.so; source installs can also rebuild it
lazily on first import (euler_tpu/graph/native.py build_native)."""

import os
import subprocess
import sys

import setuptools
from setuptools.command.build_py import build_py as _build_py

_ROOT = os.path.dirname(os.path.abspath(__file__))
_NATIVE = os.path.join(_ROOT, "euler_tpu", "graph", "_native")


class build_py(_build_py):
    def run(self):
        try:
            subprocess.run(["make", "-s", "-j"], cwd=_NATIVE, check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            # no toolchain at build time: ship sources only — the
            # package rebuilds lazily on first import (native.py
            # build_native), provided make/g++ exist at runtime
            print(
                f"warning: native engine build skipped ({e}); "
                "libeuler_graph.so will be built on first import",
                file=sys.stderr,
            )
        super().run()


cmdclass = {"build_py": build_py}
try:
    from wheel.bdist_wheel import bdist_wheel as _bdist_wheel

    class bdist_wheel(_bdist_wheel):
        def finalize_options(self):
            super().finalize_options()
            self.root_is_pure = False  # carries a compiled .so

    cmdclass["bdist_wheel"] = bdist_wheel
except ImportError:  # building an sdist without wheel installed
    pass


def _version() -> str:
    # single source: euler_tpu/__init__.py __version__ (regex-read — the
    # package is not importable at build time without jax installed)
    import re

    with open(os.path.join(_ROOT, "euler_tpu", "__init__.py")) as f:
        return re.search(
            r'^__version__ = "([^"]+)"', f.read(), re.M
        ).group(1)


setuptools.setup(
    name="euler-tpu",
    version=_version(),
    description=(
        "TPU-native graph learning framework: C++ host graph engine + "
        "JAX/Flax/pjit training with device-resident sampling"
    ),
    long_description=open(
        os.path.join(_ROOT, "README.md"), encoding="utf-8"
    ).read(),
    long_description_content_type="text/markdown",
    license="Apache License 2.0",
    packages=setuptools.find_packages(include=["euler_tpu*"]),
    package_data={
        # ship the built engine AND its sources+Makefile so source
        # checkouts / sdists can rebuild with plain make
        "euler_tpu.graph": [
            "_native/*.so",
            "_native/*.cc",
            "_native/*.h",
            "_native/Makefile",
            "_native/*.supp",
        ]
    },
    include_package_data=True,
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "optax",
        "orbax-checkpoint",
        "numpy",
    ],
    extras_require={"remote-fs": ["fsspec"]},
    entry_points={
        "console_scripts": [
            # the reference's `python -m tf_euler` / console / converter /
            # service entry points as installed commands
            "euler-tpu = euler_tpu.run_loop:main",
            "euler-tpu-console = euler_tpu.console:main",
            "euler-tpu-convert = euler_tpu.graph.convert:main",
            "euler-tpu-service = euler_tpu.graph.service:main",
        ]
    },
    cmdclass=cmdclass,
)
