#!/usr/bin/env bash
# Distributed training launcher on one machine: N graph-service shards +
# shared-mode training (each training process serves its shard and connects
# a remote client over the flat-file registry).
#
# Reference equivalent: tf_euler/scripts/dist_tf_euler.sh (PS + worker
# processes + ZK-registered graph shards) — here there are no parameter
# servers (gradients all-reduce inside the jitted step) and no ZooKeeper
# (flat-file registry).
#
# Usage: examples/dist_train.sh DATA_DIR NUM_SHARDS [extra run_loop flags...]
set -euo pipefail

DATA_DIR=${1:?usage: dist_train.sh DATA_DIR NUM_SHARDS [flags...]}
NUM_SHARDS=${2:?usage: dist_train.sh DATA_DIR NUM_SHARDS [flags...]}
shift 2

REGISTRY=$(mktemp -d /tmp/euler_registry.XXXXXX)
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$ROOT${PYTHONPATH:+:$PYTHONPATH}"

pids=()
cleanup() { kill "${pids[@]}" 2>/dev/null || true; }
trap cleanup EXIT

# Shards 1..N-1 as standalone service processes; shard 0 is served by the
# training process itself (--graph_mode=shared).
for ((s = 1; s < NUM_SHARDS; s++)); do
  python -m euler_tpu.graph.service \
    --data_dir "$DATA_DIR" --shard_idx "$s" --shard_num "$NUM_SHARDS" \
    --registry "$REGISTRY" &
  pids+=($!)
done

python -m euler_tpu \
  --data_dir "$DATA_DIR" --graph_mode shared --registry "$REGISTRY" \
  --num_processes "$NUM_SHARDS" --process_id 0 "$@"
