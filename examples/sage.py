"""Self-contained supervised GraphSAGE on (synthetic) PPI.

Reference equivalent: examples/sage.py:80-98 — batch 512, fanouts [10,10],
dim 256, Adam 0.01, 2000 steps, streaming micro-F1. Data prep is the
synthetic PPI-scale generator (euler_tpu/datasets.py) because this
environment has no network egress; swap in real PPI by pointing --data_dir
at a directory of converted .dat partitions (euler_tpu.graph.convert).

    PYTHONPATH=. python examples/sage.py [--steps 2000] [--data_dir DIR]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import euler_tpu
from euler_tpu.parallel import probe_backend_or_die

probe_backend_or_die()  # fail fast (with options) on a wedged TPU relay
from euler_tpu import train as train_lib
from euler_tpu.datasets import PPI, build_ppi
from euler_tpu.models import SupervisedGraphSage


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default="/tmp/euler_tpu_ppi")
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch_size", type=int, default=512)
    args = ap.parse_args()

    build_ppi(args.data_dir)
    graph = euler_tpu.Graph(directory=args.data_dir)
    model = SupervisedGraphSage(
        label_idx=0,
        label_dim=PPI["label_dim"],
        metapath=[[0], [0]],
        fanouts=[10, 10],
        dim=256,
        feature_idx=1,
        feature_dim=PPI["feature_dim"],
        max_id=PPI["num_nodes"] - 1,
    )

    def source(step):
        return np.asarray(graph.sample_node(args.batch_size, -1))

    state, history = train_lib.train(
        model,
        graph,
        source,
        num_steps=args.steps,
        optimizer="adam",
        learning_rate=0.01,
        log_every=100,
        prefetch_threads=4,
        prefetch_depth=3,
    )
    print("final:", history[-1])


if __name__ == "__main__":
    main()
