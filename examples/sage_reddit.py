"""Supervised GraphSAGE on (synthetic) Reddit.

Reference equivalent: examples/sage_reddit.py:80-97 — batch 1000, fanouts
[4,4], dim 64, Adam 0.03, 2000 steps, softmax classes. Synthetic data at
Reddit scale (232965 nodes, 602-dim features, 41 classes) — see
examples/sage.py for why.

    PYTHONPATH=. python examples/sage_reddit.py [--steps 2000]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import euler_tpu
from euler_tpu.parallel import probe_backend_or_die

probe_backend_or_die()  # fail fast (with options) on a wedged TPU relay
from euler_tpu import train as train_lib
from euler_tpu.datasets import REDDIT, build_reddit
from euler_tpu.models import SupervisedGraphSage


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default="/tmp/euler_tpu_reddit")
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch_size", type=int, default=1000)
    args = ap.parse_args()

    build_reddit(args.data_dir)
    graph = euler_tpu.Graph(directory=args.data_dir)
    model = SupervisedGraphSage(
        label_idx=0,
        label_dim=REDDIT["label_dim"],
        metapath=[[0], [0]],
        fanouts=[4, 4],
        dim=64,
        feature_idx=1,
        feature_dim=REDDIT["feature_dim"],
        max_id=REDDIT["num_nodes"] - 1,
        sigmoid_loss=False,
    )

    def source(step):
        return np.asarray(graph.sample_node(args.batch_size, -1))

    state, history = train_lib.train(
        model,
        graph,
        source,
        num_steps=args.steps,
        optimizer="adam",
        learning_rate=0.03,
        log_every=100,
        prefetch_threads=4,
        prefetch_depth=3,
    )
    print("final:", history[-1])


if __name__ == "__main__":
    main()
