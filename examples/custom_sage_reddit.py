"""Build-your-own-GraphSAGE on (synthetic) Reddit from the primitive ops.

Reference equivalent: examples/gcn_sage_reddit.py — that example's point
is not the model (it re-implements mean-aggregator GraphSAGE) but the
EXTENSION API: a user model composed from the framework's primitives
(custom aggregator layer -> custom encoder -> custom model) rather than
the model zoo. The same recipe here, the euler_tpu way: the model is a
(host sample phase, flax module) pair —

  sample(graph, roots): ops.sample_fanout + graph.get_dense_feature
                        (numpy, runs in prefetch threads)
  _CustomSage(nn.Module): per-layer mean aggregation + softmax loss
                        (pure JAX, one XLA program)

For graphs with SPARSE id features instead of dense vectors, swap the
encoder for euler_tpu.nn.SparseSageEncoder (reference
encoders.py:522-560): host-side, gather per-hop padded sparse ids with
graph.get_sparse_feature; device-side the encoder embeds each slot
(16-dim, concatenated) and Sage-aggregates — same fanout/hop layout as
here.

    PYTHONPATH=. python examples/custom_sage_reddit.py [--steps 2000]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import flax.linen as nn
import jax.numpy as jnp
import optax

import euler_tpu
from euler_tpu.parallel import probe_backend_or_die

probe_backend_or_die()  # fail fast (with options) on a wedged TPU relay
from euler_tpu import ops
from euler_tpu import train as train_lib
from euler_tpu.datasets import REDDIT, build_reddit
from euler_tpu.models import base
from euler_tpu.nn import metrics


class MeanAggregator(nn.Module):
    """Neighbors-only mean aggregation (reference gcn_sage_reddit.py
    MeanAggregator: reduce_mean over the fanout axis, then dense)."""

    dim: int
    use_activation: bool = True

    @nn.compact
    def __call__(self, neigh):  # [batch, fanout, dim_in]
        agg = jnp.mean(neigh, axis=1)
        out = nn.Dense(self.dim, use_bias=False)(agg)
        return nn.relu(out) if self.use_activation else out


class _CustomSage(nn.Module):
    """The reference example's SageEncoder + softmax decoder: layer L
    aggregates hop h+1 into hop h for every remaining hop, no self/concat
    path (unlike the zoo's SageEncoder)."""

    fanouts: tuple
    dim: int
    num_classes: int

    @nn.compact
    def __call__(self, batch):
        hidden = batch["hops"]  # per-hop [n_h, feature_dim] features
        num_layers = len(self.fanouts)
        for layer in range(num_layers):
            agg = MeanAggregator(
                self.dim, use_activation=layer < num_layers - 1
            )
            hidden = [
                agg(
                    hidden[hop + 1].reshape(
                        hidden[hop].shape[0], self.fanouts[hop], -1
                    )
                )
                for hop in range(num_layers - layer)
            ]
        embedding = hidden[0]
        logits = nn.Dense(self.num_classes)(embedding)
        labels = batch["labels"]
        loss = optax.softmax_cross_entropy(logits, labels).mean()
        preds = nn.one_hot(jnp.argmax(logits, -1), self.num_classes)
        return base.ModelOutput(
            embedding=embedding,
            loss=loss,
            metric_name="f1",
            metric=metrics.f1_counts(labels, preds),
        )


class CustomSage(base.Model):
    metric_name = "f1"

    def __init__(self, fanouts, dim, feature_idx, feature_dim, label_idx,
                 label_dim, edge_type=(0,)):
        super().__init__()
        self.fanouts = tuple(fanouts)
        self.feature_idx = feature_idx
        self.feature_dim = feature_dim
        self.label_idx = label_idx
        self.label_dim = label_dim
        self.edge_types = [list(edge_type)] * len(fanouts)
        self.module = _CustomSage(self.fanouts, dim, label_dim)

    def sample(self, graph, inputs) -> dict:
        roots = np.asarray(inputs, dtype=np.int64).reshape(-1)
        ids_per_hop, _, _ = ops.sample_fanout(
            graph, roots, self.edge_types, list(self.fanouts)
        )
        hops = [
            graph.get_dense_feature(
                ids, [self.feature_idx], [self.feature_dim]
            )
            for ids in ids_per_hop
        ]
        labels = graph.get_dense_feature(
            roots, [self.label_idx], [self.label_dim]
        )
        return {"hops": hops, "labels": labels}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data_dir", default="/tmp/euler_tpu_reddit")
    ap.add_argument("--steps", type=int, default=2000)
    ap.add_argument("--batch_size", type=int, default=1000)
    args = ap.parse_args()

    build_reddit(args.data_dir)
    graph = euler_tpu.Graph(directory=args.data_dir)
    model = CustomSage(
        fanouts=[4, 4],
        dim=64,
        feature_idx=1,
        feature_dim=REDDIT["feature_dim"],
        label_idx=0,
        label_dim=REDDIT["label_dim"],
    )

    def source(step):
        return np.asarray(graph.sample_node(args.batch_size, -1))

    state, history = train_lib.train(
        model,
        graph,
        source,
        num_steps=args.steps,
        optimizer="adam",
        learning_rate=0.03,
        log_every=100,
        prefetch_threads=4,
        prefetch_depth=3,
    )
    print("final:", history[-1])


if __name__ == "__main__":
    main()
