#!/usr/bin/env python3
"""Collect a dead cluster's postmortem dumps and merge them into one
incident timeline.

When a shard dies (SIGSEGV, abort, OOM kill mid-handler), its blackbox
(graph/_native/eg_blackbox) writes ``postmortem.<pid>.json`` into the
shard's ``--postmortem_dir``: flight-recorder rings, the full counter
ledger, admission gauges, resource history, and a backtrace. This
script is the incident-response half (DEPLOY.md runbook: "shard died →
scripts/postmortem.py BEFORE restarting"):

  * **collect** — parse every dump in a directory (shared-FS clusters
    drop all shards' dumps in one place; per-host dirs can be rsync'd
    together first) and print a per-dump summary: signal, shard,
    counters that moved, resource tail, the flight-recorder tail;
  * **merge** — fold the dumps into a client-side Chrome trace (the
    ``run_loop --trace_file`` export): each dump becomes a process
    lane of instant events on the shared CLOCK_MONOTONIC timeline,
    and every wire-v3 trace id seen on BOTH a client rpc slice and a
    dead shard's ring gets a flow arrow — the incident reads as ONE
    timeline from the training step to the exact request the shard
    died serving.

Usage:
    python scripts/postmortem.py --dir /shared/postmortems
    python scripts/postmortem.py --dir pm/ --trace run.trace.json \\
        --out incident.json          # open incident.json in Perfetto
    python scripts/postmortem.py --smoke   # self-contained drill
                                           # (verify.sh gate)

See OBSERVABILITY.md "Postmortems" for the file format and the
async-signal-safety constraints it honors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# pid lane for postmortem shards in the merged trace: distinct from the
# live-scrape shard lanes (trace.py PID_SHARD_BASE = 100) so a trace
# that has BOTH (shard scraped before it died, dump after) stays legible
PID_POSTMORTEM_BASE = 200


def summarize(dump: dict, out=sys.stdout) -> None:
    """Human summary of one postmortem dump."""
    print(f"== {dump.get('path', '?')} ==", file=out)
    print(f"  {dump['signal_name']} (signal {dump['signal']})  "
          f"pid {dump['pid']}  shard {dump['shard']}", file=out)
    moved = {k: v for k, v in dump["counters"].items() if v}
    if moved:
        print(f"  counters: {moved}", file=out)
    if dump.get("gauges"):
        print(f"  admission: {dump['gauges']}", file=out)
    hist = dump.get("resource_history", [])
    if hist:
        r = hist[-1]
        print(f"  resource at death: rss {r['rss_bytes'] / 1e6:.1f}MB  "
              f"fds {r['open_fds']}  threads {r['threads']}  "
              f"cache {r['cache_bytes'] / 1e6:.1f}MB  "
              f"({len(hist)} samples)", file=out)
    for ring in dump.get("rings", []):
        evs = ring["events"]
        if not evs:
            continue
        print(f"  ring tid={ring['tid']} ({ring['head']} events, "
              f"last {min(len(evs), 5)}):", file=out)
        for e in evs[-5:]:
            print(f"    {e['t_us']:>14d}us {e['point']:12s} "
                  f"op={e['op']:<2d} shard={e['shard']:<3d} "
                  f"value={e['value']:<8d} trace={int(e['trace']):#x}",
                  file=out)
    if dump.get("backtrace_symbols"):
        print(f"  backtrace ({len(dump['backtrace_symbols'])} frames):",
              file=out)
        for line in dump["backtrace_symbols"][:6]:
            print(f"    {line}", file=out)


def _dump_trace_events(dump: dict, pid: int) -> list:
    """One dump's rings -> instant events on its own process lane.

    Ring events become cat="rpc" instants carrying the trace id and a
    side label, so trace.py's correlated_trace_ids() and the flow
    emitter below treat a dead shard's last-seen requests exactly like
    a live shard's journal spans."""
    events = []
    label = (f"postmortem shard {dump['shard']} "
             f"({dump['signal_name']}, pid {dump['pid']})")
    events.append({
        "name": "process_name", "ph": "M", "pid": pid,
        "args": {"name": label},
    })
    for tid, ring in enumerate(dump.get("rings", []), start=1):
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": f"ring tid={ring['tid']}"},
        })
        for e in ring["events"]:
            ev = {
                "name": e["point"], "cat": "rpc", "ph": "i", "s": "t",
                "ts": e["t_us"], "pid": pid, "tid": tid,
                "args": {
                    "trace": f"{int(e['trace']):#x}",
                    "side": "server",
                    "outcome": e["outcome"], "shard": e["shard"],
                    "op": e["op"], "value": e["value"],
                    "source": label,
                },
            }
            events.append(ev)
    return events


def merge_trace(dumps: list, base_trace: dict | None = None) -> dict:
    """Merge postmortem dumps into a (possibly empty) client trace.

    Returns the merged Chrome-trace dict; every wire-v3 trace id seen
    on BOTH a client rpc slice (the --trace file) and a dead shard's
    ring gets an s/f flow arrow, so Perfetto draws the line from the
    training step to the request the shard died serving."""
    events = list((base_trace or {}).get("traceEvents", []))
    for i, dump in enumerate(dumps):
        shard = dump.get("shard", -1)
        pid = PID_POSTMORTEM_BASE + (shard if shard >= 0 else 50 + i)
        events.extend(_dump_trace_events(dump, pid))
    # flow arrows: client slice -> postmortem instant, keyed by trace id
    clients: dict = {}
    servers: dict = {}
    for ev in events:
        args = ev.get("args") or {}
        if ev.get("cat") != "rpc" or "trace" not in args:
            continue
        if int(args["trace"], 16) == 0:
            continue
        if args.get("side") == "client":
            clients.setdefault(args["trace"], ev)
        elif ev["pid"] >= PID_POSTMORTEM_BASE:
            servers.setdefault(args["trace"], ev)
    for trace, cli in clients.items():
        srv = servers.get(trace)
        if srv is None:
            continue
        common = {"name": "fatal-rpc", "cat": "rpc-flow", "id": trace}
        events.append({**common, "ph": "s", "ts": cli["ts"],
                       "pid": cli["pid"], "tid": cli["tid"]})
        events.append({**common, "ph": "f", "bp": "e", "ts": srv["ts"],
                       "pid": srv["pid"], "tid": srv["tid"]})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def correlated_fatal_ids(merged: dict) -> set:
    """Trace ids linked client-side AND in a postmortem lane — the
    'incident reads as one timeline' pin the acceptance test asserts."""
    sides: dict = {}
    for ev in merged["traceEvents"]:
        args = ev.get("args") or {}
        if ev.get("cat") != "rpc" or "trace" not in args:
            continue
        if int(args["trace"], 16) == 0:
            continue
        if args.get("side") == "client":
            sides.setdefault(args["trace"], set()).add("client")
        elif ev["pid"] >= PID_POSTMORTEM_BASE:
            sides.setdefault(args["trace"], set()).add("postmortem")
    return {t for t, ss in sides.items()
            if {"client", "postmortem"} <= ss}


def run_smoke() -> int:
    """Self-contained incident drill (the verify.sh gate): live 2-shard
    subprocess cluster, shard 1 restarted with a seeded crash
    failpoint, client traffic kills it, then collect + merge and assert
    the timeline correlates by trace id."""
    import shutil
    import socket
    import subprocess
    import tempfile
    import time

    import euler_tpu
    from euler_tpu import trace as trace_mod
    from scripts.remote_bench import build_powerlaw_fixture

    tmp = tempfile.mkdtemp(prefix="euler_postmortem_smoke_")
    procs = []

    def launch(idx, fault=None, pmdir=None):
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        cmd = [sys.executable, "-m", "euler_tpu.graph.service",
               "--data_dir", data, "--shard_idx", str(idx),
               "--shard_num", "2", "--registry", reg]
        if fault:
            cmd += ["--fault", fault, "--fault_seed", "7"]
        if pmdir:
            cmd += ["--postmortem_dir", pmdir]
        p = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                             stderr=subprocess.DEVNULL, env=env)
        procs.append(p)
        return p

    def wait_up(idx, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for f in os.listdir(reg):
                if not f.startswith(f"{idx}#"):
                    continue
                host, port = f.split("#", 1)[1].rsplit("_", 1)
                try:
                    with socket.create_connection((host, int(port)), 1.0):
                        return
                except OSError:
                    continue
            time.sleep(0.1)
        raise TimeoutError(f"shard {idx} never came up")

    try:
        data = os.path.join(tmp, "data")
        os.makedirs(data)
        build_powerlaw_fixture(data, 120, 6, 8)
        reg = os.path.join(tmp, "reg")
        os.makedirs(reg)
        pmdir = os.path.join(tmp, "pm")
        os.makedirs(pmdir)

        launch(0)
        victim = launch(1)
        wait_up(0)
        wait_up(1)
        g = euler_tpu.Graph(
            mode="remote", registry=reg, retries=1, timeout_ms=1500,
            backoff_ms=10, rediscover_ms=200,
        )
        try:
            euler_tpu.telemetry_reset()
            roots = g.sample_node(16, -1)
            g.get_dense_feature(roots, [0], [8])

            # the incident: shard 1 comes back armed to die on its next
            # request, with the postmortem path armed
            victim.terminate()
            victim.wait(timeout=30)
            for f in list(os.listdir(reg)):
                if f.startswith("1#"):
                    os.unlink(os.path.join(reg, f))
            victim = launch(1, fault="crash:err@1#1", pmdir=pmdir)
            wait_up(1)
            time.sleep(0.5)  # let the client re-discover the new port

            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                g.sample_node(8, -1)
                g.get_dense_feature(roots, [0], [8])
                if any(f.startswith("postmortem.")
                       for f in os.listdir(pmdir)):
                    break
                time.sleep(0.2)
            dumps = euler_tpu.postmortem_read(pmdir)
            assert dumps, "no postmortem written by the crashed shard"
            dump = dumps[-1]
            assert dump["signal_name"] == "SIGSEGV", dump["signal_name"]
            assert dump["counters"]["crashes"] == 1, dump["counters"]
            recvs = [e for ring in dump["rings"] for e in ring["events"]
                     if e["point"] == "server_recv"]
            assert recvs, "fatal call not in the flight-recorder tail"

            # client-side trace (run_loop --trace_file form), then merge
            trace_path = os.path.join(tmp, "client.trace.json")
            client_trace = trace_mod.write_trace(trace_path, None, g)
            merged = merge_trace(dumps, client_trace)
            out_path = os.path.join(tmp, "incident.json")
            with open(out_path, "w") as f:
                json.dump(merged, f)
            trace_mod.validate_chrome_trace(merged)
            linked = correlated_fatal_ids(merged)
            assert linked, (
                "no trace id correlated between the client journal and "
                "the dead shard's postmortem rings"
            )
            for d in dumps:
                summarize(d)
            print(f"postmortem smoke: OK ({len(dumps)} dump(s), "
                  f"{len(linked)} fatal call(s) correlated)")
            return 0
        finally:
            g.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--dir", default="", help=(
        "postmortem directory to collect (every postmortem.*.json; "
        "rsync per-host dirs together first on multi-host clusters)"))
    ap.add_argument("--trace", default="", help=(
        "client-side Chrome trace (run_loop --trace_file / "
        "trace_dump.py output) to merge the dumps into"))
    ap.add_argument("--out", default="", help=(
        "write the merged incident trace here (open in "
        "ui.perfetto.dev)"))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable: one JSON array of dumps")
    ap.add_argument("--smoke", action="store_true", help=(
        "self-contained incident drill against a live 2-shard cluster "
        "(the verify.sh gate)"))
    args = ap.parse_args()

    if args.smoke:
        return run_smoke()
    if not args.dir:
        ap.error("need --dir (or --smoke)")

    import euler_tpu

    dumps = euler_tpu.postmortem_read(args.dir)
    if not dumps:
        print(f"no postmortem.*.json dumps in {args.dir}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(dumps))
    else:
        for d in dumps:
            summarize(d)
    base = None
    if args.trace:
        with open(args.trace) as f:
            base = json.load(f)
    if args.out or args.trace:
        merged = merge_trace(dumps, base)
        linked = correlated_fatal_ids(merged)
        out_path = args.out or "incident.json"
        with open(out_path, "w") as f:
            json.dump(merged, f)
        print(f"incident trace: {len(merged['traceEvents'])} events, "
              f"{len(linked)} fatal call(s) correlated client<->shard "
              f"-> {out_path} (open in ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
