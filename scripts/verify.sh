#!/usr/bin/env bash
# One exit-code-honest verification gate (see STATIC_ANALYSIS.md):
#   invariant linter -> ruff -> mypy -> compileall floor -> tier-1 pytest
#
# Every step that RUNS contributes to the exit code; a tool that is not
# installed in this image is skipped LOUDLY (ruff/mypy may be absent in
# the hermetic container — their configs in pyproject.toml apply wherever
# they do exist). `make analyze` (gcc -fanalyzer + cppcheck/clang-tidy)
# is a separate, slower gate: run it when touching _native/.
#
# Usage: scripts/verify.sh          (from anywhere; cd's to the repo root)
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
step() { printf '\n== %s ==\n' "$1"; }

step "native invariant linter (scripts/check_native.py)"
python scripts/check_native.py || fail=1

step "escape audit (scripts/check_native.py --escapes)"
# Every `eg-lint: allow(...)` escape must still suppress something —
# a stale escape is a waiver nobody is using that will waive the NEXT
# real violation on that line.
python scripts/check_native.py --escapes || fail=1

step "cross-layer contract analyzer (scripts/check_contracts.py)"
# ABI/wire/ledger/config parity + lock discipline + artifact hygiene
# (STATIC_ANALYSIS.md "Cross-layer contracts").
python scripts/check_contracts.py || fail=1

step "ruff"
if command -v ruff >/dev/null 2>&1; then
  ruff check euler_tpu scripts tests examples bench.py || fail=1
else
  echo "SKIPPED: ruff not installed in this image (config: pyproject.toml [tool.ruff])"
fi

step "mypy"
if command -v mypy >/dev/null 2>&1; then
  mypy euler_tpu || fail=1
else
  echo "SKIPPED: mypy not installed in this image (config: pyproject.toml [tool.mypy])"
fi

step "remote-bench smoke (scripts/remote_bench.py --smoke)"
# End-to-end remote hot path against a real in-process 2-shard cluster:
# asserts the dedup/cache ledger shows a real ids-on-wire reduction, so
# a silent coalescing regression fails verify before it reaches PERF.md.
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/remote_bench.py --smoke >/dev/null || fail=1

step "chaos soak + failpoint counters (FAULTS.md)"
# Runs the fault-injection suites by name so a transport regression
# fails fast with a targeted log, before the full tier-1 sweep below
# (which includes them again as ordinary members).
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_fault_injection.py tests/test_chaos_soak.py -q \
  -p no:cacheprovider || fail=1

step "telemetry + step-phase profiler suites + scrape/trace smokes (OBSERVABILITY.md)"
# Histograms/trace spans/STATS scrape + the step-phase profiler: the
# deterministic-bucket, stall-attribution, and scrape-parity pins, then
# a real metrics_dump scrape and a trace_dump Perfetto export against a
# live 2-shard cluster — a silent telemetry regression fails verify
# before any perf PR cites its numbers.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_telemetry.py tests/test_phase_profiler.py -q \
  -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/metrics_dump.py --smoke >/dev/null || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/trace_dump.py --smoke >/dev/null || fail=1

step "data-plane heat: sketch exactness + doc-drift gate + skew-report smoke (OBSERVABILITY.md 'Data-plane heat')"
# The eg_heat access profiler: space-saving/count-min exactness pins,
# the ids ledger identity on a live cluster, the metric-name doc-drift
# gate (every eg_* family emitted by metrics_text() must be in the
# OBSERVABILITY.md glossary and vice versa), then a real heat_dump skew
# report against a 2-shard cluster — ROADMAP item 5's pre-measurement
# instrument cannot silently rot.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_heat.py tests/test_metric_docs.py -q \
  -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/heat_dump.py --smoke >/dev/null || fail=1

step "locality: placement routing + frequency-aware caches + A/B smoke (PERF.md 'Locality')"
# The ROADMAP item 5 layer: degree-aware partitioner validation, exact
# TinyLFU admit/reject ledgers, neighbor-cache promotion arithmetic,
# and the live hash-vs-placement A/B (edge-cut strictly down on the
# same graph) — a silent locality regression fails verify before any
# PR cites the edge-cut numbers.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_locality.py -q -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/heat_dump.py --ab-smoke >/dev/null || fail=1

step "blackbox postmortem drill (OBSERVABILITY.md 'Postmortems')"
# The flight-recorder/crash-dump suites by name, then the incident
# drill: a seeded crash failpoint kills a live shard, the postmortem is
# collected and merged with the client trace by trace id — a silent
# regression in the forensic path fails verify before the incident
# that needed it.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_blackbox.py -q -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/postmortem.py --smoke >/dev/null || fail=1

step "rolling-restart drill + connection storm + wire fuzz (DEPLOY.md runbook)"
# Server-side survivability: SIGTERM-drain/restart of every shard
# mid-training with zero failed calls, BUSY load-shedding under a
# 32-client storm, and malformed-frame/wire-version fuzzing against a
# live service.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_rolling_restart.py tests/test_wire_fuzz.py -q \
  -p no:cacheprovider || fail=1

step "snapshot epochs: delta flips + failpoint arithmetic + live flip drill (DEPLOY.md 'Rolling graph refresh')"
# eg_epoch: whole-step consistency under the depth-2 async ring, exact
# delta_load/epoch_flip failpoint counters, contradictory-delta
# refusals, then the live drill — GraphSAGE training while each shard
# flips mid-flight, zero failed calls, loss parity on the unchanged
# subgraph, post-flip reads bit-identical to a fresh merged load.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_epoch.py -q -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/epoch_drill.py --smoke >/dev/null || fail=1

step "serve: micro-batch parity + shedding + closed-loop load drill (DEPLOY.md 'Serving runbook')"
# eg_serve: SLO math + batcher coalescing/shedding/deadline pins, the
# bit-parity contract under concurrent mixed traffic, then the
# closed-loop drill — 16 clients over a live 2-shard cluster, p99
# bounded, shedding proven on a live scrape, served rows bit-identical
# to the direct forward.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_serve.py -q -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/serve_drill.py --smoke >/dev/null || fail=1

step "device plane: recompile attribution + merged-trace drill (OBSERVABILITY.md 'Device plane')"
# eg_devprof: exact recompile arithmetic under injected shape drift,
# kill-switch silence, the serve compile-storm guard on a live drill,
# then the devprof_dump smoke — jit, drift, profiler capture, and a
# validated host+device Perfetto merge — so a silent regression in the
# compile ledger or the trace alignment fails verify first.
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_devprof.py -q -p no:cacheprovider || fail=1
timeout -k 10 300 env JAX_PLATFORMS=cpu \
  python scripts/devprof_dump.py --smoke >/dev/null || fail=1

step "perf gate (scripts/perf_gate.py — strict for bench_smoke, warn-only remote)"
# Smoke-to-smoke throughput trajectory check (PERF.md "Throughput
# trajectory"). The host-only bench.py --smoke config now GATES verify
# (its history has a multi-round trajectory and it runs without the
# remote path's 1-core container noise); the remote configs stay
# warn-only. `perf_gate.py --strict` enforces everything.
timeout -k 10 600 env JAX_PLATFORMS=cpu \
  python scripts/perf_gate.py --strict-configs bench_smoke || fail=1

step "sanitizer smoke (scripts/sanitize.sh --smoke; SANITIZERS.md)"
# One TSAN round over the fuzz barrage (16 threads of garbage +
# concurrent valid traffic against a live service — the densest
# concurrency per wall-clock second in the tree). The instrumented
# side build under _native/.sanitize/ is incremental, so this is
# seconds once warm; the full round set is scripts/sanitize.sh.
timeout -k 10 600 scripts/sanitize.sh --smoke || fail=1

step "python syntax floor (compileall)"
# stdlib floor under the optional tools above: at minimum, every file parses
python -m compileall -q euler_tpu tests scripts examples bench.py || fail=1

step "tier-1 tests (ROADMAP.md)"
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
[ "$rc" -ne 0 ] && fail=1

step "verdict"
if [ "$fail" -ne 0 ]; then
  echo "verify: FAIL"
  exit 1
fi
echo "verify: OK"
