#!/usr/bin/env python3
"""Remote-path benchmark: edges/s + counter ledger over a local 2-shard
cluster, before/after the hot-path optimizations.

The ROADMAP's scaling story — shard the graph, serve millions of users —
had no PERF.md row until this script: the single-chip device path is
measured to death while the remote client was never timed at all. This
drives the workload the remote client actually serves in training (a
2-hop fanout + a dense-feature batch over the fanout frontier, the
model.sample shape) against REAL shard services on localhost, twice:

  baseline   coalesce=0, feature_cache_mb=0 — the pre-PR wire shape
             (every duplicate id re-sent, every feature row refetched)
  optimized  defaults — persistent dispatcher + duplicate-id coalescing
             + client-side feature-row cache

and reports edges/s for both plus the counter ledger
(ids_deduped / cache_hits / cache_misses / rpc_chunks, FAULTS.md) and
the ids-on-wire accounting
(ids_on_wire = ids_requested - ids_deduped - cache_hits).

The graph is synthetic power-law (hub-heavy, the Reddit shape): hubs
carry most edge mass, so the fanout frontier is dominated by duplicate
hub ids — exactly the regime the optimizations target. Localhost TCP
understates the win of cutting wire BYTES (loopback bandwidth is free);
the dedup win measured here is mostly serialization + server lookup
work, so treat the edges/s ratio as a floor for real networks.

Usage:
    python scripts/remote_bench.py             # full run, JSON to stdout
    python scripts/remote_bench.py --smoke     # small/fast (verify.sh)
    python bench.py --configs remote           # same, bench-driver shaped

Subprocess shards by default (one OS process per shard, like the chaos
soak) so server CPU is not attributed to the client loop; --inproc uses
in-process services (faster startup, used by --smoke).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_SHARDS = 2
NUM_PARTITIONS = 4

PL_META = {
    "node_type_num": 2,
    "edge_type_num": 2,
    "node_uint64_feature_num": 1,
    "node_float_feature_num": 1,
    "node_binary_feature_num": 0,
    "edge_uint64_feature_num": 0,
    "edge_float_feature_num": 0,
    "edge_binary_feature_num": 0,
}


def powerlaw_fixture_nodes(num_nodes: int, avg_degree: int,
                           feature_dim: int, alpha: float = 1.1,
                           seed: int = 7) -> list:
    """Node dicts of the hub-heavy synthetic graph: zipf(alpha)-ranked
    destination draws, so the first few ids soak up most edge mass (the
    Reddit heavy tail at bench size). Split from the .dat writer so the
    locality A/B (scripts/heat_dump.py --ab-smoke) can partition ONE
    node set two ways."""
    rng = np.random.default_rng(seed)
    # zipf-ish rank weights over destinations
    ranks = np.arange(1, num_nodes + 1, dtype=np.float64)
    probs = ranks ** (-alpha)
    probs /= probs.sum()
    nodes = []
    for nid in range(num_nodes):
        deg = max(1, int(rng.poisson(avg_degree)))
        dsts = rng.choice(num_nodes, size=deg, p=probs)
        groups: dict = {}
        for d in dsts:
            d = int(d)
            t = d % 2
            groups.setdefault(t, {})
            groups[t][d] = groups[t].get(d, 0.0) + 1.0
        nodes.append(
            {
                "node_id": nid,
                "node_type": nid % 2,
                "node_weight": 1.0,
                "neighbor": {
                    str(t): {str(d): w for d, w in g.items()}
                    for t, g in groups.items()
                },
                "uint64_feature": {"0": [nid]},
                "float_feature": {
                    "0": (np.arange(feature_dim) * 0.01 + nid * 0.001)
                    .astype(float).tolist()
                },
                "binary_feature": {},
                "edge": [
                    {
                        "src_id": nid, "dst_id": d, "edge_type": t,
                        "weight": w, "uint64_feature": {},
                        "float_feature": {}, "binary_feature": {},
                    }
                    for t, g in groups.items()
                    for d, w in g.items()
                ],
            }
        )
    return nodes


def build_powerlaw_fixture(directory: str, num_nodes: int, avg_degree: int,
                           feature_dim: int, alpha: float = 1.1,
                           seed: int = 7, placement: str = "hash") -> None:
    """Partition the hub-heavy fixture into NUM_PARTITIONS .dat files
    (placement='degree' adds the converter's placement artifact)."""
    import euler_tpu

    euler_tpu.convert_dicts(
        powerlaw_fixture_nodes(num_nodes, avg_degree, feature_dim, alpha,
                               seed),
        PL_META, os.path.join(directory, "part"),
        num_partitions=NUM_PARTITIONS, placement=placement,
    )


def _launch_shards_subproc(data: str, reg: str):
    """One OS process per shard (the chaos-soak launcher shape)."""
    import socket
    import subprocess

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "euler_tpu.graph.service",
             "--data_dir", data, "--shard_idx", str(s),
             "--shard_num", str(NUM_SHARDS), "--registry", reg],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        )
        for s in range(NUM_SHARDS)
    ]
    deadline = time.monotonic() + 90.0
    for s in range(NUM_SHARDS):
        while True:
            entry = next(
                (f for f in os.listdir(reg) if f.startswith(f"{s}#")), None
            )
            if entry is not None:
                host, port = entry.split("#", 1)[1].rsplit("_", 1)
                try:
                    with socket.create_connection((host, int(port)), 1.0):
                        break
                except OSError:
                    pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"shard {s} never registered in {reg}")
            time.sleep(0.1)
    return procs


def _launch_shards_inproc(data: str, reg: str):
    from euler_tpu.graph.service import GraphService

    return [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]


def run_workload(graph, steps: int, batch: int, fanouts, feature_dim: int,
                 seed: int = 5):
    """The training-shaped remote workload: per step draw roots, run the
    2-hop fanout, fetch dense features for the full frontier (roots +
    both hops — what model.sample feeds the encoder). Returns (edges/s,
    wall s, ids_requested) where ids_requested counts every id a
    pre-dedup client would put on the wire."""
    from euler_tpu.graph import native
    from euler_tpu.telemetry import record_phase

    f1, f2 = fanouts
    edges_per_step = batch * (f1 + f1 * f2)
    native.lib().eg_seed(seed)
    requested = 0
    t0 = time.perf_counter()
    for _ in range(steps):
        t_step = time.perf_counter()
        roots = graph.sample_node(batch, -1)
        hop_ids, _, _ = graph.sample_fanout(roots, [[0, 1], [0, 1]], [f1, f2])
        requested += batch + batch * f1  # fanout hop inputs
        frontier = np.concatenate(hop_ids)
        graph.get_dense_feature(frontier, [0], [feature_dim])
        requested += len(frontier)
        # step-phase profiler hooks ride the measured loop so the
        # telemetry on/off A/B prices them too (the <2% overhead
        # contract now covers the profiler, not just the RPC histograms)
        dur_us = (time.perf_counter() - t_step) * 1e6
        record_phase("sample", dur_us)
        record_phase("step", dur_us)
    dt = time.perf_counter() - t0
    return edges_per_step * steps / dt, dt, requested


def bench_config(reg: str, steps: int, batch: int, fanouts,
                 feature_dim: int, label: str, **graph_kwargs):
    """One measured client configuration against the running cluster:
    returns {edges_per_sec, wall_s, ids_requested, ids_on_wire,
    counters} for `steps` workload iterations (after one untimed warmup
    step that pays dial/compile costs)."""
    import euler_tpu
    from euler_tpu.graph import native

    g = euler_tpu.Graph(mode="remote", registry=reg, **graph_kwargs)
    try:
        run_workload(g, 1, batch, fanouts, feature_dim)  # warm dials/cache
        native.reset_counters()
        eps, dt, requested = run_workload(g, steps, batch, fanouts,
                                          feature_dim)
        ctr = native.counters()
    finally:
        g.close()
    # the PR-3 identity extended by PR 9: neighbor-cache hits are ids
    # served locally too (a hub hop sampled from the cached slice never
    # reaches the wire)
    on_wire = (requested - ctr["ids_deduped"] - ctr["cache_hits"]
               - ctr["nbr_cache_hits"])
    return {
        "label": label,
        "edges_per_sec": round(eps, 1),
        "wall_s": round(dt, 3),
        "ids_requested": requested,
        "ids_on_wire": on_wire,
        "counters": {k: v for k, v in ctr.items() if v},
    }


def depth_sweep(reg: str, steps: int, batch: int, fanouts,
                feature_dim: int, depths=(0, 1, 2, 4)) -> dict:
    """Per-depth input-stall measurement of the async step pipeline
    (PERF.md "Pipelined sampling"): the train.py sampler_depth= shape —
    step k's simulated device compute overlapping step k+1..k+depth's
    whole-step sampling through the engine's completion queue
    (eg_remote_sample_async). depth 0 is the sync before-picture: the
    consumer IS the sampler, so its measured input_stall equals the full
    sample latency. Each arm reports the measured mean consumer stall,
    whether it clears the ROADMAP item-1 threshold (stall < 5% of the
    device step), edges/s, and the counter ledger — the depth-1-vs-2 A/B
    is the PERF.md evidence row."""
    import euler_tpu
    from euler_tpu.graph import native
    from euler_tpu.parallel import pipeline
    from euler_tpu.telemetry import (
        phase_hists,
        record_phase,
        set_telemetry,
        telemetry_reset,
    )

    f1, f2 = fanouts
    edges_per_step = batch * (f1 + f1 * f2)
    # the input_stall histogram IS this measurement — make sure a
    # preceding kill-switch A/B arm didn't leave recording off
    set_telemetry(True)
    g = euler_tpu.Graph(mode="remote", registry=reg)
    try:
        # Calibrate the simulated device step to the measured sync
        # sample time: "hidden" must be a real race between sampling and
        # compute, not a foregone conclusion against a huge device step.
        native.lib().eg_seed(11)
        t0 = time.perf_counter()
        calib = 3
        for _ in range(calib):
            roots = g.sample_node(batch, -1)
            hop_ids, _, _ = g.sample_fanout(
                roots, [[0, 1], [0, 1]], [f1, f2]
            )
            g.get_dense_feature(
                np.concatenate(hop_ids), [0], [feature_dim]
            )
        device_s = max(0.002, (time.perf_counter() - t0) / calib)

        def start_fn(step):
            roots = g.sample_node(batch, -1)
            return roots, g.sample_fanout_async(
                roots, [[0, 1], [0, 1]], [f1, f2]
            )

        def finish_fn(step, pending):
            roots, h = pending
            if h is None:  # async pool exhausted: degrade to sync
                hop_ids, _, _ = g.sample_fanout(
                    roots, [[0, 1], [0, 1]], [f1, f2]
                )
            else:
                hop_ids, _, _ = h.take()
            g.get_dense_feature(
                np.concatenate(hop_ids), [0], [feature_dim]
            )
            return hop_ids

        rows = []
        for depth in depths:
            native.lib().eg_seed(17)
            native.reset_counters()
            telemetry_reset()
            t0 = time.perf_counter()
            if depth == 0:
                for s in range(steps):
                    t_w = time.perf_counter()
                    roots = g.sample_node(batch, -1)
                    hop_ids, _, _ = g.sample_fanout(
                        roots, [[0, 1], [0, 1]], [f1, f2]
                    )
                    g.get_dense_feature(
                        np.concatenate(hop_ids), [0], [feature_dim]
                    )
                    if s > 0:  # steady state only (see below)
                        record_phase(
                            "input_stall",
                            (time.perf_counter() - t_w) * 1e6,
                        )
                    time.sleep(device_s)
            else:
                first = True
                for _ in pipeline(start_fn, finish_fn, steps,
                                  depth=depth):
                    if first:
                        # step 0's stall is the pipeline fill (nothing
                        # was in flight yet) — every depth pays it
                        # identically, so drop it and measure the
                        # steady-state stall the depth actually buys
                        telemetry_reset()
                        first = False
                    time.sleep(device_s)  # simulated device step
            dt = time.perf_counter() - t0
            ctr = native.counters()
            stall_h = phase_hists().get("input_stall")
            stall_ms = (
                stall_h["sum_us"] / stall_h["count"] / 1000.0
                if stall_h and stall_h["count"] else 0.0
            )
            rows.append({
                "sampler_depth": depth,
                "input_stall_ms": round(stall_ms, 3),
                "sampling_hidden_by_prefetch": bool(
                    stall_ms < 0.05 * device_s * 1e3
                ),
                "edges_per_sec": round(edges_per_step * steps / dt, 1),
                "wall_s": round(dt, 3),
                "counters": {
                    k: v for k, v in ctr.items()
                    if v and (k.startswith("async")
                              or k in ("rpc_chunks", "rpc_errors",
                                       "ids_deduped", "cache_hits",
                                       "nbr_cache_hits",
                                       "prefetch_produced"))
                },
            })
        return {"device_step_ms": round(device_s * 1e3, 2), "rows": rows}
    finally:
        g.close()


def heat_ab_paired(reg: str, pairs: int, steps: int, batch: int, fanouts,
                   feature_dim: int) -> dict:
    """Paired interleaved heat on/off measurement on ONE client against
    the running cluster: per pair, both arms run back-to-back (order
    alternating), and the per-pair relative wall difference is the
    sample. Single-shot A/B draws scatter +-4pp on the 1-core container
    (box drift between configs lands entirely in the difference);
    pairing cancels the drift, so the median here is the number the <2%
    overhead contract is judged on (PERF.md "Data-plane heat")."""
    import statistics

    import euler_tpu
    from euler_tpu.heat import set_heat

    g = euler_tpu.Graph(mode="remote", registry=reg)
    try:
        run_workload(g, 2, batch, fanouts, feature_dim)  # warm
        diffs = []
        for pair in range(pairs):
            walls = {}
            arms = [True, False] if pair % 2 == 0 else [False, True]
            for flag in arms:
                set_heat(flag)
                t0 = time.perf_counter()
                run_workload(g, steps, batch, fanouts, feature_dim)
                walls[flag] = time.perf_counter() - t0
            diffs.append(
                (walls[True] - walls[False]) / walls[False] * 100.0
            )
        diffs.sort()
        return {
            "pairs": pairs,
            "steps_per_arm": steps,
            "median_overhead_pct": round(statistics.median(diffs), 2),
            "mean_overhead_pct": round(statistics.mean(diffs), 2),
            "sem_pct": round(
                statistics.stdev(diffs) / len(diffs) ** 0.5, 2
            ) if len(diffs) > 1 else 0.0,
        }
    finally:
        set_heat(True)
        g.close()


def devprof_ab_paired(pairs: int, steps: int) -> dict:
    """Paired interleaved devprof on/off measurement of the device-plane
    hooks on the training hot path: a Watched jit step (recompile
    attribution) plus the per-batch h2d/d2h byte census, exactly the
    instrumentation train.py runs every step. The step is a fixed
    4-layer matmul sized to a real train step (~0.5-1 ms on this CPU
    image) — NOT the smoke graph's toy dims, where a ~10 us dispatch
    would read any fixed per-step hook cost as a huge percentage. Same
    pairing rationale as heat_ab_paired above — per pair both arms run
    back-to-back with alternating order so box drift cancels, and the
    median relative wall difference is the number the <2% overhead
    contract is judged on (OBSERVABILITY.md "Device plane")."""
    import statistics

    import jax
    import jax.numpy as jnp

    from euler_tpu import devprof

    devprof.install()

    def _step(w, x):
        h = x
        for _ in range(4):
            h = jnp.tanh(h @ w)
        return h.sum()

    step = devprof.watch(jax.jit(_step), name="devprof_ab_step")
    w = jnp.ones((128, 128), jnp.float32)
    x = jnp.ones((256, 128), jnp.float32)
    jax.block_until_ready(step(w, x))  # warm: compile priced outside arms
    diffs = []
    try:
        # settle pass (untimed): one full arm's worth of dispatches so
        # allocator/dispatch caches reach steady state before pair 0 —
        # a cold first arm otherwise lands entirely in its difference
        for _ in range(steps):
            out = step(w, x)
        jax.block_until_ready(out)
        for pair in range(pairs):
            walls = {}
            arms = [True, False] if pair % 2 == 0 else [False, True]
            for flag in arms:
                devprof.set_devprof(flag)
                t0 = time.perf_counter()
                for _ in range(steps):
                    devprof.count_h2d((w, x))
                    out = step(w, x)
                    devprof.count_d2h(out)
                jax.block_until_ready(out)
                walls[flag] = time.perf_counter() - t0
            diffs.append(
                (walls[True] - walls[False]) / walls[False] * 100.0
            )
    finally:
        devprof.set_devprof(True)
    diffs.sort()
    return {
        "pairs": pairs,
        "steps_per_arm": steps,
        "median_overhead_pct": round(statistics.median(diffs), 2),
        "mean_overhead_pct": round(statistics.mean(diffs), 2),
        "sem_pct": round(
            statistics.stdev(diffs) / len(diffs) ** 0.5, 2
        ) if len(diffs) > 1 else 0.0,
    }


def run_remote_bench(smoke: bool = False, inproc: bool | None = None,
                     steps: int | None = None) -> dict:
    """Full before/after measurement; returns the bench-driver-shaped
    result dict (metric/value/unit/vs_baseline/detail)."""
    import shutil
    import tempfile

    if smoke:
        num_nodes, avg_degree, feature_dim = 300, 10, 16
        batch, fanouts = 32, (5, 5)
        steps = steps or 4
        if inproc is None:
            inproc = True
    else:
        num_nodes, avg_degree, feature_dim = 20000, 30, 64
        batch, fanouts = 512, (10, 10)
        steps = steps or 20
        if inproc is None:
            inproc = False

    tmp = tempfile.mkdtemp(prefix="euler_remote_bench_")
    data = os.path.join(tmp, "data")
    reg = os.path.join(tmp, "reg")
    os.makedirs(data)
    os.makedirs(reg)
    procs = []
    try:
        build_powerlaw_fixture(data, num_nodes, avg_degree, feature_dim)
        procs = (_launch_shards_inproc if inproc else
                 _launch_shards_subproc)(data, reg)

        # BASELINE: the pre-PR wire shape (dedup + BOTH caches off; the
        # dispatcher still runs — thread spawn/join cannot be re-added)
        before = bench_config(
            reg, steps, batch, fanouts, feature_dim, "baseline",
            coalesce=False, feature_cache_mb=0, neighbor_cache_mb=0,
        )
        # OPTIMIZED: defaults (coalesce on, cache on, telemetry on)
        after = bench_config(
            reg, steps, batch, fanouts, feature_dim, "optimized",
            telemetry=True,
        )
        # TELEMETRY A/B: the optimized path with BOTH observability
        # kill-switches thrown — telemetry (histograms/spans/phases)
        # AND the blackbox flight recorder — so the <2% overhead
        # contract (PERF.md "Telemetry overhead") prices every recorder
        # on the hot path, eg_blackbox's ring writes included. The
        # config keys are process-global, so the client AND the
        # in-process shards all stop recording; re-enabled in the
        # finally below.
        tel_off = bench_config(
            reg, steps, batch, fanouts, feature_dim, "telemetry_off",
            telemetry=False, blackbox=False,
        )
        telemetry_overhead_pct = round(
            (tel_off["edges_per_sec"] - after["edges_per_sec"])
            / tel_off["edges_per_sec"] * 100.0, 2,
        ) if tel_off["edges_per_sec"] > 0 else 0.0
        # HEAT A/B: the optimized path with ONLY the data-plane heat
        # profiler off (telemetry/blackbox stay on), so the sketch +
        # top-K + fan-out recording is priced on its own under the same
        # <2% contract (PERF.md "Data-plane heat"). heat= is
        # process-global, so the in-process shards stop feeding too;
        # re-enabled in the finally below.
        heat_off = bench_config(
            reg, steps, batch, fanouts, feature_dim, "heat_off",
            heat=False,
        )
        heat_overhead_pct = round(
            (heat_off["edges_per_sec"] - after["edges_per_sec"])
            / heat_off["edges_per_sec"] * 100.0, 2,
        ) if heat_off["edges_per_sec"] > 0 else 0.0
        # the statistically sound form: paired interleaved arms cancel
        # the box drift a single-shot config comparison cannot
        heat_ab = heat_ab_paired(
            reg, pairs=3 if smoke else 10, steps=max(2, steps // 2),
            batch=batch, fanouts=fanouts, feature_dim=feature_dim,
        )
        # DEVPROF A/B: the device-plane hooks priced the same paired way,
        # on the jit-dispatch hot path they actually ride (the remote
        # sampling loop above never crosses a jit boundary, so a config
        # A/B there would price nothing).
        devprof_ab = devprof_ab_paired(
            pairs=3 if smoke else 10,
            steps=50 if smoke else 200,
        )
        # ASYNC DEPTH SWEEP: sampler_depth in {1,2,4} vs the sync
        # before-picture (depth 0) — the pipelined-sampling evidence
        # (PERF.md "Pipelined sampling", ROADMAP item 1)
        sweep = depth_sweep(
            reg, steps=max(4, steps // 2), batch=batch, fanouts=fanouts,
            feature_dim=feature_dim,
        )
        depth2 = next(
            (r for r in sweep["rows"] if r["sampler_depth"] == 2), None
        )
        reduction = (
            after["ids_requested"] / after["ids_on_wire"]
            if after["ids_on_wire"] > 0 else float("inf")
        )
        value = after["edges_per_sec"]
        return {
            "metric": "remote_edges/sec",
            "value": value,
            "unit": "edges/s",
            "vs_baseline": round(value / 2_000_000.0, 3),
            "detail": {
                "config": "remote",
                "cluster": f"{NUM_SHARDS} shards, localhost, "
                           f"{'in-process' if inproc else 'subprocess'}",
                "graph": {
                    "num_nodes": num_nodes, "avg_degree": avg_degree,
                    "feature_dim": feature_dim, "powerlaw_alpha": 1.1,
                },
                "workload": {
                    "batch": batch, "fanouts": list(fanouts),
                    "steps": steps,
                },
                "before": before,
                "after": after,
                "telemetry_off": tel_off,
                "telemetry_overhead_pct": telemetry_overhead_pct,
                "heat_off": heat_off,
                "heat_overhead_pct": heat_overhead_pct,
                "heat_ab": heat_ab,
                "devprof_ab": devprof_ab,
                "sampler_depth_sweep": sweep,
                # the bench-breakdown contract for the remote path: the
                # measured depth-2 stall vs the (simulated, sample-time
                # calibrated) device step, judged at the same 5%
                # threshold bench.py applies to the local host path
                "breakdown": {
                    "device_step_ms": sweep["device_step_ms"],
                    "sampler_depth": 2,
                    "input_stall_ms": (
                        depth2["input_stall_ms"] if depth2 else None
                    ),
                    "sampling_hidden_by_prefetch": bool(
                        depth2 and depth2["sampling_hidden_by_prefetch"]
                    ),
                },
                "speedup": round(
                    after["edges_per_sec"] / before["edges_per_sec"], 3
                ),
                "ids_on_wire_reduction": round(reduction, 2),
            },
        }
    finally:
        from euler_tpu.blackbox import set_blackbox
        from euler_tpu.heat import set_heat
        from euler_tpu.telemetry import set_telemetry

        set_telemetry(True)  # the kill-switch A/Bs are process-global
        set_blackbox(True)
        set_heat(True)
        for p in procs:
            if hasattr(p, "stop"):
                p.stop()
            elif p.poll() is None:
                p.kill()
                p.wait()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--smoke", action="store_true",
                    help="small graph, few steps, in-process shards "
                    "(the verify.sh gate)")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--inproc", action="store_true", default=None,
                    help="in-process shard services instead of "
                    "subprocesses")
    args = ap.parse_args()
    result = run_remote_bench(smoke=args.smoke, inproc=args.inproc,
                              steps=args.steps)
    print(json.dumps(result), flush=True)
    detail = result["detail"]
    # per-depth throughput into the perf_gate smoke history, so a
    # pipelined-sampling regression shows up in the same trajectory the
    # gate reads (keys beyond bench_smoke/remote_smoke are carried, not
    # enforced — the 1-core container noise rule)
    try:
        from perf_gate import append_history

        sweep_vals = {
            f"remote_depth{r['sampler_depth']}": r["edges_per_sec"]
            for r in detail["sampler_depth_sweep"]["rows"]
        }
        append_history({"unix": int(time.time()), "values": sweep_vals})
    except Exception as e:
        print(f"history append skipped: {e}", file=sys.stderr)
    if args.smoke:
        # the smoke gate's contract: the optimized path must demonstrably
        # coalesce — a silent dedup regression fails verify, not PERF.md
        assert detail["ids_on_wire_reduction"] >= 2.0, detail
        assert detail["after"]["counters"].get("ids_deduped", 0) > 0, detail
        # and the async pipeline must demonstrably run (submits on the
        # ledger) — hidden-ness is judged on the full run, not smoke
        d2 = next(r for r in detail["sampler_depth_sweep"]["rows"]
                  if r["sampler_depth"] == 2)
        assert d2["counters"].get("async_submits", 0) > 0, d2
    return 0


if __name__ == "__main__":
    sys.exit(main())
