#!/usr/bin/env python
"""Closed-loop serving load drill (DEPLOY.md "Serving runbook").

The capstone proof for eg_serve: a trained checkpoint served over a
LIVE 2-shard graph cluster sustains a closed-loop client fleet with

  * bounded tail latency — exact p99 (SLOTracker window) under the
    configured SLO,
  * shedding under pressure — the tiny queue_cap forces BUSY rejects
    that clients absorb with retry+backoff; the drill asserts the
    `serve_busy_rejects` counter moved ON A LIVE SCRAPE (the frontend's
    `stats` op), not via in-process peeking,
  * bit-exact answers — a post-drill spot check pins served rows
    against EmbedServer.embed_direct (the no-batching reference path)
    for ids that just went through coalesced mixed-traffic batches,
  * zero worker deaths — every client thread completes its quota and
    the dispatcher/frontend shut down cleanly.

Smoke mode (`--smoke`, the verify.sh gate) runs a small planted graph,
a short training run, 16 clients x 12 requests; the full drill scales
all of it up. Exit code is the verdict.
"""

import argparse
import os
import random
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_SHARDS = 2


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="small/fast config (the verify.sh serve gate)")
    p.add_argument("--clients", type=int, default=16,
                   help="concurrent closed-loop clients (>= 16 is the "
                        "acceptance bar)")
    p.add_argument("--requests", type=int, default=40,
                   help="successful embeds each client must complete")
    p.add_argument("--num_nodes", type=int, default=2000)
    p.add_argument("--train_steps", type=int, default=30)
    p.add_argument("--slo_ms", type=float, default=2000.0,
                   help="p99 bound asserted at the end (generous: the "
                        "drill runs on whatever CPU verify.sh has)")
    p.add_argument("--queue_cap", type=int, default=4,
                   help="tiny on purpose: the drill must *provoke* "
                        "shedding, not avoid it")
    p.add_argument("--max_batch", type=int, default=16)
    p.add_argument("--max_wait_us", type=int, default=2000)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.smoke:
        args.clients = max(args.clients, 16)
        args.requests = min(args.requests, 12)
        args.num_nodes = min(args.num_nodes, 400)
        args.train_steps = min(args.train_steps, 12)

    import tempfile

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.checkpoint import Checkpointer
    from euler_tpu.datasets import build_planted
    from euler_tpu.graph.service import GraphService
    from euler_tpu.models import SupervisedGraphSage
    from euler_tpu.serving import BusyError, DeadlineError, EmbedClient

    t_start = time.monotonic()
    failures: list = []

    def check(ok: bool, what: str) -> None:
        print(f"  [{'ok' if ok else 'FAIL'}] {what}")
        if not ok:
            failures.append(what)

    tmp = tempfile.mkdtemp(prefix="serve_drill_")
    data = os.path.join(tmp, "data")
    reg = os.path.join(tmp, "reg")
    ckpt_dir = os.path.join(tmp, "ckpt")
    os.makedirs(reg)
    k_comm, fdim = 4, 8
    build_planted(
        data, num_nodes=args.num_nodes, num_communities=k_comm,
        feature_dim=fdim, avg_degree=8, num_partitions=NUM_SHARDS,
        seed=23,
    )

    print(f"== serve drill: {args.clients} clients x {args.requests} "
          f"requests over a live {NUM_SHARDS}-shard cluster ==")

    # ---- train -> checkpoint (the artifact being served) ----
    local = euler_tpu.Graph(directory=data)
    model = SupervisedGraphSage(
        label_idx=0, label_dim=k_comm, metapath=[[0], [0]],
        fanouts=[5, 5], dim=16, feature_idx=1, feature_dim=fdim,
        max_id=args.num_nodes - 1, sigmoid_loss=False,
    )
    train_lib.train(
        model, local, lambda s: local.sample_node(64, -1),
        num_steps=args.train_steps, learning_rate=0.01,
        checkpoint_dir=ckpt_dir, checkpoint_every=args.train_steps,
        log_every=10_000, seed=5,
    )

    # ---- live 2-shard cluster + remote serving graph ----
    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    server = frontend = None
    try:
        remote = euler_tpu.Graph(mode="remote", registry=reg, retries=4)

        # restore into a FRESH state structure: the drill must prove the
        # served params came off disk, not out of the training process
        import jax

        from euler_tpu.serve import EmbedServer
        from euler_tpu.serving import EmbedFrontend

        state = model.init_state(
            jax.random.PRNGKey(99), remote,
            np.arange(64, dtype=np.int64),
            train_lib.get_optimizer("adam", 0.01),
        )
        state = Checkpointer(ckpt_dir).restore(state)
        server = EmbedServer(
            model, remote, state, max_batch=args.max_batch,
            max_wait_us=args.max_wait_us, queue_cap=args.queue_cap,
            slo_ms=args.slo_ms,
        ).start()
        frontend = EmbedFrontend(server, port=0,
                                 max_conns=args.clients + 4)
        # warm the fixed-shape jitted program OUTSIDE the SLO window
        # (embed_direct skips the batcher, so compile time never lands
        # in a served request's tail)
        server.embed_direct(0)

        # ---- the storm: closed-loop clients with retry+backoff ----
        results: dict = {}

        def client(cid: int) -> None:
            rng = random.Random(1000 + cid)
            c = EmbedClient(frontend.address)
            done = busy_retries = 0
            try:
                while done < args.requests:
                    ids = [rng.randrange(args.num_nodes)
                           for _ in range(rng.randint(1, 4))]
                    try:
                        rows = c.embed(ids)
                    except BusyError:
                        busy_retries += 1
                        time.sleep(0.002 * min(busy_retries, 10))
                        continue
                    except DeadlineError:
                        continue
                    assert rows.shape == (len(ids), 16)
                    done += 1
                results[cid] = busy_retries
            finally:
                c.close()

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(args.clients)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        wall = time.monotonic() - t0

        # ---- verdict, against a LIVE scrape ----
        scrape = EmbedClient(frontend.address)
        stats = scrape.stats()
        slo = stats["slo"]
        ctr = stats["counters"]
        total = args.clients * args.requests
        print(f"  served {slo['count']} requests in {wall:.1f}s "
              f"({slo['count'] / max(wall, 1e-9):.0f} rps), "
              f"p50={slo['p50_ms']}ms p99={slo['p99_ms']}ms, "
              f"busy_rejects={ctr.get('serve_busy_rejects', 0)}, "
              f"batches={ctr.get('serve_batches', 0)} "
              f"(mean {stats['batch'].get('mean_unique_ids', 0)} "
              f"unique ids)")
        check(len(results) == args.clients,
              f"zero client deaths ({len(results)}/{args.clients} "
              "completed their quota)")
        check(slo["count"] >= total,
              f"all {total} requests served (slo count {slo['count']})")
        check(slo["p99_ms"] <= args.slo_ms,
              f"p99 {slo['p99_ms']}ms within SLO {args.slo_ms}ms")
        check(ctr.get("serve_busy_rejects", 0) > 0,
              "shedding provoked and visible on the live scrape "
              f"(serve_busy_rejects={ctr.get('serve_busy_rejects', 0)})")
        check(ctr.get("serve_batches", 1) < ctr.get("serve_requests", 0),
              "micro-batching coalesced (fewer dispatches than requests)")

        # bit-parity spot check: ids that just flowed through coalesced
        # mixed batches must equal the no-batching reference path
        spot = [1, args.num_nodes // 2, args.num_nodes - 1]
        served = scrape.embed(spot)
        direct = np.stack([server.embed_direct(i) for i in spot])
        check(served.dtype == direct.dtype
              and np.array_equal(served, direct),
              "served embeddings bit-identical to direct forward")
        scrape.close()
    finally:
        if frontend is not None:
            frontend.drain(grace_s=2.0)
        if server is not None:
            server.close()
        if frontend is not None:
            frontend.stop()
        for s in services:
            s.drain()
            s.stop()

    print(f"== serve drill {'FAIL' if failures else 'OK'} "
          f"({time.monotonic() - t_start:.1f}s) ==")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
