"""Full-node-count dress rehearsal of the real-Reddit data path.

Companion to scripts/ppi_dress_rehearsal.py for the DGL npz format:
builds a replica of the DGL reddit release files at the REAL node count
and feature shape — 232,965 nodes, [N, 602] float32 feature array,
41 classes, node_types 1/2/3 at the real split proportions (~66% train
/ ~10% val / ~24% test), scipy-CSR self-loop adjacency — and drives
them end-to-end the way a user with the real files would:

    prepare_reddit -> .dat partitions + {train,val,test}.id
    -> python -m euler_tpu.reddit_main --mode train
    -> --mode evaluate --id_file val.id

One honest reduction: average degree defaults to 25 (26 entries per
row with the self loop — 6.06M directed edges at full node count)
instead of the real ~492 (114.6M) — the file FORMATS and every
array shape the reader touches are exact, but converting 114M edges
through the line-block writer on this 1-core container would take
hours for no additional coverage. --avg-degree raises it if you have
the cores. Labels are a fixed linear function of the features, so
accuracy above 1/41 chance proves the model learns from the prepared
files. Recorded full-node-count run in README.

    JAX_PLATFORMS=cpu python scripts/reddit_dress_rehearsal.py
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def write_replica(data_dir: str, num_nodes: int, avg_degree: int,
                  feature_dim: int = 602, num_classes: int = 41,
                  seed: int = 0) -> dict:
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((num_nodes, feature_dim)).astype(np.float32)
    w = rng.standard_normal((feature_dim, num_classes)) / np.sqrt(feature_dim)
    labels = np.argmax(feats @ w, axis=1).astype(np.int64)
    # real split proportions: ~66% train / 10% val / 24% test, 1-based
    u = rng.random(num_nodes)
    node_types = np.where(u < 0.66, 1, np.where(u < 0.76, 2, 3)).astype(
        np.int64
    )
    os.makedirs(data_dir, exist_ok=True)
    np.savez(
        os.path.join(data_dir, "reddit_data.npz"),
        feature=feats,
        node_ids=np.arange(num_nodes, dtype=np.int64),
        label=labels,
        node_types=node_types,
    )
    # CSR with avg_degree random neighbors per row plus the self loop
    # (the DGL file is the self-loop variant)
    deg = avg_degree
    indices = rng.integers(0, num_nodes, num_nodes * deg, dtype=np.int32)
    indices = np.concatenate(
        [indices.reshape(num_nodes, deg),
         np.arange(num_nodes, dtype=np.int32)[:, None]],
        axis=1,
    ).reshape(-1)
    indptr = np.arange(num_nodes + 1, dtype=np.int64) * (deg + 1)
    adj = sp.csr_matrix(
        (np.ones(len(indices), np.float32), indices, indptr),
        shape=(num_nodes, num_nodes),
    )
    sp.save_npz(os.path.join(data_dir, "reddit_self_loop_graph.npz"), adj)
    # majority-class accuracy on the val split: the strongest
    # label-marginal-only predictor; the learned model must clear it by
    # a margin (the test gate)
    val_labels = labels[node_types == 2]
    counts = np.bincount(val_labels, minlength=num_classes)
    return {
        "train": int((node_types == 1).sum()),
        "val": int((node_types == 2).sum()),
        "test": int((node_types == 3).sum()),
        "edges": int(len(indices)),
        "majority_acc": round(float(counts.max() / max(len(val_labels), 1)),
                              4),
    }


def run(num_nodes: int, avg_degree: int, epochs: int, batch_size: int,
        workdir: str | None = None) -> dict:
    from euler_tpu import reddit_main
    from euler_tpu.datasets import prepare_reddit

    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="reddit_rehearsal_")
    src = os.path.join(workdir, "dgl")
    out = os.path.join(workdir, "dat")
    model_dir = os.path.join(workdir, "ck")
    summary: dict = {"num_nodes": num_nodes}
    try:
        t0 = time.time()
        summary["splits"] = write_replica(src, num_nodes, avg_degree)
        summary["write_replica_s"] = round(time.time() - t0, 1)

        t1 = time.time()
        prepare_reddit(src, out, num_partitions=2)
        summary["prepare_reddit_s"] = round(time.time() - t1, 1)

        common = [
            "--data_dir", out, "--model_dir", model_dir,
            "--model", "graphsage_supervised",
            "--max_id", str(num_nodes - 1),
            "--batch_size", str(batch_size), "--dim", "64",
            "--fanouts", "4,4", "--train_edge_type", "0",
            "--num_epochs", str(epochs), "--log_steps", "20",
        ]
        t2 = time.time()
        rc = reddit_main.run(common + ["--mode", "train"])
        summary["train_s"] = round(time.time() - t2, 1)
        summary["train_rc"] = rc
        if rc == 0:
            t3 = time.time()
            rc = reddit_main.run(
                common + [
                    "--mode", "evaluate",
                    "--id_file", os.path.join(out, "val.id"),
                ]
            )
            summary["evaluate_s"] = round(time.time() - t3, 1)
            summary["evaluate_rc"] = rc
            eval_json = os.path.join(model_dir, "eval.json")
            if rc == 0 and os.path.exists(eval_json):
                with open(eval_json) as f:
                    summary["val_metrics"] = json.load(f)
        return summary
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-nodes", type=int, default=232965)
    ap.add_argument("--avg-degree", type=int, default=25)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=1000)
    ap.add_argument("--workdir", default=None)
    args = ap.parse_args()
    summary = run(args.num_nodes, args.avg_degree, args.epochs,
                  args.batch_size, args.workdir)
    print(json.dumps(summary))
    ok = summary.get("train_rc") == 0 and summary.get("evaluate_rc") == 0
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
