"""Real-degree Reddit evidence: build a power-law graph at the REAL
edge budget (~114.6M directed edges over 232,965 nodes, mean ~490,
heavy tail), drive it end-to-end through convert + host engine load,
and measure what the reference-semantics questions actually need
measured (VERDICT r3 next-#2):

  --full              the 114M-edge build + load: generation time, .dat
                      bytes, achieved edge count, degree stats, engine
                      load time + RSS, the device-memory table (padded
                      slab at max_degree in {64, 256, 512} and at the
                      observed max — the unbuildable case — vs the
                      O(E) alias form), and device-sampling step timing
                      at the reference reddit recipe (batch 1000,
                      fanouts [4,4]) for the truncated-slab and exact
                      alias samplers.
  --truncation-study  the learning-cost question at a tractable scale:
                      a planted-community POWER-LAW graph (hub degrees
                      ~100x the slab caps) trained with device sampling
                      at max_degree in {8, 32, 128}, with the exact
                      alias sampler, and with the untruncated host
                      path; reports val micro-F1 and final loss per
                      variant. The alias row must match the host path
                      (both exact); the small-cap rows price the
                      truncation deviation from reference semantics
                      (CompactNode samples over ALL neighbors,
                      euler/core/compact_node.cc:42-101).

Both print one JSON summary; PERF.md records the numbers. The full
build is slow by nature (~114M edges through the line-block writer on
one core) and caches in --workdir: rerunning skips generation.

    JAX_PLATFORMS=cpu python scripts/reddit_heavytail.py --truncation-study
    python scripts/reddit_heavytail.py --full --workdir /root/repo/.data/reddit_ht
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def full_scale(workdir: str, num_edges: int, batch: int, steps: int) -> dict:
    import euler_tpu
    from euler_tpu.datasets import REDDIT_HEAVYTAIL, build_powerlaw
    from euler_tpu.graph import device as dg

    cfg = dict(REDDIT_HEAVYTAIL)
    cfg["num_edges"] = num_edges
    out: dict = {"config": cfg}

    t0 = time.time()
    build_powerlaw(workdir, progress_every=20000, **cfg)
    out["generate_s"] = round(time.time() - t0, 1)
    out["dat_bytes"] = sum(
        os.path.getsize(os.path.join(workdir, f))
        for f in os.listdir(workdir) if f.endswith(".dat")
    )

    rss0 = rss_mb()
    t1 = time.time()
    g = euler_tpu.Graph(directory=workdir)
    out["engine_load_s"] = round(time.time() - t1, 1)
    out["engine_rss_mb"] = round(rss_mb() - rss0, 1)

    n = cfg["num_nodes"]
    counts = np.zeros(n, np.int64)
    for lo in range(0, n, 65536):
        ids = np.arange(lo, min(lo + 65536, n))
        _, _, _, c = g.get_full_neighbor(ids, [0])
        counts[lo:lo + len(ids)] = c
    # Graph.num_edges counts edge-feature OBJECTS (this generator writes
    # none); the achieved adjacency size is the degree sum
    out["num_edges_achieved"] = int(counts.sum())
    out["degree"] = {
        "mean": round(float(counts.mean()), 1),
        "p99": int(np.percentile(counts, 99)),
        "max": int(counts.max()),
    }

    # device-memory table: slab (nbr+cum+packed where eligible) vs alias
    w_max = int(counts.max())
    mem = {}
    for w in (64, 256, 512, w_max):
        slab = (n + 2) * w * 8                      # nbr int32 + cum f32
        packed = (
            2 * ((w + 127) // 128) * (n + 2) * 128 * 4 if w <= 512 else None
        )
        mass_kept = float(np.minimum(counts, w).sum() / counts.sum())
        mem[f"slab_w{w}"] = {
            "slab_bytes": slab,
            "packed_bytes": packed,
            "edge_mass_kept": round(mass_kept, 4),
        }
    e = int(counts.sum())
    mem["alias_exact"] = {
        "bytes": 12 * e + 8 * (n + 2), "edge_mass_kept": 1.0,
    }
    out["device_memory"] = mem

    # device-sampling step timing at the reference reddit recipe
    # (batch 1000 roots x fanouts [4,4]); on CPU this is context, on a
    # TPU backend it is the real number — bench.py --configs
    # reddit_heavytail is the driver-visible form of the same measure
    import jax
    import jax.numpy as jnp

    t2 = time.time()
    aadj = dg.build_alias_adjacency(g, [0], n - 1)
    out["alias_build_s"] = round(time.time() - t2, 1)
    aadj = jax.device_put({k: jnp.asarray(v) for k, v in aadj.items()})

    def step(adj, key):
        roots = jax.random.randint(key, (batch,), 0, n)
        hops = dg.sample_fanout([adj, adj], roots, key, [4, 4])
        return hops[-1].sum()

    # adjacency is a jit ARGUMENT, not a closure capture: captured
    # device arrays are baked into the executable as constants, which
    # would keep the ~1.4 GB alias tables resident (immune to the del
    # below) through the slab phase's own device allocation
    f = jax.jit(step)
    f(aadj, jax.random.PRNGKey(0)).block_until_ready()
    t3 = time.time()
    for i in range(steps):
        r = f(aadj, jax.random.PRNGKey(i + 1))
    r.block_until_ready()
    dt = (time.time() - t3) / steps
    edges_per_step = batch * (4 + 4 * 4)
    out["alias_sampling"] = {
        "ms_per_step": round(dt * 1e3, 3),
        "edges_per_s": round(edges_per_step / dt),
        "platform": jax.default_backend(),
    }
    del aadj

    t4 = time.time()
    slab = dg.build_adjacency(g, [0], n - 1, max_degree=512)
    out["slab512_build_s"] = round(time.time() - t4, 1)
    slab = jax.device_put({k: jnp.asarray(v) for k, v in slab.items()})
    f(slab, jax.random.PRNGKey(0)).block_until_ready()
    t5 = time.time()
    for i in range(steps):
        r = f(slab, jax.random.PRNGKey(i + 1))
    r.block_until_ready()
    dt2 = (time.time() - t5) / steps
    out["slab512_sampling"] = {
        "ms_per_step": round(dt2 * 1e3, 3),
        "edges_per_s": round(edges_per_step / dt2),
    }
    out["peak_rss_mb"] = round(rss_mb(), 1)
    return out


def truncation_study(steps: int, batch: int) -> dict:
    """Train the same GraphSAGE on a heavy-tailed planted graph under
    each sampler form; report val micro-F1 + final loss."""
    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.datasets import (
        build_planted, nearest_centroid_accuracy,
    )
    from euler_tpu.graph import device as dg
    from euler_tpu.models import SupervisedGraphSage

    n, k_comm, fdim = 6000, 4, 16
    d = tempfile.mkdtemp(prefix="trunc_study_")
    out_dir, info = build_planted(
        d, num_nodes=n, num_communities=k_comm, feature_dim=fdim,
        avg_degree=60, max_degree=1500, alpha=1.6, noise=1.2,
        num_partitions=2, seed=29,
    )
    g = euler_tpu.Graph(directory=out_dir)
    counts = g.get_full_neighbor(np.arange(n), [0])[3]
    summary: dict = {
        "graph": {
            "num_nodes": n,
            "mean_degree": round(float(counts.mean()), 1),
            "max_degree": int(counts.max()),
        },
        "feat_acc": round(nearest_centroid_accuracy(info, False), 3),
        "hop1_acc": round(nearest_centroid_accuracy(info, True), 3),
        "variants": {},
    }

    def run(name, device_sampling, max_degree=None, alias=False):
        model = SupervisedGraphSage(
            label_idx=0, label_dim=k_comm, metapath=[[0], [0]],
            fanouts=[10, 10], dim=32, feature_idx=1, feature_dim=fdim,
            max_id=n - 1, sigmoid_loss=False,
            device_sampling=device_sampling, device_features=True,
        )
        if device_sampling:
            model.set_sampling_options(max_degree=max_degree, alias=alias)
        state, history = train_lib.train(
            model, g, lambda s: g.sample_node(batch, -1),
            num_steps=steps, learning_rate=0.01, optimizer="adam",
            log_every=50, seed=5,
        )
        ids = np.arange(n, dtype=np.int64)
        batches = [ids[i:i + 400] for i in range(0, n, 400)]
        f1 = train_lib.evaluate(model, g, batches, state)["f1"]
        summary["variants"][name] = {
            "f1": round(float(f1), 4),
            "final_loss": round(
                float(np.mean([h["loss"] for h in history[-3:]])), 4
            ),
        }

    run("host_exact", device_sampling=False)
    for cap in (8, 32, 128):
        run(f"slab_w{cap}", device_sampling=True, max_degree=cap)
    run("alias_exact", device_sampling=True, alias=True)
    return summary


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--truncation-study", action="store_true")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--num-edges", type=int, default=114_600_000,
                    help="edge target; the generator (unique-fill + "
                    "Gumbel-top-k hub rows) lands <1%% under this "
                    "(measured 0.8%% under at the Reddit recipe)")
    ap.add_argument("--batch", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--study-steps", type=int, default=400)
    ap.add_argument("--study-batch", type=int, default=256)
    args = ap.parse_args()
    out = {}
    if args.truncation_study:
        out["truncation_study"] = truncation_study(
            args.study_steps, args.study_batch
        )
    if args.full:
        # default to the SAME cache bench.py's reddit_heavytail config
        # uses (EULER_TPU_HEAVYTAIL_CACHE override, <repo>/.data
        # otherwise) so the documented script-then-bench queue builds
        # the ~2 GB graph once, not twice
        wd = args.workdir or os.environ.get(
            "EULER_TPU_HEAVYTAIL_CACHE",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                ".data", "reddit_ht",
            ),
        )
        out["full_scale"] = full_scale(
            wd, args.num_edges, args.batch, args.steps
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
