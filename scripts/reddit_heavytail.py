"""Real-degree Reddit evidence: build a power-law graph at the REAL
edge budget (~114.6M directed edges over 232,965 nodes, mean ~490,
heavy tail), drive it end-to-end through convert + host engine load,
and measure what the reference-semantics questions actually need
measured (VERDICT r3 next-#2):

  --full              the 114M-edge build + load: generation time, .dat
                      bytes, achieved edge count, degree stats, engine
                      load time + RSS, the device-memory table (padded
                      slab at max_degree in {64, 256, 512} and at the
                      observed max — the unbuildable case — vs the
                      O(E) alias form), and device-sampling step timing
                      at the reference reddit recipe (batch 1000,
                      fanouts [4,4]) for the truncated-slab and exact
                      alias samplers.
  --truncation-study  the learning-cost question at a tractable scale:
                      a planted-community POWER-LAW graph (hub degrees
                      ~100x the slab caps) trained with device sampling
                      at max_degree in {8, 32, 128}, with the exact
                      alias sampler, and with the untruncated host
                      path; reports val micro-F1 and final loss per
                      variant. The alias row must match the host path
                      (both exact); the small-cap rows price the
                      truncation deviation from reference semantics
                      (CompactNode samples over ALL neighbors,
                      euler/core/compact_node.cc:42-101).

Both print one JSON summary; PERF.md records the numbers. The full
build is slow by nature (~114M edges through the line-block writer on
one core) and caches in --workdir: rerunning skips generation.

    JAX_PLATFORMS=cpu python scripts/reddit_heavytail.py --truncation-study
    python scripts/reddit_heavytail.py --full --workdir /root/repo/.data/reddit_ht
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def full_scale(workdir: str, num_edges: int, batch: int, steps: int) -> dict:
    import euler_tpu
    from euler_tpu.datasets import REDDIT_HEAVYTAIL, build_powerlaw
    from euler_tpu.graph import device as dg

    cfg = dict(REDDIT_HEAVYTAIL)
    cfg["num_edges"] = num_edges
    out: dict = {"config": cfg}

    t0 = time.time()
    build_powerlaw(workdir, progress_every=20000, **cfg)
    out["generate_s"] = round(time.time() - t0, 1)
    out["dat_bytes"] = sum(
        os.path.getsize(os.path.join(workdir, f))
        for f in os.listdir(workdir) if f.endswith(".dat")
    )

    rss0 = rss_mb()
    t1 = time.time()
    g = euler_tpu.Graph(directory=workdir)
    out["engine_load_s"] = round(time.time() - t1, 1)
    out["engine_rss_mb"] = round(rss_mb() - rss0, 1)

    n = cfg["num_nodes"]
    counts = np.zeros(n, np.int64)
    for lo in range(0, n, 65536):
        ids = np.arange(lo, min(lo + 65536, n))
        _, _, _, c = g.get_full_neighbor(ids, [0])
        counts[lo:lo + len(ids)] = c
    # Graph.num_edges counts edge-feature OBJECTS (this generator writes
    # none); the achieved adjacency size is the degree sum
    out["num_edges_achieved"] = int(counts.sum())
    out["degree"] = {
        "mean": round(float(counts.mean()), 1),
        "p99": int(np.percentile(counts, 99)),
        "max": int(counts.max()),
    }

    # device-memory table: slab (nbr+cum+packed where eligible) vs alias
    w_max = int(counts.max())
    mem = {}
    for w in (64, 256, 512, w_max):
        slab = (n + 2) * w * 8                      # nbr int32 + cum f32
        packed = (
            2 * ((w + 127) // 128) * (n + 2) * 128 * 4 if w <= 512 else None
        )
        mass_kept = float(np.minimum(counts, w).sum() / counts.sum())
        mem[f"slab_w{w}"] = {
            "slab_bytes": slab,
            "packed_bytes": packed,
            "edge_mass_kept": round(mass_kept, 4),
        }
    e = int(counts.sum())
    mem["alias_exact"] = {
        "bytes": 12 * e + 8 * (n + 2), "edge_mass_kept": 1.0,
    }
    out["device_memory"] = mem

    # device-sampling step timing at the reference reddit recipe
    # (batch 1000 roots x fanouts [4,4]); on CPU this is context, on a
    # TPU backend it is the real number — bench.py --configs
    # reddit_heavytail is the driver-visible form of the same measure
    import jax
    import jax.numpy as jnp

    t2 = time.time()
    aadj = dg.build_alias_adjacency(g, [0], n - 1)
    out["alias_build_s"] = round(time.time() - t2, 1)
    aadj = jax.device_put({k: jnp.asarray(v) for k, v in aadj.items()})

    def step(adj, key):
        roots = jax.random.randint(key, (batch,), 0, n)
        hops = dg.sample_fanout([adj, adj], roots, key, [4, 4])
        return hops[-1].sum()

    # adjacency is a jit ARGUMENT, not a closure capture: captured
    # device arrays are baked into the executable as constants, which
    # would keep the ~1.4 GB alias tables resident (immune to the del
    # below) through the slab phase's own device allocation
    f = jax.jit(step)
    f(aadj, jax.random.PRNGKey(0)).block_until_ready()
    t3 = time.time()
    for i in range(steps):
        r = f(aadj, jax.random.PRNGKey(i + 1))
    r.block_until_ready()
    dt = (time.time() - t3) / steps
    edges_per_step = batch * (4 + 4 * 4)
    out["alias_sampling"] = {
        "ms_per_step": round(dt * 1e3, 3),
        "edges_per_s": round(edges_per_step / dt),
        "platform": jax.default_backend(),
    }
    del aadj

    t4 = time.time()
    slab = dg.build_adjacency(g, [0], n - 1, max_degree=512)
    out["slab512_build_s"] = round(time.time() - t4, 1)
    slab = jax.device_put({k: jnp.asarray(v) for k, v in slab.items()})
    f(slab, jax.random.PRNGKey(0)).block_until_ready()
    t5 = time.time()
    for i in range(steps):
        r = f(slab, jax.random.PRNGKey(i + 1))
    r.block_until_ready()
    dt2 = (time.time() - t5) / steps
    out["slab512_sampling"] = {
        "ms_per_step": round(dt2 * 1e3, 3),
        "edges_per_s": round(edges_per_step / dt2),
    }
    out["peak_rss_mb"] = round(rss_mb(), 1)
    return out


def walk_study(
    pairs_per_cap: int = 400,
    seed: int = 11,
    caps=(64, 256, 512),
    num_nodes: int = 6000,
    num_edges: int = 600_000,
) -> dict:
    """Quantify the biased-walk truncation distortion the device.py
    docstring documents (device.py biased_random_walk: with max_degree
    truncation a dropped real neighbor of the PARENT classifies as
    d_tx=2 (1/q) instead of d_tx=1, on top of the truncated sampling
    support of the CURRENT node).

    Both one-step transition distributions are computed ANALYTICALLY
    (no sampling noise): the exact node2vec distribution from the host
    engine's full neighbor lists (reference BuildWeights semantics,
    euler/client/graph.cc:120-151) vs the truncated-slab model that
    mirrors build_adjacency(sorted=True, max_degree=W) +
    biased_random_walk exactly. Steps measured are the AFFECTED class:
    parent x is a truncated (hub) row, current v drawn from x's kept
    set — any walk step with a hub parent is in this class; the
    edge-mass share of such steps is reported alongside. Metrics per
    cap W: mean/max total-variation distance and the mean exact-mass
    misclassified 1 -> 1/q."""
    import euler_tpu
    from euler_tpu.datasets import build_powerlaw
    from euler_tpu.graph import device as dg

    n, e = num_nodes, num_edges
    d = tempfile.mkdtemp(prefix="walk_study_")
    try:
        build_powerlaw(d, num_nodes=n, num_edges=e, feature_dim=4,
                       label_dim=3, alpha=1.6, seed=seed)
        g = euler_tpu.Graph(directory=d)
    finally:
        # the native load copies the .dat bytes into the store (no
        # mmap), so the multi-MB workdir can go the moment the graph is
        # up — repeated invocations (incl. tests) must not litter /tmp
        shutil.rmtree(d, ignore_errors=True)
    full_nbr, full_w, _, cnt = g.get_full_neighbor(np.arange(n), [0])
    rows = []          # per-node (ids, weights) from the host engine
    off = 0
    for c in cnt:
        rows.append((full_nbr[off:off + c], full_w[off:off + c]))
        off += c
    rng = np.random.default_rng(seed)
    out = {
        "graph": {"num_nodes": n, "num_edges": int(cnt.sum()),
                  "mean_degree": round(float(cnt.mean()), 1),
                  "max_degree": int(cnt.max())},
        "caps": {},
    }

    def exact_dist(x_set, x_id, v, p, q):
        # adjacency beats the parent match (a parent self-loop is
        # d_tx=1): the reference merge's equality branch runs before
        # its candidate<parent check (euler/client/graph.cc:126-140)
        ids, w = rows[v]
        scale = np.where(
            np.isin(ids, x_set), 1.0,
            np.where(ids == x_id, 1.0 / p, 1.0 / q),
        )
        pr = w * scale
        return ids, pr / pr.sum()

    for W in caps:
        hubs = np.flatnonzero(cnt > W)
        if len(hubs) == 0:
            out["caps"][f"W{W}"] = {
                "rows_truncated": 0,
                "note": "cap >= observed max degree: no truncation",
            }
            continue
        adj = dg.build_adjacency(g, [0], n - 1, max_degree=W, sorted=True)
        nbr, deg = np.asarray(adj["nbr"]), np.asarray(adj["deg"])
        cum = np.asarray(adj["cum"], dtype=np.float64)
        # share of steps in the affected class: a step's support/classes
        # are wrong iff its PARENT row is truncated; under a uniform
        # edge-mass proxy that share is the edge mass leaving hub rows
        mass_from_hubs = float(cnt[hubs].sum() / cnt.sum())
        tvds, miscls = [], []
        for _ in range(pairs_per_cap):
            x = int(rng.choice(hubs))
            kept_x = nbr[x][:deg[x]]
            v = int(rng.choice(kept_x))
            if cnt[v] == 0 or deg[v] == 0:
                continue
            x_full = rows[x][0]
            for p, q in ((0.25, 4.0), (4.0, 0.25)):
                ids_e, pr_e = exact_dist(x_full, x, v, p, q)
                ids_set = {int(i) for i in ids_e}
                # truncated model: v's kept slots + weights from cum
                # diffs; membership against x's KEPT sorted row
                kv = nbr[v][:deg[v]]
                wv = np.diff(np.concatenate([[0.0], cum[v][:deg[v]]]))
                pos = np.searchsorted(kept_x, kv)
                in_x = (pos < deg[x]) & (
                    kept_x[np.clip(pos, 0, deg[x] - 1)] == kv
                )
                sc = np.where(
                    in_x, 1.0, np.where(kv == x, 1.0 / p, 1.0 / q)
                )
                pr_t = wv * sc
                pr_t = pr_t / pr_t.sum()
                t = {int(y): 0.0 for y in ids_set}
                for i, y in enumerate(kv):
                    t[int(y)] = t.get(int(y), 0.0) + pr_t[i]
                tvd = 0.5 * (
                    sum(abs(t.get(int(y), 0.0) - pe)
                        for y, pe in zip(ids_e, pr_e))
                    + sum(v2 for y, v2 in t.items()
                          if y not in ids_set)
                )
                tvds.append(tvd)
                # exact mass whose CLASS flips 1 -> 1/q: candidates the
                # device still reaches (in v's kept row) that are real
                # neighbors of x but absent from x's kept row. Mass on
                # candidates dropped from v's row is SUPPORT truncation,
                # counted by the TVD, not here.
                flipped = (
                    np.isin(ids_e, x_full)
                    & ~np.isin(ids_e, kept_x)
                    & np.isin(ids_e, kv)
                )
                miscls.append(float(pr_e[flipped].sum()))
        entry = {
            "rows_truncated": int(len(hubs)),
            "edge_mass_from_truncated_rows": round(mass_from_hubs, 4),
        }
        if tvds:  # all-dead-end draws leave no valid pairs; avoid NaN
            entry.update(
                mean_tvd=round(float(np.mean(tvds)), 4),
                max_tvd=round(float(np.max(tvds)), 4),
                mean_exact_mass_misclassified=round(
                    float(np.mean(miscls)), 4
                ),
            )
        else:
            entry["note"] = "no valid (hub parent, sampleable v) pairs"
        out["caps"][f"W{W}"] = entry

    # The exact device alternative: alias + rejection
    # (device.alias_biased_random_walk). Empirical — the sampler is
    # stochastic, so its TVD floor is sampling noise ~0.4*sqrt(S/K) for
    # support size S — on the SAME affected step class (hub parent).
    out["alias_rejection"] = _alias_rejection_study(
        g, rows, cnt, seed=seed, pairs=min(pairs_per_cap, 40),
    )
    return out


def _alias_rejection_study(g, rows, cnt, seed: int, pairs: int,
                           draws: int = 20000) -> dict:
    """Empirical TVD of the exact alias+rejection biased step vs the
    analytic node2vec distribution, over hub-parent steps (the class the
    truncated slab distorts at mean TVD ~0.35). Expected: TVD at the
    sampling-noise floor for `draws` draws."""
    import jax
    from euler_tpu.graph import device as dg

    n = len(rows)
    adj = dg.build_alias_adjacency(g, [0], n - 1, sorted=True)
    rng = np.random.default_rng(seed + 1)
    hubs = np.flatnonzero(cnt >= np.quantile(cnt[cnt > 0], 0.99))
    if len(hubs) == 0:
        return {"note": "no hub rows"}
    tvds = []
    T = dg.DEFAULT_WALK_TRIALS
    for p, q in ((0.25, 4.0), (4.0, 0.25)):
        step = jax.jit(
            lambda cur, par, key, p=p, q=q: dg._alias_biased_step(
                adj, cur, par, key, p, q, T
            )
        )
        for i in range(pairs):
            x = int(rng.choice(hubs))
            x_full, _ = rows[x]
            if len(x_full) == 0:
                continue
            v = int(rng.choice(x_full))
            ids, w = rows[v]
            if len(ids) == 0 or w.sum() <= 0:
                continue
            # analytic target with the reference's branch order
            scale = np.where(
                np.isin(ids, x_full), 1.0,
                np.where(ids == x, 1.0 / p, 1.0 / q),
            )
            pr = w * scale
            pr = pr / pr.sum()
            cur = np.full(draws, v, np.int32)
            par = np.full(draws, x, np.int32)
            got = np.asarray(
                step(cur, par, jax.random.PRNGKey(seed * 1000 + i))
            )
            uy, uc = np.unique(got, return_counts=True)
            emp = {int(a): b / draws for a, b in zip(uy, uc)}
            support = {int(y) for y in ids}
            tvd = 0.5 * (
                sum(abs(emp.get(int(y), 0.0) - pe)
                    for y, pe in zip(ids, pr))
                + sum(pv for y, pv in emp.items() if y not in support)
            )
            tvds.append(tvd)
    if not tvds:
        return {"note": "no valid pairs"}
    return {
        "mean_tvd": round(float(np.mean(tvds)), 4),
        "max_tvd": round(float(np.max(tvds)), 4),
        "pairs": len(tvds),
        "draws_per_pair": draws,
        "trials": T,
    }


def truncation_study(steps: int, batch: int) -> dict:
    """Train the same GraphSAGE on a heavy-tailed planted graph under
    each sampler form; report val micro-F1 + final loss."""
    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.datasets import (
        build_planted, nearest_centroid_accuracy,
    )
    from euler_tpu.graph import device as dg
    from euler_tpu.models import SupervisedGraphSage

    n, k_comm, fdim = 6000, 4, 16
    d = tempfile.mkdtemp(prefix="trunc_study_")
    try:
        out_dir, info = build_planted(
            d, num_nodes=n, num_communities=k_comm, feature_dim=fdim,
            avg_degree=60, max_degree=1500, alpha=1.6, noise=1.2,
            num_partitions=2, seed=29,
        )
        g = euler_tpu.Graph(directory=out_dir)
    finally:
        shutil.rmtree(d, ignore_errors=True)  # store holds a copy
    counts = g.get_full_neighbor(np.arange(n), [0])[3]
    summary: dict = {
        "graph": {
            "num_nodes": n,
            "mean_degree": round(float(counts.mean()), 1),
            "max_degree": int(counts.max()),
        },
        "feat_acc": round(nearest_centroid_accuracy(info, False), 3),
        "hop1_acc": round(nearest_centroid_accuracy(info, True), 3),
        "variants": {},
    }

    def run(name, device_sampling, max_degree=None, alias=False):
        model = SupervisedGraphSage(
            label_idx=0, label_dim=k_comm, metapath=[[0], [0]],
            fanouts=[10, 10], dim=32, feature_idx=1, feature_dim=fdim,
            max_id=n - 1, sigmoid_loss=False,
            device_sampling=device_sampling, device_features=True,
        )
        if device_sampling:
            model.set_sampling_options(max_degree=max_degree, alias=alias)
        state, history = train_lib.train(
            model, g, lambda s: g.sample_node(batch, -1),
            num_steps=steps, learning_rate=0.01, optimizer="adam",
            log_every=50, seed=5,
        )
        ids = np.arange(n, dtype=np.int64)
        batches = [ids[i:i + 400] for i in range(0, n, 400)]
        f1 = train_lib.evaluate(model, g, batches, state)["f1"]
        summary["variants"][name] = {
            "f1": round(float(f1), 4),
            "final_loss": round(
                float(np.mean([h["loss"] for h in history[-3:]])), 4
            ),
        }

    run("host_exact", device_sampling=False)
    for cap in (8, 32, 128):
        run(f"slab_w{cap}", device_sampling=True, max_degree=cap)
    run("alias_exact", device_sampling=True, alias=True)
    return summary


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--truncation-study", action="store_true")
    ap.add_argument("--walk-study", action="store_true")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--num-edges", type=int, default=114_600_000,
                    help="edge target; the generator (unique-fill + "
                    "Gumbel-top-k hub rows) lands <1%% under this "
                    "(measured 0.8%% under at the Reddit recipe)")
    ap.add_argument("--batch", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--study-steps", type=int, default=400)
    ap.add_argument("--study-batch", type=int, default=256)
    args = ap.parse_args()
    out = {}
    if args.truncation_study:
        out["truncation_study"] = truncation_study(
            args.study_steps, args.study_batch
        )
    if args.walk_study:
        out["walk_study"] = walk_study()
    if args.full:
        # default to the SAME cache bench.py's reddit_heavytail config
        # uses (one resolver: datasets.heavytail_cache_dir) so the
        # documented script-then-bench queue builds the ~2 GB graph
        # once, not twice
        from euler_tpu.datasets import heavytail_cache_dir

        wd = args.workdir or heavytail_cache_dir()
        out["full_scale"] = full_scale(
            wd, args.num_edges, args.batch, args.steps
        )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
