#!/usr/bin/env python
"""Live delta-flip drill (DEPLOY.md "Rolling graph refresh").

The capstone proof for the snapshot-epoch layer (_native/eg_epoch): a
GraphSAGE training run over a LIVE 2-shard cluster keeps training while
every shard merges a delta and flips to the new snapshot mid-run — with
the sampler_depth=2 async ring holding steps in flight across each flip
— and the drill asserts

  * zero failed calls — the flips are invisible to the data plane
    (`calls_failed` and `delta_loads_failed` both zero, exactly one
    flip and one drain per shard on the ledger),
  * loss parity on the unchanged subgraph — a pre-flip fan-out whose
    2-hop closure provably avoids every mutated node is re-assembled
    after the flips: features AND the resulting train-step loss are
    bit-identical,
  * the mutation landed — mutated nodes read the new feature rows,
  * closure — post-flip remote reads are bit-identical to a fresh
    LOCAL load of base + the same delta (`Graph(directory=..,
    delta=..)`), the property every other epoch guarantee reduces to.

Smoke mode (`--smoke`, the verify.sh gate) runs a small planted graph
and a short run; the full drill scales it up. Exit code is the verdict.
"""

import argparse
import os
import sys
import time
from collections import deque

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_SHARDS = 2
FDIM = 8
K_COMM = 4


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--smoke", action="store_true",
                   help="small/fast config (the verify.sh epoch gate)")
    p.add_argument("--num_nodes", type=int, default=4000)
    p.add_argument("--train_steps", type=int, default=120)
    p.add_argument("--batch", type=int, default=64)
    return p.parse_args(argv)


def _planted_node(nid, info, mutated=None):
    """Reconstruct one node dict exactly as build_planted packed it
    (same field order and dtypes, so pack_block bytes match and
    make_delta emits ONLY the mutated records)."""
    communities = info["communities"]
    labels = np.zeros(K_COMM)
    labels[communities[nid]] = 1.0
    feats = info["features"][nid]
    if mutated is not None and nid in mutated:
        feats = feats + np.float32(1.5)
    return {
        "node_id": nid,
        "node_type": 0,
        "node_weight": 1.0,
        "neighbor": {
            "0": {str(int(d)): 1.0 for d in info["neighbors"][nid]}
        },
        "uint64_feature": {},
        "float_feature": {
            "0": labels.tolist(),
            "1": np.asarray(feats, dtype=np.float32).tolist(),
        },
        "binary_feature": {},
        "edge": [],
    }


def main(argv=None) -> int:
    args = _parse_args(argv)
    if args.smoke:
        args.num_nodes = min(args.num_nodes, 1200)
        args.train_steps = min(args.train_steps, 40)

    import tempfile

    import jax

    import euler_tpu
    from euler_tpu import train as train_lib
    from euler_tpu.datasets import build_planted
    from euler_tpu.graph import native
    from euler_tpu.graph.convert import make_delta, pack_delta
    from euler_tpu.graph.service import GraphService
    from euler_tpu.models import SupervisedGraphSage

    t_start = time.monotonic()
    failures: list = []

    def check(ok: bool, what: str) -> None:
        print(f"  [{'ok' if ok else 'FAIL'}] {what}")
        if not ok:
            failures.append(what)

    tmp = tempfile.mkdtemp(prefix="epoch_drill_")
    data = os.path.join(tmp, "data")
    reg = os.path.join(tmp, "reg")
    os.makedirs(reg)
    _, info = build_planted(
        data, num_nodes=args.num_nodes, num_communities=K_COMM,
        feature_dim=FDIM, avg_degree=6, num_partitions=NUM_SHARDS,
        seed=23,
    )
    n = args.num_nodes

    # ---- eval roots + their 2-hop closure: the UNCHANGED subgraph ----
    # Every node a fan-out from these roots can possibly draw lives in
    # the closure, so mutating only nodes OUTSIDE it makes the parity
    # claim exact, not statistical.
    eval_roots = np.arange(8, dtype=np.int64)
    closure = set(int(r) for r in eval_roots)
    frontier = list(closure)
    for _ in range(2):
        nxt = []
        for s in frontier:
            for d in info["neighbors"][s]:
                d = int(d)
                if d not in closure:
                    closure.add(d)
                    nxt.append(d)
        frontier = nxt
    mutated = sorted(set(range(n)) - closure)[: max(50, n // 10)]
    if len(mutated) < 20:
        print(f"drill config error: only {len(mutated)} nodes outside "
              f"the eval closure ({len(closure)}/{n}) — grow num_nodes")
        return 1

    # ---- the delta: feature refresh on the mutated set ----
    mset = set(mutated)
    old_nodes = [_planted_node(i, info) for i in range(n)]
    new_nodes = [_planted_node(i, info, mutated=mset) for i in range(n)]
    rm_n, rm_e, blob = make_delta(
        old_nodes, new_nodes,
        {"node_type_num": 1, "edge_type_num": 1,
         "node_uint64_feature_num": 0, "node_float_feature_num": 2,
         "node_binary_feature_num": 0, "edge_uint64_feature_num": 0,
         "edge_float_feature_num": 0, "edge_binary_feature_num": 0},
    )
    dpath = os.path.join(tmp, "part.delta.1")
    with open(dpath, "wb") as f:
        f.write(pack_delta(1, rm_n, rm_e, blob))

    print(f"== epoch drill: {args.train_steps} steps over a live "
          f"{NUM_SHARDS}-shard cluster, {len(mutated)} nodes mutated "
          f"behind a {len(closure)}-node eval closure ==")

    services = [
        GraphService(data, s, NUM_SHARDS, registry=reg)
        for s in range(NUM_SHARDS)
    ]
    try:
        native.reset_counters()
        g = euler_tpu.Graph(mode="remote", registry=reg, retries=4,
                            neighbor_cache_mb=0)
        model = SupervisedGraphSage(
            label_idx=0, label_dim=K_COMM, metapath=[[0], [0]],
            fanouts=[5, 5], dim=16, feature_idx=1, feature_dim=FDIM,
            max_id=n - 1, sigmoid_loss=False,
        )
        opt = train_lib.get_optimizer("adam", 0.01)
        step = jax.jit(model.make_train_step(opt), donate_argnums=(0,))
        eval_step = jax.jit(model.make_train_step(opt))  # non-donating

        rng = np.random.default_rng(7)
        native.lib().eg_seed(1234)
        state = model.init_state(
            jax.random.PRNGKey(0), g,
            rng.integers(0, n, args.batch).astype(np.int64), opt,
        )

        # pre-flip capture: one fan-out from the eval roots; its hop ids
        # are frozen, its features re-read before and after the flips
        ids_per_hop, _, _ = g.sample_fanout(
            eval_roots, model.metapath, model.fanouts, -1
        )
        drawn = {int(i) for hop in ids_per_hop for i in np.asarray(hop)}
        check(drawn <= closure,
              f"eval fan-out stayed inside the closure "
              f"({len(drawn)} drawn ids)")
        batch_pre = model._batch_from_hops(g, eval_roots, ids_per_hop)
        feats_mut_pre = g.get_dense_feature(
            np.array(mutated[:16], dtype=np.int64), [1], [FDIM]
        )

        # ---- train through both flips, depth-2 ring in flight ----
        flip_steps = {args.train_steps // 3: 0,
                      args.train_steps // 2: 1}
        losses = []
        inflight = deque()
        submitted = 0
        while len(losses) < args.train_steps:
            while (submitted < args.train_steps
                   and len(inflight) < 2):
                shard = flip_steps.get(submitted)
                if shard is not None:
                    ep = g.load_delta(dpath, shard=shard)
                    print(f"  step {submitted}: shard {shard} flipped "
                          f"to epoch {ep} (mid-flight)")
                roots = rng.integers(0, n, args.batch).astype(np.int64)
                inflight.append(model.sample_start(g, roots))
                submitted += 1
            batch = model.sample_finish(g, inflight.popleft())
            state, loss, _ = step(state, batch)
            losses.append(float(loss))

        # ---- verdict ----
        check(all(np.isfinite(x) for x in losses),
              "every loss finite across both flips")
        check(float(np.mean(losses[-5:])) < losses[0],
              f"net training progress ({losses[0]:.3f} -> "
              f"{float(np.mean(losses[-5:])):.3f})")

        # data plane never saw the flips
        ctr = native.counters()
        check(ctr["calls_failed"] == 0,
              f"zero failed calls (calls_failed={ctr['calls_failed']})")
        check(ctr["delta_loads_failed"] == 0,
              "zero refused delta loads")
        # ledger: one flip per shard; every retired epoch drains once
        # its in-flight pins release
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            ctr = native.counters()
            if ctr["epoch_drains"] == ctr["epoch_flips"] == NUM_SHARDS:
                break
            g.sample_neighbor(eval_roots, [0], 2, default_node=-1)
            time.sleep(0.05)
        check(ctr["epoch_flips"] == NUM_SHARDS,
              f"exactly one flip per shard "
              f"(epoch_flips={ctr['epoch_flips']})")
        check(ctr["epoch_drains"] == NUM_SHARDS,
              f"every retired epoch drained "
              f"(epoch_drains={ctr['epoch_drains']})")
        check(all(g.shard_epoch(s) == 1 for s in range(NUM_SHARDS))
              and g.epoch() == 1,
              "client passively observed both flips (epoch 1 everywhere)")
        check(g.cache_gen >= 1,
              f"cache generation bumped (cache_gen={g.cache_gen})")

        # loss parity on the unchanged subgraph: same hop ids, features
        # re-read post-flip, same frozen state -> bit-identical loss
        batch_post = model._batch_from_hops(g, eval_roots, ids_per_hop)
        pre_leaves = jax.tree_util.tree_leaves(batch_pre)
        post_leaves = jax.tree_util.tree_leaves(batch_post)
        same = len(pre_leaves) == len(post_leaves) and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(pre_leaves, post_leaves)
        )
        check(same, "unchanged-subgraph batch bit-identical across flips")
        _, loss_pre, _ = eval_step(state, batch_pre)
        _, loss_post, _ = eval_step(state, batch_post)
        check(float(loss_pre) == float(loss_post),
              f"loss parity on unchanged subgraph "
              f"({float(loss_pre):.6f} == {float(loss_post):.6f})")

        # the mutation landed: mutated rows read the refreshed features
        feats_mut_post = g.get_dense_feature(
            np.array(mutated[:16], dtype=np.int64), [1], [FDIM]
        )
        check(np.array_equal(feats_mut_post,
                             feats_mut_pre + np.float32(1.5)),
              "mutated nodes serve the refreshed feature rows")

        # closure: remote post-flip == fresh local base+delta, bit for bit
        fresh = euler_tpu.Graph(directory=data, delta=dpath)
        try:
            probe = np.array(
                mutated[:16] + sorted(closure)[:16], dtype=np.int64
            )
            check(fresh.epoch() == 1, "fresh merged load sits at epoch 1")
            check(np.array_equal(
                      g.get_dense_feature(probe, [1], [FDIM]),
                      fresh.get_dense_feature(probe, [1], [FDIM])),
                  "post-flip reads bit-identical to fresh merged load")
        finally:
            fresh.close()
        g.close()
    finally:
        for s in services:
            s.stop()

    print(f"== epoch drill {'FAIL' if failures else 'OK'} "
          f"({time.monotonic() - t_start:.1f}s) ==")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
